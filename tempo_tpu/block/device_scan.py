"""Device scan plane for backend blocks.

The storage-level first pass (`condition_mask`) evaluated every pushdown
predicate as a numpy mask over object-dtype string columns — the hot loop
of SURVEY §3.3 (ref `block_traceql.go:1538` compiling conditions into
per-value predicate iterators, `parquetquery/predicates.go:15`) never
touched the chip. Here the dictionary-coded form of the scan does:

- string columns stay dictionary-coded (parquet already stores them that
  way): codes are an int32 device column; a predicate becomes a tiny
  boolean lookup table built on host over the DICTIONARY (|dict| entries,
  not |rows|) — equality and full regex both cost O(|dict|) host work —
  then one device gather. This is the reference's dictionary-page
  predicate pushdown (`predicates.go` `*DictionaryPredicate`) turned into
  a gather instead of a page scan.
- integer columns (duration, kind, status, nested-set coords, int/bool
  attributes, timestamps) compare EXACTLY on device: each int64 value is
  split into two int32 halves (hi = v >> 31, lo = v & 0x7fffffff) and a
  literal compare becomes a lexicographic (hi, lo) compare — no float32
  rounding, so the device mask is bit-identical to the float64 numpy
  plane for every integral column (the whole intrinsic set is integral).
  Non-integral literals are normalized on host (`duration > 1.5` ⇒
  `>= 2`); genuinely float-valued attribute columns fall back to host.
- masks AND/OR-combine on device; one transfer returns the final mask.

Two planes share this machinery:

`device_pred_mask` — per-row-group sync offload for `condition_mask`,
OPT-IN via TEMPO_TPU_DEVICE_SCAN=1 (each mask pays a device round trip;
float32 compares). Kept for diagnostics.

`BlockScanPlane` — the PRODUCTION plane: per immutable block, columns are
adopted lazily (first query referencing a column pays one host factorize
+ upload; blocks are immutable so adoption is permanent), and a query's
whole first pass — predicates, time clip, row-group shard selection,
step bucketing, group-by, metric scatter — runs as ONE fused dispatch.
`db/tempodb.py` routes product search/query_range through it via
`db/plane_cache.py`.
"""

from __future__ import annotations

import functools
import re
import os
import threading
import time
from typing import Optional, Sequence

import numpy as np

from tempo_tpu.block.fetch import _dict_codes
from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.eval import (BOOL, KIND, NUM, STATUS, STR, Col,
                                    eval_expr)

_NUM_OPS = {A.Op.EQ, A.Op.NEQ, A.Op.GT, A.Op.GTE, A.Op.LT, A.Op.LTE}
_STR_OPS = {A.Op.EQ, A.Op.NEQ, A.Op.REGEX, A.Op.NOT_REGEX}

_NUM_INTRINSICS = {
    A.Intrinsic.DURATION: "duration",
    A.Intrinsic.KIND: "kind",
    A.Intrinsic.STATUS: "status",
    A.Intrinsic.NESTED_SET_LEFT: "nestedSetLeft",
    A.Intrinsic.NESTED_SET_RIGHT: "nestedSetRight",
    A.Intrinsic.NESTED_SET_PARENT: "nestedSetParent",
}

# static type → column type tag, for the reference's comparability lattice
# (`enum_statics.go`: status/kind/num are distinct; see eval._comparable)
_STATIC_T = {
    A.StaticType.INT: NUM, A.StaticType.FLOAT: NUM,
    A.StaticType.DURATION: NUM, A.StaticType.STRING: STR,
    A.StaticType.BOOL: BOOL, A.StaticType.STATUS: STATUS,
    A.StaticType.KIND: KIND,
}

_INT_MAX = 1 << 62   # |values| beyond this can't ride the hi/lo split


def enabled() -> bool:
    """Per-row-group sync offload policy for `condition_mask` — OPT-IN
    (TEMPO_TPU_DEVICE_SCAN=1): each synchronous mask pays a full device
    round trip and compares in float32. The block-level `BlockScanPlane`
    (one fused dispatch per block, exact int compares) is the production
    device plane."""
    return os.environ.get("TEMPO_TPU_DEVICE_SCAN", "") == "1"


# ---------------------------------------------------------------------------
# shared host-side predicate compilation
# ---------------------------------------------------------------------------

_STR_ORD = {A.Op.GT: lambda a, b: a > b, A.Op.GTE: lambda a, b: a >= b,
            A.Op.LT: lambda a, b: a < b, A.Op.LTE: lambda a, b: a <= b}


def _dict_term(op: A.Op, v, dvals: list):
    """Compile a string predicate over dictionary values into a (sig
    entry, lut) pair; None when the shape is unsupported. Regexes are
    ANCHORED (fullmatch), matching `eval.regex_match_col` / pkg/regexp.
    Ordered compares are lexicographic like the numpy plane's astype(str)
    compare."""
    if not isinstance(v, str):
        return None
    if op in (A.Op.EQ, A.Op.NEQ):
        matched = [i for i, s in enumerate(dvals) if s == v]
    elif op in _STR_ORD:
        f = _STR_ORD[op]
        matched = [i for i, s in enumerate(dvals) if f(s, v)]
    elif op in (A.Op.REGEX, A.Op.NOT_REGEX):
        try:
            rx = re.compile(v)
        except re.error:
            return None
        matched = [i for i, s in enumerate(dvals) if rx.fullmatch(s)]
    else:
        return None
    lut = np.zeros(len(dvals), bool)
    if matched:
        lut[np.asarray(matched)] = True
    return ("lut", None, op in (A.Op.NEQ, A.Op.NOT_REGEX)), lut


def _num_term(op: A.Op, v):
    """(sig entry, float literal) for a numeric compare; None otherwise."""
    if op not in _NUM_OPS or isinstance(v, (str, bytes)):
        return None
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return ("cmp", op, False), f


def _int_literal(op: A.Op, v) -> tuple:
    """Normalize (op, literal) for the exact integer plane.

    Returns ("const", bool) when the comparison is decidable on host
    (non-integral EQ, out-of-range literals) or ("icmp", op', int_lit).
    Non-integral range literals shift to the nearest integer bound:
    `v > 1.5` over ints ⟺ `v >= 2`; `v < 1.5` ⟺ `v <= 1`.
    """
    try:
        f = float(v)
    except (TypeError, ValueError):
        return ("const", False)
    if f != f:                                   # NaN compares are false
        return ("const", False)
    if float(f).is_integer() and abs(f) < _INT_MAX:
        return ("icmp", op, int(f))
    if op == A.Op.EQ:
        return ("const", False)
    if op == A.Op.NEQ:
        return ("const", True)
    if abs(f) >= _INT_MAX:
        big = f > 0
        if op in (A.Op.GT, A.Op.GTE):
            return ("const", not big)
        return ("const", big)                    # LT / LTE
    import math

    if op in (A.Op.GT, A.Op.GTE):
        return ("icmp", A.Op.GTE, int(math.ceil(f)))
    return ("icmp", A.Op.LTE, int(math.floor(f)))


def _split_i64(v: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int64 → (hi, lo) int32 halves; lexicographic (hi, lo) order equals
    the int64 order (hi is the arithmetic shift, lo is non-negative)."""
    v = np.asarray(v, np.int64)
    return (v >> 31).astype(np.int32), (v & 0x7FFFFFFF).astype(np.int32)


def _sortable_f64(v: np.ndarray) -> np.ndarray:
    """float64 → order-preserving int64 (no NaN): non-negative floats keep
    their bit pattern (already increasing); negative floats reflect so
    more-negative maps lower. -0.0 and +0.0 both map to 0 — equal floats
    must encode equal."""
    b = np.asarray(v, np.float64).view(np.int64)
    return np.where(b >= 0, b, np.int64(-2**63) - b)


def _split_i64_biased(v: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """FULL-RANGE int64 → (hi, lo) int32 halves whose signed lexicographic
    order equals the int64 order: 32/32 split with the low half's sign
    bit flipped (signed compare of the biased low == unsigned compare of
    the true low). The 33/31 `_split_i64` would overflow hi for |v| ≥
    2^62 — which sortable-float encodings reach."""
    v = np.asarray(v, np.int64)
    hi = (v >> 32).astype(np.int32)
    lo = ((v & 0xFFFFFFFF).astype(np.uint32)
          ^ np.uint32(0x80000000)).view(np.int32)
    return hi, lo


def _split_lit_biased(lit: int) -> tuple[int, int]:
    x = (int(lit) & 0xFFFFFFFF) ^ 0x80000000
    if x >= 1 << 31:
        x -= 1 << 32
    return int(lit) >> 32, x


def _split_lit(lit: int) -> tuple[int, int]:
    return int(lit >> 31), int(lit & 0x7FFFFFFF)


# ---------------------------------------------------------------------------
# fused mask kernels
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _compiled_mask(sig: tuple, all_conditions: bool):
    """One fused jitted kernel per predicate-plan shape: the whole
    conjunction/disjunction is a single device dispatch per row group.
    (float32 numeric path — the per-row-group opt-in plane only.)"""
    import jax
    import jax.numpy as jnp

    def fn(*args):
        i = 0
        mask = None
        for kind, op, neg in sig:
            if kind == "lut":
                codes, lut = args[i], args[i + 1]
                i += 2
                m = jnp.take(lut, codes)
                if neg:
                    m = ~m
            else:
                col, lit = args[i], args[i + 1]
                i += 2
                if op == A.Op.EQ:
                    m = col == lit
                elif op == A.Op.NEQ:
                    m = col != lit
                elif op == A.Op.GT:
                    m = col > lit
                elif op == A.Op.GTE:
                    m = col >= lit
                elif op == A.Op.LT:
                    m = col < lit
                else:
                    m = col <= lit
            mask = m if mask is None else (mask & m if all_conditions
                                           else mask | m)
        return mask

    from tempo_tpu.obs.jaxruntime import instrumented_jit
    return instrumented_jit(fn, name="plane_predicate_mask")


def _icmp(jnp, op: A.Op, hi, lo, lh, ll):
    """Exact int64 compare from (hi, lo) int32 halves."""
    if op == A.Op.EQ:
        return (hi == lh) & (lo == ll)
    if op == A.Op.NEQ:
        return (hi != lh) | (lo != ll)
    if op == A.Op.GT:
        return (hi > lh) | ((hi == lh) & (lo > ll))
    if op == A.Op.GTE:
        return (hi > lh) | ((hi == lh) & (lo >= ll))
    if op == A.Op.LT:
        return (hi < lh) | ((hi == lh) & (lo < ll))
    return (hi < lh) | ((hi == lh) & (lo <= ll))


def _term_masks(jnp, sig: tuple, args, n: int, ivec, ibase: int):
    """Evaluate each term of a plan signature → list of bool vectors.

    Device arrays ride in `args` (consumed left to right); EVERY scalar
    literal is an element of the single packed int32 vector `ivec`
    (starting at `ibase`) — one H2D transfer per call however many
    predicates the plan holds, which is what makes the plane win behind
    a high-latency device link. Term shapes:
      ("lut", neg, has_ex)    args: codes, lut, [exists]
      ("icmp", op, has_ex)    args: hi, lo, [exists]; ivec: lh, ll
      ("nil", want, has_ex)   args: [exists]   (x = nil / x != nil)
      ("const", val)          —
    Missing attributes never match (exists ANDs after negation), matching
    `Col.bool_mask` in the numpy plane.
    """
    out = []
    i = 0
    k = ibase
    for term in sig:
        kind = term[0]
        if kind == "lut":
            _, neg, has_ex = term
            codes, lut = args[i], args[i + 1]
            i += 2
            m = jnp.take(lut, codes)
            if neg:
                m = ~m
            if has_ex:
                m = m & args[i]
                i += 1
        elif kind == "icmp":
            _, op, has_ex = term
            hi, lo = args[i], args[i + 1]
            i += 2
            m = _icmp(jnp, op, hi, lo, ivec[k], ivec[k + 1])
            k += 2
            if has_ex:
                m = m & args[i]
                i += 1
        elif kind == "nil":
            _, want, has_ex = term
            if has_ex:
                ex = args[i]
                i += 1
                m = ex if want else ~ex
            else:
                m = jnp.full((n,), bool(want))
        else:                                    # ("const", val)
            m = jnp.full((n,), bool(term[1]))
        out.append(m)
    return out, i, k


@functools.lru_cache(maxsize=128)
def _block_mask_kernel(n: int, pred_sig: tuple, extra_sig: tuple,
                       all_conditions: bool):
    """Fused block mask: predicate terms combine per all_conditions;
    extra terms (time clip, row-group shard) always AND."""
    import jax
    import jax.numpy as jnp

    def fn(ivec, *args):
        pred_masks, used, k = _term_masks(jnp, pred_sig, args, n, ivec, 0)
        extra_masks, _, _ = _term_masks(jnp, extra_sig, args[used:], n,
                                        ivec, k)
        mask = None
        for m in pred_masks:
            mask = m if mask is None else (mask & m if all_conditions
                                           else mask | m)
        if mask is None:
            mask = jnp.ones((n,), bool)
        for m in extra_masks:
            mask = mask & m
        # bit-pack on device: the D2H is n/8 bytes instead of n (the
        # transfer is the cost behind a network-attached device)
        pad = (-n) % 8
        mp = jnp.pad(mask, (0, pad)).reshape(-1, 8).astype(jnp.uint8)
        weights = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.uint8)
        return (mp * weights).sum(axis=1).astype(jnp.uint8)

    from tempo_tpu.obs.jaxruntime import instrumented_jit
    return instrumented_jit(fn, name="plane_packed_mask")


# ---------------------------------------------------------------------------
# per-row-group opt-in plane (diagnostic; float32 numerics)
# ---------------------------------------------------------------------------



def _col_for(view, attr: A.Attribute):
    """("dict", key, codes, dictvals) | ("num", key, values) | None."""
    if attr.intrinsic == A.Intrinsic.NAME:
        c = view.meta.get("name_col")
        if c is not None:
            return ("dict", "name") + _dict_codes(view, "name", c)
    if (attr.intrinsic == A.Intrinsic.NONE and attr.name == "service.name"
            and attr.scope in (A.Scope.RESOURCE, A.Scope.NONE)):
        c = view.meta.get("service_col")
        if c is not None:
            return ("dict", "service") + _dict_codes(view, "service", c)
    key = _NUM_INTRINSICS.get(attr.intrinsic)
    if key:
        col = view.col(key)
        if col is not None:
            return ("num", key, col.values)
    return None


def _dev_array(view, key: str, values: np.ndarray, dtype):
    """Device-resident copy of a scan column, cached on the view so a
    multi-query/multi-pass scan transfers each column once."""
    import jax.numpy as jnp

    cache = view.meta.setdefault("_dev_arrays", {})
    arr = cache.get(key)
    if arr is None:
        arr = cache[key] = jnp.asarray(np.asarray(values, dtype))
    return arr


def device_pred_mask(view, preds: Sequence, all_conditions: bool
                     ) -> Optional[np.ndarray]:
    """Evaluate pushdown predicates on device; None when unsupported."""
    if not enabled() or not preds:
        return None
    import jax.numpy as jnp

    sig = []
    args = []
    for c in preds:
        if not c.operands:
            return None
        info = _col_for(view, c.attr)
        if info is None:
            return None
        v = c.operands[0].value
        if info[0] == "dict":
            _, key, codes, dvals = info
            term = _dict_term(c.op, v, dvals)
            if term is None:
                return None
            sig.append(term[0])
            args.append(_dev_array(view, f"dict:{key}", codes, np.int32))
            args.append(jnp.asarray(term[1]))
        else:
            _, key, values = info
            term = _num_term(c.op, v)
            if term is None:
                return None
            sig.append(term[0])
            args.append(_dev_array(view, f"num:{key}", values, np.float32))
            args.append(jnp.float32(term[1]))
    if not sig:
        return None
    fn = _compiled_mask(tuple(sig), all_conditions)
    return np.asarray(fn(*args))


# ---------------------------------------------------------------------------
# the production block plane
# ---------------------------------------------------------------------------

class GridHandle:
    """An in-flight fused metrics grid: the dispatch is async; fetch()
    performs the single packed D2H and unpacks (labels, main, cnt, vcnt).
    Callers launch every block's grid before fetching any, so N blocks
    pipeline their device round trips instead of serializing them."""

    __slots__ = ("labels", "_packed", "_main_shape", "_cnt_shape")

    def __init__(self, labels, packed, main_shape, cnt_shape):
        self.labels = labels
        self._packed = packed
        self._main_shape = main_shape
        self._cnt_shape = cnt_shape

    def fetch(self):
        flat = np.asarray(self._packed)
        m = int(np.prod(self._main_shape))
        c = int(np.prod(self._cnt_shape))
        main = flat[:m].reshape(self._main_shape)
        cnt = flat[m:m + c].reshape(self._cnt_shape)
        vcnt = flat[m + c:].reshape(self._cnt_shape)
        return self.labels, main, cnt, vcnt


def _fmt_group_labels(values: np.ndarray, t: str) -> tuple[np.ndarray, list]:
    """Factorize a host column into int32 codes + formatted label strings,
    matching `engine_metrics._group_slots` label semantics exactly (object
    arrays go through astype("U"): None → "None")."""
    from tempo_tpu.traceql.engine_metrics import _fmt_label

    if values.dtype == object:
        values = values.astype("U")
    u, inv = np.unique(values, return_inverse=True)
    labels = [_fmt_label(v, t) for v in u]
    return inv.astype(np.int32), labels


class BlockScanPlane:
    """Device-resident scan cache for one immutable block.

    Columns adopt LAZILY: the first query touching a column pays one host
    materialization (via the same `eval_expr` path the numpy engine uses,
    so scoping/parent/intrinsic semantics are identical by construction)
    plus one upload; every later query reuses the device copy. A query's
    whole first pass then costs one fused dispatch for the whole block and
    one small boolean D2H — the economics that make the device plane win
    even when the chip sits behind a high-latency link.

    Numeric columns ride the exact (hi, lo) int32 split when integral
    (all intrinsics are); float-valued attribute columns are refused
    (caller falls back to the float64 host plane) — the exactness story
    demanded before this became the default path.
    """

    def __init__(self, views: Sequence, mesh=None) -> None:
        self.views = list(views)
        self.sizes = [int(v.n) for v in self.views]
        self.offsets = np.concatenate(
            [[0], np.cumsum(self.sizes)]).astype(np.int64)
        self.n = int(self.offsets[-1])
        # optional multi-device mesh: span-dim columns shard over its
        # 'data' axis; LUTs/grids replicate, and XLA's SPMD partitioner
        # inserts the cross-device reduce for the grid scatters — the SAME
        # fused kernels run single- or multi-chip (scaling-book recipe:
        # annotate shardings, let the compiler place collectives)
        self.mesh = mesh
        self.time_base_ns = 0
        self._cols: dict = {}          # (kind, key) → entry | None
        self._qr_cache: dict = {}
        self._lock = threading.RLock()
        self.device_bytes = 0
        self.host_bytes = 0            # adoption-side host copies (budget)
        # why the last metrics_grid call refused, + running cause counts
        # (round-4 weak #4: fallbacks were invisible — a workload that
        # silently loses the fused-plane win must show WHERE on /metrics)
        self.last_fallback: "str | None" = None
        self.fallback_causes: dict = {}

    def _bail(self, reason: str) -> str:
        """Record a fused-path refusal cause and return it; `metrics_grid`
        surfaces the cause in its return value so callers never read it
        back off shared plane state (a concurrent query on the same
        cached plane could overwrite it in between)."""
        with self._lock:
            self.last_fallback = reason
            self.fallback_causes[reason] = \
                self.fallback_causes.get(reason, 0) + 1
        return reason

    # -- adoption ----------------------------------------------------------

    def _up(self, arr: np.ndarray, is_span_dim: bool = True):
        import jax
        import jax.numpy as jnp

        if self.mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            # span-dim arrays shard over 'data'; everything else (dict
            # LUTs, row-group tables) replicates. The flag is EXPLICIT
            # from each adoption site (ADVICE r5 #3): a replicated LUT
            # whose length coincidentally equals the span count must not
            # be sharded — XLA SPMD would stay correct but pay gathers/
            # collectives on every kernel using it. The shape check stays
            # as a belt-and-braces guard for span-dim arrays.
            spec = P("data") if (is_span_dim
                                 and getattr(arr, "ndim", 0) >= 1
                                 and arr.shape[0] == self.n) else P()
            d = jax.device_put(np.asarray(arr),
                               NamedSharding(self.mesh, spec))
        else:
            d = jnp.asarray(arr)
        self.device_bytes += int(arr.nbytes)
        from tempo_tpu.obs.jaxruntime import record_device_put
        record_device_put(int(arr.nbytes), "plane_column")
        # per-request attribution: the query that forced this adoption
        # pays the upload — later queries ride the resident copy for free
        from tempo_tpu.obs import querystats
        querystats.add(device_scan_bytes=int(arr.nbytes))
        return d

    def _host_col(self, attr: A.Attribute) -> Optional[Col]:
        with self._lock:
            key = ("host", attr)
            if key in self._cols:
                return self._cols[key]
            cols = [eval_expr(v, attr) for v in self.views]
            t = cols[0].t if cols else NUM
            if not cols or any(c.t != t for c in cols):
                ent = None
            else:
                ent = Col(t, np.concatenate([c.values for c in cols]),
                          np.concatenate([c.exists for c in cols]))
                self.host_bytes += int(ent.values.nbytes + ent.exists.nbytes)
            self._cols[key] = ent
            return ent

    def _arrow_dict_fast(self, attr: A.Attribute):
        """(codes[int32], labels) for name/service straight from the
        on-disk arrow dictionary encoding — an index remap instead of the
        generic object-array factorize (the hottest two columns)."""
        if attr.intrinsic == A.Intrinsic.NAME:
            meta_key, ckey = "name_col", "name"
        elif (attr.intrinsic == A.Intrinsic.NONE
                and attr.name == "service.name"
                and attr.scope in (A.Scope.RESOURCE, A.Scope.NONE)):
            meta_key, ckey = "service_col", "service"
        else:
            return None
        parts = []
        block_ids: dict = {}
        for v in self.views:
            c = v.meta.get(meta_key)
            if c is None:
                return None
            codes, dvals = _dict_codes(v, ckey, c)
            lut = np.empty(len(dvals), np.int32)
            for i, s in enumerate(dvals):
                lut[i] = block_ids.setdefault(s, len(block_ids))
            parts.append(lut[codes] if len(dvals) else codes)
        labels = [s for s, _ in sorted(block_ids.items(),
                                       key=lambda kv: kv[1])]
        cat = (np.concatenate(parts) if parts
               else np.zeros(0, np.int32)).astype(np.int32)
        return cat, labels

    def _ensure_dict(self, attr: A.Attribute):
        """("dict", codes_dev, labels, exists_dev|None) for a STR column."""
        with self._lock:
            key = ("dict", attr)
            if key in self._cols:
                return self._cols[key]
            ent = None
            fast = self._arrow_dict_fast(attr)
            if fast is not None:
                codes, labels = fast
                ent = ("dict", self._up(codes), labels, None)
            else:
                c = self._host_col(attr)
                if c is not None and c.t == STR:
                    codes, labels = _fmt_group_labels(c.values, STR)
                    ex = None if c.exists.all() else self._up(c.exists)
                    ent = ("dict", self._up(codes), labels, ex)
            self._cols[key] = ent
            return ent

    def _ensure_int(self, attr: A.Attribute):
        """("int"|"flt", hi, lo, exists|None, t) — exact numeric column.

        Integral columns keep their int64 value; genuinely FLOAT-valued
        columns (round-4 weak #4: they used to refuse and lose the whole
        fused-plane win) are encoded as ORDER-PRESERVING int64 — the
        float64 bit pattern, with negatives reflected so the int order
        equals the float order (`_sortable_f64`). Literals map through
        the same encoding, so the (hi, lo) limb compare is bit-identical
        to the host engine's float64 compare (ref predicate analog:
        pkg/parquetquery/predicates.go:15-120). NaN values (no consistent
        order) still fall back."""
        with self._lock:
            key = ("int", attr)
            if key in self._cols:
                return self._cols[key]
            c = self._host_col(attr)
            ent = None
            if c is not None and c.t in (NUM, STATUS, KIND, BOOL):
                vals = np.asarray(c.values)
                kind = "int"
                if vals.dtype == bool:
                    iv = vals.astype(np.int64)
                elif vals.dtype == object:
                    iv = None
                else:
                    v = vals.astype(np.float64)
                    chk = v[c.exists]
                    if np.isnan(chk).any():
                        iv = None              # NaN has no order: fallback
                    elif (np.isfinite(chk).all()
                            and (np.floor(chk) == chk).all()
                            and (np.abs(chk) < _INT_MAX).all()):
                        iv = np.where(c.exists, v, 0.0).astype(np.int64)
                    else:
                        kind = "flt"
                        iv = _sortable_f64(np.where(c.exists, v, 0.0))
                if iv is not None:
                    hi, lo = (_split_i64_biased(iv) if kind == "flt"
                              else _split_i64(iv))
                    ex = None if c.exists.all() else self._up(c.exists)
                    ent = (kind, self._up(hi), self._up(lo), ex, c.t)
            self._cols[key] = ent
            return ent

    def _host_group_codes(self, expr):
        """(codes[int32], labels, host_exists|None) for one by()-able key —
        ONE factorization (arrow-dict fast path or host np.unique), cached
        host-side (budget-accounted) and shared by the single-key upload
        and the two-key composition."""
        with self._lock:
            key = ("hgroup", expr)
            if key in self._cols:
                return self._cols[key]
            ent = None
            if isinstance(expr, A.Attribute):
                fast = self._arrow_dict_fast(expr)
                if fast is not None:
                    ent = (fast[0], fast[1], None)
                else:
                    c = self._host_col(expr)
                    if c is not None and c.t in (STR, NUM, STATUS, KIND,
                                                 BOOL):
                        codes, labels = _fmt_group_labels(
                            np.asarray(c.values), c.t)
                        ent = (codes, labels,
                               None if c.exists.all() else c.exists)
            if ent is not None:
                self.host_bytes += int(ent[0].nbytes)
                if ent[2] is not None:
                    self.host_bytes += int(ent[2].nbytes)
            self._cols[key] = ent
            return ent

    def _ensure_group(self, expr):
        """("group", codes_dev, labels, exists_dev|None) for any by()-able
        column type (STR dict, status/kind/num/bool factorized)."""
        with self._lock:
            key = ("group", expr)
            if key in self._cols:
                return self._cols[key]
            h = self._host_group_codes(expr)
            ent = None
            if h is not None:
                codes, labels, hex_ = h
                ex = None if hex_ is None else self._up(hex_)
                ent = ("group", self._up(codes), labels, ex)
            self._cols[key] = ent
            return ent

    # hard construction bound for composed multi-key grids: label lists
    # and code composition stay sane; the caller's max_groups applies per
    # query
    _GROUP2_BUILD_CAP = 1 << 20

    def _ensure_groupn(self, exprs):
        """("groupn", codes_dev, labels, exists|None) for a multi-key
        by() (2 or 3 keys): codes compose mixed-radix on host at adoption
        (c1*|d2|*|d3| + c2*|d3| + c3 — the engine's `group_slots`
        composition, engine_metrics.py), labels are value tuples in the
        same slot order (itertools.product iterates the last key fastest,
        matching the composition). Unobserved combos cost grid rows but
        never emit (the obs-count gate). The whole build runs under the
        plane lock like every other adoption (a racing duplicate would
        double-count device_bytes)."""
        import itertools

        with self._lock:
            key = ("groupn",) + tuple(exprs)
            if key in self._cols:
                return self._cols[key]
            ent = None
            hs = [self._host_group_codes(e) for e in exprs]
            if all(h is not None for h in hs):
                prod = 1
                for h in hs:
                    prod *= len(h[1])
                if 0 < prod <= self._GROUP2_BUILD_CAP:
                    codes = np.zeros(self.n, np.int64)
                    for h in hs:
                        codes = codes * len(h[1]) + h[0]
                    labels = [tuple(p) for p in
                              itertools.product(*[h[1] for h in hs])]
                    ex = None
                    if any(h[2] is not None for h in hs):
                        both = np.ones(self.n, bool)
                        for h in hs:
                            if h[2] is not None:
                                both &= h[2]
                        ex = self._up(both)
                    ent = ("groupn", self._up(codes.astype(np.int32)),
                           labels, ex)
            self._cols[key] = ent
            return ent

    def _ensure_group2(self, e1, e2):
        """Back-compat shim for the former two-key entry point."""
        return self._ensure_groupn((e1, e2))

    def _ensure_value(self, attr):
        """("val", f32_dev, bucket_dev, exists|None): the measured column of
        a metrics aggregate — f32 values (seconds for duration intrinsics,
        mirroring the engine's ns→s divide) + precomputed log2 buckets
        (exact: host float64 bucketing at adoption, ref `Log2Bucketize`
        engine_metrics.go:1392)."""
        from tempo_tpu.traceql.engine_metrics import (_is_duration_attr,
                                                      log2_bucket_np)

        with self._lock:
            key = ("val", attr)
            if key in self._cols:
                return self._cols[key]
            ent = None
            c = self._host_col(attr) if isinstance(attr, A.Attribute) else None
            if c is not None and c.t == NUM and c.values.dtype != object:
                v = np.asarray(c.values, np.float64)
                buckets = log2_bucket_np(np.where(c.exists, v, 1.0))
                scaled = v / 1e9 if _is_duration_attr(attr) else v
                ex = None if c.exists.all() else self._up(c.exists)
                ent = ("val", self._up(scaled.astype(np.float32)),
                       self._up(buckets.astype(np.int32)), ex)
            self._cols[key] = ent
            return ent

    def _ensure_value_log(self, attr):
        """("vlog", z_dev, exists|None): clipped log values (ns domain)
        for the moments-tier quantile grid — host float64 log at
        adoption, f32 cast, the SAME computation MetricsEvaluator's
        dispatch applies to its staged values, so fused and host moment
        sums agree up to f32 scatter order (inside the moments error
        gate). Missing rows log a placeholder 1.0; the value-exists
        mask drops them before they reach the grid."""
        import math

        from tempo_tpu.ops import moments as msk

        with self._lock:
            key = ("vlog", attr)
            if key in self._cols:
                return self._cols[key]
            ent = None
            c = self._host_col(attr) if isinstance(attr, A.Attribute) else None
            if c is not None and c.t == NUM and c.values.dtype != object:
                v = np.asarray(c.values, np.float64)
                z = np.log(np.clip(np.where(c.exists, v, 1.0),
                                   math.exp(msk.QUERY_LO),
                                   math.exp(msk.QUERY_HI))
                           ).astype(np.float32)
                ex = None if c.exists.all() else self._up(c.exists)
                ent = ("vlog", self._up(z), ex)
            self._cols[key] = ent
            return ent

    def _ensure_times(self) -> bool:
        with self._lock:
            if ("times",) in self._cols:
                return self._cols[("times",)] is not None
            cols = [v.col("__startTime") for v in self.views]
            if not cols or any(c is None for c in cols):
                self._cols[("times",)] = None
                return False
            starts = np.concatenate([np.asarray(c.values, np.float64)
                                     for c in cols]).astype(np.int64)
            self.time_base_ns = int(starts.min()) if len(starts) else 0
            hi, lo = _split_i64(starts)
            self._cols[("times",)] = (
                self._up(((starts - self.time_base_ns) / 1e9
                          ).astype(np.float32)),
                self._up(hi), self._up(lo))
            return True

    def _ensure_rgids(self):
        with self._lock:
            if ("rgids",) in self._cols:
                return self._cols[("rgids",)]
            ids = np.repeat(np.arange(len(self.sizes), dtype=np.int32),
                            self.sizes)
            ent = self._cols[("rgids",)] = self._up(ids)
            return ent

    def load_times(self, views: Sequence = ()) -> None:
        """Back-compat shim: time columns now adopt lazily."""
        self._ensure_times()

    # -- plan compilation ---------------------------------------------------

    def _plan_pred(self, c) -> Optional[tuple]:
        """One Condition → (sig entry, args list) or None (unsupported)."""
        import jax.numpy as jnp

        if not c.operands or not isinstance(c.attr, A.Attribute):
            return None
        static = c.operands[0]
        v = static.value
        # nil comparisons prune on the existence mask alone
        if getattr(static, "type", None) == A.StaticType.NIL:
            if c.op not in (A.Op.EQ, A.Op.NEQ):
                return (("const", False), [], [])
            host = self._host_col(c.attr)
            if host is None:
                return None
            want = c.op == A.Op.NEQ
            if host.exists.all():
                return (("const", want), [], [])
            with self._lock:
                ex = self._cols.get(("ex", c.attr))
                if ex is None:
                    ex = self._cols[("ex", c.attr)] = self._up(host.exists)
            return (("nil", want, True), [ex], [])
        lit_t = _STATIC_T.get(getattr(static, "type", None))
        if lit_t is None:
            return None
        if lit_t == STR:
            ent = self._ensure_dict(c.attr)
            if ent is None:
                # a scalar non-STR column compared to a string is
                # incomparable → constant false (the type lattice); list
                # and mixed columns fall back to the host plane
                host = self._host_col(c.attr)
                if host is not None and host.t in (NUM, STATUS, KIND, BOOL):
                    return (("const", False), [], [])
                return None
            # the uploaded lut is cached per (attr, op, value): repeated
            # queries pay ZERO H2D transfers for their predicates. The
            # cache stores (neg, lut) so _dict_term stays the single
            # source of negation truth; entries are budget-accounted and
            # capacity-capped (high-cardinality literal workloads must
            # not grow device memory unboundedly)
            lkey = ("plut", c.attr, c.op, v)
            with self._lock:
                cached = self._cols.get(lkey)
            if cached is None:
                term = _dict_term(c.op, v, ent[2])
                if term is None:
                    return None
                (kind, _, neg), lut = term
                lut_dev = self._up(lut, is_span_dim=False)
                with self._lock:
                    # re-check under the lock: a racing thread may have
                    # inserted the same key while we uploaded — keep its
                    # entry and refund our duplicate's budget accounting
                    again = self._cols.get(lkey)
                    if again is not None:
                        self.device_bytes -= int(lut.nbytes)
                        neg, lut_dev = again
                    else:
                        pluts = [k for k in self._cols if k[0] == "plut"]
                        if len(pluts) >= 256:
                            for k in pluts[:128]:
                                arr = self._cols.pop(k)[1]
                                self.device_bytes -= int(arr.nbytes)
                        self._cols[lkey] = (neg, lut_dev)
            else:
                neg, lut_dev = cached
            has_ex = ent[3] is not None
            args = [ent[1], lut_dev]
            if has_ex:
                args.append(ent[3])
            return (("lut", neg, has_ex), args, [])
        # numeric-family literal
        if c.op not in _NUM_OPS:
            return None
        ent = self._ensure_int(c.attr)
        if ent is None:
            host = self._host_col(c.attr)
            if host is not None and host.t == STR:
                return (("const", False), [], [])  # str col vs num literal
            return None                          # float col → host fallback
        ekind, hi, lo, ex, col_t = ent
        if col_t != lit_t:                       # distinct lattices → false
            return (("const", False), [], [])
        if ekind == "flt":
            # float-valued column: the literal rides the same
            # order-preserving encoding, ops unchanged (monotone map)
            f = float(v if not isinstance(v, bool) else int(v))
            if f != f:                           # NaN literal: host plane
                return None
            lh, ll = _split_lit_biased(
                int(_sortable_f64(np.asarray([f]))[0]))
            has_ex = ex is not None
            args = [hi, lo] + ([ex] if has_ex else [])
            return (("icmp", c.op, has_ex), args, [lh, ll])
        norm = _int_literal(c.op, v if not isinstance(v, bool) else int(v))
        if norm[0] == "const":
            if not norm[1] or ex is None:
                return (("const", norm[1]), [], [])
            # the literal-compare is constant-TRUE for every present value
            # (e.g. `.x != 1.5` on an int column), but spans missing the
            # attribute must still be excluded — the host plane ANDs
            # l.exists (eval._compare) — so emit the existence mask, not
            # a bare const
            return (("nil", True, True), [ex], [])
        _, op2, lit = norm
        lh, ll = _split_lit(lit)
        has_ex = ex is not None
        args = [hi, lo]
        if has_ex:
            args.append(ex)
        return (("icmp", op2, has_ex), args, [lh, ll])

    def _plan(self, preds: Sequence, all_conditions: bool):
        sig, args, ints = [], [], []
        for c in preds:
            got = self._plan_pred(c)
            if got is None:
                return None
            sig.append(got[0])
            args.extend(got[1])
            ints.extend(got[2])
        return tuple(sig), args, ints

    def _ensure_rg_lut(self, row_groups):
        key = ("rglut", tuple(row_groups))
        with self._lock:
            got = self._cols.get(key)
        if got is None:
            lut = np.zeros(len(self.sizes), bool)
            sel = [g for g in row_groups if 0 <= g < len(self.sizes)]
            if sel:
                lut[np.asarray(sel)] = True
            # row-group LUT: replicated, never span-dim (budget-accounted
            # like all uploads)
            got = self._up(lut, is_span_dim=False)
            with self._lock:
                again = self._cols.get(key)
                if again is not None:         # lost an upload race: refund
                    self.device_bytes -= int(lut.nbytes)
                    got = again
                else:
                    rgluts = [k for k in self._cols if k[0] == "rglut"]
                    if len(rgluts) >= 64:
                        for k in rgluts[:32]:
                            self.device_bytes -= int(self._cols.pop(k).nbytes)
                    self._cols[key] = got
        return got

    def _extra_terms(self, time_range, row_groups):
        """Always-AND terms: exact time clip + row-group shard selection.
        Returns (sig, device args, int literals)."""
        sig, args, ints = [], [], []
        if time_range is not None and any(time_range):
            lo_ns, hi_ns = time_range
            if not self._ensure_times():
                return None
            _, thi, tlo = self._cols[("times",)]
            # the host plane compares float64 start values against the
            # literal PROMOTED to float64; round the clip bounds the same
            # way so boundary spans classify identically on both paths
            if lo_ns:
                lh, ll = _split_lit(int(np.float64(lo_ns)))
                sig.append(("icmp", A.Op.GTE, False))
                args.extend([thi, tlo])
                ints.extend([lh, ll])
            if hi_ns:
                lh, ll = _split_lit(int(np.float64(hi_ns)))
                sig.append(("icmp", A.Op.LT, False))
                args.extend([thi, tlo])
                ints.extend([lh, ll])
        if row_groups is not None:
            sig.append(("lut", None, False))
            args.extend([self._ensure_rgids(),
                         self._ensure_rg_lut(row_groups)])
        return tuple(sig), args, ints

    # -- masks --------------------------------------------------------------

    def mask_async(self, preds: Sequence, all_conditions: bool,
                   time_range=None, row_groups=None):
        """Launch the fused block mask; returns a BIT-PACKED device array
        (uint8, big-endian bit order — unpack with `unpack_mask`) or None
        when a predicate shape is unsupported. No sync, no D2H; a single
        packed-literal H2D rides along with the call."""
        plan = self._plan(list(preds), all_conditions)
        if plan is None:
            return None
        extra = self._extra_terms(time_range, row_groups)
        if extra is None:
            return None
        sig, args, ints = plan
        esig, eargs, eints = extra
        fn = _block_mask_kernel(self.n, sig, esig, all_conditions)
        ivec = np.asarray(ints + eints, np.int32)
        # query-class job on the shared device scheduler: live-ingest
        # batches order ahead of scans, the dispatch is accounted, and
        # the launch stays async (the handle returns without a sync)
        from tempo_tpu import sched
        return sched.run(lambda: fn(ivec, *args, *eargs),
                         kernel="plane_packed_mask")

    def mask(self, preds: Sequence, all_conditions: bool,
             time_range=None, row_groups=None) -> Optional[np.ndarray]:
        from tempo_tpu.obs import querystats

        m = self.mask_async(preds, all_conditions, time_range, row_groups)
        if m is None:
            return None
        t0 = time.perf_counter_ns()
        with querystats.stage("device_scan"):
            packed = np.asarray(m)        # the sync point: device → host
        querystats.add(kernel_wall_ns=time.perf_counter_ns() - t0)
        return self.unpack_mask(packed)

    def unpack_mask(self, packed: np.ndarray) -> np.ndarray:
        """Bit-packed device mask → bool[n]."""
        return np.unpackbits(np.asarray(packed, np.uint8))[:self.n]             .astype(bool)

    def split_mask(self, packed: np.ndarray) -> list[np.ndarray]:
        """Bit-packed block mask → per-row-group candidate row arrays."""
        mask = self.unpack_mask(packed)
        return [np.flatnonzero(mask[self.offsets[i]:self.offsets[i + 1]])
                for i in range(len(self.sizes))]

    # -- fused metrics grid -------------------------------------------------

    def metrics_grid(self, m, preds: Sequence, all_conditions: bool,
                     start_ns: int, end_ns: int, step_ns: int,
                     clip_start_ns: int | None = None,
                     clip_end_ns: int | None = None,
                     row_groups=None, max_groups: int = 65536,
                     moments: bool = False):
        """The FULL device metrics path: predicate mask → exact time clip →
        step bucketing → per-group scatter into device grids, one fused
        dispatch over the resident block (SURVEY §3.4's hot loop with zero
        host work per span). Covers every `*_over_time` kind including the
        log2-bucket histogram axis behind `quantile_over_time` /
        `histogram_over_time` (ref `Log2Bucketize` engine_metrics.go:1392).

        `m` is the A.MetricsAggregate. Returns `(handle, cause)`:
        `(None, cause)` when any shape is unsupported (caller falls back
        to the host engine; `cause` is the refusal reason, returned here
        rather than stashed on shared plane state so concurrent queries
        on one cached plane cannot misattribute each other's fallbacks),
        else `(handle, None)` — a GridHandle whose fetch() yields
        (group_label_list, main_grid, obs_count_grid, value_count_grid):
          count/rate       main [G, steps] counts
          min/max/sum/avg  main [G, steps]
          quantile/hist    main [G, steps, 64] bucket counts
        obs counts gate series emission (group matched the filter);
        value counts back avg's companion `__meta: count` series.

        Transfer economics (the plane must win through a high-latency
        device link): per call, H2D is ONE packed int32 literal vector +
        ONE packed f32 vector; D2H is ONE packed grid (the three grids
        concatenate raveled). Launches are async — the caller launches
        every block's grid before fetching any (`db/tempodb.py`).
        """
        import jax
        import jax.numpy as jnp

        kind_tag = {
            A.MetricsKind.RATE: "count",
            A.MetricsKind.COUNT_OVER_TIME: "count",
            A.MetricsKind.MIN_OVER_TIME: "min",
            A.MetricsKind.MAX_OVER_TIME: "max",
            A.MetricsKind.SUM_OVER_TIME: "sum",
            A.MetricsKind.AVG_OVER_TIME: "avg",
            A.MetricsKind.QUANTILE_OVER_TIME: "hist",
            A.MetricsKind.HISTOGRAM_OVER_TIME: "hist",
        }.get(m.kind)
        if moments and m.kind == A.MetricsKind.QUANTILE_OVER_TIME:
            # moments query tier: quantile accumulates a [G, steps, k+3]
            # moment grid (k+1 Chebyshev sums + the two support-bound
            # planes) instead of the log2 bucket axis — add-merge for
            # the sums, max-merge for the bounds, both grid-shaped, so
            # the same packed D2H and combiner conventions apply
            kind_tag = "mom"
        if kind_tag is None or step_ns <= 0 or end_ns <= start_ns:
            return None, self._bail("shape")
        if len(m.by) > 3:
            return None, self._bail("group")
        if not self._ensure_times():
            return None, self._bail("times")

        plan = self._plan(list(preds), all_conditions)
        if plan is None:
            return None, self._bail("predicate")
        clip_lo = max(start_ns, clip_start_ns or start_ns)
        clip_hi = min(end_ns, clip_end_ns or end_ns)
        extra = self._extra_terms((clip_lo, clip_hi), row_groups)
        if extra is None:
            return None, self._bail("times")
        sig, args, ints = plan
        esig, eargs, eints = extra

        if len(m.by) >= 2:
            gent = self._ensure_groupn(tuple(m.by))
            if gent is None or len(gent[2]) > max_groups:
                return None, self._bail("group")
            _, gcodes, glabels, gex = gent
        elif m.by:
            gent = self._ensure_group(m.by[0])
            if gent is None or len(gent[2]) > max_groups:
                return None, self._bail("group")
            _, gcodes, glabels, gex = gent
        else:
            gcodes, glabels, gex = None, [None], None

        from tempo_tpu.ops import moments as _mom
        mom_cols = _mom.QUERY_K + 3
        needs_value = kind_tag in ("min", "max", "sum", "avg", "hist", "mom")
        vargs = []
        if needs_value:
            if m.attr is None:
                return None, self._bail("value")
            if kind_tag == "mom":
                vent = self._ensure_value_log(m.attr)
                if vent is None:
                    return None, self._bail("value")
                _, zvals, vex = vent
                vargs = [zvals]
            else:
                vent = self._ensure_value(m.attr)
                if vent is None:
                    return None, self._bail("value")
                _, vvals, vbuckets, vex = vent
                vargs = [vbuckets if kind_tag == "hist" else vvals]
            if vex is not None:
                vargs.append(vex)
            v_has_ex = vex is not None
        else:
            v_has_ex = False

        n_steps = max(int(-(-(end_ns - start_ns) // step_ns)), 1)
        n_groups = len(glabels)
        grid_width = {"hist": 64, "mom": mom_cols}.get(kind_tag, 1)
        if n_groups * n_steps * grid_width * 4 > 1 << 28:
            return None, self._bail("grid_size")
        delta_ns = self.time_base_ns - start_ns
        q_steps = delta_ns // step_ns              # exact whole steps (host)
        frac_ns = delta_ns - q_steps * step_ns     # in [0, step_ns)
        if abs(q_steps) > 1 << 30:
            return None, self._bail("window")

        # exact step bucketing is available when the grid is small enough
        # that 16-bit limb products stay in int32 and the f32 estimate is
        # provably within one step of the truth (guard below); outside it
        # the f32 path applies with a documented boundary tolerance
        exact = (n_steps <= (1 << 14) and abs(q_steps) <= (1 << 20)
                 and start_ns >= 0 and step_ns > 0
                 and start_ns + (n_steps + 1) * step_ns < (1 << 63))
        key = (sig, esig, all_conditions, kind_tag, n_groups, n_steps,
               gcodes is not None, gex is not None, v_has_ex, exact)
        with self._lock:
            fn = self._qr_cache.get(key)
        if fn is None:
            n = self.n

            def build(rel, thi, tlo, ivec, fvec, gcodes, gex, vcol, vex,
                      *margs):
                q_steps = ivec[0]
                frac_s, step_s = fvec[0], fvec[1]
                pred_masks, used, k = _term_masks(jnp, sig, margs, n,
                                                  ivec, 1)
                extra_masks, _, _ = _term_masks(jnp, esig, margs[used:], n,
                                                ivec, k)
                mask = None
                for pm in pred_masks:
                    mask = pm if mask is None else (
                        mask & pm if all_conditions else mask | pm)
                if mask is None:
                    mask = jnp.ones((n,), bool)
                for em in extra_masks:
                    mask = mask & em
                # step index split for precision: the whole-step offset
                # between window start and block base is EXACT int host
                # math; f32 only covers the sub-step fraction + intra-
                # block offsets. The f32 estimate is then snapped to the
                # EXACT integer floor((t_ns - start_ns) / step_ns) by
                # comparing the resident (hi, lo) int timestamps against
                # the limb-computed boundaries start_ns + q*step_ns — the
                # host engine's float64 bucketing is exact for ns < 2^53,
                # so boundary spans classify identically on both planes.
                local = rel + frac_s
                step_idx = q_steps + jnp.floor(local / step_s
                                               ).astype(jnp.int32)
                if exact:
                    # ivec tail: step_ns 16-bit limbs (4), start_ns 16-bit
                    # limbs (4), low-to-high; the guard (n_steps <= 2^14,
                    # |q_steps| <= 2^20) bounds the f32 error under one
                    # step and keeps every limb product inside int32
                    sl = [ivec[-8 + i] for i in range(4)]
                    ul = [ivec[-4 + i] for i in range(4)]
                    # t_ns = thi * 2^31 + tlo (the 33/31 _split_i64 form;
                    # tlo is non-negative) → 16-bit limbs low-to-high
                    w = [tlo & 0xffff,
                         ((tlo >> 16) & 0x7fff) | ((thi & 1) << 15),
                         (thi >> 1) & 0xffff,
                         (thi >> 17) & 0xffff]

                    def ge_boundary(q):
                        # t_ns >= start_ns + q*step_ns, via 16-bit limbs
                        carry = 0
                        r = []
                        for i in range(4):
                            v = ul[i] + q * sl[i] + carry
                            r.append(v & 0xffff)
                            carry = v >> 16
                        ge = w[0] >= r[0]
                        for wi, ri in zip(w[1:], r[1:]):
                            ge = jnp.where(wi == ri, ge, wi > ri)
                        return ge

                    qc = jnp.clip(step_idx, 0, n_steps)
                    # the guard bounds |estimate - truth| <= 1, so the
                    # true index is qc+1, qc, or qc-1 (qc-1 is -1 when
                    # the span truly precedes the window, since qc
                    # clips at 0 — the ok mask drops it)
                    step_idx = jnp.where(
                        ge_boundary(qc + 1), qc + 1,
                        jnp.where(ge_boundary(qc), qc, qc - 1))
                ok = mask & (step_idx >= 0) & (step_idx < n_steps)
                if gcodes is not None:
                    slots = gcodes
                    if gex is not None:
                        ok = ok & gex
                else:
                    slots = jnp.zeros((n,), jnp.int32)
                steps = jnp.clip(step_idx, 0, n_steps - 1)
                # obs counts IGNORE the value-exists mask: the host engine
                # registers a group's series when any span matches the
                # filter, even if the measured attribute is missing on all
                # of them (zero/inf series) — emission must agree
                obs_slots = jnp.where(ok, slots, n_groups)
                cnt = jnp.zeros((n_groups, n_steps), jnp.float32
                                ).at[obs_slots, steps].add(
                    jnp.where(ok, 1.0, 0.0), mode="drop")
                pack = lambda main, vcnt: jnp.concatenate(
                    [main.reshape(-1), cnt.reshape(-1), vcnt.reshape(-1)])
                if kind_tag == "count":
                    return pack(cnt, cnt)
                okv = ok & vex if vex is not None else ok
                slots = jnp.where(okv, slots, n_groups)
                ones = jnp.where(okv, 1.0, 0.0)
                if kind_tag == "hist":
                    grid = jnp.zeros((n_groups, n_steps, 64), jnp.float32)
                    grid = grid.at[slots, steps, vcol].add(ones, mode="drop")
                    return pack(grid, cnt)
                if kind_tag == "mom":
                    # vcol is the clipped log-ns value; the Chebyshev
                    # recurrence runs on device — the SAME basis the host
                    # evaluator scatters — and the two support-bound
                    # planes ride the last two columns of the one grid
                    # (add-merge sums, max-merge bounds; non-matching
                    # rows carry slot == n_groups and drop)
                    c0 = (_mom.QUERY_LO + _mom.QUERY_HI) / 2.0
                    h0 = (_mom.QUERY_HI - _mom.QUERY_LO) / 2.0
                    sb = jnp.clip((vcol - c0) / h0, -1.0, 1.0)
                    basis = jnp.stack(
                        _mom.chebyshev_basis(sb, _mom.QUERY_K), axis=-1)
                    mcols = jnp.arange(_mom.QUERY_K + 1, dtype=jnp.int32)
                    grid = jnp.zeros((n_groups, n_steps, mom_cols),
                                     jnp.float32)
                    grid = grid.at[slots[:, None], steps[:, None],
                                   mcols[None, :]].add(basis, mode="drop")
                    grid = grid.at[slots, steps, _mom.QUERY_K + 1].max(
                        vcol - _mom.QUERY_LO, mode="drop")
                    grid = grid.at[slots, steps, _mom.QUERY_K + 2].max(
                        _mom.QUERY_HI - vcol, mode="drop")
                    return pack(grid, cnt)
                vals = vcol
                if kind_tag == "min":
                    grid = jnp.full((n_groups, n_steps), jnp.inf,
                                    jnp.float32)
                    grid = grid.at[slots, steps].min(
                        jnp.where(okv, vals, jnp.inf), mode="drop")
                    return pack(grid, cnt)
                if kind_tag == "max":
                    grid = jnp.full((n_groups, n_steps), -jnp.inf,
                                    jnp.float32)
                    grid = grid.at[slots, steps].max(
                        jnp.where(okv, vals, -jnp.inf), mode="drop")
                    return pack(grid, cnt)
                grid = jnp.zeros((n_groups, n_steps), jnp.float32
                                 ).at[slots, steps].add(
                    jnp.where(okv, vals, 0.0), mode="drop")
                if kind_tag == "avg":
                    # avg's companion count series counts VALUED spans only
                    vcnt = jnp.zeros((n_groups, n_steps), jnp.float32
                                     ).at[slots, steps].add(ones,
                                                            mode="drop")
                    return pack(grid, vcnt)
                return pack(grid, cnt)

            from tempo_tpu.obs.jaxruntime import instrumented_jit
            fn = instrumented_jit(build, name="plane_query_range_grid")
            with self._lock:
                if len(self._qr_cache) >= 64:
                    self._qr_cache.pop(next(iter(self._qr_cache)))
                fn = self._qr_cache.setdefault(key, fn)

        ivals = [q_steps] + ints + eints
        if exact:
            ivals += [(step_ns >> s) & 0xffff for s in (0, 16, 32, 48)]
            ivals += [(start_ns >> s) & 0xffff for s in (0, 16, 32, 48)]
        ivec = np.asarray(ivals, np.int32)
        fvec = np.asarray([frac_ns / 1e9, step_ns / 1e9], np.float32)
        trel, thi, tlo = self._cols[("times",)]
        # fused grid launch rides the scheduler's query class (async —
        # the GridHandle fetch is the only sync point)
        from tempo_tpu import sched
        packed = sched.run(
            lambda: fn(trel, thi, tlo, ivec, fvec,
                       gcodes, gex, vargs[0] if vargs else None,
                       vargs[1] if len(vargs) > 1 else None,
                       *args, *eargs),
            kernel="plane_query_range_grid")
        main_shape = ((n_groups, n_steps, 64) if kind_tag == "hist"
                      else (n_groups, n_steps, mom_cols)
                      if kind_tag == "mom" else (n_groups, n_steps))
        return GridHandle(glabels, packed, main_shape,
                          (n_groups, n_steps)), None

    # -- back-compat wrapper (bench/tests from round 3) ---------------------

    def query_range_grid(self, preds: Sequence, all_conditions: bool,
                         group: str | None, start_ns: int, end_ns: int,
                         step_ns: int):
        """rate/count grid keyed by the legacy "name"/"service" group
        names; returns (labels, grid ndarray) or None."""
        by = ()
        if group == "name":
            by = (A.Attribute.intrinsic_of(A.Intrinsic.NAME),)
        elif group == "service":
            by = (A.Attribute("service.name", A.Scope.RESOURCE),)
        m = A.MetricsAggregate(kind=A.MetricsKind.COUNT_OVER_TIME, by=by)
        got, _cause = self.metrics_grid(m, preds, all_conditions, start_ns,
                                        end_ns, step_ns)
        if got is None:
            return None
        labels, main, _cnt, _vcnt = got.fetch()
        return labels, main
