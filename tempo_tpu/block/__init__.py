"""Block encoding plane: columnar (parquet) blocks, bloom filters, WAL
(SURVEY.md §2.2 'encoding/vparquet4' + 'wal', re-designed one-row-per-span
with trace segment keys + nested-set coordinates for TPU-friendly scans)."""

from tempo_tpu.block.bloom import BloomFilter, ShardedBloom, shard_name
from tempo_tpu.block.reader import BackendBlock
from tempo_tpu.block.schema import (
    VERSION,
    block_schema,
    nested_set,
    spans_by_trace,
    traces_to_table,
)
from tempo_tpu.block.wal import WALBlock, rescan_blocks
from tempo_tpu.block.writer import DATA_NAME, INDEX_NAME, write_block

__all__ = [
    "BackendBlock", "BloomFilter", "DATA_NAME", "INDEX_NAME", "ShardedBloom",
    "VERSION", "WALBlock", "block_schema", "nested_set", "rescan_blocks",
    "shard_name", "spans_by_trace", "traces_to_table", "write_block",
]
