"""Columnar block schema: flat span rows + nested-set tree coordinates.

The TPU-first re-design of vparquet4's nested one-row-per-trace schema
(`tempodb/encoding/vparquet4/schema.go:120-258`). Instead of nested lists
(trace → resource → scope → span), a block is ONE ROW PER SPAN with a
`trace_idx` segment key: rows of a trace are contiguous (sorted by trace id),
so per-trace reductions are `segment_sum`-style ops over a monotone key — the
shape XLA wants — and span columns map 1:1 onto SpanBatch SoA tensors with
zero restructuring at fetch time.

Structural TraceQL operators (`>`, `>>`, `~`, `&>>`) use the same nested-set
model the reference computes (`vparquet4/nested_set_model.go`): each span
gets (nested_left, nested_right, parent_row); descendant = interval
containment, child = parent_row equality — both pure vector compares.

Attributes: per-type parallel list columns (string/int/double/bool × span/
resource scope), matching vparquet4's typed attr columns, plus dedicated
promoted columns from `BlockMeta.dedicated_columns`
(`vparquet4/dedicated_columns.go`). Resource attrs are denormalized onto
span rows; parquet dictionary+RLE encoding reclaims the redundancy on disk.

Events and links are kept as list columns (vparquet4 event/link columns,
`schema.go:162-236`).
"""

from __future__ import annotations

from typing import Any, Iterable, Sequence

import numpy as np
import pyarrow as pa

VERSION = "vtpu1"

# Columns every block carries, in schema order.
CORE_FIELDS = [
    ("trace_id", pa.binary(16)),
    ("trace_idx", pa.int32()),
    ("span_id", pa.binary(8)),
    ("parent_span_id", pa.binary(8)),
    ("parent_row", pa.int32()),      # parent span's index WITHIN its trace; -1 root
    ("nested_left", pa.int32()),
    ("nested_right", pa.int32()),
    ("is_root", pa.bool_()),
    ("name", pa.string()),
    ("service", pa.string()),
    ("kind", pa.int8()),
    ("status_code", pa.int8()),
    ("status_message", pa.string()),
    ("start_unix_nano", pa.int64()),
    ("duration_ns", pa.int64()),
    # typed generic attributes (span scope)
    ("sattr_str_keys", pa.list_(pa.string())),
    ("sattr_str_vals", pa.list_(pa.string())),
    ("sattr_int_keys", pa.list_(pa.string())),
    ("sattr_int_vals", pa.list_(pa.int64())),
    ("sattr_f64_keys", pa.list_(pa.string())),
    ("sattr_f64_vals", pa.list_(pa.float64())),
    ("sattr_bool_keys", pa.list_(pa.string())),
    ("sattr_bool_vals", pa.list_(pa.bool_())),
    # typed generic attributes (resource scope)
    ("rattr_str_keys", pa.list_(pa.string())),
    ("rattr_str_vals", pa.list_(pa.string())),
    ("rattr_int_keys", pa.list_(pa.string())),
    ("rattr_int_vals", pa.list_(pa.int64())),
    ("rattr_f64_keys", pa.list_(pa.string())),
    ("rattr_f64_vals", pa.list_(pa.float64())),
    ("rattr_bool_keys", pa.list_(pa.string())),
    ("rattr_bool_vals", pa.list_(pa.bool_())),
    # events / links
    ("event_times", pa.list_(pa.int64())),
    ("event_names", pa.list_(pa.string())),
    ("link_trace_ids", pa.list_(pa.binary(16))),
    ("link_span_ids", pa.list_(pa.binary(8))),
]


def dedicated_field_name(scope: str, index: int) -> str:
    return f"ded_{'s' if scope == 'span' else 'r'}_{index:02d}"


def block_schema(dedicated: Sequence[Any] = ()) -> pa.Schema:
    fields = [pa.field(n, t) for n, t in CORE_FIELDS]
    for i, col in enumerate(dedicated):
        fields.append(pa.field(dedicated_field_name(col.scope, i), pa.string()))
    return pa.schema(fields)


# ---------------------------------------------------------------------------
# Nested-set numbering (vparquet4/nested_set_model.go)
# ---------------------------------------------------------------------------

def nested_set(span_ids: list[bytes], parent_ids: list[bytes]) -> tuple[list, list, list]:
    """Assign (left, right, parent_idx) per span of ONE trace.

    Orphans (parent not present) and cycle remnants are treated as roots,
    as the reference does. Iterative DFS; left/right are 1-based within the
    trace; parent_idx is the LOCAL span index (-1 for roots).
    """
    n = len(span_ids)
    row_of = {sid: i for i, sid in enumerate(span_ids)}
    children: list[list[int]] = [[] for _ in range(n)]
    parent_idx = [-1] * n
    for i, pid in enumerate(parent_ids):
        p = row_of.get(pid) if pid and pid != b"\x00" * 8 else None
        if p is not None and p != i:
            parent_idx[i] = p
            children[p].append(i)
    roots = [i for i in range(n) if parent_idx[i] == -1]
    left = [0] * n
    right = [0] * n
    counter = 1
    visited = [False] * n
    for r in roots:
        # stack of (node, child_cursor)
        stack = [(r, 0)]
        visited[r] = True
        left[r] = counter
        counter += 1
        while stack:
            node, cur = stack[-1]
            if cur < len(children[node]):
                stack[-1] = (node, cur + 1)
                c = children[node][cur]
                if not visited[c]:
                    visited[c] = True
                    left[c] = counter
                    counter += 1
                    stack.append((c, 0))
            else:
                right[node] = counter
                counter += 1
                stack.pop()
    # components unreachable from any root contain a parent cycle. Break ONE
    # edge per cycle (making that node a root) and DFS-number the component,
    # preserving every non-cycle parent link.
    for start in range(n):
        if visited[start]:
            continue
        # walk up the parent chain to find the cycle node
        path_set = set()
        node = start
        while node not in path_set and not visited[node] and parent_idx[node] != -1:
            path_set.add(node)
            node = parent_idx[node]
        if not visited[node]:
            # `node` is on the cycle: break its parent edge
            p = parent_idx[node]
            if p != -1:
                children[p].remove(node)
                parent_idx[node] = -1
            stack = [(node, 0)]
            visited[node] = True
            left[node] = counter
            counter += 1
            while stack:
                cur_node, cur = stack[-1]
                if cur < len(children[cur_node]):
                    stack[-1] = (cur_node, cur + 1)
                    c = children[cur_node][cur]
                    if not visited[c]:
                        visited[c] = True
                        left[c] = counter
                        counter += 1
                        stack.append((c, 0))
                else:
                    right[cur_node] = counter
                    counter += 1
                    stack.pop()
    return left, right, parent_idx


# ---------------------------------------------------------------------------
# Trace spans → arrow rows
# ---------------------------------------------------------------------------

def _split_attrs(attrs: dict[str, Any]):
    sk, sv, ik, iv, fk, fv, bk, bv = [], [], [], [], [], [], [], []
    for k, v in (attrs or {}).items():
        if isinstance(v, bool):
            bk.append(k); bv.append(v)
        elif isinstance(v, int):
            ik.append(k); iv.append(v)
        elif isinstance(v, float):
            fk.append(k); fv.append(v)
        elif isinstance(v, str):
            sk.append(k); sv.append(v)
        else:  # arrays/kvlists/bytes stringified, like attrToParquet (schema.go:253)
            sk.append(k); sv.append(str(v))
    return sk, sv, ik, iv, fk, fv, bk, bv


def traces_to_table(traces: Iterable[tuple[bytes, list[dict]]],
                    dedicated: Sequence[Any] = ()) -> pa.Table:
    """[(trace_id, [span dicts])] → arrow table in block row order.

    Traces MUST be pre-sorted by trace_id; spans of each trace are laid out
    parent-before-child (DFS order is not required; rows keep input order).
    """
    cols: dict[str, list] = {name: [] for name, _ in CORE_FIELDS}
    ded_names = [dedicated_field_name(c.scope, i) for i, c in enumerate(dedicated)]
    for dn in ded_names:
        cols[dn] = []
    for t_idx, (trace_id, spans) in enumerate(traces):
        sids = [s.get("span_id", b"") for s in spans]
        pids = [s.get("parent_span_id", b"") for s in spans]
        left, right, parent_local = nested_set(sids, pids)
        for j, s in enumerate(spans):
            cols["trace_id"].append(trace_id.ljust(16, b"\0")[:16])
            cols["trace_idx"].append(t_idx)
            cols["span_id"].append((sids[j] or b"").ljust(8, b"\0")[:8])
            cols["parent_span_id"].append((pids[j] or b"").ljust(8, b"\0")[:8])
            cols["parent_row"].append(parent_local[j])
            cols["nested_left"].append(left[j])
            cols["nested_right"].append(right[j])
            cols["is_root"].append(parent_local[j] < 0)
            cols["name"].append(s.get("name", ""))
            cols["service"].append(s.get("service", ""))
            cols["kind"].append(s.get("kind", 0))
            cols["status_code"].append(s.get("status_code", 0))
            cols["status_message"].append(s.get("status_message", ""))
            start = int(s.get("start_unix_nano", 0))
            cols["start_unix_nano"].append(start)
            cols["duration_ns"].append(max(int(s.get("end_unix_nano", start)) - start, 0))
            sk, sv, ik, iv, fk, fv, bk, bv = _split_attrs(s.get("attrs"))
            cols["sattr_str_keys"].append(sk); cols["sattr_str_vals"].append(sv)
            cols["sattr_int_keys"].append(ik); cols["sattr_int_vals"].append(iv)
            cols["sattr_f64_keys"].append(fk); cols["sattr_f64_vals"].append(fv)
            cols["sattr_bool_keys"].append(bk); cols["sattr_bool_vals"].append(bv)
            rk, rv, rik, riv, rfk, rfv, rbk, rbv = _split_attrs(s.get("res_attrs"))
            cols["rattr_str_keys"].append(rk); cols["rattr_str_vals"].append(rv)
            cols["rattr_int_keys"].append(rik); cols["rattr_int_vals"].append(riv)
            cols["rattr_f64_keys"].append(rfk); cols["rattr_f64_vals"].append(rfv)
            cols["rattr_bool_keys"].append(rbk); cols["rattr_bool_vals"].append(rbv)
            evs = s.get("events") or []
            cols["event_times"].append([int(e.get("time_unix_nano", 0)) for e in evs])
            cols["event_names"].append([str(e.get("name", "")) for e in evs])
            links = s.get("links") or []
            cols["link_trace_ids"].append(
                [bytes(l.get("trace_id", b"")).ljust(16, b"\0")[:16] for l in links])
            cols["link_span_ids"].append(
                [bytes(l.get("span_id", b"")).ljust(8, b"\0")[:8] for l in links])
            for dn, dc in zip(ded_names, dedicated):
                src = s.get("attrs") if dc.scope == "span" else s.get("res_attrs")
                v = (src or {}).get(dc.name)
                cols[dn].append(None if v is None else str(v))
    schema = block_schema(dedicated)
    return pa.Table.from_pydict({n: cols[n] for n in schema.names}, schema=schema)


def table_stats(table: pa.Table) -> dict:
    """Aggregates the writer stores in BlockMeta."""
    n = table.num_rows
    if n == 0:
        return {"total_spans": 0, "total_objects": 0, "start_time": 0.0, "end_time": 0.0}
    start = table.column("start_unix_nano").to_numpy()
    dur = table.column("duration_ns").to_numpy()
    tidx = table.column("trace_idx").to_numpy()
    return {
        "total_spans": int(n),
        "total_objects": int(tidx.max()) + 1,
        "start_time": float(start.min() / 1e9),
        "end_time": float((start + dur).max() / 1e9),
    }


def spans_by_trace(spans: Iterable[dict]) -> list[tuple[bytes, list[dict]]]:
    """Group flat span dicts by trace id, sorted by trace id (block order) —
    the regroup the distributor does in `requestsByTraceID`."""
    groups: dict[bytes, list[dict]] = {}
    for s in spans:
        groups.setdefault(bytes(s.get("trace_id", b"")), []).append(s)
    return sorted(groups.items())
