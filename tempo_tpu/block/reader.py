"""BackendBlock reader: trace-by-ID, columnar scan batches, tag scans.

Read side of the block encoding (`vparquet4/block_findtracebyid.go`,
`block_traceql.go`, `block_search_tags.go`). All object reads go through the
RawReader (so the role-keyed cache layer and, later, hedging apply); parquet
row groups are fetched with byte-range reads via a small file adapter.

The scan interface hands the query engines *column batches*: dicts of numpy
arrays per row group — the staging format the TraceQL mask-algebra engine
turns into device tensors (replacing the reference's pointer-chasing
`parquetquery` iterator tree, `pkg/parquetquery/iters.go`).
"""

from __future__ import annotations

import io
import json
from typing import Iterator, Sequence

import numpy as np
import pyarrow.parquet as pq

from tempo_tpu.backend.meta import BlockMeta
from tempo_tpu.backend.raw import DoesNotExist, RawReader, block_keypath
from tempo_tpu.obs import querystats
from tempo_tpu.block import schema as bs
from tempo_tpu.block.bloom import BloomFilter, shard_name
from tempo_tpu.block.writer import DATA_NAME, INDEX_NAME


class _RangeFile(io.RawIOBase):
    """File-like over RawReader byte-range reads (parquet footer/row groups)."""

    def __init__(self, r: RawReader, name: str, kp, size: int):
        self._r = r
        self._name = name
        self._kp = kp
        self._size = size
        self._pos = 0

    def seekable(self) -> bool:
        return True

    def readable(self) -> bool:
        return True

    def seek(self, off: int, whence: int = 0) -> int:
        self._pos = {0: off, 1: self._pos + off, 2: self._size + off}[whence]
        return self._pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._size - self._pos
        data = self._r.read_range(self._name, self._kp, self._pos, n)
        self._pos += len(data)
        return data

    def size(self) -> int:
        return self._size


class BackendBlock:
    """One immutable block in object storage."""

    def __init__(self, r: RawReader, meta: BlockMeta):
        self.r = r
        self.meta = meta
        self.kp = block_keypath(meta.block_id, meta.tenant_id)
        self._pf: pq.ParquetFile | None = None
        self._index: list[dict] | None = None

    # -- plumbing ----------------------------------------------------------

    def parquet_file(self) -> pq.ParquetFile:
        if self._pf is None:
            size = self.meta.size_bytes
            if size <= 0:
                size = self.r.size(DATA_NAME, self.kp)  # type: ignore[attr-defined]
            self._pf = pq.ParquetFile(
                _RangeFile(self.r, DATA_NAME, self.kp, size))
        return self._pf

    def row_group_index(self) -> list[dict]:
        if self._index is None:
            try:
                doc = json.loads(self.r.read(INDEX_NAME, self.kp))
                self._index = doc["row_groups"]
            except DoesNotExist:
                self._index = []
        return self._index

    # -- trace by id (`block_findtracebyid.go`) -----------------------------

    def _bloom_maybe(self, trace_id: bytes) -> bool:
        shard = (trace_id[0] if trace_id else 0) % max(self.meta.bloom_shard_count, 1)
        try:
            bf = BloomFilter.from_bytes(self.r.read(shard_name(shard), self.kp))
        except DoesNotExist:
            return True  # no bloom → must scan
        return trace_id in bf

    def find_trace_by_id(self, trace_id: bytes) -> list[dict] | None:
        """Spans of one trace as flat dicts, or None. Bloom probe → row-group
        binary search on the index bounds → single-group read."""
        tid = bytes(trace_id).ljust(16, b"\0")[:16]
        if not self._bloom_maybe(tid):
            querystats.add(blocks_skipped=1)      # bloom prune
            return None
        hexid = tid.hex()
        pf = self.parquet_file()
        index = self.row_group_index()
        if index:
            rgs = [i for i, g in enumerate(index)
                   if g["min_trace_id"] <= hexid <= g["max_trace_id"]]
        else:
            rgs = list(range(pf.num_row_groups))  # index lost: full scan
        if not rgs:
            querystats.add(blocks_skipped=1)      # row-group bounds prune
            return None
        querystats.add(blocks_scanned=1)
        out: list[dict] = []
        for rg in rgs:
            with querystats.stage("block_fetch"):
                tbl = pf.read_row_group(rg)
            querystats.add(inspected_bytes=tbl.nbytes,
                           inspected_spans=tbl.num_rows)
            sel = np.asarray(tbl.column("trace_id").to_numpy(zero_copy_only=False)) == tid
            if sel.any():
                out.extend(_rows_to_spans(tbl, np.flatnonzero(sel)))
        return out or None

    # -- columnar scan -----------------------------------------------------

    def column_batches(self, columns: Sequence[str] | None = None,
                       row_groups: Sequence[int] | None = None) -> Iterator[dict]:
        """Yield {column: numpy array} per row group (+ '_row_offset', '_rows').

        List-typed columns come back as arrow arrays (offsets+values);
        fixed-width columns as numpy. The caller picks only the columns its
        compiled conditions touch — the pushdown analog of `AllConditions`.
        """
        pf = self.parquet_file()
        index = self.row_group_index()
        rgs = range(pf.num_row_groups) if row_groups is None else row_groups
        for rg in rgs:
            with querystats.stage("block_fetch"):
                tbl = pf.read_row_group(rg, columns=list(columns) if columns else None)
            querystats.add(inspected_bytes=tbl.nbytes)
            out: dict = {"_rows": tbl.num_rows}
            out["_row_offset"] = index[rg]["row_offset"] if rg < len(index) else None
            for name in tbl.schema.names:
                col = tbl.column(name)
                if pa_is_fixed(col.type):
                    out[name] = col.to_numpy(zero_copy_only=False)
                else:
                    out[name] = col.combine_chunks()
            yield out

    def dedicated_column_name(self, scope: str, attr: str) -> str | None:
        for i, c in enumerate(self.meta.dedicated_columns):
            if c.scope == scope and c.name == attr:
                return bs.dedicated_field_name(scope, i)
        return None


def pa_is_fixed(t) -> bool:
    import pyarrow as pa

    return not (pa.types.is_list(t) or pa.types.is_large_list(t))


def _rows_to_spans(tbl, rows: np.ndarray) -> list[dict]:
    """Materialize selected rows back into flat span dicts (find-by-id path)."""
    cols = {n: tbl.column(n) for n in tbl.schema.names}
    out = []
    for r in rows.tolist():
        attrs: dict = {}
        for kcol, vcol in (("sattr_str_keys", "sattr_str_vals"),
                           ("sattr_int_keys", "sattr_int_vals"),
                           ("sattr_f64_keys", "sattr_f64_vals"),
                           ("sattr_bool_keys", "sattr_bool_vals")):
            ks = cols[kcol][r].as_py() or []
            vs = cols[vcol][r].as_py() or []
            attrs.update(zip(ks, vs))
        res_attrs: dict = {}
        for kcol, vcol in (("rattr_str_keys", "rattr_str_vals"),
                           ("rattr_int_keys", "rattr_int_vals"),
                           ("rattr_f64_keys", "rattr_f64_vals"),
                           ("rattr_bool_keys", "rattr_bool_vals")):
            ks = cols[kcol][r].as_py() or []
            vs = cols[vcol][r].as_py() or []
            res_attrs.update(zip(ks, vs))
        start = cols["start_unix_nano"][r].as_py()
        out.append({
            "trace_id": cols["trace_id"][r].as_py(),
            "span_id": cols["span_id"][r].as_py(),
            "parent_span_id": cols["parent_span_id"][r].as_py(),
            "name": cols["name"][r].as_py(),
            "service": cols["service"][r].as_py(),
            "kind": cols["kind"][r].as_py(),
            "status_code": cols["status_code"][r].as_py(),
            "status_message": cols["status_message"][r].as_py(),
            "start_unix_nano": start,
            "end_unix_nano": start + cols["duration_ns"][r].as_py(),
            "attrs": attrs,
            "res_attrs": res_attrs,
            "events": [{"time_unix_nano": t, "name": n} for t, n in
                       zip(cols["event_times"][r].as_py() or [],
                           cols["event_names"][r].as_py() or [])],
            "links": [{"trace_id": t, "span_id": s} for t, s in
                      zip(cols["link_trace_ids"][r].as_py() or [],
                          cols["link_span_ids"][r].as_py() or [])],
        })
    return out
