"""Sharded bloom filters over trace IDs.

Analog of the reference's bloom layer (`tempodb/encoding/common` ShardedBloomFilter,
consumed by `vparquet4/block_findtracebyid.go`): trace-by-ID first probes the
bloom shard owning the ID and skips the block entirely on a miss. Shards are
selected by the first trace-ID byte so a reader fetches exactly one shard
object (`bloom-<n>`) per probe.

Implementation: classic m-bit/k-hash bloom backed by a numpy bit array;
the k probe positions come from blake2b-derived double hashing, so filters
are deterministic across processes (no Python hash randomization).
"""

from __future__ import annotations

import hashlib
import math

import numpy as np


def _h2(item: bytes) -> tuple[int, int]:
    d = hashlib.blake2b(item, digest_size=16).digest()
    return int.from_bytes(d[:8], "little"), int.from_bytes(d[8:], "little")


class BloomFilter:
    def __init__(self, n_items: int, fpp: float = 0.01) -> None:
        n = max(n_items, 1)
        m = int(-n * math.log(max(min(fpp, 0.5), 1e-9)) / (math.log(2) ** 2))
        self.m = max(64, (m + 7) & ~7)  # byte-aligned
        self.k = max(1, round(self.m / n * math.log(2)))
        self.bits = np.zeros(self.m, dtype=bool)

    def add(self, item: bytes) -> None:
        h1, h2 = _h2(item)
        for i in range(self.k):
            # wrap to 64 bits to match the vectorized uint64 arithmetic
            self.bits[((h1 + i * h2) & 0xFFFFFFFFFFFFFFFF) % self.m] = True

    def add_many(self, items: list[bytes]) -> None:
        if not items:
            return
        hs = np.array([_h2(it) for it in items], dtype=np.uint64)  # [n, 2]
        ks = np.arange(self.k, dtype=np.uint64)[None, :]
        pos = (hs[:, 0:1] + ks * hs[:, 1:2]) % np.uint64(self.m)
        self.bits[pos.reshape(-1)] = True

    def __contains__(self, item: bytes) -> bool:
        h1, h2 = _h2(item)
        return all(self.bits[((h1 + i * h2) & 0xFFFFFFFFFFFFFFFF) % self.m]
                   for i in range(self.k))

    def to_bytes(self) -> bytes:
        head = self.m.to_bytes(8, "little") + self.k.to_bytes(8, "little")
        return head + np.packbits(self.bits).tobytes()

    @staticmethod
    def from_bytes(data: bytes) -> "BloomFilter":
        m = int.from_bytes(data[:8], "little")
        k = int.from_bytes(data[8:16], "little")
        bf = BloomFilter.__new__(BloomFilter)
        bf.m, bf.k = m, k
        bf.bits = np.unpackbits(np.frombuffer(data[16:], np.uint8))[:m].astype(bool)
        return bf


class ShardedBloom:
    """`bloom_shard_count` filters; shard = first trace-ID byte % shards."""

    def __init__(self, shard_count: int, n_items: int, fpp: float = 0.01) -> None:
        self.shard_count = max(1, shard_count)
        per = max(1, n_items // self.shard_count)
        self.shards = [BloomFilter(per, fpp) for _ in range(self.shard_count)]

    def shard_of(self, trace_id: bytes) -> int:
        return (trace_id[0] if trace_id else 0) % self.shard_count

    def add(self, trace_id: bytes) -> None:
        self.shards[self.shard_of(trace_id)].add(trace_id)

    def __contains__(self, trace_id: bytes) -> bool:
        return trace_id in self.shards[self.shard_of(trace_id)]

    def shard_bytes(self, i: int) -> bytes:
        return self.shards[i].to_bytes()


def shard_name(i: int) -> str:
    return f"bloom-{i}"
