"""Block writer: arrow table → parquet + bloom + row-group index + meta.

The create path of the encoding layer (`tempodb/encoding/vparquet4/create.go`):
one sorted `data.parquet` per block plus `meta.json`, sharded `bloom-*`, and
`index.json` (per-row-group trace-id bounds for binary-searchable
trace-by-ID and page-ranged query jobs — the analog of vparquet4's row-group
index used by `block_findtracebyid.go` and the frontend sharders).
"""

from __future__ import annotations

import io
import json
from typing import Iterable, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from tempo_tpu.backend.meta import BlockMeta, DedicatedColumn, write_block_meta
from tempo_tpu.backend.raw import RawWriter, block_keypath
from tempo_tpu.block import schema as bs
from tempo_tpu.block.bloom import ShardedBloom, shard_name

DATA_NAME = "data.parquet"
INDEX_NAME = "index.json"

DEFAULT_ROW_GROUP_ROWS = 50_000
DEFAULT_BLOOM_FPP = 0.01


def write_block(
    w: RawWriter,
    tenant: str,
    traces: Iterable[tuple[bytes, list[dict]]],
    *,
    block_id: str | None = None,
    dedicated_columns: Sequence[DedicatedColumn] = (),
    row_group_rows: int = DEFAULT_ROW_GROUP_ROWS,
    bloom_fpp: float = DEFAULT_BLOOM_FPP,
    bloom_shard_count: int = 1,
    replication_factor: int = 3,
    compaction_level: int = 0,
    compression: str = "zstd",
) -> BlockMeta:
    """Write one complete block from pre-sorted (trace_id, spans) groups."""
    traces = list(traces)
    table = bs.traces_to_table(traces, dedicated_columns)
    return write_block_from_table(
        w, tenant, table, [tid for tid, _ in traces],
        block_id=block_id, dedicated_columns=dedicated_columns,
        row_group_rows=row_group_rows, bloom_fpp=bloom_fpp,
        bloom_shard_count=bloom_shard_count,
        replication_factor=replication_factor,
        compaction_level=compaction_level, compression=compression)


def _trace_aligned_slices(table: pa.Table, target_rows: int) -> list[tuple[int, int]]:
    """Row ranges for row groups: >= target_rows each but never splitting a
    trace (trace_idx runs are kept whole)."""
    n = table.num_rows
    if n == 0:
        return []
    import numpy as np

    tidx = table.column("trace_idx").to_numpy()
    # first row of each trace
    starts = np.flatnonzero(np.diff(tidx, prepend=tidx[0] - 1))
    out = []
    lo = 0
    while lo < n:
        want = lo + target_rows
        if want >= n:
            out.append((lo, n))
            break
        # next trace boundary at or after `want`
        j = int(np.searchsorted(starts, want, side="left"))
        hi = int(starts[j]) if j < len(starts) else n
        if hi <= lo:
            hi = n
        out.append((lo, hi))
        lo = hi
    return out


def write_block_from_table(
    w: RawWriter,
    tenant: str,
    table: pa.Table,
    trace_ids: list[bytes],
    *,
    block_id: str | None = None,
    dedicated_columns: Sequence[DedicatedColumn] = (),
    row_group_rows: int = DEFAULT_ROW_GROUP_ROWS,
    bloom_fpp: float = DEFAULT_BLOOM_FPP,
    bloom_shard_count: int = 1,
    replication_factor: int = 3,
    compaction_level: int = 0,
    compression: str = "zstd",
) -> BlockMeta:
    meta = BlockMeta.new(
        tenant, block_id,
        version=bs.VERSION,
        encoding=compression,
        replication_factor=replication_factor,
        compaction_level=compaction_level,
        dedicated_columns=list(dedicated_columns),
        bloom_shard_count=bloom_shard_count,
    )
    kp = block_keypath(meta.block_id, tenant)

    # data.parquet — dictionary+RLE on string columns, zstd pages. Row groups
    # are cut at TRACE boundaries (unlike naive row_group_size) so every scan
    # batch holds whole traces: structural operators and per-trace reductions
    # evaluate within one row group with no stitching.
    buf = io.BytesIO()
    writer = pq.ParquetWriter(buf, table.schema, compression=compression,
                              use_dictionary=True, write_statistics=True)
    for lo, hi in _trace_aligned_slices(table, max(row_group_rows, 1)):
        writer.write_table(table.slice(lo, hi - lo), row_group_size=hi - lo)
    writer.close()
    data = buf.getvalue()
    w.write(DATA_NAME, kp, data)

    # row-group index: trace-id bounds + row offsets per row group.
    pf = pq.ParquetFile(io.BytesIO(data))
    groups = []
    row = 0
    tid_np = table.column("trace_id").to_numpy(zero_copy_only=False) if table.num_rows else []
    for rg in range(pf.num_row_groups):
        nrows = pf.metadata.row_group(rg).num_rows
        first = tid_np[row] if len(tid_np) else b""
        last = tid_np[row + nrows - 1] if len(tid_np) else b""
        groups.append({
            "row_offset": row,
            "rows": nrows,
            "min_trace_id": bytes(first).hex(),
            "max_trace_id": bytes(last).hex(),
        })
        row += nrows
    w.write(INDEX_NAME, kp, json.dumps({"row_groups": groups}).encode())

    # bloom shards
    bloom = ShardedBloom(bloom_shard_count, max(len(trace_ids), 1), bloom_fpp)
    for tid in trace_ids:
        bloom.add(bytes(tid).ljust(16, b"\0")[:16])
    for i in range(bloom.shard_count):
        w.write(shard_name(i), kp, bloom.shard_bytes(i))

    if groups:
        meta.min_trace_id = groups[0]["min_trace_id"]
        meta.max_trace_id = groups[-1]["max_trace_id"]
    stats = bs.table_stats(table)
    meta.total_spans = stats["total_spans"]
    meta.total_objects = stats["total_objects"]
    meta.start_time = stats["start_time"]
    meta.end_time = stats["end_time"]
    meta.size_bytes = len(data)
    meta.row_group_count = pf.num_row_groups
    meta.footer_size = int.from_bytes(data[-8:-4], "little") if len(data) >= 8 else 0
    write_block_meta(w, meta)
    return meta
