"""ColumnView over a SpanBatch — the matview appender's ingest-side view.

The recompute path evaluates TraceQL over views built from stored spans
(`traceql/memview.py view_from_traces`, block scans); the materializer
evaluates the SAME expressions over the ingest batch *before* it is
stored. This module builds that view straight from the SpanBatch SoA
columns — vectorized id→string decodes, lazy per-attribute resolvers,
no per-span dicts — so a 4k-span batch costs a handful of numpy ops,
not 4k dict materializations.

Trace-structural coordinates (nested set, parent rows, roots) are NOT
available on a single ingest batch (a trace's spans arrive across many
batches), so queries needing them are refused at subscribe time
(`matview.materializer.query_supported`) and never reach this view.
Label formatting and type mapping mirror `view_from_traces` exactly —
the bit-identity contract of the materialized tier depends on both
views minting identical group keys.
"""

from __future__ import annotations

import numpy as np

from tempo_tpu.model.interner import INVALID_ID
from tempo_tpu.model.span_batch import (ATTR_BOOL, ATTR_DOUBLE, ATTR_INT,
                                        ATTR_STRING, SpanBatch)
from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.eval import (BOOL, KIND, NUM, STATUS, STR, Col,
                                    ColumnView)


def _decode_ids_coded(interner, ids: np.ndarray):
    """[n] int32 interned ids → (values, codes, code_values): object
    strings plus the dictionary view (codes int32 into code_values,
    INVALID_ID → ""). The dictionary rides the Col so `group_slots`
    takes its code fast path instead of re-uniquing strings per view."""
    uniq, inv = np.unique(ids, return_inverse=True)
    strs = np.empty(len(uniq), object)
    for i, sid in enumerate(uniq.tolist()):
        strs[i] = "" if sid == INVALID_ID else interner.lookup(int(sid))
    return strs[inv], inv.astype(np.int32), strs.tolist()


def _decode_ids(interner, ids: np.ndarray) -> np.ndarray:
    """[n] int32 interned ids → [n] object strings (INVALID_ID → "")."""
    return _decode_ids_coded(interner, ids)[0]


def _hex_rows(b: np.ndarray) -> np.ndarray:
    out = np.empty(len(b), object)
    for i in range(len(b)):
        out[i] = b[i].tobytes().hex()
    return out


def _attr_resolver(interner, keys, svals, fvals, typs, kid):
    """Lazy Col builder for one attribute key over [n, K] attr matrices.
    First-seen type wins, like `view_from_traces`'s mixed-type rule."""

    def build():
        hit = keys == kid                         # [n, K]
        has = hit.any(axis=1)
        j = hit.argmax(axis=1)
        rows = np.flatnonzero(has)
        if len(rows) == 0:
            return None
        t0 = int(typs[rows[0], j[rows[0]]])
        n = keys.shape[0]
        if t0 == ATTR_STRING:
            vals = np.empty(n, object)
            sel = svals[rows, j[rows]]
            tmask = typs[rows, j[rows]] == ATTR_STRING
            vals[rows[tmask]] = _decode_ids(interner, sel[tmask])
            exists = np.zeros(n, bool)
            exists[rows[tmask]] = True
            return Col(STR, vals, exists)
        if t0 == ATTR_BOOL:
            vals = np.zeros(n, bool)
            tmask = typs[rows, j[rows]] == ATTR_BOOL
            vals[rows[tmask]] = fvals[rows, j[rows]][tmask] != 0
            exists = np.zeros(n, bool)
            exists[rows[tmask]] = True
            return Col(BOOL, vals, exists)
        vals = np.zeros(n)
        tmask = np.isin(typs[rows, j[rows]], (ATTR_INT, ATTR_DOUBLE))
        vals[rows[tmask]] = fvals[rows, j[rows]][tmask]
        exists = np.zeros(n, bool)
        exists[rows[tmask]] = True
        return Col(NUM, vals, exists)

    return build


def view_from_span_batch(sb: SpanBatch) -> ColumnView:
    """Valid rows of a SpanBatch as a ColumnView (intrinsics + lazy
    span./resource. attribute columns)."""
    rows = np.flatnonzero(sb.valid[: sb.n])
    n = len(rows)
    view = ColumnView(n)
    it = sb.interner
    ones = np.ones(n, bool)

    start = sb.start_unix_nano[rows].astype(np.float64)
    end = sb.end_unix_nano[rows].astype(np.float64)
    view.set_col("__startTime", Col(NUM, start, ones))
    view.set_col("duration", Col(NUM, np.maximum(end - start, 0.0), ones))
    nvals, ncodes, ndict = _decode_ids_coded(it, sb.name_id[rows])
    view.set_col("name", Col(STR, nvals, ones,
                             codes=ncodes, code_values=ndict))
    svals_, scodes, sdict = _decode_ids_coded(it, sb.service_id[rows])
    view.set_col("resource.service.name",
                 Col(STR, svals_, ones, codes=scodes, code_values=sdict))
    # OTLP wire status → traceql enum, vectorized (0/1/2 → unset/ok/error)
    sc = sb.status_code[rows]
    status = np.full(n, float(A.STATUS_UNSET))
    status[sc == 1] = float(A.STATUS_OK)
    status[sc == 2] = float(A.STATUS_ERROR)
    view.set_col("status", Col(STATUS, status, ones))
    mvals, mcodes, mdict = _decode_ids_coded(it, sb.status_message_id[rows])
    view.set_col("statusMessage",
                 Col(STR, mvals, ones, codes=mcodes, code_values=mdict))
    view.set_col("kind", Col(KIND, sb.kind[rows].astype(np.float64), ones))
    view.set_resolver("trace:id", lambda: Col(
        STR, _hex_rows(sb.trace_id[rows]), np.ones(n, bool)))
    view.set_resolver("span:id", lambda: Col(
        STR, _hex_rows(sb.span_id[rows]), np.ones(n, bool)))
    view.set_resolver("span:parentID", lambda: Col(
        STR, _hex_rows(sb.parent_span_id[rows]), np.ones(n, bool)))

    for scope, keys, svals, fvals, typs in (
            ("span", sb.span_attr_key[rows], sb.span_attr_sval[rows],
             sb.span_attr_fval[rows], sb.span_attr_typ[rows]),
            ("resource", sb.res_attr_key[rows], sb.res_attr_sval[rows],
             sb.res_attr_fval[rows], sb.res_attr_typ[rows])):
        if keys.shape[1] == 0:
            continue
        for kid in np.unique(keys).tolist():
            if kid == INVALID_ID:
                continue
            key = f"{scope}.{it.lookup(int(kid))}"
            if key == "resource.service.name":
                continue          # intrinsic service column wins
            view.set_resolver(key, _attr_resolver(
                it, keys, svals, fvals, typs, kid))
    return view


__all__ = ["view_from_span_batch"]
