"""tempo_tpu.matview — incremental materialized query grids.

Hot recurring TraceQL-metrics queries become standing device-resident
grids that every ingest batch streams into; dashboard reads turn into a
grid slice + the normal combiner/final pass instead of a block/registry
recompute. See `materializer.py` for the design notes and
`operations/runbook.md` ("Materialized query grids") for the
operational story.
"""

from tempo_tpu.matview.materializer import (
    Materializer,
    MatViewConfig,
    Subscription,
    configure,
    materializer,
    query_supported,
    reset,
)

__all__ = ["Materializer", "MatViewConfig", "Subscription", "configure",
           "materializer", "query_supported", "reset"]
