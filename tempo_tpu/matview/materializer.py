"""Materialized query grids: dashboard-scale reads from streaming planes.

Every `query_range` today recomputes from registry/block state, so 10k
dashboards polling the same handful of queries at 10s intervals costs
O(queries × state). This module materializes the hot recurring queries
instead: for each subscription the generator appends every ingest
batch's contribution to a standing device-resident grid — a ring of
step columns shaped exactly like the per-request evaluator's grids
(`traceql/engine_metrics.py`):

    rate / count_over_time          [series, steps]       count grid
    quantile / histogram (log2)     [series, steps, 64]   bucket grid
    quantile (moments tier)         [series, steps, k+1]  moment grid
                                    + two [series, steps] bound planes

Appends ride the shared device scheduler as ingest-class jobs (the same
coalescer/ledger path as the spanmetrics fused updates) and reuse the
engine's jitted scatter kernels, so steady state adds ZERO new XLA
traces. Reads become a host slice of an already-built grid (memoized
between appends — 10k pollers between two batches share one D2H copy)
plus the normal combiner/final pass: the maxent solve for moments
quantiles, log2 interpolation for bucket grids, rate division for
counts. Answers are bit-identical to the recompute path for dd/count
kinds (integer f32 sums are order-independent below 2^24); moments sums
are f32 add-order class, covered by the existing plane-fuzz budget.

Grid↔truth consistency:

- subscriptions are built (and REBUILT, e.g. when a tenant's overrides
  change) by running the real `MetricsEvaluator` over the local-blocks
  views and remapping its linear grid into ring columns — the backfill
  IS the recompute path, so a fresh grid cannot disagree with it;
- appends evaluate the same parsed query with the same shared helpers
  (`matching_rows` / `group_slots`) over a vectorized view of the
  ingest batch (`batchview.py`);
- reads are served only when the grid covers the request window, the
  request is step-aligned, and the grid saw a batch within the
  staleness bound — everything else falls through to the recompute
  path, surfaced per-reason in `tempo_matview_reads_total`.

Process-wide singleton like sched/pages/serving: `configure()` from the
app config, `materializer()` everywhere else, `reset()` in tests.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

import numpy as np

import jax.numpy as jnp

from tempo_tpu.obs.jaxruntime import RUNTIME, instrumented_jit
from tempo_tpu.obs.queryfp import query_fingerprint
from tempo_tpu.ops import moments as msk
from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.conditions import extract_conditions
from tempo_tpu.traceql.engine_metrics import (
    HBUCKETS,
    _LABEL_BUCKET,
    _LABEL_MOMENT,
    MetricsEvaluator,
    QueryRangeRequest,
    SeriesIndex,
    TimeSeries,
    _pad_pow2,
    _scatter_add2,
    _scatter_add3,
    _scatter_moments,
    group_slots,
    matching_rows,
)
from tempo_tpu.traceql.eval import NUM, eval_expr
from tempo_tpu.traceql.parser import parse


@dataclasses.dataclass
class MatViewConfig:
    """The `matview:` app-config block (bounds in `config.check()`)."""

    enabled: bool = True
    # process-wide subscription budget; explicit subscribes past it are
    # refused, auto-subscribes silently stop
    max_subscriptions: int = 1024
    # per-grid series budget: groups past it are dropped (counted) —
    # a by() explosion must not eat HBM
    max_series: int = 4096
    # ring depth: step columns retained per grid. window_steps × step is
    # the furthest-back a materialized read can reach
    window_steps: int = 128
    min_step_s: float = 1.0
    max_step_s: float = 3600.0
    # serve-from-grid bound: a grid that saw no ingest batch for this
    # long falls back to the recompute path (and the gauge shows why)
    max_staleness_s: float = 60.0
    # auto-subscribe: queries whose fingerprint recurs this many times
    # within qlog's sliding window get a grid without an explicit call
    auto_subscribe: bool = True
    auto_subscribe_after: int = 32
    # auto-subscribed grids nobody read for this long are dropped
    idle_expire_s: float = 3600.0
    # how often a tenant's resolved overrides are re-fingerprinted on
    # the push path (change → expire + rebuild that tenant's grids)
    overrides_check_interval_s: float = 10.0


# kinds a grid can hold. min/max rings would need ±inf column recycling
# and sum/avg accumulate floats whose merge order is visible — those
# kinds stay on the recompute path by design.
_KINDS = (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME,
          A.MetricsKind.QUANTILE_OVER_TIME,
          A.MetricsKind.HISTOGRAM_OVER_TIME)

# intrinsics a per-batch view can answer (batchview.py); anything
# trace-structural needs the whole trace and is refused at subscribe
_SUPPORTED_INTRINSICS = {
    A.Intrinsic.NONE, A.Intrinsic.DURATION, A.Intrinsic.NAME,
    A.Intrinsic.STATUS, A.Intrinsic.STATUS_MESSAGE, A.Intrinsic.KIND,
    A.Intrinsic.SPAN_START_TIME, A.Intrinsic.TRACE_ID,
    A.Intrinsic.SPAN_ID, A.Intrinsic.PARENT_ID,
}
_SUPPORTED_SCOPES = (A.Scope.NONE, A.Scope.SPAN, A.Scope.RESOURCE)


def query_supported(query: str) -> "tuple[bool, str]":
    """(materializable, reason). A query qualifies when its kind has a
    grid layout and every referenced column exists on a single-batch
    view — trace-structural features (nested set, roots, spanset
    combines, scalar filters) need the stored trace and fall through to
    the recompute path."""
    try:
        q = parse(query)
    except Exception as e:
        return False, f"parse: {e}"
    if q.metrics is None:
        return False, "not a metrics query"
    if q.metrics.kind not in _KINDS:
        return False, f"kind {q.metrics.kind.value} not materializable"
    for stage in q.stages:
        if not isinstance(stage, A.SpansetFilter):
            return False, "pipeline stage needs whole-trace evaluation"
    bad = _unsupported_attr(q)
    if bad:
        return False, f"attribute {bad} needs whole-trace evaluation"
    return True, ""


def _unsupported_attr(node) -> "str | None":
    if isinstance(node, A.Attribute):
        if node.parent or node.scope not in _SUPPORTED_SCOPES \
                or node.intrinsic not in _SUPPORTED_INTRINSICS:
            return str(node)
        return None
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            for x in (v if isinstance(v, (list, tuple)) else (v,)):
                bad = _unsupported_attr(x)
                if bad:
                    return bad
    return None


# ---------------------------------------------------------------------------
# device kernels (shared scatter kernels come from engine_metrics; the
# only new trace is the ring-advance column zeroer)
# ---------------------------------------------------------------------------

def _zero_cols_impl(grid, cols):
    """Zero recycled ring columns (rank-agnostic; OOB sentinel drops)."""
    return grid.at[:, cols].set(0.0, mode="drop")


_zero_cols = instrumented_jit(_zero_cols_impl, name="matview_zero_cols",
                              donate_argnums=0)


def _grow_rows(grid: "jnp.ndarray", need: int) -> "jnp.ndarray":
    g = jnp.zeros((need,) + grid.shape[1:], grid.dtype)
    return g.at[: grid.shape[0]].set(grid)


def _pad_cols(cols: np.ndarray, sentinel: int, lo: int = 8) -> "jnp.ndarray":
    size = _pad_pow2(max(len(cols), 1), lo)
    return jnp.asarray(np.pad(cols.astype(np.int32),
                              (0, size - len(cols)),
                              constant_values=sentinel))


# ---------------------------------------------------------------------------
# subscription: one standing grid
# ---------------------------------------------------------------------------

class Subscription:
    """One materialized query: parsed pipeline + series index + a ring
    of device step columns. All mutation happens under `lock`."""

    def __init__(self, tenant: str, query: str, step_s: float, fp: str,
                 cfg: MatViewConfig, origin: str) -> None:
        self.tenant = tenant
        self.query = query
        self.step_ns = int(round(step_s * 1e9))
        self.step_s = step_s
        self.fp = fp
        self.cfg = cfg
        self.origin = origin                 # "explicit" | "auto"
        self.q = parse(query)
        self.m = self.q.metrics
        self.kind = self.m.kind
        self.fetch_req = extract_conditions(self.q)   # no time clamp:
        # the ring covers a moving window; coverage clips at read time
        self.need_second_pass = not (
            self.fetch_req.all_conditions
            and self.kind in (A.MetricsKind.RATE,
                              A.MetricsKind.COUNT_OVER_TIME))
        self.moments = False                 # captured at (re)build
        self.lock = threading.Lock()
        # serializes the needs_build check-then-build: two concurrent
        # pushes must not both run build_from (the second would discard
        # the first's just-appended batch — its backfill predates it)
        self.build_lock = threading.Lock()
        self.series = SeriesIndex()
        self.grids: dict[str, "jnp.ndarray"] = {}
        self.cap = 0
        self.hi_step: "int | None" = None    # newest absolute step seen
        self.lo_valid: "int | None" = None   # build floor (absolute)
        self.needs_build = True
        self.version = 0                     # bumped per append (D2H memo)
        self._host: "tuple[int, dict] | None" = None
        # wall clocks (materializer's now())
        self.created_wall = 0.0
        self.last_batch_wall = 0.0
        self.last_read_wall = 0.0
        # counters
        self.appends = 0
        self.append_spans = 0
        self.late_dropped = 0
        self.overflow_dropped = 0

    # -- layout -------------------------------------------------------------

    def _grid_names(self) -> tuple:
        if self.kind in (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME):
            return ("count",)
        if self.moments:
            return ("mmt", "mhi", "mlo")
        return ("hist",)

    def _tail_shape(self, name: str) -> tuple:
        if name == "hist":
            return (HBUCKETS,)
        if name == "mmt":
            return (msk.QUERY_K + 1,)
        return ()

    def _ensure_grids(self, need_series: int) -> None:
        need = min(_pad_pow2(max(need_series, 1), 64),
                   _pad_pow2(max(self.cfg.max_series, 1), 64))
        if need <= self.cap and self.grids:
            return
        w = self.cfg.window_steps
        for name in self._grid_names():
            g = self.grids.get(name)
            if g is None:
                self.grids[name] = jnp.zeros(
                    (need, w) + self._tail_shape(name), jnp.float32)
            elif g.shape[0] < need:
                self.grids[name] = _grow_rows(g, need)
        self.cap = need

    def state_bytes(self) -> int:
        return sum(int(np.prod(g.shape)) * 4 for g in self.grids.values())

    # -- build / rebuild (the recompute path IS the backfill) ---------------

    def build_from(self, views_iter, now_s: float, cause: str) -> None:
        """(Re)initialize the ring from stored local-blocks state: run
        the per-request evaluator over the full ring window and remap
        its linear step axis onto ring columns. `views_iter` None (no
        local-blocks processor) starts an empty grid whose coverage
        floor is *now* — reads miss until the window refills."""
        w = self.cfg.window_steps
        cur = int(now_s * 1e9) // self.step_ns
        start_step = cur - w + 1
        with self.lock:
            self.series = SeriesIndex()
            self.grids = {}
            self.cap = 0
            self._host = None
            self.version += 1
            self.moments = (self.kind == A.MetricsKind.QUANTILE_OVER_TIME
                            and msk.query_moments_active())
            self.hi_step = cur
            self.lo_valid = start_step if views_iter is not None else cur
            self.needs_build = False
            if views_iter is None:
                return
            req = QueryRangeRequest(
                query=self.query, start_ns=start_step * self.step_ns,
                end_ns=(cur + 1) * self.step_ns, step_ns=self.step_ns,
                exemplars=0)
            ev = MetricsEvaluator(req)
            for view, cand in views_iter:
                if len(cand):
                    ev.observe(view)
            nseries = len(ev.series)
            if nseries == 0:
                return
            self.series = ev.series
            self._ensure_grids(nseries)
            # linear step j holds absolute step start+j; ring column r
            # holds the abs step ≡ r (mod w) — one gather per grid
            inv = (np.arange(w, dtype=np.int64) - start_step) % w
            jinv = jnp.asarray(inv.astype(np.int32))
            for name in self._grid_names():
                src = ev._grids.get(name)
                if src is None:
                    continue
                g = self.grids[name]
                take = jnp.take(src, jinv, axis=1)
                self.grids[name] = g.at[: src.shape[0]].set(
                    take[: g.shape[0]])

    # -- append -------------------------------------------------------------

    def observe(self, view, now_s: float) -> None:
        """Evaluate the subscription over one ingest-batch view and
        scatter the contribution into the ring (device work rides the
        scheduler as ONE ingest-class job: advance + scatter)."""
        self.last_batch_wall = now_s
        rows = matching_rows(self.q, self.fetch_req,
                             self.need_second_pass, view)
        if len(rows) == 0:
            return
        st = view.col("__startTime")
        if st is None:
            return
        with self.lock:
            self._observe_locked(view, rows, st)

    def _observe_locked(self, view, rows, st) -> None:
        w = self.cfg.window_steps
        ts = st.values[rows]
        abs_step = np.floor_divide(ts, self.step_ns).astype(np.int64)
        new_hi = int(abs_step.max()) if self.hi_step is None \
            else max(self.hi_step, int(abs_step.max()))
        cover_lo = new_hi - w + 1
        fresh = abs_step >= cover_lo
        self.late_dropped += int((~fresh).sum())
        rows, abs_step = rows[fresh], abs_step[fresh]
        if len(rows) == 0:
            return
        grouped = group_slots(self.m.by, self.series, view, rows)
        if grouped is None:
            slots = np.zeros(len(rows), np.int32)
            self.series.lookup([()])
        else:
            keep, slots = grouped
            rows, abs_step = rows[keep], abs_step[keep]
            if len(rows) == 0:
                return
        vals = None
        if self.m.attr is not None:
            c = eval_expr(view, self.m.attr)
            if c.t != NUM:
                return
            vex = c.exists[rows]
            rows, abs_step, slots = rows[vex], abs_step[vex], slots[vex]
            if len(rows) == 0:
                return
            vals = c.values[rows].astype(np.float64)
        self._ensure_grids(len(self.series))
        over = slots >= self.cap
        self.overflow_dropped += int(over.sum())
        # over-budget slots pad to cap and drop on device (mode="drop")
        slots = np.where(over, self.cap, slots).astype(np.int64)

        ring = (abs_step % w).astype(np.int32)
        size = _pad_pow2(len(rows), 64)
        pad = size - len(rows)
        jslots = jnp.asarray(np.pad(slots, (0, pad),
                                    constant_values=self.cap))
        jring = jnp.asarray(np.pad(ring, (0, pad)))
        ones = jnp.asarray(np.pad(np.ones(len(rows), np.float32),
                                  (0, pad)))
        advance = self.hi_step is not None and new_hi > self.hi_step
        if advance:
            gap = new_hi - self.hi_step
            if gap >= w:
                zcols = np.arange(w, dtype=np.int64)
            else:
                zcols = np.arange(self.hi_step + 1, new_hi + 1) % w
            jz = _pad_cols(zcols, sentinel=w)
        names = self._grid_names()

        def dispatch():
            if advance:
                for name in names:
                    self.grids[name] = _zero_cols(self.grids[name], jz)
            if names == ("count",):
                self.grids["count"] = _scatter_add2(
                    self.grids["count"], jslots, jring, ones)
            elif names == ("hist",):
                from tempo_tpu.traceql.engine_metrics import log2_bucket_np
                b = jnp.asarray(np.pad(log2_bucket_np(vals), (0, pad)))
                self.grids["hist"] = _scatter_add3(
                    self.grids["hist"], jslots, jring, b, ones)
            else:
                import math
                z = np.log(np.clip(vals, math.exp(msk.QUERY_LO),
                                   math.exp(msk.QUERY_HI))
                           ).astype(np.float32)
                jz2 = jnp.asarray(np.pad(z, (0, pad),
                                         constant_values=msk.QUERY_LO))
                (self.grids["mmt"], self.grids["mhi"],
                 self.grids["mlo"]) = _scatter_moments(
                    self.grids["mmt"], self.grids["mhi"],
                    self.grids["mlo"], jslots, jring, jz2)

        from tempo_tpu import sched
        sched.run(dispatch, kernel="matview_append",
                  priority=sched.PRIO_INGEST, tenant=self.tenant)
        self.hi_step = new_hi
        self.version += 1
        self._host = None
        self.appends += 1
        self.append_spans += len(rows)

    # -- read ---------------------------------------------------------------

    @staticmethod
    def _served_lo(lo_valid, hi, w: int) -> "int | None":
        """Oldest absolute step the grid can serve: the build floor,
        clipped by the ring window once appends advanced past it. THE
        coverage rule — read() admission and slice_series share it."""
        if lo_valid is None:
            return None
        if hi is None:
            return lo_valid
        return max(lo_valid, hi - w + 1)

    def covers(self, first_abs: int) -> bool:
        """Locked admission check: can a request starting at absolute
        step `first_abs` be served entirely from this grid?"""
        with self.lock:
            lo = self._served_lo(self.lo_valid, self.hi_step,
                                 self.cfg.window_steps)
        return lo is not None and first_abs >= lo

    def _host_grids(self) -> dict:
        """Host mirror of the device grids, memoized per append version
        — consecutive polls between two ingest batches share one D2H."""
        if self._host is not None and self._host[0] == self.version:
            return self._host[1]
        host = {name: np.asarray(g) for name, g in self.grids.items()}
        self._host = (self.version, host)
        return host

    def slice_series(self, req: QueryRangeRequest) -> list:
        """Raw job-level TimeSeries for the request window, shaped
        exactly like `MetricsEvaluator.results()` so the combiner/final
        pass downstream cannot tell the difference."""
        w = self.cfg.window_steps
        with self.lock:
            host = self._host_grids()
            keys = list(self.series.keys)
            hi, lo_valid = self.hi_step, self.lo_valid
        n = req.n_steps
        first = req.start_ns // self.step_ns
        steps_abs = first + np.arange(n, dtype=np.int64)
        served_lo = self._served_lo(lo_valid, hi, w)
        if served_lo is None or hi is None:
            valid = np.zeros(n, bool)
        else:
            valid = (steps_abs >= served_lo) & (steps_abs <= hi)
        cols = (steps_abs % w).astype(np.int64)

        def window(g: np.ndarray, i: int) -> np.ndarray:
            out = np.zeros((n,) + g.shape[2:], np.float64)
            if valid.any():
                out[valid] = g[i, cols[valid]]
            return out

        out: list[TimeSeries] = []
        if not keys:
            return out
        if self.kind in (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME):
            g = host.get("count")
            for i, key in enumerate(keys):
                if g is None or i >= g.shape[0]:
                    break
                s = window(g, i)
                if s.any():
                    out.append(TimeSeries(key, s))
            return out
        if self.moments:
            mmt, mhi, mlo = (host.get("mmt"), host.get("mhi"),
                             host.get("mlo"))
            for i, key in enumerate(keys):
                if mmt is None or i >= mmt.shape[0]:
                    break
                m = window(mmt, i)
                if not m[:, 0].any():
                    continue
                for j in range(msk.QUERY_K + 1):
                    if m[:, j].any():
                        out.append(TimeSeries(
                            key + ((_LABEL_MOMENT, str(j)),), m[:, j]))
                out.append(TimeSeries(key + ((_LABEL_MOMENT, "hi"),),
                                      window(mhi, i)))
                out.append(TimeSeries(key + ((_LABEL_MOMENT, "lo"),),
                                      window(mlo, i)))
            return out
        g = host.get("hist")
        for i, key in enumerate(keys):
            if g is None or i >= g.shape[0]:
                break
            s = window(g, i)             # [n, HBUCKETS]
            for b in range(HBUCKETS):
                if s[:, b].any():
                    out.append(TimeSeries(
                        key + ((_LABEL_BUCKET, 2.0 ** b / 1e9),), s[:, b]))
        return out

    def staleness_s(self, now_s: float) -> float:
        if not self.last_batch_wall:
            return float("inf")
        return max(now_s - self.last_batch_wall, 0.0)


# ---------------------------------------------------------------------------
# the process-wide materializer
# ---------------------------------------------------------------------------

class Materializer:
    def __init__(self, cfg: MatViewConfig | None = None,
                 overrides=None,
                 now: Callable[[], float] = time.time) -> None:
        self.cfg = cfg or MatViewConfig()
        self.overrides = overrides
        self.now = now
        self._lock = threading.Lock()
        self._subs: dict[tuple, Subscription] = {}
        self._by_tenant: dict[str, list] = {}
        self._tenants: frozenset = frozenset()   # lock-free wants()
        self._ovr_fp: dict[str, str] = {}
        self._ovr_checked: dict[str, float] = {}
        # counters (snapshot via *_snapshot() — the render lambdas and
        # status() must never iterate a dict a writer is growing)
        self.reads: dict[str, int] = {}
        self.rebuilds: dict[str, int] = {}
        self.auto_subscribed = 0
        self.refused: dict[str, int] = {}
        self._last_sweep = 0.0

    # -- subscription management -------------------------------------------

    def wants(self, tenant: str) -> bool:
        """Cheap push-path gate: does any grid want this tenant?"""
        return tenant in self._tenants

    def fingerprint(self, query: str, step_s: float) -> str:
        return query_fingerprint("metrics", query, step_s)

    def subscribe(self, tenant: str, query: str, step_s: float,
                  origin: str = "explicit"
                  ) -> "tuple[Subscription | None, str]":
        """Register a standing grid; returns (sub, "") or (None, why).
        The grid builds (backfills from local-blocks state) on the next
        ingest batch for the tenant."""
        if not self.cfg.enabled:
            return None, "matview disabled"
        if not (self.cfg.min_step_s <= step_s <= self.cfg.max_step_s):
            return None, (f"step {step_s}s outside "
                          f"[{self.cfg.min_step_s}, {self.cfg.max_step_s}]")
        ok, why = query_supported(query)
        if not ok:
            with self._lock:
                self.refused[why[:60]] = self.refused.get(why[:60], 0) + 1
            return None, why
        fp = self.fingerprint(query, step_s)
        with self._lock:
            got = self._subs.get((tenant, fp))
            if got is not None:
                return got, "exists"
            if len(self._subs) >= self.cfg.max_subscriptions:
                return None, "subscription budget exhausted"
            sub = Subscription(tenant, query, step_s, fp, self.cfg, origin)
            sub.created_wall = sub.last_read_wall = self.now()
            self._subs[(tenant, fp)] = sub
            self._by_tenant.setdefault(tenant, []).append(sub)
            self._tenants = frozenset(self._by_tenant)
            return sub, ""

    def unsubscribe(self, tenant: str, query: str, step_s: float) -> bool:
        fp = self.fingerprint(query, step_s)
        with self._lock:
            sub = self._subs.pop((tenant, fp), None)
            if sub is None:
                return False
            lst = self._by_tenant.get(tenant, [])
            if sub in lst:
                lst.remove(sub)
            if not lst:
                self._by_tenant.pop(tenant, None)
            self._tenants = frozenset(self._by_tenant)
            return True

    def consider_auto_subscribe(self, tenant: str, query: str,
                                step_s: float, recurrences: int) -> None:
        """Auto-subscribe hook, fed by the frontend after every metrics
        request with qlog's fingerprint-recurrence count."""
        if not self.cfg.enabled or not self.cfg.auto_subscribe:
            return
        from tempo_tpu.utils import tracing
        if tracing.is_reserved(tenant):
            # the selftrace loopback tenant must never grow query-driven
            # state: a grid over self-spans would emit spans of its own
            # on every observe_batch, re-entering the loop it observes
            return
        if recurrences < self.cfg.auto_subscribe_after:
            return
        sub, why = self.subscribe(tenant, query, step_s, origin="auto")
        if sub is not None and why == "":     # freshly created, not found
            with self._lock:
                self.auto_subscribed += 1

    # -- push-path hook ------------------------------------------------------

    def observe_batch(self, tenant: str, sb, lb=None,
                      limits_fn=None) -> None:
        """Feed one ingest batch (post-slack SpanBatch) to every grid of
        the tenant. `lb` (the tenant's local-blocks processor, if any)
        is the backfill source for builds/rebuilds; `limits_fn` resolves
        the tenant's current overrides for the expiry fingerprint."""
        subs = self._tenant_subs(tenant)
        if not subs:
            return
        now_s = self.now()
        self._check_overrides(tenant, subs, now_s, limits_fn)
        self._expire_idle(tenant, subs, now_s)
        subs = self._tenant_subs(tenant)
        if not subs:
            return
        view = None
        for sub in subs:
            if sub.needs_build:
                with sub.build_lock:         # double-checked: exactly
                    if sub.needs_build:      # one concurrent push builds
                        views = lb.views_for_matview() \
                            if lb is not None else None
                        sub.build_from(views, now_s, cause="build")
            if view is None:
                from tempo_tpu.matview.batchview import view_from_span_batch
                view = view_from_span_batch(sb)
            sub.observe(view, now_s)

    def _tenant_subs(self, tenant: str) -> list:
        with self._lock:
            return list(self._by_tenant.get(tenant, ()))

    def _check_overrides(self, tenant: str, subs, now_s: float,
                         limits_fn) -> None:
        src = limits_fn or (
            (lambda: self.overrides.for_tenant(tenant))
            if self.overrides is not None else None)
        if src is None:
            return
        last = self._ovr_checked.get(tenant, 0.0)
        if now_s - last < self.cfg.overrides_check_interval_s:
            return
        self._ovr_checked[tenant] = now_s
        fp = repr(src())
        old = self._ovr_fp.get(tenant)
        self._ovr_fp[tenant] = fp
        if old is not None and old != fp:
            for sub in subs:
                sub.needs_build = True
            with self._lock:
                self.rebuilds["overrides"] = \
                    self.rebuilds.get("overrides", 0) + len(subs)

    def _expire_idle(self, tenant: str, subs, now_s: float) -> None:
        for sub in subs:
            if sub.origin == "auto" and \
                    now_s - max(sub.last_read_wall, sub.created_wall) \
                    > self.cfg.idle_expire_s:
                self.unsubscribe(tenant, sub.query, sub.step_s)

    def _maybe_sweep(self, now_s: float) -> None:
        """Rate-limited whole-process idle sweep: a tenant whose ingest
        stopped (or moved to another fleet member) never triggers
        observe_batch again, so its auto grids must also expire from
        the read/scrape paths or their device arrays leak forever."""
        if now_s - self._last_sweep < 60.0:
            return
        self._last_sweep = now_s
        for sub in self.subscriptions():
            if sub.origin == "auto" and \
                    now_s - max(sub.last_read_wall, sub.created_wall) \
                    > self.cfg.idle_expire_s:
                self.unsubscribe(sub.tenant, sub.query, sub.step_s)

    # -- read path -----------------------------------------------------------

    def read(self, tenant: str, req: QueryRangeRequest
             ) -> "list | None":
        """Serve a query_range from its grid, or None (fall through to
        the recompute path). Every outcome lands in
        tempo_matview_reads_total{result}."""
        if not self.cfg.enabled:
            return None
        step_s = req.step_ns / 1e9
        fp = self.fingerprint(req.query, step_s)
        with self._lock:
            sub = self._subs.get((tenant, fp))
        if sub is None:
            return self._miss("unsubscribed")
        now_s = self.now()
        if sub.needs_build:
            return self._miss("unbuilt")
        if sub.kind == A.MetricsKind.QUANTILE_OVER_TIME and \
                sub.moments != msk.query_moments_active():
            sub.needs_build = True        # tier flipped: rebuild lazily
            return self._miss("tier_changed")
        if sub.staleness_s(now_s) > self.cfg.max_staleness_s:
            return self._miss("stale")
        if req.start_ns % req.step_ns != 0:
            return self._miss("unaligned")
        if not sub.covers(req.start_ns // sub.step_ns):
            return self._miss("coverage")
        series = sub.slice_series(req)
        sub.last_read_wall = now_s
        self._maybe_sweep(now_s)
        with self._lock:
            self.reads["hit"] = self.reads.get("hit", 0) + 1
        return series

    def _miss(self, reason: str) -> None:
        with self._lock:
            key = f"miss_{reason}"
            self.reads[key] = self.reads.get(key, 0) + 1
        return None

    # -- introspection -------------------------------------------------------

    def subscriptions(self) -> list:
        with self._lock:
            return list(self._subs.values())

    def reads_snapshot(self) -> dict:
        with self._lock:
            return dict(self.reads)

    def rebuilds_snapshot(self) -> dict:
        with self._lock:
            return dict(self.rebuilds)

    def status(self) -> dict:
        now_s = self.now()
        self._maybe_sweep(now_s)
        subs = self.subscriptions()
        return {
            "enabled": self.cfg.enabled,
            "subscriptions": len(subs),
            "grids_built": sum(1 for s in subs if not s.needs_build),
            "series": sum(len(s.series) for s in subs),
            "state_bytes": sum(s.state_bytes() for s in subs),
            "reads": self.reads_snapshot(),
            "rebuilds": self.rebuilds_snapshot(),
            "auto_subscribed": self.auto_subscribed,
            "max_staleness_s": max(
                (s.staleness_s(now_s) for s in subs
                 if not s.needs_build and s.last_batch_wall),
                default=0.0),
            "subscribed": [
                {"tenant": s.tenant, "query": s.query, "step_s": s.step_s,
                 "fp": s.fp, "origin": s.origin, "series": len(s.series),
                 "built": not s.needs_build, "appends": s.appends,
                 "staleness_s": (round(s.staleness_s(now_s), 3)
                                 if s.last_batch_wall else None)}
                for s in subs[:64]],
        }


# ---------------------------------------------------------------------------
# process-wide singleton (sched/pages/serving pattern)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_default: "Materializer | None" = None


def configure(cfg: MatViewConfig | None = None, overrides=None,
              now: Callable[[], float] = time.time
              ) -> "Materializer | None":
    """Install the process materializer from app config; None when the
    tier is disabled (every hook no-ops)."""
    global _default
    with _lock:
        if cfg is not None and not cfg.enabled:
            _default = None
        else:
            _default = Materializer(cfg, overrides=overrides, now=now)
        return _default


def materializer() -> "Materializer | None":
    return _default


def reset() -> None:
    """Drop the process materializer (tests)."""
    global _default
    with _lock:
        _default = None


# ---------------------------------------------------------------------------
# obs: matview families in the process-wide runtime registry
# ---------------------------------------------------------------------------

def _mv_subs():
    mv = _default
    if mv is None:
        return []
    by_origin: dict[str, int] = {}
    for s in mv.subscriptions():
        by_origin[s.origin] = by_origin.get(s.origin, 0) + 1
    return [((o,), float(n)) for o, n in by_origin.items()]


def _mv_sum(field):
    def fn():
        mv = _default
        if mv is None:
            return []
        return [((), float(sum(getattr(s, field)
                               for s in mv.subscriptions())))]
    return fn


RUNTIME.gauge_func(
    "tempo_matview_subscriptions", _mv_subs,
    help="Materialized-query subscriptions by origin (explicit API vs "
         "qlog-recurrence auto-subscribe)", labels=("origin",))
RUNTIME.gauge_func(
    "tempo_matview_grids",
    lambda: [((), float(sum(1 for s in _default.subscriptions()
                            if not s.needs_build)))] if _default else [],
    help="Materialized grids currently built (serving-eligible; "
         "subscriptions pending their first backfill are excluded)")
RUNTIME.gauge_func(
    "tempo_matview_series",
    lambda: [((), float(sum(len(s.series)
                            for s in _default.subscriptions())))]
    if _default else [],
    help="Series rows across all materialized grids")
RUNTIME.gauge_func(
    "tempo_matview_state_bytes",
    lambda: [((), float(sum(s.state_bytes()
                            for s in _default.subscriptions())))]
    if _default else [],
    help="Device bytes held by materialized query grids")
RUNTIME.counter_func(
    "tempo_matview_appends_total", _mv_sum("appends"),
    help="Ingest-batch contributions scattered into materialized grids "
         "(each rides the device scheduler as one ingest-class job)")
RUNTIME.counter_func(
    "tempo_matview_append_spans_total", _mv_sum("append_spans"),
    help="Spans accumulated into materialized grids")


def _mv_dropped():
    mv = _default
    if mv is None:
        return []
    subs = mv.subscriptions()
    return [(("late",), float(sum(s.late_dropped for s in subs))),
            (("series_overflow",),
             float(sum(s.overflow_dropped for s in subs)))]


RUNTIME.counter_func(
    "tempo_matview_dropped_spans_total", _mv_dropped,
    help="Matched spans a grid could not hold: 'late' = older than the "
         "ring window, 'series_overflow' = past the per-grid series "
         "budget (matview.max_series)", labels=("reason",))
RUNTIME.counter_func(
    "tempo_matview_reads_total",
    lambda: [((k,), float(v))
             for k, v in _default.reads_snapshot().items()]
    if _default else [],
    help="query_range reads consulting the materialized tier, by "
         "outcome (hit = served from a grid; miss_* fall through to "
         "the recompute path)", labels=("result",))
RUNTIME.counter_func(
    "tempo_matview_rebuilds_total",
    lambda: [((k,), float(v))
             for k, v in _default.rebuilds_snapshot().items()]
    if _default else [],
    help="Grid expiry/rebuild cycles by cause (overrides = tenant "
         "limits changed; the rebuild backfills from local-blocks "
         "state through the recompute evaluator)", labels=("cause",))
RUNTIME.counter_func(
    "tempo_matview_auto_subscribed_total",
    lambda: [((), float(_default.auto_subscribed))] if _default else [],
    help="Grids created by qlog-recurrence auto-subscription")


def _mv_staleness():
    mv = _default
    if mv is None:
        return []
    now_s = mv.now()
    by_tenant: dict[str, float] = {}
    for s in mv.subscriptions():
        if s.needs_build or not s.last_batch_wall:
            continue
        age = s.staleness_s(now_s)
        by_tenant[s.tenant] = max(by_tenant.get(s.tenant, 0.0), age)
    return [((t,), v) for t, v in by_tenant.items()]


RUNTIME.gauge_func(
    "tempo_matview_staleness_seconds", _mv_staleness,
    help="Worst-case materialized-grid staleness per tenant (wall time "
         "since the tenant's last ingest batch reached the grid); reads "
         "past matview.max_staleness_s fall back to the recompute path",
    labels=("tenant",))


__all__ = ["MatViewConfig", "Materializer", "Subscription", "configure",
           "materializer", "reset", "query_supported"]
