"""tempo-vulture analog: black-box write/read consistency prober.

Writes synthetic traces through the public OTLP endpoint, then re-reads
them by ID and by TraceQL search, and checks metrics sanity — the
continuous canary of `cmd/tempo-vulture/main.go:85-110`.

  python -m tempo_tpu.vulture --url http://localhost:3200 --cycles 3
"""

from __future__ import annotations

import argparse
import json
import random
import sys
import time


def make_trace(rng: random.Random, t0_ns: int) -> tuple[str, dict]:
    tid = "".join(rng.choice("0123456789abcdef") for _ in range(32))
    n_spans = rng.randint(1, 5)
    spans = []
    for i in range(n_spans):
        sid = "".join(rng.choice("0123456789abcdef") for _ in range(16))
        start = t0_ns + i * 1_000_000
        spans.append({
            "traceId": tid, "spanId": sid,
            "parentSpanId": spans[0]["spanId"] if i else "",
            "name": f"vulture-op-{i}", "kind": 2,
            "startTimeUnixNano": str(start),
            "endTimeUnixNano": str(start + rng.randint(1, 50) * 1_000_000),
            "attributes": [{"key": "vulture", "value": {"boolValue": True}}],
            "status": {"code": 0},
        })
    payload = {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name", "value": {"stringValue": "vulture"}}]},
        "scopeSpans": [{"spans": spans}]}]}
    return tid, payload


def run_cycle(client, rng: random.Random, read_delay_s: float) -> dict:
    res = {"written": 0, "read_ok": 0, "read_missing": 0,
           "search_ok": 0, "search_missing": 0, "errors": 0}
    t0_ns = int((time.time() - 1) * 1e9)
    written: list[str] = []
    for _ in range(5):
        tid, payload = make_trace(rng, t0_ns)
        try:
            client.push_otlp_json(payload)
            written.append(tid)
            res["written"] += 1
        except Exception:
            res["errors"] += 1
    time.sleep(read_delay_s)
    for tid in written:
        try:
            doc = client.trace_by_id(tid)
            if doc.get("spans"):
                res["read_ok"] += 1
            else:
                res["read_missing"] += 1
        except Exception:
            res["read_missing"] += 1
    try:
        found = client.search('{ resource.service.name = "vulture" }',
                              limit=200)
        ids = {t["traceID"] for t in found.get("traces", [])}
        for tid in written:
            if tid in ids:
                res["search_ok"] += 1
            else:
                res["search_missing"] += 1
    except Exception:
        res["errors"] += 1
    return res


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser("tempo_tpu.vulture")
    ap.add_argument("--url", default="http://127.0.0.1:3200")
    ap.add_argument("--tenant", default="")
    ap.add_argument("--cycles", type=int, default=0, help="0 = forever")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--read-delay", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args(argv)

    from tempo_tpu.client import Client
    client = Client(args.url, tenant=args.tenant)
    rng = random.Random(args.seed)
    cycle = 0
    failures = 0
    while args.cycles == 0 or cycle < args.cycles:
        res = run_cycle(client, rng, args.read_delay)
        ok = (res["read_missing"] == 0 and res["errors"] == 0
              and res["search_missing"] == 0)
        failures += 0 if ok else 1
        print(json.dumps({"cycle": cycle, "ok": ok, **res}), flush=True)
        cycle += 1
        if args.cycles == 0 or cycle < args.cycles:
            time.sleep(args.interval)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
