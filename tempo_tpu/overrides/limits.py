"""The per-tenant limit record.

Field-for-field analog of the reference's limit surface
(`modules/overrides/config.go:71-200`), grouped the way its new-style YAML
config groups them (ingestion / read / compaction / metrics-generator /
global). All byte quantities are ints, durations are float seconds.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class IngestionLimits:
    rate_strategy: str = "local"            # local | global (Tempo default local)
    rate_limit_bytes: int = 15_000_000
    burst_size_bytes: int = 20_000_000
    max_traces_per_user: int = 10_000       # live traces per tenant per ingester
    max_attribute_bytes: int = 0            # 0 = unlimited; truncate past this
    tenant_shard_size: int = 0              # shuffle-shard size (0 = whole ring)


@dataclasses.dataclass
class ReadLimits:
    max_bytes_per_tag_values_query: int = 1_000_000
    max_blocks_per_tag_values_query: int = 0
    max_search_duration_s: float = 0.0      # 0 = unlimited
    max_metrics_duration_s: float = 0.0
    max_bytes_per_trace: int = 50_000_000   # enforced at ingest + combine


@dataclasses.dataclass
class CompactionLimits:
    block_retention_s: float = 0.0          # 0 = use compactor default
    compaction_disabled: bool = False


@dataclasses.dataclass
class GeneratorLimits:
    processors: tuple[str, ...] = ()        # enabled processors for the tenant
    max_active_series: int = 65536
    collection_interval_s: float = 15.0
    disable_collection: bool = False
    ingestion_time_range_slack_s: float = 30.0
    remote_write_headers: dict[str, str] = dataclasses.field(default_factory=dict)
    # spanmetrics knobs
    # quantile sketch tier: "" = the process default
    # (generator.spanmetrics.sketch); "dd" | "moments" | "both" override
    # per tenant — a high-cardinality tenant can ride the ~15-float
    # moments rows while others keep the DDSketch plane
    sketch: str = ""
    sketch_moments_k: int = 0               # 0 = process default (moments_k)
    # update-kernel tier: "" = the process default
    # (generator.spanmetrics.kernel); "xla" | "pallas" override per
    # tenant — per-tenant arenas share the pool, so tiers can mix
    kernel: str = ""
    histogram_buckets: tuple[float, ...] = ()
    intrinsic_dimensions: dict[str, bool] = dataclasses.field(default_factory=dict)
    dimensions: tuple[str, ...] = ()
    span_multiplier_key: str = ""
    target_info_enabled: bool = True
    native_histograms: str = "classic"      # classic | native | both
    # service-graphs knobs
    sg_histogram_buckets: tuple[float, ...] = ()
    sg_dimensions: tuple[str, ...] = ()
    sg_peer_attributes: tuple[str, ...] = ()
    sg_wait_s: float = 10.0
    sg_max_items: int = 10_000
    # localblocks knobs
    lb_max_live_traces: int = 0
    lb_max_block_duration_s: float = 60.0
    lb_max_block_bytes: int = 500_000_000
    lb_flush_to_storage: bool = False
    # trace-analytics knobs (0 = the process default from
    # generator.traceanalytics)
    ta_trace_idle_s: float = 0.0
    ta_late_window_s: float = 0.0
    ta_max_live_traces: int = 0
    ta_max_spans_per_trace: int = 0


@dataclasses.dataclass
class SamplingLimits:
    """Per-tenant graceful-overload sampling policy (the `sampling:`
    group): how this tenant's spans behave when the process-wide
    overload controller (`sched.keep_fraction`) is below 1.0. The
    controller decides WHEN to sample and how hard; the policy decides
    how far this tenant may be sampled and what is never dropped."""

    enabled: bool = True          # False: tenant opts out → old hard-429 cliff
    floor: float = 0.25           # effective keep-fraction never drops below
    keep_errors: bool = True      # error-status spans always kept (exact)
    # latency-tail always-keep: spans whose duration sits above this
    # quantile of the tenant's own recent duration distribution are
    # kept at weight 1 (exact tail). 0 disables tail protection.
    tail_quantile: float = 0.99
    # observations the host duration sketch needs before the tail
    # threshold arms (an unwarmed threshold would force-keep everything)
    tail_min_spans: int = 1024


@dataclasses.dataclass
class Limits:
    """Everything a tenant can override. Defaults mirror the reference's
    (`config.go` RegisterFlagsAndApplyDefaults defaults)."""

    ingestion: IngestionLimits = dataclasses.field(default_factory=IngestionLimits)
    read: ReadLimits = dataclasses.field(default_factory=ReadLimits)
    compaction: CompactionLimits = dataclasses.field(default_factory=CompactionLimits)
    generator: GeneratorLimits = dataclasses.field(default_factory=GeneratorLimits)
    sampling: SamplingLimits = dataclasses.field(default_factory=SamplingLimits)

    def merged_with(self, patch: dict) -> "Limits":
        """New Limits with `patch` (nested dict) applied over self."""
        out = dataclasses.replace(self)
        for group, fields in (patch or {}).items():
            if not hasattr(out, group) or not isinstance(fields, dict):
                continue
            sub = dataclasses.replace(getattr(out, group))
            for k, v in fields.items():
                if hasattr(sub, k):
                    if isinstance(v, list):
                        v = tuple(v)
                    setattr(sub, k, v)
            setattr(out, group, sub)
        return out


def limits_from_dict(d: dict) -> Limits:
    return Limits().merged_with(d)
