"""Per-tenant limits: runtime-config overrides + user-configurable API.

Analog of `modules/overrides`: a `Limits` record per tenant
(`modules/overrides/config.go:71-200`), a reloading runtime-config source
(`runtime_config_overrides.go`), and a user-configurable subset persisted to
the object-store backend (`user_configurable_overrides.go`) that wins over
runtime config for the fields it carries.
"""

from tempo_tpu.overrides.limits import Limits
from tempo_tpu.overrides.overrides import Overrides, UserConfigurableOverrides

__all__ = ["Limits", "Overrides", "UserConfigurableOverrides"]
