"""Overrides service: runtime-config file + user-configurable backend layer.

Analog of `modules/overrides/{runtime_config_overrides,
user_configurable_overrides}.go`: the runtime-config file carries
`overrides: {tenant: {...}}` plus a `*` wildcard default and reloads on
mtime change; the user-configurable layer is a JSON blob per tenant stored
in the object-store backend under `<tenant>/overrides.json`, exposed via an
API, and applied on top of runtime config for the subset of fields users may
set (validated in `cmd/tempo/app/overrides_validation.go`).
"""

from __future__ import annotations

import json
import os
import threading

import yaml

from tempo_tpu.backend.raw import DoesNotExist, KeyPath, RawReader, RawWriter
from tempo_tpu.overrides.limits import Limits

WILDCARD = "*"

# Fields tenants may set through the user-configurable API — the same subset
# the reference allows (`user_configurable_overrides.go` UserConfigurableLimits:
# forwarders, metrics-generator processors/collection-interval/dimensions...).
USER_CONFIGURABLE_FIELDS = {
    "generator": {
        "processors", "collection_interval_s", "disable_collection",
        "dimensions", "histogram_buckets",
    },
}


class Overrides:
    """Per-tenant limit resolution: defaults < runtime file < user-config."""

    def __init__(self, defaults: Limits | None = None,
                 runtime_config_path: str | None = None,
                 user_configurable: "UserConfigurableOverrides | None" = None):
        self.defaults = defaults or Limits()
        self.path = runtime_config_path
        self.user_configurable = user_configurable
        self._mtime = 0.0
        self._lock = threading.Lock()
        self._per_tenant: dict[str, dict] = {}
        self._wildcard: dict = {}
        if self.path:
            self.reload()

    # -- runtime config file ----------------------------------------------

    def reload(self) -> bool:
        """Re-read the runtime-config file if its mtime moved (the dskit
        runtimeconfig watcher pattern). Returns True when content changed."""
        if not self.path or not os.path.exists(self.path):
            return False
        mtime = os.path.getmtime(self.path)
        if mtime == self._mtime:
            return False
        with open(self.path) as f:
            doc = yaml.safe_load(f) or {}
        per_tenant = dict(doc.get("overrides", {}))
        with self._lock:
            self._mtime = mtime
            self._wildcard = per_tenant.pop(WILDCARD, {}) or {}
            self._per_tenant = per_tenant
        return True

    def set_tenant_patch(self, tenant: str, patch: dict) -> None:
        """Programmatic override injection (tests, single-binary config)."""
        with self._lock:
            self._per_tenant[tenant] = patch

    # -- resolution --------------------------------------------------------

    def for_tenant(self, tenant: str) -> Limits:
        with self._lock:
            wildcard = self._wildcard
            patch = self._per_tenant.get(tenant, {})
        lim = self.defaults.merged_with(wildcard).merged_with(patch)
        if self.user_configurable is not None:
            uc = self.user_configurable.get(tenant)
            if uc:
                lim = lim.merged_with(_filter_user_configurable(uc))
        return lim


def _filter_user_configurable(patch: dict) -> dict:
    out: dict = {}
    for group, fields in (patch or {}).items():
        allowed = USER_CONFIGURABLE_FIELDS.get(group)
        if not allowed or not isinstance(fields, dict):
            continue
        kept = {k: v for k, v in fields.items() if k in allowed}
        if kept:
            out[group] = kept
    return out


class UserConfigurableOverrides:
    """Tenant-editable override blobs persisted to the backend.

    Storage layout mirrors the reference (`user_configurable_overrides.go`
    client): one JSON object per tenant at `overrides/<tenant>/overrides.json`
    with optimistic concurrency via a version string.
    """

    NAME = "overrides.json"

    def __init__(self, r: RawReader, w: RawWriter):
        self.r = r
        self.w = w

    def _kp(self, tenant: str) -> KeyPath:
        return KeyPath(("overrides", tenant))

    def get(self, tenant: str) -> dict | None:
        try:
            raw = self.r.read(self.NAME, self._kp(tenant))
        except (DoesNotExist, KeyError, FileNotFoundError):
            return None
        doc = json.loads(raw.decode())
        return doc.get("limits")

    def set(self, tenant: str, limits_patch: dict,
            version: str | None = None) -> str:
        bad = _validate_user_patch(limits_patch)
        if bad:
            raise ValueError(f"field not user-configurable: {bad}")
        cur = self._read_doc(tenant)
        cur_ver = cur.get("version", "0") if cur else "0"
        if version is not None and version != cur_ver:
            raise RuntimeError(f"version conflict: have {cur_ver}, got {version}")
        new_ver = str(int(cur_ver) + 1)
        doc = {"version": new_ver, "limits": limits_patch}
        self.w.write(self.NAME, self._kp(tenant), json.dumps(doc).encode())
        return new_ver

    def delete(self, tenant: str) -> None:
        try:
            self.w.delete(self.NAME, self._kp(tenant))
        except (DoesNotExist, KeyError, FileNotFoundError):
            pass

    def _read_doc(self, tenant: str) -> dict | None:
        try:
            return json.loads(self.r.read(self.NAME, self._kp(tenant)).decode())
        except (DoesNotExist, KeyError, FileNotFoundError):
            return None


def _validate_user_patch(patch: dict) -> str | None:
    for group, fields in (patch or {}).items():
        allowed = USER_CONFIGURABLE_FIELDS.get(group)
        if allowed is None:
            return group
        if not isinstance(fields, dict):
            return group
        for k in fields:
            if k not in allowed:
                return f"{group}.{k}"
    return None
