"""Blockbuilder: partition consumer that builds backend blocks directly.

Analog of `modules/blockbuilder`: replaces the ingester on the
ingest-storage path — consumes its partitions from the bus, accumulates
per-tenant live traces, writes RF1 blocks straight to object storage, and
commits consumed offsets only AFTER the flush succeeds so a crash replays
rather than loses (`consumePartition` `blockbuilder.go:266`, commit-after-
flush `blockbuilder.go:209-265`).
"""

from tempo_tpu.blockbuilder.blockbuilder import BlockBuilder, BlockBuilderConfig

__all__ = ["BlockBuilder", "BlockBuilderConfig"]
