"""The blockbuilder service."""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Sequence

from tempo_tpu.backend.raw import RawWriter
from tempo_tpu.block.writer import write_block
from tempo_tpu.ingest.bus import Bus
from tempo_tpu.ingest.encoding import decode_push
from tempo_tpu.model.combine import combine_spans, sort_spans
from tempo_tpu.utils.livetraces import LiveTraceStore

CONSUMER_GROUP = "blockbuilder"


@dataclasses.dataclass
class BlockBuilderConfig:
    # owned partitions; None = consumer-group mode on a Kafka bus (the
    # group protocol assigns + re-assigns partitions across replicas)
    partitions: "tuple[int, ...] | None" = (0,)
    consume_cycle_records: int = 1000        # per-cycle fetch budget
    max_block_objects: int = 100_000
    dedicated_columns: tuple = ()
    # emit the sketch sidecar (block/sidecar.py) at cut time, while the
    # spans are still resident — the compactor only backfills blocks that
    # predate this knob
    sidecars: bool = True


class BlockBuilder:
    def __init__(self, bus: Bus, writer: RawWriter,
                 cfg: BlockBuilderConfig | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.bus = bus
        self.writer = writer
        self.cfg = cfg or BlockBuilderConfig()
        self.now = now
        self.blocks_flushed = 0
        self.records_consumed = 0
        self._cg = None                      # lazy ConsumerGroup

    def _owned(self):
        """(partitions, group) for this cycle: static assignment, or the
        consumer-group's current assignment (rebalances between cycles
        as replicas come and go — reader_client.go's franz-go group)."""
        if self.cfg.partitions is not None:
            return list(self.cfg.partitions), None
        if hasattr(self.bus, "group_request"):
            if self._cg is None:
                from tempo_tpu.ingest.kafka import ConsumerGroup
                self._cg = ConsumerGroup(self.bus, CONSUMER_GROUP,
                                         now=self.now)
            return self._cg.ensure_active(), self._cg
        return list(range(getattr(self.bus, "n_partitions", 1))), None

    def consume_cycle(self) -> int:
        """One cycle: per owned partition, drain from the committed offset,
        build+flush one block per tenant, then commit. Returns records."""
        total = 0
        parts, cg = self._owned()
        for p in parts:
            total += self._consume_partition(p, cg)
        return total

    def _consume_partition(self, partition: int, cg=None) -> int:
        start = self.bus.committed(CONSUMER_GROUP, partition)
        recs = self.bus.fetch(partition, start, self.cfg.consume_cycle_records)
        if not recs:
            return 0
        # accumulate per tenant (tenant_store.go live traces)
        stores: dict[str, LiveTraceStore] = {}
        for rec in recs:
            store = stores.setdefault(rec.tenant, LiveTraceStore(now=self.now))
            for tid, spans in decode_push(rec.value):
                store.push(tid, spans)
        # RF1 block(s) per tenant per cycle, flushed BEFORE commit; large
        # cycles split at max_block_objects traces per block
        for tenant, store in stores.items():
            traces = [(lt.trace_id, sort_spans(combine_spans(lt.spans)))
                      for lt in store.cut(immediate=True)]
            traces.sort(key=lambda t: t[0])
            cap = max(self.cfg.max_block_objects, 1)
            for lo in range(0, len(traces), cap):
                chunk = traces[lo: lo + cap]
                meta = write_block(self.writer, tenant, chunk,
                                   dedicated_columns=self.cfg.dedicated_columns,
                                   replication_factor=1)
                if self.cfg.sidecars:
                    from tempo_tpu.backend.meta import write_block_meta
                    from tempo_tpu.block.sidecar import (
                        sidecar_from_traces, write_sidecar)
                    write_sidecar(self.writer, tenant, meta.block_id,
                                  sidecar_from_traces(chunk))
                    meta.sidecar = True
                    write_block_meta(self.writer, meta)
                self.blocks_flushed += 1
        next_offset = recs[-1].offset + 1
        if cg is not None:
            cg.commit(partition, next_offset)    # generation-fenced
        else:
            self.bus.commit(CONSUMER_GROUP, partition, next_offset)
        n = len(recs)
        self.records_consumed += n
        return n


# producer helper re-export (moved to the encoding module; kept here for
# discoverability next to the consumer)
from tempo_tpu.ingest.encoding import produce_traces  # noqa: E402,F401
