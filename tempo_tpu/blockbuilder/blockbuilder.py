"""The blockbuilder service."""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Sequence

from tempo_tpu.backend.raw import RawWriter
from tempo_tpu.block.writer import write_block
from tempo_tpu.ingest.bus import Bus
from tempo_tpu.ingest.encoding import decode_push
from tempo_tpu.model.combine import combine_spans, sort_spans
from tempo_tpu.utils.livetraces import LiveTraceStore

CONSUMER_GROUP = "blockbuilder"


@dataclasses.dataclass
class BlockBuilderConfig:
    partitions: tuple[int, ...] = (0,)       # owned partitions
    consume_cycle_records: int = 1000        # per-cycle fetch budget
    max_block_objects: int = 100_000
    dedicated_columns: tuple = ()


class BlockBuilder:
    def __init__(self, bus: Bus, writer: RawWriter,
                 cfg: BlockBuilderConfig | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.bus = bus
        self.writer = writer
        self.cfg = cfg or BlockBuilderConfig()
        self.now = now
        self.blocks_flushed = 0
        self.records_consumed = 0

    def consume_cycle(self) -> int:
        """One cycle: per owned partition, drain from the committed offset,
        build+flush one block per tenant, then commit. Returns records."""
        total = 0
        for p in self.cfg.partitions:
            total += self._consume_partition(p)
        return total

    def _consume_partition(self, partition: int) -> int:
        start = self.bus.committed(CONSUMER_GROUP, partition)
        recs = self.bus.fetch(partition, start, self.cfg.consume_cycle_records)
        if not recs:
            return 0
        # accumulate per tenant (tenant_store.go live traces)
        stores: dict[str, LiveTraceStore] = {}
        for rec in recs:
            store = stores.setdefault(rec.tenant, LiveTraceStore(now=self.now))
            for tid, spans in decode_push(rec.value):
                store.push(tid, spans)
        # RF1 block(s) per tenant per cycle, flushed BEFORE commit; large
        # cycles split at max_block_objects traces per block
        for tenant, store in stores.items():
            traces = [(lt.trace_id, sort_spans(combine_spans(lt.spans)))
                      for lt in store.cut(immediate=True)]
            traces.sort(key=lambda t: t[0])
            cap = max(self.cfg.max_block_objects, 1)
            for lo in range(0, len(traces), cap):
                write_block(self.writer, tenant, traces[lo: lo + cap],
                            dedicated_columns=self.cfg.dedicated_columns,
                            replication_factor=1)
                self.blocks_flushed += 1
        next_offset = recs[-1].offset + 1
        self.bus.commit(CONSUMER_GROUP, partition, next_offset)
        n = len(recs)
        self.records_consumed += n
        return n


# producer helper re-export (moved to the encoding module; kept here for
# discoverability next to the consumer)
from tempo_tpu.ingest.encoding import produce_traces  # noqa: E402,F401
