"""HTTP API client (`pkg/httpclient` analog) — used by the CLI, vulture,
and tests that drive a live server."""

from __future__ import annotations

import json
import urllib.parse
import urllib.request


class Client:
    def __init__(self, base_url: str, tenant: str = "",
                 timeout_s: float = 30.0) -> None:
        self.base = base_url.rstrip("/")
        self.tenant = tenant
        self.timeout = timeout_s

    def _headers(self) -> dict:
        h = {"Content-Type": "application/json"}
        if self.tenant:
            h["X-Scope-OrgID"] = self.tenant
        return h

    def _get(self, path: str, params: dict | None = None) -> dict:
        url = self.base + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(url, headers=self._headers())
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read() or b"{}")

    def _post(self, path: str, body: bytes,
              ctype: str = "application/json") -> dict:
        h = self._headers()
        h["Content-Type"] = ctype
        req = urllib.request.Request(self.base + path, data=body, headers=h)
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            return json.loads(r.read() or b"{}")

    # -- API surface -------------------------------------------------------

    def push_otlp_json(self, payload: dict) -> dict:
        return self._post("/v1/traces", json.dumps(payload).encode())

    def trace_by_id(self, trace_id_hex: str) -> dict:
        return self._get(f"/api/traces/{trace_id_hex}")

    def search(self, query: str = "{ }", limit: int = 20,
               start_s: float | None = None, end_s: float | None = None) -> dict:
        params: dict = {"q": query, "limit": limit}
        if start_s is not None:
            params["start"] = start_s
        if end_s is not None:
            params["end"] = end_s
        return self._get("/api/search", params)

    def search_tags(self, scope: str = "") -> dict:
        return self._get("/api/search/tags", {"scope": scope} if scope else None)

    def search_tag_values(self, tag: str) -> dict:
        return self._get(f"/api/search/tag/{tag}/values")

    def query_range(self, query: str, start_s: float, end_s: float,
                    step_s: float = 60.0) -> dict:
        return self._get("/api/metrics/query_range", {
            "q": query, "start": start_s, "end": end_s, "step": step_s})

    def metrics_summary(self, query: str = "{ }", group_by: str = "") -> dict:
        return self._get("/api/metrics/summary",
                         {"q": query, "groupBy": group_by})

    def ready(self) -> bool:
        try:
            req = urllib.request.Request(self.base + "/ready")
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                return r.status == 200
        except Exception:
            return False
