"""tempo-cli analog: block inspection, direct block queries, maintenance.

Commands (subset of the reference's 27, the operationally load-bearing ones):

  list blocks <tenant>            blocklist table (`cmd-list-blocks.go`)
  list block <tenant> <block>     one block's meta + row groups
  list compaction-summary <tenant> per-level rollup (`cmd-list-compactionsummary.go`)
  analyse block <tenant> <block>  attr cardinality/bytes → dedicated-column
                                  candidates (`cmd-analyse-block.go`)
  query trace <tenant> <hex-id>   direct backend trace lookup (`cmd-query-blocks.go`)
  query search <tenant> <traceql> direct backend TraceQL search
  query api ...                   against a live server via the HTTP client
  gen bloom|index <tenant> <block>  regenerate derived files (`cmd-gen-*.go`)
  rewrite drop <tenant> <block> <hex-id>  rebuild a block without a trace
                                  (`cmd-rewrite-blocks.go` drop-trace)
  migrate tenant <src-tenant> <dst-tenant>  copy blocks (`cmd-migrate-tenant.go`)
  list column-sizes <tenant> <block>  per-column byte stats (`cmd-list-column.go`)
  list wal <dir>                  WAL segment/span inventory
  view rows <tenant> <block>      dump span rows as JSON lines
  query attr <tenant> <key> <value>  one-attribute backend search
  compact dry-run <tenant>        pending compaction jobs, read-only

Backend selection: --backend local --path DIR (or mem for tests).
"""

from __future__ import annotations

import argparse
import json
import sys


def _open_backend(args):
    if args.backend == "local":
        from tempo_tpu.backend.local import LocalBackend
        be = LocalBackend(args.path)
        return be, be
    raise SystemExit(f"unsupported backend {args.backend!r} (use --backend local)")


def _db(args):
    from tempo_tpu.db.tempodb import TempoDB
    r, w = _open_backend(args)
    db = TempoDB(r, w)
    db.poll_now()
    return db


def cmd_list_blocks(args) -> int:
    db = _db(args)
    metas = db.blocklist.metas(args.tenant)
    print(f"{'ID':38} {'LVL':>3} {'OBJECTS':>9} {'SPANS':>9} {'SIZE':>10} "
          f"{'RF':>2} {'START':>12} {'END':>12}")
    for m in sorted(metas, key=lambda m: m.start_time):
        print(f"{m.block_id:38} {m.compaction_level:>3} {m.total_objects:>9} "
              f"{m.total_spans:>9} {m.size_bytes:>10} {m.replication_factor:>2} "
              f"{m.start_time:>12.0f} {m.end_time:>12.0f}")
    print(f"total: {len(metas)} blocks, "
          f"{sum(m.total_objects for m in metas)} traces, "
          f"{sum(m.size_bytes for m in metas)} bytes")
    return 0


def cmd_list_block(args) -> int:
    db = _db(args)
    from tempo_tpu.backend.meta import read_block_meta
    m = read_block_meta(db.r, args.block, args.tenant)
    print(json.dumps(m.to_json(), indent=2))
    b = db.backend_block(m)
    for i, rg in enumerate(b.row_group_index()):
        print(f"row group {i}: rows={rg['rows']} offset={rg['row_offset']} "
              f"ids=[{rg['min_trace_id'][:8]}..{rg['max_trace_id'][:8]}]")
    return 0


def cmd_cache_summary(args) -> int:
    """Bloom-filter bytes by age (days) × compaction level — the cache
    sizing view (`cmd-list-cachesummary.go`: operators size the bloom
    cache role from this table)."""
    import time as _time

    from tempo_tpu.backend.raw import block_keypath
    from tempo_tpu.block.bloom import shard_name

    db = _db(args)
    now = _time.time()
    # (level, age_days) -> [shard_count, bloom_bytes]
    table: dict[tuple[int, int], list[int]] = {}
    max_lvl = max_age = 0
    for m in db.blocklist.metas(args.tenant):
        age = max(int((now - m.start_time) / 86400), 0)
        lvl = int(m.compaction_level)
        max_lvl, max_age = max(max_lvl, lvl), max(max_age, age)
        cell = table.setdefault((lvl, age), [0, 0])
        kp = block_keypath(m.block_id, args.tenant)
        for i in range(max(m.bloom_shard_count, 1)):
            try:
                cell[1] += db.r.size(shard_name(i), kp)
                cell[0] += 1
            except Exception:
                pass
    print("bloom filter shards by age (days) x compaction level:")
    hdr = "lvl " + "".join(f"{f'{d}d':>12}" for d in range(max_age + 1))
    print(hdr)
    total = 0
    for lvl in range(max_lvl + 1):
        row = [table.get((lvl, d), [0, 0]) for d in range(max_age + 1)]
        total += sum(c[1] for c in row)
        print(f"{lvl:>3} " + "".join(
            f"{f'{c[0]}/{c[1]}B':>12}" for c in row))
    print(f"total bloom bytes: {total}")
    return 0


def cmd_trace_summary(args) -> int:
    """Cross-block summary of one trace: block/span counts, duration,
    root span, service breakdown (`cmd-query-trace-summary.go`)."""
    db = _db(args)
    tid = bytes.fromhex(args.trace_id)
    n_blocks = 0
    spans: list[dict] = []
    size = 0
    for m in db.blocks(args.tenant):
        got = db.backend_block(m).find_trace_by_id(tid)
        if got:
            n_blocks += 1
            spans.extend(got)
            size += sum(len(s.get("name", "")) + 64 for s in got)
    if not spans:
        print("trace not found")
        return 1
    from tempo_tpu.model.combine import combine_spans
    spans = combine_spans(spans)
    start = min(s["start_unix_nano"] for s in spans)
    end = max(s["end_unix_nano"] for s in spans)
    by_svc: dict[str, int] = {}
    root = None
    for s in spans:
        by_svc[s.get("service", "")] = by_svc.get(s.get("service", ""), 0) + 1
        if not s.get("parent_span_id", b"").rstrip(b"\0"):
            root = s
    print(f"number of blocks: {n_blocks}")
    print(f"span count: {len(spans)}")
    print(f"trace size: ~{size} B")
    print(f"trace duration: {(end - start) / 1e9:.3f} seconds")
    print(f"root service name: {root.get('service', '') if root else '-'}")
    if root is not None:
        print(f"root span: name={root.get('name')!r} "
              f"kind={root.get('kind')} status={root.get('status_code')} "
              f"dur={(root['end_unix_nano'] - root['start_unix_nano']) / 1e6:.1f}ms")
    else:
        print("no root span found")
    print("top service.names:")
    for svc, n in sorted(by_svc.items(), key=lambda kv: -kv[1])[:10]:
        print(f"  {n:>6} {svc}")
    return 0


def cmd_compaction_summary(args) -> int:
    db = _db(args)
    levels: dict[int, list] = {}
    for m in db.blocklist.metas(args.tenant):
        levels.setdefault(m.compaction_level, []).append(m)
    print(f"{'LVL':>3} {'BLOCKS':>7} {'OBJECTS':>10} {'SIZE':>12}")
    for lvl in sorted(levels):
        ms = levels[lvl]
        print(f"{lvl:>3} {len(ms):>7} {sum(m.total_objects for m in ms):>10} "
              f"{sum(m.size_bytes for m in ms):>12}")
    return 0


def _accumulate_attr_bytes(pf, totals: dict) -> None:
    """Sum per-(scope, key) value bytes over a block's attr list columns
    (shared by `analyse block` and `analyse blocks`)."""
    for rg in range(pf.num_row_groups):
        tbl = pf.read_row_group(rg, columns=[
            c for c in pf.schema_arrow.names if "attr" in c])
        for col in tbl.schema.names:
            if not col.endswith("_keys"):
                continue
            vals_col = col.replace("_keys", "_vals")
            if vals_col not in tbl.schema.names:
                continue
            scope = "span" if col.startswith("s") else "resource"
            kf = tbl.column(col).combine_chunks().values.to_pylist()
            vf = tbl.column(vals_col).combine_chunks().values.to_pylist()
            for k, v in zip(kf, vf):
                totals[(scope, k)] = totals.get((scope, k), 0) + len(str(v))


def cmd_analyse_block(args) -> int:
    """Attribute stats → dedicated-column candidates (`cmd-analyse-block.go`)."""
    db = _db(args)
    from tempo_tpu.backend.meta import read_block_meta
    m = read_block_meta(db.r, args.block, args.tenant)
    stats: dict[tuple, int] = {}
    _accumulate_attr_bytes(db.backend_block(m).parquet_file(), stats)
    top = sorted(stats.items(), key=lambda kv: -kv[1])[: args.top]
    print(f"{'SCOPE':>9} {'ATTRIBUTE':40} {'BYTES':>12}")
    for (scope, k), sz in top:
        print(f"{scope:>9} {k:40} {sz:>12}")
    print("\ndedicated-column candidates (YAML):")
    for (scope, k), _ in top[:10]:
        print(f"  - {{scope: {scope}, name: {k}, type: string}}")
    return 0


def cmd_query_trace(args) -> int:
    db = _db(args)
    spans = db.find_trace_by_id(args.tenant, bytes.fromhex(args.trace_id))
    if not spans:
        print("trace not found", file=sys.stderr)
        return 1
    for s in spans:
        print(json.dumps({**s, "trace_id": s["trace_id"].hex(),
                          "span_id": s.get("span_id", b"").hex(),
                          "parent_span_id": s.get("parent_span_id", b"").hex()}))
    return 0


def cmd_query_search(args) -> int:
    db = _db(args)
    res = db.search(args.tenant, args.query, limit=args.limit)
    for md in res:
        print(json.dumps(md.to_json()))
    return 0


def cmd_query_api(args) -> int:
    from tempo_tpu.client import Client
    c = Client(args.url, tenant=args.tenant)
    if args.what == "trace":
        print(json.dumps(c.trace_by_id(args.arg), indent=2))
    elif args.what == "search":
        print(json.dumps(c.search(args.arg, limit=args.limit), indent=2))
    elif args.what == "tags":
        print(json.dumps(c.search_tags(), indent=2))
    return 0


def cmd_gen(args) -> int:
    """Regenerate bloom/index for a block from its data file."""
    db = _db(args)
    from tempo_tpu.backend.meta import read_block_meta
    from tempo_tpu.backend.raw import block_keypath
    from tempo_tpu.block.bloom import ShardedBloom, shard_name
    m = read_block_meta(db.r, args.block, args.tenant)
    b = db.backend_block(m)
    pf = b.parquet_file()
    kp = block_keypath(args.block, args.tenant)
    tids = []
    rgs = []
    row = 0
    for rg in range(pf.num_row_groups):
        tbl = pf.read_row_group(rg, columns=["trace_id"])
        col = tbl.column("trace_id").to_pylist()
        tids.extend(col)
        rgs.append({"row_offset": row, "rows": len(col),
                    "min_trace_id": bytes(col[0]).hex() if col else "",
                    "max_trace_id": bytes(col[-1]).hex() if col else ""})
        row += len(col)
    uniq = sorted({bytes(t) for t in tids})
    if args.what == "bloom":
        bloom = ShardedBloom(m.bloom_shard_count, max(len(uniq), 1), 0.01)
        for t in uniq:
            bloom.add(t.ljust(16, b"\0")[:16])
        for i in range(bloom.shard_count):
            db.w.write(shard_name(i), kp, bloom.shard_bytes(i))
        print(f"bloom regenerated: {len(uniq)} ids, {m.bloom_shard_count} shard(s)")
    else:
        db.w.write("index.json", kp, json.dumps({"row_groups": rgs}).encode())
        print(f"index regenerated: {len(rgs)} row groups")
    return 0


def cmd_rewrite_drop(args) -> int:
    """Rebuild a block excluding a trace id (`tempo-cli rewrite-blocks`)."""
    db = _db(args)
    from tempo_tpu.backend.meta import mark_block_compacted, read_block_meta
    from tempo_tpu.block.writer import write_block
    from tempo_tpu.db.compactor import iter_trace_groups
    drop = bytes.fromhex(args.trace_id)
    m = read_block_meta(db.r, args.block, args.tenant)
    b = db.backend_block(m)
    kept = [(tid, spans) for tid, spans in iter_trace_groups(b)
            if tid.rstrip(b"\0") != drop.rstrip(b"\0")]
    new = write_block(db.w, args.tenant, kept,
                      dedicated_columns=m.dedicated_columns,
                      replication_factor=m.replication_factor,
                      compaction_level=m.compaction_level)
    mark_block_compacted(db.r, db.w, m.block_id, args.tenant)
    print(f"rewrote {m.block_id} -> {new.block_id}: "
          f"{m.total_objects} -> {new.total_objects} traces")
    return 0


def cmd_migrate_tenant(args) -> int:
    db = _db(args)
    from tempo_tpu.backend.raw import block_keypath, blocks as list_blocks
    n = 0
    for bid in list_blocks(db.r, args.src):
        src_kp = block_keypath(bid, args.src)
        dst_kp = block_keypath(bid, args.dst)
        for name in db.r.find(src_kp):
            data = db.r.read(name, src_kp)
            if name == "meta.json":
                d = json.loads(data)
                d["tenant_id"] = args.dst
                data = json.dumps(d).encode()
            db.w.write(name, dst_kp, data)
        n += 1
    print(f"migrated {n} blocks {args.src} -> {args.dst}")
    return 0


def cmd_analyse_blocks(args) -> int:
    """Cross-block rollup of `analyse block` (`cmd-analyse-blocks.go`)."""
    db = _db(args)
    metas = sorted(db.blocklist.metas(args.tenant),
                   key=lambda m: -m.size_bytes)[: args.max_blocks]
    if not metas:
        print("no blocks", file=sys.stderr)
        return 1
    totals: dict[tuple, int] = {}
    for m in metas:
        _accumulate_attr_bytes(db.backend_block(m).parquet_file(), totals)
    top = sorted(totals.items(), key=lambda kv: -kv[1])[: args.top]
    print(f"analysed {len(metas)} block(s)")
    print(f"{'SCOPE':>9} {'ATTRIBUTE':40} {'BYTES':>12}")
    for (scope, k), sz in top:
        print(f"{scope:>9} {k:40} {sz:>12}")
    return 0


def cmd_list_index(args) -> int:
    """Tenant index contents (`cmd-list-index.go`)."""
    from tempo_tpu.backend import meta as bm
    db = _db(args)
    try:
        idx = bm.read_tenant_index(db.r, args.tenant)
    except Exception as e:
        print(f"no tenant index: {e}", file=sys.stderr)
        return 1
    print(json.dumps({
        "created_at": idx.created_at,
        "meta": [m.to_json() for m in idx.metas],
        "compacted": [c.to_json() for c in idx.compacted],
    }, indent=2))
    return 0


def cmd_view_schema(args) -> int:
    """Parquet schema of a block's data file (`cmd-view-pq-schema.go`)."""
    db = _db(args)
    from tempo_tpu.backend.meta import read_block_meta
    m = read_block_meta(db.r, args.block, args.tenant)
    pf = db.backend_block(m).parquet_file()
    print(pf.schema_arrow)
    print(f"\nrow groups: {pf.num_row_groups}  rows: {pf.metadata.num_rows}"
          f"  size: {m.size_bytes}B")
    return 0


def cmd_query_metrics(args) -> int:
    """TraceQL metrics over backend blocks (the query-range path the
    metrics queriers run; `tempo-cli query api metrics` analog)."""
    import time as _t

    from tempo_tpu.traceql.engine_metrics import QueryRangeRequest
    db = _db(args)
    end = args.end or _t.time()
    start = args.start or end - 3600
    req = QueryRangeRequest(query=args.query, start_ns=int(start * 1e9),
                            end_ns=int(end * 1e9),
                            step_ns=int(args.step * 1e9))
    for s in db.query_range(args.tenant, req):
        print(json.dumps({"labels": list(s.labels),
                          "samples": [float(v) for v in s.samples]}))
    return 0


def cmd_query_tags(args) -> int:
    """Distinct attr keys straight off the blocks' key-list columns."""
    from tempo_tpu.block.fetch import block_tag_names
    db = _db(args)
    out: dict[str, set] = {"span": set(), "resource": set()}
    for m in db.blocklist.metas(args.tenant):
        got = block_tag_names(db.backend_block(m), limit=args.limit)
        out["span"] |= got["span"]
        out["resource"] |= got["resource"]
    print(json.dumps({k: sorted(v) for k, v in out.items()}, indent=2))
    return 0


def cmd_list_column_sizes(args) -> int:
    """Per-parquet-column compressed/uncompressed byte stats for one block
    (`cmd-list-column.go` / the size half of `cmd-analyse-block.go`)."""
    from tempo_tpu.backend.meta import read_block_meta

    db = _db(args)
    m = read_block_meta(db.r, args.block, args.tenant)
    md = db.backend_block(m).parquet_file().metadata
    agg: dict[str, list[int]] = {}
    for rg in range(md.num_row_groups):
        g = md.row_group(rg)
        for ci in range(g.num_columns):
            c = g.column(ci)
            a = agg.setdefault(c.path_in_schema, [0, 0])
            a[0] += c.total_compressed_size
            a[1] += c.total_uncompressed_size
    total_c = sum(v[0] for v in agg.values()) or 1
    print(f"{'COLUMN':42} {'COMPRESSED':>12} {'RAW':>12} {'%':>6}")
    for name, (comp, raw) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        print(f"{name:42} {comp:>12} {raw:>12} {100 * comp / total_c:>5.1f}%")
    print(f"total: {total_c} compressed bytes, "
          f"{md.num_rows} rows, {md.num_row_groups} row groups")
    return 0


def cmd_view_rows(args) -> int:
    """Dump span rows of one block as JSON lines (block inspect /
    dump-rows; `cmd-parquet-...`-style deep inspection)."""
    from tempo_tpu.backend.meta import read_block_meta
    from tempo_tpu.block.fetch import scan_views

    db = _db(args)
    block = db.backend_block(read_block_meta(db.r, args.block, args.tenant))
    rgs = [args.rg] if args.rg is not None else None
    left = args.limit
    for view, _cand in scan_views(block, None, row_groups=rgs):
        tid = view.col("trace:id")
        sid = view.col("span:id")
        name = view.col("name")
        svc = view.col("resource.service.name")
        dur = view.col("duration")
        st = view.col("__startTime")
        for i in range(view.n):
            if left <= 0:
                return 0
            print(json.dumps({
                "traceID": tid.values[i], "spanID": sid.values[i],
                "name": name.values[i], "service": svc.values[i],
                "startUnixNano": int(st.values[i]),
                "durationNanos": int(dur.values[i])}))
            left -= 1
    return 0


def cmd_search_attr(args) -> int:
    """Search backend blocks by one attribute equality — the quick
    operator triage shape (`cmd-search.go` attr mode) without writing
    TraceQL by hand."""
    import re as _re

    v = args.value
    qstr = '"' + v.replace('"', '\\"') + '"'
    if _re.fullmatch(r"-?\d+(\.\d+)?", v):
        # numeric-looking values OR both typings: attrs stored as string
        # "200" vs int 200 both match (incomparable arms are just false).
        # Strict literal check — float() would admit nan/inf/1_0, which
        # are not TraceQL numbers
        query = f'{{ .{args.key} = {qstr} || .{args.key} = {v} }}'
    else:
        query = f'{{ .{args.key} = {qstr} }}'
    db = _db(args)
    res = db.search(args.tenant, query, limit=args.limit)
    for md in res:
        print(f"{md.trace_id} {md.root_service_name} "
              f"{md.root_trace_name} {md.duration_ms}ms")
    print(f"{len(res)} traces for {query}")
    return 0


def cmd_list_wal(args) -> int:
    """Inspect a WAL directory: per-block segment/span/byte counts
    (`cmd-list-...` over `tempodb/wal`)."""
    import os

    from tempo_tpu.block.wal import rescan_blocks

    blocks = rescan_blocks(args.dir)
    print(f"{'TENANT':16} {'BLOCK':38} {'SEGMENTS':>8} {'SPANS':>8} "
          f"{'BYTES':>10}")
    total = 0
    for wb in blocks:
        segs = wb.segments()
        nbytes = sum(os.path.getsize(s) for s in segs
                     if os.path.exists(s))
        nspans = sum(1 for _ in wb.iter_spans())
        total += nspans
        print(f"{wb.tenant:16} {wb.block_id:38} {len(segs):>8} "
              f"{nspans:>8} {nbytes:>10}")
    print(f"total: {len(blocks)} wal blocks, {total} spans")
    return 0


def cmd_compact_dryrun(args) -> int:
    """Show which block groups the time-window selector WOULD compact —
    no reads, no writes (`tempodb/compaction_block_selector.go` applied
    read-only)."""
    db = _db(args)
    metas = db.blocklist.metas(args.tenant)
    jobs = db.selector.blocks_to_compact(metas)
    if not jobs:
        print("nothing to compact")
        return 0
    for gi, group in enumerate(jobs):
        total = sum(m.size_bytes for m in group)
        print(f"job {gi}: {len(group)} blocks, {total} bytes")
        for m in group:
            print(f"  {m.block_id} lvl={m.compaction_level} "
                  f"objects={m.total_objects} size={m.size_bytes}")
    print(f"{len(jobs)} compaction job(s) pending")
    return 0


def cmd_usage_stats(args) -> int:
    """Print the persisted anonymized usage report (pkg/usagestats)."""
    from tempo_tpu.backend.raw import KeyPath
    from tempo_tpu.utils.usagestats import REPORT_NAME
    r, _w = _open_backend(args)
    try:
        print(r.read(REPORT_NAME, KeyPath(("usage-stats",))).decode())
    except Exception as e:
        print(f"no usage report: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_version(_args) -> int:
    from tempo_tpu import __version__
    print(f"tempo_tpu {__version__}")
    return 0


def cmd_gen_docs(_args) -> int:
    """Config manifest from the dataclasses (`pkg/docsgen`
    generate_manifest.go analog): every key, type, and default."""
    import dataclasses

    from tempo_tpu.app.config import Config

    print("# Configuration manifest\n")
    print("Generated from the config dataclasses "
          "(`python -m tempo_tpu.cli gen docs`).\n")

    def walk(cls, prefix: str) -> None:
        rows = []
        subs = []
        for f in dataclasses.fields(cls):
            default = f.default
            if default is dataclasses.MISSING and \
                    f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                default = f.default_factory()                       # type: ignore[misc]
            if dataclasses.is_dataclass(default):
                subs.append((f.name, type(default)))
                continue
            t = getattr(f.type, "__name__", None) or str(f.type)
            rows.append((f.name, t, default))
        if rows:
            print(f"## {prefix or '(root)'}\n")
            print("| key | type | default |")
            print("|---|---|---|")
            for name, t, d in rows:
                print(f"| `{prefix}{name}` | {t} | `{d!r}` |")
            print()
        for name, sub in subs:
            walk(sub, f"{prefix}{name}.")

    walk(Config, "")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser("tempo_tpu.cli")
    ap.add_argument("--backend", default="local")
    ap.add_argument("--path", default="./tempo-data/blocks")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("list")
    ls = p.add_subparsers(dest="what", required=True)
    q = ls.add_parser("blocks"); q.add_argument("tenant"); q.set_defaults(fn=cmd_list_blocks)
    q = ls.add_parser("block"); q.add_argument("tenant"); q.add_argument("block"); q.set_defaults(fn=cmd_list_block)
    q = ls.add_parser("compaction-summary"); q.add_argument("tenant"); q.set_defaults(fn=cmd_compaction_summary)
    q = ls.add_parser("index"); q.add_argument("tenant"); q.set_defaults(fn=cmd_list_index)
    q = ls.add_parser("column-sizes"); q.add_argument("tenant"); q.add_argument("block")
    q.set_defaults(fn=cmd_list_column_sizes)
    q = ls.add_parser("wal"); q.add_argument("dir"); q.set_defaults(fn=cmd_list_wal)
    q = ls.add_parser("cachesummary"); q.add_argument("tenant")
    q.set_defaults(fn=cmd_cache_summary)

    p = sub.add_parser("analyse")
    an = p.add_subparsers(dest="what", required=True)
    q = an.add_parser("block"); q.add_argument("tenant"); q.add_argument("block")
    q.add_argument("--top", type=int, default=20); q.set_defaults(fn=cmd_analyse_block)
    q = an.add_parser("blocks"); q.add_argument("tenant")
    q.add_argument("--top", type=int, default=20)
    q.add_argument("--max-blocks", type=int, default=10)
    q.set_defaults(fn=cmd_analyse_blocks)

    p = sub.add_parser("view")
    vw = p.add_subparsers(dest="what", required=True)
    q = vw.add_parser("pq-schema"); q.add_argument("tenant"); q.add_argument("block")
    q.set_defaults(fn=cmd_view_schema)
    q = vw.add_parser("rows"); q.add_argument("tenant"); q.add_argument("block")
    q.add_argument("--rg", type=int, default=None)
    q.add_argument("--limit", type=int, default=50)
    q.set_defaults(fn=cmd_view_rows)

    p = sub.add_parser("query")
    qs = p.add_subparsers(dest="what", required=True)
    q = qs.add_parser("trace"); q.add_argument("tenant"); q.add_argument("trace_id"); q.set_defaults(fn=cmd_query_trace)
    q = qs.add_parser("trace-summary"); q.add_argument("tenant")
    q.add_argument("trace_id"); q.set_defaults(fn=cmd_trace_summary)
    q = qs.add_parser("search"); q.add_argument("tenant"); q.add_argument("query")
    q.add_argument("--limit", type=int, default=20); q.set_defaults(fn=cmd_query_search)
    q = qs.add_parser("metrics"); q.add_argument("tenant"); q.add_argument("query")
    q.add_argument("--start", type=float, default=0.0)
    q.add_argument("--end", type=float, default=0.0)
    q.add_argument("--step", type=float, default=60.0)
    q.set_defaults(fn=cmd_query_metrics)
    q = qs.add_parser("tags"); q.add_argument("tenant")
    q.add_argument("--limit", type=int, default=1000)
    q.set_defaults(fn=cmd_query_tags)
    q = qs.add_parser("attr"); q.add_argument("tenant")
    q.add_argument("key"); q.add_argument("value")
    q.add_argument("--limit", type=int, default=20)
    q.set_defaults(fn=cmd_search_attr)
    for what in ("trace", "search", "tags"):
        q = qs.add_parser(f"api-{what}")
        q.add_argument("url"); q.add_argument("tenant")
        q.add_argument("arg", nargs="?" if what == "tags" else None, default="")
        q.add_argument("--limit", type=int, default=20)
        q.set_defaults(fn=cmd_query_api, what=what)

    p = sub.add_parser("gen")
    g = p.add_subparsers(dest="what", required=True)
    for what in ("bloom", "index"):
        q = g.add_parser(what); q.add_argument("tenant"); q.add_argument("block")
        q.set_defaults(fn=cmd_gen, what=what)
    q = g.add_parser("docs"); q.set_defaults(fn=cmd_gen_docs)

    p = sub.add_parser("rewrite")
    rw = p.add_subparsers(dest="what", required=True)
    q = rw.add_parser("drop"); q.add_argument("tenant"); q.add_argument("block")
    q.add_argument("trace_id"); q.set_defaults(fn=cmd_rewrite_drop)

    p = sub.add_parser("migrate")
    mg = p.add_subparsers(dest="what", required=True)
    q = mg.add_parser("tenant"); q.add_argument("src"); q.add_argument("dst")
    q.set_defaults(fn=cmd_migrate_tenant)

    p = sub.add_parser("compact")
    cp = p.add_subparsers(dest="what", required=True)
    q = cp.add_parser("dry-run"); q.add_argument("tenant")
    q.set_defaults(fn=cmd_compact_dryrun)

    q = sub.add_parser("usage-stats"); q.set_defaults(fn=cmd_usage_stats)
    q = sub.add_parser("version"); q.set_defaults(fn=cmd_version)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
