"""Operator CLI (`cmd/tempo-cli` analog): `python -m tempo_tpu.cli`."""
