"""Device-mesh parallelism: sharded sketch/registry pipelines.

The TPU-native replacement for the reference's scale-out constructs
(SURVEY.md §2.6): data parallelism over span batches replaces the
distributor's ring fan-out; series-dimension sharding replaces per-instance
registry partitioning; collective merges (psum for counts, pmax for HLL
registers) replace the frontend's combiner tree over gRPC.
"""

from tempo_tpu.parallel.mesh import (
    make_mesh,
    make_multihost_mesh,
    merge_sketch_states,
    mesh_fingerprint,
    sharded_query_range_step,
    sharded_serving_step,
    sharded_spanmetrics_step,
    shard_batch_arrays,
    validate_mesh_shape,
)
from tempo_tpu.parallel.serving import MeshConfig, ServingMesh

__all__ = [k for k in dir() if not k.startswith("_")]
