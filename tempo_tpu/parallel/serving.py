"""Process-wide serving mesh: the data×series mesh as a FIRST-CLASS
serving mode, not a parity demo.

`parallel.mesh` / `parallel.product` prove the sharded BlockScanPlane
kernels and `shard_map` spanmetrics pushes bit-match single-device
answers; this module is the production wiring that keeps the serving
process on the mesh permanently:

- the generator's registry and sketch planes (`registry/`,
  `ops/sketches.py`, spanmetrics) live sharded over 'series' as DONATED
  device buffers — one live copy per shard, no per-push state copy and
  no host round-trip (`place_spanmetrics_state` + the donated
  `mesh.sharded_serving_step`);
- the sched coalescer becomes mesh-aware: one padded batch window feeds
  every shard with a single `shard_map` dispatch (`submit_rows` align /
  shards), instead of per-device launches;
- the frontend combiner's cross-shard fold collapses into the in-mesh
  reduce (`engine_metrics.SeriesCombiner` consults `active()`), so
  merged series leave the mesh exactly once;
- the tempodb read plane adopts the same devices data-major
  (`plane_mesh`), the sequence-parallel scan of SNIPPETS [1]/[3].

Axis choice: 'series' is the PRIMARY serving axis — the same axis the
paged-state refactor (ROADMAP item 2, "Ragged Paged Attention") will
page over. Series sharding shrinks every shard's state plane (cache- and
HBM-bound scatter), needs NO collectives on the write path (each slot
lives on exactly one shard), and keeps collect() bit-identical at every
shard count: each shard scatters the same rows in the same order into
the slots it owns. The 'data' axis (batch rows sharded, delta psum)
remains available for real multi-chip row scaling; changing its size
changes float summation order, so the bit-stability guarantee is
per-data-layout.

Like `tempo_tpu.sched`, the mesh is process-level state: `App` calls
`configure()` from the `mesh:` config block before any module that
dispatches kernels is constructed; standalone callers (tests, bench)
use `use()` / `reset()`.

Nothing here imports jax at module import time — `Config` imports this
for the `mesh:` dataclass and must stay light.
"""

from __future__ import annotations

import dataclasses
import logging
import threading

import numpy as np

_LOG = logging.getLogger("tempo_tpu.mesh")


@dataclasses.dataclass
class MeshConfig:
    """Knobs for the serving mesh (`mesh:` in the app YAML)."""

    enabled: bool = False
    # devices to enlist; 0 = every visible device. Non-power-of-two
    # counts are clamped DOWN to the largest power of two so pow-2
    # coalescer buckets always split evenly across shards.
    devices: int = 0
    # series shards; 0 = auto (all enlisted devices — data axis 1, the
    # bit-stable no-collective layout). Must divide the device count;
    # devices // series_shards becomes the 'data' axis.
    series_shards: int = 0
    # frontend in-mesh combine: minimum pending sample count
    # (series x steps) before the cross-shard fold rides the device
    # reduce — small folds are microseconds on the host, and the device
    # path pays a matrix build + H2D + dispatch + gather
    combine_min_elements: int = 16384

    def check(self) -> list[str]:
        """Config warnings (chained into `app.config.Config.check()`).
        Pure shape math — never touches jax (config load must not
        initialize a backend)."""
        problems = []
        if self.devices < 0:
            problems.append("mesh.devices must be >= 0 (0 = all)")
        elif self.devices and self.devices & (self.devices - 1):
            problems.append(
                f"mesh.devices ({self.devices}) is not a power of two: "
                f"serve time clamps to {_pow2_floor(self.devices)} so "
                "pow-2 batch buckets split evenly across shards")
        if self.series_shards < 0:
            problems.append("mesh.series_shards must be >= 0 (0 = auto)")
        if self.devices and self.series_shards:
            from tempo_tpu.parallel.mesh import validate_mesh_shape
            problems += validate_mesh_shape(_pow2_floor(self.devices),
                                            self.series_shards)
        if self.combine_min_elements < 1:
            problems.append("mesh.combine_min_elements must be >= 1")
        return ["mesh: " + p for p in problems] if problems else []


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


class ServingMesh:
    """The resolved serving mesh + its sharding/step caches.

    Built once per `configure()`; every cache lives on the instance, so
    a reconfigure drops the old meshes AND their jitted steps together —
    no `id()`-keyed global cache to alias (see `mesh.mesh_fingerprint`
    for the product-path fix of that bug class).
    """

    def __init__(self, cfg: MeshConfig) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from tempo_tpu.parallel.mesh import make_mesh, validate_mesh_shape

        self.cfg = cfg
        devs = jax.devices()
        n = cfg.devices or len(devs)
        n = min(n, len(devs))
        p2 = _pow2_floor(max(n, 1))
        if p2 != n:
            _LOG.warning(
                "serving mesh: clamping %d devices to %d (largest power of "
                "two) so pow-2 batch buckets split evenly across shards",
                n, p2)
            n = p2
        series = cfg.series_shards or n
        if validate_mesh_shape(n, series):
            # keep as much series sharding as the clamped device count
            # allows (n is a power of two, so any pow-2 <= n divides it)
            # — falling all the way to 1 would silently pick the
            # data-parallel O(state) delta+psum layout instead
            fallback = _pow2_floor(max(min(series, n), 1))
            _LOG.warning(
                "serving mesh: series_shards %d invalid for %d devices "
                "(%s); falling back to %d",
                series, n, "; ".join(validate_mesh_shape(n, series)),
                fallback)
            series = fallback
        self.n_devices = n
        self.series_shards = series
        self.data_shards = n // series
        # registry mesh: the write-path layout (state over 'series',
        # batch over 'data')
        self.registry_mesh = make_mesh(n, series_shards=series)
        # read-plane mesh: every device on 'data' — BlockScanPlane
        # shards span columns sequence-parallel, XLA inserts the reduces
        self.plane_mesh = self.registry_mesh if series == 1 \
            else make_mesh(n, series_shards=1)
        self.series_1d = NamedSharding(self.registry_mesh, P("series"))
        self.series_2d = NamedSharding(self.registry_mesh,
                                       P("series", None))
        self.data_sharding = NamedSharding(self.registry_mesh, P("data"))
        # the packed [roles, bucket] batch matrix: columns over 'data' —
        # one H2D per dispatch (the transfer COUNT is the cost behind a
        # high-latency device link, mirroring the packed push paths)
        self.packed_sharding = NamedSharding(self.registry_mesh,
                                             P(None, "data"))
        self._steps: dict[tuple, object] = {}
        self._combine: dict[tuple, object] = {}
        self._lock = threading.Lock()

    # -- write path --------------------------------------------------------

    def fits_state(self, capacity: int, dd_rows: int,
                   mom_rows: int = 0) -> bool:
        """Whether a (series table, sketch planes) set can shard over
        this mesh (every shard needs an equal slot range)."""
        s = self.series_shards
        return capacity % s == 0 and (not dd_rows or dd_rows % s == 0) \
            and (not mom_rows or mom_rows % s == 0)

    def serving_step(self, edges: tuple, gamma: float, min_value: float,
                     capacity: int, dd_rows: int, packed: bool = False,
                     mom_rows: int = 0, mom_meta: "tuple | None" = None):
        """The donated sharded fused spanmetrics step, memoized per
        hyperparameter set (the mesh itself is fixed per instance)."""
        key = (tuple(edges), float(gamma), float(min_value),
               int(capacity), int(dd_rows), bool(packed),
               int(mom_rows), mom_meta)
        with self._lock:
            fn = self._steps.get(key)
            if fn is None:
                from tempo_tpu.parallel.mesh import sharded_serving_step
                fn = self._steps[key] = sharded_serving_step(
                    self.registry_mesh, tuple(edges), gamma, min_value,
                    capacity, dd_rows, packed=packed, mom_rows=mom_rows,
                    mom_meta=mom_meta)
            return fn

    def put_batch(self, *arrays):
        """Host batch vectors → device, leading dim sharded over 'data'.
        Lengths must be a multiple of `data_shards` (the coalescer's
        `align` guarantees it for scheduled dispatches)."""
        import jax

        return tuple(jax.device_put(a, self.data_sharding) for a in arrays)

    def put_packed(self, mat: np.ndarray):
        """One [roles, bucket] f32 matrix → device, columns over 'data'
        — the single-transfer batch upload."""
        import jax

        return jax.device_put(mat, self.packed_sharding)

    # -- frontend combine --------------------------------------------------

    def combine(self, stacked: np.ndarray, op: str) -> np.ndarray:
        """The in-mesh cross-shard fold: `stacked` is [K, C, T] f32 —
        K merged series (sharded over 'series'), C per-series
        contributions (sub-requests/shards/jobs), T steps. One device
        reduce over C (the psum/pmax of the combiner tree), one gather
        out — merged series leave the mesh exactly once. K must divide
        by series_shards (callers pad; identity fill rows reduce to the
        identity)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        key = (op, stacked.shape[1], stacked.shape[2])
        with self._lock:
            fn = self._combine.get(key)
            if fn is None:
                from tempo_tpu.obs.jaxruntime import instrumented_jit

                red = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}[op]
                fn = self._combine[key] = instrumented_jit(
                    lambda m: red(m, axis=1),
                    name="frontend_mesh_combine")
        sh = NamedSharding(self.registry_mesh, P("series", None, None))
        out = fn(jax.device_put(stacked, sh))
        return np.asarray(out)


# ---------------------------------------------------------------------------
# the process-wide mesh (configured by App, consulted everywhere)
# ---------------------------------------------------------------------------

_active: "ServingMesh | None" = None
_lock = threading.Lock()


def configure(cfg: MeshConfig | None) -> "ServingMesh | None":
    """Build (or drop) the process serving mesh from the `mesh:` config
    block. Returns the active mesh or None when disabled. Never raises
    on a bad shape — it warns and falls back (serve time must not die
    on a config typo; `Config.check()` already surfaced it)."""
    global _active
    with _lock:
        if cfg is None or not cfg.enabled:
            _active = None
            return None
        try:
            _active = ServingMesh(cfg)
        except Exception as e:  # noqa: BLE001 — config fallback, logged
            _LOG.error("serving mesh disabled: %r", e)
            _active = None
        return _active


def active() -> "ServingMesh | None":
    """The process serving mesh, or None — callers fall back to their
    single-device dispatch."""
    return _active


def reset() -> None:
    """Drop the process mesh (test isolation)."""
    global _active
    with _lock:
        _active = None


class use:
    """Install a mesh (or None) as the process serving mesh for a
    with-block (tests, bench arms)."""

    def __init__(self, sm: "ServingMesh | None") -> None:
        self.sm = sm
        self._prev: "ServingMesh | None" = None

    def __enter__(self) -> "ServingMesh | None":
        global _active
        with _lock:
            self._prev, _active = _active, self.sm
        return self.sm

    def __exit__(self, *exc) -> None:
        global _active
        with _lock:
            _active = self._prev


def place_spanmetrics_state(proc, sm: "ServingMesh | None" = None) -> bool:
    """Re-place a SpanMetricsProcessor's device state onto the serving
    mesh: slot dims shard over 'series', replicated over 'data'.
    Idempotent (device_put to the same sharding is a no-op move).
    Returns False (and leaves state alone) when the capacities don't
    split evenly across the shards. Caller holds the registry
    state_lock — this rebinds live state."""
    sm = sm or _active
    if sm is None:
        return False
    if getattr(proc, "_paged", False):
        # paged processors shard at the POOL level: arenas are placed
        # page-aligned over 'series' when the pool is built, and the
        # paged fused step is mesh-aware — there is no per-tenant dense
        # state to move (and no capacity-divisibility requirement)
        return False
    from tempo_tpu.ops.moments import moments_place
    from tempo_tpu.ops.sketches import dd_place
    from tempo_tpu.registry import metrics as rm

    dd_rows = proc.dd.counts.shape[0] if proc.dd is not None else 0
    mom = getattr(proc, "mom", None)
    mom_rows = mom.data.shape[0] if mom is not None else 0
    if not sm.fits_state(proc.calls.table.capacity, dd_rows, mom_rows):
        _LOG.warning(
            "serving mesh: capacity %d / sketch rows %d/%d not divisible "
            "by series_shards %d — processor stays single-device",
            proc.calls.table.capacity, dd_rows, mom_rows, sm.series_shards)
        return False
    proc.calls.state = rm.place_state(proc.calls.state, sm.series_1d,
                                      sm.series_2d)
    proc.latency.state = rm.place_state(proc.latency.state, sm.series_1d,
                                        sm.series_2d)
    proc.sizes.state = rm.place_state(proc.sizes.state, sm.series_1d,
                                      sm.series_2d)
    if proc.dd is not None:
        proc.dd = dd_place(proc.dd, sm.series_1d, sm.series_2d)
    if mom is not None:
        proc.mom = moments_place(mom, sm.series_2d)
    return True


# ---------------------------------------------------------------------------
# obs: mesh families in the process-wide runtime registry
# ---------------------------------------------------------------------------

from tempo_tpu.obs.jaxruntime import RUNTIME  # noqa: E402

RUNTIME.gauge_func(
    "tempo_mesh_devices",
    lambda: [] if _active is None else [((), float(_active.n_devices))],
    help="Devices enlisted in the serving mesh (absent family values "
         "when mesh mode is off)")
RUNTIME.gauge_func(
    "tempo_mesh_series_shards",
    lambda: [] if _active is None else [((), float(_active.series_shards))],
    help="'series' axis size of the serving mesh: registry/sketch slot "
         "ranges are partitioned this many ways")
RUNTIME.gauge_func(
    "tempo_mesh_data_shards",
    lambda: [] if _active is None else [((), float(_active.data_shards))],
    help="'data' axis size of the serving mesh: coalesced batch rows "
         "split this many ways per dispatch")


__all__ = ["MeshConfig", "ServingMesh", "configure", "active", "reset",
           "use", "place_spanmetrics_state"]
