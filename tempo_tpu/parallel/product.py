"""PRODUCT paths under a device mesh (round-4 weak #3 closure).

The kernel-level sharded steps in `parallel.mesh` prove the collectives
compile; these helpers run the REAL product objects multi-device:

- `sharded_push_batch`: a `SpanMetricsProcessor`'s push — its own host
  staging (`_label_rows` + `resolve_slots`, the same series table and
  interner) feeding the fused update under `shard_map`, with the
  processor's ACTUAL state arrays sharded over 'series' and the span
  batch over 'data'. The processor's `collect()` then reads the sharded
  state transparently (jax gathers on np.asarray) — registry semantics
  (exemplars, staleness, budgets) stay host-side and unchanged.
- Multi-device `query_range` needs no helper: pass a mesh via
  `TempoDBConfig(plane_mesh=...)` and every `BlockScanPlane` kernel runs
  SPMD-sharded over 'data' (adoption shards the span columns; XLA's
  partitioner inserts the grid reduce). `tests/test_parallel.py` and
  `__graft_entry__.dryrun_multichip` assert parity against the host
  engine and the single-device plane on real queries.

Reference combine tree analog:
`modules/frontend/combiner/metrics_query_range.go` — the cross-job tensor
add becomes the 'data'-axis psum.
"""

from __future__ import annotations

import numpy as np

_STEP_CACHE: dict = {}


def _cached_step(mesh, edges, gamma, min_value):
    """Jitted sharded step memoized per (mesh, hyperparams) — a fresh
    shard_map per push would recompile every call. Keyed by the mesh's
    VALUE identity (shape + device ids), never `id(mesh)`: ids are
    reused after garbage collection, and an aliased entry would hand a
    new mesh a jitted step compiled for a dead mesh's device layout."""
    from tempo_tpu.parallel.mesh import mesh_fingerprint

    key = (mesh_fingerprint(mesh), edges, float(gamma), float(min_value))
    fn = _STEP_CACHE.get(key)
    if fn is None:
        from tempo_tpu.parallel.mesh import sharded_spanmetrics_step

        if len(_STEP_CACHE) >= 16:
            _STEP_CACHE.clear()
        fn = _STEP_CACHE[key] = sharded_spanmetrics_step(
            mesh, edges, gamma, min_value)
    return fn


def shard_processor_state(proc, mesh) -> None:
    """Re-place a SpanMetricsProcessor's device state for `mesh`: slot
    dimensions shard over 'series', everything replicated over 'data'.
    Idempotent; call once before `sharded_push_batch`."""
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    s1 = NamedSharding(mesh, P("series"))
    s2 = NamedSharding(mesh, P("series", None))
    put = jax.device_put
    cs, hs, zs = proc.calls.state, proc.latency.state, proc.sizes.state
    proc.calls.state = type(cs)(put(cs.values, s1))
    proc.latency.state = type(hs)(put(hs.bucket_counts, s2),
                                  put(hs.sums, s1), put(hs.counts, s1),
                                  hs.edges)
    proc.sizes.state = type(zs)(put(zs.values, s1))
    if proc.dd is not None:
        dd = proc.dd
        proc.dd = type(dd)(put(dd.counts, s2), put(dd.zeros, s1),
                           dd.gamma, dd.min_value)


def sharded_push_batch(proc, mesh, sb, span_sizes=None) -> None:
    """One PRODUCT spanmetrics push under the mesh.

    Host staging is the processor's own: label rows built on the tenant
    interner, slots resolved against the shared series table (so the
    single-device and sharded paths agree on slot assignment bit-for-bit).
    The device update is `parallel.mesh.sharded_spanmetrics_step` over the
    processor's state arrays; exemplars ride the same `note_exemplars`.
    """
    import jax
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from tempo_tpu.ops import sketches
    from tempo_tpu.registry import metrics as rm

    if sb.interner is not proc.registry.interner:
        raise ValueError("SpanBatch must use the tenant registry's interner")
    valid = sb.valid.copy()
    if proc._policies:
        keep = proc._policies(sb)
        proc.spans_discarded += int((valid & ~keep).sum())
        valid &= keep
    rows = proc._label_rows(sb)
    slots = proc.calls.resolve_slots(rows, valid=valid)
    dur_s = (sb.duration_ns / 1e9).astype(np.float32)
    if span_sizes is None:
        span_sizes = np.zeros(sb.capacity, np.float32)
    weights = np.ones(sb.capacity, np.float32)

    dd = proc.dd
    step = _cached_step(
        mesh, tuple(proc.latency.state.edges),
        dd.gamma if dd is not None else sketches.dd_params(0.01)[0],
        dd.min_value if dd is not None else 1e-9)
    data_sh = NamedSharding(mesh, P("data"))
    put = jax.device_put
    batch = (put(np.ascontiguousarray(slots, np.int32), data_sh),
             put(dur_s, data_sh),
             put(span_sizes.astype(np.float32), data_sh),
             put(weights, data_sh))
    cs, hs, zs = proc.calls.state, proc.latency.state, proc.sizes.state
    dd_counts = dd.counts if dd is not None else \
        np.zeros((cs.values.shape[0], 1), np.float32)
    dd_zeros = dd.zeros if dd is not None else \
        np.zeros((cs.values.shape[0],), np.float32)
    out = step(cs.values, hs.bucket_counts, hs.sums, hs.counts, zs.values,
               dd_counts, dd_zeros, *batch)
    proc.calls.state = rm.CounterState(out[0])
    proc.latency.state = rm.HistogramState(out[1], out[2], out[3], hs.edges)
    proc.sizes.state = rm.CounterState(out[4])
    if dd is not None:
        proc.dd = sketches.DDSketch(out[5], out[6], dd.gamma, dd.min_value)
    ts_ms = int(proc.registry.now() * 1000)
    proc.calls.note_exemplars(slots, sb.trace_id, dur_s, ts_ms)
    proc.latency.exemplars = proc.calls.exemplars
