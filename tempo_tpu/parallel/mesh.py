"""Mesh construction and the sharded aggregation step.

Parallelism axes (the analog of the reference's strategies, SURVEY.md §2.6):

- `data`: span batches are split across devices — the ring-of-ingesters /
  shuffle-shard fan-out (`distributor.go:511-547`) becomes a sharded array
  dimension. Registry updates happen on local shards; the quorum-merge
  becomes a `psum` over this axis.
- `series`: metric series slots are sharded — the per-instance registry
  partitioning becomes a sharded state dimension. Each device owns
  max_active_series / series_shards slots; a slot's owner is slot//shard_cap,
  so updates need no all-to-all (mirroring how the reference routes series to
  exactly one generator instance via the partition ring).

The canonical step below (spanmetrics fused update under shard_map) is what
`__graft_entry__.dryrun_multichip` compiles across an N-device mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from tempo_tpu.ops import sketches
from tempo_tpu.registry import metrics as rm


def validate_mesh_shape(n_devices: int, series_shards: int) -> list[str]:
    """Config-style problem list for a proposed mesh shape (empty = ok).
    Shared by `config.check()` (the `mesh:` block warnings) and the mesh
    constructors, so a bad shard count surfaces as a standard config
    warning at load time instead of an AssertionError at serve time."""
    problems = []
    if series_shards < 1:
        problems.append(f"mesh series_shards must be >= 1 "
                        f"(got {series_shards})")
    elif series_shards > n_devices:
        problems.append(f"mesh series_shards ({series_shards}) exceeds the "
                        f"device count ({n_devices}): shards <= devices")
    elif n_devices % series_shards:
        problems.append(f"mesh series_shards ({series_shards}) must divide "
                        f"the device count ({n_devices})")
    return problems


def make_mesh(n_devices: int | None = None, series_shards: int = 1) -> Mesh:
    """2D mesh ('data', 'series'). series_shards must divide device count."""
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    devs = np.array(devs[:n])
    problems = validate_mesh_shape(n, series_shards)
    if problems:
        raise ValueError("; ".join(problems))
    return Mesh(devs.reshape(n // series_shards, series_shards), ("data", "series"))


def make_multihost_mesh(series_shards: int = 1) -> Mesh:
    """Multi-host mesh: 'data' spans hosts (DCN), 'series' stays within a
    host's slice (ICI) — collectives on 'series' ride ICI, the data-psum
    crosses DCN once per step, mirroring how the reference keeps ingester
    traffic local and only ships merged series to the frontend.

    Falls back to the flat single-host mesh when only one process exists.
    """
    if jax.process_count() == 1:
        return make_mesh(series_shards=series_shards)
    from jax.experimental import mesh_utils

    per_host = jax.local_device_count()
    problems = validate_mesh_shape(per_host, series_shards)
    if problems:
        raise ValueError("; ".join(problems))
    devs = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(per_host // series_shards, series_shards),
        dcn_mesh_shape=(jax.process_count(), 1))
    return Mesh(devs, ("data", "series"))


def mesh_fingerprint(mesh: Mesh) -> tuple:
    """Value identity for a mesh, safe to key caches on. `id(mesh)` is NOT:
    ids are reused after garbage collection, so a cache keyed on it can
    alias a dead mesh's jitted step onto a brand-new mesh with a
    different device layout."""
    return (tuple(mesh.shape.items()),
            tuple(d.id for d in mesh.devices.flat))


def shard_batch_arrays(mesh: Mesh, arrays: dict) -> dict:
    """Place host batch columns with leading dim sharded over 'data'."""
    sh = NamedSharding(mesh, P("data"))
    return {k: jax.device_put(v, sh) for k, v in arrays.items()}


def merge_sketch_states(state, axis_name: str = "data"):
    """Collective merge of sketch/registry pytrees inside shard_map/pjit:
    HLL registers merge with pmax, everything else (counts/sums) with psum."""

    def merge(path, leaf):
        if any(getattr(p, "name", "") == "registers" for p in path):
            return jax.lax.pmax(leaf, axis_name)
        return jax.lax.psum(leaf, axis_name)

    return jax.tree_util.tree_map_with_path(merge, state)


def sharded_spanmetrics_step(mesh: Mesh, edges: tuple, gamma: float,
                             min_value: float):
    """Build the jitted multi-device spanmetrics step over `mesh`.

    Layout: span columns sharded over 'data' (replicated over 'series');
    registry state arrays sharded over 'series' on their slot dim and
    replicated over 'data'. Each device updates only the slots it owns; a
    psum over 'data' yields the global state — the collective that replaces
    the reference's frontend combiner tree.

    Takes/returns raw arrays (static hyperparams via closure) so the
    shard_map in/out specs are flat.
    """

    def step(calls_v, h_buckets, h_sums, h_counts, size_v, dd_counts,
             dd_zeros, slots, dur_s, sizes, weights):
        shard_cap = calls_v.shape[0]  # local slot count
        my_shard = jax.lax.axis_index("series")
        owner = jnp.where(slots >= 0, slots // shard_cap, -1)
        local = jnp.where(owner == my_shard, slots - my_shard * shard_cap, -1)

        # Updates start from ZERO states so only the delta is psum'd over
        # 'data' (the base state is replicated across data shards; summing it
        # would multiply prior state by the data-shard count every step).
        z = jnp.zeros_like
        calls_d = rm.counter_update(rm.CounterState(z(calls_v)), local, weights)
        hist_d = rm.histogram_update(
            rm.HistogramState(z(h_buckets), z(h_sums), z(h_counts), edges),
            local, dur_s, weights)
        size_d = rm.counter_update(rm.CounterState(z(size_v)), local,
                                   sizes * weights)
        keep = local >= 0
        dd_d = sketches.dd_update(
            sketches.DDSketch(z(dd_counts), z(dd_zeros), gamma, min_value),
            jnp.where(keep, local, 0), dur_s, mask=keep, weights=weights)
        deltas = (calls_d.values, hist_d.bucket_counts, hist_d.sums,
                  hist_d.counts, size_d.values, dd_d.counts, dd_d.zeros)
        base = (calls_v, h_buckets, h_sums, h_counts, size_v, dd_counts, dd_zeros)
        return tuple(b + jax.lax.psum(d, "data") for b, d in zip(base, deltas))

    state_specs = (P("series"), P("series", None), P("series"), P("series"),
                   P("series"), P("series", None), P("series"))
    batch_specs = (P("data"),) * 4
    fn = _shard_map(step, mesh=mesh,
                    in_specs=state_specs + batch_specs,
                    out_specs=state_specs)
    return jax.jit(fn)


def sharded_serving_step(mesh: Mesh, edges: tuple, gamma: float,
                         min_value: float, capacity: int, dd_rows: int,
                         packed: bool = False, mom_rows: int = 0,
                         mom_meta: "tuple | None" = None):
    """The MESH-RESIDENT serving twin of `sharded_spanmetrics_step`:
    the fused spanmetrics update a `SpanMetricsProcessor` dispatches when
    the process serving mesh is on (`tempo_tpu.parallel.serving`).

    Differences from the dryrun step above:

    - **Donated**: the state arrays (the ~90MB fused plane at default
      capacity) are donated like the single-device fast paths — one
      live copy per shard, no per-push state copy. Callers hold the
      registry `state_lock` across dispatch + rebind, same discipline as
      `_fused_update_donated`.
    - **Sketch plane capacity**: the DDSketch plane may be SMALLER than
      the series table (`sketch_max_series < max_active_series`), so its
      slot→shard mapping uses its own shard capacity; slots beyond the
      plane are masked, matching `_fused_update_impl`. `dd_rows=0`
      builds a sketchless step (no dd arguments at all).
    - **Bit-stability across series shard counts**: each series shard
      scatters the SAME batch rows in the same order into the slots it
      owns (others drop), so per-slot float accumulation order is
      independent of `series_shards` — collect() is bit-identical at
      every shard count as long as the data axis stays fixed. (Changing
      DATA shards changes psum association: close, not bit-equal.)
    - **Packed form** (`packed=True`): the batch arrives as ONE
      [4, bucket] f32 matrix (slots, dur_s, sizes, weights) sharded
      over 'data' on its column axis — a single H2D per dispatch, the
      mesh twin of `_fused_update_packed4`. Slot ids ride f32 exactly
      under the caller's capacity < 2^24 gate.

    `mom_rows` / `mom_meta` = (k, lo, hi): the moments-sketch sidecar
    plane (ops/moments.py) — rides the same slot→shard mapping as the
    DDSketch plane; its state array appends AFTER the dd pair. Combine
    on the data axis: the moment-sum columns psum like every counter,
    the two bound columns pmax (see `moments_merge`).

    Returns jit(fn(states..., slots, dur_s, sizes, weights) -> states)
    — or jit(fn(states..., packed_matrix) -> states) when `packed`.
    """
    from tempo_tpu.ops import moments as msk

    n_series_shards = mesh.shape["series"]
    data_shards = mesh.shape["data"]
    if capacity % n_series_shards or \
            (dd_rows and dd_rows % n_series_shards) or \
            (mom_rows and mom_rows % n_series_shards):
        raise ValueError(
            f"serving mesh: state capacities ({capacity}, dd {dd_rows}, "
            f"moments {mom_rows}) must divide by series_shards "
            f"({n_series_shards})")
    shard_cap = capacity // n_series_shards
    dd_shard = dd_rows // n_series_shards if dd_rows else 0
    mom_shard = mom_rows // n_series_shards if mom_rows else 0
    n_sketch = (2 if dd_shard else 0) + (1 if mom_shard else 0)

    def step(calls_v, h_buckets, h_sums, h_counts, size_v, *rest):
        sk = rest[:n_sketch]
        dd_counts = dd_zeros = mom_data = None
        if dd_shard:
            dd_counts, dd_zeros = sk[0], sk[1]
        if mom_shard:
            mom_data = sk[-1]
        rest = rest[n_sketch:]
        if packed:
            mat = rest[0]
            slots = mat[0].astype(jnp.int32)
            dur_s, sizes, weights = mat[1], mat[2], mat[3]
        else:
            slots, dur_s, sizes, weights = rest
        my_shard = jax.lax.axis_index("series")
        owner = jnp.where(slots >= 0, slots // shard_cap, -1)
        local = jnp.where(owner == my_shard, slots - my_shard * shard_cap, -1)
        if dd_shard:
            # the sketch plane's OWN slot→shard mapping (it may be a
            # strict prefix of the series table)
            dd_keep = (slots >= 0) & (slots < dd_rows) & \
                (slots // dd_shard == my_shard)
            local_dd = jnp.where(dd_keep, slots - my_shard * dd_shard, 0)
        if mom_shard:
            mom_keep = (slots >= 0) & (slots < mom_rows) & \
                (slots // mom_shard == my_shard)
            local_mom = jnp.where(mom_keep, slots - my_shard * mom_shard, -1)
            mk, mlo, mhi = mom_meta
        if data_shards == 1:
            # series-only layout (the serving default): each shard owns
            # its slots OUTRIGHT, so the scatter lands straight in the
            # donated base state — no zero-delta staging, no full-state
            # add, no collective at all. This is also what keeps the
            # update cost per dispatch O(batch + touched rows) instead
            # of O(state): the delta+psum form below walks the whole
            # ~90MB fused plane every dispatch.
            calls = rm.counter_update(rm.CounterState(calls_v), local,
                                      weights)
            hist = rm.histogram_update(
                rm.HistogramState(h_buckets, h_sums, h_counts, edges),
                local, dur_s, weights)
            size_c = rm.counter_update(rm.CounterState(size_v), local,
                                       sizes * weights)
            out = (calls.values, hist.bucket_counts, hist.sums, hist.counts,
                   size_c.values)
            if dd_shard:
                dd = sketches.dd_update(
                    sketches.DDSketch(dd_counts, dd_zeros, gamma, min_value),
                    local_dd, dur_s, mask=dd_keep, weights=weights)
                out += (dd.counts, dd.zeros)
            if mom_shard:
                mom = msk.moments_update(
                    msk.MomentsSketch(mom_data, mk, mlo, mhi),
                    local_mom, dur_s, mask=mom_keep, weights=weights)
                out += (mom.data,)
            return out
        # data-parallel layout: deltas from ZERO state so only the delta
        # psums over 'data' (the base state is replicated across data
        # shards; summing it would multiply prior state every step)
        z = jnp.zeros_like
        calls_d = rm.counter_update(rm.CounterState(z(calls_v)), local,
                                    weights)
        hist_d = rm.histogram_update(
            rm.HistogramState(z(h_buckets), z(h_sums), z(h_counts), edges),
            local, dur_s, weights)
        size_d = rm.counter_update(rm.CounterState(z(size_v)), local,
                                   sizes * weights)
        deltas = [calls_d.values, hist_d.bucket_counts, hist_d.sums,
                  hist_d.counts, size_d.values]
        base = [calls_v, h_buckets, h_sums, h_counts, size_v]
        if dd_shard:
            dd_d = sketches.dd_update(
                sketches.DDSketch(z(dd_counts), z(dd_zeros), gamma,
                                  min_value),
                local_dd, dur_s, mask=dd_keep, weights=weights)
            deltas += [dd_d.counts, dd_d.zeros]
            base += [dd_counts, dd_zeros]
        out = [b + jax.lax.psum(d, "data") for b, d in zip(base, deltas)]
        if mom_shard:
            # the moments delta: sum columns psum like every counter;
            # the two bound columns combine with pmax (support maxes)
            mom_d = msk.moments_update(
                msk.MomentsSketch(z(mom_data), mk, mlo, mhi),
                local_mom, dur_s, mask=mom_keep, weights=weights).data
            summed = mom_data[..., :mk + 1] + \
                jax.lax.psum(mom_d[..., :mk + 1], "data")
            bounds = jnp.maximum(mom_data[..., mk + 1:],
                                 jax.lax.pmax(mom_d[..., mk + 1:], "data"))
            out.append(jnp.concatenate([summed, bounds], axis=-1))
        return tuple(out)

    n_states = 5 + n_sketch
    state_specs = (P("series"), P("series", None), P("series"), P("series"),
                   P("series"))
    if dd_shard:
        state_specs += (P("series", None), P("series"))
    if mom_shard:
        state_specs += (P("series", None),)
    batch_specs = (P(None, "data"),) if packed else (P("data"),) * 4
    # check_rep=False: the base-scatter branch's outputs ARE replicated
    # over 'data' (the axis has size 1 there), but without a psum the
    # static replication checker can't infer it
    fn = _shard_map(step, mesh=mesh,
                    in_specs=state_specs + batch_specs,
                    out_specs=state_specs, check_rep=False)
    # instrumented: the serving path's zero-steady-state-recompile gate
    # (bench multichip stage) reads the per-fn compile counters
    from tempo_tpu.obs.jaxruntime import instrumented_jit

    return instrumented_jit(fn, name="spanmetrics_fused_update_mesh",
                            donate_argnums=tuple(range(n_states)))


def sharded_query_range_step(mesh: Mesh, n_buckets: int = 0):
    """Multi-device TraceQL-metrics observation: the sequence-parallel scan.

    The reference shards a query's *time/span space* into jobs combined at
    the frontend (`metrics_query_range_sharder.go` + `combiner/`); here the
    span batch is the sharded sequence dimension and the combine is one
    psum. Layout: spans (slots/steps/values) sharded over 'data'; the
    [series, steps] (or [series, steps, buckets] when n_buckets>0 — the
    quantile histogram plane) grid sharded over 'series' on dim 0. Each
    device scatter-adds its span shard into the slots it owns; psum over
    'data' is the cross-shard combine.

    Returns jit(fn(grid, slots, steps, values) -> grid).
    """

    def step(grid, slots, steps, values):
        shard_cap = grid.shape[0]
        my_shard = jax.lax.axis_index("series")
        owner = jnp.where(slots >= 0, slots // shard_cap, -1)
        local = jnp.where(owner == my_shard, slots - my_shard * shard_cap,
                          shard_cap)  # OOB row + mode=drop = masked
        delta = jnp.zeros_like(grid)
        if n_buckets:
            b = jnp.clip(jnp.ceil(jnp.log2(jnp.maximum(values, 1.0))),
                         0, n_buckets - 1).astype(jnp.int32)
            delta = delta.at[local, steps, b].add(1.0, mode="drop")
        else:
            delta = delta.at[local, steps].add(values, mode="drop")
        return grid + jax.lax.psum(delta, "data")

    grid_spec = P("series", None, None) if n_buckets else P("series", None)
    fn = _shard_map(step, mesh=mesh,
                    in_specs=(grid_spec, P("data"), P("data"), P("data")),
                    out_specs=grid_spec)
    return jax.jit(fn)
