"""Mesh construction and the sharded aggregation step.

Parallelism axes (the analog of the reference's strategies, SURVEY.md §2.6):

- `data`: span batches are split across devices — the ring-of-ingesters /
  shuffle-shard fan-out (`distributor.go:511-547`) becomes a sharded array
  dimension. Registry updates happen on local shards; the quorum-merge
  becomes a `psum` over this axis.
- `series`: metric series slots are sharded — the per-instance registry
  partitioning becomes a sharded state dimension. Each device owns
  max_active_series / series_shards slots; a slot's owner is slot//shard_cap,
  so updates need no all-to-all (mirroring how the reference routes series to
  exactly one generator instance via the partition ring).

The canonical step below (spanmetrics fused update under shard_map) is what
`__graft_entry__.dryrun_multichip` compiles across an N-device mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

from tempo_tpu.ops import sketches
from tempo_tpu.registry import metrics as rm


def make_mesh(n_devices: int | None = None, series_shards: int = 1) -> Mesh:
    """2D mesh ('data', 'series'). series_shards must divide device count."""
    devs = jax.devices()
    n = len(devs) if n_devices is None else n_devices
    devs = np.array(devs[:n])
    assert n % series_shards == 0, (n, series_shards)
    return Mesh(devs.reshape(n // series_shards, series_shards), ("data", "series"))


def make_multihost_mesh(series_shards: int = 1) -> Mesh:
    """Multi-host mesh: 'data' spans hosts (DCN), 'series' stays within a
    host's slice (ICI) — collectives on 'series' ride ICI, the data-psum
    crosses DCN once per step, mirroring how the reference keeps ingester
    traffic local and only ships merged series to the frontend.

    Falls back to the flat single-host mesh when only one process exists.
    """
    if jax.process_count() == 1:
        return make_mesh(series_shards=series_shards)
    from jax.experimental import mesh_utils

    per_host = jax.local_device_count()
    assert per_host % series_shards == 0, (per_host, series_shards)
    devs = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(per_host // series_shards, series_shards),
        dcn_mesh_shape=(jax.process_count(), 1))
    return Mesh(devs, ("data", "series"))


def shard_batch_arrays(mesh: Mesh, arrays: dict) -> dict:
    """Place host batch columns with leading dim sharded over 'data'."""
    sh = NamedSharding(mesh, P("data"))
    return {k: jax.device_put(v, sh) for k, v in arrays.items()}


def merge_sketch_states(state, axis_name: str = "data"):
    """Collective merge of sketch/registry pytrees inside shard_map/pjit:
    HLL registers merge with pmax, everything else (counts/sums) with psum."""

    def merge(path, leaf):
        if any(getattr(p, "name", "") == "registers" for p in path):
            return jax.lax.pmax(leaf, axis_name)
        return jax.lax.psum(leaf, axis_name)

    return jax.tree_util.tree_map_with_path(merge, state)


def sharded_spanmetrics_step(mesh: Mesh, edges: tuple, gamma: float,
                             min_value: float):
    """Build the jitted multi-device spanmetrics step over `mesh`.

    Layout: span columns sharded over 'data' (replicated over 'series');
    registry state arrays sharded over 'series' on their slot dim and
    replicated over 'data'. Each device updates only the slots it owns; a
    psum over 'data' yields the global state — the collective that replaces
    the reference's frontend combiner tree.

    Takes/returns raw arrays (static hyperparams via closure) so the
    shard_map in/out specs are flat.
    """

    def step(calls_v, h_buckets, h_sums, h_counts, size_v, dd_counts,
             dd_zeros, slots, dur_s, sizes, weights):
        shard_cap = calls_v.shape[0]  # local slot count
        my_shard = jax.lax.axis_index("series")
        owner = jnp.where(slots >= 0, slots // shard_cap, -1)
        local = jnp.where(owner == my_shard, slots - my_shard * shard_cap, -1)

        # Updates start from ZERO states so only the delta is psum'd over
        # 'data' (the base state is replicated across data shards; summing it
        # would multiply prior state by the data-shard count every step).
        z = jnp.zeros_like
        calls_d = rm.counter_update(rm.CounterState(z(calls_v)), local, weights)
        hist_d = rm.histogram_update(
            rm.HistogramState(z(h_buckets), z(h_sums), z(h_counts), edges),
            local, dur_s, weights)
        size_d = rm.counter_update(rm.CounterState(z(size_v)), local,
                                   sizes * weights)
        keep = local >= 0
        dd_d = sketches.dd_update(
            sketches.DDSketch(z(dd_counts), z(dd_zeros), gamma, min_value),
            jnp.where(keep, local, 0), dur_s, mask=keep, weights=weights)
        deltas = (calls_d.values, hist_d.bucket_counts, hist_d.sums,
                  hist_d.counts, size_d.values, dd_d.counts, dd_d.zeros)
        base = (calls_v, h_buckets, h_sums, h_counts, size_v, dd_counts, dd_zeros)
        return tuple(b + jax.lax.psum(d, "data") for b, d in zip(base, deltas))

    state_specs = (P("series"), P("series", None), P("series"), P("series"),
                   P("series"), P("series", None), P("series"))
    batch_specs = (P("data"),) * 4
    fn = _shard_map(step, mesh=mesh,
                    in_specs=state_specs + batch_specs,
                    out_specs=state_specs)
    return jax.jit(fn)


def sharded_query_range_step(mesh: Mesh, n_buckets: int = 0):
    """Multi-device TraceQL-metrics observation: the sequence-parallel scan.

    The reference shards a query's *time/span space* into jobs combined at
    the frontend (`metrics_query_range_sharder.go` + `combiner/`); here the
    span batch is the sharded sequence dimension and the combine is one
    psum. Layout: spans (slots/steps/values) sharded over 'data'; the
    [series, steps] (or [series, steps, buckets] when n_buckets>0 — the
    quantile histogram plane) grid sharded over 'series' on dim 0. Each
    device scatter-adds its span shard into the slots it owns; psum over
    'data' is the cross-shard combine.

    Returns jit(fn(grid, slots, steps, values) -> grid).
    """

    def step(grid, slots, steps, values):
        shard_cap = grid.shape[0]
        my_shard = jax.lax.axis_index("series")
        owner = jnp.where(slots >= 0, slots // shard_cap, -1)
        local = jnp.where(owner == my_shard, slots - my_shard * shard_cap,
                          shard_cap)  # OOB row + mode=drop = masked
        delta = jnp.zeros_like(grid)
        if n_buckets:
            b = jnp.clip(jnp.ceil(jnp.log2(jnp.maximum(values, 1.0))),
                         0, n_buckets - 1).astype(jnp.int32)
            delta = delta.at[local, steps, b].add(1.0, mode="drop")
        else:
            delta = delta.at[local, steps].add(values, mode="drop")
        return grid + jax.lax.psum(delta, "data")

    grid_spec = P("series", None, None) if n_buckets else P("series", None)
    fn = _shard_map(step, mesh=mesh,
                    in_specs=(grid_spec, P("data"), P("data"), P("data")),
                    out_specs=grid_spec)
    return jax.jit(fn)
