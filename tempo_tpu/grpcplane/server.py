"""gRPC server: generic method handlers bound to an App's modules.

Services registered (mirroring `pkg/tempopb/tempo.proto:9-44` and the OTLP
receiver factory `modules/distributor/receiver/shim.go:165-171`):

- ``opentelemetry.proto.collector.trace.v1.TraceService/Export`` — the real
  OTLP/gRPC protobuf, decoded by the native C++ scanner (fallback: the
  Python wire codec). Stock OTel SDKs exporting OTLP/gRPC land here.
- ``tempopb.Pusher/PushBytesV2`` — distributor→ingester push (varint-framed
  span groups, the ingest-bus record encoding).
- ``tempopb.MetricsGenerator/{PushSpans,QueryRange,GetMetrics}``.
- ``tempopb.Querier/{FindTraceByID,SearchRecent,SearchTags,SearchTagValues}``
  — the ingester-side query surface the querier fans out to.
- ``tempopb.StreamingQuerier/Search`` — server-streaming search with diff
  responses (`tempo.proto:30-38`, `combiner/search.go` diff combiner).
- ``tempopb.Frontend/Process`` — the worker-pull job stream: remote queriers
  dial the frontend and pull job batches (`v1/frontend.go:204-293`,
  `worker/frontend_processor.go:69-195`).

Tenant rides the ``x-scope-orgid`` metadata key, as in the reference's
dskit user injection.
"""

from __future__ import annotations

import json
import threading
from concurrent import futures

import grpc

FAKE_TENANT = "single-tenant"

OTLP_EXPORT = "/opentelemetry.proto.collector.trace.v1.TraceService/Export"


def _ident(b):
    return b


def _tenant(context, multitenancy: bool) -> str:
    md = dict(context.invocation_metadata() or ())
    t = md.get("x-scope-orgid", "")
    if not t:
        if multitenancy:
            context.abort(grpc.StatusCode.UNAUTHENTICATED, "no org id")
        return FAKE_TENANT
    return t


def _jload(b: bytes) -> dict:
    return json.loads(b or b"{}")


def _jdump(obj) -> bytes:
    return json.dumps(obj).encode()


class _Services:
    """All unary/stream handlers, bound to one App."""

    def __init__(self, app) -> None:
        self.app = app

    # -- OTLP TraceService --------------------------------------------------

    def otlp_export(self, request: bytes, context) -> bytes:
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        from tempo_tpu.distributor.distributor import (MalformedPayload,
                                                       RateLimited)

        try:
            self.app.distributor.push_otlp(tenant, request)
        except MalformedPayload as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"malformed otlp payload: {e}")
        except RateLimited as e:
            # the reference translates rate limits to ResourceExhausted with
            # RetryInfo so SDK exporters back off (shim.go RetryableError)
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        return b""   # empty ExportTraceServiceResponse = full success

    # -- jaeger api_v2 collector (gRPC reporter protocol) -------------------

    def jaeger_post_spans(self, request: bytes, context) -> bytes:
        """`jaeger.api_v2.CollectorService/PostSpans` — the gRPC half of
        the jaeger receiver (thrift-over-HTTP is in app/api.py); ref
        `modules/distributor/receiver/shim.go:165-171`."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        from tempo_tpu.distributor.distributor import RateLimited
        from tempo_tpu.model.jaeger import spans_from_jaeger_proto

        try:
            spans = spans_from_jaeger_proto(request)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        try:
            self.app.distributor.push_spans(tenant, spans)
        except RateLimited as e:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        return b""   # empty PostSpansResponse

    # -- opencensus agent trace service (legacy reporter protocol) ----------

    def opencensus_export(self, request_iterator, context):
        """`opencensus.proto.agent.trace.v1.TraceService/Export` (bidi
        stream): Node/Resource arrive on the first message and persist
        for the stream; spans on every message. Last of the reference
        shim's receiver protocols (`shim.go:165-171`)."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        from tempo_tpu.distributor.distributor import RateLimited
        from tempo_tpu.model.opencensus import spans_from_opencensus

        service = ""
        res_attrs: dict = {}
        for request in request_iterator:
            try:
                spans, service, res_attrs = spans_from_opencensus(
                    request, service, res_attrs)
            except ValueError as e:
                context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            if spans:
                try:
                    self.app.distributor.push_spans(tenant, spans)
                except RateLimited as e:
                    context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                                  str(e))
            yield b""   # empty ExportTraceServiceResponse per message

    # -- Pusher (ingester) --------------------------------------------------

    def push_bytes_v2(self, request: bytes, context) -> bytes:
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        from tempo_tpu.model import tempopb
        from tempo_tpu.rpc import decode_push_body

        errs = self.app.ingester.push(tenant, decode_push_body(request))
        return tempopb.enc_push_response(errs or ())

    def push_otlp_traces(self, request: bytes, context) -> bytes:
        """Raw OTLP wire-slice push from the columnar distributor path;
        sparse per-trace rejection map back."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        try:
            errs = self.app.ingester.push_otlp(tenant, request)
        except (ValueError, KeyError, TypeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"malformed otlp payload: {e}")
        return _jdump({"errors": errs})

    # -- MetricsGenerator ---------------------------------------------------

    def generator_push_spans(self, request: bytes, context) -> bytes:
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        from tempo_tpu.rpc import decode_push_body

        spans = [s for _tid, group in decode_push_body(request)
                 for s in group]
        self.app.generator.push_spans(tenant, spans)
        return b"{}"

    def generator_push_otlp(self, request: bytes, context) -> bytes:
        """Raw OTLP ResourceSpans payload — the wire shape of the
        reference's PushSpansRequest — staged by the vectorized scan."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        try:
            n = self.app.generator.push_otlp(tenant, request)
        except (ValueError, KeyError, TypeError) as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"malformed otlp payload: {e}")
        return _jdump({"spans": n})

    def generator_query_range(self, request: bytes, context) -> bytes:
        """JSON request (tiny), protobuf TimeSeries response (the heavy
        side; `tempo.proto` QueryRangeResponse)."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        from tempo_tpu.model import tempopb
        from tempo_tpu.traceql.engine_metrics import QueryRangeRequest

        d = _jload(request)
        req = QueryRangeRequest(query=d["query"], start_ns=d["start_ns"],
                                end_ns=d["end_ns"], step_ns=d["step_ns"])
        series = self.app.generator.query_range(
            tenant, req, clip_start_ns=d.get("clip_start_ns"))
        return tempopb.enc_query_range_response(series)

    def generator_get_metrics(self, request: bytes, context) -> bytes:
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        d = _jload(request)
        res = self.app.generator.get_metrics(
            tenant, d.get("query", "{ }"), d.get("group_by", []))
        return _jdump({"summaries": [s.to_json() for s in res.results()],
                       "estimated": res.estimated})

    # -- Querier (ingester-side query surface) ------------------------------

    def find_trace_by_id(self, request: bytes, context) -> bytes:
        """Protobuf both ways: TraceByIDRequest in, OTLP trace bytes out
        (`tempopb.Trace` is OTLP-shaped ResourceSpans)."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        from tempo_tpu.model import tempopb

        tid = tempopb.dec_trace_by_id_request(request)
        spans = self.app.ingester.find_trace_by_id(tenant, tid)
        return tempopb.enc_trace_by_id_response(spans)

    def search_recent(self, request: bytes, context) -> bytes:
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        from tempo_tpu.model import tempopb
        from tempo_tpu.obs import querystats

        d = tempopb.dec_search_request(request)
        # per-RPC stats scope, serialized into the response's metrics
        # submessage — the gRPC-trailer analog the remote querier merges
        # into its own request scope
        with querystats.scope() as st:
            res = self.app.ingester.search(
                tenant, d.get("q", "{ }"), int(d.get("limit", 20)),
                float(d.get("start", 0)), float(d.get("end", 0)))
        st.floor_inspected_traces(len(res))
        return tempopb.enc_search_response(res, inspected=len(res), stats=st)

    def search_tags(self, request: bytes, context) -> bytes:
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        return _jdump({"scopes": self.app.ingester.tag_names(tenant)})

    def search_tag_values(self, request: bytes, context) -> bytes:
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        d = _jload(request)
        return _jdump({"tagValues": self.app.ingester.tag_values(
            tenant, d["name"], int(d.get("limit", 1000)))})

    # -- StreamingQuerier ---------------------------------------------------

    def _stream_partials(self, context, run_fn, enc_diff, enc_final):
        """Shared server-streaming scaffold (`combiner/*.go` diff shape):
        `run_fn(emit)` executes the frontend call on a worker thread,
        calling `emit(batch)` for each diff the endpoint's filter kept;
        batches are encoded + yielded as they arrive, then the final
        result ends the stream (or the error aborts it)."""
        import queue as _q

        diffs: _q.Queue = _q.Queue()
        out: dict = {}

        def run() -> None:
            try:
                out["res"] = run_fn(diffs.put)
            except Exception as e:  # surfaced as the stream's final state
                out["err"] = e
            diffs.put(None)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        while True:
            batch = diffs.get()
            if batch is None:
                break
            yield enc_diff(batch)
        t.join()
        if "err" in out:
            from tempo_tpu.sched import QueryBackpressure
            if isinstance(out["err"], QueryBackpressure):
                # shed load is RETRYABLE, not a server bug: mirror the
                # HTTP 503 + Retry-After semantics (shim RetryableError)
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED,
                              str(out["err"]))
            context.abort(grpc.StatusCode.INTERNAL, str(out["err"]))
        yield enc_final(out.get("res"))

    def streaming_search(self, request: bytes, context):
        """Server-streaming search: partial diff responses while sub-queries
        complete, then the final message (`combiner/search.go` diffs)."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        d = _jload(request)
        from tempo_tpu.model import tempopb
        from tempo_tpu.obs import querystats

        sent: set[str] = set()
        stats_box: dict = {}

        def run_fn(emit):
            def on_partial(results) -> None:
                fresh = [md for md in results if md.trace_id not in sent]
                if fresh:
                    sent.update(md.trace_id for md in fresh)
                    emit(fresh)

            # scope opened on the stream's worker thread; the FINAL
            # message carries the merged stats (SearchMetrics trailer)
            with querystats.scope() as st:
                stats_box["st"] = st
                return self.app.frontend.search(
                    tenant, d.get("q", "{ }"), limit=int(d.get("limit", 20)),
                    start_s=float(d["start"]) if "start" in d else None,
                    end_s=float(d["end"]) if "end" in d else None,
                    on_partial=on_partial)

        def enc_final(res) -> bytes:
            st = stats_box.get("st")
            if st is not None:
                # legacy clients read only the scalar `inspected` (field 1
                # == inspected_traces): keep its old len(res) floor even
                # for fully cache-served queries
                st.floor_inspected_traces(len(res or []))
            return tempopb.enc_search_response(
                res or [], inspected=len(res or []), final=True, stats=st)

        yield from self._stream_partials(
            context, run_fn,
            lambda batch: tempopb.enc_search_response(batch, final=False),
            enc_final)

    def streaming_metrics_query_range(self, request: bytes, context):
        """Server-streaming TraceQL metrics: series-DIFF messages as
        sub-results (generator recent window, per-block backend jobs)
        fold in, then the complete final series set
        (`tempo.proto` StreamingQuerier/MetricsQueryRange; diff shape
        mirrors the search stream). Each message carries only series whose
        samples CHANGED since the last message — a high-cardinality
        `by()` no longer buffers the whole set in one response."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        d = _jload(request)
        import numpy as np

        from tempo_tpu.model import tempopb

        last: dict = {}

        def run_fn(emit):
            def on_partial(series) -> None:
                fresh = []
                for s in series:
                    sig = np.asarray(s.samples).tobytes()
                    if last.get(s.labels) != sig:
                        last[s.labels] = sig
                        fresh.append(s)
                if fresh:
                    emit(fresh)

            return self.app.frontend.query_range(
                tenant, d["query"], start_s=float(d["start"]),
                end_s=float(d["end"]), step_s=float(d.get("step", 60.0)),
                on_partial=on_partial)

        yield from self._stream_partials(
            context, run_fn, tempopb.enc_query_range_response,
            lambda res: tempopb.enc_query_range_response(res or []))

    def streaming_search_tags(self, request: bytes, context):
        """Server-streaming tag-name autocomplete: scope-diff messages as
        the ingester pass and each contributing backend block merge in,
        then the final scopes map (`StreamingQuerier/SearchTags`)."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        last: dict = {}

        def run_fn(emit):
            def on_partial(scopes: dict) -> None:
                fresh = {k: v for k, v in scopes.items()
                         if last.get(k) != v}
                if fresh:
                    last.update(fresh)
                    emit(fresh)

            return self.app.frontend.tag_names(tenant,
                                               on_partial=on_partial)

        yield from self._stream_partials(
            context, run_fn,
            lambda batch: _jdump({"scopes": batch, "final": False}),
            lambda res: _jdump({"scopes": res or {}, "final": True}))

    def streaming_search_tag_values(self, request: bytes, context):
        """Server-streaming tag-value autocomplete: value diffs as the
        ingester pass merges in, then the final list
        (`StreamingQuerier/SearchTagValues`)."""
        tenant = _tenant(context, self.app.cfg.multitenancy_enabled)
        d = _jload(request)
        sent: set = set()

        def run_fn(emit):
            def on_partial(values: list) -> None:
                fresh = [v for v in values
                         if (v.get("type"), v.get("value")) not in sent]
                if fresh:
                    sent.update((v.get("type"), v.get("value"))
                                for v in fresh)
                    emit(fresh)

            return self.app.frontend.tag_values(
                tenant, d["name"], int(d.get("limit", 1000)),
                on_partial=on_partial)

        yield from self._stream_partials(
            context, run_fn,
            lambda batch: _jdump({"tagValues": batch, "final": False}),
            lambda res: _jdump({"tagValues": res or [], "final": True}))

    # -- Frontend worker-pull dispatch --------------------------------------

    def frontend_process(self, request_iterator, context):
        """One connected querier worker: stream job batches out, fold result
        messages back into the pending jobs. The pull direction matches the
        reference (querier dials frontend), so queriers scale out with zero
        frontend-side discovery."""
        fe = self.app.frontend
        pending: dict[int, object] = {}
        plock = threading.Condition()
        next_id = [0]
        done = threading.Event()

        def read_results() -> None:
            try:
                for msg in request_iterator:
                    m = _jload(msg)
                    if m.get("type") == "hello":
                        continue
                    with plock:
                        wj = pending.pop(int(m["job_id"]), None)
                        plock.notify_all()
                    if wj is None:
                        continue
                    try:
                        if m["type"] == "result":
                            wj.result = fe.decode_job_result(
                                wj.spec, m.get("result"))
                            if m.get("stats"):
                                # the worker's serialized per-job stats —
                                # folded into the parent request when the
                                # issuer folds this job's result
                                from tempo_tpu.obs.querystats import \
                                    QueryStats
                                wj.stats.merge(
                                    QueryStats.from_json(m["stats"]))
                        else:
                            wj.error = RuntimeError(
                                m.get("error", "worker error"))
                    except Exception as e:
                        # a malformed result must still complete the job —
                        # the issuer has no other wake-up path once claimed
                        wj.error = e
                    finally:
                        wj.event.set()
            except Exception:
                pass
            finally:
                done.set()
                with plock:
                    plock.notify_all()

        reader = threading.Thread(target=read_results, daemon=True)
        reader.start()
        fe.remote_worker_attached()
        try:
            while context.is_active() and not done.is_set():
                batch = fe.queue.dequeue_batch(fe.cfg.max_batch_size,
                                               timeout_s=0.2)
                jobs = []
                local_jobs = []
                with plock:
                    for wj in batch:
                        if wj.spec is None:     # not remotable: runs local,
                            local_jobs.append(wj)   # AFTER the yield and
                            continue            # outside plock — neither
                        if not wj.try_claim():  # the worker nor the result
                            continue            # reader should wait on it
                        jid = next_id[0]
                        next_id[0] += 1
                        pending[jid] = wj
                        jobs.append({"job_id": jid, "spec": wj.spec})
                if jobs:
                    yield _jdump({"type": "jobs", "jobs": jobs})
                for wj in local_jobs:
                    wj.run()
                if jobs:
                    # one batch in flight per worker stream: wait for this
                    # batch's results before pulling more so concurrent
                    # workers share the queue (the reference's
                    # request-response Process loop has the same effect)
                    with plock:
                        while pending and not done.is_set():
                            plock.wait(timeout=0.2)
                            if not context.is_active():
                                break
        finally:
            fe.remote_worker_detached()
            # worker went away: fail outstanding jobs fast so the query
            # retries/errors instead of hanging (frontend cancels on
            # disconnect in the reference too)
            with plock:
                for wj in pending.values():
                    wj.error = RuntimeError("querier worker disconnected")
                    wj.event.set()
                pending.clear()


def build_grpc_server(app, address: str = "127.0.0.1:0",
                      max_workers: int = 16) -> tuple[grpc.Server, int]:
    """Create + start a grpc server for the App's enabled modules.

    Returns (server, bound_port). Only services whose backing module exists
    on this target are registered — a `-target=ingester` process serves
    Pusher + Querier, a frontend serves StreamingQuerier + Frontend, etc.
    Every handler is timed into the gRPC request-duration histogram
    (method + status labels), the RPC-plane twin of the HTTP histogram.
    """
    import time as _time

    svc = _Services(app)
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))

    hist = getattr(app, "grpc_request_duration", None)

    def unary(fn, method: str):
        def handler(request, context):
            t0 = _time.perf_counter()
            status = "OK"
            try:
                return fn(request, context)
            except BaseException:          # context.abort raises
                status = "error"
                raise
            finally:
                if hist is not None:
                    hist.observe(_time.perf_counter() - t0,
                                 (method, status))
        return grpc.unary_unary_rpc_method_handler(
            handler, request_deserializer=_ident,
            response_serializer=_ident)

    def _timed_stream(fn, method: str):
        def handler(request, context):
            t0 = _time.perf_counter()
            status = "OK"
            try:
                yield from fn(request, context)
            except BaseException:
                status = "error"
                raise
            finally:
                if hist is not None:
                    hist.observe(_time.perf_counter() - t0,
                                 (method, status))
        return handler

    def sstream(fn, method: str):
        return grpc.unary_stream_rpc_method_handler(
            _timed_stream(fn, method), request_deserializer=_ident,
            response_serializer=_ident)

    def bidi(fn, method: str):
        return grpc.stream_stream_rpc_method_handler(
            _timed_stream(fn, method), request_deserializer=_ident,
            response_serializer=_ident)

    if app.distributor is not None:
        server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
            "opentelemetry.proto.collector.trace.v1.TraceService",
            {"Export": unary(svc.otlp_export, "TraceService/Export")}),))
        server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
            "jaeger.api_v2.CollectorService",
            {"PostSpans": unary(svc.jaeger_post_spans,
                                "CollectorService/PostSpans")}),))
        server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
            "opencensus.proto.agent.trace.v1.TraceService",
            {"Export": bidi(svc.opencensus_export,
                            "OpenCensus.TraceService/Export")}),))
    if app.ingester is not None:
        server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
            "tempopb.Pusher",
            {"PushBytesV2": unary(svc.push_bytes_v2,
                                  "Pusher/PushBytesV2"),
             "PushOTLP": unary(svc.push_otlp_traces,
                               "Pusher/PushOTLP")}),))
        server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
            "tempopb.Querier",
            {"FindTraceByID": unary(svc.find_trace_by_id,
                                    "Querier/FindTraceByID"),
             "SearchRecent": unary(svc.search_recent,
                                   "Querier/SearchRecent"),
             "SearchTags": unary(svc.search_tags, "Querier/SearchTags"),
             "SearchTagValues": unary(svc.search_tag_values,
                                      "Querier/SearchTagValues")}),))
    if app.generator is not None:
        server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
            "tempopb.MetricsGenerator",
            {"PushSpans": unary(svc.generator_push_spans,
                                "MetricsGenerator/PushSpans"),
             "PushOTLP": unary(svc.generator_push_otlp,
                               "MetricsGenerator/PushOTLP"),
             "QueryRange": unary(svc.generator_query_range,
                                 "MetricsGenerator/QueryRange"),
             "GetMetrics": unary(svc.generator_get_metrics,
                                 "MetricsGenerator/GetMetrics")}),))
    if app.frontend is not None:
        server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
            "tempopb.StreamingQuerier",
            {"Search": sstream(svc.streaming_search,
                               "StreamingQuerier/Search"),
             "MetricsQueryRange": sstream(
                 svc.streaming_metrics_query_range,
                 "StreamingQuerier/MetricsQueryRange"),
             "SearchTags": sstream(svc.streaming_search_tags,
                                   "StreamingQuerier/SearchTags"),
             "SearchTagValues": sstream(
                 svc.streaming_search_tag_values,
                 "StreamingQuerier/SearchTagValues")}),))
        server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(
            "tempopb.Frontend",
            {"Process": bidi(svc.frontend_process, "Frontend/Process")}),))
    port = server.add_insecure_port(address)
    server.start()
    return server, port
