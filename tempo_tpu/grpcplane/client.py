"""gRPC clients: the remote halves of the service seams.

`GrpcIngesterClient` / `GrpcGeneratorClient` satisfy the same client
protocols as the in-process service objects and the HTTP clients in
`tempo_tpu.rpc`, so a peer address with a ``grpc://`` scheme swaps the
transport without touching the services. `FrontendWorker` is the querier's
side of the worker-pull plane (`modules/querier/worker/frontend_processor.go:69-195`):
it dials the frontend, pulls job batches off the bidi stream, executes them
on the local querier, and streams results back.
"""

from __future__ import annotations

import json
import queue as _q
import threading
from typing import Sequence

import grpc

from tempo_tpu.ingest.encoding import encode_push


def _jdump(obj) -> bytes:
    return json.dumps(obj).encode()


def _jload(b: bytes) -> dict:
    return json.loads(b or b"{}")


def _one_record(traces) -> bytes:
    return b"".join(encode_push(traces, max_record_bytes=1 << 62))


class _BaseGrpcClient:
    def __init__(self, target: str, timeout_s: float = 30.0) -> None:
        if target.startswith("grpc://"):
            target = target[len("grpc://"):]
        self.channel = grpc.insecure_channel(target)
        self.timeout = timeout_s

    def _call(self, method: str, body: bytes, tenant: str) -> bytes:
        fn = self.channel.unary_unary(method)
        return fn(body, timeout=self.timeout,
                  metadata=(("x-scope-orgid", tenant),))

    def close(self) -> None:
        self.channel.close()


class GrpcIngesterClient(_BaseGrpcClient):
    """IngesterClient + IngesterQueryClient over gRPC (`Pusher.PushBytesV2`
    + the `tempopb.Querier` service)."""

    def push(self, tenant: str,
             traces: Sequence[tuple[bytes, list[dict]]]) -> list[str | None]:
        from tempo_tpu.model import tempopb

        body = self._call("/tempopb.Pusher/PushBytesV2",
                          _one_record(traces), tenant)
        return tempopb.dec_push_response(body, len(traces))

    def push_otlp(self, tenant: str, payload: bytes) -> dict[str, str]:
        import json as _json

        body = self._call("/tempopb.Pusher/PushOTLP", payload, tenant)
        return _json.loads(body or b"{}").get("errors", {})

    def find_trace_by_id(self, tenant: str, trace_id: bytes):
        from tempo_tpu.model import tempopb

        body = self._call("/tempopb.Querier/FindTraceByID",
                          tempopb.enc_trace_by_id_request(trace_id), tenant)
        return tempopb.dec_trace_by_id_response(body)

    def search(self, tenant: str, query: str, limit: int = 20,
               start_s: float = 0, end_s: float = 0):
        from tempo_tpu.model import tempopb
        from tempo_tpu.obs import querystats

        body = self._call(
            "/tempopb.Querier/SearchRecent",
            tempopb.enc_search_request(query, limit, start_s, end_s), tenant)
        mds, _final, _inspected, stats = tempopb.dec_search_response(body)
        # the remote ingester's stats trailer folds into this process's
        # ambient request scope (the gRPC-trailer merge direction)
        querystats.absorb(stats)
        return mds

    def tag_names(self, tenant: str) -> dict[str, list[str]]:
        res = _jload(self._call("/tempopb.Querier/SearchTags", b"{}", tenant))
        return res.get("scopes", {})

    def tag_values(self, tenant: str, name: str, limit: int = 1000):
        res = _jload(self._call("/tempopb.Querier/SearchTagValues",
                                _jdump({"name": name, "limit": limit}),
                                tenant))
        return res.get("tagValues", [])


class GrpcGeneratorClient(_BaseGrpcClient):
    """GeneratorClient over gRPC (`MetricsGenerator` service)."""

    def push_spans(self, tenant: str, spans: Sequence[dict]) -> None:
        groups: dict[bytes, list[dict]] = {}
        for s in spans:
            groups.setdefault(s.get("trace_id", b""), []).append(s)
        self._call("/tempopb.MetricsGenerator/PushSpans",
                   _one_record(list(groups.items())), tenant)

    def push_otlp(self, tenant: str, data: bytes) -> int:
        res = _jload(self._call("/tempopb.MetricsGenerator/PushOTLP",
                                data, tenant))
        return int(res.get("spans", 0))

    def query_range(self, tenant: str, req, clip_start_ns: int | None = None):
        from tempo_tpu.model import tempopb

        body = self._call(
            "/tempopb.MetricsGenerator/QueryRange",
            _jdump({"query": req.query, "start_ns": req.start_ns,
                    "end_ns": req.end_ns, "step_ns": req.step_ns,
                    "clip_start_ns": clip_start_ns}), tenant)
        return tempopb.dec_query_range_response(body)

    def get_metrics(self, tenant: str, query: str, group_by) -> dict:
        return _jload(self._call(
            "/tempopb.MetricsGenerator/GetMetrics",
            _jdump({"query": query, "group_by": list(group_by)}), tenant))


def streaming_search(target: str, tenant: str, query: str, *,
                     limit: int = 20, start_s: float | None = None,
                     end_s: float | None = None, timeout_s: float = 60.0):
    """Client for `tempopb.StreamingQuerier/Search`: yields (traces, final)
    tuples as partial diffs stream in."""
    if target.startswith("grpc://"):
        target = target[len("grpc://"):]
    with grpc.insecure_channel(target) as ch:
        fn = ch.unary_stream("/tempopb.StreamingQuerier/Search")
        body: dict = {"q": query, "limit": limit}
        if start_s is not None:
            body["start"] = start_s
        if end_s is not None:
            body["end"] = end_s
        from tempo_tpu.model import tempopb

        for msg in fn(_jdump(body), timeout=timeout_s,
                      metadata=(("x-scope-orgid", tenant),)):
            mds, final, _inspected, _stats = tempopb.dec_search_response(msg)
            yield mds, final


def streaming_metrics_query_range(target: str, tenant: str, query: str, *,
                                  start_s: float, end_s: float,
                                  step_s: float = 60.0,
                                  timeout_s: float = 60.0):
    """Client for `tempopb.StreamingQuerier/MetricsQueryRange`: yields one
    series list per message — diff batches while sub-results fold in,
    then the complete final set (last message)."""
    if target.startswith("grpc://"):
        target = target[len("grpc://"):]
    from tempo_tpu.model import tempopb

    with grpc.insecure_channel(target) as ch:
        fn = ch.unary_stream("/tempopb.StreamingQuerier/MetricsQueryRange")
        body = {"query": query, "start": start_s, "end": end_s,
                "step": step_s}
        for msg in fn(_jdump(body), timeout=timeout_s,
                      metadata=(("x-scope-orgid", tenant),)):
            yield tempopb.dec_query_range_response(msg)


def streaming_search_tags(target: str, tenant: str, *,
                          timeout_s: float = 60.0):
    """Client for `tempopb.StreamingQuerier/SearchTags`: yields
    (scopes_dict, final) as scope diffs stream in."""
    if target.startswith("grpc://"):
        target = target[len("grpc://"):]
    with grpc.insecure_channel(target) as ch:
        fn = ch.unary_stream("/tempopb.StreamingQuerier/SearchTags")
        for msg in fn(b"{}", timeout=timeout_s,
                      metadata=(("x-scope-orgid", tenant),)):
            d = _jload(msg)
            yield d.get("scopes", {}), bool(d.get("final"))


class FrontendWorker:
    """Querier-side worker: dial the frontend, pull jobs, execute, reply.

    One bidi stream per worker thread (`worker.go` runs `parallelism`
    processors per frontend address). Job specs are executed through the
    local Querier — the worker process shares the object-store backend, so
    a block job only needs the meta + row-group slice.
    """

    def __init__(self, frontend_addr: str, querier, *,
                 worker_id: str = "worker", parallelism: int = 1) -> None:
        if frontend_addr.startswith("grpc://"):
            frontend_addr = frontend_addr[len("grpc://"):]
        self.addr = frontend_addr
        self.querier = querier
        self.worker_id = worker_id
        self.parallelism = parallelism
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self.jobs_executed = 0

    def start(self) -> None:
        for i in range(self.parallelism):
            t = threading.Thread(target=self._run, args=(i,), daemon=True)
            t.start()
            self._threads.append(t)

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=3)

    # -- internals ----------------------------------------------------------

    def _run(self, idx: int) -> None:
        import time

        while not self._stop.is_set():
            try:
                self._process_stream(idx)
            except grpc.RpcError:
                # frontend down/restarting: back off and redial
                # (`frontend_processor.go` retry loop)
                time.sleep(0.3)
            except Exception:
                time.sleep(0.3)

    def _process_stream(self, idx: int) -> None:
        outbox: _q.Queue = _q.Queue()
        outbox.put(_jdump({"type": "hello",
                           "worker_id": f"{self.worker_id}-{idx}"}))

        def requests():
            while not self._stop.is_set():
                try:
                    yield outbox.get(timeout=0.2)
                except _q.Empty:
                    continue

        with grpc.insecure_channel(self.addr) as ch:
            fn = ch.stream_stream("/tempopb.Frontend/Process")
            for msg in fn(requests()):
                if self._stop.is_set():
                    return
                m = _jload(msg)
                for job in m.get("jobs", []):
                    outbox.put(self._execute(job))

    def _execute(self, job: dict) -> bytes:
        from tempo_tpu.obs import querystats

        jid = job["job_id"]
        try:
            # per-job stats scope: the worker-side half of the stats
            # trailer — serialized into the result message so the
            # frontend can merge shard stats into the parent request
            with querystats.scope() as st:
                result = execute_job_spec(self.querier, job["spec"])
            self.jobs_executed += 1
            return _jdump({"type": "result", "job_id": jid, "result": result,
                           "stats": st.to_json()})
        except Exception as e:
            return _jdump({"type": "error", "job_id": jid, "error": str(e)})


def execute_job_spec(querier, spec: dict):
    """Run one frontend job spec on a local querier; returns JSON-safe
    result (the worker side of `querier.SearchBlock` / query-range jobs)."""
    from tempo_tpu.backend.meta import BlockMeta

    kind = spec["kind"]
    meta = BlockMeta.from_json(spec["meta"]) if spec.get("meta") else None
    rgs = tuple(spec.get("row_groups") or ()) or None
    if kind == "search_block":
        res = querier.search_block(
            spec["tenant"], spec["query"], meta, rgs,
            limit=int(spec.get("limit", 20)),
            start_s=spec.get("start_s"), end_s=spec.get("end_s"))
        return [md.to_json() for md in res]
    if kind == "query_range_block":
        from tempo_tpu.traceql.engine_metrics import QueryRangeRequest

        req = QueryRangeRequest(
            query=spec["query"], start_ns=spec["start_ns"],
            end_ns=spec["end_ns"], step_ns=spec["step_ns"],
            moments=bool(spec.get("moments", False)))
        series = querier.query_range_block(
            spec["tenant"], req, meta, rgs,
            clip_start_ns=spec.get("clip_start_ns"),
            clip_end_ns=spec.get("clip_end_ns"))
        # same shape _encode_series/_decode_series (frontend.py) use —
        # exemplars included, or the remote path degrades results AND the
        # frontend's fold-time cache write persists the degradation
        return [{"labels": [list(kv) for kv in s.labels],
                 "samples": list(map(float, s.samples)),
                 "exemplars": list(getattr(s, "exemplars", []))}
                for s in series]
    raise ValueError(f"unknown job kind {kind!r}")
