"""gRPC plane: OTLP/gRPC ingest + inter-service RPC + worker-pull dispatch.

The analog of the reference's entire gRPC surface (`pkg/tempopb/tempo.proto:9-44`
services Pusher / MetricsGenerator / Querier / StreamingQuerier carried by the
dskit server, plus the httpgrpc frontend↔querier tunnel
`modules/frontend/v1/frontend.go:204-293`).

Design: grpc generic method handlers over explicit wire payloads — the OTLP
receiver speaks the real `opentelemetry.proto.collector.trace.v1.TraceService`
protobuf (so stock OTel SDKs can export to it), while inter-service methods
carry this framework's own encodings (varint-framed span groups on the hot
push path, JSON on control paths). No generated stubs: the protobuf layer
that is 22k generated lines in the reference collapses into the wire codec
in `model/proto_wire.py`.
"""

from tempo_tpu.grpcplane.server import build_grpc_server
from tempo_tpu.grpcplane.client import (
    GrpcGeneratorClient,
    GrpcIngesterClient,
    FrontendWorker,
)

__all__ = [
    "build_grpc_server",
    "GrpcIngesterClient",
    "GrpcGeneratorClient",
    "FrontendWorker",
]
