"""tempo_tpu.obs — the process self-telemetry substrate.

- `registry`: Counter/Gauge/Histogram families, callback collectors,
  HELP/TYPE text exposition, conformance parser.
- `jaxruntime`: process-wide JAX/TPU runtime metrics (jit compiles,
  device-put bytes, kernel wall time) in the shared `RUNTIME` registry.
- `querystats`: contextvar-scoped per-request read-path statistics
  (the dskit `stats` / SearchMetrics axis).
- `qlog`: structured JSON "query complete" logging with tail-based
  slow-query capture.
- `drift`: alert/dashboard ↔ registry drift gate.
"""

from tempo_tpu.obs.registry import (
    DEFAULT_DURATION_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    escape_label,
    exponential_buckets,
    parse_exposition,
)
from tempo_tpu.obs.querystats import QueryStats

__all__ = ["Registry", "Counter", "Gauge", "Histogram", "escape_label",
           "exponential_buckets", "parse_exposition",
           "DEFAULT_DURATION_BUCKETS", "QueryStats"]
