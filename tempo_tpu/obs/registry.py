"""Process-wide Prometheus-style instrumentation registry.

The single source of truth behind `/metrics`: modules register their own
metric families (Counter / Gauge / Histogram, plus callback-backed
families that snapshot existing module state at scrape time) and the
exposition renderer emits the whole registry as Prometheus text format
0.0.4 — `# HELP`/`# TYPE` metadata, centralized label escaping, sorted
deterministic output, no duplicate series.

Design notes (mirroring prometheus/client_golang semantics sized to this
build):

- Families are get-or-create by name: re-registering the same name with
  the same kind and label names returns the existing family (modules and
  request handlers may race to the same instrument); a kind or label
  mismatch raises.
- Histograms use exponential bucket boundaries by default (the
  "Moment-Based Quantile Sketches" observation that log-spaced buckets
  are the right compact primitive for high-rate latency telemetry) and
  can carry an exemplar-style trace id per series, the bridge between
  self-metrics and `SelfTracer` (slow requests are findable by trace).
- Callback families (`counter_func` / `gauge_func`) read module state at
  render time so hot paths that already keep plain dict counters pay
  ZERO extra cost per event — only new latency histograms touch the hot
  path, and those are one lock + one bisect per observation.
- `Registry(enabled=False)` hands out no-op instruments: the bench
  harness measures instrumentation overhead as (enabled - disabled).
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from typing import Callable, Iterable, Sequence

# ---------------------------------------------------------------------------
# label / value formatting (centralized: call sites never hand-escape)
# ---------------------------------------------------------------------------


def escape_label(v: str) -> str:
    """Prometheus exposition label escaping: backslash, quote, newline.
    Attacker-controlled values (tenant header, span attrs) must never be
    able to forge or corrupt exposition lines."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_le(edge: float) -> str:
    return format(edge, ".12g")


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{escape_label(v)}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


def exponential_buckets(start: float, factor: float, count: int) -> tuple:
    """`count` upper bounds starting at `start`, each `factor` apart."""
    return tuple(start * factor ** i for i in range(count))


# 1ms .. ~65s in powers of two — wide enough for request latencies and
# compaction cycles alike while staying 17 buckets per series
DEFAULT_DURATION_BUCKETS = exponential_buckets(0.001, 2.0, 17)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------


class _Family:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: tuple) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()

    def _check_labels(self, labels: tuple) -> tuple:
        labels = tuple(str(v) for v in labels)
        if len(labels) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: got {len(labels)} label values for "
                f"{len(self.labelnames)} label names {self.labelnames}")
        return labels

    def metric_names(self) -> set[str]:
        return {self.name}

    def render(self, out: list[str]) -> None:
        raise NotImplementedError


class Counter(_Family):
    kind = "counter"

    def __init__(self, name: str, help: str, labelnames: tuple) -> None:
        super().__init__(name, help, labelnames)
        self._series: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, labels: tuple = ()) -> None:
        labels = self._check_labels(labels)
        with self._lock:
            self._series[labels] = self._series.get(labels, 0.0) + amount

    def value(self, labels: tuple = ()) -> float:
        with self._lock:
            return self._series.get(tuple(str(v) for v in labels), 0.0)

    def render(self, out: list[str]) -> None:
        with self._lock:
            items = sorted(self._series.items())
        if not self.labelnames and not items:
            items = [((), 0.0)]          # unlabeled counters expose 0
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(self.labelnames, labels)} "
                       f"{_fmt_value(v)}")


class Gauge(_Family):
    kind = "gauge"

    def __init__(self, name: str, help: str, labelnames: tuple) -> None:
        super().__init__(name, help, labelnames)
        self._series: dict[tuple, float] = {}

    def set(self, value: float, labels: tuple = ()) -> None:
        labels = self._check_labels(labels)
        with self._lock:
            self._series[labels] = float(value)

    def add(self, amount: float, labels: tuple = ()) -> None:
        labels = self._check_labels(labels)
        with self._lock:
            self._series[labels] = self._series.get(labels, 0.0) + amount

    def value(self, labels: tuple = ()) -> float:
        with self._lock:
            return self._series.get(tuple(str(v) for v in labels), 0.0)

    def render(self, out: list[str]) -> None:
        with self._lock:
            items = sorted(self._series.items())
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(self.labelnames, labels)} "
                       f"{_fmt_value(v)}")


class Histogram(_Family):
    """Cumulative histogram with exponential (configurable) buckets.

    Per-series state is (bucket counts, sum, count) plus the most recent
    exemplar — a `(trace_id, value, ts)` triple attached by observations
    that carried a trace id (the SelfTracer bridge: requests over the SLO
    threshold stamp their trace so a p99 spike is one click from a
    concrete slow trace). Exemplars ride the snapshot API, not the 0.0.4
    text format (which predates them)."""

    kind = "histogram"

    def __init__(self, name: str, help: str, labelnames: tuple,
                 buckets: Sequence[float] | None = None) -> None:
        super().__init__(name, help, labelnames)
        edges = tuple(sorted(buckets or DEFAULT_DURATION_BUCKETS))
        if not edges:
            raise ValueError(f"{name}: histogram needs >= 1 bucket")
        self.edges = edges
        # series -> [per-bucket counts (len edges+1, last = >last edge),
        #            sum, count]
        self._series: dict[tuple, list] = {}
        self._exemplars: dict[tuple, tuple] = {}

    def observe(self, value: float, labels: tuple = (),
                trace_id: str | None = None) -> None:
        labels = self._check_labels(labels)
        value = float(value)
        i = bisect.bisect_left(self.edges, value)
        with self._lock:
            s = self._series.get(labels)
            if s is None:
                s = self._series[labels] = [[0] * (len(self.edges) + 1),
                                            0.0, 0]
            s[0][i] += 1
            s[1] += value
            s[2] += 1
            if trace_id:
                self._exemplars[labels] = (trace_id, value, time.time())

    def snapshot(self, labels: tuple = ()) -> dict | None:
        """(buckets, sum, count, exemplar) for one series, or None."""
        labels = tuple(str(v) for v in labels)
        with self._lock:
            s = self._series.get(labels)
            if s is None:
                return None
            return {"buckets": list(s[0]), "sum": s[1], "count": s[2],
                    "exemplar": self._exemplars.get(labels)}

    def exemplar(self, labels: tuple = ()) -> tuple | None:
        with self._lock:
            return self._exemplars.get(tuple(str(v) for v in labels))

    def metric_names(self) -> set[str]:
        return {self.name, f"{self.name}_bucket", f"{self.name}_sum",
                f"{self.name}_count"}

    def render(self, out: list[str]) -> None:
        with self._lock:
            items = sorted((k, (list(v[0]), v[1], v[2]))
                           for k, v in self._series.items())
        lnames = self.labelnames + ("le",)
        for labels, (counts, total, n) in items:
            cum = 0
            for edge, c in zip(self.edges, counts):
                cum += c
                out.append(
                    f"{self.name}_bucket"
                    f"{_fmt_labels(lnames, labels + (_fmt_le(edge),))} {cum}")
            out.append(f"{self.name}_bucket"
                       f"{_fmt_labels(lnames, labels + ('+Inf',))} {n}")
            base = _fmt_labels(self.labelnames, labels)
            out.append(f"{self.name}_sum{base} {_fmt_value(total)}")
            out.append(f"{self.name}_count{base} {n}")


class _FuncFamily(_Family):
    """Family whose series are produced by a callback at render time:
    `fn() -> iterable[(label_values_tuple, value)]`. The bridge that lets
    modules keep their existing lock-free dict counters and still own a
    first-class registered family (name, HELP, TYPE) — the render pays
    the snapshot, the hot path pays nothing."""

    def __init__(self, name: str, help: str, labelnames: tuple,
                 fn: Callable[[], Iterable], kind: str) -> None:
        super().__init__(name, help, labelnames)
        self.kind = kind
        self.fn = fn

    def render(self, out: list[str]) -> None:
        try:
            items = sorted((tuple(str(v) for v in labels), value)
                           for labels, value in self.fn())
        except Exception:
            return    # a failing collector must never break /metrics
        if not self.labelnames and not items and self.kind == "counter":
            items = [((), 0.0)]
        for labels, v in items:
            if len(labels) != len(self.labelnames):
                continue
            out.append(f"{self.name}{_fmt_labels(self.labelnames, labels)} "
                       f"{_fmt_value(v)}")


class _Noop:
    """Disabled-registry instrument: every method is a cheap no-op."""

    def inc(self, amount: float = 1.0, labels: tuple = ()) -> None: ...
    def set(self, value: float, labels: tuple = ()) -> None: ...
    def add(self, amount: float, labels: tuple = ()) -> None: ...
    def observe(self, value: float, labels: tuple = (),
                trace_id: str | None = None) -> None: ...
    def value(self, labels: tuple = ()) -> float:
        return 0.0
    def snapshot(self, labels: tuple = ()):
        return None
    def exemplar(self, labels: tuple = ()):
        return None


_NOOP = _Noop()


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class Registry:
    def __init__(self, enabled: bool = True) -> None:
        self.enabled = enabled
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    # -- registration ------------------------------------------------------

    def _get_or_create(self, cls, name: str, help: str, labels: tuple,
                       **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != cls.kind or fam.labelnames != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, wanted "
                        f"{cls.kind}{tuple(labels)}")
                buckets = kw.get("buckets")
                if buckets is not None:
                    edges = tuple(sorted(buckets))
                    if edges != fam.edges:
                        raise ValueError(
                            f"metric {name!r} already registered with "
                            f"buckets {fam.edges}, wanted {edges}")
                return fam
            fam = cls(name, help, tuple(labels), **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labels: tuple = ()) -> Counter:
        if not self.enabled:
            return _NOOP
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: tuple = ()) -> Gauge:
        if not self.enabled:
            return _NOOP
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", labels: tuple = (),
                  buckets: Sequence[float] | None = None) -> Histogram:
        if not self.enabled:
            return _NOOP
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def counter_func(self, name: str, fn: Callable[[], Iterable],
                     help: str = "", labels: tuple = ()) -> None:
        if not self.enabled:
            return
        with self._lock:
            if name in self._families:
                raise ValueError(f"metric {name!r} already registered")
            self._families[name] = _FuncFamily(name, help, tuple(labels),
                                               fn, "counter")

    def gauge_func(self, name: str, fn: Callable[[], Iterable],
                   help: str = "", labels: tuple = ()) -> None:
        if not self.enabled:
            return
        with self._lock:
            if name in self._families:
                raise ValueError(f"metric {name!r} already registered")
            self._families[name] = _FuncFamily(name, help, tuple(labels),
                                               fn, "gauge")

    # -- introspection / exposition ----------------------------------------

    def get(self, name: str) -> _Family | None:
        with self._lock:
            return self._families.get(name)

    def metric_names(self) -> set[str]:
        """Every exposable sample name, including a histogram's derived
        `_bucket`/`_sum`/`_count` names — the drift gate's ground truth."""
        with self._lock:
            fams = list(self._families.values())
        out: set[str] = set()
        for f in fams:
            out |= f.metric_names()
        return out

    def render(self, extra: "Sequence[Registry]" = ()) -> str:
        """Full text-format exposition of this registry plus any `extra`
        registries (e.g. the process-wide JAX runtime registry). Name
        collisions resolve in favor of the first registry seen."""
        fams: dict[str, _Family] = {}
        for reg in (self, *extra):
            with reg._lock:
                for name, fam in reg._families.items():
                    fams.setdefault(name, fam)
        out: list[str] = []
        for name in sorted(fams):
            fam = fams[name]
            if fam.help:
                out.append(f"# HELP {name} {_escape_help(fam.help)}")
            out.append(f"# TYPE {name} {fam.kind}")
            fam.render(out)
        return "\n".join(out) + ("\n" if out else "")


# ---------------------------------------------------------------------------
# text-format conformance validation (the round-trip parser)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(\{(.*)\})?"                           # optional label set
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)|[+-]Inf|NaN)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> dict:
    """Parse Prometheus text format; raises ValueError on any conformance
    violation (malformed line, bad escaping, duplicate series, sample
    without a TYPE, non-cumulative histogram buckets). Returns
    {family -> {"type", "help", "samples": {(name, labeltuple): value}}}."""
    families: dict[str, dict] = {}
    seen: set[tuple] = set()
    by_base: dict[str, str] = {}     # sample name -> declaring family

    def family_of(sample_name: str) -> str | None:
        if sample_name in by_base:
            return by_base[sample_name]
        for suffix in ("_bucket", "_sum", "_count"):
            if sample_name.endswith(suffix):
                base = sample_name[: -len(suffix)]
                if families.get(base, {}).get("type") == "histogram":
                    return base
        return None

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP "):].split(" ", 1)
            fam = families.setdefault(parts[0], {"type": None, "help": None,
                                                 "samples": {}})
            fam["help"] = parts[1] if len(parts) > 1 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split(" ")
            if len(parts) != 2 or parts[1] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE line {line!r}")
            fam = families.setdefault(parts[0], {"type": None, "help": None,
                                                 "samples": {}})
            if fam["type"] is not None:
                raise ValueError(f"line {lineno}: duplicate TYPE {parts[0]}")
            fam["type"] = parts[1]
            by_base[parts[0]] = parts[0]
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name, _, labelblob, value = m.groups()
        labels: tuple = ()
        if labelblob:
            consumed = _LABEL_RE.sub("", labelblob).strip(", ")
            if consumed:
                raise ValueError(
                    f"line {lineno}: malformed labels {labelblob!r}")
            labels = tuple(sorted(_LABEL_RE.findall(labelblob)))
        key = (name, labels)
        if key in seen:
            raise ValueError(f"line {lineno}: duplicate series {key}")
        seen.add(key)
        fam_name = family_of(name)
        if fam_name is None:
            raise ValueError(f"line {lineno}: sample {name!r} has no TYPE")
        families[fam_name]["samples"][key] = float(value)

    # histogram invariants: buckets cumulative, +Inf == _count
    for fname, fam in families.items():
        if fam["type"] != "histogram":
            continue
        series: dict[tuple, list] = {}
        for (name, labels), v in fam["samples"].items():
            if name == f"{fname}_bucket":
                rest = tuple(kv for kv in labels if kv[0] != "le")
                le = next(kv[1] for kv in labels if kv[0] == "le")
                series.setdefault(rest, []).append((le, v))
        for rest, buckets in series.items():
            def _le_key(item):
                le = item[0]
                return float("inf") if le == "+Inf" else float(le)
            ordered = sorted(buckets, key=_le_key)
            vals = [v for _le, v in ordered]
            if vals != sorted(vals):
                raise ValueError(
                    f"{fname}{dict(rest)}: buckets not cumulative {vals}")
            count = fam["samples"].get((f"{fname}_count", rest))
            if count is not None and ordered and ordered[-1][0] == "+Inf" \
                    and ordered[-1][1] != count:
                raise ValueError(
                    f"{fname}{dict(rest)}: +Inf bucket {ordered[-1][1]} "
                    f"!= count {count}")
    return families


__all__ = ["Registry", "Counter", "Gauge", "Histogram", "escape_label",
           "exponential_buckets", "parse_exposition",
           "DEFAULT_DURATION_BUCKETS"]
