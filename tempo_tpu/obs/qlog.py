"""Structured query logging: one JSON "query complete" line per request.

The reference's query-frontend logs a structured result line per query
(`modules/frontend/handler.go` "query stats" logging) carrying tenant,
query, duration, and the merged stats fields. This module is that
emitter, with tail-based capture so log volume tracks interesting
queries, not traffic:

- errors log unconditionally (ERROR level);
- queries slower than a moment-sketch-estimated latency quantile log as
  slow queries (WARNING) — the in-process log2 sketch gives cheap
  mergeable quantiles (arXiv:1803.01969's observation that log-spaced
  summaries are the right compact primitive for latency telemetry), so
  the threshold self-tunes to each op's own distribution instead of a
  static number;
- everything else is head-sampled 1-in-N (INFO).

Non-error emission is token-bucket rate-limited so a latency regression
cannot turn the query log into its own outage; errors bypass the bucket.
Every record is one `json.dumps` line on the `tempo_tpu.query` logger —
machine-parseable, greppable, and carrying the active SelfTracer trace
id so a slow line is one click from its trace.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from collections import OrderedDict
from typing import Callable

from tempo_tpu.obs.querystats import QueryStats

LOGGER_NAME = "tempo_tpu.query"

_NBUCKETS = 64
# bucket offset shifts coverage down to sub-millisecond latencies:
# bucket b>0 holds durations in [2^(b-1-_OFFSET), 2^(b-_OFFSET)) seconds,
# so with _OFFSET=32 the range spans ~2^-32s .. ~2^31s (ops/sketches
# Log2Histogram geometry, host-side — one int array, no device round trip)
_OFFSET = 32


class LatencySketch:
    """Per-op power-of-two latency histogram with interpolated quantile —
    the host twin of `ops.sketches.Log2Histogram` (same bucketing, same
    exponential interpolation), sized for one counter add per query."""

    def __init__(self) -> None:
        self.counts = [0] * _NBUCKETS
        self.total = 0

    def record(self, seconds: float) -> None:
        if seconds <= 0:
            b = 0
        else:
            b = min(max(int(math.floor(math.log2(seconds))) + 1 + _OFFSET, 0),
                    _NBUCKETS - 1)
        self.counts[b] += 1
        self.total += 1

    def quantile(self, q: float) -> float:
        """Interpolated q-quantile in seconds (0.0 when empty)."""
        if self.total <= 0:
            return 0.0
        target = max(q * self.total, 1e-12)
        cum = 0
        for b, c in enumerate(self.counts):
            cum += c
            if cum >= target:
                if b == 0:
                    return 0.0
                frac = (target - (cum - c)) / c if c else 1.0
                return 2.0 ** (b - 1 - _OFFSET + frac)
        return 2.0 ** (_NBUCKETS - 1 - _OFFSET)


class QueryLogger:
    """Level- and rate-limit-aware structured query logger.

    `log_query` is called once per frontend request; whether a record is
    emitted follows the error > slow > sampled cascade above. Emission
    counts are kept per outcome (for a registry callback family) so
    suppressed volume stays observable.
    """

    def __init__(self, *,
                 slow_quantile: float = 0.95,
                 sample_every: int = 100,
                 min_observations: int = 30,
                 rate_limit_per_s: float = 10.0,
                 burst: int = 20,
                 logger: "logging.Logger | None" = None,
                 now: Callable[[], float] = time.time) -> None:
        self.slow_quantile = float(slow_quantile)
        self.sample_every = max(int(sample_every), 1)
        self.min_observations = int(min_observations)
        self.now = now
        self._logger = logger if logger is not None \
            else logging.getLogger(LOGGER_NAME)
        self._lock = threading.Lock()
        self._sketches: dict[str, LatencySketch] = {}
        self._seen: dict[str, int] = {}
        # recurring-query recognition: per-fingerprint (obs/queryfp.py
        # — the identity shared with tempo_tpu.matview) hit counts over
        # a sliding window, bounded LRU so dashboard churn cannot grow
        # it without bound. The materialized-view tier reads these
        # counts to auto-subscribe hot queries.
        self._recur: "OrderedDict[str, tuple[int, float]]" = OrderedDict()
        self._recur_window_s = 600.0
        self._recur_max = 4096
        # token bucket for non-error records (errors always emit)
        self._rate = float(rate_limit_per_s)
        self._burst = float(burst)
        self._tokens = float(burst)
        self._last_refill = now()
        self.emitted: dict[str, int] = {}      # reason -> count
        self.suppressed = 0

    # -- decision helpers ---------------------------------------------------

    def threshold(self, op: str) -> float:
        """Current slow-query duration threshold for an op, seconds
        (0.0 until the sketch has min_observations)."""
        with self._lock:
            sk = self._sketches.get(op)
            if sk is None or sk.total < self.min_observations:
                return 0.0
            return sk.quantile(self.slow_quantile)

    def _take_token(self) -> bool:
        t = self.now()
        self._tokens = min(self._burst,
                           self._tokens + (t - self._last_refill) * self._rate)
        self._last_refill = t
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False

    def _decide(self, op: str, status: str, duration_s: float) -> "str | None":
        """Returns the emission reason, or None to suppress. Also feeds
        the duration sketch (every query observes, logged or not)."""
        with self._lock:
            sk = self._sketches.get(op)
            if sk is None:
                sk = self._sketches[op] = LatencySketch()
            warmed = sk.total >= self.min_observations
            thr = sk.quantile(self.slow_quantile) if warmed else 0.0
            sk.record(duration_s)
            if status != "ok":
                return "error"
            # head-sampling counts only ok queries (errors always emit and
            # must not steal a sample slot)
            self._seen[op] = n = self._seen.get(op, 0) + 1
            if warmed and duration_s >= thr:
                reason = "slow"
            elif (n - 1) % self.sample_every == 0:
                reason = "sampled"
            else:
                self.suppressed += 1
                return None
            if not self._take_token():
                self.suppressed += 1
                return None
            return reason

    def note_fingerprint(self, fp: str) -> int:
        """Count one sighting of a query fingerprint; returns how many
        times it recurred within the sliding window. The frontend feeds
        every metrics request through here and hands the count to the
        materializer's auto-subscribe decision — qlog owns recurrence so
        the query log and the matview tier see the same hot set."""
        t = self.now()
        with self._lock:
            n, first = self._recur.get(fp, (0, t))
            if t - first > self._recur_window_s:
                n, first = 0, t            # window rolled: restart count
            self._recur[fp] = (n + 1, first)
            self._recur.move_to_end(fp)
            while len(self._recur) > self._recur_max:
                self._recur.popitem(last=False)
            return n + 1

    def fingerprint_count(self, fp: str) -> int:
        with self._lock:
            n, first = self._recur.get(fp, (0, 0.0))
            if n and self.now() - first > self._recur_window_s:
                return 0
            return n

    # -- emission -----------------------------------------------------------

    def log_query(self, *, op: str, tenant: str, query: str, status: str,
                  duration_s: float, stats: "QueryStats | None" = None,
                  trace_id: "str | None" = None,
                  error: "str | None" = None,
                  extra: "dict | None" = None) -> "dict | None":
        """Emit (or suppress) one "query complete" record; returns the
        record dict when emitted, None when suppressed. `extra` merges
        additional context fields into the record (e.g. the frontend's
        ingest keep-fraction exemplar while overload sampling is active
        — a reader of a slow/odd query line needs to know whether its
        quantiles came from a sampled stream)."""
        reason = self._decide(op, status, duration_s)
        if reason is None:
            return None
        record = {
            "msg": "query complete",
            "reason": reason,
            "op": op,
            "tenant": tenant,
            "query": query,
            "status": status,
            "durationMs": round(duration_s * 1e3, 3),
            "traceId": trace_id,
        }
        if extra:
            record.update(extra)
        if error:
            record["error"] = str(error)[:500]
        if stats is not None:
            record.update(stats.search_metrics())
            # tenant read-cost investigation fields, pre-derived so a
            # reader never joins against /metrics: the request's device
            # wall (device-time ledger attribution, obs/devtime.py) and
            # the share of its duration spent waiting on the device
            # scheduler (high share = the chip, not the query, is slow)
            record["deviceSeconds"] = round(record["deviceNanos"] / 1e9, 6)
            if duration_s > 0:
                wait_ns = record["stageDurationNanos"].get("sched_wait", 0)
                record["schedWaitShare"] = round(
                    min(wait_ns / 1e9 / duration_s, 1.0), 4)
        level = (logging.ERROR if reason == "error"
                 else logging.WARNING if reason == "slow" else logging.INFO)
        with self._lock:
            self.emitted[reason] = self.emitted.get(reason, 0) + 1
        self._logger.log(level, json.dumps(record, sort_keys=True))
        return record

    # -- registry bridge ----------------------------------------------------

    def emitted_by_reason(self) -> list:
        """Callback-family shape: [((reason,), count), ...] plus the
        suppressed count under reason="suppressed"."""
        with self._lock:
            out = [((k,), float(v)) for k, v in self.emitted.items()]
            out.append((("suppressed",), float(self.suppressed)))
        return out


__all__ = ["QueryLogger", "LatencySketch", "LOGGER_NAME"]
