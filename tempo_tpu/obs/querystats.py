"""Request-scoped query statistics: the dskit `stats` analog.

The reference threads a per-request stats object from querier block scans
back through gRPC trailers to the query-frontend (`pkg/usagestats` /
dskit stats middleware), which merges shard stats, returns them in
`SearchMetrics`, and logs a structured "query complete" line. This module
is that axis for this build: a `QueryStats` accumulator installed in a
contextvar (the `SelfTracer` span-stack pattern, utils/tracing.py), so
the read path records into the ambient scope with ZERO coupling — and a
None-check-only cost when no query is in flight (loops, compaction,
ingest never pay).

Scoping rules:

- An entry point (API handler, frontend endpoint, RPC server handler)
  opens `scope()`; everything on that thread records into it.
- The frontend gives every sharded sub-request job its OWN QueryStats
  and the executing worker installs it with `scope(job.stats)` — contextvars
  do not cross thread-pool boundaries, and per-job objects mean no lock
  contention between shards. The issuer merges child stats at fold time.
- Cross-process, stats ride the RPC plane (tempopb metrics submessage,
  worker-stream result messages, `/internal/*` JSON bodies — the
  gRPC-trailer analog) and `absorb()` folds them into the ambient scope.

Stage wall-times (`stage_ns`) are per-stage wall clocks: stages nest and
overlap (block-fetch happens inside engine-eval's lazy view pull), so
they are attribution hints, not a partition of the request duration.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import threading
import time

_current: "contextvars.ContextVar[QueryStats | None]" = contextvars.ContextVar(
    "tempo_query_stats", default=None)

# counter fields, in wire order (tempopb assigns proto field numbers from
# this tuple's order — append only, never reorder)
COUNTER_FIELDS = (
    "inspected_traces",      # traces whose spans a scan examined
    "inspected_bytes",       # bytes materialized from block row groups
    "inspected_spans",       # candidate spans the engines evaluated
    "total_blocks",          # blocks the sharder considered
    "blocks_scanned",        # block slices actually scanned (per job)
    "blocks_skipped",        # bloom + time-range/shard prunes
    "total_jobs",            # sharded sub-requests issued
    "completed_jobs",        # sub-requests folded (incl. cache hits)
    "cache_hits",            # sub-requests served from the response cache
    "device_scan_bytes",     # bytes uploaded to the device read plane
    "kernel_wall_ns",        # wall nanos blocked on device kernel results
    "sched_jobs",            # device-scheduler jobs this request queued
    "device_ns",             # device-dispatch wall attributed by the
    #                          device-time ledger (obs/devtime.py): the
    #                          request's share of scheduler dispatches
)

# canonical per-stage wall-time breakdown keys (free-form keys are
# accepted; these are the ones the read path records)
STAGES = ("queue_wait", "block_fetch", "device_scan", "engine_eval", "merge",
          "sched_wait")


@dataclasses.dataclass
class QueryStats:
    """One request's (or sub-request's) accumulated read-path statistics.

    Thread-safe: a lock guards every mutation so an issuer folding child
    stats can race a straggler worker without corrupting counts.
    """

    inspected_traces: int = 0
    inspected_bytes: int = 0
    inspected_spans: int = 0
    total_blocks: int = 0
    blocks_scanned: int = 0
    blocks_skipped: int = 0
    total_jobs: int = 0
    completed_jobs: int = 0
    cache_hits: int = 0
    device_scan_bytes: int = 0
    kernel_wall_ns: int = 0
    sched_jobs: int = 0
    device_ns: int = 0
    stage_ns: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        self._lock = threading.Lock()

    # -- recording ----------------------------------------------------------

    def add(self, **fields: int) -> None:
        with self._lock:
            for name, n in fields.items():
                setattr(self, name, getattr(self, name) + int(n))

    def add_stage_ns(self, stage: str, ns: int) -> None:
        with self._lock:
            self.stage_ns[stage] = self.stage_ns.get(stage, 0) + int(ns)

    def merge(self, other: "QueryStats | None") -> None:
        """Fold a child's (shard job, remote leg) stats into this one."""
        if other is None or other is self:
            return
        with other._lock:
            counters = {f: getattr(other, f) for f in COUNTER_FIELDS}
            stages = dict(other.stage_ns)
        with self._lock:
            for f, n in counters.items():
                setattr(self, f, getattr(self, f) + n)
            for s, ns in stages.items():
                self.stage_ns[s] = self.stage_ns.get(s, 0) + ns

    def floor_inspected_traces(self, n: int) -> None:
        """Lift inspected_traces to >= n: results RETURNED were at least
        inspected, even when they came from a path that records nothing
        (ingester live-trace scans, fully cache-served shard sets). Every
        response surface applies this once before rendering stats."""
        with self._lock:
            if self.inspected_traces < n:
                self.inspected_traces = int(n)

    # -- serialization ------------------------------------------------------

    def to_json(self) -> dict:
        """JSON-safe snapshot (snake_case — the internal RPC shape)."""
        with self._lock:
            out = {f: getattr(self, f) for f in COUNTER_FIELDS
                   if getattr(self, f)}
            if self.stage_ns:
                out["stage_ns"] = dict(self.stage_ns)
        return out

    @classmethod
    def from_json(cls, d: "dict | None") -> "QueryStats":
        st = cls()
        if not d:
            return st
        for f in COUNTER_FIELDS:
            if f in d:
                setattr(st, f, int(d[f]))
        for s, ns in (d.get("stage_ns") or {}).items():
            st.stage_ns[str(s)] = int(ns)
        return st

    def search_metrics(self) -> dict:
        """`SearchMetrics`-shaped dict for API responses (camelCase,
        every field present so consumers need no existence checks)."""
        with self._lock:
            return {
                "inspectedTraces": self.inspected_traces,
                "inspectedBytes": self.inspected_bytes,
                "inspectedSpans": self.inspected_spans,
                "totalBlocks": self.total_blocks,
                "blocksScanned": self.blocks_scanned,
                "blocksSkipped": self.blocks_skipped,
                "totalJobs": self.total_jobs,
                "completedJobs": self.completed_jobs,
                "cacheHits": self.cache_hits,
                "deviceScanBytes": self.device_scan_bytes,
                "kernelWallNanos": self.kernel_wall_ns,
                "schedJobs": self.sched_jobs,
                "deviceNanos": self.device_ns,
                "stageDurationNanos": dict(self.stage_ns),
            }


# ---------------------------------------------------------------------------
# ambient scope
# ---------------------------------------------------------------------------


def current() -> "QueryStats | None":
    return _current.get()


@contextlib.contextmanager
def scope(stats: "QueryStats | None" = None):
    """Install a stats object (a fresh one by default) as the ambient
    scope for the duration of the block. Workers use `scope(job.stats)`
    to adopt a sub-request's accumulator on their own thread."""
    st = stats if stats is not None else QueryStats()
    token = _current.set(st)
    try:
        yield st
    finally:
        _current.reset(token)


@contextlib.contextmanager
def ensure_scope():
    """Join the ambient scope, or open a fresh one when none is active —
    frontend entry points use this so an API handler's scope (which must
    outlive the call to render the response) is reused, while direct
    programmatic calls still get stats for the query log."""
    st = _current.get()
    if st is not None:
        yield st
        return
    with scope() as st:
        yield st


def add(**fields: int) -> None:
    """Record counters into the ambient scope; no-op (one None check)
    outside any query."""
    st = _current.get()
    if st is not None:
        st.add(**fields)


def absorb(child: "QueryStats | None") -> None:
    """Merge a deserialized child (remote shard / ingester leg) into the
    ambient scope, if any."""
    st = _current.get()
    if st is not None and child is not None:
        st.merge(child)


@contextlib.contextmanager
def stage(name: str):
    """Time a region into the ambient scope's per-stage breakdown;
    no-op outside any query."""
    st = _current.get()
    if st is None:
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        st.add_stage_ns(name, time.perf_counter_ns() - t0)


__all__ = ["QueryStats", "COUNTER_FIELDS", "STAGES", "current", "scope",
           "ensure_scope", "add", "absorb", "stage"]
