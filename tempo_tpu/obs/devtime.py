"""Device-time ledger + online dispatch cost model.

Every nanosecond the device spends belongs to some (kernel, shape
bucket, priority class, mesh shard-width) — and, through the jobs that
rode the batch, to some tenant. The scheduler already measured dispatch
wall time (`tempo_sched_dispatch_duration_seconds`) but threw the
structure away; this module is the process-wide **ledger** every sched
dispatch records into, and the substrate two consumers build on:

- **Attribution.** Per-tenant device-seconds (each merged batch's wall
  split across its jobs' tenants proportionally to submitted rows) ride
  `/metrics`, `/status`, and — through `QueryStats.device_ns` — the
  qlog "query complete" line, so a read-cost investigation never needs
  a metrics join. The attribution invariant (tenant shares sum to the
  batch wall, within float rounding) is what the bench soak stage gates
  on.
- **Prediction.** An online per-(kernel, bucket) **affine cost model**
  (cost ≈ a + b·rows) fit from the ledger stream with exponentially
  decayed least squares and winsorized residuals (one GC pause must not
  poison the fit — the "TpuGraphs" observation that dispatch cost is a
  learnable function of shape, reduced to the two coefficients this
  scheduler actually needs). `DeviceScheduler` `tuning: auto` consults
  it to pick batch-window deadlines; `/status cost_model` and the
  `tempo_sched_cost_model_*` families expose the fit, and the
  `TempoSchedCostModelStale` alert fires when tuning is live but the
  model has stopped learning.

Both singletons (`LEDGER`, `COST_MODEL`) are process-wide like the
scheduler that feeds them; `reset()` drops state between tests. The hot
path is one lock + a handful of dict updates per MERGED BATCH (not per
row, not per span) — the exposition renders through callback families,
so scrapes never block dispatch.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable

from tempo_tpu.obs.jaxruntime import RUNTIME
from tempo_tpu.obs.registry import exponential_buckets

# priority-class names duplicated from tempo_tpu.sched to avoid an
# import cycle (sched imports this module for the ledger hooks)
_CLASS_NAMES = ("ingest", "query", "compaction")


class _Cell:
    """One ledger accumulator row (all monotonic counters)."""

    __slots__ = ("wall_ns", "batches", "rows", "padded_rows",
                 "queue_wait_ns", "h2d_bytes")

    def __init__(self) -> None:
        self.wall_ns = 0
        self.batches = 0
        self.rows = 0
        self.padded_rows = 0
        self.queue_wait_ns = 0
        self.h2d_bytes = 0


class DeviceTimeLedger:
    """Where every device-nanosecond goes, keyed
    (kernel, shape bucket, priority class, mesh shard-width).

    `shard` is the dispatch's 'data'-shard width as a string ("" for
    single-device dispatches): a mesh dispatch occupies every shard for
    its wall time, so the wall is a per-mesh — not per-chip — figure,
    the same convention the sched occupancy families use.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cells: dict[tuple, _Cell] = {}
        self._tenant_ns: dict[str, int] = {}

    # -- recording ---------------------------------------------------------

    def record_batch(self, *, kernel: str, bucket: int, prio: int,
                     shards: int, wall_ns: int, rows: int,
                     padded_rows: int, queue_wait_ns: int,
                     h2d_bytes: int,
                     tenant_rows: "dict[str, int] | None" = None) -> None:
        """One dispatched batch (merged row batch, or a fn job with
        bucket 0 / rows 0). `tenant_rows` maps tenant → submitted rows
        for the jobs that rode this batch; the batch wall splits across
        them proportionally (padding is overhead shared the same way),
        so per-tenant device-seconds sum to total device time."""
        cls = _CLASS_NAMES[prio] if 0 <= prio < len(_CLASS_NAMES) \
            else str(prio)
        key = (kernel, int(bucket), cls, str(shards) if shards else "")
        wall_ns = max(int(wall_ns), 0)
        with self._lock:
            c = self._cells.get(key)
            if c is None:
                c = self._cells[key] = _Cell()
            c.wall_ns += wall_ns
            c.batches += 1
            c.rows += max(int(rows), 0)
            c.padded_rows += max(int(padded_rows), 0)
            c.queue_wait_ns += max(int(queue_wait_ns), 0)
            c.h2d_bytes += max(int(h2d_bytes), 0)
            if tenant_rows:
                total = sum(tenant_rows.values())
                if total > 0:
                    for t, r in tenant_rows.items():
                        self._tenant_ns[t] = self._tenant_ns.get(t, 0) \
                            + wall_ns * r // total
                else:
                    # fn jobs carry no rows: split the wall evenly
                    share = wall_ns // len(tenant_rows)
                    for t in tenant_rows:
                        self._tenant_ns[t] = \
                            self._tenant_ns.get(t, 0) + share
            else:
                # no tenant on the job (deep read-path kernels launch
                # below the tenant boundary): keep the sum invariant
                # exact with an explicit bucket — "how much device time
                # is not tenant-attributable" is itself a signal
                self._tenant_ns["_unattributed"] = \
                    self._tenant_ns.get("_unattributed", 0) + wall_ns

    # -- reading -----------------------------------------------------------

    def total_device_ns(self) -> int:
        with self._lock:
            return sum(c.wall_ns for c in self._cells.values())

    def tenant_device_ns(self) -> dict[str, int]:
        with self._lock:
            return dict(self._tenant_ns)

    def snapshot(self) -> dict[tuple, dict]:
        """{(kernel, bucket, class, shard) -> counters dict} (tests and
        /status)."""
        with self._lock:
            return {k: {s: getattr(c, s) for s in _Cell.__slots__}
                    for k, c in self._cells.items()}

    def _rows(self, field: str) -> list:
        with self._lock:
            return [((k[0], str(k[1]), k[2], k[3]), float(getattr(c, field)))
                    for k, c in self._cells.items()]

    def status(self, top_tenants: int = 10) -> dict:
        """The /status "devtime" object: totals plus the costliest
        tenants (full per-tenant detail is on /metrics)."""
        with self._lock:
            total = sum(c.wall_ns for c in self._cells.values())
            queue = sum(c.queue_wait_ns for c in self._cells.values())
            rows = sum(c.rows for c in self._cells.values())
            padded = sum(c.padded_rows for c in self._cells.values())
            tenants = sorted(self._tenant_ns.items(),
                             key=lambda kv: -kv[1])[:top_tenants]
        out = {
            "device_seconds_total": round(total / 1e9, 6),
            "queue_wait_seconds_total": round(queue / 1e9, 6),
            "rows_total": rows,
            "padded_rows_total": padded,
            "top_tenant_device_seconds": {
                t: round(ns / 1e9, 6) for t, ns in tenants},
        }
        # the paged layout's HBM cost, attributed next to device time:
        # arena bytes held per tenant (page ownership × page bytes)
        from tempo_tpu.registry import pages
        pool = pages.active()
        if pool is not None:
            top = sorted(pool.tenant_bytes().items(),
                         key=lambda kv: -kv[1])[:top_tenants]
            out["top_tenant_arena_bytes"] = dict(top)
        return out


class _PairFit:
    """Decayed least-squares state for one (kernel, bucket) pair: EWMA
    moments of (rows, cost) solve the 2x2 normal equations for
    cost ≈ a + b·rows."""

    __slots__ = ("n", "m_r", "m_r2", "m_y", "m_ry", "err", "err_med",
                 "med_y", "last_t")

    def __init__(self) -> None:
        self.n = 0
        self.m_r = self.m_r2 = self.m_y = self.m_ry = 0.0
        self.err = 0.0          # EWMA of |pred - actual| / actual
        # streaming MEDIAN of the same relative error (constant-step
        # sign update): per-sample jitter visibility — on a contended
        # host individual dispatch walls swing ±50% with GIL/scheduler
        # noise no shape model can predict
        self.err_med = 0.0
        # streaming median of the RAW observed cost (relative-step sign
        # update): the "typical dispatch cost" the tuner actually plans
        # on; prediction vs this median is the soak's accuracy gate
        self.med_y = 0.0
        self.last_t = 0.0

    def coeffs(self) -> "tuple[float, float] | None":
        """(a, b) seconds / seconds-per-row, or None while degenerate
        (single rows value seen: fall back to a pure mean — b = 0).
        Dispatch cost is monotone in rows: a negative fitted slope is
        always contention noise, collapse it to the mean."""
        if self.n == 0:
            return None
        var = self.m_r2 - self.m_r * self.m_r
        if var <= 1e-12 * max(self.m_r2, 1.0):
            return (self.m_y, 0.0)
        b = (self.m_ry - self.m_r * self.m_y) / var
        if b < 0:
            return (self.m_y, 0.0)
        a = self.m_y - b * self.m_r
        return (a, b)


class CostModel:
    """Online affine dispatch-cost model, per (kernel, shape bucket).

    - `observe()` is called by the scheduler once per merged dispatch
      with the REAL rows and the measured wall seconds.
    - Robustness: once a pair is warm, an observation is winsorized into
      [pred/clip, pred*clip] before it updates the moments — a one-off
      stall (GC, XLA re-trace, a neighbor hogging the chip) shifts the
      fit by at most the clip factor instead of poisoning it.
    - `predict()` answers in seconds; None until the pair has
      `min_samples` observations (the scheduler's static-window
      fallback condition).
    """

    def __init__(self, *, alpha: float = 0.05, min_samples: int = 50,
                 clip: float = 4.0,
                 now: Callable[[], float] = time.time) -> None:
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.clip = float(clip)
        self.now = now
        self._lock = threading.Lock()
        self._pairs: dict[tuple[str, int], _PairFit] = {}

    # -- learning ----------------------------------------------------------

    def observe(self, kernel: str, bucket: int, rows: int,
                seconds: float) -> None:
        if seconds < 0 or rows < 0:
            return
        key = (kernel, int(bucket))
        with self._lock:
            p = self._pairs.get(key)
            if p is None:
                p = self._pairs[key] = _PairFit()
            y = float(seconds)
            if p.n == 0:
                p.med_y = y
            else:
                step = max(abs(p.med_y) * 0.05, 1e-7)
                p.med_y = max(p.med_y + (step if y > p.med_y else -step),
                              0.0)
            c = p.coeffs()
            if c is not None and p.n >= self.min_samples:
                pred = max(c[0] + c[1] * rows, 1e-9)
                x = abs(pred - y) / max(y, 1e-9)
                p.err += self.alpha * (x - p.err)
                p.err_med = max(
                    p.err_med + (0.02 if x > p.err_med else -0.02), 0.0)
                y = min(max(y, pred / self.clip), pred * self.clip)
            elif p.n >= 3:
                # not warm enough to predict, but already robust: clip
                # against the pair's own running mean so one early
                # scheduling stall (tenant-creation phase, a GC pause)
                # cannot seed the moments orders of magnitude high
                ref = max(p.m_y, 1e-12)
                y = min(max(y, ref / self.clip), ref * self.clip)
            # debiased warm-up: behave as a plain running mean until the
            # sample count overtakes 1/alpha, THEN decay exponentially —
            # a fixed small alpha would keep early outliers alive for
            # ~1/alpha more observations
            a = max(self.alpha, 1.0 / (p.n + 1))
            r = float(rows)
            p.m_r += a * (r - p.m_r)
            p.m_r2 += a * (r * r - p.m_r2)
            p.m_y += a * (y - p.m_y)
            p.m_ry += a * (r * y - p.m_ry)
            p.n += 1
            p.last_t = self.now()

    # -- prediction --------------------------------------------------------

    def warm(self, kernel: str, bucket: int) -> bool:
        with self._lock:
            p = self._pairs.get((kernel, int(bucket)))
            return p is not None and p.n >= self.min_samples

    def warm_pairs(self, kernel: "str | None" = None) -> list:
        with self._lock:
            return [k for k, p in self._pairs.items()
                    if p.n >= self.min_samples
                    and (kernel is None or k[0] == kernel)]

    def predict(self, kernel: str, bucket: int,
                rows: "int | None" = None) -> "float | None":
        """Predicted dispatch seconds for `rows` real rows in `bucket`
        (rows defaults to the bucket itself), or None while cold. When
        the exact bucket is cold but a neighbor bucket of the same
        kernel is warm, extrapolates from the nearest warm bucket — the
        tuner must be able to score a window it has never closed at."""
        key = (kernel, int(bucket))
        r = float(bucket if rows is None else rows)
        with self._lock:
            p = self._pairs.get(key)
            if p is None or p.n < self.min_samples:
                near = None
                for (k, b), q in self._pairs.items():
                    if k != kernel or q.n < self.min_samples:
                        continue
                    if near is None or abs(math.log2(max(b, 1))
                                           - math.log2(max(bucket, 1))) < \
                            abs(math.log2(max(near[0], 1))
                                - math.log2(max(bucket, 1))):
                        near = (b, q)
                if near is None:
                    return None
                p = near[1]
            c = p.coeffs()
        if c is None:
            return None
        return max(c[0] + c[1] * r, 0.0)

    def rel_error(self, kernel: str, bucket: int) -> "float | None":
        """EWMA (mean) relative prediction error for a warm pair, or
        None while cold. Outlier-sensitive by design: a rising mean
        with a flat median means stalls, not a bad fit."""
        with self._lock:
            p = self._pairs.get((kernel, int(bucket)))
            if p is None or p.n <= self.min_samples:
                return None
            return p.err

    def rel_error_median(self, kernel: str, bucket: int) -> "float | None":
        """Streaming median of the PER-SAMPLE relative prediction error
        (dispatch jitter visibility), or None while cold."""
        with self._lock:
            p = self._pairs.get((kernel, int(bucket)))
            if p is None or p.n <= self.min_samples:
                return None
            return p.err_med

    def typical_error(self, kernel: str, bucket: int) -> "float | None":
        """|predicted − observed-median| / observed-median for a warm
        pair — prediction accuracy against the TYPICAL dispatch cost
        (what the window tuner plans on), immune to the per-dispatch
        GIL/scheduling jitter no shape model can predict. The bench
        soak gates this ≤ 0.25 on warm pairs. None while cold."""
        with self._lock:
            p = self._pairs.get((kernel, int(bucket)))
            if p is None or p.n < self.min_samples or p.med_y <= 0:
                return None
            c = p.coeffs()
            if c is None:
                return None
            pred = max(c[0] + c[1] * p.m_r, 0.0)
            return abs(pred - p.med_y) / p.med_y

    # -- exposition --------------------------------------------------------

    def status(self) -> list[dict]:
        """The /status "cost_model" array: one entry per pair, warm
        first, coefficients in engineering units."""
        now = self.now()
        with self._lock:
            items = sorted(self._pairs.items(),
                           key=lambda kv: (-kv[1].n, kv[0]))
            out = []
            for (kernel, bucket), p in items:
                c = p.coeffs()
                typical = None
                if c is not None and p.med_y > 0:
                    typical = abs(max(c[0] + c[1] * p.m_r, 0.0)
                                  - p.med_y) / p.med_y
                out.append({
                    "kernel": kernel, "bucket": bucket, "samples": p.n,
                    "warm": p.n >= self.min_samples,
                    "a_us": round(c[0] * 1e6, 3) if c else None,
                    "b_ns_per_row": round(c[1] * 1e9, 3) if c else None,
                    "typical_cost_us": round(p.med_y * 1e6, 3),
                    "typical_error": round(typical, 4)
                    if typical is not None else None,
                    "rel_error": round(p.err, 4),
                    "rel_error_median": round(p.err_med, 4),
                    "age_s": round(max(now - p.last_t, 0.0), 3),
                })
        return out

    def _gauge_rows(self, what: str) -> list:
        now = self.now()
        with self._lock:
            out = []
            for (kernel, bucket), p in self._pairs.items():
                c = p.coeffs()
                if c is None:
                    continue
                if what == "typical":
                    if p.med_y <= 0:
                        continue
                    v = abs(max(c[0] + c[1] * p.m_r, 0.0)
                            - p.med_y) / p.med_y
                else:
                    v = {"a": c[0], "b": c[1], "err": p.err,
                         "err_med": p.err_med,
                         "age": max(now - p.last_t, 0.0)}[what]
                out.append(((kernel, str(bucket)), float(v)))
        return out


# ---------------------------------------------------------------------------
# process-wide singletons + test reset
# ---------------------------------------------------------------------------

LEDGER = DeviceTimeLedger()
COST_MODEL = CostModel()


def reset() -> None:
    """Drop ledger + model state (test isolation — mirrors sched.reset;
    the singletons keep their identity so registered callback families
    stay valid)."""
    with LEDGER._lock:
        LEDGER._cells.clear()
        LEDGER._tenant_ns.clear()
    with COST_MODEL._lock:
        COST_MODEL._pairs.clear()


# ---------------------------------------------------------------------------
# /metrics families (process-wide RUNTIME registry, callback-backed:
# scrapes snapshot the ledger, dispatch never touches the registry)
# ---------------------------------------------------------------------------

_LEDGER_LABELS = ("kernel", "bucket", "class", "shard")

RUNTIME.counter_func(
    "tempo_devtime_device_seconds_total",
    lambda: [(k, v / 1e9) for k, v in LEDGER._rows("wall_ns")],
    help="Device-dispatch wall seconds by kernel, shape bucket, priority "
         "class, and mesh shard-width (shard=\"\" = single-device) — the "
         "device-time ledger's primary axis",
    labels=_LEDGER_LABELS)
RUNTIME.counter_func(
    "tempo_devtime_batches_total",
    lambda: LEDGER._rows("batches"),
    help="Dispatched batches recorded in the device-time ledger",
    labels=_LEDGER_LABELS)
RUNTIME.counter_func(
    "tempo_devtime_submitted_rows_total",
    lambda: LEDGER._rows("rows"),
    help="Real (caller-submitted) rows dispatched, by ledger key — "
         "with padded_rows, the shape-bucket padding overhead split "
         "the tuner is minimizing against",
    labels=_LEDGER_LABELS)
RUNTIME.counter_func(
    "tempo_devtime_padded_rows_total",
    lambda: LEDGER._rows("padded_rows"),
    help="Padding rows dispatched beyond real rows, by ledger key",
    labels=_LEDGER_LABELS)
RUNTIME.counter_func(
    "tempo_devtime_queue_wait_seconds_total",
    lambda: [(k, v / 1e9) for k, v in LEDGER._rows("queue_wait_ns")],
    help="Seconds jobs waited between enqueue and dispatch start, "
         "summed per ledger key (queue-wait share of device latency)",
    labels=_LEDGER_LABELS)
RUNTIME.counter_func(
    "tempo_devtime_h2d_bytes_total",
    lambda: LEDGER._rows("h2d_bytes"),
    help="Host-to-device bytes shipped by dispatched batches, by ledger "
         "key (padded tensors, post-coalescing)",
    labels=_LEDGER_LABELS)
RUNTIME.counter_func(
    "tempo_devtime_tenant_device_seconds_total",
    lambda: [((t,), ns / 1e9)
             for t, ns in LEDGER.tenant_device_ns().items()],
    help="Device wall seconds attributed per tenant (each batch's wall "
         "split across its jobs' tenants by submitted rows; sums to "
         "tempo_devtime_device_seconds_total within rounding)",
    labels=("tenant",))
# enqueue → landed latency per ROW JOB (not per batch): the quantity
# `tuning: auto` minimizes and the soak stage's tuned-vs-static p99
# gate reads — window wait + queue wait + dispatch wall, the moment a
# push's rows became visible in device state
INGEST_LATENCY = RUNTIME.histogram(
    "tempo_devtime_ingest_visible_latency_seconds",
    "Enqueue to merged-dispatch-landed latency per coalesced row job, "
    "by kernel: the ingest-visible device latency the batch-window "
    "tuner minimizes (window wait + queue wait + dispatch wall)",
    labels=("kernel",),
    buckets=exponential_buckets(1e-4, 1.6, 24))


def quantile_from_counts(edges, counts, q: float) -> float:
    """Interpolated q-quantile from histogram bucket counts (len(edges)+1,
    last = overflow). Geometric interpolation inside a bucket — right for
    the exponential bucket layouts every histogram here uses. Returns 0.0
    on an empty histogram; the top edge when the quantile falls in the
    overflow bucket (a floor, not an estimate)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = max(q * total, 1e-12)
    cum = 0.0
    for i, c in enumerate(counts):
        cum += c
        if cum < target:
            continue
        if i >= len(edges):
            return float(edges[-1])
        hi = float(edges[i])
        lo = float(edges[i - 1]) if i > 0 else hi / 16.0
        frac = (target - (cum - c)) / c if c else 1.0
        return lo * (hi / lo) ** frac
    return float(edges[-1])


RUNTIME.gauge_func(
    "tempo_sched_cost_model_coeff_a_seconds",
    lambda: COST_MODEL._gauge_rows("a"),
    help="Fixed per-dispatch cost (intercept a of cost ≈ a + b·rows) "
         "fit online per (kernel, shape bucket)",
    labels=("kernel", "bucket"))
RUNTIME.gauge_func(
    "tempo_sched_cost_model_coeff_b_seconds_per_row",
    lambda: COST_MODEL._gauge_rows("b"),
    help="Marginal per-row cost (slope b of cost ≈ a + b·rows) fit "
         "online per (kernel, shape bucket)",
    labels=("kernel", "bucket"))
RUNTIME.gauge_func(
    "tempo_sched_cost_model_rel_error",
    lambda: COST_MODEL._gauge_rows("err"),
    help="EWMA (mean) relative prediction error of the dispatch cost "
         "model per (kernel, shape bucket); outlier-sensitive — "
         "compare against the median family to separate stalls from "
         "a bad fit",
    labels=("kernel", "bucket"))
RUNTIME.gauge_func(
    "tempo_sched_cost_model_rel_error_median",
    lambda: COST_MODEL._gauge_rows("err_med"),
    help="Streaming median of the per-sample relative prediction error "
         "per (kernel, shape bucket) — dispatch jitter the shape model "
         "cannot (and should not) absorb",
    labels=("kernel", "bucket"))
RUNTIME.gauge_func(
    "tempo_sched_cost_model_typical_error",
    lambda: COST_MODEL._gauge_rows("typical"),
    help="Prediction vs the observed MEDIAN dispatch cost per (kernel, "
         "shape bucket) — the tuner plans on typical costs; the soak "
         "gate holds warm pairs under 0.25",
    labels=("kernel", "bucket"))
RUNTIME.gauge_func(
    "tempo_sched_cost_model_age_seconds",
    lambda: COST_MODEL._gauge_rows("age"),
    help="Seconds since the cost model last observed a dispatch for "
         "this (kernel, bucket) — TempoSchedCostModelStale fires when "
         "tuning is active but every pair has gone quiet",
    labels=("kernel", "bucket"))


__all__ = ["DeviceTimeLedger", "CostModel", "LEDGER", "COST_MODEL",
           "INGEST_LATENCY", "quantile_from_counts", "reset"]
