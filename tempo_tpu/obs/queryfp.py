"""Recurring-query fingerprint: the shared "same query" identity.

The query log (obs/qlog.py) wants to notice that 10k dashboards are
polling the same handful of TraceQL-metrics queries, and the
materialized-view tier (tempo_tpu/matview) wants to serve exactly those
queries from standing device grids — both need to agree, byte for byte,
on what "the same query" means, so the identity lives here and nowhere
else.

A fingerprint covers (op, canonical query text, step) and deliberately
EXCLUDES the time window: a dashboard re-polling `rate()` every 10s
shifts start/end on every request but is still the same recurring
query (the whole point of materializing it). Canonicalization re-prints
the parsed AST — whitespace, quoting, and duration formatting normalize
for free — and additionally sorts the operands of commutative boolean
operators (`&&`/`||` inside filters, `&&`/`||` between spansets), so
`{a && b}` and `{b && a}` fingerprint identically. Queries that fail to
parse fall back to a whitespace-collapsed raw string: they still get a
stable (if weaker) identity instead of an exception on the log path.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import re

from tempo_tpu.traceql import ast as A

_WS = re.compile(r"\s+")

_COMMUTATIVE = (A.Op.AND, A.Op.OR)


def _canon_node(node):
    """Recursively canonicalize an AST node: rebuild frozen dataclasses
    with canonicalized children, flattening + sorting commutative
    boolean chains by their printed form."""
    if isinstance(node, A.BinaryOp) and node.op in _COMMUTATIVE:
        ops = _flatten(node, node.op)
        ops = sorted((_canon_node(o) for o in ops), key=str)
        out = ops[0]
        for o in ops[1:]:
            out = A.BinaryOp(node.op, out, o)
        return out
    if isinstance(node, A.SpansetCombine):
        lhs, rhs = _canon_node(node.lhs), _canon_node(node.rhs)
        if node.op in (A.SpansetOp.AND, A.SpansetOp.OR) \
                and str(rhs) < str(lhs):
            lhs, rhs = rhs, lhs
        return dataclasses.replace(node, lhs=lhs, rhs=rhs)
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        changes = {}
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            if isinstance(v, (list, tuple)):
                nv = type(v)(_canon_node(x) for x in v)
                if nv != v:
                    changes[f.name] = nv
            else:
                nv = _canon_node(v)
                if nv is not v:
                    changes[f.name] = nv
        return dataclasses.replace(node, **changes) if changes else node
    return node


def _flatten(node, op) -> list:
    if isinstance(node, A.BinaryOp) and node.op == op:
        return _flatten(node.lhs, op) + _flatten(node.rhs, op)
    return [node]


@functools.lru_cache(maxsize=4096)
def canonical_query(query: str) -> str:
    """Whitespace/order-normalized form of a TraceQL query (parse →
    canonicalize → re-print); unparseable input collapses whitespace.
    Memoized — the matview read path fingerprints every poll of the
    same few hundred dashboard queries."""
    from tempo_tpu.traceql.parser import parse

    try:
        q = parse(query)
    except Exception:
        return _WS.sub(" ", (query or "").strip())
    return str(_canon_node(q))


def query_fingerprint(op: str, query: str,
                      step_s: "float | None" = None) -> str:
    """The recurring-query identity: 16 hex chars over
    (op, canonical query, step-in-ms). Time-window independent by
    construction — start/end never enter the hash."""
    step_ms = "" if step_s is None else str(int(round(step_s * 1e3)))
    raw = "\x00".join((op, canonical_query(query), step_ms))
    return hashlib.sha1(raw.encode()).hexdigest()[:16]


__all__ = ["canonical_query", "query_fingerprint"]
