"""JAX/TPU runtime self-metrics: jit compiles, transfers, kernel walls.

One PROCESS-WIDE registry (`RUNTIME`), distinct from the per-App
registry: jit compilation caches, device transfers, and kernel dispatch
are process-level facts shared by every App in the process (tests boot
several), so their counters live here and `/metrics` renders them as an
`extra` registry alongside the App's own families.

Nothing in this module imports jax at import time — `instrumented_jit`
defers the import to first use so CPU-only unit tests of the registry
never pay (or require) a jax initialization.
"""

from __future__ import annotations

import contextlib
import time

from tempo_tpu.obs.registry import Registry, exponential_buckets

RUNTIME = Registry()

JIT_COMPILES = RUNTIME.counter(
    "tempo_jax_jit_compile_total",
    "Number of XLA compilations per instrumented jitted function "
    "(cache-miss traces; steady state should be flat)",
    labels=("fn",))
JIT_COMPILE_SECONDS = RUNTIME.counter(
    "tempo_jax_jit_compile_seconds_total",
    "Wall seconds spent inside calls that triggered an XLA compilation, "
    "per instrumented jitted function",
    labels=("fn",))
DEVICE_PUT_BYTES = RUNTIME.counter(
    "tempo_jax_device_put_bytes_total",
    "Bytes uploaded host-to-device, by call site",
    labels=("site",))
KERNEL_SECONDS = RUNTIME.histogram(
    "tempo_jax_kernel_duration_seconds",
    "Device kernel wall time measured around block_until_ready at the "
    "ops/sketches result-fetch sites, per kernel",
    labels=("kernel",),
    buckets=exponential_buckets(1e-5, 4.0, 12))


def instrumented_jit(fn, *, name: str | None = None, **jit_kwargs):
    """`jax.jit` wrapper that detects per-call compile-cache growth and
    records compile count + wall seconds under the `fn` label.

    Detection uses the jitted callable's `_cache_size()` when available
    (any growth during a call means at least one fresh trace+compile);
    older jax falls back to counting only the first call."""
    import jax

    jfn = jax.jit(fn, **jit_kwargs)
    label = name or getattr(fn, "__name__", "jit")
    state = {"first": True}

    def _cache_size():
        try:
            return jfn._cache_size()
        except Exception:
            return None

    def wrapper(*args, **kwargs):
        before = _cache_size()
        t0 = time.perf_counter()
        out = jfn(*args, **kwargs)
        after = _cache_size()
        if after is not None and before is not None:
            if after > before:
                JIT_COMPILES.inc(after - before, (label,))
                JIT_COMPILE_SECONDS.inc(time.perf_counter() - t0, (label,))
        elif state["first"]:
            state["first"] = False
            JIT_COMPILES.inc(1, (label,))
            JIT_COMPILE_SECONDS.inc(time.perf_counter() - t0, (label,))
        return out

    wrapper.__name__ = getattr(fn, "__name__", "jit")
    wrapper._jit = jfn          # escape hatch: .lower() etc.
    return wrapper


def record_device_put(nbytes: int, site: str) -> None:
    DEVICE_PUT_BYTES.inc(int(nbytes), (site,))


@contextlib.contextmanager
def kernel_timer(kernel: str):
    """Time a device-synchronizing region (a block_until_ready / result
    fetch) into the kernel wall-time histogram."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        KERNEL_SECONDS.observe(time.perf_counter() - t0, (kernel,))


__all__ = ["RUNTIME", "instrumented_jit", "record_device_put",
           "kernel_timer", "JIT_COMPILES", "JIT_COMPILE_SECONDS",
           "DEVICE_PUT_BYTES", "KERNEL_SECONDS"]
