"""Alert/dashboard ↔ registry drift gate.

Extracts every `tempo_*` metric name referenced by
`operations/alerts.yaml` and `operations/dashboards/*.json` and checks
each against the set of names actually registered in the obs registries
— the guarantee the tempo-mixin gets from generating everything out of
one jsonnet tree. A dashboard panel or alert expression can no longer
reference a metric this process never emits.

Used three ways: `operations/check_metrics_drift.py` (CLI, wired into
the `gen_dashboards.py --check` flow), the CI test
(tests/test_obs.py::test_ops_metric_names_registered), and ad-hoc from a
REPL against a live App.
"""

from __future__ import annotations

import json
import os
import re

METRIC_NAME_RE = re.compile(r"\btempo_[a-z0-9_]+")

# tokens the regex catches that are prose, not metric names (the python
# package name shows up in dashboard descriptions)
_NOT_METRICS = frozenset({"tempo_tpu"})


def referenced_metric_names(ops_dir: str) -> dict[str, set[str]]:
    """{metric_name -> {relative file paths referencing it}} over
    alerts.yaml + dashboards/*.json."""
    out: dict[str, set[str]] = {}

    def scan(path: str) -> None:
        rel = os.path.relpath(path, ops_dir)
        with open(path) as f:
            text = f.read()
        for name in METRIC_NAME_RE.findall(text):
            if name not in _NOT_METRICS:
                out.setdefault(name, set()).add(rel)

    alerts = os.path.join(ops_dir, "alerts.yaml")
    if os.path.exists(alerts):
        scan(alerts)
    dash_dir = os.path.join(ops_dir, "dashboards")
    if os.path.isdir(dash_dir):
        for fname in sorted(os.listdir(dash_dir)):
            if fname.endswith(".json"):
                # parse: a dashboard that stops being JSON should fail
                # here, not silently degrade to a text grep
                with open(os.path.join(dash_dir, fname)) as f:
                    json.load(f)
                scan(os.path.join(dash_dir, fname))
    return out


def registered_metric_names(registries) -> set[str]:
    out: set[str] = set()
    for reg in registries:
        out |= reg.metric_names()
    return out


def check_drift(ops_dir: str, registries) -> list[str]:
    """Return human-readable drift findings (empty = clean): every
    referenced metric name that no registry registers."""
    known = registered_metric_names(registries)
    problems: list[str] = []
    for name, files in sorted(referenced_metric_names(ops_dir).items()):
        if name in known:
            continue
        problems.append(
            f"{name} (referenced by {', '.join(sorted(files))}) is not "
            f"registered in the obs registry")
    return problems


_BAIL_RE = re.compile(r'_bail\("([a-z_]+)"\)')
_RUNBOOK_CAUSE_RE = re.compile(r"^\| `([a-z_]+)` \|", re.MULTILINE)


def check_bail_causes(ops_dir: str) -> list[str]:
    """Static source↔runbook gate: every `_bail("<cause>")` string in
    `block/device_scan.py` must have a row in the runbook's
    fallback-cause table ("Reading the read plane"). A new refusal path
    cannot ship without an operator-facing explanation — the same
    one-source-of-truth guarantee the metric-name check gives
    dashboards."""
    repo = os.path.dirname(ops_dir)
    scan_path = os.path.join(repo, "tempo_tpu", "block", "device_scan.py")
    runbook_path = os.path.join(ops_dir, "runbook.md")
    problems: list[str] = []
    if not os.path.exists(scan_path) or not os.path.exists(runbook_path):
        return [f"bail-cause gate: missing {scan_path} or {runbook_path}"]
    with open(scan_path) as f:
        causes = set(_BAIL_RE.findall(f.read()))
    with open(runbook_path) as f:
        documented = set(_RUNBOOK_CAUSE_RE.findall(f.read()))
    for cause in sorted(causes - documented):
        problems.append(
            f'_bail("{cause}") in block/device_scan.py has no row in the '
            f"runbook fallback-cause table (operations/runbook.md, "
            f'"Reading the read plane")')
    return problems


def default_registries():
    """Boot a `target=all` in-memory App and return its registries —
    the canonical "what does a full process register" answer for the
    CLI gate. Caller must App.shutdown() the returned app."""
    import tempfile

    from tempo_tpu.app import App
    from tempo_tpu.app.config import Config
    from tempo_tpu.obs.jaxruntime import RUNTIME

    tmp = tempfile.mkdtemp(prefix="tempo-obs-drift-")
    cfg = Config(target="all")
    cfg.storage.backend = "mem"
    cfg.storage.wal_path = os.path.join(tmp, "wal")
    cfg.generator.localblocks.data_dir = os.path.join(tmp, "lb")
    app = App(cfg)
    return [app.obs, RUNTIME], app


__all__ = ["referenced_metric_names", "registered_metric_names",
           "check_drift", "check_bail_causes", "default_registries",
           "METRIC_NAME_RE"]
