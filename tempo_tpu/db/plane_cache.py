"""Per-block device-plane cache: the product read fast path.

Backend blocks are immutable, which makes (tenant, block_id) a perfect
cache key: the first query against a block pays one full columnar read
(host ColumnViews per row group) and lazy device-column adoption
(`BlockScanPlane`); every later query runs its whole first pass — pushdown
predicates, time clip, row-group shard selection, and for metrics the
complete grid aggregation — as one fused device dispatch over the
resident block. This is the analog of the reference's parquet page cache
plus dictionary-page predicate pushdown (`tempodb/tempodb.go:481` Fetch
dispatch, `block_traceql.go:1031`), restructured around the economics of
an accelerator: upload once, dispatch per query, tiny D2H.

Eviction is LRU under a device-byte budget plus an entry-count bound; a
dead block (compacted away) is dropped explicitly by the poller hook in
`db/tempodb.py`.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Iterator, Optional, Sequence

import numpy as np

from tempo_tpu.block.device_scan import BlockScanPlane
from tempo_tpu.block.reader import BackendBlock
from tempo_tpu.traceql.conditions import FetchSpansRequest


class CachedBlock:
    """Host views + device plane for one immutable block."""

    def __init__(self, block: BackendBlock, mesh=None):
        from tempo_tpu.block.fetch import scan_views

        self.block = block
        self.views = [v for v, _ in scan_views(block, None)]
        self.plane = BlockScanPlane(self.views, mesh=mesh)
        # device path usage counters (tests + /metrics)
        self.device_scans = 0
        self.host_scans = 0
        try:
            md = block.parquet_file().metadata
            self._base_host_bytes = sum(
                md.row_group(i).total_byte_size
                for i in range(md.num_row_groups))
        except Exception:
            self._base_host_bytes = int(block.meta.size_bytes)

    @property
    def device_bytes(self) -> int:
        return self.plane.device_bytes

    @property
    def host_bytes(self) -> int:
        """Resident host estimate: decoded views (uncompressed parquet
        size) + the plane's adoption-side concatenated copies."""
        return self._base_host_bytes + self.plane.host_bytes

    def scan(self, req: Optional[FetchSpansRequest],
             row_groups: Optional[Sequence[int]] = None
             ) -> Iterator[tuple]:
        """Same contract as `fetch.scan_views`, served from the cache: the
        first pass runs on device when every predicate shape is supported,
        else falls back to the host mask per view."""
        from tempo_tpu.block.fetch import condition_mask, prefilter_is_noop
        from tempo_tpu.obs import querystats

        idxs = list(range(len(self.views)) if row_groups is None
                    else (i for i in row_groups
                          if 0 <= i < len(self.views)))
        # read-cost attribution for cache-served scans: each row-group
        # view the query examines charges its share of the block's
        # resident (uncompressed) size — warm queries inspect the same
        # data a cold scan would have read
        querystats.add(inspected_bytes=len(idxs) * (
            self._base_host_bytes // max(len(self.views), 1)))
        if req is None:
            for i in idxs:
                yield self.views[i], np.arange(self.views[i].n)
            return
        preds = [c for c in req.conditions if c.op is not None]
        cands = None
        if not prefilter_is_noop(req):
            m = self.plane.mask_async(
                preds, req.all_conditions,
                time_range=(req.start_ns, req.end_ns),
                row_groups=list(row_groups) if row_groups is not None
                else None)
            if m is not None:
                self.device_scans += 1
                cands = self.plane.split_mask(np.asarray(m))
        if cands is not None:
            for i in idxs:
                cand = cands[i]
                if len(cand) == 0 and req.all_conditions:
                    continue
                yield self.views[i], cand
            return
        self.host_scans += 1
        for i in idxs:
            view = self.views[i]
            mask = condition_mask(view, req)
            cand = np.flatnonzero(mask)
            if len(cand) == 0 and req.all_conditions:
                continue
            yield view, cand


class PlaneCache:
    """LRU of CachedBlocks bounded by device bytes, host bytes, and entry
    count (the device budget is the scarce resource; the host budget keeps
    pinned decoded views from growing to max_blocks full blocks)."""

    def __init__(self, budget_bytes: int = 1 << 30, max_blocks: int = 64,
                 host_budget_bytes: int = 4 << 30, mesh=None,
                 max_folds: int = 1024):
        self.budget_bytes = budget_bytes
        self.max_blocks = max_blocks
        self.host_budget_bytes = host_budget_bytes
        self.mesh = mesh              # multi-device planes (see BlockScanPlane)
        self._entries: "OrderedDict[tuple, CachedBlock]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        # sidecar-fold result cache: (tenant, block_id) → {window key →
        # job-level series}. Keyed by block so compaction eviction (drop/
        # drop_dead) can never leave a compacted-away block serving stale
        # folds; bounded by total cached window entries, LRU by block.
        self.max_folds = max_folds
        self._folds: "OrderedDict[tuple, dict]" = OrderedDict()
        self.fold_hits = 0
        self.fold_misses = 0

    def get(self, block: BackendBlock) -> CachedBlock:
        key = (block.meta.tenant_id, block.meta.block_id)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                # lazy adoption grows footprints AFTER insertion; re-check
                # the budgets on hits too, or a stable hit-only working
                # set would never trigger eviction
                self._evict_locked()
                return entry
        # build outside the lock (full-block read); a racing duplicate
        # build is wasted work, not a correctness problem — last one wins
        entry = CachedBlock(block, mesh=self.mesh)
        with self._lock:
            self.misses += 1
            self._entries[key] = entry
            self._evict_locked()
        return entry

    def peek(self, tenant: str, block_id: str) -> Optional[CachedBlock]:
        with self._lock:
            return self._entries.get((tenant, block_id))

    def drop(self, tenant: str, block_id: str) -> None:
        with self._lock:
            self._entries.pop((tenant, block_id), None)
            self._folds.pop((tenant, block_id), None)

    def drop_dead(self, tenant: str, live_block_ids: set) -> None:
        with self._lock:
            for key in [k for k in self._entries
                        if k[0] == tenant and k[1] not in live_block_ids]:
                del self._entries[key]
            for key in [k for k in self._folds
                        if k[0] == tenant and k[1] not in live_block_ids]:
                del self._folds[key]

    # -- sidecar-fold results (block/sidecar.py) ---------------------------

    def fold_get(self, tenant: str, block_id: str, fold_key) -> "list | None":
        with self._lock:
            per_block = self._folds.get((tenant, block_id))
            got = None if per_block is None else per_block.get(fold_key)
            if got is None:
                self.fold_misses += 1
                return None
            self._folds.move_to_end((tenant, block_id))
            self.fold_hits += 1
            return got

    def fold_put(self, tenant: str, block_id: str, fold_key,
                 series: list) -> None:
        with self._lock:
            self._folds.setdefault((tenant, block_id), {})[fold_key] = series
            self._folds.move_to_end((tenant, block_id))
            while (sum(len(d) for d in self._folds.values()) > self.max_folds
                   and len(self._folds) > 1):
                self._folds.popitem(last=False)

    def _evict_locked(self) -> None:
        while len(self._entries) > self.max_blocks:
            self._entries.popitem(last=False)
        total = sum(e.device_bytes for e in self._entries.values())
        host = sum(e.host_bytes for e in self._entries.values())
        while ((total > self.budget_bytes or host > self.host_budget_bytes)
               and len(self._entries) > 1):
            _, gone = self._entries.popitem(last=False)
            total -= gone.device_bytes
            host -= gone.host_bytes

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "device_bytes": sum(e.device_bytes
                                    for e in self._entries.values()),
                "host_bytes": sum(e.host_bytes
                                  for e in self._entries.values()),
                "device_budget_bytes": self.budget_bytes,
                "host_budget_bytes": self.host_budget_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "fold_entries": sum(len(d) for d in self._folds.values()),
                "fold_hits": self.fold_hits,
                "fold_misses": self.fold_misses,
            }
