"""In-memory per-tenant blocklist — analog of `tempodb/blocklist/list.go`.

The queryable snapshot of "which blocks exist per tenant", rebuilt by the
poller and adjusted in-place by the compactor between polls (ApplyPollResults
/ Update semantics), so queries never see a block both live and compacted.
"""

from __future__ import annotations

import threading

from tempo_tpu.backend.meta import BlockMeta, CompactedBlockMeta


class List:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metas: dict[str, list[BlockMeta]] = {}
        self._compacted: dict[str, list[CompactedBlockMeta]] = {}

    def tenants(self) -> list[str]:
        with self._lock:
            return sorted(set(self._metas) | set(self._compacted))

    def metas(self, tenant: str) -> list[BlockMeta]:
        with self._lock:
            return list(self._metas.get(tenant, ()))

    def compacted_metas(self, tenant: str) -> list[CompactedBlockMeta]:
        with self._lock:
            return list(self._compacted.get(tenant, ()))

    def apply_poll_results(self, metas: dict[str, list[BlockMeta]],
                           compacted: dict[str, list[CompactedBlockMeta]]) -> None:
        with self._lock:
            self._metas = {t: list(v) for t, v in metas.items()}
            self._compacted = {t: list(v) for t, v in compacted.items()}

    def update(self, tenant: str, add: list[BlockMeta] = (),
               remove: list[BlockMeta] = (),
               compacted_add: list[CompactedBlockMeta] = (),
               compacted_remove: list[CompactedBlockMeta] = ()) -> None:
        """Compactor's in-place adjustment between polls (`list.go` Update)."""
        with self._lock:
            cur = self._metas.setdefault(tenant, [])
            rm = {m.block_id for m in remove}
            cur[:] = [m for m in cur if m.block_id not in rm]
            have = {m.block_id for m in cur}
            cur.extend(m for m in add if m.block_id not in have)
            ccur = self._compacted.setdefault(tenant, [])
            crm = {c.meta.block_id for c in compacted_remove}
            ccur[:] = [c for c in ccur if c.meta.block_id not in crm]
            chave = {c.meta.block_id for c in ccur}
            ccur.extend(c for c in compacted_add if c.meta.block_id not in chave)
