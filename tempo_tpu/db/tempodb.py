"""tempodb facade: Reader/Writer/Compactor over backend + blocks.

Analog of `tempodb/tempodb.go:74-116` and its loops: block write (ingester
flush target), trace lookup fan-out with time/shard pruning (`Find`
`tempodb.go:624` includeBlock), blocklist polling (`EnablePolling`
`tempodb.go:551`), compaction + retention loops (`EnableCompaction`
`tempodb.go:518`, `compactor.go:79-185`). Loops run as explicit `*_once`
ticks (tests) or daemon threads (services).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Iterable, Sequence

from tempo_tpu.backend import meta as bm
from tempo_tpu.backend.raw import RawReader, RawWriter
from tempo_tpu.block.reader import BackendBlock
from tempo_tpu.block.writer import write_block
from tempo_tpu.db import compactor as comp
from tempo_tpu.db.blocklist import List
from tempo_tpu.db.pool import Pool
from tempo_tpu.db.poller import Poller, PollerConfig
from tempo_tpu.model.combine import combine_spans
from tempo_tpu.obs import Registry
from tempo_tpu.obs import querystats

log = logging.getLogger("tempo_tpu.db")


@dataclasses.dataclass
class TempoDBConfig:
    poller: PollerConfig = dataclasses.field(default_factory=PollerConfig)
    compactor: comp.CompactorConfig = dataclasses.field(default_factory=comp.CompactorConfig)
    pool_workers: int = 30
    dedicated_columns: tuple = ()
    row_group_rows: int = 50_000
    # device read plane (block/device_scan.py): per-block resident column
    # cache + fused first pass; LRU under a device-byte budget
    device_plane: bool = True
    plane_budget_bytes: int = 1 << 30
    plane_max_blocks: int = 64
    plane_host_budget_bytes: int = 4 << 30
    # optional jax Mesh: fused plane kernels run sharded over its 'data'
    # axis (XLA SPMD inserts the grid reduce) — the multi-chip read path
    plane_mesh: object = None


class TempoDB:
    def __init__(self, r: RawReader, w: RawWriter,
                 cfg: TempoDBConfig | None = None,
                 registry: Registry | None = None,
                 now: Callable[[], float] = time.time):
        self.r = r
        self.w = w
        self.cfg = cfg or TempoDBConfig()
        self.now = now
        self.blocklist = List()
        self.poller = Poller(r, w, self.cfg.poller, now=now)
        self.pool = Pool(self.cfg.pool_workers)
        self.selector = comp.TimeWindowBlockSelector(self.cfg.compactor)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._block_cache: dict[tuple[str, str], BackendBlock] = {}
        self.planes = None
        if self.cfg.device_plane:
            from tempo_tpu.db.plane_cache import PlaneCache

            self.planes = PlaneCache(self.cfg.plane_budget_bytes,
                                     self.cfg.plane_max_blocks,
                                     self.cfg.plane_host_budget_bytes,
                                     mesh=self.cfg.plane_mesh)
        # read-plane routing counters: how many block scans took the fused
        # device path vs the host engine (tests + /metrics)
        self.plane_stats = {"fused_metric_blocks": 0, "host_metric_blocks": 0}
        # device cold tier: compaction + sidecar-fold counters (tests,
        # /metrics, and the bench `coldtier` stage all read these)
        self.compaction_stats = {
            "blocks": 0,             # input blocks through the device route
            "spans": 0,              # spans merged/deduped on device
            "device_seconds": 0.0,   # wall time inside the merge dispatch
            "sidecars_written": 0,   # compaction outputs + backfills
            "sidecar_folds": 0,      # historical blocks answered by folds
            "sidecar_fallbacks": 0,  # fold-eligible blocks that re-scanned
        }
        self._device_compact_warned = False
        self.obs = registry if registry is not None else Registry()
        self._register_obs(self.obs)

    def _register_obs(self, reg: Registry) -> None:
        reg.counter_func(
            "tempo_read_plane_fused_metric_blocks_total",
            lambda: [((), self.plane_stats["fused_metric_blocks"])],
            help="Metrics blocks answered by the fused device plane")
        reg.counter_func(
            "tempo_read_plane_host_metric_blocks_total",
            lambda: [((), self.plane_stats["host_metric_blocks"])],
            help="Metrics blocks answered by the host engine")
        reg.counter_func(
            "tempo_read_plane_fallback_total",
            lambda: [((k[len("fallback_"):],), v)
                     for k, v in self.plane_stats.items()
                     if k.startswith("fallback_")],
            help="Host-engine fallbacks by cause (query_shape, predicate, "
                 "group, value, grid_size, window, times, disabled)",
            labels=("cause",))

        def plane_stat(key):
            def fn():
                if self.planes is None:
                    return []
                return [((), self.planes.stats()[key])]
            return fn

        for key in ("entries", "device_bytes", "host_bytes",
                    "device_budget_bytes", "host_budget_bytes"):
            reg.gauge_func(f"tempo_read_plane_cache_{key}", plane_stat(key),
                           help=f"Device read-plane cache {key.replace('_', ' ')}")
        reg.counter_func("tempo_read_plane_cache_hits_total",
                         plane_stat("hits"),
                         help="Device read-plane cache hits")
        reg.counter_func("tempo_read_plane_cache_misses_total",
                         plane_stat("misses"),
                         help="Device read-plane cache misses")
        self.compaction_duration = reg.histogram(
            "tempo_compactor_cycle_duration_seconds",
            "One per-tenant compaction sweep (selection + block rewrites)")

        def comp_stat(key):
            return lambda: [((), self.compaction_stats[key])]

        for key, hlp in (
                ("blocks", "Input blocks compacted via the device route"),
                ("spans", "Spans merged/deduped/re-sorted on device"),
                ("device_seconds",
                 "Wall seconds inside device compaction-merge dispatches"),
                ("sidecars_written",
                 "Sketch sidecars written (compaction outputs, block cuts, "
                 "backfills)"),
                ("sidecar_folds",
                 "Historical query blocks answered by sidecar folds"),
                ("sidecar_fallbacks",
                 "Fold-eligible blocks that fell back to the host scan")):
            reg.counter_func(f"tempo_compaction_{key}_total", comp_stat(key),
                             help=hlp)

    # -- writer ------------------------------------------------------------

    def write_block(self, tenant: str, traces: Iterable[tuple[bytes, list[dict]]],
                    *, block_id: str | None = None,
                    replication_factor: int = 3) -> bm.BlockMeta:
        meta = write_block(
            self.w, tenant, traces, block_id=block_id,
            dedicated_columns=list(self.cfg.dedicated_columns),
            row_group_rows=self.cfg.row_group_rows,
            replication_factor=replication_factor)
        self.blocklist.update(tenant, add=[meta])
        return meta

    # -- reader ------------------------------------------------------------

    def backend_block(self, meta: bm.BlockMeta) -> BackendBlock:
        key = (meta.tenant_id, meta.block_id)
        b = self._block_cache.get(key)
        if b is None or b.meta.size_bytes != meta.size_bytes:
            # size change means the object was rewritten; otherwise refresh
            # the meta reference and keep the parsed parquet footer
            b = self._block_cache[key] = BackendBlock(self.r, meta)
        else:
            b.meta = meta
        return b

    def _evict_dead_blocks(self, tenant: str) -> None:
        live = {m.block_id for m in self.blocklist.metas(tenant)}
        for key in [k for k in self._block_cache
                    if k[0] == tenant and k[1] not in live]:
            del self._block_cache[key]
        if self.planes is not None:
            self.planes.drop_dead(tenant, live)

    def scan_source(self, meta: bm.BlockMeta, req,
                    row_groups: Sequence[int] | None = None,
                    cached_only: bool = False):
        """(view, candidate_rows) stream for one block: the plane cache's
        fused device first pass when enabled, else a direct parquet scan.
        The shared read path behind search, query_range, and tag
        autocomplete. `cached_only` serves from the cache ONLY when the
        block is already resident — metadata endpoints must not pay
        full-block reads (or thrash the LRU) for a miss when a projected
        one-column scan suffices."""
        from tempo_tpu.block.fetch import scan_views

        if self.planes is not None:
            if cached_only:
                entry = self.planes.peek(meta.tenant_id, meta.block_id)
                if entry is not None:
                    return entry.scan(req, row_groups)
            else:
                return self.planes.get(self.backend_block(meta)).scan(
                    req, row_groups)
        return scan_views(self.backend_block(meta), req,
                          row_groups=row_groups)

    def blocks(self, tenant: str, start_s: float | None = None,
               end_s: float | None = None,
               shard_bounds: tuple[bytes, bytes] | None = None) -> list[bm.BlockMeta]:
        """Blocklist pruned by time overlap and trace-id shard bounds
        (includeBlock `tempodb.go:624`)."""
        lo = shard_bounds[0].hex() if shard_bounds else None
        hi = shard_bounds[1].hex() if shard_bounds else None
        out = []
        metas = self.blocklist.metas(tenant)
        for m in metas:
            if start_s is not None and m.end_time < start_s:
                continue
            if end_s is not None and m.start_time > end_s:
                continue
            if lo is not None and m.max_trace_id and m.max_trace_id < lo:
                continue
            if hi is not None and m.min_trace_id and m.min_trace_id > hi:
                continue
            out.append(m)
        # time/shard prunes into the ambient query scope (no-op outside a
        # request — poll and compaction loops call this too)
        querystats.add(blocks_skipped=len(metas) - len(out))
        return out

    def find_trace_by_id(self, tenant: str, trace_id: bytes,
                         start_s: float | None = None,
                         end_s: float | None = None) -> list[dict] | None:
        """Fan out across candidate blocks on the worker pool, combine spans
        (RF dedup via combine_spans)."""
        metas = self.blocks(tenant, start_s, end_s)
        if not metas:
            return None
        results, errors = self.pool.run_jobs(
            metas, lambda m: self.backend_block(m).find_trace_by_id(trace_id))
        if errors and not results:
            raise errors[0]
        found = [spans for spans in results if spans]
        return combine_spans(*found) if found else None

    def search(self, tenant: str, query: str, *, limit: int = 20,
               start_s: float | None = None, end_s: float | None = None,
               metas: Sequence[bm.BlockMeta] | None = None,
               row_groups: Sequence[int] | None = None):
        """TraceQL search over backend blocks (`tempodb.Search/Fetch`
        `tempodb.go:368,481`): compile once, stream row-group views from
        every candidate block through the engine. The first pass rides the
        device plane cache when enabled (one fused dispatch per block)."""
        from tempo_tpu.traceql.engine import compile_query, execute_search

        q, req = compile_query(query,
                               int((start_s or 0) * 1e9), int((end_s or 0) * 1e9))
        if metas is None:
            metas = self.blocks(tenant, start_s, end_s)
        views = (v for m in metas
                 for v in self.scan_source(m, req, row_groups))
        return execute_search(q, views, limit=limit,
                              start_ns=int((start_s or 0) * 1e9),
                              end_ns=int((end_s or 0) * 1e9))

    def query_range(self, tenant: str, req, *,
                    metas: Sequence[bm.BlockMeta] | None = None,
                    row_groups: Sequence[int] | None = None,
                    clip_start_ns: int | None = None,
                    clip_end_ns: int | None = None):
        """TraceQL metrics over backend blocks: the raw MetricsEvaluator
        path (`engine_metrics.go:802`); returns job-level TimeSeries for a
        frontend combiner (or final series when used standalone). The clip
        bounds restrict observation without changing the step grid.

        Blocks whose query shape the device plane supports run the WHOLE
        aggregation — mask, clip, step bucketing, group-by, metric scatter,
        including the log2 histogram axis behind quantile_over_time — as
        one fused dispatch per resident block; unsupported blocks/shapes
        fall back to the host engine, and both merge through the job-level
        series combiner (sums/min/max — the same tensor-add combine the
        frontend applies across jobs)."""
        from tempo_tpu.traceql import ast as A
        from tempo_tpu.traceql.engine import compile_query
        from tempo_tpu.traceql.engine_metrics import (MetricsEvaluator,
                                                      SeriesCombiner,
                                                      grid_series)

        _, freq = compile_query(req.query, req.start_ns, req.end_ns)
        if metas is None:
            metas = self.blocks(tenant, req.start_ns / 1e9, req.end_ns / 1e9)
        ev = MetricsEvaluator(req, clip_start_ns, clip_end_ns, batched=True)
        # the fused path is exact only when the pushdown IS the filter:
        # a single filter pipeline that is pure-AND (all_conditions, the
        # optimize() precondition of engine_metrics.go:885) or a pure OR
        # of pushed compares (the OR mask of exact terms is exact —
        # round 5), and no compare() stage
        fusable = (self.planes is not None
                   and (ev.fetch_req.all_conditions
                        or ev.fetch_req.pure_disjunction)
                   and all(isinstance(s, A.SpansetFilter) for s in ev.q.stages)
                   and ev.m.kind != A.MetricsKind.COMPARE)
        preds = [c for c in ev.fetch_req.conditions if c.op is not None]
        # phase 1: LAUNCH every supported block's fused grid (async — the
        # dispatches pipeline their device round trips) and run the host
        # engine over unsupported blocks meanwhile
        handles: list = []
        fused_blocks: list = []
        fused_parts: list = []
        MAX_INFLIGHT = 8   # bound live device grids (hist grids are big)

        from tempo_tpu.obs.jaxruntime import kernel_timer

        def drain(to: int) -> None:
            while len(handles) > to:
                t0 = time.perf_counter_ns()
                with kernel_timer("plane_metrics_grid"), \
                        querystats.stage("device_scan"):
                    labels, main, cnt, vcnt = handles.pop(0).fetch()
                querystats.add(kernel_wall_ns=time.perf_counter_ns() - t0)
                fused_parts.append(grid_series(ev.m, labels, main, cnt,
                                               vcnt, moments=ev._moments))

        for m in metas:
            handle = cb = bail_cause = None
            if fusable:
                cb = self.planes.get(self.backend_block(m))
                handle, bail_cause = cb.plane.metrics_grid(
                    ev.m, preds, ev.fetch_req.all_conditions,
                    req.start_ns, req.end_ns, req.step_ns,
                    clip_start_ns, clip_end_ns, row_groups,
                    moments=ev._moments)
            if handle is not None:
                self.plane_stats["fused_metric_blocks"] += 1
                # the fused path never surfaces row bytes to the host —
                # charge the block slice's stored size as inspected
                n_rg = max(m.row_group_count, 1)
                frac = (len(row_groups) / n_rg) if row_groups else 1.0
                querystats.add(inspected_bytes=int(m.size_bytes * frac))
                handles.append(handle)
                fused_blocks.append(cb)
                drain(MAX_INFLIGHT - 1)   # pipeline, bounded residency
            else:
                self.plane_stats["host_metric_blocks"] += 1
                # distinguish WHY (round-4 weak #4: a float-attr workload
                # silently lost the fused win with no visible cause). The
                # cause rides metrics_grid's RETURN — never read back off
                # shared plane state, where a concurrent query bailing on
                # the same cached plane could overwrite it (ADVICE r5 #2)
                cause = (bail_cause or "unknown") if fusable \
                    else ("disabled" if self.planes is None
                          else "query_shape")
                k = f"fallback_{cause}"
                self.plane_stats[k] = self.plane_stats.get(k, 0) + 1
                for view, cand in self.scan_source(m, freq, row_groups):
                    if len(cand):
                        ev.observe(view)
        drain(0)
        if not fused_parts:
            return ev.results()
        comb = SeriesCombiner(ev.m.kind, req.n_steps)
        comb.add_all(ev.results())
        for part in fused_parts:
            comb.add_all(part)
        out = list(comb.series.values())
        self._fused_exemplars(out, ev, fused_blocks, req)
        return out

    def _fused_exemplars(self, series, ev, fused_blocks, req) -> None:
        """Best-effort exemplars for the fused path (the grid kernel keeps
        no row identities): sample a few matching rows from the first
        cached view and attach trace-id exemplars to their group's series,
        like `MetricsEvaluator._note_exemplars`."""
        import numpy as np

        from tempo_tpu.block.fetch import condition_mask
        from tempo_tpu.traceql.engine_metrics import _fmt_label
        from tempo_tpu.traceql.eval import eval_expr

        if req.exemplars <= 0 or not fused_blocks:
            return
        budget = req.exemplars - sum(len(s.exemplars) for s in series)
        if budget <= 0:
            return
        cb = fused_blocks[0]
        if not cb.views:
            return
        view = cb.views[0]
        tid = view.col("trace:id")
        st = view.col("__startTime")
        if tid is None or st is None:
            return
        # sample only rows inside the step window AND the observation clip,
        # like the host path (observe() filters before _note_exemplars)
        mask = condition_mask(view, ev.fetch_req)
        ts = st.values
        mask = mask & (ts >= ev.clip_start_ns) & (ts < ev.clip_end_ns)
        rows = np.flatnonzero(mask)[:min(8, budget)]
        if len(rows) == 0:
            return
        gcol = eval_expr(view, ev.m.by[0]) if ev.m.by else None
        gname = str(ev.m.by[0]) if ev.m.by else None
        dur = view.col("duration")
        by_group: dict = {}
        for s in series:
            d = dict(s.labels)
            key = d.get(gname) if gname is not None else ""
            by_group.setdefault(key, s)
        for r in rows:
            if gcol is not None:
                if not gcol.exists[r]:
                    continue
                key = _fmt_label(gcol.values[r], gcol.t)
            else:
                key = ""
            target = by_group.get(key)
            if target is None or len(target.exemplars) >= 2:
                continue
            target.exemplars.append({
                "traceId": str(tid.values[r]),
                "value": float(dur.values[r]) if dur is not None else 0.0,
                "timestampMs": int(st.values[r] / 1e6),
            })

    # -- polling -----------------------------------------------------------

    def poll_now(self) -> None:
        metas, compacted = self.poller.do()
        self.blocklist.apply_poll_results(metas, compacted)
        for tenant in {k[0] for k in self._block_cache}:
            self._evict_dead_blocks(tenant)

    def enable_polling(self, interval_s: float | None = None) -> None:
        self._spawn(self._poll_loop, interval_s or self.cfg.poller.poll_interval_s)

    # -- compaction / retention -------------------------------------------

    def compact_tenant_once(self, tenant: str,
                            owns: Callable[[str], bool] = lambda key: True) -> int:
        """One compaction sweep for a tenant; `owns` is the ring-ownership
        predicate keyed like `modules/compactor/compactor.go:190`."""
        t0 = time.perf_counter()
        metas = self.blocklist.metas(tenant)
        jobs = self.selector.blocks_to_compact(metas)
        done = 0
        for group in jobs:
            key = f"{tenant}-{group[0].block_id}"
            if not owns(key):
                continue
            out = self._compact_group(tenant, group)
            self.blocklist.update(
                tenant, add=out, remove=group,
                compacted_add=[bm.CompactedBlockMeta(m, self.now()) for m in group])
            # compacted-away inputs must not serve stale cached state:
            # drop their parquet handles, device planes, AND any cached
            # sidecar-fold results immediately (not at the next poll)
            for m in group:
                self._block_cache.pop((tenant, m.block_id), None)
                if self.planes is not None:
                    self.planes.drop(tenant, m.block_id)
            done += 1
        self.compaction_duration.observe(time.perf_counter() - t0)
        return done

    def _compact_group(self, tenant: str, group: list) -> list:
        """Device-route compaction of one input group, host fallback on
        any decode/schema surprise (warn-once)."""
        cfg = self.cfg.compactor
        if cfg.device:
            try:
                return comp.compact_device(
                    self.r, self.w, tenant, group, cfg,
                    stats=self.compaction_stats,
                    dispatch=self._compaction_dispatch(tenant))
            except Exception:
                if not self._device_compact_warned:
                    self._device_compact_warned = True
                    log.exception(
                        "device compaction failed; host fallback "
                        "(tenant=%s, logged once)", tenant)
        return comp.compact(self.r, self.w, tenant, group, cfg)

    def _compaction_dispatch(self, tenant: str):
        """Compaction-class admission to the shared device scheduler:
        merge dispatches queue BEHIND ingest/query work (and behind the
        anti-starvation floor, sched.compaction_min_share)."""
        from tempo_tpu import sched

        return lambda fn: sched.run(fn, kernel="compaction_merge",
                                    priority=sched.PRIO_COMPACTION,
                                    tenant=tenant)

    # -- sketch sidecars: historical folds + backfill ----------------------

    def sidecar_plan(self, query: str):
        """FoldPlan when `query` is answerable from sidecars, else None."""
        from tempo_tpu.block import sidecar as sdc

        return sdc.eligible_plan(query)

    def sidecar_series(self, tenant: str, req, meta, plan,
                       clip_end_ns: int | None = None):
        """One historical block answered from its sidecar: job-level
        TimeSeries for the frontend combiner, or None → caller re-scans
        (missing/unreadable/domain-mismatched sidecar). Fold results ride
        the plane cache keyed by (block, query window) and are evicted
        with the block on compaction."""
        from tempo_tpu.block import sidecar as sdc

        fkey = (req.query, req.start_ns, req.end_ns, req.step_ns,
                clip_end_ns or 0)
        if self.planes is not None:
            hit = self.planes.fold_get(tenant, meta.block_id, fkey)
            if hit is not None:
                self.compaction_stats["sidecar_folds"] += 1
                return hit
        sc = sdc.read_sidecar(self.r, tenant, meta.block_id)
        series = None if sc is None else sdc.fold_series(
            sc, meta, req, plan, clip_end_ns)
        if series is None:
            self.compaction_stats["sidecar_fallbacks"] += 1
            return None
        self.compaction_stats["sidecar_folds"] += 1
        if self.planes is not None:
            self.planes.fold_put(tenant, meta.block_id, fkey, series)
        return series

    def backfill_sidecars_once(self, tenant: str,
                               limit: int | None = None) -> int:
        """Attach sidecars to up to `limit` existing blocks without one
        (low-priority compaction-class work; the compactor service calls
        this each sweep so history converges to fold-served)."""
        cfg = self.cfg.compactor
        if limit is None:
            limit = cfg.backfill_sidecars
        if limit <= 0 or not cfg.sidecars:
            return 0
        run = self._compaction_dispatch(tenant)
        done = 0
        for m in self.blocklist.metas(tenant):
            if done >= limit:
                break
            if m.sidecar:
                continue
            if run(lambda m=m: comp.backfill_sidecar(
                    self.r, self.w, tenant, m, self.compaction_stats)):
                done += 1
        return done

    def retention_once(self, tenant: str) -> tuple[list, list]:
        marked, deleted = comp.do_retention(
            self.r, self.w, tenant, self.blocklist.metas(tenant),
            self.blocklist.compacted_metas(tenant), self.cfg.compactor, self.now)
        self.blocklist.update(
            tenant, remove=marked,
            compacted_add=[bm.CompactedBlockMeta(m, self.now()) for m in marked],
            compacted_remove=[c for c in self.blocklist.compacted_metas(tenant)
                              if c.meta.block_id in set(deleted)])
        return marked, deleted

    def enable_compaction(self, interval_s: float = 30.0,
                          owns: Callable[[str], bool] = lambda key: True) -> None:
        self._spawn(self._compaction_loop, interval_s, owns)

    # -- loops -------------------------------------------------------------

    def _spawn(self, fn, *args) -> None:
        t = threading.Thread(target=fn, args=args, daemon=True)
        t.start()
        self._threads.append(t)

    def _poll_loop(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                self.poll_now()
            except Exception:
                log.exception("poll cycle failed")

    def _compaction_loop(self, interval_s: float, owns) -> None:
        while not self._stop.wait(interval_s):
            for tenant in self.blocklist.tenants():
                try:
                    self.compact_tenant_once(tenant, owns)
                    self.retention_once(tenant)
                except Exception:
                    log.exception("compaction cycle failed (tenant=%s)", tenant)

    def shutdown(self) -> None:
        self._stop.set()
        self.pool.shutdown()
