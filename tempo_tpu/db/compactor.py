"""Compaction: time-window block selection + trace-merging rewrites.

Analog of `tempodb/compactor.go:79-185` + `compaction_block_selector.go` +
`vparquet4/compactor.go`: pick same-level blocks in the same time window,
k-way merge their trace groups (dedup spans per trace id like
`pkg/model/trace/combine.go`), emit size-targeted output blocks one level
up, then mark inputs compacted. Ring ownership is a pluggable `owns`
predicate (`modules/compactor/compactor.go:190`).
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
from typing import Callable, Iterable, Iterator

from tempo_tpu.backend import meta as bm
from tempo_tpu.backend.raw import RawReader, RawWriter
from tempo_tpu.block.reader import BackendBlock, _rows_to_spans
from tempo_tpu.block.writer import write_block
from tempo_tpu.model.combine import combine_spans

import numpy as np

log = logging.getLogger("tempo_tpu.db.compactor")


@dataclasses.dataclass
class CompactorConfig:
    """Subset of `tempodb/config.go` CompactorConfig."""

    max_compaction_window_s: float = 3600.0
    min_inputs: int = 2
    max_inputs: int = 4               # MaxCompactionObjects guard analog
    max_block_objects: int = 1_000_000
    max_block_bytes: int = 100 << 30
    compacted_grace_s: float = 3600.0  # retention grace for compacted markers
    retention_s: float = 14 * 86400.0


class TimeWindowBlockSelector:
    """Group candidate blocks by (level, time window); oldest window first
    (`compaction_block_selector.go:29,119`)."""

    def __init__(self, cfg: CompactorConfig):
        self.cfg = cfg

    def blocks_to_compact(self, metas: list[bm.BlockMeta]) -> list[list[bm.BlockMeta]]:
        win = self.cfg.max_compaction_window_s
        groups: dict[tuple[int, int], list[bm.BlockMeta]] = {}
        for m in metas:
            groups.setdefault((m.compaction_level, int(m.end_time // win)), []).append(m)
        out = []
        for (_lvl, _w), ms in sorted(groups.items(), key=lambda kv: (kv[0][1], kv[0][0])):
            ms.sort(key=lambda m: m.size_bytes)
            while len(ms) >= self.cfg.min_inputs:
                take = ms[: self.cfg.max_inputs]
                ms = ms[self.cfg.max_inputs:]
                if len(take) >= self.cfg.min_inputs:
                    out.append(take)
        return out


def iter_trace_groups(block: BackendBlock) -> Iterator[tuple[bytes, list[dict]]]:
    """Stream (trace_id, spans) in trace-id order from one block; rows of a
    trace are contiguous, so groups fall out of row-group scans."""
    pending_tid: bytes | None = None
    pending: list[dict] = []
    pf = block.parquet_file()
    for rg in range(pf.num_row_groups):
        tbl = pf.read_row_group(rg)
        spans = _rows_to_spans(tbl, np.arange(tbl.num_rows))
        for s in spans:
            tid = bytes(s["trace_id"])
            if tid != pending_tid:
                if pending_tid is not None:
                    yield pending_tid, pending
                pending_tid, pending = tid, []
            pending.append(s)
    if pending_tid is not None:
        yield pending_tid, pending


def merge_blocks(blocks: Iterable[BackendBlock]) -> Iterator[tuple[bytes, list[dict]]]:
    """K-way merge by trace id with span dedup across blocks."""
    iters = [iter_trace_groups(b) for b in blocks]
    merged = heapq.merge(*iters, key=lambda g: g[0])
    cur_tid: bytes | None = None
    cur_lists: list[list[dict]] = []
    for tid, spans in merged:
        if tid != cur_tid:
            if cur_tid is not None:
                yield cur_tid, combine_spans(*cur_lists)
            cur_tid, cur_lists = tid, []
        cur_lists.append(spans)
    if cur_tid is not None:
        yield cur_tid, combine_spans(*cur_lists)


def compact(r: RawReader, w: RawWriter, tenant: str,
            inputs: list[bm.BlockMeta], cfg: CompactorConfig) -> list[bm.BlockMeta]:
    """Compact one input group → output metas (inputs marked compacted)."""
    blocks = [BackendBlock(r, m) for m in inputs]
    level = max(m.compaction_level for m in inputs) + 1
    ded = inputs[0].dedicated_columns
    out_metas: list[bm.BlockMeta] = []
    batch: list[tuple[bytes, list[dict]]] = []
    nspans = 0
    ntraces = 0
    est_bytes_per_span = max(
        sum(m.size_bytes for m in inputs) // max(sum(m.total_spans for m in inputs), 1), 1)

    def flush():
        nonlocal batch, nspans, ntraces
        if not batch:
            return
        meta = write_block(w, tenant, batch, dedicated_columns=ded,
                           compaction_level=level,
                           replication_factor=inputs[0].replication_factor)
        out_metas.append(meta)
        batch, nspans, ntraces = [], 0, 0

    for tid, spans in merge_blocks(blocks):
        batch.append((tid, spans))
        nspans += len(spans)
        ntraces += 1
        if (ntraces >= cfg.max_block_objects
                or nspans * est_bytes_per_span >= cfg.max_block_bytes):
            flush()
    flush()
    for m in inputs:
        bm.mark_block_compacted(r, w, m.block_id, tenant)
    log.info("compacted %d blocks -> %d (tenant=%s level=%d)",
             len(inputs), len(out_metas), tenant, level)
    return out_metas


def do_retention(r: RawReader, w: RawWriter, tenant: str,
                 metas: list[bm.BlockMeta], compacted: list[bm.CompactedBlockMeta],
                 cfg: CompactorConfig, now: Callable[[], float]) -> tuple[list, list]:
    """Mark over-retention live blocks compacted; delete compacted blocks
    past the grace period (`tempodb/retention.go:17-113`). Returns
    (marked_metas, deleted_block_ids)."""
    marked = []
    deleted = []
    cutoff = now() - cfg.retention_s
    for m in metas:
        if m.end_time < cutoff:
            bm.mark_block_compacted(r, w, m.block_id, tenant)
            marked.append(m)
    grace = now() - cfg.compacted_grace_s
    for c in compacted:
        if c.compacted_time < grace:
            bm.clear_block(w, c.meta.block_id, tenant)
            deleted.append(c.meta.block_id)
    return marked, deleted
