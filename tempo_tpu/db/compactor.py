"""Compaction: time-window block selection + trace-merging rewrites.

Analog of `tempodb/compactor.go:79-185` + `compaction_block_selector.go` +
`vparquet4/compactor.go`: pick same-level blocks in the same time window,
k-way merge their trace groups (dedup spans per trace id like
`pkg/model/trace/combine.go`), emit size-targeted output blocks one level
up, then mark inputs compacted. Ring ownership is a pluggable `owns`
predicate (`modules/compactor/compactor.go:190`).
"""

from __future__ import annotations

import dataclasses
import heapq
import logging
import time
from typing import Callable, Iterable, Iterator

from tempo_tpu.backend import meta as bm
from tempo_tpu.backend.raw import RawReader, RawWriter
from tempo_tpu.block.reader import BackendBlock, _rows_to_spans
from tempo_tpu.block.writer import write_block, write_block_from_table
from tempo_tpu.model.combine import combine_spans

import numpy as np

log = logging.getLogger("tempo_tpu.db.compactor")


@dataclasses.dataclass
class CompactorConfig:
    """Subset of `tempodb/config.go` CompactorConfig."""

    max_compaction_window_s: float = 3600.0
    min_inputs: int = 2
    max_inputs: int = 4               # MaxCompactionObjects guard analog
    max_block_objects: int = 1_000_000
    max_block_bytes: int = 100 << 30
    compacted_grace_s: float = 3600.0  # retention grace for compacted markers
    retention_s: float = 14 * 86400.0
    # device cold tier (runbook "Compacting on device"): merge/dedup/
    # re-sort input blocks on device (`ops/compact.py`, one columnar
    # decode per input) instead of the host heapq merge; any failure
    # falls back to the host path for that group, warn-once
    device: bool = True
    # emit a sketch sidecar (block/sidecar.py) next to every compaction
    # output — the historical-fold tier's per-block summary
    sidecars: bool = True
    # compactor sweeps also backfill sidecars for pre-existing blocks
    # (low-priority compaction-class work), this many per tenant sweep
    backfill_sidecars: int = 2


class TimeWindowBlockSelector:
    """Group candidate blocks by (level, time window); oldest window first
    (`compaction_block_selector.go:29,119`)."""

    def __init__(self, cfg: CompactorConfig):
        self.cfg = cfg

    def blocks_to_compact(self, metas: list[bm.BlockMeta]) -> list[list[bm.BlockMeta]]:
        win = self.cfg.max_compaction_window_s
        groups: dict[tuple[int, int], list[bm.BlockMeta]] = {}
        for m in metas:
            groups.setdefault((m.compaction_level, int(m.end_time // win)), []).append(m)
        out = []
        for (_lvl, _w), ms in sorted(groups.items(), key=lambda kv: (kv[0][1], kv[0][0])):
            ms.sort(key=lambda m: m.size_bytes)
            while len(ms) >= self.cfg.min_inputs:
                take = ms[: self.cfg.max_inputs]
                ms = ms[self.cfg.max_inputs:]
                if len(take) >= self.cfg.min_inputs:
                    out.append(take)
        return out


def iter_trace_groups(block: BackendBlock) -> Iterator[tuple[bytes, list[dict]]]:
    """Stream (trace_id, spans) in trace-id order from one block; rows of a
    trace are contiguous, so groups fall out of row-group scans."""
    pending_tid: bytes | None = None
    pending: list[dict] = []
    pf = block.parquet_file()
    for rg in range(pf.num_row_groups):
        tbl = pf.read_row_group(rg)
        spans = _rows_to_spans(tbl, np.arange(tbl.num_rows))
        for s in spans:
            tid = bytes(s["trace_id"])
            if tid != pending_tid:
                if pending_tid is not None:
                    yield pending_tid, pending
                pending_tid, pending = tid, []
            pending.append(s)
    if pending_tid is not None:
        yield pending_tid, pending


def merge_blocks(blocks: Iterable[BackendBlock]) -> Iterator[tuple[bytes, list[dict]]]:
    """K-way merge by trace id with span dedup across blocks."""
    iters = [iter_trace_groups(b) for b in blocks]
    merged = heapq.merge(*iters, key=lambda g: g[0])
    cur_tid: bytes | None = None
    cur_lists: list[list[dict]] = []
    for tid, spans in merged:
        if tid != cur_tid:
            if cur_tid is not None:
                yield cur_tid, combine_spans(*cur_lists)
            cur_tid, cur_lists = tid, []
        cur_lists.append(spans)
    if cur_tid is not None:
        yield cur_tid, combine_spans(*cur_lists)


def compact(r: RawReader, w: RawWriter, tenant: str,
            inputs: list[bm.BlockMeta], cfg: CompactorConfig) -> list[bm.BlockMeta]:
    """Compact one input group → output metas (inputs marked compacted)."""
    blocks = [BackendBlock(r, m) for m in inputs]
    level = max(m.compaction_level for m in inputs) + 1
    ded = inputs[0].dedicated_columns
    out_metas: list[bm.BlockMeta] = []
    batch: list[tuple[bytes, list[dict]]] = []
    nspans = 0
    ntraces = 0
    est_bytes_per_span = max(
        sum(m.size_bytes for m in inputs) // max(sum(m.total_spans for m in inputs), 1), 1)

    def flush():
        nonlocal batch, nspans, ntraces
        if not batch:
            return
        meta = write_block(w, tenant, batch, dedicated_columns=ded,
                           compaction_level=level,
                           replication_factor=inputs[0].replication_factor)
        out_metas.append(meta)
        batch, nspans, ntraces = [], 0, 0

    for tid, spans in merge_blocks(blocks):
        batch.append((tid, spans))
        nspans += len(spans)
        ntraces += 1
        if (ntraces >= cfg.max_block_objects
                or nspans * est_bytes_per_span >= cfg.max_block_bytes):
            flush()
    flush()
    for m in inputs:
        bm.mark_block_compacted(r, w, m.block_id, tenant)
    log.info("compacted %d blocks -> %d (tenant=%s level=%d)",
             len(inputs), len(out_metas), tenant, level)
    return out_metas


# ---------------------------------------------------------------------------
# device route: decode once → merge/dedup/re-sort on device → stream back
# ---------------------------------------------------------------------------

def _id_matrix(col, width: int) -> np.ndarray:
    """Arrow binary column → [n, width] uint8 (one join, no per-row numpy)."""
    vals = col.to_numpy(zero_copy_only=False)
    joined = b"".join(bytes(v).ljust(width, b"\0")[:width] for v in vals)
    return np.frombuffer(joined, np.uint8).reshape(len(vals), width)


def _write_merged(w: RawWriter, tenant: str, table, order: np.ndarray,
                  inputs: list[bm.BlockMeta], cfg: CompactorConfig,
                  stats: dict | None) -> list[bm.BlockMeta]:
    """Permute the concatenated input table into merged order and write
    size-targeted output blocks (+ sidecars) — the host `flush` loop's
    trace/byte budgets applied to trace RUNS of the merged order."""
    import pyarrow as pa

    level = max(m.compaction_level for m in inputs) + 1
    est_bytes_per_span = max(
        sum(m.size_bytes for m in inputs)
        // max(sum(m.total_spans for m in inputs), 1), 1)
    out = table.take(pa.array(order, type=pa.int64()))
    tid_np = out.column("trace_id").to_numpy(zero_copy_only=False)
    n = len(tid_np)
    # trace run boundaries in merged order (order is tid-grouped)
    starts = [0] + [i for i in range(1, n)
                    if bytes(tid_np[i]) != bytes(tid_np[i - 1])]
    starts.append(n)
    out_metas: list[bm.BlockMeta] = []
    lo_t = 0
    while lo_t < len(starts) - 1:
        # host-flush boundary semantics: add whole traces until the
        # trace/byte budget trips ON the trace just added (inclusive)
        hi_t = lo_t
        while hi_t < len(starts) - 1:
            hi_t += 1
            if (hi_t - lo_t >= cfg.max_block_objects
                    or (starts[hi_t] - starts[lo_t]) * est_bytes_per_span
                    >= cfg.max_block_bytes):
                break
        lo_r, hi_r = starts[lo_t], starts[hi_t]
        chunk = out.slice(lo_r, hi_r - lo_r)
        # dense per-block trace index (writer normally derives it from
        # the trace grouping; the permuted table carries stale values)
        run_lens = np.diff(starts[lo_t:hi_t + 1])
        chunk = chunk.set_column(
            chunk.schema.get_field_index("trace_idx"), "trace_idx",
            pa.array(np.repeat(np.arange(len(run_lens), dtype=np.int64),
                               run_lens)))
        trace_ids = [bytes(tid_np[starts[t]]) for t in range(lo_t, hi_t)]
        meta = write_block_from_table(
            w, tenant, chunk, trace_ids,
            dedicated_columns=inputs[0].dedicated_columns,
            compaction_level=level,
            replication_factor=inputs[0].replication_factor)
        if cfg.sidecars:
            write_sidecar_for_table(w, tenant, meta, chunk, stats)
        out_metas.append(meta)
        lo_t = hi_t
    return out_metas


def write_sidecar_for_table(w: RawWriter, tenant: str, meta: bm.BlockMeta,
                            table, stats: dict | None = None) -> None:
    """Build + write the sketch sidecar from block-resident columns and
    flip the meta marker (blocks are born with sidecars on this path)."""
    from tempo_tpu.block import sidecar as sdc

    sc = sdc.build_sidecar(
        table.column("service").to_numpy(zero_copy_only=False),
        table.column("name").to_numpy(zero_copy_only=False),
        table.column("duration_ns").to_numpy(),
        _id_matrix(table.column("trace_id"), 16))
    sdc.write_sidecar(w, tenant, meta.block_id, sc)
    meta.sidecar = True
    bm.write_block_meta(w, meta)
    if stats is not None:
        stats["sidecars_written"] += 1


def compact_device(r: RawReader, w: RawWriter, tenant: str,
                   inputs: list[bm.BlockMeta], cfg: CompactorConfig,
                   stats: dict | None = None,
                   dispatch: Callable | None = None) -> list[bm.BlockMeta]:
    """Device-route `compact`: each input block is decoded ONCE into the
    concatenated columnar table, the merge/dedup/re-sort permutation is
    computed on device (`ops/compact.merge_order` — bit-compatible with
    the host heapq/combine_spans contract), and outputs stream back
    through the standard writer with sketch sidecars attached.

    `dispatch` wraps the device call (the sched compaction-class hook);
    raises on any decode/schema surprise — callers fall back to the
    host `compact`.
    """
    import pyarrow as pa

    from tempo_tpu.ops import compact as cops

    blocks = [BackendBlock(r, m) for m in inputs]
    tables = [b.parquet_file().read() for b in blocks]
    table = pa.concat_tables(tables) if len(tables) > 1 else tables[0]
    out_metas: list[bm.BlockMeta] = []
    if table.num_rows:
        tid = _id_matrix(table.column("trace_id"), 16)
        sid = _id_matrix(table.column("span_id"), 8)
        t0 = time.monotonic()
        run = dispatch if dispatch is not None else (lambda fn: fn())
        order = run(lambda: cops.merge_order(tid, sid))
        dt = time.monotonic() - t0
        out_metas = _write_merged(w, tenant, table, order, inputs, cfg, stats)
        if stats is not None:
            stats["device_seconds"] += dt
    for m in inputs:
        bm.mark_block_compacted(r, w, m.block_id, tenant)
    if stats is not None:
        stats["blocks"] += len(inputs)
        stats["spans"] += int(table.num_rows)
    log.info("device-compacted %d blocks -> %d (tenant=%s spans=%d)",
             len(inputs), len(out_metas), tenant, table.num_rows)
    return out_metas


def backfill_sidecar(r: RawReader, w: RawWriter, tenant: str,
                     meta: bm.BlockMeta, stats: dict | None = None) -> bool:
    """Attach a sidecar to an existing block (columnar read of just the
    four needed columns). Returns False when the block vanished
    mid-backfill (compaction races are benign — the marker never flips)."""
    try:
        pf = BackendBlock(r, meta).parquet_file()
        table = pf.read(columns=["trace_id", "service", "name",
                                 "duration_ns"])
    except Exception:
        return False
    write_sidecar_for_table(w, tenant, meta, table, stats)
    return True


def do_retention(r: RawReader, w: RawWriter, tenant: str,
                 metas: list[bm.BlockMeta], compacted: list[bm.CompactedBlockMeta],
                 cfg: CompactorConfig, now: Callable[[], float]) -> tuple[list, list]:
    """Mark over-retention live blocks compacted; delete compacted blocks
    past the grace period (`tempodb/retention.go:17-113`). Returns
    (marked_metas, deleted_block_ids)."""
    marked = []
    deleted = []
    cutoff = now() - cfg.retention_s
    for m in metas:
        if m.end_time < cutoff:
            bm.mark_block_compacted(r, w, m.block_id, tenant)
            marked.append(m)
    grace = now() - cfg.compacted_grace_s
    for c in compacted:
        if c.compacted_time < grace:
            bm.clear_block(w, c.meta.block_id, tenant)
            deleted.append(c.meta.block_id)
    return marked, deleted
