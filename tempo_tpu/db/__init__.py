"""Storage engine facade (SURVEY.md §2.2 'tempodb core'): blocklist, poller,
compaction, retention, bounded query pool, TempoDB Reader/Writer/Compactor."""

from tempo_tpu.db.blocklist import List
from tempo_tpu.db.compactor import (
    CompactorConfig,
    TimeWindowBlockSelector,
    compact,
    do_retention,
    iter_trace_groups,
    merge_blocks,
)
from tempo_tpu.db.pool import Pool
from tempo_tpu.db.poller import Poller, PollerConfig
from tempo_tpu.db.tempodb import TempoDB, TempoDBConfig

__all__ = [
    "CompactorConfig", "List", "Poller", "PollerConfig", "Pool", "TempoDB",
    "TempoDBConfig", "TimeWindowBlockSelector", "compact", "do_retention",
    "iter_trace_groups", "merge_blocks",
]
