"""Bounded worker pool for fan-out block queries.

Analog of `tempodb/pool/pool.go:49-210` (`RunJobs`): run N jobs over a
bounded thread pool, collect results, support stop-on-first-result (the
trace-by-ID path stops once a quorum of results arrives).
"""

from __future__ import annotations

from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Callable, Iterable, TypeVar

T = TypeVar("T")
R = TypeVar("R")


class Pool:
    def __init__(self, max_workers: int = 30, queue_depth: int = 10_000):
        self.max_workers = max_workers
        self.queue_depth = queue_depth
        self._ex = ThreadPoolExecutor(max_workers=max_workers,
                                      thread_name_prefix="tempodb-pool")

    def run_jobs(self, payloads: Iterable[T], fn: Callable[[T], R],
                 stop_when: Callable[[list[R]], bool] | None = None) -> tuple[list[R], list[Exception]]:
        """Run fn over payloads; returns (results, errors). `stop_when`
        short-circuits remaining jobs once satisfied on collected results."""
        payloads = list(payloads)
        if len(payloads) > self.queue_depth:
            raise RuntimeError(f"too many jobs: {len(payloads)} > {self.queue_depth}")
        futures = {self._ex.submit(fn, p) for p in payloads}
        results: list[R] = []
        errors: list[Exception] = []
        try:
            while futures:
                done, futures = wait(futures, return_when=FIRST_COMPLETED)
                for f in done:
                    try:
                        r = f.result()
                        if r is not None:
                            results.append(r)
                    except Exception as e:  # collect, don't abort the fan-out
                        errors.append(e)
                if stop_when is not None and stop_when(results):
                    for f in futures:
                        f.cancel()
                    break
        finally:
            for f in futures:
                f.cancel()
        return results, errors

    def shutdown(self) -> None:
        self._ex.shutdown(wait=False, cancel_futures=True)
