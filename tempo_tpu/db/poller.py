"""Backend poller: scan object store → per-tenant index → blocklist.

Analog of `tempodb/blocklist/poller.go:126-533`: one elected builder per
tenant lists every block and (re)writes the gzipped tenant index; everyone
else just reads the index (`pollTenantAndCreateIndex` `poller.go:239`,
builder election `poller.go:485`). Index staleness falls back to a full
listing, so a dead builder degrades to slow-but-correct.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable

from tempo_tpu.backend import meta as bm
from tempo_tpu.backend.raw import DoesNotExist, RawReader, RawWriter, blocks, tenants

log = logging.getLogger("tempo_tpu.db.poller")


@dataclasses.dataclass
class PollerConfig:
    poll_interval_s: float = 300.0
    stale_tenant_index_s: float = 0.0   # 0 = accept any age
    tolerate_consecutive_errors: int = 1


class Poller:
    def __init__(self, r: RawReader, w: RawWriter,
                 cfg: PollerConfig | None = None,
                 is_index_builder: Callable[[str], bool] = lambda tenant: True,
                 now: Callable[[], float] = time.time):
        self.r = r
        self.w = w
        self.cfg = cfg or PollerConfig()
        self.is_index_builder = is_index_builder
        self.now = now
        self.consecutive_errors = 0

    # -- one full poll cycle (`Do` poller.go:139) ---------------------------

    def do(self) -> tuple[dict, dict]:
        metas: dict[str, list[bm.BlockMeta]] = {}
        compacted: dict[str, list[bm.CompactedBlockMeta]] = {}
        for tenant in tenants(self.r):
            try:
                m, c = self.poll_tenant(tenant)
            except Exception:
                self.consecutive_errors += 1
                if self.consecutive_errors > self.cfg.tolerate_consecutive_errors:
                    raise
                log.exception("poll tenant %s failed (tolerated)", tenant)
                continue
            self.consecutive_errors = 0
            if m or c:
                metas[tenant] = m
                compacted[tenant] = c
        return metas, compacted

    def poll_tenant(self, tenant: str):
        if self.is_index_builder(tenant):
            m, c = self._list_tenant(tenant)
            bm.write_tenant_index(self.w, tenant, m, c)
            return m, c
        try:
            idx = bm.read_tenant_index(self.r, tenant)
            age = self.now() - idx.created_at
            if (self.cfg.stale_tenant_index_s
                    and age > self.cfg.stale_tenant_index_s):
                raise DoesNotExist("stale tenant index")
            return idx.metas, idx.compacted
        except DoesNotExist:
            # no/stale index: fall back to listing (poller.go fallback)
            return self._list_tenant(tenant)

    def _list_tenant(self, tenant: str):
        metas: list[bm.BlockMeta] = []
        compacted: list[bm.CompactedBlockMeta] = []
        for block_id in blocks(self.r, tenant):
            try:
                metas.append(bm.read_block_meta(self.r, block_id, tenant))
                continue
            except DoesNotExist:
                pass
            try:
                compacted.append(bm.read_compacted_block_meta(self.r, block_id, tenant))
            except DoesNotExist:
                pass  # block mid-write or mid-delete: ignore this cycle
        return metas, compacted
