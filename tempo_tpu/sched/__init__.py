"""tempo_tpu.sched — shared device-execution scheduler.

Continuous micro-batching for every device caller in the process:
bounded per-priority-class queues (live-ingest > query > compaction)
with load shedding and backpressure, a cross-tenant coalescer over
padded power-of-two shape buckets, and an adaptive batch window. See
`scheduler.py` for the design notes and `operations/runbook.md`
("Reading the scheduler") for the operational story.
"""

from tempo_tpu.sched.scheduler import (
    CLASS_NAMES,
    DeviceScheduler,
    Job,
    PRIO_COMPACTION,
    PRIO_INGEST,
    PRIO_QUERY,
    QueryBackpressure,
    SchedConfig,
    WindowTuner,
    bucket_rows,
    configure,
    flush,
    fraction_for_pressure,
    ingest_keep_fraction,
    reset,
    run,
    scheduler,
    use,
)

__all__ = [
    "CLASS_NAMES", "DeviceScheduler", "Job", "PRIO_COMPACTION",
    "PRIO_INGEST", "PRIO_QUERY", "QueryBackpressure", "SchedConfig",
    "WindowTuner", "bucket_rows", "configure", "flush",
    "fraction_for_pressure",
    "ingest_keep_fraction", "reset", "run", "scheduler", "use",
]
