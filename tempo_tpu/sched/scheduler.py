"""Process-wide device-execution scheduler: continuous micro-batching.

Every device caller in this codebase — spanmetrics fused updates on the
write path, `BlockScanPlane` masks/grids and the metrics-engine scatter
kernels on the read path — used to dispatch its own small, oddly-shaped
batches straight into jit, paying per-call dispatch overhead and a fresh
XLA trace on every new shape. LLM inference stacks solved exactly this
with continuous batching over padded, bucketed shapes (cf. ragged paged
attention batching for TPU serving), and the mergeable-sketch kernels we
run are commutative (counts/histograms/DDSketch merge by addition), so
coalescing update batches is safe by construction.

This module is the shared seam:

- **Bounded per-priority-class queues** (live-ingest > query >
  compaction) with load shedding and backpressure: ingest admission is
  gated at the distributor (429 + Retry-After via
  `distributor/limiter.IngestBackpressure`), the frontend sheds new
  queries with `QueryBackpressure` (503) when the query class saturates,
  and an over-full class never queues unboundedly — excess jobs execute
  inline on the caller (shed) and are counted.
- **A coalescer** that merges same-kernel jobs that target the same
  device state plane into ONE padded tensor per array role. Jobs from
  different tenants share the batch window, the drain cycle, and the
  shape-bucket cache (one wake, one lock, zero re-traces); jobs whose
  `merge_key` matches (same state plane — sketch updates commute, so
  concatenation is exact for the counts) additionally merge into a
  single dispatch. Padding rows carry slot -1 / weight 0 and are
  dropped by the scatter kernels (`mode="drop"`).
- **Power-of-two shape bucketing** with a warm-bucket cache: merged
  batches pad to the next power of two (floor `min_bucket_rows`), so the
  set of shapes reaching jit is small and steady state never re-traces
  — the compile counters in `obs/jaxruntime` are the proof surface.
- **An adaptive batch window**: a merge group closes when its occupancy
  reaches `occupancy_target * max_batch_rows` OR when `batch_window_ms`
  elapses since its first job, whichever comes first — p99 ingest
  latency stays bounded under light load, batches stay full under heavy
  load. Query-class jobs never wait on the window.

Everything is observable: queue depth/limit gauges, per-class job and
shed counters, per-kernel batch/occupancy/padding-waste/dispatch-wall
families (registered in the process-wide `obs.jaxruntime.RUNTIME`
registry, rendered on /metrics next to the jit-compile counters), and
read-path jobs thread their scheduler wait + job count into the ambient
per-request `QueryStats`. Every dispatch additionally records into the
**device-time ledger** (`obs/devtime.py`: per-(kernel, bucket, class,
shard) wall/rows/queue-wait/H2D with per-tenant attribution, plus each
request's `device_ns`) and feeds the online affine dispatch **cost
model** — which `tuning: auto` consults (`WindowTuner`) to pick batch
windows and bucket close targets that minimize predicted ingest
latency, hard-clamped so backpressure/flush semantics never change.

The scheduler is config-gated (`SchedConfig.enabled`, default on via
`app.config.Config.sched`); every caller preserves its original
synchronous dispatch as the fallback path, bit-identical to the
pre-scheduler behavior when disabled.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import math
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Sequence

import numpy as np

from tempo_tpu.utils import faults

from tempo_tpu.obs import devtime
from tempo_tpu.utils import tracing

_LOG = logging.getLogger("tempo_tpu.sched")

# priority classes, best first (live ingest must never starve behind an
# expensive analytical scan; compaction yields to both)
PRIO_INGEST, PRIO_QUERY, PRIO_COMPACTION = 0, 1, 2
CLASS_NAMES = ("ingest", "query", "compaction")


class QueryBackpressure(RuntimeError):
    """The query class is saturated: the frontend rejects NEW requests
    (503 + Retry-After) instead of queuing them unboundedly — already
    admitted work still runs (shed executes inline)."""

    def __init__(self, retry_after_s: float = 1.0) -> None:
        super().__init__("device scheduler query queue saturated")
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class SchedConfig:
    """Knobs for the shared device-execution scheduler (`sched:` in the
    app YAML)."""

    enabled: bool = True
    # bounded submission queues per priority class (jobs, not rows)
    max_queue_ingest: int = 1024
    max_queue_query: int = 512
    max_queue_compaction: int = 256
    # adaptive batch window: a merge group closes on occupancy target or
    # deadline, whichever first
    batch_window_ms: float = 2.0
    occupancy_target: float = 0.75
    max_batch_rows: int = 16384          # coalesced rows per dispatch
    min_bucket_rows: int = 64            # smallest pow-2 shape bucket
    retry_after_s: float = 1.0           # advertised on 429/503 rejections
    # ingest staging pipeline depth: how many decoded-but-undispatched
    # batches a producer may run AHEAD of the device (the staging-buffer
    # ring is depth+1 deep). 0 disables the decode/update overlap ring —
    # submissions still coalesce, but every push allocates fresh staging.
    pipeline_depth: int = 2
    # graceful-overload sampling (the pressure → keep-fraction
    # controller): when the live-ingest queue fills past
    # `sampling_start_pressure` of its bound, the distributor's span
    # sampler shrinks the per-push keep fraction linearly from 1.0 down
    # to `sampling_min_fraction` at full saturation — overload degrades
    # to a representative sampled stream FIRST; the hard 429 (which
    # still fires at depth == limit) becomes the escalation of last
    # resort. Below the start pressure the fraction is exactly 1.0 and
    # the sampling stage is bypassed entirely (bit-identical path).
    # Per-tenant policy/floors live in overrides (`sampling:` limits).
    sampling_enabled: bool = True
    sampling_start_pressure: float = 0.5
    sampling_min_fraction: float = 0.05
    # EWMA time constant for the published fraction: pressure is spiky
    # push to push; the controller must ramp, not flap. 0 = unsmoothed.
    sampling_smoothing_s: float = 2.0
    # scheduler tuning mode: "static" keeps the fixed batch_window_ms /
    # occupancy close; "auto" lets the scheduler pick per-kernel batch
    # windows (and pow-2 bucket close targets) that minimize PREDICTED
    # ingest latency using the online dispatch cost model fit from the
    # device-time ledger (obs/devtime.py). Auto falls back to the static
    # window per kernel until the model is warm, and is HARD-BOUNDED:
    # the tuned window stays inside [tuning_window_min_ms,
    # tuning_window_max_ms], the tuned close target never exceeds the
    # static occupancy close, and flush()/backpressure semantics are
    # untouched (force-drain ignores windows; queue bounds are not
    # tuned).
    tuning: str = "static"
    tuning_window_min_ms: float = 0.25
    tuning_window_max_ms: float = 8.0
    tuning_interval_s: float = 0.5      # how often a kernel's choice refits
    # compaction-class minimum dispatch share: compaction jobs normally
    # run only when ingest/query are fully idle, which under SUSTAINED
    # load is NEVER — the cold tier would starve forever. With share s,
    # after ceil(1/s) consecutive drain cycles that skipped a waiting
    # compaction job, one is force-dispatched (so compaction gets at
    # least ~s of drain cycles under saturation). 0 restores pure
    # idle-only dispatch. Bounded (0, 0.5] by config.check().
    compaction_min_share: float = 0.05


def fraction_for_pressure(pressure: float, start: float,
                          floor: float) -> float:
    """Pure pressure → keep-fraction control law (the testable core of
    the overload controller): 1.0 at or below `start`, then a linear
    ramp down to `floor` at full saturation (pressure 1.0). Exactly 1.0
    below the threshold — the distributor bypasses its sampling stage
    entirely there, keeping the unpressured path bit-identical."""
    if pressure <= start:
        return 1.0
    if start >= 1.0:
        return 1.0
    span = 1.0 - start
    frac = 1.0 - (min(pressure, 1.0) - start) / span * (1.0 - floor)
    return max(min(frac, 1.0), floor)


def bucket_rows(n: int, lo: int = 64, hi: int | None = None) -> int:
    """Power-of-two shape bucket for a row count: next pow2 >= max(n, lo)
    (capped at `hi` when given). The whole point of bucketing is a SMALL
    closed set of shapes reaching jit, so steady state never re-traces."""
    b = max(int(lo), 1)
    while b < n:
        b <<= 1
    if hi is not None:
        b = min(b, hi)
    return b


class WindowTuner:
    """`tuning: auto`: per-kernel batch-window deadlines and bucket
    close targets chosen to minimize PREDICTED ingest latency.

    Model (the testable core): rows arrive at a measured rate λ (EWMA of
    the kernel's submit stream). A window of length w accumulates ≈ λ·w
    rows, pads to the pow-2 bucket B(λ·w), and pays the cost model's
    predicted dispatch wall c(B, λ·w). The first row of the window
    observes ≈ w + c latency — the window-driven ingest tail — so the
    tuner picks, over a geometric candidate grid inside the configured
    bounds, the w minimizing w + c subject to FEASIBILITY c ≤ w (the
    device must drain one window's batch within the window, or the
    queue grows without bound and backpressure fires). If no candidate
    is feasible the device is saturated regardless of windowing: the
    largest window wins (maximum amortization). While the cost model is
    cold for a kernel the answer is None and the scheduler keeps its
    static window — warm-up is observable as
    tempo_sched_tuning_active=0.

    The hard guard lives in the CALLER (`_group_close_params`): tuned
    windows are clamped to the configured bounds and the tuned close
    target can only LOWER the static occupancy close, so backpressure
    and flush semantics are exactly the static mode's.
    """

    N_CANDIDATES = 9

    def __init__(self, now: Callable[[], float] = time.monotonic) -> None:
        self.now = now
        self._lock = threading.Lock()
        # kernel -> [rows accumulated since last refit, refit wall t,
        #            EWMA rows/s, (window_s, target_rows) | None]
        self._state: dict[str, list] = {}

    def note_rows(self, kernel: str, rows: int) -> None:
        """Per-submit arrival accounting (called under no other lock)."""
        with self._lock:
            st = self._state.get(kernel)
            if st is None:
                self._state[kernel] = [rows, self.now(), 0.0, None]
            else:
                st[0] += rows

    def choice(self, kernel: str, cfg: SchedConfig
               ) -> "tuple[float, int] | None":
        """(window_seconds, bucket_target_rows) for a kernel, or None
        while the cost model is cold (static fallback). Cached; refits
        at most every cfg.tuning_interval_s."""
        now = self.now()
        with self._lock:
            st = self._state.get(kernel)
            if st is None:
                st = self._state[kernel] = [0, now, 0.0, None]
            dt = now - st[1]
            if dt < cfg.tuning_interval_s:
                # cache None picks too: a cold model must not turn
                # every submit into a full grid refit under _cond, nor
                # reset the arrival accumulator before it has seen a
                # full interval of traffic
                return st[3]
            if dt > 0:
                rate = st[0] / dt
                # EWMA over refit intervals: the arrival rate swings
                # push to push, the window choice should not
                st[2] = rate if st[2] == 0.0 else st[2] + 0.3 * (rate - st[2])
            st[0], st[1] = 0, now
            rate = st[2]
        lo = max(cfg.tuning_window_min_ms, 1e-3) / 1e3
        hi = max(cfg.tuning_window_max_ms, cfg.tuning_window_min_ms) / 1e3
        best = None          # (latency, window, target)
        fallback = None      # largest window with any prediction
        step = (hi / lo) ** (1.0 / max(self.N_CANDIDATES - 1, 1))
        w = lo
        for _ in range(self.N_CANDIDATES):
            exp_rows = max(rate * w, 1.0)
            bucket = bucket_rows(int(math.ceil(exp_rows)),
                                 cfg.min_bucket_rows, cfg.max_batch_rows)
            cost = devtime.COST_MODEL.predict(kernel, bucket,
                                              min(exp_rows, bucket))
            if cost is not None:
                latency = w + cost
                fallback = (latency, w, bucket)
                if cost <= w and (best is None or latency < best[0]):
                    best = (latency, w, bucket)
            w *= step
        pick = best or fallback
        out = (pick[1], pick[2]) if pick is not None else None
        with self._lock:
            st = self._state.get(kernel)
            if st is not None:
                st[3] = out
        return out

    def windows_ms(self) -> list:
        """[(kernel, tuned window ms), ...] for the exposition gauge."""
        with self._lock:
            return [(k, st[3][0] * 1e3) for k, st in self._state.items()
                    if st[3] is not None]


class Job:
    """One unit of device work. Row jobs (`arrays` set) are coalescible:
    same-merge_key jobs concatenate into one padded tensor per array
    role. Fn jobs (`fn` set) execute as-is in priority order."""

    __slots__ = ("priority", "kernel", "merge_key", "arrays", "pads",
                 "n_rows", "dispatch", "fn", "tenant", "enqueue_t",
                 "event", "result", "error", "stats", "wait_s",
                 "traceparent")

    def __init__(self, *, priority: int, kernel: str, merge_key=None,
                 arrays: "tuple | None" = None,
                 pads: "tuple | None" = None, n_rows: int = 0,
                 dispatch: "Callable | None" = None,
                 fn: "Callable | None" = None, tenant: str = "",
                 stats=None) -> None:
        self.priority = priority
        self.kernel = kernel
        self.merge_key = merge_key
        self.arrays = arrays
        self.pads = pads
        self.n_rows = n_rows
        self.dispatch = dispatch
        self.fn = fn
        self.tenant = tenant
        self.enqueue_t = 0.0
        self.event = threading.Event()
        self.result = None
        self.error: "BaseException | None" = None
        self.stats = stats     # caller's QueryStats, adopted by the worker
        self.wait_s = 0.0      # enqueue → execution-start (set by worker)
        # submitter's trace context: the dispatch span LINKS the whole
        # coalesced batch back to each contributing request's tree
        # (fn jobs re-enter it so device work parents under the query)
        self.traceparent = tracing.tracer().traceparent()

    def wait(self, timeout: "float | None" = None) -> bool:
        """Block until dispatched; re-raises the dispatch error, if any."""
        ok = self.event.wait(timeout)
        if ok and self.error is not None:
            raise self.error
        return ok


class _MergeGroup:
    """Pending coalescible jobs sharing one merge_key: one state plane,
    one dispatch closure, one eventual padded tensor."""

    __slots__ = ("kernel", "pads", "dispatch", "jobs", "rows", "first_t",
                 "pack", "align", "shards")

    def __init__(self, kernel: str, pads: tuple, dispatch: Callable,
                 first_t: float, pack: bool = False, align: int = 1,
                 shards: int = 0) -> None:
        self.kernel = kernel
        self.pads = pads
        self.dispatch = dispatch
        self.jobs: list[Job] = []
        self.rows = 0
        self.first_t = first_t
        self.pack = pack
        self.align = align
        self.shards = shards


class DeviceScheduler:
    """The shared scheduler. One per process in production (see
    `configure()` / `scheduler()`); tests construct their own with
    `start_worker=False` and drive `drain_once()` by hand."""

    def __init__(self, cfg: SchedConfig | None = None,
                 now: Callable[[], float] = time.monotonic,
                 start_worker: bool = True) -> None:
        self.cfg = cfg or SchedConfig()
        self.now = now
        self._cond = threading.Condition()
        # fn jobs per class; row jobs live in merge groups (ingest class)
        self._queues: tuple[deque, ...] = (deque(), deque(), deque())
        self._groups: "OrderedDict[object, _MergeGroup]" = OrderedDict()
        self._inflight = 0
        # re-entrant: a dispatched job may itself flush() (e.g. a
        # scheduled read that needs queued sketch updates drained first)
        self._drain_lock = threading.RLock()
        self._drainer: "int | None" = None
        # guards the per-kernel stat dicts: the worker and shed-path
        # caller threads dispatch concurrently, and losing increments
        # during saturation would corrupt exactly the metrics that
        # diagnose saturation
        self._stats_lock = threading.Lock()
        self._stop = threading.Event()
        self._worker: "threading.Thread | None" = None
        self._worker_ident: "int | None" = None
        # plain-dict stats (obs renders them through callback families;
        # the hot path pays dict increments, never registry locks)
        self.jobs_total = {c: 0 for c in CLASS_NAMES}
        self.shed_total = {c: 0 for c in CLASS_NAMES}
        self.batches_total: dict[str, int] = {}
        self.coalesced_total: dict[str, int] = {}
        self.padding_waste_bytes: dict[str, int] = {}
        # serving-mesh split of the padding waste, keyed (kernel, shard):
        # only mesh dispatches (submits with shards set) populate it; the
        # exposition renders it as `shard` label rows next to the
        # non-mesh aggregate (shard="") without double counting
        self.padding_waste_shard: dict[tuple[str, str], int] = {}
        self.bucket_warmups: dict[str, int] = {}
        self.dispatch_errors = 0
        # compaction-class anti-starvation: consecutive drains that left
        # a non-empty compaction queue untouched, and how many jobs the
        # minimum-dispatch-share floor force-dispatched (guarded by _cond)
        self._comp_starved = 0
        self.comp_forced_total = 0
        self.occupancy_sum: dict[str, float] = {}
        self._warm_buckets: set[tuple] = set()
        # pressure → keep-fraction controller state (EWMA-smoothed; see
        # keep_fraction below). Guarded by _frac_lock: the distributor
        # reads per push from any receiver thread.
        self._frac_lock = threading.Lock()
        self._frac = 1.0
        self._frac_t: "float | None" = None
        # ingest jobs currently being dispatched (popped off the queues
        # but not yet landed) — the controller's pressure must include
        # them or it collapses to zero mid-drain (see control_pressure)
        self._inflight_ingest = 0
        # `tuning: auto` window/bucket chooser (always constructed —
        # only consulted when the mode says so, and the mode can change
        # via reconfigure())
        self._tuner = WindowTuner(now=now)
        if start_worker and self.cfg.enabled:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._stop.clear()
        self._worker = threading.Thread(target=self._worker_loop,
                                        name="tempo-sched", daemon=True)
        self._worker.start()

    def stop(self, flush: bool = True) -> None:
        if flush:
            self.flush()
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=2)
            self._worker = None
            self._worker_ident = None

    def reconfigure(self, cfg: SchedConfig) -> None:
        """Adopt new knobs in place (multiple Apps in one process share
        the singleton; last writer wins, like jax runtime flags)."""
        self.cfg = cfg
        if cfg.enabled:
            self.start()

    # -- introspection -----------------------------------------------------

    def _limit(self, prio: int) -> int:
        return (self.cfg.max_queue_ingest, self.cfg.max_queue_query,
                self.cfg.max_queue_compaction)[prio]

    def depth(self, prio: int) -> int:
        with self._cond:
            n = len(self._queues[prio])
            if prio == PRIO_INGEST:
                n += sum(len(g.jobs) for g in self._groups.values())
            return n

    def pending(self) -> int:
        with self._cond:
            return (sum(len(q) for q in self._queues)
                    + sum(len(g.jobs) for g in self._groups.values())
                    + self._inflight)

    def pressure(self) -> dict[str, float]:
        """class → fill ratio of its bounded queue (the backpressure
        signal the distributor and frontend consult)."""
        return {CLASS_NAMES[p]: self.depth(p) / max(self._limit(p), 1)
                for p in (PRIO_INGEST, PRIO_QUERY, PRIO_COMPACTION)}

    def ingest_saturated(self) -> bool:
        return self.cfg.enabled and \
            self.depth(PRIO_INGEST) >= self._limit(PRIO_INGEST)

    def query_saturated(self) -> bool:
        return self.cfg.enabled and \
            self.depth(PRIO_QUERY) >= self._limit(PRIO_QUERY)

    def ingest_retry_after(self) -> "float | None":
        """Seconds a rejected producer should back off, or None to
        admit — the `IngestBackpressure` hook contract."""
        return self.cfg.retry_after_s if self.ingest_saturated() else None

    def control_pressure(self) -> float:
        """Live-ingest pressure for the sampling controller: queued PLUS
        in-flight jobs over the bound (may exceed 1.0 while the device
        chews a popped backlog). The hard-429 signal stays queue-only —
        the bound protects queue memory — but the controller must keep
        sampling through a batchy drain, or the fraction sawtooths to
        1.0 every time the worker pops the backlog and re-saturates the
        moment full-row pushes resume."""
        with self._cond:
            inflight = self._inflight_ingest
        return (self.depth(PRIO_INGEST) + inflight) \
            / max(self._limit(PRIO_INGEST), 1)

    def keep_fraction(self) -> float:
        """The overload controller's current span keep-fraction in
        (0, 1]: 1.0 means sampling is off (the distributor bypasses its
        sampling stage entirely), anything lower tells the distributor
        to hash-sample non-forced spans at that rate. Driven by the SAME
        live-ingest queue that feeds `IngestBackpressure` (plus its
        in-flight tail, see control_pressure), so the escalation order
        is: full stream → sampled stream → 429.

        The published value is EWMA-smoothed (`sampling_smoothing_s`)
        because queue fill is spiky push to push; it snaps back to
        exactly 1.0 once the raw control law has fully recovered so the
        below-threshold path stays bit-identical."""
        cfg = self.cfg
        if not cfg.enabled or not cfg.sampling_enabled:
            return 1.0
        raw = fraction_for_pressure(self.control_pressure(),
                                    cfg.sampling_start_pressure,
                                    cfg.sampling_min_fraction)
        tau = cfg.sampling_smoothing_s
        if tau <= 0:
            return raw
        now = self.now()
        with self._frac_lock:
            if self._frac_t is None:
                self._frac = raw
            else:
                dt = max(now - self._frac_t, 0.0)
                # asymmetric: shed fast (tau/4), recover slowly (tau) —
                # a batchy drain makes raw pressure sawtooth, and a
                # controller that snaps back to 1.0 between drain cycles
                # re-saturates the queue with full-row pushes every cycle
                tau_eff = tau if raw > self._frac else tau / 4.0
                alpha = 1.0 - math.exp(-dt / tau_eff)
                self._frac += alpha * (raw - self._frac)
                if raw >= 1.0 and self._frac >= 0.99:
                    self._frac = 1.0   # recovered: exact off, not 0.99…
            self._frac_t = now
            return max(self._frac, cfg.sampling_min_fraction)

    def mean_occupancy(self, kernel: "str | None" = None) -> float:
        if kernel is not None:
            n = self.batches_total.get(kernel, 0)
            return self.occupancy_sum.get(kernel, 0.0) / n if n else 0.0
        n = sum(self.batches_total.values())
        return sum(self.occupancy_sum.values()) / n if n else 0.0

    # -- submission --------------------------------------------------------

    def submit_rows(self, kernel: str, merge_key, arrays: Sequence,
                    n_rows: int, dispatch: Callable,
                    pads: "Sequence | None" = None,
                    tenant: str = "", pack: bool = False,
                    align: int = 1, shards: int = 0) -> Job:
        """Enqueue a coalescible row batch (live-ingest class).

        `arrays` are row-aligned host vectors (one per kernel argument
        role); `pads[i]` is the fill value padding rows take in role i
        (defaults: -1 for the first role — the slot ids every scatter
        kernel drops — and 0 for the rest). `dispatch(*padded_arrays)`
        runs ONCE per merged batch on the worker thread and must bind the
        new device state itself (under its own state lock).

        `pack=True` ships the merged batch as ONE row-major f32 matrix
        `[n_roles, bucket]` (dispatch receives a single array): behind a
        high-latency device link the per-dispatch transfer COUNT is the
        cost, so all roles ride one H2D — the coalescer-side twin of the
        spanmetrics packed fast path. Every role must survive an f32
        round trip (slot ids do while the series capacity is < 2^24; the
        caller owns that gate).

        `align` (serving-mesh mode) rounds the merged pow-2 bucket UP to
        a multiple of it, so the single padded window splits evenly
        across the mesh's 'data' shards under `shard_map` — ONE dispatch
        feeds every device instead of per-device launches. `shards` is
        the mesh dispatch's data-shard count for observability (0 =
        non-mesh): mesh dispatches emit one occupancy sample per shard
        under the `shard` label, non-mesh batches keep the aggregate
        under shard="".

        Never blocks and never drops data: on a saturated queue the job
        executes inline on the caller (shed, counted) — ADMISSION control
        lives at the distributor boundary, which consults
        `ingest_retry_after()` before accepting the bytes at all.
        """
        pads = tuple(pads) if pads is not None else \
            (-1,) + (0,) * (len(arrays) - 1)
        job = Job(priority=PRIO_INGEST, kernel=kernel, merge_key=merge_key,
                  arrays=tuple(arrays), pads=pads, n_rows=int(n_rows),
                  dispatch=dispatch, tenant=tenant)
        if not self.cfg.enabled:
            self._run_group(_group_of(job, pack, align, shards))
            return job
        if self.cfg.tuning == "auto":
            # arrival-rate accounting for the window tuner (outside
            # _cond: the tuner has its own lock)
            self._tuner.note_rows(kernel, job.n_rows)
        with self._cond:
            depth = len(self._queues[PRIO_INGEST]) + sum(
                len(g.jobs) for g in self._groups.values())
            if depth >= self._limit(PRIO_INGEST):
                self.shed_total["ingest"] += 1
            else:
                job.enqueue_t = self.now()
                g = self._groups.get(merge_key)
                if g is None:
                    g = self._groups[merge_key] = _MergeGroup(
                        kernel, pads, dispatch, job.enqueue_t, pack=pack,
                        align=align, shards=shards)
                g.jobs.append(job)
                g.rows += job.n_rows
                self.jobs_total["ingest"] += 1
                # wake the worker only when it has something new to DO:
                # the first job of a group (arm the deadline timer) or an
                # occupancy-threshold crossing (close now). Waking per
                # submit costs a context switch per push and was measured
                # to eat the whole coalescing win on the CPU backend.
                target = self._group_close_params(kernel)[1]
                if len(g.jobs) == 1 or (g.rows >= target
                                        and g.rows - job.n_rows < target):
                    self._cond.notify_all()
                return job
        # shed path: dispatch inline, outside the lock
        self._run_group(_group_of(job, pack, align, shards))
        return job

    def run(self, fn: Callable, kernel: str = "fn",
            priority: int = PRIO_QUERY, tenant: str = ""):
        """Execute `fn` (a device-dispatching closure) under scheduler
        ordering and return its result. Runs inline when the scheduler is
        disabled, when called FROM the worker (re-entrancy), when the
        scheduler is idle (no queue to order against — zero added
        latency on the common light-load path), or when the class queue
        is full (shed, counted)."""
        if not self.cfg.enabled or \
                threading.get_ident() == self._worker_ident:
            return fn()
        cls = CLASS_NAMES[priority]
        with self._cond:
            idle = not any(self._queues) and not self._groups \
                and self._inflight == 0
            if idle:
                self.jobs_total[cls] += 1
            elif len(self._queues[priority]) >= self._limit(priority):
                self.shed_total[cls] += 1
                idle = True            # run inline below
            else:
                from tempo_tpu.obs import querystats
                job = Job(priority=priority, kernel=kernel, fn=fn,
                          tenant=tenant, stats=querystats.current())
                job.enqueue_t = self.now()
                self._queues[priority].append(job)
                self.jobs_total[cls] += 1
                self._cond.notify_all()
        if idle:
            return self._run_inline(fn, kernel, priority, tenant)
        job.wait()
        # pure QUEUE wait (enqueue → execution start, stamped by the
        # worker): the kernel's own wall time is already attributed by
        # the job's recording inside the adopted QueryStats scope
        wait_ns = max(int(job.wait_s * 1e9), 0)
        if job.stats is not None:
            job.stats.add_stage_ns("sched_wait", wait_ns)
            job.stats.add(sched_jobs=1)
        _QUEUE_WAIT.observe(wait_ns / 1e9, (cls,))
        return job.result

    def _run_inline(self, fn: Callable, kernel: str, priority: int,
                    tenant: str):
        """Idle/shed fast path of run(): execute on the caller, but
        still feed the device-time ledger and the ambient QueryStats —
        device-seconds attribution must not have a light-load blind
        spot (most query-class dispatches take exactly this path)."""
        from tempo_tpu.obs import querystats

        t0 = time.perf_counter()
        try:
            return fn()
        finally:
            wall_ns = int((time.perf_counter() - t0) * 1e9)
            devtime.LEDGER.record_batch(
                kernel=kernel, bucket=0, prio=priority, shards=0,
                wall_ns=wall_ns, rows=0, padded_rows=0, queue_wait_ns=0,
                h2d_bytes=0,
                tenant_rows={tenant: 0} if tenant else None)
            st = querystats.current()
            if st is not None:
                st.add(device_ns=wall_ns)

    def _queued_count(self) -> int:
        with self._cond:
            return (sum(len(q) for q in self._queues)
                    + sum(len(g.jobs) for g in self._groups.values()))

    def flush(self, timeout: float = 30.0) -> bool:
        """Barrier: force-dispatch everything queued (windows ignored)
        and wait for in-flight work; returns True on a clean drain.
        Collection ticks, sketch-quantile reads, and stale-series purges
        call this so reads never miss queued updates (and slot reuse can
        never misroute one). Safe to call from INSIDE a dispatched job:
        the nested drain runs on the same thread and only waits for
        queued work, never for its own in-flight frame. Must not be
        called while holding a registry state_lock (dispatch closures
        take those locks)."""
        deadline = time.monotonic() + timeout
        inside = threading.get_ident() == self._drainer
        while time.monotonic() < deadline:
            if (self._queued_count() if inside else self.pending()) == 0:
                return True
            if not self.drain_once(force=True) and not inside:
                time.sleep(0.0005)
        # NEVER time out silently with work still queued: the caller is
        # about to read (or purge) state this barrier was supposed to
        # cover — a slot-reuse misroute downstream would be invisible
        _LOG.error("tempo-sched: flush timed out after %ss with %d jobs "
                   "still queued", timeout, self._queued_count())
        return False

    # -- draining ----------------------------------------------------------

    def _group_close_params(self, kernel: str) -> tuple[float, float]:
        """(window_seconds, close_target_rows) for a merge group — the
        static config, or the tuner's pick in `tuning: auto` once the
        cost model is warm for the kernel. HARD GUARD: the tuned window
        is clamped to the configured bounds and the tuned close target
        can only be ≤ the static occupancy close — auto mode can close
        batches earlier or stretch the window within bounds, but can
        never queue more rows per batch than static mode would, so the
        backpressure and flush semantics PR 5–6 rely on are untouched."""
        cfg = self.cfg
        window_s = cfg.batch_window_ms / 1000.0
        target = cfg.occupancy_target * cfg.max_batch_rows
        if cfg.tuning == "auto":
            choice = self._tuner.choice(kernel, cfg)
            if choice is not None:
                lo = max(cfg.tuning_window_min_ms, 1e-3) / 1e3
                hi = max(cfg.tuning_window_max_ms,
                         cfg.tuning_window_min_ms) / 1e3
                window_s = min(max(choice[0], lo), hi)
                target = min(float(choice[1]), target)
        return window_s, target

    def _group_ready(self, g: _MergeGroup, now: float) -> bool:
        window_s, target = self._group_close_params(g.kernel)
        return g.rows >= target or (now - g.first_t) >= window_s

    def tuned_window_ms(self, kernel: str) -> float:
        """The window currently in force for a kernel, milliseconds
        (the static config until auto mode is warm) — /status surface."""
        return self._group_close_params(kernel)[0] * 1e3

    def tuning_active(self) -> bool:
        """True when auto mode is live AND at least one kernel is being
        tuned from a warm cost model (the gauge behind
        TempoSchedCostModelStale's gating)."""
        return (self.cfg.enabled and self.cfg.tuning == "auto"
                and bool(self._tuner.windows_ms()))

    def _wait_budget_locked(self) -> "float | None":
        """How long the worker may sleep (caller holds _cond): 0 when
        anything is dispatchable right now, the nearest group deadline
        otherwise, None when idle."""
        if any(self._queues):
            return 0.0
        if not self._groups:
            return None
        now = self.now()
        if any(self._group_ready(g, now) for g in self._groups.values()):
            return 0.0
        return max(0.0, min(
            g.first_t + self._group_close_params(g.kernel)[0] - now
            for g in self._groups.values()))

    def drain_once(self, force: bool = False) -> bool:
        """One scheduling cycle: pop everything dispatchable right now
        and execute it in priority order (ready ingest groups, ingest
        fns, query fns; compaction only when nothing better is pending).
        Returns True when any work ran. Thread-safe: the worker loop and
        `flush()` callers serialize on the drain lock."""
        with self._drain_lock:
            prev_drainer, self._drainer = self._drainer, threading.get_ident()
            try:
                return self._drain_locked(force)
            finally:
                self._drainer = prev_drainer

    def _drain_locked(self, force: bool) -> bool:
        with self._cond:
            now = self.now()
            groups = [k for k, g in self._groups.items()
                      if force or self._group_ready(g, now)]
            ready = [self._groups.pop(k) for k in groups]
            ingest_fns = list(self._queues[PRIO_INGEST])
            self._queues[PRIO_INGEST].clear()
            query_fns = list(self._queues[PRIO_QUERY])
            self._queues[PRIO_QUERY].clear()
            comp_fns: list[Job] = []
            if (not ready and not ingest_fns and not query_fns
                    and not self._groups) or force:
                comp_fns = list(self._queues[PRIO_COMPACTION])
                self._queues[PRIO_COMPACTION].clear()
                self._comp_starved = 0
            elif self._queues[PRIO_COMPACTION]:
                # anti-starvation floor (compaction_min_share): sustained
                # ingest/query pressure means the idle-only branch above
                # never fires; after 1/share consecutive starved drains,
                # force ONE compaction job through — a bounded minimum
                # dispatch share that can't invert priorities
                self._comp_starved += 1
                share = self.cfg.compaction_min_share
                if share > 0.0 and self._comp_starved * share >= 1.0:
                    comp_fns = [self._queues[PRIO_COMPACTION].popleft()]
                    self._comp_starved = 0
                    self.comp_forced_total += 1
            n = (len(ready) + len(ingest_fns) + len(query_fns)
                 + len(comp_fns))
            n_ing = sum(len(g.jobs) for g in ready) + len(ingest_fns)
            self._inflight += n
            self._inflight_ingest += n_ing
        if n == 0:
            return False
        try:
            for g in ready:
                self._run_group(g)
            for job in ingest_fns + query_fns + comp_fns:
                self._run_fn(job)
        finally:
            with self._cond:
                self._inflight -= n
                self._inflight_ingest -= n_ing
                self._cond.notify_all()
        return True

    def _worker_loop(self) -> None:
        self._worker_ident = threading.get_ident()
        while not self._stop.is_set():
            with self._cond:
                # the readiness check and the wait share ONE lock
                # acquisition: a submit's notify between a check and a
                # separate wait would otherwise be lost and stretch a
                # 2ms batch window to the 200ms fallback sleep
                wait = self._wait_budget_locked()
                if wait is None or wait > 0:
                    self._cond.wait(min(wait, 0.2) if wait is not None
                                    else 0.2)
            if self._stop.is_set():
                break
            try:
                self.drain_once()
            except BaseException as e:       # noqa: BLE001 — keep alive
                # a dead worker is a total silent outage (every queued
                # caller hangs, ingest fills to 429): log and keep going
                _LOG.exception("tempo-sched: drain cycle failed: %r", e)

    # -- execution ---------------------------------------------------------

    def _run_group(self, g: _MergeGroup) -> None:
        """Coalesce one merge group into padded pow-2 tensors and
        dispatch, chunked at `max_batch_rows`."""
        jobs = g.jobs
        i = 0
        while i < len(jobs):
            chunk = [jobs[i]]
            rows = jobs[i].n_rows
            i += 1
            while i < len(jobs) and \
                    rows + jobs[i].n_rows <= self.cfg.max_batch_rows:
                rows += jobs[i].n_rows
                chunk.append(jobs[i])
                i += 1
            self._dispatch_chunk(g, chunk, rows)

    def _dispatch_chunk(self, g: _MergeGroup, chunk: list[Job],
                        rows: int) -> None:
        # queue wait stamps at execution start (enqueue → now), summed
        # into the ledger so wait vs device-wall shares are separable
        t_start = self.now()
        queue_wait_ns = 0
        tenant_rows: dict[str, int] = {}
        for j in chunk:
            if j.enqueue_t:
                j.wait_s = max(t_start - j.enqueue_t, 0.0)
                queue_wait_ns += int(j.wait_s * 1e9)
            tenant_rows[j.tenant] = tenant_rows.get(j.tenant, 0) + j.n_rows
        t0 = time.perf_counter()
        bucket = h2d_bytes = 0
        err: "BaseException | None" = None
        try:
            # the WHOLE build+dispatch sits under the guard: a failure
            # anywhere (allocation, a bad job array, the kernel itself)
            # must land on the jobs, never escape to kill the worker
            if faults.ARMED:
                faults.fire("sched.dispatch")
            bucket = bucket_rows(max(rows, 1), self.cfg.min_bucket_rows)
            if g.align > 1 and bucket % g.align:
                # serving mesh: the padded window must split evenly over
                # the 'data' shards for the single shard_map dispatch
                bucket = -(-bucket // g.align) * g.align
            waste = 0
            if g.pack:
                # one row-major f32 matrix = ONE H2D for the whole batch
                mat = np.empty((len(g.pads), bucket), np.float32)
                for role, pad_val in enumerate(g.pads):
                    off = 0
                    for j in chunk:
                        a = j.arrays[role]
                        mat[role, off:off + len(a)] = a
                        off += len(a)
                    mat[role, off:] = pad_val
                waste = (bucket - rows) * mat.dtype.itemsize * len(g.pads)
                padded = [mat]
            else:
                padded = []
                for role, pad_val in enumerate(g.pads):
                    parts = [np.asarray(j.arrays[role]) for j in chunk]
                    cat = parts[0] if len(parts) == 1 \
                        else np.concatenate(parts)
                    if len(cat) < bucket:
                        out = np.full(bucket, pad_val, dtype=cat.dtype)
                        out[: len(cat)] = cat
                        cat = out
                    waste += (bucket - rows) * cat.dtype.itemsize
                    padded.append(cat)
            sig = (g.kernel, bucket) + tuple(a.dtype.str for a in padded)
            occ = rows / bucket
            with self._stats_lock:
                if sig not in self._warm_buckets:
                    self._warm_buckets.add(sig)
                    self.bucket_warmups[g.kernel] = \
                        self.bucket_warmups.get(g.kernel, 0) + 1
                self.occupancy_sum[g.kernel] = \
                    self.occupancy_sum.get(g.kernel, 0.0) + occ
                self.batches_total[g.kernel] = \
                    self.batches_total.get(g.kernel, 0) + 1
                self.coalesced_total[g.kernel] = \
                    self.coalesced_total.get(g.kernel, 0) + len(chunk)
                self.padding_waste_bytes[g.kernel] = \
                    self.padding_waste_bytes.get(g.kernel, 0) + waste
                if g.shards:
                    self._note_shard_stats(g, bucket, rows, waste)
            if g.shards:
                # mesh mode: one occupancy sample PER 'data' shard — rows
                # pack contiguously, so the tail shard carries the
                # padding; a persistently cold last shard means the batch
                # window is closing under-full for this mesh width
                per = bucket // g.shards
                for i in range(g.shards):
                    real = min(max(rows - i * per, 0), per)
                    _OCCUPANCY.observe(real / per, (g.kernel, str(i)))
            else:
                _OCCUPANCY.observe(occ, (g.kernel, ""))
            h2d_bytes = sum(int(a.nbytes) for a in padded)
            # slow dispatches are findable by trace: same span surface
            # as distributor.push / frontend.Search (NoopTracer default
            # costs one dict build per MERGED batch). The span LINKS the
            # coalesced batch back to each contributing request's tree
            # (bounded: a batch is a fan-in, links are how OTel models
            # it) and carries the devtime ledger identity — kernel,
            # bucket, device_ns — so device time is attributable per
            # trace. A single-tenant batch goes through the tenant-aware
            # guard: an all-reserved-tenant batch (loopback self-ingest)
            # must not re-trace itself.
            attrs = {"kernel": g.kernel, "bucket": bucket, "rows": rows,
                     "shard": str(g.shards) if g.shards else ""}
            links = sorted({j.traceparent for j in chunk
                            if j.traceparent is not None})
            if links:
                attrs["link.traceparents"] = ",".join(links[:8])
            tenants = {j.tenant for j in chunk}
            only = next(iter(tenants)) if len(tenants) == 1 else ""
            cm = tracing.span_for_tenant("sched.dispatch", only, **attrs) \
                if only else tracing.span("sched.dispatch", **attrs)
            with cm as sp:
                td0 = time.perf_counter()
                g.dispatch(*padded)
                if sp is not None:
                    sp.attrs["device_ns"] = \
                        int((time.perf_counter() - td0) * 1e9)
        except BaseException as e:           # noqa: BLE001 — propagated
            err = e
            self._note_dispatch_error(g.kernel, e)
        wall_s = time.perf_counter() - t0
        _DISPATCH_SECONDS.observe(wall_s, (g.kernel,))
        # the device-time ledger sees every dispatch (failed ones too —
        # their wall was still spent); the cost model learns only from
        # clean, really-bucketed dispatches so an exploding kernel or a
        # build failure cannot poison the fit
        devtime.LEDGER.record_batch(
            kernel=g.kernel, bucket=bucket, prio=PRIO_INGEST,
            shards=g.shards, wall_ns=int(wall_s * 1e9), rows=rows,
            padded_rows=max(bucket - rows, 0),
            queue_wait_ns=queue_wait_ns, h2d_bytes=h2d_bytes,
            tenant_rows=tenant_rows)
        if err is None and bucket:
            devtime.COST_MODEL.observe(g.kernel, bucket, rows, wall_s)
        t_end = self.now()
        for j in chunk:
            if j.enqueue_t and err is None:
                # ingest-VISIBLE latency per job (window + queue wait +
                # dispatch): the quantity `tuning: auto` minimizes. A
                # failed dispatch dropped its rows — they never became
                # visible, so they must not count as fast ones here
                devtime.INGEST_LATENCY.observe(
                    max(t_end - j.enqueue_t, 0.0), (g.kernel,))
            j.error = err
            j.event.set()

    def _note_shard_stats(self, g: _MergeGroup, bucket: int, rows: int,
                          waste: int) -> None:
        """Per-'data'-shard padding split of a mesh dispatch (caller
        holds _stats_lock). Rows pack contiguously across the shards, so
        padding concentrates on the tail shard."""
        pad_rows = bucket - rows
        if pad_rows <= 0:
            return
        per = bucket // g.shards
        for i in range(g.shards):
            shard_pad = per - min(max(rows - i * per, 0), per)
            if shard_pad:
                key = (g.kernel, str(i))
                self.padding_waste_shard[key] = \
                    self.padding_waste_shard.get(key, 0) \
                    + waste * shard_pad // pad_rows

    def _note_dispatch_error(self, kernel: str, e: BaseException) -> None:
        """Dispatch failures must never be silent: ingest-route jobs are
        fire-and-forget, so the error is counted (exported as
        tempo_sched_dispatch_errors_total) AND logged — a persistently
        failing kernel means updates are being dropped."""
        with self._stats_lock:
            self.dispatch_errors += 1
        _LOG.error("tempo-sched: dispatch of kernel %r failed: %r",
                   kernel, e)

    def _run_fn(self, job: Job) -> None:
        from tempo_tpu.obs import querystats

        if job.enqueue_t:
            job.wait_s = max(self.now() - job.enqueue_t, 0.0)
        t0 = time.perf_counter()
        try:
            # re-enter the submitter's trace context: query-route device
            # work parents under the request's tree across the worker
            # thread boundary (the row-job path links instead — a
            # coalesced batch has many parents, a fn job has one)
            with tracing.adopted(job.traceparent), \
                    tracing.span_for_tenant("sched.dispatch", job.tenant,
                                            kernel=job.kernel, bucket=0,
                                            rows=0, shard="") as sp:
                if job.stats is not None:
                    # adopt the caller's per-request QueryStats on this
                    # thread so the kernel's own recording (device_scan
                    # bytes, kernel wall) lands in the right request scope
                    with querystats.scope(job.stats):
                        job.result = job.fn()
                else:
                    job.result = job.fn()
                if sp is not None:
                    sp.attrs["device_ns"] = \
                        int((time.perf_counter() - t0) * 1e9)
        except BaseException as e:           # noqa: BLE001 — propagated
            # fn jobs have a waiting caller who re-raises and owns the
            # error surface; dispatch_errors stays a dropped-ingest-batch
            # signal (its family help + dashboard panel say so)
            job.error = e
        wall_s = time.perf_counter() - t0
        _DISPATCH_SECONDS.observe(wall_s, (job.kernel,))
        wall_ns = int(wall_s * 1e9)
        # fn jobs ledger under bucket 0 (no coalesced shape); their wall
        # is attributed to the query via QueryStats.device_ns so the
        # qlog line carries the request's device-seconds directly
        devtime.LEDGER.record_batch(
            kernel=job.kernel, bucket=0, prio=job.priority, shards=0,
            wall_ns=wall_ns, rows=0, padded_rows=0,
            queue_wait_ns=int(job.wait_s * 1e9), h2d_bytes=0,
            tenant_rows={job.tenant: 0} if job.tenant else None)
        if job.stats is not None:
            job.stats.add(device_ns=wall_ns)
        job.event.set()


def _group_of(job: Job, pack: bool = False, align: int = 1,
              shards: int = 0) -> _MergeGroup:
    g = _MergeGroup(job.kernel, job.pads, job.dispatch, job.enqueue_t,
                    pack=pack, align=align, shards=shards)
    g.jobs.append(job)
    g.rows = job.n_rows
    return g


# ---------------------------------------------------------------------------
# the process-wide scheduler (configured by App, consulted everywhere)
# ---------------------------------------------------------------------------

_default: "DeviceScheduler | None" = None
_default_lock = threading.Lock()


def configure(cfg: SchedConfig | None = None,
              now: Callable[[], float] = time.monotonic) -> DeviceScheduler:
    """Create or reconfigure the process-wide scheduler (App wiring).
    Like the JAX runtime registry, it is process-level state: several
    Apps in one test process share it, last configuration wins."""
    global _default
    with _default_lock:
        if _default is None:
            _default = DeviceScheduler(cfg, now=now)
        else:
            _default.reconfigure(cfg or SchedConfig())
        return _default


def scheduler() -> "DeviceScheduler | None":
    """The process-wide scheduler, or None when never configured —
    callers fall back to their original synchronous dispatch."""
    return _default


def reset() -> None:
    """Flush + drop the process scheduler (test isolation: a test that
    booted an App must not leave later standalone tests' dispatches
    riding a scheduler they never asked for). The device-time ledger
    and cost model reset with it — they are the scheduler's memory."""
    global _default
    with _default_lock:
        sc, _default = _default, None
    if sc is not None:
        sc.stop(flush=True)
    devtime.reset()


@contextlib.contextmanager
def use(sc: "DeviceScheduler | None"):
    """Install `sc` as the process scheduler for a with-block (tests)."""
    global _default
    with _default_lock:
        prev, _default = _default, sc
    try:
        yield sc
    finally:
        with _default_lock:
            _default = prev


def run(fn: Callable, kernel: str = "fn",
        priority: int = PRIO_QUERY, tenant: str = ""):
    """Route one device-dispatching closure through the process
    scheduler; plain `fn()` when none is configured or it is disabled."""
    sc = _default
    if sc is None or not sc.cfg.enabled:
        return fn()
    return sc.run(fn, kernel=kernel, priority=priority, tenant=tenant)


def flush() -> None:
    """Barrier on the process scheduler, if any (collection ticks,
    state readers)."""
    sc = _default
    if sc is not None and sc.cfg.enabled:
        sc.flush()


def ingest_keep_fraction() -> float:
    """The process-wide overload keep-fraction (1.0 = sampling off):
    the distributor's span sampler and the frontend's query-log
    annotation both read this one signal."""
    sc = _default
    if sc is None:
        return 1.0
    return sc.keep_fraction()


# ---------------------------------------------------------------------------
# obs: scheduler families in the process-wide runtime registry
# ---------------------------------------------------------------------------

from tempo_tpu.obs.jaxruntime import RUNTIME  # noqa: E402
from tempo_tpu.obs.registry import exponential_buckets  # noqa: E402


def _per_class(field: str):
    def fn():
        sc = _default
        if sc is None:
            return []
        return [((c,), float(v)) for c, v in getattr(sc, field).items()]
    return fn


def _per_kernel(field: str):
    def fn():
        sc = _default
        if sc is None:
            return []
        return [((k,), float(v)) for k, v in getattr(sc, field).items()]
    return fn


RUNTIME.gauge_func(
    "tempo_sched_queue_depth",
    lambda: [] if _default is None else
    [((CLASS_NAMES[p],), float(_default.depth(p))) for p in (0, 1, 2)],
    help="Jobs waiting in the device scheduler, by priority class",
    labels=("class",))
RUNTIME.gauge_func(
    "tempo_sched_queue_limit",
    lambda: [] if _default is None else
    [((CLASS_NAMES[p],), float(_default._limit(p))) for p in (0, 1, 2)],
    help="Bounded queue capacity per priority class (saturation "
         "denominator for alerting)",
    labels=("class",))
RUNTIME.counter_func(
    "tempo_sched_jobs_total", _per_class("jobs_total"),
    help="Jobs accepted by the device scheduler, by priority class",
    labels=("class",))
RUNTIME.counter_func(
    "tempo_sched_shed_jobs_total", _per_class("shed_total"),
    help="Jobs shed to inline execution because their class queue was "
         "full (sustained shedding means the device is the bottleneck)",
    labels=("class",))
RUNTIME.counter_func(
    "tempo_sched_batches_total", _per_kernel("batches_total"),
    help="Merged batches dispatched, by kernel",
    labels=("kernel",))
RUNTIME.counter_func(
    "tempo_sched_coalesced_jobs_total", _per_kernel("coalesced_total"),
    help="Row jobs folded into merged batches, by kernel "
         "(coalesced/batches = jobs amortized per dispatch)",
    labels=("kernel",))
def _padding_waste_rows():
    """Padding waste with the serving-mesh `shard` split: per-shard rows
    for mesh dispatches, the remaining (non-mesh) waste under shard="" —
    the label values sum to the true per-kernel total, no double count."""
    sc = _default
    if sc is None:
        return []
    # snapshot under the stats lock: padding_waste_shard grows at
    # dispatch time and a concurrent scrape iterating a resizing dict
    # would raise and 500 the whole /metrics render
    with sc._stats_lock:
        shard_items = list(sc.padding_waste_shard.items())
        kernel_items = list(sc.padding_waste_bytes.items())
    out = []
    sharded_by_kernel: dict[str, int] = {}
    for (k, s), v in shard_items:
        out.append(((k, s), float(v)))
        sharded_by_kernel[k] = sharded_by_kernel.get(k, 0) + v
    for k, v in kernel_items:
        rest = v - sharded_by_kernel.get(k, 0)
        if rest or k not in sharded_by_kernel:
            out.append(((k, ""), float(max(rest, 0))))
    return out


RUNTIME.counter_func(
    "tempo_sched_padding_waste_bytes_total",
    _padding_waste_rows,
    help="Bytes of pow-2 padding dispatched beyond real rows, by kernel "
         "(the price of the shape-bucket jit cache); serving-mesh "
         "dispatches additionally split by 'data' shard (non-mesh waste "
         "keeps shard=\"\")",
    labels=("kernel", "shard"))
RUNTIME.counter_func(
    "tempo_sched_bucket_warmups_total", _per_kernel("bucket_warmups"),
    help="First-time (kernel, shape-bucket) combinations dispatched; "
         "flat after warmup means zero steady-state re-traces",
    labels=("kernel",))
RUNTIME.gauge_func(
    "tempo_sched_ingest_keep_fraction",
    lambda: [] if _default is None else
    [((), float(_default.keep_fraction()))],
    help="Overload controller's current span keep-fraction (1.0 = "
         "sampling off; below 1.0 the distributor hash-samples "
         "non-forced spans before hard 429)")
RUNTIME.counter_func(
    "tempo_sched_dispatch_errors_total",
    lambda: [] if _default is None else
    [((), float(_default.dispatch_errors))],
    help="Scheduler dispatches that raised (fire-and-forget ingest "
         "batches were DROPPED; also logged on tempo_tpu.sched)")
RUNTIME.counter_func(
    "tempo_sched_compaction_forced_dispatches_total",
    lambda: [] if _default is None else
    [((), float(_default.comp_forced_total))],
    help="Compaction jobs force-dispatched by the anti-starvation floor "
         "(sched.compaction_min_share) while ingest/query stayed busy")
RUNTIME.gauge_func(
    "tempo_sched_tuned_window_ms",
    lambda: [] if _default is None else
    [((k,), float(ms)) for k, ms in _default._tuner.windows_ms()],
    help="Batch window currently chosen by `tuning: auto` per kernel, "
         "milliseconds (absent until the cost model is warm; compare "
         "against the static sched.batch_window_ms)",
    labels=("kernel",))
RUNTIME.gauge_func(
    "tempo_sched_tuning_active",
    lambda: [] if _default is None else
    [((), 1.0 if _default.tuning_active() else 0.0)],
    help="1 while `tuning: auto` is driving batch windows from a warm "
         "cost model, 0 in static mode or during model warm-up "
         "(TempoSchedCostModelStale only fires while this is 1)")
_OCCUPANCY = RUNTIME.histogram(
    "tempo_sched_batch_occupancy_ratio",
    "Real rows / padded bucket rows per merged batch (the ISSUE floor "
    "is 0.7 at steady state); serving-mesh dispatches observe one "
    "sample per 'data' shard (non-mesh batches keep shard=\"\")",
    labels=("kernel", "shard"),
    buckets=(0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0))
_DISPATCH_SECONDS = RUNTIME.histogram(
    "tempo_sched_dispatch_duration_seconds",
    "Wall time of one scheduler dispatch (merged batch or fn job), by "
    "kernel", labels=("kernel",),
    buckets=exponential_buckets(1e-5, 4.0, 12))
_QUEUE_WAIT = RUNTIME.histogram(
    "tempo_sched_queue_wait_seconds",
    "Time a scheduled job waited between enqueue and completion, by "
    "priority class", labels=("class",),
    buckets=exponential_buckets(1e-5, 4.0, 12))


__all__ = [
    "PRIO_INGEST", "PRIO_QUERY", "PRIO_COMPACTION", "CLASS_NAMES",
    "SchedConfig", "QueryBackpressure", "Job", "WindowTuner",
    "DeviceScheduler", "bucket_rows", "configure", "scheduler", "use",
    "run", "flush", "reset", "fraction_for_pressure",
    "ingest_keep_fraction",
]
