"""TraceQL metrics engine: `query_range` aggregation on device grids.

Reference: `pkg/traceql/engine_metrics.go`. The reference's aggregator stack
(`GroupingAggregator` → per-series `StepAggregator` → `VectorAggregator`,
engine_metrics.go:332-537) walks spans one at a time; here each batch of
matching spans becomes three aligned vectors (series slot, step index,
value) and ONE scatter op updates a `[series, steps]` (or
`[series, steps, 64]` for histograms) device grid:

    rate/count_over_time  → grid.at[slot, step].add(w)
    min/max_over_time     → grid.at[slot, step].min/max(v)
    sum/avg_over_time     → add grids (+ count grid for avg)
    quantile/histogram    → grid.at[slot, step, log2bucket(v)].add(w)

Job-level results are raw series (AggregateModeSum); the frontend combiner
sums them and computes quantiles from log2 buckets with linear interpolation
— `Log2Quantile` (engine_metrics.go:1402-1468) — so cross-shard merges stay
pure tensor adds (psum-able across a mesh).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Iterable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from tempo_tpu.obs import querystats
from tempo_tpu.ops import moments as msk
from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.conditions import extract_conditions
from tempo_tpu.traceql.eval import (NUM, Col, ColumnView, eval_expr,
                                    evaluate_pipeline, resolve_attr)
from tempo_tpu.traceql.parser import parse

# log2 histogram geometry (shared with `pkg/traceqlmetrics` 64-bucket layout)
HBUCKETS = 64
# bucket b holds values in (2^(b-1), 2^b] nanoseconds; b=0 holds <=1ns
_LABEL_BUCKET = "__bucket"
_LABEL_META = "__meta_type"
# moments tier (`spanmetrics.sketch: moments`, ops/moments.py): instead
# of 64 `__bucket` series per group, quantile_over_time ships k+1 moment
# series (label value "0".."k": count + Chebyshev log-moment sums, merge
# = ADD) plus two support-bound series ("hi"/"lo": shifted running
# maxes, merge = MAX) — ~15 series of plain tensor-adds per group, the
# psum-only combine of the moments sketch
_LABEL_MOMENT = "__moment"


def _moment_bound_labels(labels) -> bool:
    """True for the two max-merged support-bound series of a moments
    quantile group (every other series in a combine sums)."""
    for k, v in labels:
        if k == _LABEL_MOMENT:
            return v in ("hi", "lo")
    return False


def _moment_labels(labels) -> bool:
    for k, _v in labels:
        if k == _LABEL_MOMENT:
            return True
    return False


def log2_bucket_np(values_ns: np.ndarray) -> np.ndarray:
    v = np.maximum(values_ns.astype(np.float64), 1.0)
    return np.clip(np.ceil(np.log2(v)), 0, HBUCKETS - 1).astype(np.int32)


def log2_quantile(q: float, buckets: np.ndarray) -> float:
    """Interpolated quantile from a [HBUCKETS] count vector; returns seconds.

    Mirrors `Log2Quantile` (engine_metrics.go:1402): find the bucket holding
    the q-th sample, then interpolate within its (2^(b-1), 2^b] range.
    """
    total = buckets.sum()
    if total <= 0:
        return 0.0
    target = max(q * total, 1e-12)  # q=0 → lower edge of first nonempty bucket
    csum = np.cumsum(buckets)
    b = int(np.searchsorted(csum, target, side="left"))
    b = min(b, HBUCKETS - 1)
    prev = csum[b - 1] if b > 0 else 0.0
    inbucket = buckets[b]
    frac = (target - prev) / inbucket if inbucket > 0 else 0.0
    lo = 0.0 if b == 0 else 2.0 ** (b - 1)
    hi = 2.0 ** b
    return (lo + (hi - lo) * frac) / 1e9


def _fold_cumulative(g: np.ndarray) -> np.ndarray:
    """The per-series cumulative-count fold of a [steps, B] bucket grid
    — factored out so `log2_quantiles_multi` provably runs it ONCE for
    any number of requested q's (tests count invocations)."""
    return np.cumsum(g, axis=1)


def log2_quantiles_multi(qs, g: np.ndarray) -> np.ndarray:
    """Every requested quantile of a [steps, HBUCKETS] grid from ONE
    cumulative fold: returns [len(qs), steps] seconds. Exactly the
    per-step `log2_quantile` math, vectorized over steps and evaluated
    for all q's off the shared cumulative counts (a multi-param
    `quantile_over_time(duration, .5, .9, .99)` used to refold the
    summed grid once per parameter)."""
    g = np.asarray(g, np.float64)
    cum = _fold_cumulative(g)
    total = cum[:, -1]
    steps = np.arange(g.shape[0])
    out = np.zeros((len(qs), g.shape[0]), np.float64)
    for qi, q in enumerate(qs):
        target = np.maximum(q * total, 1e-12)
        b = np.minimum((cum < target[:, None]).sum(axis=1), HBUCKETS - 1)
        prev = np.where(b > 0, cum[steps, np.maximum(b - 1, 0)], 0.0)
        inbucket = g[steps, b]
        frac = np.where(inbucket > 0, (target - prev) / np.maximum(
            inbucket, 1e-300), 0.0)
        lo = np.where(b == 0, 0.0, np.exp2(b - 1.0))
        hi = np.exp2(b.astype(np.float64))
        out[qi] = np.where(total > 0, (lo + (hi - lo) * frac) / 1e9, 0.0)
    return out


@dataclasses.dataclass
class QueryRangeRequest:
    query: str
    start_ns: int
    end_ns: int
    step_ns: int
    exemplars: int = 100
    # force the moments aggregation axis for this request regardless of
    # the process-global tier: the frontend sets it when the sidecar fold
    # path serves part of the window, so generator + scan-fallback shards
    # emit __moment series that combine with the folds instead of log2
    # __bucket series that would double-count the ("p", q) output
    moments: bool = False

    @property
    def n_steps(self) -> int:
        # exact integer ceiling: float64 division can round the quotient
        # and disagree with the device grid's integer math on huge windows
        return max(-(-(self.end_ns - self.start_ns) // self.step_ns), 1)

    def step_timestamps_ms(self) -> list[int]:
        # samples are stamped at interval END, like IntervalOfMs consumers
        return [int((self.start_ns + (i + 1) * self.step_ns) / 1e6)
                for i in range(self.n_steps)]


@dataclasses.dataclass
class TimeSeries:
    labels: tuple            # ((name, value), ...)
    samples: np.ndarray      # [n_steps] float64
    exemplars: list = dataclasses.field(default_factory=list)

    def key(self) -> tuple:
        return self.labels

    def to_json(self, ts_ms: list[int]) -> dict:
        return {
            "labels": [{"key": k, "value": {"stringValue": str(v)}}
                       for k, v in self.labels],
            "samples": [{"timestampMs": str(t), "value": float(v)}
                        for t, v in zip(ts_ms, self.samples)],
            "exemplars": self.exemplars,
        }


# ---------------------------------------------------------------------------
# device kernels (jit-cached per (capacity, steps) shape bucket)
# ---------------------------------------------------------------------------

# instrumented (obs/jaxruntime compile counters) so the scheduler's
# zero-steady-state-recompile guarantee is verifiable per kernel; the
# pow-2 padding below keeps the shape set bucketed and finite
from tempo_tpu.obs.jaxruntime import instrumented_jit


def _scatter_add2_impl(grid, slots, steps, w):
    return grid.at[slots, steps].add(w, mode="drop")


def _scatter_min2_impl(grid, slots, steps, v):
    return grid.at[slots, steps].min(v, mode="drop")


def _scatter_max2_impl(grid, slots, steps, v):
    return grid.at[slots, steps].max(v, mode="drop")


def _scatter_add3_impl(grid, slots, steps, buckets, w):
    return grid.at[slots, steps, buckets].add(w, mode="drop")


def _scatter_moments_impl(mmt, mhi, mlo, slots, steps, z):
    """ONE dispatch for the whole moments-tier observation: the clipped
    log values `z` [n] ride a single H2D (vs shipping the [n, k+1]
    basis matrix), the Chebyshev basis recurrence runs on device, and
    all three grids (moment sums + the two support-bound planes) update
    together. Grids are donated."""
    from tempo_tpu.ops import moments as _msk
    jnp_ = jax.numpy
    c0 = (_msk.QUERY_LO + _msk.QUERY_HI) / 2.0
    h0 = (_msk.QUERY_HI - _msk.QUERY_LO) / 2.0
    s = jnp_.clip((z - c0) / h0, -1.0, 1.0)
    basis = jnp_.stack(_msk.chebyshev_basis(s, _msk.QUERY_K), axis=-1)
    cols = jnp_.arange(basis.shape[1], dtype=jnp_.int32)
    mmt = mmt.at[slots[:, None], steps[:, None], cols[None, :]].add(
        basis, mode="drop")
    mhi = mhi.at[slots, steps].max(z - _msk.QUERY_LO, mode="drop")
    mlo = mlo.at[slots, steps].max(_msk.QUERY_HI - z, mode="drop")
    return mmt, mhi, mlo


def _add_dense_impl(grid, delta):
    return grid + delta


_scatter_add2 = instrumented_jit(_scatter_add2_impl,
                                 name="engine_scatter_add2",
                                 donate_argnums=0)
_add_dense = instrumented_jit(_add_dense_impl,
                              name="engine_add_dense",
                              donate_argnums=0)
_scatter_min2 = instrumented_jit(_scatter_min2_impl,
                                 name="engine_scatter_min2",
                                 donate_argnums=0)
_scatter_max2 = instrumented_jit(_scatter_max2_impl,
                                 name="engine_scatter_max2",
                                 donate_argnums=0)
_scatter_add3 = instrumented_jit(_scatter_add3_impl,
                                 name="engine_scatter_add3",
                                 donate_argnums=0)
_scatter_moments = instrumented_jit(_scatter_moments_impl,
                                    name="engine_scatter_moments",
                                    donate_argnums=(0, 1, 2))


def _sched_scatter(fn, *args, kernel: str = "engine_metrics_scatter"):
    """Run one grid-scatter dispatch through the shared device scheduler
    (query class): ingest batches order ahead, the dispatch is counted,
    and an idle scheduler adds zero latency (inline fast path). Direct
    call when no scheduler is configured. `kernel` names the devtime
    ledger class — the batched flush dispatches under its own name so
    the cost model learns its (much larger) bucket sizes separately."""
    from tempo_tpu import sched

    return sched.run(lambda: fn(*args), kernel=kernel)


def _pad_pow2(n: int, lo: int = 256) -> int:
    # the ONE shape-bucket policy, shared with the device scheduler's
    # coalescer (sched.bucket_rows) so the jit shape cache can't split
    from tempo_tpu.sched import bucket_rows

    return bucket_rows(n, lo)


class SeriesIndex:
    """Host-side series table: group-key tuple → dense slot (the string side
    of `GroupingAggregator`; device arrays never see strings). Shared by
    the per-request evaluator below and the standing materialized-view
    grids (`tempo_tpu.matview`), which must mint identical label keys."""

    def __init__(self):
        self.slots: dict[tuple, int] = {}
        self.keys: list[tuple] = []

    def lookup(self, keys: list[tuple]) -> np.ndarray:
        out = np.empty(len(keys), np.int32)
        for i, k in enumerate(keys):
            s = self.slots.get(k)
            if s is None:
                s = self.slots[k] = len(self.keys)
                self.keys.append(k)
            out[i] = s
        return out

    def __len__(self) -> int:
        return len(self.keys)


_SeriesIndex = SeriesIndex   # former (pre-matview) private name


def matching_rows(q: A.Pipeline, fetch_req, need_second_pass: bool,
                  view: ColumnView) -> np.ndarray:
    """Row indices of `view` matched by the query's filter stages —
    pushdown mask when the conditions cover the query, full pipeline
    evaluation otherwise. Shared by `MetricsEvaluator` and the matview
    appender so a materialized grid can never disagree with the
    recompute path about which spans count."""
    if not need_second_pass:
        from tempo_tpu.block.fetch import condition_mask

        mask = condition_mask(view, fetch_req)
        if mask.all():   # unfiltered scan: arange beats the mask walk
            return np.arange(len(mask), dtype=np.int64)
        return np.flatnonzero(mask)
    stripped = A.Pipeline(q.stages)  # pipeline minus metrics stage
    spansets = evaluate_pipeline(stripped, view)
    if not spansets:
        return np.empty(0, np.int64)
    return np.unique(np.concatenate([ss.rows for ss in spansets]))


# composed-key bincount ceiling: beyond this unique-combo product the
# dense count array would dwarf the row vectors and np.unique wins
_COMPOSE_BINCOUNT_CAP = 1 << 22


def group_slots(by, series: SeriesIndex, view: ColumnView,
                rows: np.ndarray):
    """(keep_mask, slots[int32]) or None when there's no by().

    Vectorized: each group column factorizes to integer codes, codes
    compose into one key per row, and only UNIQUE combos build Python
    label tuples — the per-span tuple loop of `GroupingAggregator`
    becomes O(distinct series) host work. Shared with the matview
    appender (same label formatting → same series keys)."""
    if not by:
        return None
    cols = [(str(e), eval_expr(view, e)) for e in by]
    keep = np.ones(len(rows), bool)
    for _, c in cols:
        # spans missing a group key are dropped; fully-present columns
        # (the common case for intrinsics) skip the per-row gather
        if not c.exists.all():
            keep &= c.exists[rows]
    kept = rows if keep.all() else rows[keep]
    if len(kept) == 0:
        return keep, np.zeros(0, np.int32)
    if len(cols) == 1 and cols[0][1].codes is not None \
            and cols[0][1].code_values is not None:
        # single dictionary-coded key (the dominant group shape): map
        # dict id → series slot through one LUT — two O(n) passes
        # (bincount + gather), no compose round trip
        name, c = cols[0]
        ck = c.codes if len(kept) == len(c.codes) else c.codes[kept]
        cv = c.code_values
        u_ids = np.flatnonzero(np.bincount(ck, minlength=len(cv)))
        uslots = series.lookup(
            [((name, _fmt_label(cv[cid], c.t)),) for cid in u_ids.tolist()])
        slot_lut = np.zeros(len(cv), np.int32)
        slot_lut[u_ids] = uslots
        return keep, slot_lut[ck]
    codes: list[np.ndarray] = []
    uniqs: list[tuple[str, np.ndarray, str]] = []
    for name, c in cols:
        if c.codes is not None and c.code_values is not None:
            # dictionary/interner sidecar: factorize int32 codes instead
            # of converting the object column to unicode per query. The
            # ids are already dense in [0, len(code_values)), so a
            # bincount + LUT gather (all O(n), no sort) replaces
            # np.unique's argsort; flatnonzero yields the same ascending
            # id order unique would. Any code→string mapping yields
            # identical series keys (SeriesIndex dedupes by key tuple).
            ck = c.codes if len(kept) == len(c.codes) else c.codes[kept]
            cv = c.code_values
            u_ids = np.flatnonzero(np.bincount(ck, minlength=len(cv)))
            lut = np.zeros(len(cv), np.int64)
            lut[u_ids] = np.arange(len(u_ids))
            u = np.empty(len(u_ids), object)
            for k, cid in enumerate(u_ids.tolist()):
                u[k] = cv[cid]
            codes.append(lut[ck])
            uniqs.append((name, u, c.t))
            continue
        vals = c.values[kept]
        if vals.dtype == object:    # python-object compares are O(n) py
            vals = vals.astype("U")
        u, inv = np.unique(vals, return_inverse=True)
        codes.append(inv.astype(np.int64))
        uniqs.append((name, u, c.t))
    comp = codes[0]
    prod = len(uniqs[0][1])
    for code, (_, u, _) in zip(codes[1:], uniqs[1:]):
        comp = comp * len(u) + code
        prod *= len(u)
    if prod <= _COMPOSE_BINCOUNT_CAP:
        # composed codes are bounded by the per-column unique-count
        # product: when that fits, the same bincount + LUT trick avoids
        # the O(n log n) unique over 1M-row scans. Each unique combo
        # decomposes back into per-column unique indices by division
        # (the mixed-radix inverse of the compose above).
        ucomp = np.flatnonzero(np.bincount(comp, minlength=prod))
        lut = np.zeros(prod, np.int64)
        lut[ucomp] = np.arange(len(ucomp))
        inv = lut[comp]
        tuples = []
        for v in ucomp.tolist():
            parts = []
            for _, u, _ in reversed(uniqs[1:]):
                v, ci = divmod(v, len(u))
                parts.append(ci)
            parts.append(v)
            parts.reverse()
            tuples.append(tuple(
                (name, _fmt_label(u[ci], t))
                for (name, u, t), ci in zip(uniqs, parts)))
    else:
        ucomp, first, inv = np.unique(comp, return_index=True,
                                      return_inverse=True)
        tuples = [
            tuple((name, _fmt_label(u[codes[k][fi]], t))
                  for k, (name, u, t) in enumerate(uniqs))
            for fi in first.tolist()
        ]
    uslots = series.lookup(tuples)
    return keep, uslots[inv].astype(np.int32)


class MetricsEvaluator:
    """Raw (storage-level) evaluator: observe batches, hold device grids.

    `CompileMetricsQueryRange` analog (engine_metrics.go:802): one instance
    per job; `observe(view)` per scan batch; `results()` → job-level series.
    """

    def __init__(self, req: QueryRangeRequest,
                 clip_start_ns: int | None = None,
                 clip_end_ns: int | None = None,
                 batched: bool = False):
        self.req = req
        # batched observation (the host-fallback path of db/tempodb.py):
        # observe() stages each view's (slots, steps, vals) vectors on
        # host and flush() issues ONE padded scatter dispatch per grid
        # over the concatenation — per-view H2D + dispatch becomes a
        # single device round per query. compare() keeps its per-view
        # dispatches (its series mint per (attr, value) row-wise).
        self._batched = bool(batched)
        self._staged: list[tuple] = []
        # observation clip: sub-requests (backend jobs vs generator window)
        # keep the FULL step grid but only observe spans inside their slice,
        # so combiner tensor-adds line up and the cutoff dedupes sources
        # (the TrimToBefore/After split, metrics_query_range_sharder.go:178)
        self.clip_start_ns = max(req.start_ns, clip_start_ns or req.start_ns)
        self.clip_end_ns = min(req.end_ns, clip_end_ns or req.end_ns)
        self.q = parse(req.query)
        if self.q.metrics is None:
            raise ValueError("not a metrics query: " + req.query)
        self.m = self.q.metrics
        self.fetch_req = extract_conditions(self.q, req.start_ns, req.end_ns)
        self.series = SeriesIndex()
        self.n_steps = req.n_steps
        self._cap = 0
        self._grids: dict[str, jax.Array] = {}
        self._exemplars: dict[int, list] = {}
        self._ex_total = 0
        k = self.m.kind
        # moments query tier: quantile_over_time accumulates
        # [series, steps, k+1] moment grids + two bound planes instead
        # of the [series, steps, 64] log2 grid (histogram_over_time
        # keeps buckets — its OUTPUT is the buckets)
        self._moments = (k == A.MetricsKind.QUANTILE_OVER_TIME
                         and (msk.query_moments_active()
                              or getattr(req, "moments", False)))
        self._hist = k in (A.MetricsKind.QUANTILE_OVER_TIME,
                           A.MetricsKind.HISTOGRAM_OVER_TIME) \
            and not self._moments
        self._is_compare = k == A.MetricsKind.COMPARE
        # `| rate()` with a single filter needs no second pass when the
        # pushdown covers it (optimize() engine_metrics.go:885)
        self._need_second_pass = not (
            self.fetch_req.all_conditions
            and k in (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME)
            and not self._is_compare)

    # -- state management ---------------------------------------------------

    def _ensure_capacity(self) -> None:
        need = _pad_pow2(max(len(self.series), 1))
        if need <= self._cap:
            return
        old, self._cap = self._grids, need

        def grow(name, fill, shape_tail=()):
            g = jnp.full((need, self.n_steps) + shape_tail, fill, jnp.float32)
            if name in old:
                o = old[name]
                g = g.at[: o.shape[0]].set(o)
            self._grids[name] = g

        k = self.m.kind
        if self._moments:
            grow("mmt", 0.0, (msk.QUERY_K + 1,))
            grow("mhi", 0.0)   # max(log v − QUERY_LO): 0 == no data
            grow("mlo", 0.0)   # max(QUERY_HI − log v)
        elif self._hist:
            grow("hist", 0.0, (HBUCKETS,))
        elif k in (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME):
            grow("count", 0.0)
        elif k == A.MetricsKind.MIN_OVER_TIME:
            grow("min", jnp.inf)
        elif k == A.MetricsKind.MAX_OVER_TIME:
            grow("max", -jnp.inf)
        elif k == A.MetricsKind.SUM_OVER_TIME:
            grow("sum", 0.0)
        elif k == A.MetricsKind.AVG_OVER_TIME:
            grow("sum", 0.0)
            grow("count", 0.0)
        elif self._is_compare:
            grow("sel", 0.0)
            grow("base", 0.0)

    # -- observation --------------------------------------------------------

    def observe(self, view: ColumnView) -> None:
        with querystats.stage("engine_eval"):
            self._observe(view)

    def _observe(self, view: ColumnView) -> None:
        rows = self._matching_rows(view)
        querystats.add(inspected_spans=len(rows))
        if len(rows) == 0:
            return
        st = view.col("__startTime")
        if st is None:
            return
        # all-true masks skip their gathers: a resident scan observing a
        # covering window would otherwise pay several 1M-row boolean
        # gathers that move nothing (the .all() probe is ~10× cheaper)
        ts = st.values if len(rows) == len(st.values) else st.values[rows]
        # floor (not truncate): step >= 0 must mean ts >= start exactly,
        # so the ts bound checks below can be skipped when they are
        # implied by the step bounds
        step = np.floor((ts - self.req.start_ns) /
                        self.req.step_ns).astype(np.int32)
        inside = (step >= 0) & (step < self.n_steps)
        # the ts bounds only cut when the clip window is narrower than
        # the step grid itself (sharded sub-requests); the unclipped
        # case skips two more 1M-row comparison passes
        grid_end = self.req.start_ns + self.n_steps * self.req.step_ns
        if self.clip_start_ns > self.req.start_ns or self.clip_end_ns < grid_end:
            inside &= (ts >= self.clip_start_ns) & (ts < self.clip_end_ns)
        if not inside.all():
            rows, step = rows[inside], step[inside]
        if len(rows) == 0:
            return

        if self._is_compare:
            self._observe_compare(view, rows, step)
            return

        # group-by key columns → host series slots
        grouped = self._group_slots(view, rows)
        if grouped is None:
            slots = np.zeros(len(rows), np.int32)
            self.series.lookup([()])
        else:
            keep, slots = grouped
            if not keep.all():
                rows, step = rows[keep], step[keep]
            if len(rows) == 0:
                return

        vals = None
        if self.m.attr is not None:
            c = eval_expr(view, self.m.attr)
            if c.t != NUM:
                return
            vexists = c.exists[rows]
            if not vexists.all():
                rows, step, slots = (rows[vexists], step[vexists],
                                     slots[vexists])
            if len(rows) == 0:
                return
            vals = c.values[rows].astype(np.float64)
            # duration intrinsics aggregate in SECONDS (reference converts
            # ns→s before the vector aggregators); histogram buckets keep ns
            # since log2 geometry is scale-consistent (labels divide by 1e9)
            # — the moments grids keep ns the same way (the final solve
            # divides by 1e9, mirroring log2_quantile)
            if not self._hist and not self._moments \
                    and _is_duration_attr(self.m.attr):
                vals = vals / 1e9

        if self._batched:
            # stage and return: slot ids are already minted (series
            # capacity only grows), so the flush pass can concatenate
            # across views and pad against the FINAL capacity
            self._staged.append((slots, step, vals))
            self._note_exemplars(view, rows, slots)
            return
        self._dispatch(slots, step, vals)
        self._note_exemplars(view, rows, slots)

    def flush(self) -> None:
        """Drain batched staging: concatenate every staged view's
        (slots, steps, vals) vectors and issue ONE dispatch per grid
        (`results()` calls this, so explicit use is only needed for
        mid-query grid reads).

        Add-mergeable kinds (count/rate/sum/avg/histogram) fold the
        concatenation into a DENSE grid-shaped delta with one host
        bincount pass — grid + delta is the scatter, so the device round
        ships [cap, steps(, buckets)] floats instead of row vectors and
        the dispatch cost no longer scales with row count at all.
        Order-insensitive min/max and the moments recurrence keep the
        padded row scatter, still one dispatch per grid per flush."""
        if not self._staged:
            return
        staged, self._staged = self._staged, []
        with querystats.stage("engine_eval"):
            if self._flush_dense(staged):
                return
            slots = np.concatenate([s for s, _, _ in staged])
            step = np.concatenate([t for _, t, _ in staged])
            vals = (np.concatenate([v for _, _, v in staged])
                    if staged[0][2] is not None else None)
            self._dispatch(slots, step, vals, kernel_suffix="_batched")

    def _flush_dense(self, staged: list[tuple]) -> bool:
        """Dense-delta flush for the add-merge kinds: fold each staged
        chunk into the grid-shaped delta (no 1M-row concatenation) and
        ship it in one device add per grid. False → caller falls back
        to the padded row scatter."""
        k = self.m.kind
        if self._moments or k in (A.MetricsKind.MIN_OVER_TIME,
                                  A.MetricsKind.MAX_OVER_TIME):
            return False
        want_sum = k in (A.MetricsKind.SUM_OVER_TIME,
                         A.MetricsKind.AVG_OVER_TIME)
        want_count = k in (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME,
                           A.MetricsKind.AVG_OVER_TIME)
        if not (self._hist or want_sum or want_count):
            return False
        self._ensure_capacity()
        cap, S = self._cap, self.n_steps
        deltas: dict[str, np.ndarray] = {}

        def fold(name, m, flat, weights=None):
            d = deltas.get(name)
            if d is None:
                d = deltas[name] = np.zeros(m, np.float64)
            d += np.bincount(flat, weights=weights, minlength=m)

        for slots, step, vals in staged:
            flat = slots * np.int32(S) + step  # int32: cap*S is tiny
            if self._hist:
                b = log2_bucket_np(vals).astype(np.int64)
                fold("hist", cap * S * HBUCKETS,
                     flat.astype(np.int64) * HBUCKETS + b)
            if want_sum:
                fold("sum", cap * S, flat, vals)
            if want_count:
                fold("count", cap * S, flat)
        shape = (cap, S, HBUCKETS) if self._hist else (cap, S)
        for name, d in deltas.items():
            self._grids[name] = _sched_scatter(
                _add_dense, self._grids[name],
                jnp.asarray(d.astype(np.float32).reshape(shape)),
                kernel="engine_metrics_scatter_batched")
        return True

    def _dispatch(self, slots: np.ndarray, step: np.ndarray,
                  vals, kernel_suffix: str = "") -> None:
        """One padded scatter round per grid over row-aligned update
        vectors — the shared tail of the per-view and batched paths."""
        self._ensure_capacity()
        n = len(slots)
        # pad update vectors to pow2 sizes: stable shapes → one jit cache
        # entry per bucket. Padding rows use slot index == capacity, which is
        # out of bounds and dropped (mode="drop"); never -1 (jax wraps it).
        size = _pad_pow2(n, 64)
        pad = size - n
        jslots = jnp.asarray(np.pad(slots, (0, pad), constant_values=self._cap))
        jsteps = jnp.asarray(np.pad(step.astype(np.int32), (0, pad)))
        ones = jnp.asarray(np.pad(np.ones(n, np.float32), (0, pad)))
        jvals = (jnp.asarray(np.pad(vals.astype(np.float32), (0, pad)))
                 if vals is not None else None)
        _scatter = lambda fn, *args: _sched_scatter(
            fn, *args, kernel="engine_metrics_scatter" + kernel_suffix)
        k = self.m.kind
        if self._moments:
            # ~15 floats per (series, step) instead of 64 buckets: ship
            # the clipped log values ONCE ([n] f32 — not the [n, k+1]
            # basis), compute the Chebyshev recurrence on device, and
            # update moment sums + both support-bound planes in a
            # single dispatch. Padding rows carry slot == capacity and
            # drop on device (mode="drop"), like every other grid
            # scatter here; their z value is arbitrary.
            z = np.log(np.clip(vals, math.exp(msk.QUERY_LO),
                               math.exp(msk.QUERY_HI))).astype(np.float32)
            jz = jnp.asarray(np.pad(z, (0, pad),
                                    constant_values=msk.QUERY_LO))
            (self._grids["mmt"], self._grids["mhi"],
             self._grids["mlo"]) = _scatter(
                _scatter_moments, self._grids["mmt"], self._grids["mhi"],
                self._grids["mlo"], jslots, jsteps, jz)
        elif self._hist:
            b = jnp.asarray(np.pad(log2_bucket_np(vals), (0, pad)))
            self._grids["hist"] = _scatter(
                _scatter_add3, self._grids["hist"], jslots, jsteps, b, ones)
        elif k in (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME):
            self._grids["count"] = _scatter(
                _scatter_add2, self._grids["count"], jslots, jsteps, ones)
        elif k == A.MetricsKind.MIN_OVER_TIME:
            self._grids["min"] = _scatter(
                _scatter_min2, self._grids["min"], jslots, jsteps, jvals)
        elif k == A.MetricsKind.MAX_OVER_TIME:
            self._grids["max"] = _scatter(
                _scatter_max2, self._grids["max"], jslots, jsteps, jvals)
        elif k == A.MetricsKind.SUM_OVER_TIME:
            self._grids["sum"] = _scatter(
                _scatter_add2, self._grids["sum"], jslots, jsteps, jvals)
        elif k == A.MetricsKind.AVG_OVER_TIME:
            self._grids["sum"] = _scatter(
                _scatter_add2, self._grids["sum"], jslots, jsteps, jvals)
            self._grids["count"] = _scatter(
                _scatter_add2, self._grids["count"], jslots, jsteps, ones)

    def _matching_rows(self, view: ColumnView) -> np.ndarray:
        return matching_rows(self.q, self.fetch_req,
                             self._need_second_pass, view)

    def _group_slots(self, view: ColumnView, rows: np.ndarray):
        return group_slots(self.m.by, self.series, view, rows)

    def _observe_compare(self, view: ColumnView, rows: np.ndarray,
                         step: np.ndarray) -> None:
        sel_mask = eval_expr(view, self.m.compare_filter).bool_mask()[rows]
        # count by (attr, value) across a default set of comparison columns:
        # status + every span attribute present (approximation of the
        # reference's dynamic attr diff, engine_metrics_compare.go)
        self._ensure_capacity()
        for which, m in (("selection", sel_mask), ("baseline", ~sel_mask)):
            r, s = rows[m], step[m]
            if len(r) == 0:
                continue
            status = view.col("status")
            keys = [((_LABEL_META, which), ("status", _fmt_label(status.values[x], "status")))
                    for x in r]
            slots = self.series.lookup(keys)
            self._ensure_capacity()
            size = _pad_pow2(len(r), 64)
            pad = size - len(r)
            g = "sel" if which == "selection" else "base"
            self._grids[g] = _sched_scatter(
                _scatter_add2, self._grids[g],
                jnp.asarray(np.pad(slots, (0, pad), constant_values=self._cap)),
                jnp.asarray(np.pad(s.astype(np.int32), (0, pad))),
                jnp.asarray(np.pad(np.ones(len(r), np.float32), (0, pad))))

    def _note_exemplars(self, view, rows, slots) -> None:
        if self.req.exemplars <= 0 or self._ex_total >= self.req.exemplars:
            return
        tid = view.col("trace:id")
        dur = view.col("duration")
        if tid is None:
            return
        for r, s in zip(rows[:8], slots[:8]):
            lst = self._exemplars.setdefault(int(s), [])
            if len(lst) < 2 and self._ex_total < self.req.exemplars:
                lst.append({
                    "traceId": str(tid.values[r]),
                    "value": float(dur.values[r]) if dur is not None else 0.0,
                    "timestampMs": int(view.col("__startTime").values[r] / 1e6),
                })
                self._ex_total += 1

    # -- results ------------------------------------------------------------

    def results(self) -> list[TimeSeries]:
        """Job-level series (AggregateModeSum — raw sums, no rate division;
        the frontend applies final math after combining)."""
        self.flush()
        out: list[TimeSeries] = []
        nseries = len(self.series)
        if nseries == 0:
            return out
        k = self.m.kind
        if self._moments:
            # one series per moment column (merge = add) + the two
            # support bounds (merge = max): ≤ k+3 series per group vs
            # up to 64 bucket series — the combine-payload shrink
            mmt = np.asarray(self._grids["mmt"])[:nseries]
            mhi = np.asarray(self._grids["mhi"])[:nseries]
            mlo = np.asarray(self._grids["mlo"])[:nseries]
            for i, key in enumerate(self.series.keys):
                if not mmt[i, :, 0].any():
                    continue
                for j in range(msk.QUERY_K + 1):
                    col = mmt[i, :, j]
                    if col.any():
                        out.append(TimeSeries(
                            key + ((_LABEL_MOMENT, str(j)),),
                            col.astype(np.float64),
                            self._exemplars.get(i, []) if j == 0 else []))
                out.append(TimeSeries(key + ((_LABEL_MOMENT, "hi"),),
                                      mhi[i].astype(np.float64)))
                out.append(TimeSeries(key + ((_LABEL_MOMENT, "lo"),),
                                      mlo[i].astype(np.float64)))
            return out
        if self._hist:
            hist = np.asarray(self._grids["hist"])[:nseries]
            for i, key in enumerate(self.series.keys):
                for b in range(HBUCKETS):
                    col = hist[i, :, b]
                    if col.any():
                        labels = key + ((_LABEL_BUCKET, 2.0 ** b / 1e9),)
                        out.append(TimeSeries(labels, col.astype(np.float64),
                                              self._exemplars.get(i, [])))
            return out
        if self._is_compare:
            for g, which in (("sel", "selection"), ("base", "baseline")):
                grid = np.asarray(self._grids[g])[:nseries]
                for i, key in enumerate(self.series.keys):
                    if dict(key).get(_LABEL_META) != which:
                        continue
                    if grid[i].any():
                        out.append(TimeSeries(key, grid[i].astype(np.float64)))
            return out
        name = {A.MetricsKind.RATE: "count", A.MetricsKind.COUNT_OVER_TIME: "count",
                A.MetricsKind.MIN_OVER_TIME: "min", A.MetricsKind.MAX_OVER_TIME: "max",
                A.MetricsKind.SUM_OVER_TIME: "sum", A.MetricsKind.AVG_OVER_TIME: "sum"}[k]
        grid = np.asarray(self._grids[name])[:nseries]
        counts = (np.asarray(self._grids["count"])[:nseries]
                  if k == A.MetricsKind.AVG_OVER_TIME else None)
        for i, key in enumerate(self.series.keys):
            samples = grid[i].astype(np.float64)
            ts = TimeSeries(key, samples, self._exemplars.get(i, []))
            out.append(ts)
            if counts is not None:
                out.append(TimeSeries(key + (("__meta", "count"),),
                                      counts[i].astype(np.float64)))
        return out


def grid_series(m: A.MetricsAggregate, labels: list, main: np.ndarray,
                cnt: np.ndarray, vcnt: np.ndarray,
                moments: bool = False) -> list[TimeSeries]:
    """Device metrics grids → job-level TimeSeries, with the exact emission
    semantics of `MetricsEvaluator.results()`: a series exists iff its
    group matched the filter at least once (obs cnt row nonzero — even
    when the measured attribute was missing on every matching span, like
    the host registry); histogram kinds emit one series per nonzero log2
    bucket; avg emits the companion `__meta: count` series counting VALUED
    spans (vcnt). With `moments` (the moments query tier), quantile's
    `main` is the fused [G, steps, k+3] moment grid and emission follows
    the evaluator's moments branch: group gated on a nonzero weighted
    count (moment column 0), per-column gating, bounds unconditional.
    Labels ride pre-formatted from the plane's factorization (same
    `_fmt_label` path)."""
    group_names = tuple(str(e) for e in m.by)
    k = m.kind
    mom = moments and k == A.MetricsKind.QUANTILE_OVER_TIME
    hist = not mom and k in (A.MetricsKind.QUANTILE_OVER_TIME,
                             A.MetricsKind.HISTOGRAM_OVER_TIME)
    out: list[TimeSeries] = []
    for gi, lbl in enumerate(labels):
        if mom:
            if not main[gi, :, 0].any():
                continue
        elif not cnt[gi].any():
            continue
        if not group_names:
            key = ()
        elif len(group_names) == 1:
            key = ((group_names[0], lbl),)
        else:   # multi-key: lbl is a value tuple in by() order
            key = tuple(zip(group_names, lbl))
        if mom:
            k1 = main.shape[2] - 2     # k+1 moment cols, then hi, lo
            for j in range(k1):
                col = main[gi, :, j]
                if col.any():
                    out.append(TimeSeries(key + ((_LABEL_MOMENT, str(j)),),
                                          col.astype(np.float64)))
            out.append(TimeSeries(key + ((_LABEL_MOMENT, "hi"),),
                                  main[gi, :, k1].astype(np.float64)))
            out.append(TimeSeries(key + ((_LABEL_MOMENT, "lo"),),
                                  main[gi, :, k1 + 1].astype(np.float64)))
        elif hist:
            for b in range(HBUCKETS):
                col = main[gi, :, b]
                if col.any():
                    out.append(TimeSeries(
                        key + ((_LABEL_BUCKET, 2.0 ** b / 1e9),),
                        col.astype(np.float64)))
        elif k == A.MetricsKind.AVG_OVER_TIME:
            out.append(TimeSeries(key, main[gi].astype(np.float64)))
            out.append(TimeSeries(key + (("__meta", "count"),),
                                  vcnt[gi].astype(np.float64)))
        else:
            out.append(TimeSeries(key, main[gi].astype(np.float64)))
    return out


def _is_duration_attr(attr) -> bool:
    return isinstance(attr, A.Attribute) and attr.intrinsic in (
        A.Intrinsic.DURATION, A.Intrinsic.TRACE_DURATION)


def _fmt_label(v, t: str) -> str:
    if t == "status":
        return A.STATUS_NAMES.get(int(v), "unset")
    if t == "kind":
        return A.KIND_NAMES.get(int(v), "unspecified")
    if t == NUM or t == "num":
        f = float(v)
        return str(int(f)) if f.is_integer() else repr(f)
    if t == "bool":
        return "true" if v else "false"
    return str(v)


# ---------------------------------------------------------------------------
# combiner + final pass (frontend level)
# ---------------------------------------------------------------------------

# metric kinds whose cross-shard merge is EXACT in f32 — integer-valued
# counts (the engine's rate/count/compare/histogram grids accumulate
# weight-1 observations) and min/max (pmin/pmax of f32-origin grid
# values). Only these ride the in-mesh combine, and sum kinds
# additionally fall back to the host f64 fold when the worst-case
# reduced sum (max contribution magnitude x widest per-key contribution
# count) could reach f32's 2^24 integer-exact ceiling; sum/avg_over_time
# accumulate float values and always keep the host fold.
_MESH_MERGE_OPS = {
    A.MetricsKind.RATE: "sum",
    A.MetricsKind.COUNT_OVER_TIME: "sum",
    A.MetricsKind.QUANTILE_OVER_TIME: "sum",
    A.MetricsKind.HISTOGRAM_OVER_TIME: "sum",
    A.MetricsKind.COMPARE: "sum",
    A.MetricsKind.MIN_OVER_TIME: "min",
    A.MetricsKind.MAX_OVER_TIME: "max",
}
_MESH_FILL = {"sum": 0.0, "min": np.inf, "max": -np.inf}


class SeriesCombiner:
    """Cross-job series merge: tensor adds (min/max for those aggregates),
    the `SimpleAggregator`/`HistogramAggregator` combine step
    (engine_metrics.go:1124,1287).

    Sub-results accumulate LAZILY and merge on first read (`series` /
    `final()`). On a single device the merge is the original per-series
    numpy fold; under the serving mesh (`parallel.serving.active()`) the
    fold of count-exact kinds collapses into ONE in-mesh reduce — every
    key's contributions stack into a [series, contribs, steps] tensor
    sharded over 'series', the psum/pmax runs on device, and the merged
    series leave the mesh exactly once instead of per (job, series)."""

    def __init__(self, kind: A.MetricsKind, n_steps: int):
        self.kind = kind
        self.n_steps = n_steps
        self._series: dict[tuple, TimeSeries] = {}
        self._pending: list[list[TimeSeries]] = []

    @property
    def series(self) -> dict:
        self._flush()
        return self._series

    def add_all(self, series: Iterable[TimeSeries]) -> None:
        lst = series if isinstance(series, list) else list(series)
        if lst:
            self._pending.append(lst)

    # -- merge -------------------------------------------------------------

    def _flush(self) -> None:
        if not self._pending:
            return
        pend, self._pending = self._pending, []
        op = _MESH_MERGE_OPS.get(self.kind)
        if op is not None:
            from tempo_tpu.parallel import serving
            sm = serving.active()
            if sm is not None and \
                    sum(len(x) for x in pend) * self.n_steps >= \
                    sm.cfg.combine_min_elements:
                if self.kind == A.MetricsKind.QUANTILE_OVER_TIME:
                    # moments tier: the whole __moment family peels onto
                    # the host f64 fold — the bounds merge by MAX, and
                    # the fractional moment sums would break the mesh
                    # gate's exactness invariant (amax*cmax < 2^24 only
                    # guarantees integer-count payloads; a fractional
                    # sum rounds in f32 at ANY magnitude, making the
                    # answer depend on which route the combine took).
                    # The tier's combine win is the PAYLOAD shrink
                    # (~15 series/group vs 64 bucket series), which the
                    # host fold keeps; log2 bucket grids still ride the
                    # in-mesh reduce below.
                    mom = [[ts for ts in lst if _moment_labels(ts.labels)]
                           for lst in pend]
                    pend = [[ts for ts in lst
                             if not _moment_labels(ts.labels)]
                            for lst in pend]
                    for lst in mom:
                        if lst:
                            self._merge_host(lst)
                    pend = [lst for lst in pend if lst]
                if pend:
                    self._merge_mesh(sm, pend, op)
                return
        for lst in pend:
            self._merge_host(lst)

    def _merge_host(self, series: list) -> None:
        take_min = self.kind == A.MetricsKind.MIN_OVER_TIME
        take_max = self.kind == A.MetricsKind.MAX_OVER_TIME
        quantile = self.kind == A.MetricsKind.QUANTILE_OVER_TIME
        for ts in series:
            cur = self._series.get(ts.key())
            if cur is None:
                self._series[ts.key()] = TimeSeries(
                    ts.labels, ts.samples.copy(), list(ts.exemplars))
            else:
                if take_min:
                    cur.samples = np.minimum(cur.samples, ts.samples)
                elif take_max or (quantile
                                  and _moment_bound_labels(ts.labels)):
                    # moments support bounds combine like the sketch's
                    # bound columns: running max, not sum
                    cur.samples = np.maximum(cur.samples, ts.samples)
                else:
                    cur.samples = cur.samples + ts.samples
                cur.exemplars.extend(ts.exemplars)

    def _merge_mesh(self, sm, pend: list, op: str) -> None:
        """The in-mesh fold: stack every key's contributions (including
        its already-merged value, if any) and reduce once on the mesh.
        Keys with a single fresh contribution and no prior value skip
        the device entirely (nothing to combine)."""
        groups: dict[tuple, list[TimeSeries]] = {}
        order: list[tuple] = []
        for lst in pend:
            for ts in lst:
                k = ts.key()
                if k not in groups:
                    groups[k] = []
                    order.append(k)
                groups[k].append(ts)
        if op == "sum":
            # exactness gate: f32 addition of integer counts is exact
            # only while the REDUCED sum stays below 2^24, so bound the
            # worst case — max contribution magnitude times the widest
            # per-key contribution count — and let the host f64 fold
            # take over past it. Min/max stay exact at any magnitude
            # (values originate from f32 grids).
            amax, cmax = 0.0, 1
            for k, lst in groups.items():
                cur = self._series.get(k)
                contribs = ([cur] if cur is not None else []) + lst
                if len(contribs) > cmax:
                    cmax = len(contribs)
                for ts in contribs:
                    a = float(np.max(np.abs(ts.samples), initial=0.0))
                    if a > amax:
                        amax = a
            if amax * cmax >= float(1 << 24):
                for lst in pend:
                    self._merge_host(lst)
                return
        multi = [k for k in order if len(groups[k]) > 1 or k in self._series]
        for k in order:
            if len(groups[k]) == 1 and k not in self._series:
                ts = groups[k][0]
                self._series[k] = TimeSeries(ts.labels, ts.samples.copy(),
                                             list(ts.exemplars))
        if not multi:
            return
        n_contrib = max(len(groups[k]) + (1 if k in self._series else 0)
                        for k in multi)
        # pad both dims to stable pow-2-ish shapes: K to a multiple of
        # the series shards (shard_map split) rounded to pow2, C to pow2
        # — a small closed set of combine shapes reaching jit
        K = max(len(multi), sm.series_shards)
        K = 1 << (K - 1).bit_length()
        C = 1 << (n_contrib - 1).bit_length()
        fill = _MESH_FILL[op]
        mat = np.full((K, C, self.n_steps), fill, np.float32)
        for i, k in enumerate(multi):
            j = 0
            if k in self._series:
                mat[i, 0] = self._series[k].samples
                j = 1
            for ts in groups[k]:
                mat[i, j] = ts.samples
                j += 1
        out = sm.combine(mat, op).astype(np.float64)
        for i, k in enumerate(multi):
            cur = self._series.get(k)
            if cur is None:
                base = groups[k][0]
                cur = self._series[k] = TimeSeries(base.labels, out[i], [])
            else:
                cur.samples = out[i]
            for ts in groups[k]:
                cur.exemplars.extend(ts.exemplars)

    def final(self, req: QueryRangeRequest) -> list[TimeSeries]:
        """Final pass: rate division, avg division, quantiles from buckets."""
        q = parse(req.query)
        kind = q.metrics.kind
        out: list[TimeSeries] = []
        if kind == A.MetricsKind.RATE:
            step_s = req.step_ns / 1e9
            for ts in self.series.values():
                out.append(TimeSeries(ts.labels, ts.samples / step_s, ts.exemplars))
            return out
        if kind == A.MetricsKind.AVG_OVER_TIME:
            sums = {k: v for k, v in self.series.items()
                    if dict(k).get("__meta") != "count"}
            for key, ts in sums.items():
                ckey = key + (("__meta", "count"),)
                cnt = self.series.get(ckey)
                with np.errstate(invalid="ignore", divide="ignore"):
                    vals = (ts.samples / cnt.samples) if cnt is not None else ts.samples
                out.append(TimeSeries(ts.labels, np.nan_to_num(vals), ts.exemplars))
            return out
        if kind == A.MetricsKind.QUANTILE_OVER_TIME:
            return self._quantile_series(q.metrics.params, req)
        if kind == A.MetricsKind.MIN_OVER_TIME:
            for ts in self.series.values():
                s = np.where(np.isfinite(ts.samples), ts.samples, 0.0)
                out.append(TimeSeries(ts.labels, s, ts.exemplars))
            return out
        if kind == A.MetricsKind.MAX_OVER_TIME:
            for ts in self.series.values():
                s = np.where(np.isfinite(ts.samples), ts.samples, 0.0)
                out.append(TimeSeries(ts.labels, s, ts.exemplars))
            return out
        return list(self.series.values())

    def _quantile_series(self, qs: tuple, req: QueryRangeRequest) -> list[TimeSeries]:
        # regroup by base labels: `__bucket` series → [steps, HBUCKETS]
        # grids (the log2 tier), `__moment` series → [steps, k+3] moment
        # rows (the moments tier; sketch-row layout of ops/moments.py)
        grids: dict[tuple, np.ndarray] = {}
        moment_rows: dict[tuple, np.ndarray] = {}
        exemplars: dict[tuple, list] = {}
        kc = msk.QUERY_K
        for ts in self.series.values():
            labels = dict(ts.labels)
            if _LABEL_MOMENT in labels:
                mv = labels.pop(_LABEL_MOMENT)
                base = tuple(sorted(labels.items()))
                rows = moment_rows.setdefault(
                    base, np.zeros((req.n_steps, msk.n_cols(kc))))
                if mv == "hi":
                    rows[:, kc + 1] = np.maximum(rows[:, kc + 1], ts.samples)
                elif mv == "lo":
                    rows[:, kc + 2] = np.maximum(rows[:, kc + 2], ts.samples)
                else:
                    rows[:, int(mv)] += ts.samples
                exemplars.setdefault(base, []).extend(ts.exemplars)
                continue
            if _LABEL_BUCKET not in labels:
                continue
            le = float(labels.pop(_LABEL_BUCKET))
            b = int(np.clip(round(math.log2(max(le * 1e9, 1.0))), 0, HBUCKETS - 1))
            base = tuple(sorted(labels.items()))
            g = grids.setdefault(base, np.zeros((req.n_steps, HBUCKETS)))
            g[:, b] += ts.samples
            exemplars.setdefault(base, []).extend(ts.exemplars)
        out = []
        for base, g in grids.items():
            # ONE cumulative fold per series; every requested q reads
            # off it (a 3-param quantile_over_time used to refold per q)
            by_q = log2_quantiles_multi(qs, g)
            for qi, qv in enumerate(qs):
                labels = base + (("p", qv),)
                out.append(TimeSeries(labels, by_q[qi],
                                      exemplars.get(base, [])))
        for base, rows in moment_rows.items():
            # all q's per step come off ONE solved CDF (monotone in q);
            # non-converged steps fall back to the support midpoint and
            # count into tempo_moments_solver_fallback_total
            vals, failed = msk.quantiles_for_rows(
                rows, kc, msk.QUERY_LO, msk.QUERY_HI, qs)
            if failed.any():
                zmax = msk.QUERY_LO + rows[:, kc + 1]
                zmin = msk.QUERY_HI - rows[:, kc + 2]
                mid = np.exp((np.minimum(zmin, zmax)
                              + np.maximum(zmin, zmax)) / 2.0)
                vals = np.where(np.isnan(vals), mid[:, None], vals)
            vals = vals / 1e9   # ns → seconds, like log2_quantile
            for qi, qv in enumerate(qs):
                labels = base + (("p", qv),)
                out.append(TimeSeries(labels, vals[:, qi].astype(np.float64),
                                      exemplars.get(base, [])))
        return out


def metrics_kind(query: str) -> A.MetricsKind:
    """Metrics stage kind of a query, without building an evaluator."""
    q = parse(query)
    if q.metrics is None:
        raise ValueError("not a metrics query: " + query)
    return q.metrics.kind


def query_range(req: QueryRangeRequest,
                view_iter: Iterable[tuple[ColumnView, np.ndarray]],
                ) -> list[TimeSeries]:
    """Single-node convenience: evaluate + combine + final in one call."""
    ev = MetricsEvaluator(req, batched=True)
    for view, cand in view_iter:
        if len(cand) == 0:
            continue
        ev.observe(view)
    comb = SeriesCombiner(ev.m.kind, req.n_steps)
    comb.add_all(ev.results())
    return comb.final(req)
