"""TraceQL lexer (reference `pkg/traceql/lexer.go`).

Hand-rolled scanner producing a flat token list. Notable behaviors kept from
the reference: scope prefixes (`span.`, `resource.`, `parent.`, `trace:` ...)
lex as single tokens; attribute names after a scope may contain dots; duration
literals (`100ms`, `1h30m` not supported — single unit like reference);
quoted attribute names (`span."http status"`).
"""

from __future__ import annotations

import dataclasses
import enum
import re


class T(enum.Enum):
    EOF = "eof"
    OPEN_BRACE = "{"
    CLOSE_BRACE = "}"
    OPEN_PAREN = "("
    CLOSE_PAREN = ")"
    COMMA = ","
    PIPE = "|"
    DOT = "."
    IDENT = "ident"
    STRING = "string"
    INT = "int"
    FLOAT = "float"
    DURATION = "duration"
    # operators
    EQ = "="
    NEQ = "!="
    REGEX = "=~"
    NOT_REGEX = "!~"
    GT = ">"
    GTE = ">="
    LT = "<"
    LTE = "<="
    AND = "&&"
    OR = "||"
    ADD = "+"
    SUB = "-"
    MULT = "*"
    DIV = "/"
    MOD = "%"
    POW = "^"
    NOT = "!"
    # structural
    DESC = ">>"
    ANCE = "<<"
    TILDE = "~"
    NOT_DESC = "!>>"
    NOT_ANCE = "!<<"
    NOT_CHILD = "!>"
    NOT_PARENT = "!<"
    UNION_CHILD = "&>"
    UNION_PARENT = "&<"
    UNION_DESC = "&>>"
    UNION_ANCE = "&<<"
    UNION_SIBLING = "&~"
    # scopes
    SCOPE = "scope"          # value: "span" | "resource" | "event" | "link" | "instrumentation"
    PARENT_DOT = "parent."
    SCOPE_COLON = "scope:"   # value: "trace" | "span" | "event" | "link" | "instrumentation"


@dataclasses.dataclass
class Token:
    kind: T
    text: str
    pos: int
    value: object = None


_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)")
_NUM_RE = re.compile(r"\d+(\.\d+)?")
_IDENT_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_\-]*")
# attribute tail after a scope dot: allow dots, dashes, slashes etc. until an
# operator/space (lexer.go attribute scanning)
_ATTR_RE = re.compile(r'[^\s{}()|,=!<>~&+\-*/%^"]+')

_DUR_SCALE = {"ns": 1, "us": 1_000, "µs": 1_000, "ms": 1_000_000,
              "s": 1_000_000_000, "m": 60_000_000_000, "h": 3_600_000_000_000}

_SCOPES_DOT = ("span", "resource", "event", "link", "instrumentation")
_SCOPES_COLON = ("trace", "span", "event", "link", "instrumentation")

_PUNCT = [  # longest first
    ("!>>", T.NOT_DESC), ("!<<", T.NOT_ANCE), ("&>>", T.UNION_DESC),
    ("&<<", T.UNION_ANCE),
    (">>", T.DESC), ("<<", T.ANCE), ("!>", T.NOT_CHILD), ("!<", T.NOT_PARENT),
    ("&>", T.UNION_CHILD), ("&<", T.UNION_PARENT), ("&~", T.UNION_SIBLING),
    ("!~", T.NOT_REGEX), ("=~", T.REGEX), ("!=", T.NEQ), (">=", T.GTE),
    ("<=", T.LTE), ("&&", T.AND), ("||", T.OR),
    ("{", T.OPEN_BRACE), ("}", T.CLOSE_BRACE), ("(", T.OPEN_PAREN),
    (")", T.CLOSE_PAREN), (",", T.COMMA), ("|", T.PIPE), ("=", T.EQ),
    (">", T.GT), ("<", T.LT), ("+", T.ADD), ("-", T.SUB), ("*", T.MULT),
    ("/", T.DIV), ("%", T.MOD), ("^", T.POW), ("!", T.NOT), ("~", T.TILDE),
    (".", T.DOT),
]


class LexError(ValueError):
    pass


def _string(s: str, i: int) -> tuple[str, int]:
    quote = s[i]
    i += 1
    out = []
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            out.append({"n": "\n", "t": "\t", "\\": "\\", '"': '"', "'": "'",
                        "`": "`"}.get(nxt, "\\" + nxt))
            i += 2
            continue
        if c == quote:
            return "".join(out), i + 1
        out.append(c)
        i += 1
    raise LexError(f"unterminated string at {i}")


def lex(src: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(src)
    while i < n:
        c = src[i]
        if c.isspace():
            i += 1
            continue
        if c in "\"'`":
            start = i
            val, i = _string(src, i)
            toks.append(Token(T.STRING, src[start:i], start, val))
            continue
        # scope prefixes (must come before ident/punct)
        matched_scope = False
        for sc in _SCOPES_DOT:
            if src.startswith(sc + ".", i):
                toks.append(Token(T.SCOPE, sc, i, sc))
                i += len(sc) + 1
                matched_scope = True
                break
        if matched_scope:
            # next: attribute name (possibly quoted, possibly dotted)
            if i < n and src[i] in "\"'`":
                start = i
                val, i = _string(src, i)
                toks.append(Token(T.IDENT, src[start:i], start, val))
            else:
                m = _ATTR_RE.match(src, i)
                if not m:
                    raise LexError(f"expected attribute name at {i}")
                toks.append(Token(T.IDENT, m.group(0), i, m.group(0)))
                i = m.end()
            continue
        if src.startswith("parent.", i):
            toks.append(Token(T.PARENT_DOT, "parent.", i))
            i += 7
            # a scope prefix (span./resource.) continues via the main loop;
            # otherwise take the raw attribute tail here
            if not any(src.startswith(sc + ".", i) for sc in _SCOPES_DOT):
                if i < n and src[i] in "\"'`":
                    start = i
                    val, i = _string(src, i)
                    toks.append(Token(T.IDENT, src[start:i], start, val))
                else:
                    m = _ATTR_RE.match(src, i)
                    if not m:
                        raise LexError(f"expected attribute after parent. at {i}")
                    toks.append(Token(T.IDENT, m.group(0), i, m.group(0)))
                    i = m.end()
            continue
        for sc in _SCOPES_COLON:
            if src.startswith(sc + ":", i):
                toks.append(Token(T.SCOPE_COLON, sc, i, sc))
                i += len(sc) + 1
                m = _IDENT_RE.match(src, i)
                if not m:
                    raise LexError(f"expected intrinsic name after {sc}: at {i}")
                toks.append(Token(T.IDENT, m.group(0), i, m.group(0)))
                i = m.end()
                matched_scope = True
                break
        if matched_scope:
            continue
        if c.isdigit():
            if _DUR_RE.match(src, i):
                # duration literal, possibly multi-part (1h30m)
                total = 0.0
                j = i
                while True:
                    m2 = _DUR_RE.match(src, j)
                    if not m2:
                        break
                    total += float(m2.group(1)) * _DUR_SCALE[m2.group(2)]
                    j = m2.end()
                toks.append(Token(T.DURATION, src[i:j], i, int(total)))
                i = j
                continue
            m = _NUM_RE.match(src, i)
            text = m.group(0)
            if "." in text:
                toks.append(Token(T.FLOAT, text, i, float(text)))
            else:
                toks.append(Token(T.INT, text, i, int(text)))
            i = m.end()
            continue
        if c == "." and i + 1 < n and src[i + 1].isdigit():
            m = _NUM_RE.match(src, i + 1)
            text = "." + m.group(0)
            toks.append(Token(T.FLOAT, text, i, float(text)))
            i = m.end()
            continue
        if c == "." and i + 1 < n and (src[i + 1].isalpha() or src[i + 1] in '_"\'`'):
            # unscoped attribute `.foo.bar`
            toks.append(Token(T.DOT, ".", i))
            i += 1
            if src[i] in "\"'`":
                start = i
                val, i = _string(src, i)
                toks.append(Token(T.IDENT, src[start:i], start, val))
            else:
                m = _ATTR_RE.match(src, i)
                toks.append(Token(T.IDENT, m.group(0), i, m.group(0)))
                i = m.end()
            continue
        m = _IDENT_RE.match(src, i)
        if m:
            toks.append(Token(T.IDENT, m.group(0), i, m.group(0)))
            i = m.end()
            continue
        for text, kind in _PUNCT:
            if src.startswith(text, i):
                toks.append(Token(kind, text, i))
                i += len(text)
                break
        else:
            raise LexError(f"unexpected character {c!r} at {i}")
    toks.append(Token(T.EOF, "", n))
    return toks
