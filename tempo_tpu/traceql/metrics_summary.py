"""Span-metrics summary: the second metrics engine (`pkg/traceqlmetrics`).

Powers `GetMetrics` / the span-metrics-summary API: per-series fixed
64-bucket power-of-two latency histograms (`LatencyHistogram`
`pkg/traceqlmetrics/metrics.go:17-98`), series keyed by up to 5 group-by
attributes (`metrics.go:100-130`), driven by a TraceQL filter with a
second-pass fetch (`GetMetrics` `metrics.go:182-330`).

Vectorized: bucket = ceil(log2(duration_ns)) for a whole column at once;
per-series accumulation is one scatter-add into an [n_series, 64] grid —
the direct CPU/TPU analog of the per-span `Record` loop.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np

from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.engine import compile_query
from tempo_tpu.traceql.eval import ColumnView, attr_key, eval_expr, resolve_attr

N_BUCKETS = 64
MAX_GROUP_BY = 5


def bucketize_ns(duration_ns: np.ndarray) -> np.ndarray:
    """Power-of-2 bucket index: smallest b with 2^b >= d (0 for d<=1),
    matching `Record` `metrics.go:41-57`."""
    d = np.maximum(np.asarray(duration_ns, np.float64), 1.0)
    return np.clip(np.ceil(np.log2(d)), 0, N_BUCKETS - 1).astype(np.int64)


@dataclasses.dataclass
class LatencyHistogram:
    buckets: np.ndarray  # [64] int64

    @staticmethod
    def empty() -> "LatencyHistogram":
        return LatencyHistogram(np.zeros(N_BUCKETS, np.int64))

    @property
    def count(self) -> int:
        return int(self.buckets.sum())

    def combine(self, other: "LatencyHistogram") -> None:
        self.buckets += other.buckets

    def percentile(self, p: float) -> int:
        """Exponential-interpolated percentile in ns (`Percentile`
        `metrics.go:64-98`)."""
        total = self.buckets.sum()
        if total == 0 or p <= 0:
            return 0
        target = p * total
        cum = np.cumsum(self.buckets)
        b = int(np.searchsorted(cum, target, side="left"))
        if b >= N_BUCKETS:
            b = N_BUCKETS - 1
        in_bucket = self.buckets[b]
        if b == 0 or in_bucket == 0:
            return 1 << b
        before = cum[b] - in_bucket
        frac = (target - before) / in_bucket
        lo, hi = float(1 << (b - 1)), float(1 << b)
        return int(lo * (hi / lo) ** frac)


@dataclasses.dataclass
class SeriesMetrics:
    labels: tuple                    # ((attr, value), ...)
    histogram: LatencyHistogram
    error_count: int = 0

    def to_json(self) -> dict:
        return {
            "series": [{"key": k, "value": str(v)} for k, v in self.labels],
            "spanCount": self.histogram.count,
            "errorSpanCount": self.error_count,
            "p50": self.histogram.percentile(0.5),
            "p90": self.histogram.percentile(0.9),
            "p99": self.histogram.percentile(0.99),
        }


class MetricsResults:
    """Accumulation across scan batches + shards (`MetricsResults.Combine`)."""

    def __init__(self, max_series: int = 1000):
        self.max_series = max_series
        self.series: dict[tuple, SeriesMetrics] = {}
        self.span_count = 0
        self.estimated = False  # truncated at max_series

    def record(self, labels: tuple, hist: LatencyHistogram, errors: int) -> None:
        s = self.series.get(labels)
        if s is None:
            if len(self.series) >= self.max_series:
                self.estimated = True
                return
            s = self.series[labels] = SeriesMetrics(labels, LatencyHistogram.empty())
        s.histogram.combine(hist)
        s.error_count += errors
        self.span_count += hist.count

    def combine(self, other: "MetricsResults") -> None:
        for labels, s in other.series.items():
            self.record(labels, s.histogram, s.error_count)
        self.estimated |= other.estimated

    def results(self) -> list[SeriesMetrics]:
        return sorted(self.series.values(),
                      key=lambda s: -s.histogram.count)


def get_metrics(query: str, group_by: Sequence[str],
                view_iter: Iterable[tuple[ColumnView, np.ndarray]],
                max_series: int = 1000) -> MetricsResults:
    """Filter spans with `query`, group by up to 5 attributes, aggregate
    latency histograms + error counts per series — vectorized per batch."""
    if len(group_by) > MAX_GROUP_BY:
        raise ValueError(f"at most {MAX_GROUP_BY} group-by attributes")
    q, _ = compile_query(query or "{ }")
    flt = _filter_expr(q)
    attrs = [_parse_groupby(g) for g in group_by]
    res = MetricsResults(max_series)

    for view, cand in view_iter:
        if len(cand) == 0:
            continue
        if flt is not None:
            mask = eval_expr(view, flt).bool_mask()
        else:
            mask = np.ones(view.n, bool)
        rows = cand[mask[cand]]
        if len(rows) == 0:
            continue
        dur = view.col("duration")
        if dur is None:
            continue
        buckets = bucketize_ns(dur.values[rows])  # duration col is ns
        status = view.col("status")
        errors = (status.values[rows] == A.STATUS_ERROR) if status is not None \
            else np.zeros(len(rows), bool)

        # group key per row: tuple of stringified label values
        label_cols = []
        for a in attrs:
            c = resolve_attr(view, a)
            vals = np.where(c.exists[rows],
                            c.values[rows].astype(str), "nil")
            label_cols.append(vals)
        if label_cols:
            stacked = np.stack(label_cols, axis=1)
            keys, inverse = np.unique(stacked, axis=0, return_inverse=True)
            for ki in range(len(keys)):
                sel = inverse == ki
                hist = LatencyHistogram(
                    np.bincount(buckets[sel], minlength=N_BUCKETS)
                    .astype(np.int64))
                labels = tuple((attr_key(a), keys[ki][j])
                               for j, a in enumerate(attrs))
                res.record(labels, hist, int(errors[sel].sum()))
        else:
            hist = LatencyHistogram(
                np.bincount(buckets, minlength=N_BUCKETS).astype(np.int64))
            res.record((), hist, int(errors.sum()))
    return res


def _filter_expr(q: A.Pipeline):
    for stage in q.stages:
        if isinstance(stage, A.SpansetFilter):
            return stage.expr
    return None


def _parse_groupby(g: str) -> A.Attribute:
    from tempo_tpu.traceql.engine import _parse_attr
    return _parse_attr(g)
