"""Build a ColumnView from in-memory traces (flat span dicts).

Serves the recent-data query paths — ingester live traces and generator
localblocks head blocks — where spans haven't reached parquet yet
(reference `modules/ingester/instance_search.go`,
`modules/generator/processor/localblocks/query_range.go`), plus unit tests.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from tempo_tpu.block.schema import nested_set
from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.eval import (BOOL, KIND, NUM, NUMLIST, STATUS, STR,
                                    STRLIST, Col, ColumnView)


def view_from_traces(traces: Sequence[tuple[bytes, list[dict]]]) -> ColumnView:
    """[(trace_id, [span dicts])] → ColumnView with all intrinsics + attrs.

    Span dicts use the same shape as block schema ingestion
    (`block/schema.py traces_to_table`): name/service/kind/status_code/
    start_unix_nano/end_unix_nano/attrs/res_attrs/events/links.
    """
    n = sum(len(spans) for _, spans in traces)
    trace_idx = np.empty(max(n, 0), np.int64)
    view = ColumnView(n, trace_idx)

    dur = np.zeros(n)
    start = np.zeros(n)
    name = np.empty(n, object)
    service = np.empty(n, object)
    status = np.zeros(n)
    status_msg = np.empty(n, object)
    kind = np.zeros(n)
    tid_hex = np.empty(n, object)
    sid_hex = np.empty(n, object)
    pid_hex = np.empty(n, object)
    root_name = np.empty(n, object)
    root_service = np.empty(n, object)
    root_exists = np.zeros(n, bool)
    trace_dur = np.zeros(n)
    parent_row = np.full(n, -1, np.int64)
    nleft = np.zeros(n, np.int64)
    nright = np.zeros(n, np.int64)
    events = np.empty(n, object)
    event_times = np.empty(n, object)
    link_tid = np.empty(n, object)
    link_sid = np.empty(n, object)
    attr_cols: dict[str, tuple[str, np.ndarray, np.ndarray]] = {}

    def attr_col(key: str, t: str):
        c = attr_cols.get(key)
        if c is None or c[0] != t:
            if c is None:
                vals = (np.empty(n, object) if t == STR else
                        np.zeros(n) if t == NUM else np.zeros(n, bool))
                c = attr_cols[key] = (t, vals, np.zeros(n, bool))
            else:
                return None  # mixed-type attr: first type wins
        return c

    row = 0
    for t_i, (trace_id, spans) in enumerate(traces):
        sids = [s.get("span_id", b"") or b"" for s in spans]
        pids = [s.get("parent_span_id", b"") or b"" for s in spans]
        left, right, parent_local = nested_set(sids, pids)
        base = row
        t_start, t_end = np.inf, -np.inf
        r_name, r_service = None, None
        for j, s in enumerate(spans):
            trace_idx[row] = t_i
            s0 = int(s.get("start_unix_nano", 0))
            e0 = int(s.get("end_unix_nano", s0))
            start[row] = s0
            dur[row] = max(e0 - s0, 0)
            t_start, t_end = min(t_start, s0), max(t_end, e0)
            name[row] = s.get("name", "")
            service[row] = s.get("service", "")
            status[row] = A.OTLP_STATUS_TO_TRACEQL.get(int(s.get("status_code", 0)), A.STATUS_UNSET)
            status_msg[row] = s.get("status_message", "")
            kind[row] = int(s.get("kind", 0))
            tid_hex[row] = bytes(trace_id).hex()
            sid_hex[row] = bytes(sids[j]).hex()
            pid_hex[row] = bytes(pids[j]).hex()
            parent_row[row] = base + parent_local[j] if parent_local[j] >= 0 else -1
            nleft[row] = left[j]
            nright[row] = right[j]
            if parent_local[j] < 0 and r_name is None:
                r_name, r_service = name[row], service[row]
            evs = s.get("events") or []
            events[row] = [str(e.get("name", "")) for e in evs] or None
            event_times[row] = [int(e.get("time_unix_nano", 0)) - s0 for e in evs] or None
            links = s.get("links") or []
            link_tid[row] = [bytes(l.get("trace_id", b"")).hex() for l in links] or None
            link_sid[row] = [bytes(l.get("span_id", b"")).hex() for l in links] or None
            for k, v in (s.get("attrs") or {}).items():
                _put_attr(attr_col, f"span.{k}", v, row)
            for k, v in (s.get("res_attrs") or {}).items():
                _put_attr(attr_col, f"resource.{k}", v, row)
            row += 1
        for r in range(base, row):
            trace_dur[r] = max(t_end - t_start, 0) if row > base else 0
            if r_name is not None:
                root_name[r] = r_name
                root_service[r] = r_service
                root_exists[r] = True

    ones = np.ones(n, bool)
    view.parent_row = parent_row
    view.nested_left = nleft
    view.nested_right = nright
    view.set_col("duration", Col(NUM, dur, ones))
    view.set_col("__startTime", Col(NUM, start, ones))
    view.set_col("name", Col(STR, name, ones))
    view.set_col("rootName", Col(STR, root_name, root_exists))
    view.set_col("rootServiceName", Col(STR, root_service, root_exists))
    view.set_col("traceDuration", Col(NUM, trace_dur, ones))
    view.set_col("status", Col(STATUS, status, ones))
    view.set_col("statusMessage", Col(STR, status_msg, ones))
    view.set_col("kind", Col(KIND, kind, ones))
    view.set_col("trace:id", Col(STR, tid_hex, ones))
    view.set_col("span:id", Col(STR, sid_hex, ones))
    view.set_col("span:parentID", Col(STR, pid_hex, ones))
    view.set_col("nestedSetLeft", Col(NUM, nleft.astype(float), ones))
    view.set_col("nestedSetRight", Col(NUM, nright.astype(float), ones))
    view.set_col("nestedSetParent",
                 Col(NUM, np.where(parent_row >= 0, nleft[np.maximum(parent_row, 0)], -1).astype(float), ones))
    view.set_col("resource.service.name", Col(STR, service, ones))
    ev_exists = np.fromiter((e is not None for e in events), bool, n) if n else np.zeros(0, bool)
    view.set_col("event:name", Col(STRLIST, events, ev_exists))
    view.set_col("event:timeSinceStart", Col(NUMLIST, event_times, ev_exists))
    lk_exists = np.fromiter((e is not None for e in link_tid), bool, n) if n else np.zeros(0, bool)
    view.set_col("link:traceID", Col(STRLIST, link_tid, lk_exists))
    view.set_col("link:spanID", Col(STRLIST, link_sid, lk_exists))
    for key, (t, vals, exists) in attr_cols.items():
        if key == "resource.service.name":
            continue  # intrinsic service column wins
        view.set_col(key, Col(t, vals, exists))
    view.meta["span_attr_keys"] = {k.partition(".")[2] for k in attr_cols
                                   if k.startswith("span.")}
    view.meta["resource_attr_keys"] = {k.partition(".")[2] for k in attr_cols
                                       if k.startswith("resource.")}
    view.meta["trace_id"] = tid_hex
    view.meta["span_id"] = sid_hex
    view.meta["start_unix_nano"] = start.astype(np.int64)
    view.meta["duration_ns"] = dur.astype(np.int64)
    view.meta["name"] = name
    view.meta["service"] = service
    return view


def _put_attr(attr_col, key: str, v, row: int) -> None:
    if isinstance(v, bool):
        c = attr_col(key, BOOL)
    elif isinstance(v, (int, float)):
        c = attr_col(key, NUM)
    else:
        c = attr_col(key, STR)
        v = str(v)
    if c is None:
        return
    _, vals, exists = c
    vals[row] = float(v) if c[0] == NUM else v
    exists[row] = True
