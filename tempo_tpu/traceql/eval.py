"""Vectorized TraceQL evaluation: mask algebra over span columns.

The reference walks spans one at a time through an interpreter
(`pkg/traceql/ast_execute.go`); here every filter expression evaluates over
ALL rows of a column batch at once (numpy ufuncs — and, on the block scan
path, these same masks compile into device kernels). Trace-level semantics
(structural operators, spanset combine, aggregates) then touch only traces
that still have candidate rows.

Type semantics follow the reference lattice (`enum_statics.go`): comparisons
between incomparable types are false, missing attributes never match (except
`= nil`), regex is fully anchored (prometheus FastRegexMatcher semantics,
`pkg/regexp/regexp.go`).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Optional, Sequence

import numpy as np

from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.conditions import _flip as _flip_op

# column type tags
NUM, STR, BOOL, STATUS, KIND = "num", "str", "bool", "status", "kind"
STRLIST, NUMLIST = "strlist", "numlist"  # per-span lists (events/links): "any element matches"
MIXED = "mixed"  # unscoped attr with different span/resource types (object values)

_STATIC_T = {
    A.StaticType.INT: NUM, A.StaticType.FLOAT: NUM, A.StaticType.DURATION: NUM,
    A.StaticType.STRING: STR, A.StaticType.BOOL: BOOL,
    A.StaticType.STATUS: STATUS, A.StaticType.KIND: KIND,
}


@dataclasses.dataclass
class Col:
    """One evaluated column: typed values + existence mask.

    String columns that originate dictionary- or interner-encoded MAY
    carry their integer codes alongside the materialized values:
    `codes[i]` indexes `code_values` and `str(code_values[codes[i]])`
    equals `values[i].astype("U")` row-for-row. Group factorization
    (`engine_metrics.group_slots`) then runs np.unique over int32 codes
    instead of paying an O(n) per-query object→unicode conversion; every
    other consumer ignores the sidecar fields."""
    t: str
    values: np.ndarray
    exists: np.ndarray
    codes: Optional[np.ndarray] = None        # int32, parallel to values
    code_values: Optional[list] = None        # code id → string

    @staticmethod
    def const(t: str, value, n: int) -> "Col":
        if t == STR:
            v = np.empty(n, object)
            v[:] = value
        elif t == BOOL:
            v = np.full(n, bool(value))
        else:
            v = np.full(n, float(value))
        return Col(t, v, np.ones(n, bool))

    def bool_mask(self) -> np.ndarray:
        """Boolean filter view: missing → false."""
        if self.t == MIXED:
            # object column: rows whose value is a true bool pass
            out = np.zeros(len(self.values), bool)
            for i in np.flatnonzero(self.exists):
                v = self.values[i]
                if isinstance(v, (bool, np.bool_)) and v:
                    out[i] = True
            return out
        if self.t != BOOL:
            return np.zeros(len(self.values), bool)
        return self.values & self.exists


class ColumnView:
    """Span columns for one scan batch (a row group, a WAL block slice, or
    an in-memory spanset) plus trace/tree coordinates.

    Attribute columns are registered under scoped keys ("span.foo",
    "resource.foo") and intrinsics under their names. Lazy resolvers let the
    fetch layer materialize parquet columns only when an expression touches
    them (the pushdown analog of `AllConditions` column pruning).
    """

    def __init__(self, n: int, trace_idx: np.ndarray | None = None):
        self.n = n
        self.trace_idx = trace_idx if trace_idx is not None else np.zeros(n, np.int64)
        self._cols: dict[str, Col] = {}
        self._resolvers: dict[str, Callable[[], Optional[Col]]] = {}
        # tree coordinates (global row indices; -1 = root). Optional: only
        # needed for structural ops / childCount / parent. attrs.
        self.parent_row: np.ndarray | None = None
        self.nested_left: np.ndarray | None = None
        self.nested_right: np.ndarray | None = None
        # identity/meta (search results)
        self.meta: dict[str, np.ndarray] = {}

    def set_col(self, key: str, col: Col) -> None:
        self._cols[key] = col

    def set_resolver(self, key: str, fn: Callable[[], Optional[Col]]) -> None:
        self._resolvers[key] = fn

    def col(self, key: str) -> Optional[Col]:
        c = self._cols.get(key)
        if c is None and key in self._resolvers:
            c = self._resolvers.pop(key)()
            if c is not None:
                self._cols[key] = c
        return c

    def missing(self) -> Col:
        return Col(NUM, np.zeros(self.n), np.zeros(self.n, bool))

    # -- intrinsic helpers --------------------------------------------------

    def child_count(self) -> Col:
        pr = self.parent_row
        if pr is None:
            return self.missing()
        counts = np.bincount(pr[pr >= 0], minlength=self.n).astype(float)
        return Col(NUM, counts, np.ones(self.n, bool))


def static_col(s: A.Static, n: int) -> Col:
    if s.type == A.StaticType.NIL:
        return Col(NUM, np.zeros(n), np.zeros(n, bool))
    t = _STATIC_T[s.type]
    v = s.value
    if t in (STATUS, KIND, NUM):
        v = float(v) if not isinstance(v, bool) else float(v)
    return Col.const(t, v, n)


# ---------------------------------------------------------------------------
# Attribute resolution
# ---------------------------------------------------------------------------

_INTRINSIC_KEYS = {
    A.Intrinsic.DURATION: "duration",
    A.Intrinsic.NAME: "name",
    A.Intrinsic.STATUS: "status",
    A.Intrinsic.STATUS_MESSAGE: "statusMessage",
    A.Intrinsic.KIND: "kind",
    A.Intrinsic.ROOT_NAME: "rootName",
    A.Intrinsic.ROOT_SERVICE: "rootServiceName",
    A.Intrinsic.TRACE_DURATION: "traceDuration",
    A.Intrinsic.NESTED_SET_LEFT: "nestedSetLeft",
    A.Intrinsic.NESTED_SET_RIGHT: "nestedSetRight",
    A.Intrinsic.NESTED_SET_PARENT: "nestedSetParent",
    A.Intrinsic.TRACE_ID: "trace:id",
    A.Intrinsic.SPAN_ID: "span:id",
    A.Intrinsic.PARENT_ID: "span:parentID",
    A.Intrinsic.EVENT_NAME: "event:name",
    A.Intrinsic.EVENT_TIME_SINCE_START: "event:timeSinceStart",
    A.Intrinsic.LINK_TRACE_ID: "link:traceID",
    A.Intrinsic.LINK_SPAN_ID: "link:spanID",
    A.Intrinsic.INSTRUMENTATION_NAME: "instrumentation:name",
    A.Intrinsic.INSTRUMENTATION_VERSION: "instrumentation:version",
    A.Intrinsic.SPAN_START_TIME: "__startTime",
}


def attr_key(a: A.Attribute) -> str:
    """Canonical column key for an attribute (ignoring unscoped fallback)."""
    if a.intrinsic != A.Intrinsic.NONE:
        return _INTRINSIC_KEYS.get(a.intrinsic, a.intrinsic.value)
    scope = a.scope.value or "span"
    return f"{scope}.{a.name}"


def resolve_attr(view: ColumnView, a: A.Attribute) -> Col:
    if a.parent:
        base = A.Attribute(a.name, a.scope, a.intrinsic, parent=False)
        child = resolve_attr(view, base)
        pr = view.parent_row
        if pr is None:
            return view.missing()
        has_parent = pr >= 0
        gather = np.where(has_parent, pr, 0)
        vals = child.values[gather]
        exists = child.exists[gather] & has_parent
        return Col(child.t, vals, exists)
    if a.intrinsic == A.Intrinsic.CHILD_COUNT:
        return view.child_count()
    if a.intrinsic != A.Intrinsic.NONE:
        c = view.col(_INTRINSIC_KEYS.get(a.intrinsic, a.intrinsic.value))
        return c if c is not None else view.missing()
    if a.scope == A.Scope.NONE:
        s = view.col(f"span.{a.name}")
        r = view.col(f"resource.{a.name}")
        if s is None and r is None:
            return view.missing()
        if s is None:
            return r  # type: ignore[return-value]
        if r is None:
            return s
        if s.t == r.t:
            vals = np.where(s.exists, s.values, r.values)
            return Col(s.t, vals, s.exists | r.exists)
        # mixed span/resource types: per-row precedence into an object
        # column; comparisons take the scoped-variant path (_eval_binary)
        vals = np.empty(len(s.values), object)
        vals[r.exists] = r.values[r.exists]
        vals[s.exists] = s.values[s.exists]
        return Col(MIXED, vals, s.exists | r.exists)
    c = view.col(attr_key(a))
    return c if c is not None else view.missing()


# ---------------------------------------------------------------------------
# Expression evaluation
# ---------------------------------------------------------------------------

_REGEX_CACHE: dict[str, "re.Pattern"] = {}


def _regex(pattern: str) -> "re.Pattern":
    p = _REGEX_CACHE.get(pattern)
    if p is None:
        p = _REGEX_CACHE[pattern] = re.compile(pattern)
        if len(_REGEX_CACHE) > 4096:
            _REGEX_CACHE.clear()
    return p


def regex_match_col(values: np.ndarray, exists: np.ndarray,
                    pattern: str) -> np.ndarray:
    """Anchored regex over an object/str column, evaluated once per unique
    value (the memoization in `pkg/regexp/regexp.go` becomes a unique+take)."""
    p = _regex(pattern)
    uniq, inv = np.unique(values.astype(str), return_inverse=True)
    hits = np.fromiter((p.fullmatch(u) is not None for u in uniq),
                       bool, count=len(uniq))
    return hits[inv] & exists


_NUM_LIKE = (NUM, STATUS, KIND)


def _comparable(lt: str, rt: str) -> bool:
    if lt == rt:
        return True
    return False  # status/kind/num are distinct lattices, like the reference


def eval_expr(view: ColumnView, e) -> Col:
    n = view.n
    if isinstance(e, A.Static):
        return static_col(e, n)
    if isinstance(e, A.Attribute):
        return resolve_attr(view, e)
    if isinstance(e, A.UnaryOp):
        inner = eval_expr(view, e.expr)
        if e.op == A.Op.NOT:
            return Col(BOOL, ~inner.bool_mask(), np.ones(n, bool))
        if e.op == A.Op.NEG:
            if inner.t != NUM:
                return view.missing()
            return Col(NUM, -inner.values, inner.exists)
    if isinstance(e, A.BinaryOp):
        return _eval_binary(view, e)
    raise TypeError(f"cannot evaluate {e!r}")


def _eval_binary(view: ColumnView, e: A.BinaryOp) -> Col:
    n = view.n
    op = e.op
    if op == A.Op.AND:
        l, r = eval_expr(view, e.lhs), eval_expr(view, e.rhs)
        return Col(BOOL, l.bool_mask() & r.bool_mask(), np.ones(n, bool))
    if op == A.Op.OR:
        l, r = eval_expr(view, e.lhs), eval_expr(view, e.rhs)
        return Col(BOOL, l.bool_mask() | r.bool_mask(), np.ones(n, bool))

    # nil comparisons (x = nil / x != nil)
    if isinstance(e.rhs, A.Static) and e.rhs.type == A.StaticType.NIL:
        l = eval_expr(view, e.lhs)
        if op == A.Op.EQ:
            return Col(BOOL, ~l.exists, np.ones(n, bool))
        if op == A.Op.NEQ:
            return Col(BOOL, l.exists.copy(), np.ones(n, bool))
        return Col(BOOL, np.zeros(n, bool), np.ones(n, bool))

    l = eval_expr(view, e.lhs)
    r = eval_expr(view, e.rhs)

    if op in (A.Op.REGEX, A.Op.NOT_REGEX):
        if not isinstance(e.rhs, A.Static) or e.rhs.type != A.StaticType.STRING:
            return Col(BOOL, np.zeros(n, bool), np.ones(n, bool))
        pattern = str(e.rhs.value)
        if l.t == STRLIST:
            hits = _strlist_match(l, lambda s: _regex(pattern).fullmatch(s) is not None)
        elif l.t == STR:
            hits = regex_match_col(l.values, l.exists, pattern)
        elif l.t == MIXED:
            p = _regex(pattern)
            hits = np.zeros(n, bool)
            for i in np.flatnonzero(l.exists):
                v = l.values[i]
                if isinstance(v, str) and p.fullmatch(v):
                    hits[i] = True
        else:
            hits = np.zeros(n, bool)
        if op == A.Op.NOT_REGEX:
            hits = ~hits & l.exists
        return Col(BOOL, hits, np.ones(n, bool))

    if op in (A.Op.EQ, A.Op.NEQ, A.Op.GT, A.Op.GTE, A.Op.LT, A.Op.LTE):
        return _compare(n, op, l, r)

    # arithmetic
    if l.t != NUM or r.t != NUM:
        return view.missing()
    with np.errstate(divide="ignore", invalid="ignore", over="ignore"):
        lv, rv = l.values.astype(float), r.values.astype(float)
        if op == A.Op.ADD:
            v = lv + rv
        elif op == A.Op.SUB:
            v = lv - rv
        elif op == A.Op.MULT:
            v = lv * rv
        elif op == A.Op.DIV:
            v = lv / rv
        elif op == A.Op.MOD:
            v = np.mod(lv, rv)
        elif op == A.Op.POW:
            v = lv ** rv
        else:
            raise ValueError(op)
    return Col(NUM, v, l.exists & r.exists)


def _strlist_match(c: Col, pred) -> np.ndarray:
    out = np.zeros(len(c.values), bool)
    for i in np.flatnonzero(c.exists):
        vals = c.values[i]
        if vals is not None and any(pred(str(v)) for v in vals):
            out[i] = True
    return out


_LIST_CMP = {A.Op.EQ: lambda a, b: a == b, A.Op.NEQ: lambda a, b: a != b,
             A.Op.GT: lambda a, b: a > b, A.Op.GTE: lambda a, b: a >= b,
             A.Op.LT: lambda a, b: a < b, A.Op.LTE: lambda a, b: a <= b}

def _py_cmp(op: A.Op, v, rv, rt: str) -> bool:
    if isinstance(v, (bool, np.bool_)):
        ok = rt == BOOL
        v = bool(v)
    elif isinstance(v, (int, float, np.integer, np.floating)):
        ok = rt == NUM
    else:
        ok = rt == STR
        v = str(v)
    if not ok:
        return False
    return bool(_LIST_CMP[op](v, rv))


def _compare(n: int, op: A.Op, l: Col, r: Col) -> Col:
    if r.t == MIXED and l.t != MIXED:
        return _compare(n, _flip_op(op), r, l)
    if l.t == MIXED:
        # per-row typed compare over the object column (mixed-type unscoped
        # attrs are rare; correctness over vectorization here)
        out = np.zeros(n, bool)
        if r.t in (NUM, STR, BOOL):
            for i in np.flatnonzero(l.exists & r.exists):
                out[i] = _py_cmp(op, l.values[i], r.values[i], r.t)
        return Col(BOOL, out, np.ones(n, bool))
    # list columns: "any element matches" (event:name, event:timeSinceStart)
    if l.t == STRLIST and r.t == STR:
        rv0 = r.values[0] if len(r.values) else ""
        if op in (A.Op.EQ, A.Op.NEQ):
            hits = _strlist_match(l, lambda s, f=_LIST_CMP[op]: f(s, rv0))
        else:
            hits = np.zeros(n, bool)
        return Col(BOOL, hits, np.ones(n, bool))
    if l.t == NUMLIST and r.t == NUM:
        rv0 = float(r.values[0]) if len(r.values) else 0.0
        fn = _LIST_CMP[op]
        out = np.zeros(n, bool)
        for i in np.flatnonzero(l.exists):
            vals = l.values[i]
            if vals is not None and any(fn(float(v), rv0) for v in vals):
                out[i] = True
        return Col(BOOL, out, np.ones(n, bool))
    if not _comparable(l.t, r.t):
        return Col(BOOL, np.zeros(n, bool), np.ones(n, bool))
    lv, rv = l.values, r.values
    ok = l.exists & r.exists
    if l.t == STR:
        lv = lv.astype(str)
        rv = rv.astype(str)
    with np.errstate(invalid="ignore"):
        if op == A.Op.EQ:
            v = lv == rv
        elif op == A.Op.NEQ:
            v = lv != rv
        elif op == A.Op.GT:
            v = lv > rv
        elif op == A.Op.GTE:
            v = lv >= rv
        elif op == A.Op.LT:
            v = lv < rv
        else:
            v = lv <= rv
    return Col(BOOL, np.asarray(v, bool) & ok, np.ones(n, bool))


# ---------------------------------------------------------------------------
# Structural operators (nested-set interval algebra)
# ---------------------------------------------------------------------------

def structural_combine(op: A.StructuralOp, view: ColumnView,
                       a_rows: np.ndarray, b_rows: np.ndarray) -> np.ndarray:
    """Row indices (within one trace slice) selected from B given A.

    nested-set containment: ancestor(a,b) ⟺ left[a] < left[b] ∧ right[a] >
    right[b] (`vparquet4/nested_set_model.go`); child via parent_row; sibling
    via parent_row equality. All as broadcast compares — O(|A|·|B|) vector ops
    on trace-sized sets.
    """
    L, R, P = view.nested_left, view.nested_right, view.parent_row
    if L is None or P is None:
        return np.empty(0, np.int64)
    neg = op in (A.StructuralOp.NOT_CHILD, A.StructuralOp.NOT_PARENT,
                 A.StructuralOp.NOT_DESCENDANT, A.StructuralOp.NOT_ANCESTOR,
                 A.StructuralOp.NOT_SIBLING)
    union = op in (A.StructuralOp.UNION_CHILD, A.StructuralOp.UNION_PARENT,
                   A.StructuralOp.UNION_DESCENDANT,
                   A.StructuralOp.UNION_ANCESTOR, A.StructuralOp.UNION_SIBLING)
    base = {
        A.StructuralOp.CHILD: "child", A.StructuralOp.NOT_CHILD: "child",
        A.StructuralOp.UNION_CHILD: "child",
        A.StructuralOp.PARENT: "parent", A.StructuralOp.NOT_PARENT: "parent",
        A.StructuralOp.UNION_PARENT: "parent",
        A.StructuralOp.DESCENDANT: "desc", A.StructuralOp.NOT_DESCENDANT: "desc",
        A.StructuralOp.UNION_DESCENDANT: "desc",
        A.StructuralOp.ANCESTOR: "ance", A.StructuralOp.NOT_ANCESTOR: "ance",
        A.StructuralOp.UNION_ANCESTOR: "ance",
        A.StructuralOp.SIBLING: "sib", A.StructuralOp.NOT_SIBLING: "sib",
        A.StructuralOp.UNION_SIBLING: "sib",
    }[op]

    if len(a_rows) == 0:
        hit_b = np.zeros(len(b_rows), bool)
        hit_a = np.zeros(0, bool)
    elif base == "child":
        hit_b = np.isin(P[b_rows], a_rows)
        hit_a = np.isin(a_rows, P[b_rows]) if union else None
    elif base == "parent":
        hit_b = np.isin(b_rows, P[a_rows])
        hit_a = np.isin(P[a_rows], b_rows) if union else None
    elif base == "desc":
        la, ra = L[a_rows][:, None], R[a_rows][:, None]
        lb, rb = L[b_rows][None, :], R[b_rows][None, :]
        m = (la < lb) & (ra > rb)          # a is ancestor of b
        hit_b = m.any(axis=0)
        hit_a = m.any(axis=1) if union else None
    elif base == "ance":
        la, ra = L[a_rows][:, None], R[a_rows][:, None]
        lb, rb = L[b_rows][None, :], R[b_rows][None, :]
        m = (lb < la) & (rb > ra)          # b is ancestor of a
        hit_b = m.any(axis=0)
        hit_a = m.any(axis=1) if union else None
    else:  # sibling
        pa, pb = P[a_rows][:, None], P[b_rows][None, :]
        m = (pa == pb) & (pa >= 0) & (a_rows[:, None] != b_rows[None, :])
        hit_b = m.any(axis=0)
        hit_a = m.any(axis=1) if union else None

    if neg:
        return b_rows[~hit_b]
    if union:
        out = b_rows[hit_b]
        if hit_a is not None and len(a_rows):
            out = np.union1d(out, a_rows[hit_a])
        return out
    return b_rows[hit_b]


# ---------------------------------------------------------------------------
# Pipeline evaluation over a batch
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Spanset:
    trace_key: int              # trace_idx within the batch
    rows: np.ndarray            # global row indices into the view
    group_attrs: tuple = ()     # ((attr_str, value), ...) from by()
    scalars: dict = dataclasses.field(default_factory=dict)  # agg results


def _trace_slices(trace_idx: np.ndarray, candidates: np.ndarray):
    """Yield (trace_key, rows) for candidate rows grouped by trace."""
    if len(candidates) == 0:
        return
    keys = trace_idx[candidates]
    order = np.argsort(keys, kind="stable")
    cand = candidates[order]
    keys = keys[order]
    bounds = np.flatnonzero(np.diff(keys)) + 1
    for chunk in np.split(cand, bounds):
        yield int(trace_idx[chunk[0]]), chunk


def eval_spanset_expr(node, view: ColumnView, trace_rows: np.ndarray,
                      filter_masks: dict) -> np.ndarray:
    """Rows of one trace surviving a spanset expression."""
    if isinstance(node, A.SpansetFilter):
        m = filter_masks[id(node)]
        return trace_rows[m[trace_rows]]
    if isinstance(node, A.StructuralExpr):
        a = eval_spanset_expr(node.lhs, view, trace_rows, filter_masks)
        b = eval_spanset_expr(node.rhs, view, trace_rows, filter_masks)
        return structural_combine(node.op, view, a, b)
    if isinstance(node, A.SpansetCombine):
        a = eval_spanset_expr(node.lhs, view, trace_rows, filter_masks)
        b = eval_spanset_expr(node.rhs, view, trace_rows, filter_masks)
        if node.op == A.SpansetOp.AND:
            if len(a) == 0 or len(b) == 0:
                return np.empty(0, np.int64)
            return np.union1d(a, b)
        return np.union1d(a, b)
    raise TypeError(f"not a spanset expr: {node!r}")


def _collect_filters(node, out: list) -> None:
    if isinstance(node, A.SpansetFilter):
        out.append(node)
    elif isinstance(node, (A.StructuralExpr, A.SpansetCombine)):
        _collect_filters(node.lhs, out)
        _collect_filters(node.rhs, out)


def _agg_value(kind: A.AggregateKind, vals: np.ndarray) -> float:
    if kind == A.AggregateKind.COUNT:
        return float(len(vals))
    if len(vals) == 0:
        return float("nan")
    return {A.AggregateKind.AVG: np.mean, A.AggregateKind.MAX: np.max,
            A.AggregateKind.MIN: np.min, A.AggregateKind.SUM: np.sum}[kind](vals)


def evaluate_pipeline(q: A.Pipeline, view: ColumnView) -> list[Spanset]:
    """Run the spanset pipeline over one batch → surviving spansets."""
    spansets: list[Spanset] | None = None
    for stage in q.stages:
        if isinstance(stage, (A.SpansetFilter, A.StructuralExpr, A.SpansetCombine)):
            filters: list = []
            _collect_filters(stage, filters)
            masks = {id(f): eval_expr(view, f.expr).bool_mask() for f in filters}
            new: list[Spanset] = []
            if spansets is None:
                any_mask = np.zeros(view.n, bool)
                for m in masks.values():
                    any_mask |= m
                # structural ops need the full trace, not just matched rows
                if isinstance(stage, A.SpansetFilter):
                    candidates = np.flatnonzero(any_mask)
                    for key, rows in _trace_slices(view.trace_idx, candidates):
                        new.append(Spanset(key, rows))
                else:
                    # structural ops need the whole trace: one grouped pass
                    # over all rows, visiting only traces with a hit
                    hit_traces = set(np.unique(view.trace_idx[any_mask]).tolist())
                    for key, trace_rows in _trace_slices(view.trace_idx,
                                                         np.arange(view.n)):
                        if key not in hit_traces:
                            continue
                        rows = eval_spanset_expr(stage, view, trace_rows, masks)
                        if len(rows):
                            new.append(Spanset(int(key), rows))
            else:
                for ss in spansets:
                    rows = eval_spanset_expr(stage, view, ss.rows, masks)
                    if len(rows):
                        new.append(dataclasses.replace(ss, rows=rows))
            spansets = new
        elif isinstance(stage, A.ScalarFilter):
            spansets = _apply_scalar_filter(stage, view, _ensure(spansets, view))
        elif isinstance(stage, A.GroupOp):
            spansets = _apply_group(stage, view, _ensure(spansets, view))
        elif isinstance(stage, A.CoalesceOp):
            merged: dict = {}
            for ss in _ensure(spansets, view):
                cur = merged.get(ss.trace_key)
                if cur is None:
                    merged[ss.trace_key] = dataclasses.replace(ss, group_attrs=())
                else:
                    cur.rows = np.union1d(cur.rows, ss.rows)
            spansets = list(merged.values())
        elif isinstance(stage, A.SelectOp):
            for e in stage.attrs:  # force-materialize selected columns
                if isinstance(e, A.Attribute):
                    resolve_attr(view, e)
        else:
            raise TypeError(f"unsupported stage {stage!r}")
    return _ensure(spansets, view)


def _ensure(spansets, view: ColumnView) -> list[Spanset]:
    if spansets is not None:
        return spansets
    # pipeline with no initial filter: every trace, all rows
    out = []
    for key, rows in _trace_slices(view.trace_idx, np.arange(view.n)):
        out.append(Spanset(key, rows))
    return out


def _scalar_operand(side, view: ColumnView, ss: Spanset) -> float:
    if isinstance(side, A.Static):
        return side.as_float()
    if isinstance(side, A.AggregateExpr):
        if side.kind == A.AggregateKind.COUNT:
            return float(len(ss.rows))
        c = eval_expr(view, side.expr)
        vals = c.values[ss.rows][c.exists[ss.rows]]
        return _agg_value(side.kind, vals.astype(float))
    raise TypeError(side)


_CMP_FN = {A.Op.EQ: np.equal, A.Op.NEQ: np.not_equal, A.Op.GT: np.greater,
           A.Op.GTE: np.greater_equal, A.Op.LT: np.less, A.Op.LTE: np.less_equal}


def _apply_scalar_filter(stage: A.ScalarFilter, view, spansets) -> list[Spanset]:
    out = []
    for ss in spansets:
        lv = _scalar_operand(stage.lhs, view, ss)
        rv = _scalar_operand(stage.rhs, view, ss)
        if not (np.isnan(lv) or np.isnan(rv)) and bool(_CMP_FN[stage.op](lv, rv)):
            name = str(stage.lhs)
            ss.scalars[name] = lv
            out.append(ss)
    return out


def _apply_group(stage: A.GroupOp, view, spansets) -> list[Spanset]:
    out = []
    cols = [(str(e), eval_expr(view, e)) for e in stage.by]
    for ss in spansets:
        keys: dict[tuple, list] = {}
        for row in ss.rows:
            kv = []
            skip = False
            for name, c in cols:
                if not c.exists[row]:
                    skip = True
                    break
                kv.append((name, c.values[row]))
            if skip:
                continue
            keys.setdefault(tuple(kv), []).append(row)
        for kv, rows in keys.items():
            out.append(Spanset(ss.trace_key, np.asarray(rows),
                               group_attrs=kv, scalars=dict(ss.scalars)))
    return out
