"""TraceQL search engine (reference `pkg/traceql/engine.go`).

`execute_search` drives fetchers (block row-group views or in-memory views)
through the two-pass pattern: storage prefilter → full pipeline evaluation →
per-trace search metadata, merged top-N by recency like the reference's
`NewMetadataCombiner` (`pkg/traceql/combine.go`).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, Optional

import numpy as np

from tempo_tpu.obs import querystats
from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.conditions import FetchSpansRequest, extract_conditions
from tempo_tpu.traceql.eval import ColumnView, Spanset, evaluate_pipeline
from tempo_tpu.traceql.parser import parse


@dataclasses.dataclass
class SpanResult:
    span_id: str
    name: str
    start_unix_nano: int
    duration_ns: int
    attributes: dict


@dataclasses.dataclass
class TraceSearchMetadata:
    trace_id: str
    root_service_name: str
    root_trace_name: str
    start_time_unix_nano: int
    duration_ms: int
    span_sets: list = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "traceID": self.trace_id,
            "rootServiceName": self.root_service_name,
            "rootTraceName": self.root_trace_name,
            "startTimeUnixNano": str(self.start_time_unix_nano),
            "durationMs": self.duration_ms,
            "spanSets": self.span_sets,
        }

    @classmethod
    def from_json(cls, t: dict) -> "TraceSearchMetadata":
        """Inverse of to_json — the one decoder every RPC transport uses."""
        return cls(
            trace_id=t["traceID"],
            root_service_name=t.get("rootServiceName", ""),
            root_trace_name=t.get("rootTraceName", ""),
            start_time_unix_nano=int(t.get("startTimeUnixNano", "0")),
            duration_ms=t.get("durationMs", 0),
            span_sets=t.get("spanSets", []))


def compile_query(query: str | A.Pipeline,
                  start_ns: int = 0, end_ns: int = 0
                  ) -> tuple[A.Pipeline, FetchSpansRequest]:
    """Parse + extract fetch conditions (`Compile` `engine.go:30-47`)."""
    q = parse(query) if isinstance(query, str) else query
    return q, extract_conditions(q, start_ns, end_ns)


class MetadataCombiner:
    """Top-N traces by start time, deduped by trace id (`combine.go`)."""

    def __init__(self, limit: int = 20):
        self.limit = limit
        self.by_id: dict[str, TraceSearchMetadata] = {}

    def add(self, md: TraceSearchMetadata) -> None:
        cur = self.by_id.get(md.trace_id)
        if cur is None:
            self.by_id[md.trace_id] = md
        else:
            cur.span_sets.extend(md.span_sets)
            cur.start_time_unix_nano = min(cur.start_time_unix_nano,
                                           md.start_time_unix_nano)
            cur.duration_ms = max(cur.duration_ms, md.duration_ms)

    def exhausted(self) -> bool:
        return len(self.by_id) >= self.limit

    def results(self) -> list[TraceSearchMetadata]:
        out = sorted(self.by_id.values(),
                     key=lambda m: -m.start_time_unix_nano)
        return out[: self.limit]


def spanset_to_json(view: ColumnView, ss: Spanset, max_spans: int = 3) -> dict:
    spans = []
    sid = view.col("span:id")
    name = view.col("name")
    st = view.meta.get("start_unix_nano")
    dur = view.meta.get("duration_ns")
    for row in ss.rows[:max_spans]:
        spans.append({
            "spanID": str(sid.values[row]) if sid is not None else "",
            "name": str(name.values[row]) if name is not None else "",
            "startTimeUnixNano": str(int(st[row])) if st is not None else "0",
            "durationNanos": str(int(dur[row])) if dur is not None else "0",
            "attributes": [
                {"key": k, "value": {"stringValue": str(v)}}
                for k, v in ss.group_attrs
            ],
        })
    out = {"spans": spans, "matched": int(len(ss.rows))}
    if ss.group_attrs:
        out["attributes"] = [
            {"key": k, "value": {"stringValue": str(v)}} for k, v in ss.group_attrs
        ]
    return out


def execute_search(
    query: str | A.Pipeline,
    view_iter: Iterable[tuple[ColumnView, np.ndarray]],
    *,
    limit: int = 20,
    start_ns: int = 0,
    end_ns: int = 0,
) -> list[TraceSearchMetadata]:
    """Run a search over an iterator of (view, candidate_rows).

    The iterator is typically `block.fetch.scan_views` chained over blocks
    (querier) or a single in-memory view (ingester live traces). Early-exits
    once the combiner has `limit` traces, like `ExecuteSearch`'s streaming
    second pass (`engine.go:82-155`).
    """
    q = parse(query) if isinstance(query, str) else query
    combiner = MetadataCombiner(limit)
    simple = bool(q.stages) and all(isinstance(s, A.SpansetFilter)
                                    for s in q.stages)
    for view, cand in view_iter:
        if len(cand) == 0:
            continue
        st = querystats.current()
        if st is not None:
            # candidate spans evaluated; trace count via contiguous-run
            # boundaries (spans of one trace are stored adjacent), O(n)
            # instead of a unique() sort
            t = view.trace_idx[cand]
            st.add(inspected_spans=int(len(cand)),
                   inspected_traces=int((np.diff(t) != 0).sum()) + 1)
        with querystats.stage("engine_eval"):
            if simple:
                # all-filter pipeline: one vectorized mask + reduceat
                # ranking replaces the per-trace Spanset loop; only the
                # top-`limit` traces materialize Python objects (the
                # second-pass analog of the pre-pass below, pulled before
                # object construction)
                spansets = _simple_filter_spansets(q, view, limit,
                                                   start_ns, end_ns)
            else:
                spansets = evaluate_pipeline(q, view)
        if not spansets:
            continue
        # Vectorized pre-pass: per-spanset time bounds via one reduceat,
        # window filter, then metadata (hex ids, root names, JSON) is built
        # for the top-`limit` most recent spansets ONLY — everything older
        # could never displace them in the combiner.
        st = view.meta.get("start_unix_nano")
        dur = view.meta.get("duration_ns")
        if st is not None and len(spansets) > limit:
            lens = np.fromiter((len(ss.rows) for ss in spansets), np.int64,
                               len(spansets))
            allrows = np.concatenate([ss.rows for ss in spansets])
            bounds = np.zeros(len(spansets), np.int64)
            np.cumsum(lens[:-1], out=bounds[1:])
            t0s = np.minimum.reduceat(st[allrows], bounds)
            t1s = np.maximum.reduceat(st[allrows] + dur[allrows], bounds)
            ok = np.ones(len(spansets), bool)
            if start_ns:
                ok &= t1s >= start_ns
            if end_ns:
                ok &= t0s < end_ns
            idxs = np.flatnonzero(ok)
            # Rank by the COMBINER's key — a trace's start is the min over
            # its merged spansets — and keep every spanset of each chosen
            # trace, so multi-spanset traces (by() queries) neither rank
            # nor truncate differently than the unfiltered path.
            first_rows = allrows[bounds[idxs]]
            tkeys = view.trace_idx[first_rows]
            ut, inv = np.unique(tkeys, return_inverse=True)
            # int64 accumulator: float64 would round ns epochs (>2^53) and
            # could cut a different trace set than the combiner's exact sort
            tmin = np.full(len(ut), np.iinfo(np.int64).max, np.int64)
            np.minimum.at(tmin, inv, t0s[idxs].astype(np.int64))
            top = np.argsort(-tmin, kind="stable")[:limit]
            chosen_traces = set(ut[top].tolist())
            spansets = [spansets[i]
                        for i, t in zip(idxs.tolist(), tkeys.tolist())
                        if t in chosen_traces]
        for ss in spansets:
            md = _trace_metadata(view, ss, start_ns, end_ns)
            if md is not None:
                combiner.add(md)
        if combiner.exhausted():
            break
    return combiner.results()


def _simple_filter_spansets(q: A.Pipeline, view: ColumnView, limit: int,
                            start_ns: int, end_ns: int) -> list[Spanset]:
    """Top-`limit` spansets of an all-SpansetFilter pipeline, fully
    vectorized: sequential filter stages compose to a mask intersection,
    trace grouping is a reduceat over the (trace-aligned) row order, and
    ranking matches the combiner's most-recent-start key exactly."""
    from tempo_tpu.traceql.eval import eval_expr

    st = view.meta.get("start_unix_nano")
    dur = view.meta.get("duration_ns")
    if st is None or dur is None:
        return evaluate_pipeline(q, view)     # in-memory view: slow path
    mask = None
    for s in q.stages:
        m = eval_expr(view, s.expr).bool_mask()
        mask = m if mask is None else mask & m
    rows = np.flatnonzero(mask)
    if len(rows) == 0:
        return []
    keys = view.trace_idx[rows]
    if len(keys) > 1 and not (np.diff(keys) >= 0).all():
        order = np.argsort(keys, kind="stable")
        rows, keys = rows[order], keys[order]
    starts = np.concatenate([[0], np.flatnonzero(np.diff(keys)) + 1])
    ends = np.concatenate([starts[1:], [len(rows)]])
    t0s = np.minimum.reduceat(st[rows], starts).astype(np.int64)
    t1s = np.maximum.reduceat(st[rows] + dur[rows], starts).astype(np.int64)
    ok = np.ones(len(starts), bool)
    if start_ns:
        ok &= t1s >= start_ns
    if end_ns:
        ok &= t0s < end_ns
    sel = np.flatnonzero(ok)
    if len(sel) == 0:
        return []
    top = np.sort(sel[np.argsort(-t0s[sel], kind="stable")[:limit]])
    # ascending (scan) order: the combiner breaks equal-start ties by
    # insertion order, so emission order must match the per-trace path
    return [Spanset(int(keys[starts[i]]), rows[starts[i]:ends[i]])
            for i in top.tolist()]


def _trace_metadata(view: ColumnView, ss: Spanset,
                    start_ns: int, end_ns: int) -> Optional[TraceSearchMetadata]:
    st = view.meta.get("start_unix_nano")
    dur = view.meta.get("duration_ns")
    rows = ss.rows
    t0 = int(st[rows].min()) if st is not None and len(rows) else 0
    t1 = int((st[rows] + dur[rows]).max()) if st is not None and len(rows) else 0
    if start_ns and t1 < start_ns:
        return None
    if end_ns and t0 >= end_ns:
        return None
    tid_col = view.col("trace:id")
    tid = str(tid_col.values[rows[0]]) if tid_col is not None and len(rows) else ""
    root_svc, root_name = "", ""
    rs = view.col("rootServiceName")
    rn = view.col("rootName")
    if rs is not None and rs.exists[rows[0]]:
        root_svc = str(rs.values[rows[0]])
    if rn is not None and rn.exists[rows[0]]:
        root_name = str(rn.values[rows[0]])
    return TraceSearchMetadata(
        trace_id=tid,
        root_service_name=root_svc,
        root_trace_name=root_name,
        start_time_unix_nano=t0,
        duration_ms=int((t1 - t0) / 1e6),
        span_sets=[spanset_to_json(view, ss)],
    )


# ---------------------------------------------------------------------------
# tag names / values (`engine.go:157-231`, `block_search_tags.go`)
# ---------------------------------------------------------------------------

def execute_tag_names(view_iter: Iterable[tuple[ColumnView, np.ndarray]],
                      scope: str = "", limit: int = 1000) -> dict[str, list[str]]:
    """Distinct attribute keys by scope. Views must carry tag metadata
    (set by fetch/memview as meta['span_attr_keys'] etc.)."""
    span_keys: set = set()
    res_keys: set = set()
    for view, _ in view_iter:
        span_keys |= set(view.meta.get("span_attr_keys", ()))
        res_keys |= set(view.meta.get("resource_attr_keys", ()))
        if len(span_keys) + len(res_keys) >= limit:
            break
    out: dict[str, list[str]] = {}
    if scope in ("", "span"):
        out["span"] = sorted(span_keys)[:limit]
    if scope in ("", "resource"):
        out["resource"] = sorted(res_keys)[:limit]
    if scope in ("", "intrinsic"):
        out["intrinsic"] = sorted(k for k in A.INTRINSIC_KEYWORDS)
    return out


def tag_values_request(attr: str, start_ns: int = 0,
                       end_ns: int = 0) -> FetchSpansRequest:
    """Fetch request that projects just the one attribute column (the
    autocomplete fetch, `ExecuteTagValues` engine.go:157)."""
    from tempo_tpu.traceql.conditions import Condition

    return FetchSpansRequest(conditions=[Condition(_parse_attr(attr))],
                             all_conditions=False,
                             start_ns=start_ns, end_ns=end_ns)


def execute_tag_values(attr: str,
                       view_iter: Iterable[tuple[ColumnView, np.ndarray]],
                       limit: int = 1000) -> list[dict]:
    """Distinct values of one attribute (autocomplete path)."""
    a = _parse_attr(attr)
    seen: dict = {}
    for view, _ in view_iter:
        from tempo_tpu.traceql.eval import resolve_attr

        c = resolve_attr(view, a)
        vals = c.values[c.exists]
        for v in np.unique(vals.astype(str) if c.t == "str" else vals):
            t = _tag_type(c.t)
            if c.t == "num":
                import math

                f = float(v)
                # integral numerics render as ints ("200", not "200.0"),
                # matching the reference's typed tag values; non-finite
                # floats (valid OTLP doubleValues) stay float-formatted
                if math.isfinite(f) and f == int(f):
                    key, t = str(int(f)), "int"
                else:
                    key = str(f)
            elif c.t == "bool":
                key = "true" if v else "false"
            else:
                key = str(v)
            if key not in seen:
                seen[key] = {"type": t, "value": key}
            if len(seen) >= limit:
                break
        if len(seen) >= limit:
            break
    return list(seen.values())


def _tag_type(t: str) -> str:
    return {"str": "string", "num": "float", "bool": "boolean"}.get(t, "string")


def _parse_attr(attr: str) -> A.Attribute:
    from tempo_tpu.traceql.parser import _Parser
    from tempo_tpu.traceql.lexer import lex

    p = _Parser(lex(attr), attr)
    node = p.parse_primary()
    if not isinstance(node, A.Attribute):
        raise ValueError(f"not an attribute: {attr}")
    return node
