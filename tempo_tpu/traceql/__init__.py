"""TraceQL: the traces-first query language (reference `pkg/traceql/`).

Re-designed for columnar/TPU execution: the parser and AST mirror the
reference grammar (`pkg/traceql/expr.y`, `lexer.go`), but evaluation is
mask algebra over struct-of-arrays span columns instead of per-span
interpreter loops, and the metrics engine scatters into
[series x steps (x buckets)] device grids.
"""

from tempo_tpu.traceql.ast import *  # noqa: F401,F403
from tempo_tpu.traceql.parser import parse, ParseError  # noqa: F401
from tempo_tpu.traceql.conditions import extract_conditions  # noqa: F401
