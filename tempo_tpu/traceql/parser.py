"""TraceQL recursive-descent parser (reference grammar `pkg/traceql/expr.y`).

Produces `ast.Pipeline`. Operator precedence inside field expressions follows
the reference: || < && < comparison < +- < */% < ^ < unary. Spanset-level
combinators (structural ops, && , ||) are left-associative at one level, as
in the yacc grammar.
"""

from __future__ import annotations

from tempo_tpu.traceql import ast as A
from tempo_tpu.traceql.lexer import LexError, T, Token, lex


class ParseError(ValueError):
    pass


_CMP = {T.EQ: A.Op.EQ, T.NEQ: A.Op.NEQ, T.REGEX: A.Op.REGEX,
        T.NOT_REGEX: A.Op.NOT_REGEX, T.GT: A.Op.GT, T.GTE: A.Op.GTE,
        T.LT: A.Op.LT, T.LTE: A.Op.LTE}

_STRUCT = {T.GT: A.StructuralOp.CHILD, T.LT: A.StructuralOp.PARENT,
           T.DESC: A.StructuralOp.DESCENDANT, T.ANCE: A.StructuralOp.ANCESTOR,
           T.TILDE: A.StructuralOp.SIBLING,
           T.NOT_CHILD: A.StructuralOp.NOT_CHILD,
           T.NOT_PARENT: A.StructuralOp.NOT_PARENT,
           T.NOT_DESC: A.StructuralOp.NOT_DESCENDANT,
           T.NOT_ANCE: A.StructuralOp.NOT_ANCESTOR,
           T.NOT_REGEX: A.StructuralOp.NOT_SIBLING,
           T.UNION_CHILD: A.StructuralOp.UNION_CHILD,
           T.UNION_PARENT: A.StructuralOp.UNION_PARENT,
           T.UNION_DESC: A.StructuralOp.UNION_DESCENDANT,
           T.UNION_ANCE: A.StructuralOp.UNION_ANCESTOR,
           T.UNION_SIBLING: A.StructuralOp.UNION_SIBLING}

_AGG = {"count": A.AggregateKind.COUNT, "avg": A.AggregateKind.AVG,
        "max": A.AggregateKind.MAX, "min": A.AggregateKind.MIN,
        "sum": A.AggregateKind.SUM}

_METRICS = {m.value: m for m in A.MetricsKind}

_STATUS_WORDS = {"ok": A.STATUS_OK, "error": A.STATUS_ERROR,
                 "unset": A.STATUS_UNSET}
_KIND_WORDS = {"unspecified": 0, "internal": 1, "server": 2, "client": 3,
               "producer": 4, "consumer": 5}


class _Parser:
    def __init__(self, toks: list[Token], src: str):
        self.toks = toks
        self.i = 0
        self.src = src

    # -- token plumbing ----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != T.EOF:
            self.i += 1
        return t

    def accept(self, kind: T) -> Token | None:
        if self.peek().kind == kind:
            return self.next()
        return None

    def expect(self, kind: T) -> Token:
        t = self.peek()
        if t.kind != kind:
            raise ParseError(
                f"parse error at {t.pos}: expected {kind.value!r}, got "
                f"{t.text!r} in {self.src!r}")
        return self.next()

    # -- entry -------------------------------------------------------------

    def parse_root(self) -> A.Pipeline:
        stages: list = [self.parse_spanset_expr()]
        metrics = None
        while self.accept(T.PIPE):
            t = self.peek()
            if t.kind == T.IDENT and t.text in _METRICS:
                metrics = self.parse_metrics()
                break
            stages.append(self.parse_stage())
        hints = self.parse_hints()
        self.expect(T.EOF)
        return A.Pipeline(tuple(stages), metrics=metrics, hints=tuple(hints))

    def parse_hints(self) -> list[A.Hint]:
        out: list[A.Hint] = []
        t = self.peek()
        if t.kind == T.IDENT and t.text == "with":
            self.next()
            self.expect(T.OPEN_PAREN)
            while True:
                name = self.expect(T.IDENT).text
                self.expect(T.EQ)
                out.append(A.Hint(name, self.parse_static()))
                if not self.accept(T.COMMA):
                    break
            self.expect(T.CLOSE_PAREN)
        return out

    # -- pipeline stages ---------------------------------------------------

    def parse_stage(self):
        t = self.peek()
        if t.kind == T.IDENT:
            if t.text == "by":
                self.next()
                self.expect(T.OPEN_PAREN)
                exprs = self.parse_expr_list()
                self.expect(T.CLOSE_PAREN)
                return A.GroupOp(tuple(exprs))
            if t.text == "select":
                self.next()
                self.expect(T.OPEN_PAREN)
                exprs = self.parse_expr_list()
                self.expect(T.CLOSE_PAREN)
                return A.SelectOp(tuple(exprs))
            if t.text == "coalesce":
                self.next()
                self.expect(T.OPEN_PAREN)
                self.expect(T.CLOSE_PAREN)
                return A.CoalesceOp()
            if t.text in _AGG:
                return self.parse_scalar_filter()
        if t.kind in (T.INT, T.FLOAT, T.DURATION):
            return self.parse_scalar_filter()
        return self.parse_spanset_expr()

    def parse_scalar_filter(self) -> A.ScalarFilter:
        lhs = self.parse_scalar_operand()
        t = self.peek()
        if t.kind not in _CMP:
            raise ParseError(f"parse error at {t.pos}: expected comparison in "
                             f"scalar filter, got {t.text!r}")
        op = _CMP[self.next().kind]
        rhs = self.parse_scalar_operand()
        return A.ScalarFilter(op, lhs, rhs)

    def parse_scalar_operand(self):
        t = self.peek()
        if t.kind == T.IDENT and t.text in _AGG:
            self.next()
            kind = _AGG[t.text]
            self.expect(T.OPEN_PAREN)
            inner = None
            if self.peek().kind != T.CLOSE_PAREN:
                inner = self.parse_field_expr()
            self.expect(T.CLOSE_PAREN)
            if kind != A.AggregateKind.COUNT and inner is None:
                raise ParseError(f"{t.text}() requires an argument")
            return A.AggregateExpr(kind, inner)
        return self.parse_static()

    # -- spanset expressions (structural / && / || over filters) ------------

    def parse_spanset_expr(self):
        lhs = self.parse_spanset_primary()
        while True:
            t = self.peek()
            if t.kind in _STRUCT and t.kind != T.NOT_REGEX:
                op = _STRUCT[self.next().kind]
                rhs = self.parse_spanset_primary()
                lhs = A.StructuralExpr(op, lhs, rhs)
            elif t.kind == T.NOT_REGEX and self._spanset_follows():
                self.next()
                rhs = self.parse_spanset_primary()
                lhs = A.StructuralExpr(A.StructuralOp.NOT_SIBLING, lhs, rhs)
            elif t.kind == T.AND:
                self.next()
                lhs = A.SpansetCombine(A.SpansetOp.AND, lhs,
                                       self.parse_spanset_primary())
            elif t.kind == T.OR:
                self.next()
                lhs = A.SpansetCombine(A.SpansetOp.OR, lhs,
                                       self.parse_spanset_primary())
            else:
                return lhs

    def _spanset_follows(self) -> bool:
        return self.peek(1).kind in (T.OPEN_BRACE, T.OPEN_PAREN)

    def parse_spanset_primary(self):
        if self.accept(T.OPEN_PAREN):
            inner = self.parse_spanset_expr()
            self.expect(T.CLOSE_PAREN)
            return inner
        self.expect(T.OPEN_BRACE)
        if self.accept(T.CLOSE_BRACE):
            return A.SpansetFilter(A.Static(A.StaticType.BOOL, True))
        expr = self.parse_field_expr()
        self.expect(T.CLOSE_BRACE)
        return A.SpansetFilter(expr)

    # -- field expressions --------------------------------------------------

    def parse_expr_list(self) -> list:
        out = [self.parse_field_expr()]
        while self.accept(T.COMMA):
            out.append(self.parse_field_expr())
        return out

    def parse_field_expr(self):
        return self.parse_or()

    def parse_or(self):
        lhs = self.parse_and()
        while self.accept(T.OR):
            lhs = A.BinaryOp(A.Op.OR, lhs, self.parse_and())
        return lhs

    def parse_and(self):
        lhs = self.parse_cmp()
        while self.accept(T.AND):
            lhs = A.BinaryOp(A.Op.AND, lhs, self.parse_cmp())
        return lhs

    def parse_cmp(self):
        lhs = self.parse_add()
        t = self.peek()
        if t.kind in _CMP:
            self.next()
            return A.BinaryOp(_CMP[t.kind], lhs, self.parse_add())
        return lhs

    def parse_add(self):
        lhs = self.parse_mul()
        while True:
            if self.accept(T.ADD):
                lhs = A.BinaryOp(A.Op.ADD, lhs, self.parse_mul())
            elif self.accept(T.SUB):
                lhs = A.BinaryOp(A.Op.SUB, lhs, self.parse_mul())
            else:
                return lhs

    def parse_mul(self):
        lhs = self.parse_pow()
        while True:
            t = self.peek()
            if t.kind == T.MULT:
                self.next()
                lhs = A.BinaryOp(A.Op.MULT, lhs, self.parse_pow())
            elif t.kind == T.DIV:
                self.next()
                lhs = A.BinaryOp(A.Op.DIV, lhs, self.parse_pow())
            elif t.kind == T.MOD:
                self.next()
                lhs = A.BinaryOp(A.Op.MOD, lhs, self.parse_pow())
            else:
                return lhs

    def parse_pow(self):
        lhs = self.parse_unary()
        if self.accept(T.POW):  # right-assoc
            return A.BinaryOp(A.Op.POW, lhs, self.parse_pow())
        return lhs

    def parse_unary(self):
        if self.accept(T.SUB):
            inner = self.parse_unary()
            if isinstance(inner, A.Static) and inner.type in (
                    A.StaticType.INT, A.StaticType.FLOAT, A.StaticType.DURATION):
                return A.Static(inner.type, -inner.value)
            return A.UnaryOp(A.Op.NEG, inner)
        if self.accept(T.NOT):
            return A.UnaryOp(A.Op.NOT, self.parse_unary())
        return self.parse_primary()

    def parse_primary(self):
        t = self.peek()
        if t.kind == T.OPEN_PAREN:
            self.next()
            inner = self.parse_field_expr()
            self.expect(T.CLOSE_PAREN)
            return inner
        if t.kind in (T.STRING, T.INT, T.FLOAT, T.DURATION):
            return self.parse_static()
        if t.kind == T.DOT:       # unscoped attribute
            self.next()
            name = self.expect(T.IDENT)
            return A.Attribute(str(name.value), scope=A.Scope.NONE)
        if t.kind == T.SCOPE:
            self.next()
            name = self.expect(T.IDENT)
            # dot-scoped names stay plain attributes; only the colon form
            # (`span:id`, `event:name`, ...) resolves to intrinsics
            return A.Attribute(str(name.value), scope=A.Scope(t.value))
        if t.kind == T.SCOPE_COLON:
            self.next()
            name = self.expect(T.IDENT)
            key = (t.value, str(name.value))
            if key not in A.SCOPED_INTRINSICS:
                raise ParseError(f"unknown intrinsic {t.value}:{name.value}")
            return A.Attribute.intrinsic_of(A.SCOPED_INTRINSICS[key])
        if t.kind == T.PARENT_DOT:
            self.next()
            nxt = self.peek()
            if nxt.kind == T.SCOPE:
                self.next()
                name = self.expect(T.IDENT)
                return A.Attribute(str(name.value), scope=A.Scope(nxt.value),
                                   parent=True)
            name = self.expect(T.IDENT)
            return A.Attribute(str(name.value), scope=A.Scope.NONE, parent=True)
        if t.kind == T.IDENT:
            word = t.text
            if word in ("true", "false"):
                self.next()
                return A.Static(A.StaticType.BOOL, word == "true")
            if word == "nil":
                self.next()
                return A.Static.nil()
            if word in _STATUS_WORDS:
                self.next()
                return A.Static(A.StaticType.STATUS, _STATUS_WORDS[word])
            if word in _KIND_WORDS:
                self.next()
                return A.Static(A.StaticType.KIND, _KIND_WORDS[word])
            if word in A.INTRINSIC_KEYWORDS:
                self.next()
                return A.Attribute.intrinsic_of(A.INTRINSIC_KEYWORDS[word])
        raise ParseError(
            f"parse error at {t.pos}: unexpected {t.text or 'eof'!r} in "
            f"{self.src!r}")

    def parse_static(self) -> A.Static:
        t = self.next()
        if t.kind == T.STRING:
            return A.Static(A.StaticType.STRING, t.value)
        if t.kind == T.INT:
            return A.Static(A.StaticType.INT, t.value)
        if t.kind == T.FLOAT:
            return A.Static(A.StaticType.FLOAT, t.value)
        if t.kind == T.DURATION:
            return A.Static(A.StaticType.DURATION, t.value)
        if t.kind == T.SUB:
            inner = self.parse_static()
            return A.Static(inner.type, -inner.value)
        if t.kind == T.IDENT:
            if t.text in ("true", "false"):
                return A.Static(A.StaticType.BOOL, t.text == "true")
            if t.text == "nil":
                return A.Static.nil()
            if t.text in _STATUS_WORDS:
                return A.Static(A.StaticType.STATUS, _STATUS_WORDS[t.text])
            if t.text in _KIND_WORDS:
                return A.Static(A.StaticType.KIND, _KIND_WORDS[t.text])
        raise ParseError(f"parse error at {t.pos}: expected literal, got {t.text!r}")

    # -- metrics ------------------------------------------------------------

    def parse_metrics(self) -> A.MetricsAggregate:
        t = self.next()
        kind = _METRICS[t.text]
        self.expect(T.OPEN_PAREN)
        attr = None
        params: list = []
        cmp_filter = None
        cmp_start = cmp_end = 0
        if kind == A.MetricsKind.COMPARE:
            self.expect(T.OPEN_BRACE)
            cmp_filter = (A.Static(A.StaticType.BOOL, True)
                          if self.peek().kind == T.CLOSE_BRACE
                          else self.parse_field_expr())
            self.expect(T.CLOSE_BRACE)
            if self.accept(T.COMMA):
                params.append(self.parse_static().as_float())
                if self.accept(T.COMMA):
                    cmp_start = int(self.parse_static().value)
                    self.expect(T.COMMA)
                    cmp_end = int(self.parse_static().value)
        elif kind in (A.MetricsKind.RATE, A.MetricsKind.COUNT_OVER_TIME):
            pass  # no args
        else:
            attr = self.parse_field_expr()
            while self.accept(T.COMMA):
                params.append(self.parse_static().as_float())
        self.expect(T.CLOSE_PAREN)
        by: tuple = ()
        nt = self.peek()
        if nt.kind == T.IDENT and nt.text == "by":
            self.next()
            self.expect(T.OPEN_PAREN)
            by = tuple(self.parse_expr_list())
            self.expect(T.CLOSE_PAREN)
        return A.MetricsAggregate(
            kind, attr=attr, params=tuple(params), by=by,
            compare_filter=cmp_filter, compare_start_ns=cmp_start,
            compare_end_ns=cmp_end)


def parse(src: str) -> A.Pipeline:
    """Parse a TraceQL query string into a Pipeline AST."""
    try:
        toks = lex(src)
    except LexError as e:
        raise ParseError(str(e)) from e
    return _Parser(toks, src).parse_root()
