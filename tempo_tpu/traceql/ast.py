"""TraceQL AST: statics with the type lattice, attributes, expressions,
pipeline stages (reference `pkg/traceql/ast.go`, `enum_attributes.go`,
`enum_operators.go`, `enum_statics.go`).

Nodes are frozen dataclasses; `str()` round-trips to valid TraceQL (the
stringer used by sharders to re-serialize sub-queries, like the reference's
`stringer.go`).
"""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Optional, Sequence, Union


# ---------------------------------------------------------------------------
# Static value types (enum_statics.go type lattice)
# ---------------------------------------------------------------------------

class StaticType(enum.Enum):
    NIL = "nil"
    INT = "int"
    FLOAT = "float"
    STRING = "string"
    BOOL = "bool"
    DURATION = "duration"   # nanoseconds, int-valued
    STATUS = "status"       # 0=error 1=ok 2=unset (reference enum order)
    KIND = "kind"

    def is_numeric(self) -> bool:
        return self in (StaticType.INT, StaticType.FLOAT, StaticType.DURATION)

    def comparable_with(self, other: "StaticType") -> bool:
        if self == other:
            return True
        return self.is_numeric() and other.is_numeric()


# Status enum values follow the reference (`enum_statics.go`: error=0, ok=1,
# unset=2 — NOT otlp order) so cross-shard proto payloads compare equal.
STATUS_ERROR, STATUS_OK, STATUS_UNSET = 0, 1, 2
STATUS_NAMES = {STATUS_ERROR: "error", STATUS_OK: "ok", STATUS_UNSET: "unset"}
KIND_NAMES = {0: "unspecified", 1: "internal", 2: "server", 3: "client",
              4: "producer", 5: "consumer"}
# OTLP wire order (trace.proto Status.StatusCode) → traceql order
OTLP_STATUS_TO_TRACEQL = {0: STATUS_UNSET, 1: STATUS_OK, 2: STATUS_ERROR}


@dataclasses.dataclass(frozen=True)
class Static:
    type: StaticType
    value: object = None

    @staticmethod
    def nil() -> "Static":
        return Static(StaticType.NIL, None)

    @staticmethod
    def of(v) -> "Static":
        if v is None:
            return Static.nil()
        if isinstance(v, bool):
            return Static(StaticType.BOOL, v)
        if isinstance(v, int):
            return Static(StaticType.INT, v)
        if isinstance(v, float):
            return Static(StaticType.FLOAT, v)
        if isinstance(v, str):
            return Static(StaticType.STRING, v)
        raise TypeError(f"no static type for {v!r}")

    def as_float(self) -> float:
        if self.type == StaticType.NIL:
            return float("nan")
        if self.type == StaticType.BOOL:
            return 1.0 if self.value else 0.0
        return float(self.value)

    def __str__(self) -> str:
        t, v = self.type, self.value
        if t == StaticType.NIL:
            return "nil"
        if t == StaticType.STRING:
            return '"' + str(v).replace("\\", "\\\\").replace('"', '\\"') + '"'
        if t == StaticType.BOOL:
            return "true" if v else "false"
        if t == StaticType.DURATION:
            return format_duration(int(v))
        if t == StaticType.STATUS:
            return STATUS_NAMES.get(int(v), "unset")
        if t == StaticType.KIND:
            return KIND_NAMES.get(int(v), "unspecified")
        return repr(v) if t == StaticType.FLOAT else str(v)


def format_duration(ns: int) -> str:
    for unit, scale in (("h", 3_600_000_000_000), ("m", 60_000_000_000),
                        ("s", 1_000_000_000), ("ms", 1_000_000), ("us", 1_000)):
        if ns >= scale and ns % scale == 0:
            return f"{ns // scale}{unit}"
    return f"{ns}ns"


# ---------------------------------------------------------------------------
# Attributes: scopes + intrinsics (enum_attributes.go)
# ---------------------------------------------------------------------------

class Scope(enum.Enum):
    NONE = ""            # unscoped `.attr` — resolves span then resource
    SPAN = "span"
    RESOURCE = "resource"
    PARENT = "parent"
    EVENT = "event"
    LINK = "link"
    INSTRUMENTATION = "instrumentation"


class Intrinsic(enum.Enum):
    NONE = ""
    DURATION = "duration"
    NAME = "name"
    STATUS = "status"
    STATUS_MESSAGE = "statusMessage"
    KIND = "kind"
    CHILD_COUNT = "childCount"
    ROOT_NAME = "rootName"
    ROOT_SERVICE = "rootServiceName"
    TRACE_DURATION = "traceDuration"
    NESTED_SET_LEFT = "nestedSetLeft"
    NESTED_SET_RIGHT = "nestedSetRight"
    NESTED_SET_PARENT = "nestedSetParent"
    TRACE_ID = "trace:id"
    SPAN_ID = "span:id"
    PARENT_ID = "span:parentID"
    EVENT_NAME = "event:name"
    EVENT_TIME_SINCE_START = "event:timeSinceStart"
    LINK_TRACE_ID = "link:traceID"
    LINK_SPAN_ID = "link:spanID"
    INSTRUMENTATION_NAME = "instrumentation:name"
    INSTRUMENTATION_VERSION = "instrumentation:version"
    # fetch-layer-only intrinsics (IntrinsicSpanStartTime — not parseable)
    SPAN_START_TIME = "__spanStartTime"
    # structural capabilities (resolved by the fetch layer)
    STRUCTURAL_DESCENDANT = "__descendant"
    STRUCTURAL_CHILD = "__child"
    STRUCTURAL_SIBLING = "__sibling"


# keyword → intrinsic for bare identifiers inside filters
INTRINSIC_KEYWORDS = {
    "duration": Intrinsic.DURATION,
    "name": Intrinsic.NAME,
    "status": Intrinsic.STATUS,
    "statusMessage": Intrinsic.STATUS_MESSAGE,
    "kind": Intrinsic.KIND,
    "childCount": Intrinsic.CHILD_COUNT,
    "rootName": Intrinsic.ROOT_NAME,
    "rootServiceName": Intrinsic.ROOT_SERVICE,
    "rootService": Intrinsic.ROOT_SERVICE,
    "traceDuration": Intrinsic.TRACE_DURATION,
    "nestedSetLeft": Intrinsic.NESTED_SET_LEFT,
    "nestedSetRight": Intrinsic.NESTED_SET_RIGHT,
    "nestedSetParent": Intrinsic.NESTED_SET_PARENT,
}

# "<scope>:<name>" scoped intrinsics (lexer.go trace:/span:/event:/link:)
SCOPED_INTRINSICS = {
    ("trace", "id"): Intrinsic.TRACE_ID,
    ("trace", "duration"): Intrinsic.TRACE_DURATION,
    ("trace", "rootName"): Intrinsic.ROOT_NAME,
    ("trace", "rootService"): Intrinsic.ROOT_SERVICE,
    ("span", "id"): Intrinsic.SPAN_ID,
    ("span", "parentID"): Intrinsic.PARENT_ID,
    ("span", "duration"): Intrinsic.DURATION,
    ("span", "name"): Intrinsic.NAME,
    ("span", "status"): Intrinsic.STATUS,
    ("span", "statusMessage"): Intrinsic.STATUS_MESSAGE,
    ("span", "kind"): Intrinsic.KIND,
    ("event", "name"): Intrinsic.EVENT_NAME,
    ("event", "timeSinceStart"): Intrinsic.EVENT_TIME_SINCE_START,
    ("link", "traceID"): Intrinsic.LINK_TRACE_ID,
    ("link", "spanID"): Intrinsic.LINK_SPAN_ID,
    ("instrumentation", "name"): Intrinsic.INSTRUMENTATION_NAME,
    ("instrumentation", "version"): Intrinsic.INSTRUMENTATION_VERSION,
}


@dataclasses.dataclass(frozen=True)
class Attribute:
    name: str
    scope: Scope = Scope.NONE
    intrinsic: Intrinsic = Intrinsic.NONE
    parent: bool = False  # parent.<scope>.<attr>

    @staticmethod
    def intrinsic_of(i: Intrinsic) -> "Attribute":
        return Attribute(name=i.value, intrinsic=i)

    def __str__(self) -> str:
        if self.intrinsic != Intrinsic.NONE:
            return self.intrinsic.value
        p = "parent." if self.parent else ""
        name = self.name
        # quote unless the lexer's raw-attr scanner would re-read it intact
        if not re.fullmatch(r'[^\s{}()|,=!<>~&+\-*/%^"]+', name):
            name = '"' + name.replace("\\", "\\\\").replace('"', '\\"') + '"'
        if self.scope == Scope.NONE:
            return f"{p}.{name}"
        return f"{p}{self.scope.value}.{name}"


# ---------------------------------------------------------------------------
# Operators
# ---------------------------------------------------------------------------

class Op(enum.Enum):
    AND = "&&"
    OR = "||"
    EQ = "="
    NEQ = "!="
    REGEX = "=~"
    NOT_REGEX = "!~"
    GT = ">"
    GTE = ">="
    LT = "<"
    LTE = "<="
    ADD = "+"
    SUB = "-"
    MULT = "*"
    DIV = "/"
    MOD = "%"
    POW = "^"
    NOT = "!"
    NEG = "-u"  # unary minus

    def is_boolean(self) -> bool:
        return self in (Op.AND, Op.OR, Op.EQ, Op.NEQ, Op.REGEX, Op.NOT_REGEX,
                        Op.GT, Op.GTE, Op.LT, Op.LTE, Op.NOT)


class StructuralOp(enum.Enum):
    CHILD = ">"
    PARENT = "<"
    DESCENDANT = ">>"
    ANCESTOR = "<<"
    SIBLING = "~"
    NOT_CHILD = "!>"
    NOT_PARENT = "!<"
    NOT_DESCENDANT = "!>>"
    NOT_ANCESTOR = "!<<"
    NOT_SIBLING = "!~"
    UNION_CHILD = "&>"
    UNION_PARENT = "&<"
    UNION_DESCENDANT = "&>>"
    UNION_ANCESTOR = "&<<"
    UNION_SIBLING = "&~"


class SpansetOp(enum.Enum):
    AND = "&&"      # both match within trace
    OR = "||"       # union


# ---------------------------------------------------------------------------
# Expressions (within a spanset filter)
# ---------------------------------------------------------------------------

FieldExpr = Union["BinaryOp", "UnaryOp", Static, Attribute]


@dataclasses.dataclass(frozen=True)
class BinaryOp:
    op: Op
    lhs: FieldExpr
    rhs: FieldExpr

    def __str__(self) -> str:
        return f"{paren(self.lhs)} {self.op.value} {paren(self.rhs)}"


@dataclasses.dataclass(frozen=True)
class UnaryOp:
    op: Op
    expr: FieldExpr

    def __str__(self) -> str:
        sym = "-" if self.op == Op.NEG else self.op.value
        return f"{sym}{paren(self.expr)}"


def paren(e) -> str:
    if isinstance(e, (BinaryOp,)):
        return f"({e})"
    return str(e)


# ---------------------------------------------------------------------------
# Pipeline elements
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class SpansetFilter:
    expr: FieldExpr  # boolean-typed

    def __str__(self) -> str:
        return "{ " + str(self.expr) + " }" if not _is_true(self.expr) else "{ }"


def _is_true(e) -> bool:
    return isinstance(e, Static) and e.type == StaticType.BOOL and e.value is True


@dataclasses.dataclass(frozen=True)
class ScalarFilter:
    """`| avg(duration) > 1s` — scalar condition over a spanset."""
    op: Op
    lhs: "AggregateExpr | Static"
    rhs: "AggregateExpr | Static"

    def __str__(self) -> str:
        return f"{self.lhs} {self.op.value} {self.rhs}"


class AggregateKind(enum.Enum):
    COUNT = "count"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    SUM = "sum"


@dataclasses.dataclass(frozen=True)
class AggregateExpr:
    kind: AggregateKind
    expr: Optional[FieldExpr] = None  # None for count()

    def __str__(self) -> str:
        inner = "" if self.expr is None else str(self.expr)
        return f"{self.kind.value}({inner})"


@dataclasses.dataclass(frozen=True)
class StructuralExpr:
    op: StructuralOp
    lhs: "SpansetExpr"
    rhs: "SpansetExpr"

    def __str__(self) -> str:
        return f"{self.lhs} {self.op.value} {self.rhs}"


@dataclasses.dataclass(frozen=True)
class SpansetCombine:
    op: SpansetOp
    lhs: "SpansetExpr"
    rhs: "SpansetExpr"

    def __str__(self) -> str:
        return f"{self.lhs} {self.op.value} {self.rhs}"


SpansetExpr = Union[SpansetFilter, StructuralExpr, SpansetCombine, "GroupOp",
                    "SelectOp", "CoalesceOp", "ScalarFilter", "Pipeline"]


@dataclasses.dataclass(frozen=True)
class GroupOp:
    by: tuple  # tuple[FieldExpr]

    def __str__(self) -> str:
        return "by(" + ", ".join(str(e) for e in self.by) + ")"


@dataclasses.dataclass(frozen=True)
class SelectOp:
    attrs: tuple  # tuple[FieldExpr]

    def __str__(self) -> str:
        return "select(" + ", ".join(str(e) for e in self.attrs) + ")"


@dataclasses.dataclass(frozen=True)
class CoalesceOp:
    def __str__(self) -> str:
        return "coalesce()"


# ---------------------------------------------------------------------------
# Metrics (engine_metrics.go second-stage grammar)
# ---------------------------------------------------------------------------

class MetricsKind(enum.Enum):
    RATE = "rate"
    COUNT_OVER_TIME = "count_over_time"
    MIN_OVER_TIME = "min_over_time"
    MAX_OVER_TIME = "max_over_time"
    AVG_OVER_TIME = "avg_over_time"
    SUM_OVER_TIME = "sum_over_time"
    QUANTILE_OVER_TIME = "quantile_over_time"
    HISTOGRAM_OVER_TIME = "histogram_over_time"
    COMPARE = "compare"


@dataclasses.dataclass(frozen=True)
class MetricsAggregate:
    kind: MetricsKind
    attr: Optional[FieldExpr] = None          # measured attribute
    params: tuple = ()                        # quantiles for quantile_over_time
    by: tuple = ()                            # group-by attributes
    # compare() extras
    compare_filter: Optional[FieldExpr] = None
    compare_start_ns: int = 0
    compare_end_ns: int = 0

    def __str__(self) -> str:
        args = []
        if self.kind == MetricsKind.COMPARE:
            args.append("{" + str(self.compare_filter) + "}")
            if self.params:
                args.append(str(self.params[0]))
            if self.compare_start_ns or self.compare_end_ns:
                args += [str(self.compare_start_ns), str(self.compare_end_ns)]
        else:
            if self.attr is not None:
                args.append(str(self.attr))
            args += [repr(p) for p in self.params]
        s = f"{self.kind.value}({', '.join(args)})"
        if self.by:
            s += " by(" + ", ".join(str(e) for e in self.by) + ")"
        return s


@dataclasses.dataclass(frozen=True)
class Hint:
    name: str
    value: Static

    def __str__(self) -> str:
        return f"{self.name}={self.value}"


@dataclasses.dataclass(frozen=True)
class Pipeline:
    """A full root query: spanset pipeline + optional metrics stage + hints."""
    stages: tuple            # tuple[SpansetExpr | ScalarFilter | GroupOp | ...]
    metrics: Optional[MetricsAggregate] = None
    hints: tuple = ()

    def __str__(self) -> str:
        s = " | ".join(str(st) for st in self.stages)
        if self.metrics is not None:
            s += " | " + str(self.metrics)
        if self.hints:
            s += " with (" + ", ".join(str(h) for h in self.hints) + ")"
        return s


def walk(node, fn) -> None:
    """Pre-order traversal over every AST node."""
    fn(node)
    children = ()
    if isinstance(node, Pipeline):
        children = node.stages + ((node.metrics,) if node.metrics else ())
    elif isinstance(node, (StructuralExpr, SpansetCombine)):
        children = (node.lhs, node.rhs)
    elif isinstance(node, SpansetFilter):
        children = (node.expr,)
    elif isinstance(node, BinaryOp):
        children = (node.lhs, node.rhs)
    elif isinstance(node, UnaryOp):
        children = (node.expr,)
    elif isinstance(node, ScalarFilter):
        children = (node.lhs, node.rhs)
    elif isinstance(node, AggregateExpr):
        children = (node.expr,) if node.expr is not None else ()
    elif isinstance(node, MetricsAggregate):
        children = tuple(x for x in (node.attr, node.compare_filter) if x is not None) + node.by
    elif isinstance(node, (GroupOp,)):
        children = node.by
    elif isinstance(node, SelectOp):
        children = node.attrs
    for c in children:
        walk(c, fn)
