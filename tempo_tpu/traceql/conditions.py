"""Condition pushdown: AST → FetchSpansRequest (reference
`pkg/traceql/ast_conditions.go`, `storage.go`).

The fetch layer receives a flat list of per-attribute predicates plus the
`all_conditions` flag: when True every condition must hold on a span for it
to be a candidate (pure AND tree → storage can intersect masks and skip the
second pass for simple queries); when False conditions are hints (OR
semantics) and the engine's second pass decides.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from tempo_tpu.traceql import ast as A


@dataclasses.dataclass(frozen=True)
class Condition:
    attr: A.Attribute
    op: Optional[A.Op] = None        # None = fetch the column only (select)
    operands: tuple = ()             # tuple[Static]
    # True when this fetch-only condition came from a filter expression the
    # storage layer can't evaluate (negation / cross-attribute compare): the
    # prefilter must not exclude rows based on sibling predicates then
    from_filter: bool = False

    def __str__(self) -> str:
        ops = ",".join(str(o) for o in self.operands)
        return f"{self.attr}{'' if self.op is None else self.op.value}{ops}"


@dataclasses.dataclass
class FetchSpansRequest:
    conditions: list
    all_conditions: bool
    start_ns: int = 0
    end_ns: int = 0
    second_pass_conditions: list = dataclasses.field(default_factory=list)
    # True when some pipeline arm matches spans unconditionally (`{ }` in an
    # OR, rhs of a structural op, ...): the storage prefilter must pass every
    # row through, since any span may participate in the second pass
    has_unconditioned_arm: bool = False
    # True when the single filter stage is a pure OR-tree whose every leaf
    # pushed down: the OR of the per-condition masks is then EXACT (not a
    # hint superset), so the fused metrics plane may serve the query even
    # though all_conditions is False (round 5)
    pure_disjunction: bool = False

    def add(self, c: Condition) -> None:
        if c not in self.conditions:
            self.conditions.append(c)


_ALWAYS_SECOND_PASS = {A.Op.NOT}  # negations can't prune at storage


def _pushable_compare(e) -> "tuple | None":
    """(attr, op, static) when `e` is a storage-pushable compare
    (attribute <op> literal, either side order) — the single source of
    pushability shared by the extractor and the pure-disjunction check,
    so the two can never disagree on what 'pushed' means."""
    if not isinstance(e, A.BinaryOp):
        return None
    lhs, rhs, op = e.lhs, e.rhs, e.op
    if isinstance(rhs, A.Attribute) and isinstance(lhs, A.Static):
        lhs, rhs = rhs, lhs
        op = _flip(op)
    if isinstance(lhs, A.Attribute) and isinstance(rhs, A.Static) and \
            op in (A.Op.EQ, A.Op.NEQ, A.Op.REGEX, A.Op.NOT_REGEX,
                   A.Op.GT, A.Op.GTE, A.Op.LT, A.Op.LTE):
        return lhs, op, rhs
    return None


def _is_pure_disjunction(e) -> bool:
    """True when `e` is an OR-tree whose EVERY leaf is itself a single
    pushable compare — the structural guarantee that the OR of the pushed
    masks equals the filter exactly. A count heuristic is NOT enough: an
    AND leaf can push net-one condition via dedup, or a boolean literal
    can push nothing, silently turning the mask into a superset."""
    if not (isinstance(e, A.BinaryOp) and e.op == A.Op.OR):
        return False

    def ok(x) -> bool:
        if isinstance(x, A.BinaryOp) and x.op == A.Op.OR:
            return ok(x.lhs) and ok(x.rhs)
        return _pushable_compare(x) is not None

    return ok(e)


def extract_conditions(q: A.Pipeline, start_ns: int = 0,
                       end_ns: int = 0) -> FetchSpansRequest:
    req = FetchSpansRequest(conditions=[], all_conditions=True,
                            start_ns=start_ns, end_ns=end_ns)
    # all_conditions only survives a single-filter pipeline with a pure AND
    # tree (ast_conditions.go SpansetFilter.extractConditions)
    filters = [s for s in q.stages if isinstance(s, A.SpansetFilter)]
    non_filters = [s for s in q.stages if not isinstance(s, A.SpansetFilter)]
    structural = any(isinstance(s, (A.StructuralExpr, A.SpansetCombine))
                     for s in q.stages)
    if len(filters) != 1 or structural:
        req.all_conditions = False
    for stage in q.stages:
        before = len(req.conditions)
        _extract_stage(stage, req)
        if isinstance(stage, A.SpansetFilter) and len(filters) == 1 \
                and not structural and _is_pure_disjunction(stage.expr):
            # structurally verified: every OR leaf is ONE pushable
            # compare, so the OR of the pushed masks IS the filter
            assert any(c.op is not None for c in req.conditions[before:])
            req.pure_disjunction = True
    if q.metrics is not None:
        if q.metrics.attr is not None:
            _collect_columns(q.metrics.attr, req)
        for e in q.metrics.by:
            _collect_columns(e, req)
        if q.metrics.compare_filter is not None:
            _collect_columns(q.metrics.compare_filter, req)
        # metrics need span start time for step bucketing
        req.add(Condition(A.Attribute.intrinsic_of(A.Intrinsic.SPAN_START_TIME)))
    # aggregates/scalar filters pull their referenced columns too
    for s in non_filters:
        if isinstance(s, A.ScalarFilter):
            for side in (s.lhs, s.rhs):
                if isinstance(side, A.AggregateExpr) and side.expr is not None:
                    _collect_columns(side.expr, req)
        elif isinstance(s, (A.GroupOp,)):
            for e in s.by:
                _collect_columns(e, req)
        elif isinstance(s, A.SelectOp):
            for e in s.attrs:
                _collect_columns(e, req)
    return req


def _extract_stage(stage, req: FetchSpansRequest) -> None:
    if isinstance(stage, A.SpansetFilter):
        before = len(req.conditions)
        _extract_expr(stage.expr, req, top_level=True)
        pushed = any(c.op is not None for c in req.conditions[before:])
        if not pushed:
            req.has_unconditioned_arm = True
    elif isinstance(stage, (A.StructuralExpr, A.SpansetCombine)):
        _extract_stage(stage.lhs, req)
        _extract_stage(stage.rhs, req)


def _extract_expr(e, req: FetchSpansRequest, top_level: bool = False) -> None:
    """Walk a boolean field expression, emitting Conditions.

    AND keeps all_conditions; OR flips it off (conditions become hints);
    anything non-extractable (cross-attribute compare, arithmetic) also
    clears the flag but still registers column fetches.
    """
    if isinstance(e, A.Static):
        # a literal `true` is an AND-identity (and a bare `{ true }` arm
        # registers via has_unconditioned_arm); anything else — `false`,
        # or a non-boolean literal — cannot be expressed as a pushed-down
        # condition, so the condition set is no longer exhaustive: clear
        # all_conditions to force the engine's exact second pass (and the
        # fused-metrics gate off) instead of silently matching everything
        if not (getattr(e, "type", None) == A.StaticType.BOOL
                and e.value is True):
            req.all_conditions = False
        return
    if isinstance(e, A.BinaryOp):
        if e.op == A.Op.AND:
            _extract_expr(e.lhs, req, top_level)
            _extract_expr(e.rhs, req, top_level)
            return
        if e.op == A.Op.OR:
            req.all_conditions = False
            _extract_expr(e.lhs, req)
            _extract_expr(e.rhs, req)
            return
        # comparison attr <op> static (either side)
        got = _pushable_compare(e)
        if got is not None:
            attr, op, static = got
            req.add(Condition(attr, op, (static,)))
            return
        # non-pushable comparison: fetch referenced columns, clear the flag
        req.all_conditions = False
        _collect_columns(e.lhs, req, from_filter=True)
        _collect_columns(e.rhs, req, from_filter=True)
        return
    if isinstance(e, A.UnaryOp):
        req.all_conditions = False
        _collect_columns(e.expr, req, from_filter=True)
        return
    if isinstance(e, A.Attribute):
        # bare boolean attribute `{ .error }`
        req.add(Condition(e, A.Op.EQ, (A.Static(A.StaticType.BOOL, True),)))
        return


def _collect_columns(e, req: FetchSpansRequest, from_filter: bool = False) -> None:
    if isinstance(e, A.Attribute):
        req.add(Condition(e, from_filter=from_filter))
    elif isinstance(e, A.BinaryOp):
        _collect_columns(e.lhs, req, from_filter)
        _collect_columns(e.rhs, req, from_filter)
    elif isinstance(e, A.UnaryOp):
        _collect_columns(e.expr, req, from_filter)


def _flip(op: A.Op) -> A.Op:
    return {A.Op.GT: A.Op.LT, A.Op.GTE: A.Op.LTE,
            A.Op.LT: A.Op.GT, A.Op.LTE: A.Op.GTE}.get(op, op)
