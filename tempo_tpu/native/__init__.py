"""Native runtime loader: compiles + binds the C++ hot paths via ctypes.

`available()` is False (and every helper falls back to numpy/python) when
g++ or the compiled library is missing — the framework never hard-requires
the native layer, it just gets faster with it.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB = None
_TRIED = False
_LOCK = threading.Lock()

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "native.cpp")
_SO = os.path.join(_DIR, "_tempo_native.so")

# numpy mirror of SpanRec (padding-free C layout, see native.cpp)
SPAN_REC_DTYPE = np.dtype([
    ("trace_id", np.uint8, 16),
    ("span_id", np.uint8, 8),
    ("parent_span_id", np.uint8, 8),
    ("start_ns", np.uint64),
    ("end_ns", np.uint64),
    ("name_off", np.int64),
    ("status_msg_off", np.int64),
    ("res_off", np.int64),
    ("span_off", np.int64),
    ("name_len", np.int32),
    ("status_msg_len", np.int32),
    ("res_len", np.int32),
    ("span_len", np.int32),
    ("kind", np.int32),
    ("status_code", np.int32),
    ("tid_len", np.int32),
    ("sid_len", np.int32),
    ("pid_len", np.int32),
    ("_pad", np.int32),
])
assert SPAN_REC_DTYPE.itemsize == 120

ATTR_REC_DTYPE = np.dtype([
    ("key_off", np.int64),
    ("sval_off", np.int64),
    ("ival", np.int64),
    ("fval", np.float64),
    ("key_len", np.int32),
    ("sval_len", np.int32),
    ("typ", np.int32),
    ("span_idx", np.int32),
])
assert ATTR_REC_DTYPE.itemsize == 48


def _build() -> str | None:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    tmp = f"{_SO}.{os.getpid()}.tmp"  # pid-unique: concurrent builds race
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load():
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("TEMPO_TPU_NO_NATIVE"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # corrupt cached build: remove so the next process rebuilds
            try:
                os.unlink(so)
            except OSError:
                pass
            return None
        try:
            lib.fnv1_tokens.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_uint32)]
            lib.fnv1_tokens.restype = None
            lib.otlp_scan.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64]
            lib.otlp_scan.restype = ctypes.c_int64
            lib.otlp_scan2.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64)]
            lib.otlp_scan2.restype = ctypes.c_int64
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


# -- fnv tokens --------------------------------------------------------------

def token_for(tenant: str, trace_ids: np.ndarray) -> np.ndarray:
    """Native `TokenFor` batch; falls back to the numpy implementation."""
    lib = _load()
    tids = np.ascontiguousarray(trace_ids, np.uint8)
    if tids.ndim == 1:
        tids = tids[None, :]
    if lib is None:
        from tempo_tpu.ops import hashing
        return hashing.token_for(tenant, tids)
    out = np.empty(tids.shape[0], np.uint32)
    tb = tenant.encode()
    lib.fnv1_tokens(
        tb, len(tb),
        tids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        tids.shape[0], tids.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return out


# -- OTLP scan ---------------------------------------------------------------

def otlp_scan(data: bytes, cap_hint: int = 4096) -> np.ndarray | None:
    """Single-pass OTLP proto scan → SpanRec structured array.

    Returns None when the native library is unavailable (callers fall back
    to the python decoder). Raises ValueError on malformed input.
    """
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    cap = max(cap_hint, 16)
    while True:
        recs = np.zeros(cap, SPAN_REC_DTYPE)
        n = lib.otlp_scan(bp, len(data), recs.ctypes.data, cap)
        if n < 0:
            raise ValueError("malformed OTLP protobuf payload")
        if n <= cap:
            return recs[:n]
        cap = int(n)


def otlp_scan2(data: bytes, cap_hint: int = 4096
               ) -> tuple[np.ndarray, np.ndarray] | None:
    """Single-pass scan → (SpanRec array, AttrRec array). None when the
    native library is unavailable; ValueError on malformed input."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    cap, attr_cap = max(cap_hint, 16), max(cap_hint * 4, 64)
    while True:
        recs = np.zeros(cap, SPAN_REC_DTYPE)
        attrs = np.zeros(attr_cap, ATTR_REC_DTYPE)
        n_attrs = ctypes.c_int64(0)
        n = lib.otlp_scan2(bp, len(data), recs.ctypes.data, cap,
                           attrs.ctypes.data, attr_cap,
                           ctypes.byref(n_attrs))
        if n < 0:
            raise ValueError("malformed OTLP protobuf payload")
        if n <= cap and n_attrs.value <= attr_cap:
            return recs[:n], attrs[: n_attrs.value]
        cap = max(cap, int(n))
        attr_cap = max(attr_cap, int(n_attrs.value))


def spans_from_otlp_proto_native(data: bytes):
    """Native scan → flat span dicts (the wire-entry contract of
    `model.otlp.spans_from_otlp_proto`). The C pass extracts every fixed
    field and attribute range; python only slices strings and builds dicts."""
    scanned = otlp_scan2(data)
    if scanned is None:
        return None
    recs, attrs = scanned
    from tempo_tpu.model.otlp import _pb_anyvalue

    # columnar extraction (bulk .tolist() beats per-row structured access)
    tid = recs["trace_id"].tobytes()
    sid = recs["span_id"].tobytes()
    pid = recs["parent_span_id"].tobytes()
    name_off = recs["name_off"].tolist(); name_len = recs["name_len"].tolist()
    sm_off = recs["status_msg_off"].tolist(); sm_len = recs["status_msg_len"].tolist()
    res_off = recs["res_off"].tolist(); res_len = recs["res_len"].tolist()
    start = recs["start_ns"].tolist(); end = recs["end_ns"].tolist()
    kind = recs["kind"].tolist(); code = recs["status_code"].tolist()

    res_cache: dict[tuple[int, int], dict] = {}

    def resource_attrs(ro: int, rl: int) -> dict:
        if ro < 0:
            return {}
        key = (ro, rl)
        cached = res_cache.get(key)
        if cached is None:
            from tempo_tpu.model import proto_wire as pw
            from tempo_tpu.model.otlp import _pb_attrs
            cached = res_cache[key] = _pb_attrs(
                [v for f, _, v in pw.iter_fields(data[ro:ro + rl]) if f == 1])
        return cached

    n = len(recs)
    tid_len = recs["tid_len"].tolist()
    sid_len = recs["sid_len"].tolist()
    pid_len = recs["pid_len"].tolist()
    # wire lengths preserved: an absent id slices to b"" and an oversized
    # one to its (uncopied, zeroed) declared size — both match the python
    # decoder's contract so the distributor's invalid-id validation fires
    # identically on either path
    out = [{
        "trace_id": tid[i * 16: i * 16 + min(tid_len[i], 16)]
        if tid_len[i] <= 16 else b"\x00" * tid_len[i],
        "span_id": sid[i * 8: i * 8 + min(sid_len[i], 8)]
        if sid_len[i] <= 8 else b"\x00" * sid_len[i],
        "parent_span_id": pid[i * 8: i * 8 + min(pid_len[i], 8)]
        if pid_len[i] <= 8 else b"\x00" * pid_len[i],
        "name": data[name_off[i]: name_off[i] + name_len[i]].decode("utf-8", "replace"),
        "service": "",
        "kind": kind[i],
        "status_code": code[i],
        "status_message": data[sm_off[i]: sm_off[i] + sm_len[i]].decode("utf-8", "replace"),
        "start_unix_nano": start[i],
        "end_unix_nano": end[i],
        "attrs": {},
        "res_attrs": None,
    } for i in range(n)]
    for i in range(n):
        ra = resource_attrs(res_off[i], res_len[i])
        out[i]["res_attrs"] = ra
        out[i]["service"] = str(ra.get("service.name", ""))

    # span attrs from the flat attr table
    a_key_off = attrs["key_off"].tolist(); a_key_len = attrs["key_len"].tolist()
    a_sval_off = attrs["sval_off"].tolist(); a_sval_len = attrs["sval_len"].tolist()
    a_fval = attrs["fval"].tolist(); a_ival = attrs["ival"].tolist()
    a_typ = attrs["typ"].tolist(); a_span = attrs["span_idx"].tolist()
    for j in range(len(attrs)):
        ko = a_key_off[j]
        k = data[ko: ko + a_key_len[j]].decode("utf-8", "replace") \
            if ko >= 0 else ""
        t = a_typ[j]
        if t == 1:
            v = data[a_sval_off[j]: a_sval_off[j] + a_sval_len[j]].decode("utf-8", "replace")
        elif t == 2:
            v = bool(a_fval[j])
        elif t == 3:
            v = a_ival[j]  # exact int64 (no double round-trip)
        elif t == 4:
            v = a_fval[j]
        else:
            v = _pb_anyvalue(data[a_sval_off[j]: a_sval_off[j] + a_sval_len[j]]) \
                if a_sval_off[j] >= 0 else None
        out[a_span[j]]["attrs"][k] = v
    return out
