"""Native runtime loader: compiles + binds the C++ hot paths via ctypes.

`available()` is False (and every helper falls back to numpy/python) when
g++ or the compiled library is missing — the framework never hard-requires
the native layer, it just gets faster with it.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

_LIB = None
_TRIED = False
_LOCK = threading.Lock()

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "native.cpp")


def _so_path() -> str:
    """Source-hash-keyed build target in a user cache dir (the build
    artifact is never committed; a stale hash simply rebuilds)."""
    with open(_SRC, "rb") as f:
        tag = hashlib.sha1(f.read()).hexdigest()[:12]
    base = os.environ.get("TEMPO_TPU_CACHE") or os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.expanduser("~/.cache"), "tempo_tpu")
    try:
        os.makedirs(base, exist_ok=True)
    except OSError:
        # last resort: a per-uid private dir under tmp — never load a .so
        # another user could have planted at a predictable shared path
        base = os.path.join(tempfile.gettempdir(),
                            f"tempo_tpu-{os.getuid()}")
        os.makedirs(base, mode=0o700, exist_ok=True)
        st = os.stat(base)
        if st.st_uid != os.getuid() or (st.st_mode & 0o077):
            raise OSError(f"refusing unsafe cache dir {base}")
    return os.path.join(base, f"_tempo_native_{tag}.so")

# numpy mirror of SpanRec (padding-free C layout, see native.cpp)
SPAN_REC_DTYPE = np.dtype([
    ("trace_id", np.uint8, 16),
    ("span_id", np.uint8, 8),
    ("parent_span_id", np.uint8, 8),
    ("start_ns", np.uint64),
    ("end_ns", np.uint64),
    ("name_off", np.int64),
    ("status_msg_off", np.int64),
    ("res_off", np.int64),
    ("span_off", np.int64),
    ("name_len", np.int32),
    ("status_msg_len", np.int32),
    ("res_len", np.int32),
    ("span_len", np.int32),
    ("kind", np.int32),
    ("status_code", np.int32),
    ("tid_len", np.int32),
    ("sid_len", np.int32),
    ("pid_len", np.int32),
    ("_pad", np.int32),
])
assert SPAN_REC_DTYPE.itemsize == 120

ATTR_REC_DTYPE = np.dtype([
    ("key_off", np.int64),
    ("sval_off", np.int64),
    ("ival", np.int64),
    ("fval", np.float64),
    ("key_len", np.int32),
    ("sval_len", np.int32),
    ("typ", np.int32),
    ("span_idx", np.int32),
])
assert ATTR_REC_DTYPE.itemsize == 48

# numpy mirrors of the otlp_stage output records (see native.cpp)
STAGE_REC_DTYPE = np.dtype([
    ("trace_id", np.uint8, 16),
    ("span_id", np.uint8, 8),
    ("parent_span_id", np.uint8, 8),
    ("start_ns", np.uint64),
    ("end_ns", np.uint64),
    ("name_id", np.int32),
    ("status_msg_id", np.int32),
    ("service_id", np.int32),
    ("res_idx", np.int32),
    ("kind", np.int32),
    ("status_code", np.int32),
    ("span_len", np.int32),
    ("tid_len", np.int32),
    ("sid_len", np.int32),
    ("pid_len", np.int32),
])
assert STAGE_REC_DTYPE.itemsize == 88

STAGE_ATTR_DTYPE = np.dtype([
    ("sval_off", np.int64),
    ("ival", np.int64),
    ("fval", np.float64),
    ("sval_len", np.int32),
    ("key_id", np.int32),
    ("sval_id", np.int32),
    ("typ", np.int32),
    ("owner", np.int32),
    ("_pad", np.int32),
])
assert STAGE_ATTR_DTYPE.itemsize == 48

STAGE_RES_DTYPE = np.dtype([
    ("service_id", np.int32),
    ("attr_start", np.int32),
    ("attr_count", np.int32),
    ("_pad", np.int32),
])
assert STAGE_RES_DTYPE.itemsize == 16

EV_REC_DTYPE = np.dtype([
    ("name_off", np.int64),
    ("time_ns", np.uint64),
    ("name_len", np.int32),
    ("span_idx", np.int32),
])
assert EV_REC_DTYPE.itemsize == 24

LINK_REC_DTYPE = np.dtype([
    ("trace_id", np.uint8, 16),
    ("span_id", np.uint8, 8),
    ("span_idx", np.int32),
    ("tid_len", np.int32),
    ("sid_len", np.int32),
    ("_pad", np.int32),
])
assert LINK_REC_DTYPE.itemsize == 40


def _build() -> str | None:
    try:
        so = _so_path()
    except OSError:
        return None
    if os.path.exists(so):
        return so
    tmp = f"{so}.{os.getpid()}.tmp"  # pid-unique: concurrent builds race
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-pthread", "-shared", "-fPIC",
             "-o", tmp, _SRC],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return so
    except Exception:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load():
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("TEMPO_TPU_NO_NATIVE"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            # corrupt cached build: remove so the next process rebuilds
            try:
                os.unlink(so)
            except OSError:
                pass
            return None
        try:
            c = ctypes
            u8p, i32p, i64p = (c.POINTER(c.c_uint8), c.POINTER(c.c_int32),
                               c.POINTER(c.c_int64))
            lib.fnv1_tokens.argtypes = [
                c.c_char_p, c.c_int64, u8p, c.c_int64, c.c_int64,
                c.POINTER(c.c_uint32)]
            lib.fnv1_tokens.restype = None
            lib.crc32c.argtypes = [c.c_char_p, c.c_int64]
            lib.crc32c.restype = c.c_uint32
            lib.group_keys.argtypes = [u8p, c.c_int64, c.c_int32, i32p, i32p]
            lib.group_keys.restype = c.c_int64
            lib.otlp_scan.argtypes = [u8p, c.c_int64, c.c_void_p, c.c_int64]
            lib.otlp_scan.restype = c.c_int64
            lib.otlp_scan_mt.argtypes = [
                u8p, c.c_int64, c.c_void_p, c.c_int64, c.c_int32]
            lib.otlp_scan_mt.restype = c.c_int64
            lib.otlp_scan2.argtypes = [
                u8p, c.c_int64, c.c_void_p, c.c_int64,
                c.c_void_p, c.c_int64, i64p]
            lib.otlp_scan2.restype = c.c_int64
            # interner
            lib.interner_new.restype = c.c_void_p
            lib.interner_free.argtypes = [c.c_void_p]
            lib.interner_intern.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
            lib.interner_intern.restype = c.c_int32
            lib.interner_find.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
            lib.interner_find.restype = c.c_int32
            lib.interner_count.argtypes = [c.c_void_p]
            lib.interner_count.restype = c.c_int64
            lib.interner_dump.argtypes = [
                c.c_void_p, c.c_int32, c.c_int32, u8p, c.c_int64, i32p]
            lib.interner_dump.restype = c.c_int64
            # row table
            lib.rowtable_new.argtypes = [c.c_int32]
            lib.rowtable_new.restype = c.c_void_p
            lib.rowtable_free.argtypes = [c.c_void_p]
            lib.rowtable_lookup.argtypes = [
                c.c_void_p, i32p, c.c_int64, u8p, i32p, i64p, c.c_int64]
            lib.rowtable_lookup.restype = c.c_int64
            lib.rowtable_insert.argtypes = [c.c_void_p, i32p, c.c_int32]
            lib.rowtable_insert.restype = None
            lib.rowtable_remove.argtypes = [c.c_void_p, i32p]
            lib.rowtable_remove.restype = None
            lib.rowtable_size.argtypes = [c.c_void_p]
            lib.rowtable_size.restype = c.c_int64
            lib.otlp_events.argtypes = [
                u8p, c.c_int64, c.c_void_p, c.c_int64,
                c.c_void_p, c.c_int64, i64p]
            lib.otlp_events.restype = c.c_int32
            # full staging
            lib.otlp_stage.argtypes = [
                c.c_void_p, u8p, c.c_int64,
                c.c_void_p, c.c_int64, c.c_void_p, c.c_int64,
                c.c_void_p, c.c_int64, c.c_void_p, c.c_int64,
                c.c_int32, i64p]
            lib.otlp_stage.restype = c.c_int32
            lib.otlp_stage_mt.argtypes = [
                c.c_void_p, u8p, c.c_int64,
                c.c_void_p, c.c_int64, c.c_void_p, c.c_int64,
                c.c_void_p, c.c_int64,
                c.c_int32, i64p, c.c_int32]
            lib.otlp_stage_mt.restype = c.c_int32
            lib.spanmetrics_resolve.argtypes = [
                c.c_void_p, c.c_void_p, c.c_int64,      # table, spans, n
                i32p, c.c_int32, i32p, i32p,            # dims, kind/status
                c.c_int64, c.c_int64, c.c_double,       # slack lo/hi, now
                c.POINTER(c.c_double),                  # last_seen
                i32p, c.c_void_p, c.c_void_p,           # slots, dur, size
                i32p, u8p, i64p, c.c_int64, i64p]       # rows, valid, miss
            lib.spanmetrics_resolve.restype = c.c_int64
            lib.spanmetrics_from_recs.argtypes = [
                c.c_void_p, c.c_void_p, u8p, c.c_int64,  # table, it, buf
                c.c_void_p, c.c_int64,                   # recs, n
                i32p, c.c_int32, i32p, i32p,             # dims, kind/status
                c.c_int64, c.c_int64, c.c_double,        # slack, now
                c.POINTER(c.c_double),                   # last_seen
                i32p, c.c_void_p, c.c_void_p,            # slots, dur, size
                i32p, u8p, i64p, c.c_int64, i64p]        # rows, valid, miss
            lib.spanmetrics_from_recs.restype = c.c_int64
            lib.group_keys_recs.argtypes = [
                c.c_void_p, c.c_int64, u8p, i32p, i32p]
            lib.group_keys_recs.restype = c.c_int64
            lib.group_keys_strided.argtypes = [
                c.c_void_p, c.c_int64, c.c_int64, c.c_int64, c.c_int64,
                u8p, i32p, i32p]
            lib.group_keys_strided.restype = c.c_int64
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


def crc32c(data: bytes) -> "int | None":
    """Native Castagnoli CRC (kafka record batches); None when the
    library is unavailable (callers fall back to the python table)."""
    lib = _load()
    if lib is None:
        return None
    return int(lib.crc32c(data, len(data)))


# -- fnv tokens --------------------------------------------------------------

def token_for(tenant: str, trace_ids: np.ndarray) -> np.ndarray:
    """Native `TokenFor` batch; falls back to the numpy implementation."""
    lib = _load()
    tids = np.ascontiguousarray(trace_ids, np.uint8)
    if tids.ndim == 1:
        tids = tids[None, :]
    if lib is None:
        from tempo_tpu.ops import hashing
        return hashing.token_for(tenant, tids)
    out = np.empty(tids.shape[0], np.uint32)
    tb = tenant.encode()
    lib.fnv1_tokens(
        tb, len(tb),
        tids.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        tids.shape[0], tids.shape[1],
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
    return out


# -- OTLP scan ---------------------------------------------------------------

def group_keys(keys: np.ndarray) -> "tuple[np.ndarray, np.ndarray] | None":
    """Group [n, k] uint8 fixed-width keys in first-occurrence order.

    Returns (first_idx[int32, n_uniq], inverse[int32, n]) — the O(n) hash
    replacement for `np.unique` over void views (which argsorts). Falls
    back to numpy when the native layer is unavailable.
    """
    keys = np.ascontiguousarray(keys, np.uint8)
    n, k = keys.shape
    lib = _load()
    if lib is None:
        void = keys.view([("v", f"V{k}")]).ravel()
        _, first, inverse = np.unique(void, return_index=True,
                                      return_inverse=True)
        # relabel np.unique's sorted order to first-occurrence order so
        # fallback hosts group identically to the native path
        order = np.argsort(first, kind="stable")
        remap = np.empty(len(order), np.int64)
        remap[order] = np.arange(len(order))
        return (first[order].astype(np.int32),
                remap[inverse].astype(np.int32))
    inverse = np.empty(n, np.int32)
    first = np.empty(max(n, 1), np.int32)
    got = lib.group_keys(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n, k,
        inverse.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        first.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return first[:got], inverse


_SCAN_THREADS = min(8, os.cpu_count() or 1)
_SCAN_MT_BYTES = 256 << 10        # payloads below this stay single-thread
# adaptive capacity hints: start where the last payload ended so steady
# traffic never pays the scan-twice-regrow pass
_CAP_HINTS: dict = {}


def otlp_scan(data: bytes, cap_hint: "int | None" = None) -> np.ndarray | None:
    """Single-pass OTLP proto scan → SpanRec structured array.

    Large payloads fan ResourceSpans ranges across threads (the GIL is
    released inside the ctypes call); output order matches the sequential
    scan exactly. Returns None when the native library is unavailable
    (callers fall back to the python decoder). Raises ValueError on
    malformed input.
    """
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    # an EXPLICIT cap_hint is honored exactly (tests exercise the regrow
    # branch with it); only the default consults the adaptive hint
    cap = cap_hint if cap_hint is not None else max(
        _CAP_HINTS.get("scan", 4096), 16)
    cap = max(cap, 16)
    mt = len(data) >= _SCAN_MT_BYTES and _SCAN_THREADS > 1
    while True:
        recs = np.empty(cap, SPAN_REC_DTYPE)   # scan fills every used rec
        if mt:
            n = lib.otlp_scan_mt(bp, len(data), recs.ctypes.data, cap,
                                 _SCAN_THREADS)
        else:
            n = lib.otlp_scan(bp, len(data), recs.ctypes.data, cap)
        if n < 0:
            raise ValueError("malformed OTLP protobuf payload")
        if n <= cap:
            # 25% headroom + a floor: size jitter must not re-trigger
            # the scan-twice regrow this hint exists to kill
            _CAP_HINTS["scan"] = max(4096, int(n) * 5 // 4)
            if n * 4 < cap:
                # don't let a small result pin a hint-inflated buffer
                return recs[:n].copy()
            return recs[:n]
        cap = int(n)


def otlp_scan2(data: bytes, cap_hint: int = 4096
               ) -> tuple[np.ndarray, np.ndarray] | None:
    """Single-pass scan → (SpanRec array, AttrRec array). None when the
    native library is unavailable; ValueError on malformed input."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    cap, attr_cap = max(cap_hint, 16), max(cap_hint * 4, 64)
    while True:
        recs = np.zeros(cap, SPAN_REC_DTYPE)
        attrs = np.zeros(attr_cap, ATTR_REC_DTYPE)
        n_attrs = ctypes.c_int64(0)
        n = lib.otlp_scan2(bp, len(data), recs.ctypes.data, cap,
                           attrs.ctypes.data, attr_cap,
                           ctypes.byref(n_attrs))
        if n < 0:
            raise ValueError("malformed OTLP protobuf payload")
        if n <= cap and n_attrs.value <= attr_cap:
            return recs[:n], attrs[: n_attrs.value]
        cap = max(cap, int(n))
        attr_cap = max(attr_cap, int(n_attrs.value))


# -- persistent interner / row table ----------------------------------------

class NativeInterner:
    """Handle on the C++ string intern table (bytes → dense int32 id).

    The Python StringInterner fronts this with a str-keyed cache and a
    lazily synced id → str mirror; see tempo_tpu.model.interner."""

    __slots__ = ("_h", "_lib")

    def __init__(self) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._h = ctypes.c_void_p(lib.interner_new())

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h and getattr(self, "_lib", None) is not None:
            try:
                self._lib.interner_free(h)
            except Exception:
                pass

    def intern_bytes(self, b: bytes) -> int:
        return int(self._lib.interner_intern(self._h, b, len(b)))

    def find_bytes(self, b: bytes) -> int:
        return int(self._lib.interner_find(self._h, b, len(b)))

    def count(self) -> int:
        return int(self._lib.interner_count(self._h))

    def dump(self, first: int, n: int) -> list[bytes]:
        """Strings [first, first+n) as raw bytes (mirror sync)."""
        if n <= 0:
            return []
        cap = max(n * 16, 1024)
        lens = np.empty(n, np.int32)
        while True:
            out = np.empty(cap, np.uint8)
            got = self._lib.interner_dump(
                self._h, first, n,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
            if got == -1:
                raise IndexError(f"interner_dump [{first}, {first + n})")
            if got < 0:
                cap = -got
                continue
            buf = out.tobytes()
            res, o = [], 0
            for ln in lens.tolist():
                res.append(buf[o:o + ln])
                o += ln
            return res

class NativeRowTable:
    """Handle on the C++ label-row → slot table (series resolution)."""

    __slots__ = ("_h", "_lib", "n_labels")

    def __init__(self, n_labels: int) -> None:
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.n_labels = n_labels
        self._h = ctypes.c_void_p(lib.rowtable_new(n_labels))

    def __del__(self) -> None:
        h = getattr(self, "_h", None)
        if h and getattr(self, "_lib", None) is not None:
            try:
                self._lib.rowtable_free(h)
            except Exception:
                pass

    def lookup(self, rows: np.ndarray, valid: np.ndarray | None
               ) -> tuple[np.ndarray, np.ndarray]:
        """(slots [n] int32 with -1 unresolved, miss first-occurrence idx).

        Every reported miss MUST be resolved via insert() or remove()
        before the next lookup (pending entries are not re-reported)."""
        rows = np.ascontiguousarray(rows, np.int32)
        n = rows.shape[0]
        out = np.empty(n, np.int32)
        miss = np.empty(n, np.int64)
        vp = None
        if valid is not None:
            vbuf = np.ascontiguousarray(valid, np.uint8)
            vp = vbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
        n_miss = self._lib.rowtable_lookup(
            self._h, rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), n,
            vp, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            miss.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n)
        return out, miss[:n_miss]

    def insert(self, row: np.ndarray, slot: int) -> None:
        row = np.ascontiguousarray(row, np.int32)
        self._lib.rowtable_insert(
            self._h, row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            slot)

    def remove(self, row: np.ndarray) -> None:
        row = np.ascontiguousarray(row, np.int32)
        self._lib.rowtable_remove(
            self._h, row.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))

    def size(self) -> int:
        return int(self._lib.rowtable_size(self._h))


def otlp_stage(interner: "NativeInterner", data: bytes,
               cap_hint: "int | None" = None, skip_span_attrs: bool = False,
               trust_attrs: bool = False):
    """One-pass OTLP bytes → interned columns.

    Returns (spans StageRec[], span_attrs StageAttr[], res_attrs
    StageAttr[], resources StageRes[]) or None when the native library is
    unavailable. Raises ValueError on malformed input. With
    `skip_span_attrs` the scan validates span attributes but neither
    interns nor emits them (intrinsic-dims-only callers); `trust_attrs`
    additionally skips that validation — ONLY for bytes already validated
    in this process (the distributor's in-process tee)."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    flags = (1 if skip_span_attrs else 0) | \
        (2 if trust_attrs and skip_span_attrs else 0)
    hint_key = "stage_skip" if skip_span_attrs else "stage_full"
    cap = cap_hint if cap_hint is not None else max(
        _CAP_HINTS.get(hint_key, 4096), 16)
    cap = max(cap, 16)
    acap = 16 if skip_span_attrs else max(
        cap * 4, _CAP_HINTS.get("stage_attrs", 64))
    rcap, rescap = 256, 64
    mt = (skip_span_attrs and len(data) >= _SCAN_MT_BYTES
          and _SCAN_THREADS > 1)
    while True:
        # stage fills every record it emits: empty alloc, no MB memsets
        spans = np.empty(cap, STAGE_REC_DTYPE)
        sattrs = np.empty(acap, STAGE_ATTR_DTYPE)
        rattrs = np.empty(rcap, STAGE_ATTR_DTYPE)
        res = np.empty(rescap, STAGE_RES_DTYPE)
        n_out = np.zeros(4, np.int64)
        if mt:
            # parallel staging (skip-attrs shapes): ResourceSpans ranges
            # fan across threads with thread-local intern memos
            rc = lib.otlp_stage_mt(
                interner._h, bp, len(data),
                spans.ctypes.data, cap,
                rattrs.ctypes.data, rcap, res.ctypes.data, rescap,
                flags, n_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                _SCAN_THREADS)
        else:
            rc = lib.otlp_stage(
                interner._h, bp, len(data),
                spans.ctypes.data, cap, sattrs.ctypes.data, acap,
                rattrs.ctypes.data, rcap, res.ctypes.data, rescap,
                flags, n_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc != 0:
            raise ValueError("malformed OTLP protobuf payload")
        ns, na, nr, nres = (int(x) for x in n_out)
        if ns <= cap and na <= acap and nr <= rcap and nres <= rescap:
            _CAP_HINTS[hint_key] = max(4096, ns * 5 // 4)
            if not skip_span_attrs:
                _CAP_HINTS["stage_attrs"] = max(256, na * 5 // 4)
            out = (spans[:ns], sattrs[:na], rattrs[:nr], res[:nres])
            if ns * 4 < cap:
                out = tuple(a.copy() for a in out)
            return out
        cap, acap = max(cap, ns), max(acap, na)
        rcap, rescap = max(rcap, nr), max(rescap, nres)


def otlp_events(data: bytes, ev_hint: int = 256, link_hint: int = 64
                ) -> tuple[np.ndarray, np.ndarray] | None:
    """Span events + links keyed by span index (EvRec/LinkRec arrays);
    None when the native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, np.uint8)
    bp = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    ecap, lcap = max(ev_hint, 16), max(link_hint, 16)
    while True:
        evs = np.zeros(ecap, EV_REC_DTYPE)
        links = np.zeros(lcap, LINK_REC_DTYPE)
        n_out = np.zeros(2, np.int64)
        rc = lib.otlp_events(
            bp, len(data), evs.ctypes.data, ecap, links.ctypes.data, lcap,
            n_out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc != 0:
            raise ValueError("malformed OTLP protobuf payload")
        ne, nl = int(n_out[0]), int(n_out[1])
        if ne <= ecap and nl <= lcap:
            return evs[:ne], links[:nl]
        ecap, lcap = max(ecap, ne), max(lcap, nl)


def spans_from_otlp_proto_native(data: bytes, return_recs: bool = False):
    """Native scan → flat span dicts (the wire-entry contract of
    `model.otlp.spans_from_otlp_proto`). The C pass extracts every fixed
    field and attribute range; python only slices strings and builds dicts.
    With `return_recs` returns (dicts, SpanRec array) so the caller can
    reuse the wire offsets (the distributor tee slices raw payloads with
    them) without a second scan."""
    scanned = otlp_scan2(data)
    if scanned is None:
        return (None, None) if return_recs else None
    recs, attrs = scanned
    from tempo_tpu.model.otlp import _pb_anyvalue

    # columnar extraction (bulk .tolist() beats per-row structured access)
    tid = recs["trace_id"].tobytes()
    sid = recs["span_id"].tobytes()
    pid = recs["parent_span_id"].tobytes()
    name_off = recs["name_off"].tolist(); name_len = recs["name_len"].tolist()
    sm_off = recs["status_msg_off"].tolist(); sm_len = recs["status_msg_len"].tolist()
    res_off = recs["res_off"].tolist(); res_len = recs["res_len"].tolist()
    start = recs["start_ns"].tolist(); end = recs["end_ns"].tolist()
    kind = recs["kind"].tolist(); code = recs["status_code"].tolist()

    res_cache: dict[tuple[int, int], dict] = {}

    def resource_attrs(ro: int, rl: int) -> dict:
        if ro < 0:
            return {}
        key = (ro, rl)
        cached = res_cache.get(key)
        if cached is None:
            from tempo_tpu.model import proto_wire as pw
            from tempo_tpu.model.otlp import _pb_attrs
            cached = res_cache[key] = _pb_attrs(
                [v for f, _, v in pw.iter_fields(data[ro:ro + rl]) if f == 1])
        return cached

    n = len(recs)
    tid_len = recs["tid_len"].tolist()
    sid_len = recs["sid_len"].tolist()
    pid_len = recs["pid_len"].tolist()
    # wire lengths preserved: an absent id slices to b"" and an oversized
    # one to its (uncopied, zeroed) declared size — both match the python
    # decoder's contract so the distributor's invalid-id validation fires
    # identically on either path
    out = [{
        "trace_id": tid[i * 16: i * 16 + min(tid_len[i], 16)]
        if tid_len[i] <= 16 else b"\x00" * tid_len[i],
        "span_id": sid[i * 8: i * 8 + min(sid_len[i], 8)]
        if sid_len[i] <= 8 else b"\x00" * sid_len[i],
        "parent_span_id": pid[i * 8: i * 8 + min(pid_len[i], 8)]
        if pid_len[i] <= 8 else b"\x00" * pid_len[i],
        "name": data[name_off[i]: name_off[i] + name_len[i]].decode("utf-8", "replace"),
        "service": "",
        "kind": kind[i],
        "status_code": code[i],
        "status_message": data[sm_off[i]: sm_off[i] + sm_len[i]].decode("utf-8", "replace"),
        "start_unix_nano": start[i],
        "end_unix_nano": end[i],
        "attrs": {},
        "res_attrs": None,
    } for i in range(n)]
    for i in range(n):
        ra = resource_attrs(res_off[i], res_len[i])
        out[i]["res_attrs"] = ra
        out[i]["service"] = str(ra.get("service.name", ""))

    # span attrs from the flat attr table
    a_key_off = attrs["key_off"].tolist(); a_key_len = attrs["key_len"].tolist()
    a_sval_off = attrs["sval_off"].tolist(); a_sval_len = attrs["sval_len"].tolist()
    a_fval = attrs["fval"].tolist(); a_ival = attrs["ival"].tolist()
    a_typ = attrs["typ"].tolist(); a_span = attrs["span_idx"].tolist()
    for j in range(len(attrs)):
        ko = a_key_off[j]
        k = data[ko: ko + a_key_len[j]].decode("utf-8", "replace") \
            if ko >= 0 else ""
        t = a_typ[j]
        if t == 1:
            v = data[a_sval_off[j]: a_sval_off[j] + a_sval_len[j]].decode("utf-8", "replace")
        elif t == 2:
            v = bool(a_fval[j])
        elif t == 3:
            v = a_ival[j]  # exact int64 (no double round-trip)
        elif t == 4:
            v = a_fval[j]
        else:
            v = _pb_anyvalue(data[a_sval_off[j]: a_sval_off[j] + a_sval_len[j]]) \
                if a_sval_off[j] >= 0 else None
        out[a_span[j]]["attrs"][k] = v

    # events/links (separate native pass; same span traversal order —
    # keeps the output contract aligned with the python decoder)
    got_ev = otlp_events(data)
    if got_ev is not None:
        evs, links = got_ev
        e_off = evs["name_off"].tolist(); e_len = evs["name_len"].tolist()
        e_t = evs["time_ns"].tolist(); e_s = evs["span_idx"].tolist()
        for j in range(len(evs)):
            o = e_off[j]
            out[e_s[j]].setdefault("events", []).append({
                "time_unix_nano": e_t[j],
                "name": data[o:o + e_len[j]].decode("utf-8", "replace")
                if o >= 0 else ""})
        l_tid = links["trace_id"].tobytes(); l_sid = links["span_id"].tobytes()
        l_tl = links["tid_len"].tolist(); l_sl = links["sid_len"].tolist()
        l_s = links["span_idx"].tolist()
        for j in range(len(links)):
            out[l_s[j]].setdefault("links", []).append({
                "trace_id": l_tid[j * 16: j * 16 + min(l_tl[j], 16)],
                "span_id": l_sid[j * 8: j * 8 + min(l_sl[j], 8)]})
    return (out, recs) if return_recs else out


class ResolveBuffers:
    """One pre-allocated staging-buffer set for the fused spanmetrics
    resolve: the arrays the C++ pass fills and the (async) device
    dispatch later reads. The ingest pipeline recycles these once the
    dispatch that reads them has landed — steady state allocates zero
    new staging memory per push."""

    __slots__ = ("cap", "n_labels", "slots", "packed", "rows", "valid",
                 "miss", "counts")

    def __init__(self, cap: int, n_labels: int) -> None:
        self.cap = cap
        self.n_labels = n_labels
        self.slots = np.full(cap, -1, np.int32)
        self.packed = np.zeros((3, cap), np.float32)
        self.rows = np.empty((max(cap, 1), n_labels), np.int32)
        self.valid = np.zeros(cap, np.uint8)
        self.miss = np.empty(max(cap, 1), np.int64)
        self.counts = np.zeros(2, np.int64)

    def reset(self) -> None:
        """Restore the fill values a fresh allocation would carry (the
        previous push's rows beyond the new n must read as padding)."""
        self.slots.fill(-1)
        self.packed.fill(0.0)
        self.valid.fill(0)


def _resolve_arrays(cap: int, n_labels: int, n: int,
                    out: "ResolveBuffers | None"):
    """(slots, packed, rows, valid, miss, counts) — from the reusable
    buffer set when one of the right shape is offered, else fresh."""
    if out is not None and out.cap == cap and out.n_labels == n_labels:
        out.reset()
        return (out.slots, out.packed, out.rows[:max(n, 1)], out.valid,
                out.miss, out.counts)
    return (np.full(cap, -1, np.int32), np.zeros((3, cap), np.float32),
            np.empty((max(n, 1), n_labels), np.int32),
            np.zeros(cap, np.uint8), np.empty(max(n, 1), np.int64),
            np.zeros(2, np.int64))


def spanmetrics_resolve(table: "NativeRowTable", spans: np.ndarray,
                        dims: np.ndarray, kind_lut: np.ndarray,
                        status_lut: np.ndarray, slack_lo: int, slack_hi: int,
                        now: float, last_seen: "np.ndarray | None",
                        cap: int, out: "ResolveBuffers | None" = None):
    """Fused staged-records → device-ready arrays (see native.cpp
    `spanmetrics_resolve`). Returns (slots, packed, rows, valid, miss_idx,
    n_valid, n_filtered): `packed` is the [3, cap] f32 single-H2D buffer
    whose rows 1/2 hold dur_s/sizes (row 0 is reserved for the caller's
    f32 slot copy); slots/valid are cap-padded (slot tail -1 → masked out
    of the scatter); rows is [n, L] for the miss-resolution pass. None
    when the library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    n = len(spans)
    if cap < n:
        raise ValueError("cap must be >= len(spans)")
    spans = np.ascontiguousarray(spans)
    dims = np.ascontiguousarray(dims, np.int32)
    kind_lut = np.ascontiguousarray(kind_lut, np.int32)
    status_lut = np.ascontiguousarray(status_lut, np.int32)
    # dur/sizes are rows 1/2 of ONE packed [3, cap] f32 buffer: the fast
    # paths upload slots+dur+sizes as a single H2D transfer (row 0 takes
    # the f32 slot copy after miss resolution)
    slots, packed, rows, valid, miss, counts = _resolve_arrays(
        cap, int(dims.shape[0]), n, out)
    dur = packed[1]
    sizes = packed[2]
    i32 = ctypes.POINTER(ctypes.c_int32)
    lsp = None
    if last_seen is not None:
        assert last_seen.dtype == np.float64 and last_seen.flags.c_contiguous
        lsp = last_seen.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    nm = lib.spanmetrics_resolve(
        table._h, spans.ctypes.data, n,
        dims.ctypes.data_as(i32), int(dims.shape[0]),
        kind_lut.ctypes.data_as(i32), status_lut.ctypes.data_as(i32),
        slack_lo, slack_hi, now, lsp,
        slots.ctypes.data_as(i32), dur.ctypes.data, sizes.ctypes.data,
        rows.ctypes.data_as(i32),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        miss.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(miss),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return (slots, packed, rows, valid, miss[:nm],
            int(counts[0]), int(counts[1]))


def spanmetrics_from_recs(table: "NativeRowTable", interner_h, data: bytes,
                          recs: np.ndarray, dims: np.ndarray,
                          kind_lut: np.ndarray, status_lut: np.ndarray,
                          slack_lo: int, slack_hi: int, now: float,
                          last_seen: "np.ndarray | None", cap: int,
                          out: "ResolveBuffers | None" = None):
    """Distributor scan records → device-ready spanmetrics arrays (see
    native.cpp `spanmetrics_from_recs`): the tee path skips the second
    protobuf walk entirely. Same return shape as `spanmetrics_resolve`;
    None when the library is unavailable OR the payload needs the Python
    service.name fixup (caller falls back to the full staging path)."""
    lib = _load()
    if lib is None:
        return None
    n = len(recs)
    if cap < n:
        raise ValueError("cap must be >= len(recs)")
    recs = np.ascontiguousarray(recs)
    buf = np.frombuffer(data, np.uint8)
    dims = np.ascontiguousarray(dims, np.int32)
    kind_lut = np.ascontiguousarray(kind_lut, np.int32)
    status_lut = np.ascontiguousarray(status_lut, np.int32)
    # dur/sizes are rows 1/2 of ONE packed [3, cap] f32 buffer: the fast
    # paths upload slots+dur+sizes as a single H2D transfer (row 0 takes
    # the f32 slot copy after miss resolution)
    slots, packed, rows, valid, miss, counts = _resolve_arrays(
        cap, int(dims.shape[0]), n, out)
    dur = packed[1]
    sizes = packed[2]
    i32 = ctypes.POINTER(ctypes.c_int32)
    lsp = None
    if last_seen is not None:
        assert last_seen.dtype == np.float64 and last_seen.flags.c_contiguous
        lsp = last_seen.ctypes.data_as(ctypes.POINTER(ctypes.c_double))
    nm = lib.spanmetrics_from_recs(
        table._h, interner_h, buf.ctypes.data_as(
            ctypes.POINTER(ctypes.c_uint8)), len(data),
        recs.ctypes.data, n,
        dims.ctypes.data_as(i32), int(dims.shape[0]),
        kind_lut.ctypes.data_as(i32), status_lut.ctypes.data_as(i32),
        slack_lo, slack_hi, now, lsp,
        slots.ctypes.data_as(i32), dur.ctypes.data, sizes.ctypes.data,
        rows.ctypes.data_as(i32),
        valid.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        miss.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(miss),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if nm < 0:
        return None      # -1 malformed / -2 fixup: full path re-validates
    return (slots, packed, rows, valid, miss[:nm],
            int(counts[0]), int(counts[1]))


def group_keys_recs(recs: np.ndarray, valid: "np.ndarray | None"
                    ) -> "tuple[np.ndarray, np.ndarray] | None":
    """`group_keys` over (trace_id ‖ tid_len) read straight from SpanRec
    rows — no key-matrix materialization. inverse/first index over the
    sequence of VALID rows (the caller's vrows order). None without the
    native library (caller builds keys and uses group_keys)."""
    lib = _load()
    if lib is None:
        return None
    recs = np.ascontiguousarray(recs)
    n = len(recs)
    nv = n if valid is None else int(valid.sum())
    inverse = np.empty(max(nv, 1), np.int32)
    first = np.empty(max(nv, 1), np.int32)
    vp = None
    if valid is not None:
        vbuf = np.ascontiguousarray(valid, np.uint8)
        vp = vbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    i32 = ctypes.POINTER(ctypes.c_int32)
    ng = lib.group_keys_recs(recs.ctypes.data, n, vp,
                             inverse.ctypes.data_as(i32),
                             first.ctypes.data_as(i32))
    return first[:ng], inverse[:nv]


def group_keys_strided(recs: np.ndarray, valid: "np.ndarray | None"
                       ) -> "tuple[np.ndarray, np.ndarray] | None":
    """`group_keys_recs` over ANY structured dtype carrying `trace_id`
    ([16] u8) and `tid_len` (i32) fields — the staged tee groups StageRec
    rows with this, no key-matrix materialization. None without the
    native library (caller builds keys and uses group_keys)."""
    lib = _load()
    if lib is None:
        return None
    recs = np.ascontiguousarray(recs)
    fields = recs.dtype.fields
    tid_off = int(fields["trace_id"][1])
    tidlen_off = int(fields["tid_len"][1])
    n = len(recs)
    nv = n if valid is None else int(valid.sum())
    inverse = np.empty(max(nv, 1), np.int32)
    first = np.empty(max(nv, 1), np.int32)
    vp = None
    if valid is not None:
        vbuf = np.ascontiguousarray(valid, np.uint8)
        vp = vbuf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    i32 = ctypes.POINTER(ctypes.c_int32)
    ng = lib.group_keys_strided(recs.ctypes.data, n,
                                recs.dtype.itemsize, tid_off, tidlen_off,
                                vp, inverse.ctypes.data_as(i32),
                                first.ctypes.data_as(i32))
    return first[:ng], inverse[:nv]
