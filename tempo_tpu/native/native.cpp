// Native host-side hot paths (ctypes shared library).
//
// The reference spends its write-path CPU in Go loops: per-span regrouping
// with fnv token hashing (`requestsByTraceID` modules/distributor/
// distributor.go:694-801, `TokenFor` pkg/util/hash.go:8) and protobuf
// unmarshalling of OTLP pushes. Here the same loops are C++: batched token
// hashing over a trace-id matrix, and a single-pass OTLP
// ExportTraceServiceRequest scanner that emits fixed-width span columns,
// a flattened attribute table, and byte ranges for the variable fields, so
// Python touches each span O(1) times instead of O(fields).
//
// Built by tempo_tpu/native/__init__.py with g++ at first import; every
// entry point has a pure-python/numpy fallback, and the scanner's output
// contract matches the python decoder exactly (id lengths preserved,
// malformed input rejected, field order independent).

#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// --- fnv1 32 token hashing -------------------------------------------------

// out[i] = fnv1_32(tenant || tids[i*16..+16])  (hash.go TokenFor semantics)
void fnv1_tokens(const uint8_t* tenant, int64_t tenant_len,
                 const uint8_t* tids, int64_t n, int64_t width,
                 uint32_t* out) {
    uint32_t seed = 2166136261u;
    for (int64_t j = 0; j < tenant_len; j++) {
        seed = (seed * 16777619u) ^ (uint32_t)tenant[j];
    }
    for (int64_t i = 0; i < n; i++) {
        uint32_t h = seed;
        const uint8_t* row = tids + i * width;
        for (int64_t j = 0; j < width; j++) {
            h = (h * 16777619u) ^ (uint32_t)row[j];
        }
        out[i] = h;
    }
}

// --- protobuf wire scanning ------------------------------------------------

struct Cursor {
    const uint8_t* p;
    const uint8_t* end;
    bool ok;
};

static inline uint64_t read_varint(Cursor& c) {
    uint64_t v = 0;
    int shift = 0;
    while (c.p < c.end && shift < 64) {
        uint8_t b = *c.p++;
        v |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) return v;
        shift += 7;
    }
    c.ok = false;
    return 0;
}

// Skips a field payload; for wiretype 2 returns (start,len) via refs.
static inline bool read_field(Cursor& c, uint32_t& fnum, uint32_t& wt,
                              uint64_t& val, const uint8_t*& start,
                              uint64_t& len) {
    if (c.p >= c.end) return false;
    uint64_t tag = read_varint(c);
    if (!c.ok) return false;
    fnum = (uint32_t)(tag >> 3);
    wt = (uint32_t)(tag & 7);
    start = nullptr; len = 0; val = 0;
    switch (wt) {
        case 0: val = read_varint(c); return c.ok;
        case 1: if (c.end - c.p < 8) { c.ok = false; return false; }
                memcpy(&val, c.p, 8); c.p += 8; return true;
        case 2: len = read_varint(c);
                if (!c.ok || (uint64_t)(c.end - c.p) < len) { c.ok = false; return false; }
                start = c.p; c.p += len; return true;
        case 5: if (c.end - c.p < 4) { c.ok = false; return false; }
                { uint32_t v32; memcpy(&v32, c.p, 4); val = v32; }
                c.p += 4; return true;
        default: c.ok = false; return false;
    }
}

// Per-span output records. Offsets are into the original buffer. Layout is
// padding-free by construction (descending alignment) so numpy mirrors it
// with a packed structured dtype. Id *_len fields preserve the wire length
// (0 = absent; >16/8 = oversized, bytes not copied) so python can apply the
// exact python-decoder contract including invalid-id validation.
struct SpanRec {
    uint8_t  trace_id[16];
    uint8_t  span_id[8];
    uint8_t  parent_span_id[8];
    uint64_t start_ns, end_ns;
    int64_t  name_off;        // variable fields: byte ranges into the buffer
    int64_t  status_msg_off;
    int64_t  res_off;         // resource attr region (shared per batch)
    int64_t  span_off;        // full span message range
    int32_t  name_len, status_msg_len, res_len, span_len;
    int32_t  kind, status_code;
    int32_t  tid_len, sid_len, pid_len;
    int32_t  _pad;
};

// One span attribute (flattened across all spans). typ follows the AnyValue
// kinds: 1=string (sval range) 2=bool 3=int64 (exact, in ival) 4=double,
// 0=other (raw AnyValue bytes at sval range; python decodes).
struct AttrRec {
    int64_t key_off;
    int64_t sval_off;
    int64_t ival;
    double  fval;
    int32_t key_len, sval_len, typ, span_idx;
};

// Extracts one KeyValue message. Returns false on MALFORMED bytes (caller
// aborts the scan, matching the python decoder's ValueError); an absent key
// or value is valid and yields key_off/sval_off = -1.
static inline bool parse_keyvalue(const uint8_t* buf, const uint8_t* kv,
                                  uint64_t kvlen, int32_t span_idx,
                                  AttrRec& a) {
    Cursor c{kv, kv + kvlen, true};
    uint32_t f, w; uint64_t v, l; const uint8_t* s;
    a.key_off = -1; a.sval_off = -1; a.ival = 0; a.fval = 0;
    a.key_len = 0; a.sval_len = 0; a.typ = 0; a.span_idx = span_idx;
    const uint8_t* val_start = nullptr; uint64_t val_len = 0;
    while (read_field(c, f, w, v, s, l)) {
        if (f == 1 && w == 2) { a.key_off = s - buf; a.key_len = (int32_t)l; }
        else if (f == 2 && w == 2) { val_start = s; val_len = l; }
    }
    if (!c.ok) return false;
    if (val_start) {
        Cursor av{val_start, val_start + val_len, true};
        while (read_field(av, f, w, v, s, l)) {
            switch (f) {
                case 1: if (w == 2) { a.typ = 1; a.sval_off = s - buf; a.sval_len = (int32_t)l; } break;
                case 2: a.typ = 2; a.fval = v ? 1.0 : 0.0; break;
                case 3: a.typ = 3; a.ival = (int64_t)v; break;
                case 4: { a.typ = 4; double d; memcpy(&d, &v, 8); a.fval = d; } break;
                default:  // array/kvlist/bytes: raw AnyValue range for python
                    if (a.typ == 0) { a.sval_off = val_start - buf; a.sval_len = (int32_t)val_len; }
                    break;
            }
        }
        if (!av.ok) return false;
    }
    return true;
}

// Scans one Span message into r (+ appends attrs). Returns false on
// malformed input.
static bool scan_span(const uint8_t* buf, const uint8_t* s3, uint64_t l3,
                      const uint8_t* res_off, uint64_t res_len,
                      int64_t span_idx, SpanRec& r,
                      AttrRec* attrs_out, int64_t attr_cap,
                      int64_t& attr_count) {
    memset(&r, 0, sizeof(SpanRec));
    r.span_off = s3 - buf; r.span_len = (int32_t)l3;
    r.res_off = res_off ? res_off - buf : -1;
    r.res_len = (int32_t)res_len;
    Cursor sp{s3, s3 + l3, true};
    uint32_t f4, w4; uint64_t v4, l4; const uint8_t* s4;
    while (read_field(sp, f4, w4, v4, s4, l4)) {
        if ((f4 <= 5 || f4 == 9 || f4 == 15) && w4 != 2) continue;
        switch (f4) {
            case 1: r.tid_len = (int32_t)l4;
                    if (l4 <= 16) memcpy(r.trace_id, s4, l4); break;
            case 2: r.sid_len = (int32_t)l4;
                    if (l4 <= 8) memcpy(r.span_id, s4, l4); break;
            case 4: r.pid_len = (int32_t)l4;
                    if (l4 <= 8) memcpy(r.parent_span_id, s4, l4); break;
            case 5: r.name_off = s4 - buf; r.name_len = (int32_t)l4; break;
            case 6: r.kind = (int32_t)v4; break;
            case 7: r.start_ns = v4; break;
            case 8: r.end_ns = v4; break;
            case 9: {
                AttrRec a;  // always validate, store only if room
                if (!parse_keyvalue(buf, s4, l4, (int32_t)span_idx, a))
                    return false;
                if (attr_count < attr_cap)
                    attrs_out[attr_count] = a;
                attr_count++;
                break;
            }
            case 15: {            // Status{message=2,code=3}
                Cursor st{s4, s4 + l4, true};
                uint32_t f5, w5; uint64_t v5, l5; const uint8_t* s5;
                while (read_field(st, f5, w5, v5, s5, l5)) {
                    if (f5 == 2 && w5 == 2) { r.status_msg_off = s5 - buf; r.status_msg_len = (int32_t)l5; }
                    else if (f5 == 3) r.status_code = (int32_t)v5;
                }
                if (!st.ok) return false;
                break;
            }
            default: break;
        }
    }
    return sp.ok;
}

// Scans an ExportTraceServiceRequest. Fills up to cap SpanRec entries and
// up to attr_cap AttrRec entries. n_attrs_out receives the total attr
// count (may exceed attr_cap). Returns the total span count (may exceed
// cap; caller re-calls with bigger buffers), or -1 on malformed input.
// Field order independent: each ResourceSpans is scanned twice, first for
// the Resource, then for its ScopeSpans.
int64_t otlp_scan2(const uint8_t* buf, int64_t buflen,
                   SpanRec* out, int64_t cap,
                   AttrRec* attrs_out, int64_t attr_cap,
                   int64_t* n_attrs_out) {
    Cursor top{buf, buf + buflen, true};
    int64_t count = 0, attr_count = 0;
    uint32_t fnum, wt; uint64_t val, len; const uint8_t* start;
    while (read_field(top, fnum, wt, val, start, len)) {
        if (fnum != 1 || wt != 2) continue;          // ResourceSpans
        // pass 1: locate the Resource (it may come after the spans)
        const uint8_t* res_off = nullptr; uint64_t res_len = 0;
        uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
        Cursor rs1{start, start + len, true};
        while (read_field(rs1, f2, w2, v2, s2, l2)) {
            if (f2 == 1 && w2 == 2) { res_off = s2; res_len = l2; }
        }
        if (!rs1.ok) return -1;
        // pass 2: spans
        Cursor rs{start, start + len, true};
        while (read_field(rs, f2, w2, v2, s2, l2)) {
            if (f2 != 2 || w2 != 2) continue;         // ScopeSpans
            Cursor ss{s2, s2 + l2, true};
            uint32_t f3, w3; uint64_t v3, l3; const uint8_t* s3;
            while (read_field(ss, f3, w3, v3, s3, l3)) {
                if (f3 != 2 || w3 != 2) continue;     // Span
                if (count < cap) {
                    if (!scan_span(buf, s3, l3, res_off, res_len, count,
                                   out[count], attrs_out, attr_cap,
                                   attr_count))
                        return -1;
                }
                count++;
            }
            if (!ss.ok) return -1;
        }
        if (!rs.ok) return -1;
    }
    if (!top.ok) return -1;
    *n_attrs_out = attr_count;
    return count;
}

// Back-compat single-output scan (no attribute extraction).
int64_t otlp_scan(const uint8_t* buf, int64_t buflen,
                  SpanRec* out, int64_t cap) {
    int64_t n_attrs = 0;
    return otlp_scan2(buf, buflen, out, cap, nullptr, 0, &n_attrs);
}

}  // extern "C"
