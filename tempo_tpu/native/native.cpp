// Native host-side hot paths (ctypes shared library).
//
// The reference spends its write-path CPU in Go loops: per-span regrouping
// with fnv token hashing (`requestsByTraceID` modules/distributor/
// distributor.go:694-801, `TokenFor` pkg/util/hash.go:8) and protobuf
// unmarshalling of OTLP pushes. Here the same loops are C++: batched token
// hashing over a trace-id matrix, and a single-pass OTLP
// ExportTraceServiceRequest scanner that emits fixed-width span columns,
// a flattened attribute table, and byte ranges for the variable fields, so
// Python touches each span O(1) times instead of O(fields).
//
// Built by tempo_tpu/native/__init__.py with g++ at first import; every
// entry point has a pure-python/numpy fallback, and the scanner's output
// contract matches the python decoder exactly (id lengths preserved,
// malformed input rejected, field order independent).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <cstddef>
#include <cstdlib>
#include <mutex>
#include <thread>
#include <vector>

extern "C" {

// --- crc32c (Castagnoli; kafka record batches) ------------------------------

static uint32_t kCrcTab[256];
static bool kCrcInit = [] {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
        kCrcTab[i] = c;
    }
    return true;
}();

uint32_t crc32c(const uint8_t* data, int64_t n) {
    uint32_t crc = 0xFFFFFFFFu;
    for (int64_t i = 0; i < n; i++)
        crc = kCrcTab[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
    return crc ^ 0xFFFFFFFFu;
}

// --- fnv1 32 token hashing -------------------------------------------------

// out[i] = fnv1_32(tenant || tids[i*16..+16])  (hash.go TokenFor semantics)
void fnv1_tokens(const uint8_t* tenant, int64_t tenant_len,
                 const uint8_t* tids, int64_t n, int64_t width,
                 uint32_t* out) {
    uint32_t seed = 2166136261u;
    for (int64_t j = 0; j < tenant_len; j++) {
        seed = (seed * 16777619u) ^ (uint32_t)tenant[j];
    }
    for (int64_t i = 0; i < n; i++) {
        uint32_t h = seed;
        const uint8_t* row = tids + i * width;
        for (int64_t j = 0; j < width; j++) {
            h = (h * 16777619u) ^ (uint32_t)row[j];
        }
        out[i] = h;
    }
}

// --- protobuf wire scanning ------------------------------------------------

struct Cursor {
    const uint8_t* p;
    const uint8_t* end;
    bool ok;
};

static inline uint64_t read_varint(Cursor& c) {
    uint64_t v = 0;
    int shift = 0;
    while (c.p < c.end && shift < 64) {
        uint8_t b = *c.p++;
        v |= (uint64_t)(b & 0x7f) << shift;
        if (!(b & 0x80)) return v;
        shift += 7;
    }
    c.ok = false;
    return 0;
}

// Skips a field payload; for wiretype 2 returns (start,len) via refs.
static inline bool read_field(Cursor& c, uint32_t& fnum, uint32_t& wt,
                              uint64_t& val, const uint8_t*& start,
                              uint64_t& len) {
    if (c.p >= c.end) return false;
    uint64_t tag = read_varint(c);
    if (!c.ok) return false;
    fnum = (uint32_t)(tag >> 3);
    wt = (uint32_t)(tag & 7);
    start = nullptr; len = 0; val = 0;
    switch (wt) {
        case 0: val = read_varint(c); return c.ok;
        case 1: if (c.end - c.p < 8) { c.ok = false; return false; }
                memcpy(&val, c.p, 8); c.p += 8; return true;
        case 2: len = read_varint(c);
                if (!c.ok || (uint64_t)(c.end - c.p) < len) { c.ok = false; return false; }
                start = c.p; c.p += len; return true;
        case 5: if (c.end - c.p < 4) { c.ok = false; return false; }
                { uint32_t v32; memcpy(&v32, c.p, 4); val = v32; }
                c.p += 4; return true;
        default: c.ok = false; return false;
    }
}

// Per-span output records. Offsets are into the original buffer. Layout is
// padding-free by construction (descending alignment) so numpy mirrors it
// with a packed structured dtype. Id *_len fields preserve the wire length
// (0 = absent; >16/8 = oversized, bytes not copied) so python can apply the
// exact python-decoder contract including invalid-id validation.
struct SpanRec {
    uint8_t  trace_id[16];
    uint8_t  span_id[8];
    uint8_t  parent_span_id[8];
    uint64_t start_ns, end_ns;
    int64_t  name_off;        // variable fields: byte ranges into the buffer
    int64_t  status_msg_off;
    int64_t  res_off;         // resource attr region (shared per batch)
    int64_t  span_off;        // full span message range
    int32_t  name_len, status_msg_len, res_len, span_len;
    int32_t  kind, status_code;
    int32_t  tid_len, sid_len, pid_len;
    int32_t  _pad;
};

// One span attribute (flattened across all spans). typ follows the AnyValue
// kinds: 1=string (sval range) 2=bool 3=int64 (exact, in ival) 4=double,
// 0=other (raw AnyValue bytes at sval range; python decodes).
struct AttrRec {
    int64_t key_off;
    int64_t sval_off;
    int64_t ival;
    double  fval;
    int32_t key_len, sval_len, typ, span_idx;
};

// Extracts one KeyValue message. Returns false on MALFORMED bytes (caller
// aborts the scan, matching the python decoder's ValueError); an absent key
// or value is valid and yields key_off/sval_off = -1.
static inline bool parse_keyvalue(const uint8_t* buf, const uint8_t* kv,
                                  uint64_t kvlen, int32_t span_idx,
                                  AttrRec& a) {
    Cursor c{kv, kv + kvlen, true};
    uint32_t f, w; uint64_t v, l; const uint8_t* s;
    a.key_off = -1; a.sval_off = -1; a.ival = 0; a.fval = 0;
    a.key_len = 0; a.sval_len = 0; a.typ = 0; a.span_idx = span_idx;
    const uint8_t* val_start = nullptr; uint64_t val_len = 0;
    while (read_field(c, f, w, v, s, l)) {
        if (f == 1 && w == 2) { a.key_off = s - buf; a.key_len = (int32_t)l; }
        else if (f == 2 && w == 2) { val_start = s; val_len = l; }
    }
    if (!c.ok) return false;
    if (val_start) {
        Cursor av{val_start, val_start + val_len, true};
        while (read_field(av, f, w, v, s, l)) {
            switch (f) {
                case 1: if (w == 2) { a.typ = 1; a.sval_off = s - buf; a.sval_len = (int32_t)l; } break;
                case 2: a.typ = 2; a.fval = v ? 1.0 : 0.0; break;
                case 3: a.typ = 3; a.ival = (int64_t)v; break;
                case 4: { a.typ = 4; double d; memcpy(&d, &v, 8); a.fval = d; } break;
                default:  // array/kvlist/bytes: raw AnyValue range for python
                    if (a.typ == 0) { a.sval_off = val_start - buf; a.sval_len = (int32_t)val_len; }
                    break;
            }
        }
        if (!av.ok) return false;
    }
    return true;
}

// Scans one Span message into r (+ appends attrs). Returns false on
// malformed input.
static bool scan_span(const uint8_t* buf, const uint8_t* s3, uint64_t l3,
                      const uint8_t* res_off, uint64_t res_len,
                      int64_t span_idx, SpanRec& r,
                      AttrRec* attrs_out, int64_t attr_cap,
                      int64_t& attr_count) {
    memset(&r, 0, sizeof(SpanRec));
    r.span_off = s3 - buf; r.span_len = (int32_t)l3;
    r.res_off = res_off ? res_off - buf : -1;
    r.res_len = (int32_t)res_len;
    Cursor sp{s3, s3 + l3, true};
    uint32_t f4, w4; uint64_t v4, l4; const uint8_t* s4;
    while (read_field(sp, f4, w4, v4, s4, l4)) {
        if ((f4 <= 5 || f4 == 9 || f4 == 15) && w4 != 2) continue;
        switch (f4) {
            case 1: r.tid_len = (int32_t)l4;
                    if (l4 <= 16) memcpy(r.trace_id, s4, l4); break;
            case 2: r.sid_len = (int32_t)l4;
                    if (l4 <= 8) memcpy(r.span_id, s4, l4); break;
            case 4: r.pid_len = (int32_t)l4;
                    if (l4 <= 8) memcpy(r.parent_span_id, s4, l4); break;
            case 5: r.name_off = s4 - buf; r.name_len = (int32_t)l4; break;
            case 6: r.kind = (int32_t)v4; break;
            case 7: r.start_ns = v4; break;
            case 8: r.end_ns = v4; break;
            case 9: {
                AttrRec a;  // always validate, store only if room
                if (!parse_keyvalue(buf, s4, l4, (int32_t)span_idx, a))
                    return false;
                if (attr_count < attr_cap)
                    attrs_out[attr_count] = a;
                attr_count++;
                break;
            }
            case 15: {            // Status{message=2,code=3}
                Cursor st{s4, s4 + l4, true};
                uint32_t f5, w5; uint64_t v5, l5; const uint8_t* s5;
                while (read_field(st, f5, w5, v5, s5, l5)) {
                    if (f5 == 2 && w5 == 2) { r.status_msg_off = s5 - buf; r.status_msg_len = (int32_t)l5; }
                    else if (f5 == 3) r.status_code = (int32_t)v5;
                }
                if (!st.ok) return false;
                break;
            }
            default: break;
        }
    }
    return sp.ok;
}

// Scans an ExportTraceServiceRequest. Fills up to cap SpanRec entries and
// up to attr_cap AttrRec entries. n_attrs_out receives the total attr
// count (may exceed attr_cap). Returns the total span count (may exceed
// cap; caller re-calls with bigger buffers), or -1 on malformed input.
// Field order independent: each ResourceSpans is scanned twice, first for
// the Resource, then for its ScopeSpans.
int64_t otlp_scan2(const uint8_t* buf, int64_t buflen,
                   SpanRec* out, int64_t cap,
                   AttrRec* attrs_out, int64_t attr_cap,
                   int64_t* n_attrs_out) {
    Cursor top{buf, buf + buflen, true};
    int64_t count = 0, attr_count = 0;
    uint32_t fnum, wt; uint64_t val, len; const uint8_t* start;
    while (read_field(top, fnum, wt, val, start, len)) {
        if (fnum != 1 || wt != 2) continue;          // ResourceSpans
        // pass 1: locate the Resource (it may come after the spans)
        const uint8_t* res_off = nullptr; uint64_t res_len = 0;
        uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
        Cursor rs1{start, start + len, true};
        while (read_field(rs1, f2, w2, v2, s2, l2)) {
            if (f2 == 1 && w2 == 2) { res_off = s2; res_len = l2; }
        }
        if (!rs1.ok) return -1;
        // pass 2: spans
        Cursor rs{start, start + len, true};
        while (read_field(rs, f2, w2, v2, s2, l2)) {
            if (f2 != 2 || w2 != 2) continue;         // ScopeSpans
            Cursor ss{s2, s2 + l2, true};
            uint32_t f3, w3; uint64_t v3, l3; const uint8_t* s3;
            while (read_field(ss, f3, w3, v3, s3, l3)) {
                if (f3 != 2 || w3 != 2) continue;     // Span
                if (count < cap) {
                    if (!scan_span(buf, s3, l3, res_off, res_len, count,
                                   out[count], attrs_out, attr_cap,
                                   attr_count))
                        return -1;
                }
                count++;
            }
            if (!ss.ok) return -1;
        }
        if (!rs.ok) return -1;
    }
    if (!top.ok) return -1;
    *n_attrs_out = attr_count;
    return count;
}

// Back-compat single-output scan (no attribute extraction).
int64_t otlp_scan(const uint8_t* buf, int64_t buflen,
                  SpanRec* out, int64_t cap) {
    int64_t n_attrs = 0;
    return otlp_scan2(buf, buflen, out, cap, nullptr, 0, &n_attrs);
}

}  // extern "C"

// --- parallel scan -----------------------------------------------------------
//
// The distributor's scan is the serial floor of the tee path (SURVEY §3.1
// hot loop ①). ResourceSpans are independent, so: one cheap sequential
// pass walks ONLY message headers to count spans per ResourceSpans (span
// bodies are skipped by length), then a prefix sum fixes each range's
// output base and worker threads deep-scan their ranges into disjoint
// slices. Output order is identical to the sequential scan.

namespace {

struct RsRange {
    const uint8_t* start; uint64_t len;
    const uint8_t* res_off; uint64_t res_len;
    int64_t out_base; int64_t span_count;
};

// Count spans in one ResourceSpans by walking headers only.
static int64_t count_spans_rs(const uint8_t* start, uint64_t len) {
    Cursor rs{start, start + len, true};
    uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
    int64_t n = 0;
    while (read_field(rs, f2, w2, v2, s2, l2)) {
        if (f2 != 2 || w2 != 2) continue;          // ScopeSpans
        Cursor ss{s2, s2 + l2, true};
        uint32_t f3, w3; uint64_t v3, l3; const uint8_t* s3;
        while (read_field(ss, f3, w3, v3, s3, l3)) {
            if (f3 == 2 && w3 == 2) n++;
        }
        if (!ss.ok) return -1;
    }
    return rs.ok ? n : -1;
}

// Deep-scan one ResourceSpans into out[r.out_base...].
static bool scan_rs_range(const uint8_t* buf, const RsRange& r,
                          SpanRec* out) {
    Cursor rs{r.start, r.start + r.len, true};
    uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
    int64_t k = r.out_base;
    int64_t attr_count = 0;
    while (read_field(rs, f2, w2, v2, s2, l2)) {
        if (f2 != 2 || w2 != 2) continue;
        Cursor ss{s2, s2 + l2, true};
        uint32_t f3, w3; uint64_t v3, l3; const uint8_t* s3;
        while (read_field(ss, f3, w3, v3, s3, l3)) {
            if (f3 != 2 || w3 != 2) continue;
            if (!scan_span(buf, s3, l3, r.res_off, r.res_len, k, out[k],
                           nullptr, 0, attr_count))
                return false;
            k++;
        }
        if (!ss.ok) return false;
    }
    return rs.ok;
}

}  // namespace

extern "C" {

// Parallel variant of otlp_scan (no attribute extraction). Returns the
// total span count (caller re-calls with a bigger buffer when > cap) or
// -1 on malformed input. Falls back to single-threaded scanning when the
// payload has too few ResourceSpans to split.
int64_t otlp_scan_mt(const uint8_t* buf, int64_t buflen,
                     SpanRec* out, int64_t cap, int32_t n_threads) {
    std::vector<RsRange> ranges;
    Cursor top{buf, buf + buflen, true};
    uint32_t fnum, wt; uint64_t val, len; const uint8_t* start;
    int64_t total = 0;
    while (read_field(top, fnum, wt, val, start, len)) {
        if (fnum != 1 || wt != 2) continue;
        RsRange r{start, len, nullptr, 0, 0, 0};
        Cursor rs1{start, start + len, true};
        uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
        while (read_field(rs1, f2, w2, v2, s2, l2)) {
            if (f2 == 1 && w2 == 2) { r.res_off = s2; r.res_len = l2; }
        }
        if (!rs1.ok) return -1;
        r.span_count = count_spans_rs(start, len);
        if (r.span_count < 0) return -1;
        r.out_base = total;
        total += r.span_count;
        ranges.push_back(r);
    }
    if (!top.ok) return -1;
    if (total > cap) return total;                 // caller regrows
    if (n_threads < 2 || ranges.size() < 2 || total < 4096) {
        for (const RsRange& r : ranges)
            if (!scan_rs_range(buf, r, out)) return -1;
        return total;
    }
    int nt = (int)std::min<size_t>(n_threads, ranges.size());
    std::atomic<bool> bad{false};
    std::vector<std::thread> threads;
    threads.reserve(nt);
    for (int t = 0; t < nt; t++) {
        threads.emplace_back([&, t]() {
            for (size_t i = t; i < ranges.size(); i += nt) {
                if (bad.load(std::memory_order_relaxed)) return;
                if (!scan_rs_range(buf, ranges[i], out))
                    bad.store(true, std::memory_order_relaxed);
            }
        });
    }
    for (auto& th : threads) th.join();
    return bad.load() ? -1 : total;
}

// --- span events / links ----------------------------------------------------
// Separate pass extracting Span.events (field 11) and Span.links (field 13)
// keyed by span index (same traversal order as otlp_scan2), so the common
// eventless payload pays nothing on the main scan.

struct EvRec {
    int64_t name_off;
    uint64_t time_ns;
    int32_t name_len;
    int32_t span_idx;
};

struct LinkRec {
    uint8_t trace_id[16];
    uint8_t span_id[8];
    int32_t span_idx;
    int32_t tid_len, sid_len, _pad;
};

// Returns 0 ok / -1 malformed. Counts written to n_out[0]=events,
// n_out[1]=links (may exceed caps; caller re-calls with bigger buffers).
int32_t otlp_events(const uint8_t* buf, int64_t buflen,
                    EvRec* evs, int64_t ecap,
                    LinkRec* links, int64_t lcap, int64_t* n_out) {
    Cursor top{buf, buf + buflen, true};
    uint32_t f, w; uint64_t v, len; const uint8_t* start;
    int64_t span_idx = -1, ne = 0, nl = 0;
    while (read_field(top, f, w, v, start, len)) {
        if (f != 1 || w != 2) continue;            // ResourceSpans
        Cursor rs{start, start + len, true};
        uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
        while (read_field(rs, f2, w2, v2, s2, l2)) {
            if (f2 != 2 || w2 != 2) continue;      // ScopeSpans
            Cursor ss{s2, s2 + l2, true};
            uint32_t f3, w3; uint64_t v3, l3; const uint8_t* s3;
            while (read_field(ss, f3, w3, v3, s3, l3)) {
                if (f3 != 2 || w3 != 2) continue;  // Span
                span_idx++;
                Cursor sp{s3, s3 + l3, true};
                uint32_t f4, w4; uint64_t v4, l4; const uint8_t* s4;
                while (read_field(sp, f4, w4, v4, s4, l4)) {
                    if (f4 == 11 && w4 == 2) {     // Event
                        EvRec e{-1, 0, 0, (int32_t)span_idx};
                        Cursor ev{s4, s4 + l4, true};
                        uint32_t f5, w5; uint64_t v5, l5; const uint8_t* s5;
                        while (read_field(ev, f5, w5, v5, s5, l5)) {
                            if (f5 == 1 && w5 != 2) e.time_ns = v5;
                            else if (f5 == 2 && w5 == 2) {
                                e.name_off = s5 - buf;
                                e.name_len = (int32_t)l5;
                            }
                        }
                        if (!ev.ok) return -1;
                        if (ne < ecap) evs[ne] = e;
                        ne++;
                    } else if (f4 == 13 && w4 == 2) {   // Link
                        LinkRec lk;
                        memset(&lk, 0, sizeof(lk));
                        lk.span_idx = (int32_t)span_idx;
                        Cursor ln{s4, s4 + l4, true};
                        uint32_t f5, w5; uint64_t v5, l5; const uint8_t* s5;
                        while (read_field(ln, f5, w5, v5, s5, l5)) {
                            if (f5 == 1 && w5 == 2) {
                                lk.tid_len = (int32_t)l5;
                                if (l5 <= 16) memcpy(lk.trace_id, s5, l5);
                            } else if (f5 == 2 && w5 == 2) {
                                lk.sid_len = (int32_t)l5;
                                if (l5 <= 8) memcpy(lk.span_id, s5, l5);
                            }
                        }
                        if (!ln.ok) return -1;
                        if (nl < lcap) links[nl] = lk;
                        nl++;
                    }
                }
                if (!sp.ok) return -1;
            }
            if (!ss.ok) return -1;
        }
        if (!rs.ok) return -1;
    }
    if (!top.ok) return -1;
    n_out[0] = ne; n_out[1] = nl;
    return 0;
}

}  // extern "C"

// --- persistent string interner --------------------------------------------
//
// The host-side dictionary behind tempo_tpu.model.interner.StringInterner:
// bytes -> dense int32 id, append-only, with a string arena so Python can
// lazily mirror id -> string. Replaces the per-unique-string Python loops
// of the staging path (VERDICT r2: `_intern_ranges`' per-length passes and
// the registry's per-row dict work dominated e2e ingest). Analog of the
// reference's LabelValueCombo hashing (`registry/hash.go`), but shared by
// every string column.

namespace {

static inline uint64_t fnv1a64(const uint8_t* p, int64_t n) {
    uint64_t h = 0xCBF29CE484222325ull;
    for (int64_t i = 0; i < n; i++) h = (h ^ p[i]) * 0x100000001B3ull;
    return h;
}

struct StrEntry {
    int64_t off;
    int32_t len;
    uint64_t hash;
};

struct Interner {
    std::mutex mu;
    std::vector<uint8_t> arena;
    std::vector<StrEntry> entries;          // id -> entry
    std::vector<int32_t> table;             // open addressing, -1 empty
    uint64_t mask = 0;

    Interner() {
        table.assign(1 << 12, -1);
        mask = table.size() - 1;
    }

    void grow() {
        std::vector<int32_t> nt(table.size() * 2, -1);
        uint64_t nmask = nt.size() - 1;
        for (int32_t id = 0; id < (int32_t)entries.size(); id++) {
            uint64_t i = entries[id].hash & nmask;
            while (nt[i] != -1) i = (i + 1) & nmask;
            nt[i] = id;
        }
        table.swap(nt);
        mask = nmask;
    }

    // lookup-or-insert; lock held by caller
    int32_t intern_locked(const uint8_t* s, int64_t len) {
        uint64_t h = fnv1a64(s, len);
        uint64_t i = h & mask;
        while (true) {
            int32_t id = table[i];
            if (id == -1) break;
            const StrEntry& e = entries[id];
            if (e.hash == h && e.len == len &&
                memcmp(arena.data() + e.off, s, len) == 0)
                return id;
            i = (i + 1) & mask;
        }
        int32_t id = (int32_t)entries.size();
        StrEntry e{(int64_t)arena.size(), (int32_t)len, h};
        arena.insert(arena.end(), s, s + len);
        entries.push_back(e);
        table[i] = id;
        if (entries.size() * 10 > table.size() * 7) grow();
        return id;
    }

    // lookup only; -1 when absent. lock held by caller.
    int32_t find_locked(const uint8_t* s, int64_t len) const {
        uint64_t h = fnv1a64(s, len);
        uint64_t i = h & mask;
        while (true) {
            int32_t id = table[i];
            if (id == -1) return -1;
            const StrEntry& e = entries[id];
            if (e.hash == h && e.len == len &&
                memcmp(arena.data() + e.off, s, len) == 0)
                return id;
            i = (i + 1) & mask;
        }
    }
};

// --- fixed-width key grouping ----------------------------------------------
//
// Group n fixed-width byte keys (e.g. the distributor's padded trace id +
// length byte, `requestsByTraceID` distributor.go:694) into first-occurrence
// order: inverse[i] = dense group id, first_idx[g] = row of g's first
// occurrence. One O(n) hash pass replaces numpy's void-view unique (an
// O(n log n) memcmp argsort that dominated the tee-path profile).

}  // namespace

extern "C" {

int64_t group_keys(const uint8_t* keys, int64_t n, int32_t key_len,
                   int32_t* inverse, int32_t* first_idx) {
    if (n <= 0) return 0;
    uint64_t cap = 64;
    while (cap < (uint64_t)n * 2) cap <<= 1;
    std::vector<int32_t> table(cap, -1);   // slot -> group id
    uint64_t mask = cap - 1;
    int64_t n_groups = 0;
    for (int64_t r = 0; r < n; r++) {
        const uint8_t* k = keys + r * key_len;
        uint64_t h = fnv1a64(k, key_len);
        uint64_t i = h & mask;
        while (true) {
            int32_t g = table[i];
            if (g == -1) {
                table[i] = (int32_t)n_groups;
                first_idx[n_groups] = (int32_t)r;
                inverse[r] = (int32_t)n_groups;
                n_groups++;
                break;
            }
            if (memcmp(keys + (int64_t)first_idx[g] * key_len, k,
                       key_len) == 0) {
                inverse[r] = g;
                break;
            }
            i = (i + 1) & mask;
        }
    }
    return n_groups;
}

void* interner_new() { return new Interner(); }
void interner_free(void* h) { delete (Interner*)h; }

int32_t interner_intern(void* h, const uint8_t* s, int64_t len) {
    Interner* it = (Interner*)h;
    std::lock_guard<std::mutex> g(it->mu);
    return it->intern_locked(s, len);
}

int32_t interner_find(void* h, const uint8_t* s, int64_t len) {
    Interner* it = (Interner*)h;
    std::lock_guard<std::mutex> g(it->mu);
    return it->find_locked(s, len);
}

int64_t interner_count(void* h) {
    Interner* it = (Interner*)h;
    std::lock_guard<std::mutex> g(it->mu);
    return (int64_t)it->entries.size();
}

// Copy strings [first, first+n) as concatenated bytes + lengths so Python
// can mirror the id->string table incrementally. Returns total bytes
// copied, or -needed when out_cap is too small (caller re-calls).
int64_t interner_dump(void* h, int32_t first, int32_t n,
                      uint8_t* out, int64_t out_cap, int32_t* lens) {
    Interner* it = (Interner*)h;
    std::lock_guard<std::mutex> g(it->mu);
    if (first < 0 || first + n > (int64_t)it->entries.size()) return -1;
    int64_t need = 0;
    for (int32_t i = 0; i < n; i++) need += it->entries[first + i].len;
    if (need > out_cap) return -need;
    int64_t o = 0;
    for (int32_t i = 0; i < n; i++) {
        const StrEntry& e = it->entries[first + i];
        memcpy(out + o, it->arena.data() + e.off, e.len);
        lens[i] = e.len;
        o += e.len;
    }
    return o;
}

}  // extern "C"

// --- persistent label-row table ---------------------------------------------
//
// [n_labels] int32 rows -> slot id; the series-resolution hot path
// (`registry/series.py lookup_or_create`). Python keeps slot lifecycle
// (free list, budget, staleness); this table only answers "which slot is
// this row" at C speed. Unseen rows are assigned a PENDING marker so each
// distinct new row is reported once; Python either inserts a real slot or
// removes the pending entry (budget rejection).

namespace {

constexpr int32_t kPending = -2;

struct RowTable {
    std::mutex mu;
    int32_t n_labels;
    std::vector<int32_t> rows;       // entry i -> rows[i*n_labels ..]
    std::vector<int32_t> slots;      // entry i -> slot id, kPending, or -3
    std::vector<int32_t> table;      // open addressing over entries
    std::vector<uint64_t> hashes;
    std::vector<int32_t> free_entries;  // tombstoned entry ids for reuse
    uint64_t mask;
    int64_t live = 0;
    int64_t cells = 0;   // occupied index cells (live + stale duplicates)

    explicit RowTable(int32_t nl) : n_labels(nl) {
        table.assign(1 << 10, -1);
        mask = table.size() - 1;
    }

    // Rebuild the index from live entries (dropping stale cells left by
    // tombstone reuse); doubles only when genuinely dense.
    void grow() {
        size_t nsize = table.size();
        if (live * 10 > (int64_t)nsize * 5) nsize *= 2;
        std::vector<int32_t> nt(nsize, -1);
        uint64_t nmask = nt.size() - 1;
        for (int32_t e = 0; e < (int32_t)hashes.size(); e++) {
            if (slots[e] == -3) continue;          // tombstone
            uint64_t i = hashes[e] & nmask;
            while (nt[i] != -1) i = (i + 1) & nmask;
            nt[i] = e;
        }
        table.swap(nt);
        mask = nmask;
        cells = live;
    }

    inline uint64_t rhash(const int32_t* row) const {
        return fnv1a64((const uint8_t*)row, n_labels * 4);
    }

    // find entry index or -1; lock held
    int32_t find_entry(const int32_t* row, uint64_t h) const {
        uint64_t i = h & mask;
        while (true) {
            int32_t e = table[i];
            if (e == -1) return -1;
            if (hashes[e] == h && slots[e] != -3 &&
                memcmp(rows.data() + (int64_t)e * n_labels, row,
                       n_labels * 4) == 0)
                return e;
            i = (i + 1) & mask;
        }
    }

    int32_t add_entry(const int32_t* row, uint64_t h, int32_t slot) {
        int32_t e;
        if (!free_entries.empty()) {
            e = free_entries.back();
            free_entries.pop_back();
            memcpy(rows.data() + (int64_t)e * n_labels, row, n_labels * 4);
            hashes[e] = h;
            slots[e] = slot;
        } else {
            e = (int32_t)hashes.size();
            rows.insert(rows.end(), row, row + n_labels);
            hashes.push_back(h);
            slots.push_back(slot);
        }
        uint64_t i = h & mask;
        while (table[i] != -1) i = (i + 1) & mask;
        table[i] = e;
        live++;
        cells++;
        if (cells * 10 > (int64_t)table.size() * 7) grow();
        return e;
    }
};

}  // namespace

extern "C" {

void* rowtable_new(int32_t n_labels) { return new RowTable(n_labels); }
void rowtable_free(void* h) { delete (RowTable*)h; }

// Resolve n rows to slots. valid may be null (all valid). Rows not in the
// table get PENDING entries (deduped within the call) and out_slots=-1;
// the first-occurrence index of each new distinct row is appended to
// miss_idx. Returns the miss count. CONTRACT: pass miss_cap >= n (misses
// can never exceed n), and resolve every reported miss (rowtable_insert
// or rowtable_remove) before the next lookup — leftover pending entries
// would resolve to -1 forever without being re-reported.
int64_t rowtable_lookup(void* h, const int32_t* rows_in, int64_t n,
                        const uint8_t* valid, int32_t* out_slots,
                        int64_t* miss_idx, int64_t miss_cap) {
    RowTable* t = (RowTable*)h;
    std::lock_guard<std::mutex> g(t->mu);
    int64_t miss = 0;
    for (int64_t i = 0; i < n; i++) {
        if (valid && !valid[i]) { out_slots[i] = -1; continue; }
        const int32_t* row = rows_in + i * t->n_labels;
        uint64_t hh = t->rhash(row);
        int32_t e = t->find_entry(row, hh);
        if (e == -1) {
            t->add_entry(row, hh, kPending);
            if (miss < miss_cap) miss_idx[miss] = i;
            miss++;
            out_slots[i] = -1;
        } else if (t->slots[e] == kPending) {
            // duplicate of a pending row within this batch: already
            // reported; stays -1 until Python assigns the slot
            out_slots[i] = -1;
        } else {
            out_slots[i] = t->slots[e];
        }
    }
    return miss;
}

// Assign a real slot to a row (overwrites pending or inserts fresh).
void rowtable_insert(void* h, const int32_t* row, int32_t slot) {
    RowTable* t = (RowTable*)h;
    std::lock_guard<std::mutex> g(t->mu);
    uint64_t hh = t->rhash(row);
    int32_t e = t->find_entry(row, hh);
    if (e == -1) t->add_entry(row, hh, slot);
    else t->slots[e] = slot;
}

// Remove a row (budget-rejected pending entry, or stale-purged series).
// Tombstones the entry for reuse; its index cell stays until grow()
// (stale cells only add probe steps — lookups check entry liveness).
void rowtable_remove(void* h, const int32_t* row) {
    RowTable* t = (RowTable*)h;
    std::lock_guard<std::mutex> g(t->mu);
    uint64_t hh = t->rhash(row);
    int32_t e = t->find_entry(row, hh);
    if (e != -1) {
        t->slots[e] = -3;
        t->free_entries.push_back(e);
        t->live--;
    }
}

int64_t rowtable_size(void* h) {
    RowTable* t = (RowTable*)h;
    std::lock_guard<std::mutex> g(t->mu);
    return t->live;
}

}  // extern "C"

// --- one-pass OTLP -> interned columns (otlp_stage) --------------------------
//
// The full staging kernel: OTLP ExportTraceServiceRequest bytes in, dense
// intern-id columns out. Combines the wire scan with dictionary encoding so
// Python never touches per-span or per-unique-string data on the generator
// ingest path (`modules/generator/generator.go:275` PushSpans analog; the
// distributor regroup stays on otlp_scan2). Non-scalar AnyValues (arrays,
// kvlists, bytes) keep their byte ranges for a rare Python fixup pass.

// Per-span staged record: fixed columns + intern ids. Padding-free
// (descending alignment); mirrored by STAGE_REC_DTYPE in __init__.py.
struct StageRec {
    uint8_t  trace_id[16];
    uint8_t  span_id[8];
    uint8_t  parent_span_id[8];
    uint64_t start_ns, end_ns;
    int32_t  name_id, status_msg_id;   // status_msg_id = -1 when absent
    int32_t  service_id, res_idx;      // resource of this span
    int32_t  kind, status_code;
    int32_t  span_len;                 // wire size (size_total accounting)
    int32_t  tid_len, sid_len, pid_len;
};

// One staged attribute (span- or resource-scope). typ follows the ATTR_*
// enums of model/span_batch.py: 1=string 2=bool 3=int 4=double; 0=other
// (sval_off/len point at the raw AnyValue; Python stringifies + interns).
struct StageAttr {
    int64_t sval_off;
    int64_t ival;
    double  fval;
    int32_t sval_len;
    int32_t key_id;
    int32_t sval_id;                   // -1 unless typ==1
    int32_t typ;
    int32_t owner;                     // span idx or resource idx
    int32_t _pad;
};

// One distinct Resource (per ResourceSpans entry, position-deduped like the
// Python path): service.name id + its attr range in the res-attr output.
struct StageRes {
    int32_t service_id;                // id of "" when absent
    int32_t attr_start, attr_count;    // range into res attrs (pre-cap)
    int32_t _pad;
};

namespace {

// Thread-local intern memo: payloads repeat a handful of strings (span
// names, service names, status messages) thousands of times; each worker
// resolves repeats from its private table and takes the global interner
// mutex only on a local miss (~|unique strings| times per thread), so the
// parallel stage is not serialized on the interner lock.
struct LocalIntern {
    struct E { uint64_t h; int64_t off; int32_t len; int32_t id; };
    std::vector<E> tab;
    uint64_t mask;
    Interner* it;
    const uint8_t* base;

    LocalIntern(Interner* i, const uint8_t* b) : it(i), base(b) {
        tab.assign(1 << 10, E{0, 0, 0, -1});
        mask = tab.size() - 1;
    }

    int32_t get(const uint8_t* s, int64_t len) {
        uint64_t h = fnv1a64(s, len);
        uint64_t i = h & mask;
        int probes = 0;
        while (probes++ < 32) {
            E& e = tab[i];
            if (e.id == -1) {
                int32_t id;
                {
                    std::lock_guard<std::mutex> g(it->mu);
                    id = it->intern_locked(s, len);
                }
                e = E{h, s - base, (int32_t)len, id};
                return id;
            }
            if (e.h == h && e.len == len &&
                memcmp(base + e.off, s, len) == 0)
                return e.id;
            i = (i + 1) & mask;
        }
        // pathological collision chain: fall back to the global table
        std::lock_guard<std::mutex> g(it->mu);
        return it->intern_locked(s, len);
    }
};

struct StageCtx {
    Interner* it;
    const uint8_t* buf;
    StageRec* spans; int64_t span_cap; int64_t n_spans = 0;
    StageAttr* sattrs; int64_t sattr_cap; int64_t n_sattrs = 0;
    StageAttr* rattrs; int64_t rattr_cap; int64_t n_rattrs = 0;
    StageRes* res; int64_t res_cap; int64_t n_res = 0;
    int32_t empty_id;
    int32_t svc_key_id;                // id of "service.name"
    LocalIntern* local = nullptr;      // set on parallel workers only

    // serial path: caller holds it->mu for the whole pass;
    // parallel path: LocalIntern takes it per local miss
    int32_t intern(const uint8_t* s, int64_t len) {
        return local ? local->get(s, len) : it->intern_locked(s, len);
    }
};

// Parse one KeyValue into a StageAttr (interning key + string value).
// Returns false on malformed bytes.
static bool stage_keyvalue(StageCtx& c, const uint8_t* kv, uint64_t kvlen,
                           int32_t owner, StageAttr& a) {
    Cursor cur{kv, kv + kvlen, true};
    uint32_t f, w; uint64_t v, l; const uint8_t* s;
    a.sval_off = -1; a.ival = 0; a.fval = 0; a.sval_len = 0;
    a.key_id = c.empty_id; a.sval_id = -1; a.typ = 0; a.owner = owner;
    a._pad = 0;
    const uint8_t* val_start = nullptr; uint64_t val_len = 0;
    while (read_field(cur, f, w, v, s, l)) {
        if (f == 1 && w == 2) a.key_id = c.intern(s, l);
        else if (f == 2 && w == 2) { val_start = s; val_len = l; }
    }
    if (!cur.ok) return false;
    if (val_start) {
        Cursor av{val_start, val_start + val_len, true};
        while (read_field(av, f, w, v, s, l)) {
            switch (f) {
                case 1: if (w == 2) {
                            a.typ = 1;
                            a.sval_id = c.intern(s, l);
                            a.sval_off = s - c.buf;
                            a.sval_len = (int32_t)l;
                        } break;
                case 2: a.typ = 2; a.fval = v ? 1.0 : 0.0; break;
                case 3: a.typ = 3; a.ival = (int64_t)v; break;
                case 4: { a.typ = 4; double d; memcpy(&d, &v, 8); a.fval = d; } break;
                default:
                    if (a.typ == 0) {
                        a.sval_off = val_start - c.buf;
                        a.sval_len = (int32_t)val_len;
                    }
                    break;
            }
        }
        if (!av.ok) return false;
    }
    return true;
}

// Parse a Resource message: intern its attrs, find service.name.
static bool stage_resource(StageCtx& c, const uint8_t* rm, uint64_t rmlen,
                           StageRes& r) {
    r.service_id = c.empty_id;
    r.attr_start = (int32_t)c.n_rattrs;
    r.attr_count = 0;
    r._pad = 0;
    if (!rm) return true;
    Cursor cur{rm, rm + rmlen, true};
    uint32_t f, w; uint64_t v, l; const uint8_t* s;
    while (read_field(cur, f, w, v, s, l)) {
        if (f != 1 || w != 2) continue;            // Resource.attributes
        StageAttr a;
        if (!stage_keyvalue(c, s, l, (int32_t)c.n_res, a)) return false;
        if (c.n_rattrs < c.rattr_cap) c.rattrs[c.n_rattrs] = a;
        c.n_rattrs++;
        r.attr_count++;
        if (a.key_id == c.svc_key_id && a.typ == 1)
            r.service_id = a.sval_id;
    }
    return cur.ok;
}

static bool stage_span(StageCtx& c, const uint8_t* sp, uint64_t splen,
                       int32_t res_idx, int32_t service_id,
                       bool skip_attrs, bool trust_attrs) {
    StageRec rec;
    memset(&rec, 0, sizeof(rec));
    rec.name_id = c.empty_id;
    rec.status_msg_id = -1;
    rec.service_id = service_id;
    rec.res_idx = res_idx;
    rec.span_len = (int32_t)splen;
    int32_t span_idx = (int32_t)c.n_spans;
    Cursor cur{sp, sp + splen, true};
    uint32_t f, w; uint64_t v, l; const uint8_t* s;
    while (read_field(cur, f, w, v, s, l)) {
        if ((f <= 5 || f == 9 || f == 15) && w != 2) continue;
        switch (f) {
            case 1: rec.tid_len = (int32_t)l;
                    if (l <= 16) memcpy(rec.trace_id, s, l); break;
            case 2: rec.sid_len = (int32_t)l;
                    if (l <= 8) memcpy(rec.span_id, s, l); break;
            case 4: rec.pid_len = (int32_t)l;
                    if (l <= 8) memcpy(rec.parent_span_id, s, l); break;
            case 5: rec.name_id = c.intern(s, l); break;
            case 6: if (w == 0) rec.kind = (int32_t)v; break;
            case 7: if (w != 2) rec.start_ns = v; break;
            case 8: if (w != 2) rec.end_ns = v; break;
            case 9: {
                if (skip_attrs) {
                    // caller's processors never read span attrs. When the
                    // bytes were already validated upstream in-process
                    // (the distributor's scan — trust_attrs), skip even
                    // the validation walk; else validate without
                    // interning or storing
                    if (!trust_attrs) {
                        AttrRec scratch;
                        if (!parse_keyvalue(c.buf, s, l, span_idx, scratch))
                            return false;
                    }
                    break;
                }
                StageAttr a;
                if (!stage_keyvalue(c, s, l, span_idx, a)) return false;
                if (c.n_sattrs < c.sattr_cap) c.sattrs[c.n_sattrs] = a;
                c.n_sattrs++;
                break;
            }
            case 15: {
                Cursor st{s, s + l, true};
                uint32_t f5, w5; uint64_t v5, l5; const uint8_t* s5;
                while (read_field(st, f5, w5, v5, s5, l5)) {
                    if (f5 == 2 && w5 == 2)
                        rec.status_msg_id = c.intern(s5, l5);
                    else if (f5 == 3) rec.status_code = (int32_t)v5;
                }
                if (!st.ok) return false;
                break;
            }
            default: break;
        }
    }
    if (!cur.ok) return false;
    if (c.n_spans < c.span_cap) c.spans[c.n_spans] = rec;
    c.n_spans++;
    return true;
}

}  // namespace

extern "C" {

// Full staging pass. Returns 0 on success, -1 on malformed input. Counts
// (which may exceed the caps; caller re-calls with bigger buffers and a
// FRESH scan) are written to n_out[0..3] = spans, span_attrs, res_attrs,
// resources. Interning is idempotent so a re-scan is safe.
// flags bit 0: skip span attrs (validate only — no interning, no output;
// the dominant per-span cost when the caller's processors read only
// intrinsic dimensions).
int32_t otlp_stage(void* interner, const uint8_t* buf, int64_t buflen,
                   StageRec* spans, int64_t span_cap,
                   StageAttr* sattrs, int64_t sattr_cap,
                   StageAttr* rattrs, int64_t rattr_cap,
                   StageRes* res, int64_t res_cap,
                   int32_t flags, int64_t* n_out) {
    Interner* it = (Interner*)interner;
    std::lock_guard<std::mutex> g(it->mu);
    StageCtx c;
    c.it = it; c.buf = buf;
    c.spans = spans; c.span_cap = span_cap;
    c.sattrs = sattrs; c.sattr_cap = sattr_cap;
    c.rattrs = rattrs; c.rattr_cap = rattr_cap;
    c.res = res; c.res_cap = res_cap;
    static const uint8_t kEmpty = 0;
    c.empty_id = it->intern_locked(&kEmpty, 0);
    c.svc_key_id = it->intern_locked((const uint8_t*)"service.name", 12);

    Cursor top{buf, buf + buflen, true};
    uint32_t f, w; uint64_t v, len; const uint8_t* start;
    while (read_field(top, f, w, v, start, len)) {
        if (f != 1 || w != 2) continue;            // ResourceSpans
        const uint8_t* rm = nullptr; uint64_t rmlen = 0;
        uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
        Cursor rs1{start, start + len, true};
        while (read_field(rs1, f2, w2, v2, s2, l2)) {
            if (f2 == 1 && w2 == 2) { rm = s2; rmlen = l2; }
        }
        if (!rs1.ok) return -1;
        StageRes r;
        if (!stage_resource(c, rm, rmlen, r)) return -1;
        int32_t res_idx = (int32_t)c.n_res;
        if (c.n_res < c.res_cap) c.res[c.n_res] = r;
        c.n_res++;
        Cursor rs{start, start + len, true};
        while (read_field(rs, f2, w2, v2, s2, l2)) {
            if (f2 != 2 || w2 != 2) continue;      // ScopeSpans
            Cursor ss{s2, s2 + l2, true};
            uint32_t f3, w3; uint64_t v3, l3; const uint8_t* s3;
            while (read_field(ss, f3, w3, v3, s3, l3)) {
                if (f3 != 2 || w3 != 2) continue;  // Span
                if (!stage_span(c, s3, l3, res_idx, r.service_id,
                                (flags & 1) != 0, (flags & 2) != 0))
                    return -1;
            }
            if (!ss.ok) return -1;
        }
        if (!rs.ok) return -1;
    }
    if (!top.ok) return -1;
    n_out[0] = c.n_spans; n_out[1] = c.n_sattrs;
    n_out[2] = c.n_rattrs; n_out[3] = c.n_res;
    return 0;
}

// Parallel staging for the skip-attrs shape (the generator's default:
// processors read only intrinsic dimensions). A sequential prelude stages
// Resources and counts spans per ResourceSpans (header walk only); worker
// threads then deep-stage disjoint output ranges with thread-local intern
// memos (LocalIntern) in front of the shared interner. Output order is
// identical to the sequential stage. Returns -1 malformed, 0 ok; when the
// span count exceeds span_cap only counts are written (caller regrows and
// re-calls — interning is idempotent).
int32_t otlp_stage_mt(void* interner, const uint8_t* buf, int64_t buflen,
                      StageRec* spans, int64_t span_cap,
                      StageAttr* rattrs, int64_t rattr_cap,
                      StageRes* res, int64_t res_cap,
                      int32_t flags, int64_t* n_out, int32_t n_threads) {
    if (!(flags & 1)) return -2;               // skip-attrs shapes only
    Interner* it = (Interner*)interner;
    struct Range {
        const uint8_t* start; uint64_t len;
        int64_t out_base; int64_t count;
        int32_t res_idx; int32_t service_id;
    };
    std::vector<Range> ranges;
    int64_t total = 0, n_res = 0;
    {
        // prelude holds the interner lock: resource staging interns the
        // (few) service names / resource keys exactly like the serial pass
        std::lock_guard<std::mutex> g(it->mu);
        StageCtx c;
        c.it = it; c.buf = buf;
        c.spans = nullptr; c.span_cap = 0;
        c.sattrs = nullptr; c.sattr_cap = 0;
        c.rattrs = rattrs; c.rattr_cap = rattr_cap;
        c.res = res; c.res_cap = res_cap;
        static const uint8_t kEmpty = 0;
        c.empty_id = it->intern_locked(&kEmpty, 0);
        c.svc_key_id = it->intern_locked((const uint8_t*)"service.name", 12);
        Cursor top{buf, buf + buflen, true};
        uint32_t f, w; uint64_t v, len; const uint8_t* start;
        while (read_field(top, f, w, v, start, len)) {
            if (f != 1 || w != 2) continue;    // ResourceSpans
            const uint8_t* rm = nullptr; uint64_t rmlen = 0;
            uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
            Cursor rs1{start, start + len, true};
            while (read_field(rs1, f2, w2, v2, s2, l2)) {
                if (f2 == 1 && w2 == 2) { rm = s2; rmlen = l2; }
            }
            if (!rs1.ok) return -1;
            StageRes r;
            if (!stage_resource(c, rm, rmlen, r)) return -1;
            int32_t res_idx = (int32_t)c.n_res;
            if (c.n_res < c.res_cap) c.res[c.n_res] = r;
            c.n_res++;
            int64_t cnt = count_spans_rs(start, len);
            if (cnt < 0) return -1;
            ranges.push_back(Range{start, len, total, cnt,
                                   res_idx, r.service_id});
            total += cnt;
        }
        if (!top.ok) return -1;
        n_res = c.n_res;
        n_out[0] = total; n_out[1] = 0;
        n_out[2] = c.n_rattrs; n_out[3] = n_res;
        if (total > span_cap || c.n_rattrs > rattr_cap)
            return 0;                          // caller regrows
    }
    static const uint8_t kEmpty2 = 0;
    int32_t empty_id, svc_key_id;
    {
        std::lock_guard<std::mutex> g(it->mu);
        empty_id = it->intern_locked(&kEmpty2, 0);
        svc_key_id = it->intern_locked((const uint8_t*)"service.name", 12);
    }
    bool skip = true, trust = (flags & 2) != 0;
    int nt = (int)std::min<size_t>(std::max(n_threads, 1),
                                   std::max<size_t>(ranges.size(), 1));
    std::atomic<bool> bad{false};

    auto work = [&](int t) {
        LocalIntern local(it, buf);
        StageCtx c;
        c.it = it; c.buf = buf;
        c.spans = spans; c.span_cap = span_cap;
        c.sattrs = nullptr; c.sattr_cap = 0;
        c.rattrs = nullptr; c.rattr_cap = 0;
        c.res = nullptr; c.res_cap = 0;
        c.empty_id = empty_id;
        c.svc_key_id = svc_key_id;
        c.local = &local;
        for (size_t ri = t; ri < ranges.size(); ri += nt) {
            if (bad.load(std::memory_order_relaxed)) return;
            const Range& r = ranges[ri];
            c.n_spans = r.out_base;
            Cursor rs{r.start, r.start + r.len, true};
            uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
            while (read_field(rs, f2, w2, v2, s2, l2)) {
                if (f2 != 2 || w2 != 2) continue;      // ScopeSpans
                Cursor ss{s2, s2 + l2, true};
                uint32_t f3, w3; uint64_t v3, l3; const uint8_t* s3;
                while (read_field(ss, f3, w3, v3, s3, l3)) {
                    if (f3 != 2 || w3 != 2) continue;  // Span
                    if (!stage_span(c, s3, l3, r.res_idx, r.service_id,
                                    skip, trust)) {
                        bad.store(true, std::memory_order_relaxed);
                        return;
                    }
                }
                if (!ss.ok) { bad.store(true); return; }
            }
            if (!rs.ok) { bad.store(true); return; }
        }
    };

    if (nt < 2 || total < 4096) {
        for (int t = 0; t < nt; t++) work(t);
    } else {
        std::vector<std::thread> threads;
        threads.reserve(nt);
        for (int t = 0; t < nt; t++) threads.emplace_back(work, t);
        for (auto& th : threads) th.join();
    }
    return bad.load() ? -1 : 0;
}

}  // extern "C"

// --- fused spanmetrics resolution (staged records -> device-ready arrays) ----
//
// The generator's dedicated-spanmetrics hot path (the PushSpans shape of
// `modules/generator/generator.go:275` with only the spanmetrics processor
// enabled): one pass over the staged records builds the intrinsic label
// row, resolves it against the persistent RowTable, applies the ingestion
// slack filter, and emits the scatter-ready arrays (slots, duration
// seconds, wire sizes) the fused device update consumes directly. This
// replaces four Python/numpy passes (SpanBatch materialization, label-row
// stacking, separate rowtable lookup, duration math) with one C loop —
// on a 1-core host the Python staging was the e2e throughput bound.
//
// dims: per-label field selector (0=service_id 1=name_id 2=kind->lut
// 3=status_code->lut). kind_lut[6]/status_lut[3] carry the intern ids of
// the SPAN_KIND_* / STATUS_CODE_* strings so rows match the generic
// `_label_rows` path bit-for-bit (same table serves both paths).
// slack_hi == 0 disables the slack filter. last_seen (may be null) is
// stamped with `now` for every resolved slot. Misses get PENDING entries
// (first occurrence appended to miss_idx, rows all emitted to rows_out);
// Python resolves them exactly like rowtable_lookup's contract requires.
// counts_out: [0]=n_valid (post-slack), [1]=n_filtered.

extern "C" {

int64_t spanmetrics_resolve(
    void* rowtable_h, const StageRec* spans, int64_t n,
    const int32_t* dims, int32_t n_dims,
    const int32_t* kind_lut, const int32_t* status_lut,
    int64_t slack_lo, int64_t slack_hi, double now, double* last_seen,
    int32_t* slots_out, float* dur_out, float* size_out,
    int32_t* rows_out, uint8_t* valid_out,
    int64_t* miss_idx, int64_t miss_cap, int64_t* counts_out) {
    RowTable* t = (RowTable*)rowtable_h;
    std::lock_guard<std::mutex> g(t->mu);
    int64_t miss = 0, n_valid = 0, n_filtered = 0;
    // one-entry memo: consecutive spans of one service/op resolve without
    // re-probing (payloads arrive grouped by resource and often by name)
    uint64_t last_h = 0;
    int32_t last_slot = -1;
    bool have_last = false;
    int32_t prev_row[8];
    const bool memo_ok = n_dims <= 8;
    for (int64_t i = 0; i < n; i++) {
        const StageRec& r = spans[i];
        int32_t* row = rows_out + i * n_dims;
        for (int32_t d = 0; d < n_dims; d++) {
            switch (dims[d]) {
                case 0: row[d] = r.service_id; break;
                case 1: row[d] = r.name_id; break;
                case 2: {
                    int32_t k = r.kind;
                    row[d] = kind_lut[k < 0 ? 0 : (k > 5 ? 5 : k)];
                    break;
                }
                default: {
                    int32_t s = r.status_code;
                    row[d] = status_lut[s < 0 ? 0 : (s > 2 ? 2 : s)];
                }
            }
        }
        int64_t end = (int64_t)r.end_ns;
        bool ok = slack_hi == 0 || (end >= slack_lo && end <= slack_hi);
        valid_out[i] = ok ? 1 : 0;
        dur_out[i] = (float)((double)(end - (int64_t)r.start_ns) * 1e-9);
        size_out[i] = (float)r.span_len;
        if (!ok) {
            slots_out[i] = -1;
            n_filtered++;
            continue;
        }
        n_valid++;
        uint64_t hh = t->rhash(row);
        if (memo_ok && have_last && hh == last_h &&
            memcmp(prev_row, row, n_dims * 4) == 0) {
            slots_out[i] = last_slot;
            continue;
        }
        int32_t e = t->find_entry(row, hh);
        int32_t slot;
        if (e == -1) {
            t->add_entry(row, hh, kPending);
            if (miss < miss_cap) miss_idx[miss] = i;
            miss++;
            slot = -1;
        } else if (t->slots[e] == kPending) {
            slot = -1;
        } else {
            slot = t->slots[e];
            if (last_seen) last_seen[slot] = now;
        }
        slots_out[i] = slot;
        last_h = hh;
        last_slot = slot;
        have_last = memo_ok && slot >= 0;
        if (memo_ok) memcpy(prev_row, row, n_dims * 4);
    }
    counts_out[0] = n_valid;
    counts_out[1] = n_filtered;
    return miss;
}

}  // extern "C"

// --- tee-path fusion: distributor scan records -> spanmetrics arrays --------
//
// The in-process generator tee (`modules/distributor/distributor.go:563`
// metrics-generator forwarding) previously re-parsed the OTLP payload the
// distributor had ALREADY scanned: otlp_scan in the distributor, then
// otlp_stage in the generator — two full protobuf walks per push. This
// kernel consumes the distributor's SpanRec offsets directly: names are
// interned by gathering their recorded byte ranges (no varint walking),
// resources resolve service.name once per distinct res_off, and the row
// resolves against the RowTable exactly like spanmetrics_resolve. The
// caller passes any SUBSET of records (ring-sharded tees) while `buf`
// stays the original payload — the re-encode slice disappears entirely.
//
// Returns miss count, -1 on malformed resource bytes, or -2 when the
// LAST service.name occurrence of some resource is non-string (the
// Python stringify fixup owns that case; caller falls back). A -2 bail
// happens BEFORE any row-table mutation (resources are pre-resolved), so
// no pending entries leak.

namespace {

// memo for byte-range interning with the interner lock already held
struct HeldIntern {
    struct E { uint64_t h; int64_t off; int32_t len; int32_t id; };
    std::vector<E> tab;
    uint64_t mask;
    Interner* it;
    const uint8_t* base;

    HeldIntern(Interner* i, const uint8_t* b) : it(i), base(b) {
        tab.assign(1 << 10, E{0, 0, 0, -1});
        mask = tab.size() - 1;
    }

    int32_t get(int64_t off, int32_t len) {
        const uint8_t* s = base + off;
        uint64_t h = fnv1a64(s, len);
        uint64_t i = h & mask;
        int probes = 0;
        while (probes++ < 32) {
            E& e = tab[i];
            if (e.id == -1) {
                e = E{h, off, len, it->intern_locked(s, len)};
                return e.id;
            }
            if (e.h == h && e.len == len &&
                memcmp(base + e.off, s, len) == 0)
                return e.id;
            i = (i + 1) & mask;
        }
        return it->intern_locked(s, len);      // memo full: direct
    }
};

// service.name of one Resource message; 0 ok, -1 malformed, -2 needs the
// Python fixup (last occurrence non-string).
static int resolve_service(const uint8_t* buf, int64_t off, int32_t len,
                           HeldIntern& hi, int32_t empty_id,
                           int32_t* out_id) {
    *out_id = empty_id;
    if (len <= 0) return 0;
    int last_typ = -1;                      // of the last service.name
    int64_t last_off = 0; int32_t last_len = 0;
    Cursor cur{buf + off, buf + off + len, true};
    uint32_t f, w; uint64_t v, l; const uint8_t* s;
    while (read_field(cur, f, w, v, s, l)) {
        if (f != 1 || w != 2) continue;     // Resource.attributes KeyValue
        Cursor kv{s, s + l, true};
        uint32_t f2, w2; uint64_t v2, l2; const uint8_t* s2;
        bool is_svc = false;
        int typ = -1; int64_t voff = 0; int32_t vlen = 0;
        while (read_field(kv, f2, w2, v2, s2, l2)) {
            if (f2 == 1 && w2 == 2) {
                is_svc = (l2 == 12 && memcmp(s2, "service.name", 12) == 0);
            } else if (f2 == 2 && w2 == 2) {
                Cursor av{s2, s2 + l2, true};
                uint32_t f3, w3; uint64_t v3, l3; const uint8_t* s3;
                while (read_field(av, f3, w3, v3, s3, l3)) {
                    if (f3 == 1 && w3 == 2) {
                        typ = 1; voff = s3 - buf; vlen = (int32_t)l3;
                    } else {
                        typ = 0;            // any non-string kind
                    }
                }
                if (!av.ok) return -1;
            }
        }
        if (!kv.ok) return -1;
        if (is_svc) { last_typ = typ; last_off = voff; last_len = vlen; }
    }
    if (!cur.ok) return -1;
    if (last_typ == -1) return 0;
    if (last_typ != 1) return -2;
    *out_id = hi.get(last_off, last_len);
    return 0;
}

}  // namespace

extern "C" {

int64_t spanmetrics_from_recs(
    void* rowtable_h, void* interner_h, const uint8_t* buf, int64_t buflen,
    const SpanRec* recs, int64_t n,
    const int32_t* dims, int32_t n_dims,
    const int32_t* kind_lut, const int32_t* status_lut,
    int64_t slack_lo, int64_t slack_hi, double now, double* last_seen,
    int32_t* slots_out, float* dur_out, float* size_out,
    int32_t* rows_out, uint8_t* valid_out,
    int64_t* miss_idx, int64_t miss_cap, int64_t* counts_out) {
    (void)buflen;
    Interner* it = (Interner*)interner_h;
    std::lock_guard<std::mutex> gi(it->mu);
    static const uint8_t kEmpty = 0;
    int32_t empty_id = it->intern_locked(&kEmpty, 0);
    HeldIntern hi(it, buf);

    // pass 1: resolve every distinct resource's service id (consecutive
    // records share resources, so the last-seen fast path covers almost
    // every record; bail on the fixup case before touching the row table)
    std::vector<int32_t> svc(n);
    std::vector<std::pair<int64_t, int32_t>> seen;   // res_off -> id
    int64_t cur_off = -1; int32_t cur_id = empty_id;
    for (int64_t i = 0; i < n; i++) {
        int64_t ro = recs[i].res_off;
        if (ro != cur_off) {
            cur_off = ro;
            int32_t id = empty_id;
            bool found = false;
            for (auto& p : seen)
                if (p.first == ro) { id = p.second; found = true; break; }
            if (!found) {
                int rc = resolve_service(buf, ro, recs[i].res_len, hi,
                                         empty_id, &id);
                if (rc != 0) return rc;
                seen.emplace_back(ro, id);
            }
            cur_id = id;
        }
        svc[i] = cur_id;
    }

    RowTable* t = (RowTable*)rowtable_h;
    std::lock_guard<std::mutex> g(t->mu);
    int64_t miss = 0, n_valid = 0, n_filtered = 0;
    uint64_t last_h = 0;
    int32_t last_slot = -1;
    bool have_last = false;
    int32_t prev_row[8];
    const bool memo_ok = n_dims <= 8;
    for (int64_t i = 0; i < n; i++) {
        const SpanRec& r = recs[i];
        int32_t* row = rows_out + i * n_dims;
        for (int32_t d = 0; d < n_dims; d++) {
            switch (dims[d]) {
                case 0: row[d] = svc[i]; break;
                case 1: row[d] = hi.get(r.name_off, r.name_len); break;
                case 2: {
                    int32_t k = r.kind;
                    row[d] = kind_lut[k < 0 ? 0 : (k > 5 ? 5 : k)];
                    break;
                }
                default: {
                    int32_t s = r.status_code;
                    row[d] = status_lut[s < 0 ? 0 : (s > 2 ? 2 : s)];
                }
            }
        }
        int64_t end = (int64_t)r.end_ns;
        bool ok = slack_hi == 0 || (end >= slack_lo && end <= slack_hi);
        valid_out[i] = ok ? 1 : 0;
        dur_out[i] = (float)((double)(end - (int64_t)r.start_ns) * 1e-9);
        size_out[i] = (float)r.span_len;
        if (!ok) {
            slots_out[i] = -1;
            n_filtered++;
            continue;
        }
        n_valid++;
        uint64_t hh = t->rhash(row);
        if (memo_ok && have_last && hh == last_h &&
            memcmp(prev_row, row, n_dims * 4) == 0) {
            slots_out[i] = last_slot;
            continue;
        }
        int32_t e = t->find_entry(row, hh);
        int32_t slot;
        if (e == -1) {
            t->add_entry(row, hh, kPending);
            if (miss < miss_cap) miss_idx[miss] = i;
            miss++;
            slot = -1;
        } else if (t->slots[e] == kPending) {
            slot = -1;
        } else {
            slot = t->slots[e];
            if (last_seen) last_seen[slot] = now;
        }
        slots_out[i] = slot;
        last_h = hh;
        last_slot = slot;
        have_last = memo_ok && slot >= 0;
        if (memo_ok) memcpy(prev_row, row, n_dims * 4);
    }
    counts_out[0] = n_valid;
    counts_out[1] = n_filtered;
    return miss;
}

}  // extern "C"

// --- trace grouping straight off the scan records ---------------------------
//
// group_keys over (trace_id ‖ tid_len) WITHOUT materializing the key
// matrix: the tee path previously copied trace ids twice (contiguous
// gather + length-column concat) per push just to feed group_keys. Reads
// SpanRec rows directly, skipping invalid ones; inverse/first index over
// the SEQUENCE of valid rows (the caller's vrows order), preserving
// `requestsByTraceID` semantics (distributor.go:694).

extern "C" {

int64_t group_keys_recs(const void* recs_p, int64_t n, const uint8_t* valid,
                        int32_t* inverse, int32_t* first_idx) {
    const SpanRec* recs = (const SpanRec*)recs_p;
    if (n <= 0) return 0;
    uint64_t cap = 64;
    while (cap < (uint64_t)n * 2) cap <<= 1;
    std::vector<int32_t> table(cap, -1);
    std::vector<int64_t> grec;                     // group -> rec row
    uint64_t mask = cap - 1;
    int64_t n_groups = 0, vi = 0;
    uint8_t key[17];
    for (int64_t r = 0; r < n; r++) {
        if (valid && !valid[r]) continue;
        const SpanRec& rec = recs[r];
        memcpy(key, rec.trace_id, 16);
        key[16] = (uint8_t)rec.tid_len;
        uint64_t h = fnv1a64(key, 17);
        uint64_t i = h & mask;
        while (true) {
            int32_t g = table[i];
            if (g == -1) {
                table[i] = (int32_t)n_groups;
                first_idx[n_groups] = (int32_t)vi;
                grec.push_back(r);
                inverse[vi] = (int32_t)n_groups;
                n_groups++;
                break;
            }
            const SpanRec& fr = recs[grec[g]];
            if (memcmp(fr.trace_id, rec.trace_id, 16) == 0 &&
                fr.tid_len == rec.tid_len) {
                inverse[vi] = g;
                break;
            }
            i = (i + 1) & mask;
        }
        vi++;
    }
    return n_groups;
}

// group_keys_recs over an ARBITRARY record layout: trace_id[16] at
// tid_off, int32 tid_len at tidlen_off, rec_size bytes per row. The
// decode-once staged tee groups StageRec rows with this (StageRec and
// SpanRec share field names but not offsets); semantics identical to
// group_keys_recs.
int64_t group_keys_strided(const void* recs_p, int64_t n, int64_t rec_size,
                           int64_t tid_off, int64_t tidlen_off,
                           const uint8_t* valid,
                           int32_t* inverse, int32_t* first_idx) {
    const uint8_t* base = (const uint8_t*)recs_p;
    if (n <= 0) return 0;
    uint64_t cap = 64;
    while (cap < (uint64_t)n * 2) cap <<= 1;
    std::vector<int32_t> table(cap, -1);
    std::vector<int64_t> grec;                     // group -> rec row
    uint64_t mask = cap - 1;
    int64_t n_groups = 0, vi = 0;
    uint8_t key[17];
    for (int64_t r = 0; r < n; r++) {
        if (valid && !valid[r]) continue;
        const uint8_t* rec = base + r * rec_size;
        const uint8_t* tid = rec + tid_off;
        int32_t tl;
        memcpy(&tl, rec + tidlen_off, 4);
        memcpy(key, tid, 16);
        key[16] = (uint8_t)tl;
        uint64_t h = fnv1a64(key, 17);
        uint64_t i = h & mask;
        while (true) {
            int32_t g = table[i];
            if (g == -1) {
                table[i] = (int32_t)n_groups;
                first_idx[n_groups] = (int32_t)vi;
                grec.push_back(r);
                inverse[vi] = (int32_t)n_groups;
                n_groups++;
                break;
            }
            const uint8_t* fr = base + grec[g] * rec_size;
            int32_t ftl;
            memcpy(&ftl, fr + tidlen_off, 4);
            if (memcmp(fr + tid_off, tid, 16) == 0 && ftl == tl) {
                inverse[vi] = g;
                break;
            }
            i = (i + 1) & mask;
        }
        vi++;
    }
    return n_groups;
}

}  // extern "C"
