"""Minimal protobuf wire-format codec (no generated code, no schema files).

Plays the role of the reference's generated `pkg/tempopb` marshaling for the
two external protobuf schemas we must speak on the wire:

- decode: OTLP `ExportTraceServiceRequest` (opentelemetry-proto trace.proto,
  a stable public schema) — see tempo_tpu.model.otlp.
- encode: Prometheus remote-write `WriteRequest` — see
  tempo_tpu.generator.remote_write.

Only the features those schemas need are implemented: varint, fixed64/32,
length-delimited. Messages decode into {field_number: [values]} dicts; the
caller interprets fields by number.
"""

from __future__ import annotations

import struct

WT_VARINT, WT_FIXED64, WT_LEN, WT_SGROUP, WT_EGROUP, WT_FIXED32 = 0, 1, 2, 3, 4, 5


# ---------------------------------------------------------------------------
# Decoding
# ---------------------------------------------------------------------------

def read_varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a serialized message.

    LEN fields yield memoryview slices (zero-copy); numeric fields yield ints.
    """
    view = memoryview(buf)
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = read_varint(buf, pos)
        fnum, wt = key >> 3, key & 7
        if wt == WT_VARINT:
            val, pos = read_varint(buf, pos)
        elif wt == WT_FIXED64:
            val = int.from_bytes(view[pos:pos + 8], "little")
            pos += 8
        elif wt == WT_FIXED32:
            val = int.from_bytes(view[pos:pos + 4], "little")
            pos += 4
        elif wt == WT_LEN:
            ln, pos = read_varint(buf, pos)
            val = view[pos:pos + ln]
            pos += ln
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fnum, wt, val


def decode_fields(buf: bytes) -> dict[int, list]:
    out: dict[int, list] = {}
    for fnum, _, val in iter_fields(buf):
        out.setdefault(fnum, []).append(val)
    return out


def zigzag_decode(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def f64(v: int) -> float:
    return struct.unpack("<d", v.to_bytes(8, "little"))[0]


# ---------------------------------------------------------------------------
# Encoding
# ---------------------------------------------------------------------------

def enc_varint(v: int) -> bytes:
    out = bytearray()
    if v < 0:
        v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def enc_tag(fnum: int, wt: int) -> bytes:
    return enc_varint((fnum << 3) | wt)


def enc_field_varint(fnum: int, v: int) -> bytes:
    return enc_tag(fnum, WT_VARINT) + enc_varint(v)


def enc_field_double(fnum: int, v: float) -> bytes:
    return enc_tag(fnum, WT_FIXED64) + struct.pack("<d", v)


def enc_field_fixed64(fnum: int, v: int) -> bytes:
    return enc_tag(fnum, WT_FIXED64) + v.to_bytes(8, "little")


def enc_field_bytes(fnum: int, v: bytes) -> bytes:
    return enc_tag(fnum, WT_LEN) + enc_varint(len(v)) + v


def enc_field_str(fnum: int, v: str) -> bytes:
    return enc_field_bytes(fnum, v.encode("utf-8"))


def enc_field_msg(fnum: int, v: bytes) -> bytes:
    return enc_field_bytes(fnum, v)
