"""String interning: the CPU-side dictionary for device-coded attributes.

Strings never reach the device. Every attribute key/value, span name, and
service name is interned to a dense int32 id on the host; device kernels see
only id columns. This plays the role the reference's `LabelValueCombo` +
series hashing plays in `modules/generator/registry/registry.go:139-144`,
and of parquet dictionary encoding in the block layer.
"""

from __future__ import annotations

import threading
from typing import Iterable

import numpy as np

INVALID_ID = -1


class StringInterner:
    """Append-only str→int32 table with reverse lookup. Thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ids: dict[str, int] = {}
        self._strs: list[str] = []

    def __len__(self) -> int:
        return len(self._strs)

    def intern(self, s: str) -> int:
        sid = self._ids.get(s)
        if sid is not None:
            return sid
        with self._lock:
            sid = self._ids.get(s)
            if sid is None:
                sid = len(self._strs)
                self._strs.append(s)
                self._ids[s] = sid
            return sid

    def intern_many(self, strs: Iterable[str]) -> np.ndarray:
        return np.fromiter((self.intern(s) for s in strs), dtype=np.int32)

    def get(self, s: str) -> int:
        """Lookup without inserting; INVALID_ID when absent (query-side)."""
        return self._ids.get(s, INVALID_ID)

    def lookup(self, sid: int) -> str:
        return self._strs[sid]

    def lookup_many(self, ids: np.ndarray) -> list[str]:
        strs = self._strs
        return [strs[i] if i >= 0 else "" for i in np.asarray(ids).tolist()]

    def snapshot(self) -> list[str]:
        with self._lock:
            return list(self._strs)
