"""String interning: the CPU-side dictionary for device-coded attributes.

Strings never reach the device. Every attribute key/value, span name, and
service name is interned to a dense int32 id on the host; device kernels see
only id columns. This plays the role the reference's `LabelValueCombo` +
series hashing plays in `modules/generator/registry/registry.go:139-144`,
and of parquet dictionary encoding in the block layer.

When the native library is available the id table lives in C++
(`native.cpp Interner`): the OTLP staging pass (`native.otlp_stage`)
interns every wire string without crossing back into Python, and this
class fronts the C++ table with a str-keyed cache plus a lazily synced
id → str mirror for reverse lookups. Raw wire bytes that are not valid
UTF-8 are interned as-is in C++ and mirrored here with replacement
characters — two such byte strings that decode identically keep distinct
ids (the pure-Python path would merge them), which at worst duplicates a
pathological series label.
"""

from __future__ import annotations

import threading
from typing import Iterable

import numpy as np

INVALID_ID = -1


def _native_interner():
    try:
        from tempo_tpu import native
        if native.available():
            return native.NativeInterner()
    except Exception:
        pass
    return None


class StringInterner:
    """Append-only str→int32 table with reverse lookup. Thread-safe."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ids: dict[str, int] = {}
        self._strs: list[str] = []
        self._native = _native_interner()

    def __len__(self) -> int:
        if self._native is not None:
            return self._native.count()
        return len(self._strs)

    def _sync_locked(self) -> None:
        """Pull strings interned C++-side (otlp_stage) into the mirror."""
        nat = self._native
        if nat is None:
            return
        cnt = nat.count()
        first = len(self._strs)
        if cnt > first:
            for b in nat.dump(first, cnt - first):
                s = b.decode("utf-8", "replace")
                self._ids.setdefault(s, len(self._strs))
                self._strs.append(s)

    def sync(self) -> None:
        with self._lock:
            self._sync_locked()

    def intern(self, s: str) -> int:
        sid = self._ids.get(s)
        if sid is not None:
            return sid
        nat = self._native
        if nat is not None:
            sid = nat.intern_bytes(s.encode("utf-8", "surrogatepass"))
            with self._lock:
                self._sync_locked()
                # guarantee a cache hit for this exact str even when the
                # mirror decode of its bytes differs (surrogates)
                self._ids.setdefault(s, sid)
            return sid
        with self._lock:
            sid = self._ids.get(s)
            if sid is None:
                sid = len(self._strs)
                self._strs.append(s)
                self._ids[s] = sid
            return sid

    def intern_many(self, strs: Iterable[str]) -> np.ndarray:
        return np.fromiter((self.intern(s) for s in strs), dtype=np.int32)

    def get(self, s: str) -> int:
        """Lookup without inserting; INVALID_ID when absent (query-side)."""
        sid = self._ids.get(s)
        if sid is not None:
            return sid
        if self._native is not None:
            return self._native.find_bytes(s.encode("utf-8", "surrogatepass"))
        return INVALID_ID

    def lookup(self, sid: int) -> str:
        if sid >= len(self._strs):
            self.sync()
        return self._strs[sid]

    def lookup_many(self, ids: np.ndarray) -> list[str]:
        ids = np.asarray(ids)
        if ids.size and int(ids.max()) >= len(self._strs):
            self.sync()
        strs = self._strs
        return [strs[i] if i >= 0 else "" for i in ids.tolist()]

    def snapshot(self) -> list[str]:
        with self._lock:
            self._sync_locked()
            return list(self._strs)

    def native_handle(self):
        """The NativeInterner behind this table, or None (staging uses it
        to intern wire strings without crossing into Python)."""
        return self._native
