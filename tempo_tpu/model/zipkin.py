"""Zipkin v2 JSON receiver decoding.

Analog of the zipkin receiver the distributor hosts in-process
(`modules/distributor/receiver/shim.go:165-171`): Zipkin v2 spans
(`POST /api/v2/spans`) map onto the flat span-dict wire form. Kind maps
SERVER/CLIENT/PRODUCER/CONSUMER; `localEndpoint.serviceName` becomes the
resource service; tags become span attrs; timestamps are µs in Zipkin.
"""

from __future__ import annotations

from typing import Any, Iterable

_KIND = {"SERVER": 2, "CLIENT": 3, "PRODUCER": 4, "CONSUMER": 5}


def _pad_id(hexstr: str, nbytes: int) -> bytes:
    h = (hexstr or "").lower()
    try:
        raw = bytes.fromhex(h.zfill(nbytes * 2)[-nbytes * 2:])
    except ValueError:
        return b""
    return raw


def spans_from_zipkin_json(payload: list[dict]) -> Iterable[dict]:
    for z in payload or []:
        ts_us = int(z.get("timestamp") or 0)
        dur_us = int(z.get("duration") or 0)
        tags: dict[str, Any] = dict(z.get("tags") or {})
        svc = ((z.get("localEndpoint") or {}).get("serviceName")
               or tags.pop("service.name", "") or "")
        status_code = 0
        if "error" in tags:
            status_code = 2
        s = {
            "trace_id": _pad_id(z.get("traceId", ""), 16),
            "span_id": _pad_id(z.get("id", ""), 8),
            "parent_span_id": _pad_id(z.get("parentId", ""), 8)
            if z.get("parentId") else b"",
            "name": z.get("name", ""),
            "service": svc,
            "kind": _KIND.get(str(z.get("kind", "")).upper(), 0),
            "status_code": status_code,
            "start_unix_nano": ts_us * 1000,
            "end_unix_nano": (ts_us + dur_us) * 1000,
        }
        if tags:
            s["attrs"] = tags
        if svc:
            s["res_attrs"] = {"service.name": svc}
        yield s
