"""SpanBatch: padded structure-of-arrays span tensors.

The reference regroups incoming spans trace-by-trace with per-span Go loops
(`modules/distributor/distributor.go:694-801` `requestsByTraceID`) and walks
spans one at a time in its hot aggregation paths
(`modules/generator/processor/spanmetrics/spanmetrics.go:158` and
`pkg/traceql/engine_metrics.go` `GroupingAggregator.Observe`). On TPU the
unit of work is instead a *batch tensor*: fixed-width numeric columns plus
dictionary-coded attribute id columns, padded to size buckets so jitted
kernels see a small set of static shapes.

Layout (N = padded span count, K/R = padded span/resource attr width):

    trace_id      [N,16] uint8   span_id/parent_span_id [N,8] uint8  (host)
    name_id, service_id, kind, status_code, status_message_id  [N] int32
    start_unix_nano [N] int64 (host) / start_rel_s [N] f32 + base (device)
    duration_ns   [N] f32 device view (int64 host)
    span_attr_{key,sval,typ} [N,K] int32/int32/int8, fval [N,K] f32
    res_attr_{...}           [N,R] likewise
    valid         [N] bool  — padding mask; every kernel threads it through

Attr value typing follows the OTLP AnyValue scalar kinds (string/bool/int/
double); non-scalar values are stringified, as the reference does when it
flattens attributes into parquet columns (vparquet4 `schema.go:253`
`attrToParquet`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import numpy as np

from tempo_tpu.model.interner import INVALID_ID, StringInterner

# OTLP span kinds (trace.proto SpanKind).
KIND_UNSPECIFIED, KIND_INTERNAL, KIND_SERVER, KIND_CLIENT, KIND_PRODUCER, KIND_CONSUMER = range(6)
# OTLP status codes (trace.proto Status.StatusCode).
STATUS_UNSET, STATUS_OK, STATUS_ERROR = range(3)

ATTR_NONE, ATTR_STRING, ATTR_BOOL, ATTR_INT, ATTR_DOUBLE = range(5)

_PAD_BUCKETS = (256, 512, 1024, 2048, 4096, 8192, 16384, 32768, 65536, 131072, 262144)
_ATTR_WIDTHS = (0, 4, 8, 16, 32, 64)


def _pad_rows(n: int) -> int:
    for b in _PAD_BUCKETS:
        if n <= b:
            return b
    # beyond the bucket table: round up to the next multiple of the largest bucket
    top = _PAD_BUCKETS[-1]
    return ((n + top - 1) // top) * top


def _pad_width(k: int) -> int:
    for b in _ATTR_WIDTHS:
        if k <= b:
            return b
    return k


def void_keys(*cols: np.ndarray) -> np.ndarray:
    """One opaque fixed-width key per row over [n, w] byte columns.

    Concatenates the columns and reinterprets each row as a single
    `np.void` scalar — the vectorized replacement for per-row
    `tobytes()` concatenation loops (servicegraphs edge keys, the
    trace-analytics live-trace index). Void rows sort / unique /
    searchsorted byte-lexicographically; `keys[i].item()` yields the
    exact bytes the old per-row concatenation produced, for dict keys
    (numpy 2 void SCALARS are unhashable, their `.item()` bytes are)."""
    mats = [np.asarray(c) for c in cols]
    mat = mats[0] if len(mats) == 1 else np.concatenate(mats, axis=1)
    mat = np.ascontiguousarray(mat)
    return mat.view(np.dtype((np.void, mat.shape[1]))).ravel()


@dataclasses.dataclass
class SpanBatch:
    """Host-resident SoA span batch. `n` real spans, arrays padded beyond."""

    n: int
    trace_id: np.ndarray          # [N,16] u8
    span_id: np.ndarray           # [N,8] u8
    parent_span_id: np.ndarray    # [N,8] u8
    name_id: np.ndarray           # [N] i32
    service_id: np.ndarray        # [N] i32
    kind: np.ndarray              # [N] i32
    status_code: np.ndarray       # [N] i32
    status_message_id: np.ndarray # [N] i32
    start_unix_nano: np.ndarray   # [N] i64
    end_unix_nano: np.ndarray     # [N] i64
    span_attr_key: np.ndarray     # [N,K] i32 (INVALID_ID = empty slot)
    span_attr_sval: np.ndarray    # [N,K] i32
    span_attr_fval: np.ndarray    # [N,K] f32
    span_attr_typ: np.ndarray     # [N,K] i8
    res_attr_key: np.ndarray      # [N,R] i32
    res_attr_sval: np.ndarray     # [N,R] i32
    res_attr_fval: np.ndarray     # [N,R] f32
    res_attr_typ: np.ndarray      # [N,R] i8
    valid: np.ndarray             # [N] bool
    interner: StringInterner

    @property
    def capacity(self) -> int:
        return self.valid.shape[0]

    @property
    def duration_ns(self) -> np.ndarray:
        return (self.end_unix_nano - self.start_unix_nano).astype(np.int64)

    def device_view(self) -> dict[str, np.ndarray]:
        """Numeric columns destined for the device, as a plain dict pytree.

        start times are rebased to the batch minimum so float32 holds
        nanosecond-scale offsets losslessly enough for step bucketing.
        """
        base = int(self.start_unix_nano[: self.n].min()) if self.n else 0
        return {
            "name_id": self.name_id,
            "service_id": self.service_id,
            "kind": self.kind,
            "status_code": self.status_code,
            "start_rel_s": ((self.start_unix_nano - base) / 1e9).astype(np.float32),
            "duration_ns": self.duration_ns.astype(np.float32),
            "span_attr_key": self.span_attr_key,
            "span_attr_sval": self.span_attr_sval,
            "span_attr_fval": self.span_attr_fval,
            "res_attr_key": self.res_attr_key,
            "res_attr_sval": self.res_attr_sval,
            "res_attr_fval": self.res_attr_fval,
            "valid": self.valid,
        }, base

    # -- host-side helpers -------------------------------------------------

    def attr_sval_column(self, key: str, scope: str = "span") -> np.ndarray:
        """[N] int32 of interned string values for `key` (INVALID_ID absent).

        The SpanBatch analog of a parquet dedicated attribute column
        (vparquet4 `dedicated_columns.go`): materialize one attribute as a
        dense column for grouping/filtering.
        """
        kid = self.interner.get(key)
        keys, svals = (
            (self.span_attr_key, self.span_attr_sval)
            if scope == "span"
            else (self.res_attr_key, self.res_attr_sval)
        )
        out = np.full(self.capacity, INVALID_ID, np.int32)
        if kid == INVALID_ID or keys.shape[1] == 0:
            return out
        hit = keys == kid  # [N,K]
        has = hit.any(axis=1)
        idx = hit.argmax(axis=1)
        out[has] = svals[np.arange(self.capacity), idx][has]
        return out

    def take_rows(self, rows: np.ndarray) -> "SpanBatch":
        """Row-gathered copy of `rows` (indices into [0, n)), re-padded to
        the bucket table. The materialization step of a tee VIEW: every
        column gathers from the shared arrays — no wire re-decode, no
        re-serialization. Full-coverage callers should skip this entirely
        and use the shared batch (see `otlp_batch.StagedView`)."""
        rows = np.asarray(rows, np.int64)
        n = len(rows)
        cap = _pad_rows(max(n, 1))
        pad = cap - n

        def g1(a, fill=0):
            out = np.full(cap, fill, a.dtype) if pad else np.empty(cap, a.dtype)
            out[:n] = a[rows]
            return out

        def g2(a, fill=0):
            out = (np.full((cap, a.shape[1]), fill, a.dtype) if pad
                   else np.empty((cap, a.shape[1]), a.dtype))
            out[:n] = a[rows]
            return out

        valid = np.zeros(cap, bool)
        valid[:n] = self.valid[rows]
        return SpanBatch(
            n=n,
            trace_id=g2(self.trace_id), span_id=g2(self.span_id),
            parent_span_id=g2(self.parent_span_id),
            name_id=g1(self.name_id, INVALID_ID),
            service_id=g1(self.service_id, INVALID_ID),
            kind=g1(self.kind), status_code=g1(self.status_code),
            status_message_id=g1(self.status_message_id, INVALID_ID),
            start_unix_nano=g1(self.start_unix_nano),
            end_unix_nano=g1(self.end_unix_nano),
            span_attr_key=g2(self.span_attr_key, INVALID_ID),
            span_attr_sval=g2(self.span_attr_sval, INVALID_ID),
            span_attr_fval=g2(self.span_attr_fval),
            span_attr_typ=g2(self.span_attr_typ),
            res_attr_key=g2(self.res_attr_key, INVALID_ID),
            res_attr_sval=g2(self.res_attr_sval, INVALID_ID),
            res_attr_fval=g2(self.res_attr_fval),
            res_attr_typ=g2(self.res_attr_typ),
            valid=valid, interner=self.interner,
        )

    def to_span_dicts(self, rows: "np.ndarray | None" = None) -> list[dict]:
        """Valid rows as flat span dicts (the WAL/storage span form).

        The bridge from the device-friendly SoA back to durable storage —
        used by the localblocks processor, whose job is persistence
        (`modules/generator/processor/localblocks/processor.go:151`) and
        by the ingester's staged-view push. `rows` restricts the
        conversion to a view's row subset (order preserved)."""
        it = self.interner
        out = []
        k_has = self.span_attr_key.shape[1] > 0
        r_has = self.res_attr_key.shape[1] > 0
        if rows is None:
            rows = np.flatnonzero(self.valid[: self.n])
        else:
            rows = np.asarray(rows, np.int64)
            rows = rows[self.valid[rows]]
        for i in rows:
            s: dict = {
                "trace_id": self.trace_id[i].tobytes(),
                "span_id": self.span_id[i].tobytes(),
                "parent_span_id": self.parent_span_id[i].tobytes(),
                "name": it.lookup(int(self.name_id[i])),
                "service": it.lookup(int(self.service_id[i])),
                "kind": int(self.kind[i]),
                "status_code": int(self.status_code[i]),
                "start_unix_nano": int(self.start_unix_nano[i]),
                "end_unix_nano": int(self.end_unix_nano[i]),
            }
            if int(self.status_message_id[i]) != INVALID_ID:
                s["status_message"] = it.lookup(int(self.status_message_id[i]))
            if k_has:
                a = self._decode_attrs(self.span_attr_key[i], self.span_attr_sval[i],
                                       self.span_attr_fval[i], self.span_attr_typ[i])
                if a:
                    s["attrs"] = a
            if r_has:
                a = self._decode_attrs(self.res_attr_key[i], self.res_attr_sval[i],
                                       self.res_attr_fval[i], self.res_attr_typ[i])
                if a:
                    s["res_attrs"] = a
            out.append(s)
        return out

    def _decode_attrs(self, keys, svals, fvals, typs) -> dict:
        it = self.interner
        out = {}
        for j in range(len(keys)):
            kid = int(keys[j])
            if kid == INVALID_ID:
                continue
            t = int(typs[j])
            if t == ATTR_STRING:
                out[it.lookup(kid)] = it.lookup(int(svals[j]))
            elif t == ATTR_BOOL:
                out[it.lookup(kid)] = bool(fvals[j])
            elif t == ATTR_INT:
                out[it.lookup(kid)] = int(fvals[j])
            elif t == ATTR_DOUBLE:
                out[it.lookup(kid)] = float(fvals[j])
        return out

    def tid_hash64(self) -> tuple[np.ndarray, np.ndarray]:
        """Two uint32 trace-id hash columns (device grouping / HLL keys)."""
        v = self.trace_id.view(np.uint32).reshape(self.capacity, 4)
        return (v[:, 0] ^ v[:, 2], v[:, 1] ^ v[:, 3])


class SpanBatchBuilder:
    """Row-append builder producing padded SpanBatches.

    The write-path staging area: receivers append decoded spans, services cut
    a batch per push (distributor) or per tick (generator), analogous to the
    rebatching in `requestsByTraceID` but emitting tensors instead of
    per-trace proto slices.
    """

    def __init__(self, interner: StringInterner | None = None,
                 max_span_attrs: int = 64, max_res_attrs: int = 32) -> None:
        self.interner = interner if interner is not None else StringInterner()
        self.max_span_attrs = max_span_attrs
        self.max_res_attrs = max_res_attrs
        self._rows: list[tuple] = []

    def __len__(self) -> int:
        return len(self._rows)

    def _code_attrs(self, attrs: dict[str, Any] | None, cap: int):
        out = []
        if attrs:
            it = self.interner
            for k, v in attrs.items():
                if len(out) >= cap:
                    break  # truncation, like distributor attr limits
                kid = it.intern(k)
                if isinstance(v, bool):
                    out.append((kid, INVALID_ID, 1.0 if v else 0.0, ATTR_BOOL))
                elif isinstance(v, (int, np.integer)):
                    out.append((kid, INVALID_ID, float(v), ATTR_INT))
                elif isinstance(v, (float, np.floating)):
                    out.append((kid, INVALID_ID, float(v), ATTR_DOUBLE))
                else:
                    out.append((kid, it.intern(str(v)), 0.0, ATTR_STRING))
        return out

    def append(
        self,
        *,
        trace_id: bytes,
        span_id: bytes,
        parent_span_id: bytes = b"",
        name: str = "",
        service: str = "",
        kind: int = KIND_UNSPECIFIED,
        status_code: int = STATUS_UNSET,
        status_message: str = "",
        start_unix_nano: int = 0,
        end_unix_nano: int = 0,
        attrs: dict[str, Any] | None = None,
        res_attrs: dict[str, Any] | None = None,
        events: list | None = None,   # accepted, not columnized: SpanBatch
        links: list | None = None,    # is the metrics plane; the block
    ) -> None:                        # schema persists events/links
        it = self.interner
        self._rows.append((
            trace_id.ljust(16, b"\0")[:16],
            span_id.ljust(8, b"\0")[:8],
            parent_span_id.ljust(8, b"\0")[:8],
            it.intern(name),
            it.intern(service),
            kind,
            status_code,
            it.intern(status_message) if status_message else INVALID_ID,
            start_unix_nano,
            end_unix_nano,
            self._code_attrs(attrs, self.max_span_attrs),
            self._code_attrs(res_attrs, self.max_res_attrs),
        ))

    def build(self) -> SpanBatch:
        rows = self._rows
        self._rows = []
        n = len(rows)
        cap = _pad_rows(max(n, 1))
        k = _pad_width(max((len(r[10]) for r in rows), default=0))
        r_ = _pad_width(max((len(r[11]) for r in rows), default=0))

        def attr_mats(col: int, width: int):
            key = np.full((cap, width), INVALID_ID, np.int32)
            sval = np.full((cap, width), INVALID_ID, np.int32)
            fval = np.zeros((cap, width), np.float32)
            typ = np.zeros((cap, width), np.int8)
            for i, row in enumerate(rows):
                for j, (kk, sv, fv, tt) in enumerate(row[col]):
                    key[i, j], sval[i, j], fval[i, j], typ[i, j] = kk, sv, fv, tt
            return key, sval, fval, typ

        sk, ss, sf, st = attr_mats(10, k)
        rk, rs, rf, rt = attr_mats(11, r_)
        u8 = lambda col, w: np.frombuffer(
            b"".join(r[col] for r in rows) or b"", dtype=np.uint8
        ).reshape(n, w) if n else np.zeros((0, w), np.uint8)

        def pad2(a, w):
            out = np.zeros((cap, w), np.uint8)
            out[:n] = a
            return out

        i32 = lambda col: np.pad(np.array([r[col] for r in rows], np.int32), (0, cap - n))
        i64 = lambda col: np.pad(np.array([r[col] for r in rows], np.int64), (0, cap - n))
        valid = np.zeros(cap, bool)
        valid[:n] = True
        return SpanBatch(
            n=n,
            trace_id=pad2(u8(0, 16), 16),
            span_id=pad2(u8(1, 8), 8),
            parent_span_id=pad2(u8(2, 8), 8),
            name_id=i32(3), service_id=i32(4), kind=i32(5),
            status_code=i32(6), status_message_id=i32(7),
            start_unix_nano=i64(8), end_unix_nano=i64(9),
            span_attr_key=sk, span_attr_sval=ss, span_attr_fval=sf, span_attr_typ=st,
            res_attr_key=rk, res_attr_sval=rs, res_attr_fval=rf, res_attr_typ=rt,
            valid=valid,
            interner=self.interner,
        )


def synthetic_batch(
    n: int,
    *,
    interner: StringInterner | None = None,
    n_services: int = 10,
    n_names: int = 50,
    error_rate: float = 0.02,
    seed: int = 0,
) -> SpanBatch:
    """Fast vectorized synthetic batch for tests and benches (k6-style load)."""
    rng = np.random.default_rng(seed)
    it = interner if interner is not None else StringInterner()
    svc_ids = it.intern_many([f"service-{i}" for i in range(n_services)])
    name_ids = it.intern_many([f"op-{i}" for i in range(n_names)])
    cap = _pad_rows(max(n, 1))
    valid = np.zeros(cap, bool)
    valid[:n] = True
    start = np.zeros(cap, np.int64)
    start[:n] = 1_700_000_000_000_000_000 + rng.integers(0, 60_000_000_000, n)
    dur = np.zeros(cap, np.int64)
    dur[:n] = rng.lognormal(mean=17.0, sigma=1.5, size=n).astype(np.int64)  # ~24ms median
    e = np.zeros((cap, 0))
    return SpanBatch(
        n=n,
        trace_id=rng.integers(0, 256, (cap, 16), dtype=np.uint8),
        span_id=rng.integers(0, 256, (cap, 8), dtype=np.uint8),
        parent_span_id=np.zeros((cap, 8), np.uint8),
        name_id=np.where(valid, name_ids[rng.integers(0, n_names, cap)], 0).astype(np.int32),
        service_id=np.where(valid, svc_ids[rng.integers(0, n_services, cap)], 0).astype(np.int32),
        kind=np.full(cap, KIND_SERVER, np.int32),
        status_code=np.where(rng.random(cap) < error_rate, STATUS_ERROR, STATUS_UNSET).astype(np.int32),
        status_message_id=np.full(cap, INVALID_ID, np.int32),
        start_unix_nano=start,
        end_unix_nano=start + dur,
        span_attr_key=np.zeros((cap, 0), np.int32),
        span_attr_sval=np.zeros((cap, 0), np.int32),
        span_attr_fval=np.zeros((cap, 0), np.float32),
        span_attr_typ=np.zeros((cap, 0), np.int8),
        res_attr_key=np.zeros((cap, 0), np.int32),
        res_attr_sval=np.zeros((cap, 0), np.int32),
        res_attr_fval=np.zeros((cap, 0), np.float32),
        res_attr_typ=np.zeros((cap, 0), np.int8),
        valid=valid,
        interner=it,
    )
