"""Trace combining/dedup — analog of `pkg/model/trace/combine.go`.

RF3 writes mean the same trace (and often the same spans) arrive from up to
three ingesters, and compaction merges blocks that may both hold a trace.
`combine_spans` merges span lists keeping one span per span-id (first wins,
matching the reference's CombineTraceProtos semantics), and `sort_spans`
orders by start time like `trace/sort.go`.
"""

from __future__ import annotations

from typing import Iterable


def combine_spans(*span_lists: Iterable[dict]) -> list[dict]:
    seen: set[bytes] = set()
    out: list[dict] = []
    for spans in span_lists:
        for s in spans:
            sid = bytes(s.get("span_id", b""))
            if sid in seen:
                continue
            seen.add(sid)
            out.append(s)
    return out


def sort_spans(spans: list[dict]) -> list[dict]:
    return sorted(spans, key=lambda s: int(s.get("start_unix_nano", 0)))


def trace_range(spans: Iterable[dict]) -> tuple[int, int]:
    """(min start, max end) nanos over the trace's spans."""
    start = None
    end = None
    for s in spans:
        st = int(s.get("start_unix_nano", 0))
        en = int(s.get("end_unix_nano", st))
        start = st if start is None else min(start, st)
        end = en if end is None else max(end, en)
    return start or 0, end or 0
