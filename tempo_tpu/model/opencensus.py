"""OpenCensus agent trace protocol → span dicts.

The last receiver protocol of the reference's shim
(`modules/distributor/receiver/shim.go:165-171` "opencensus"): legacy OC
libraries stream `opencensus.proto.agent.trace.v1.TraceService/Export`
requests — Node + Resource on the first message of a stream, spans on
every message. Hand-rolled over proto_wire like the other wire models.

Field mapping follows the collector's opencensus translator: OC kind
SERVER/CLIENT → OTel SERVER/CLIENT; Status present with code 0 → OK,
nonzero → ERROR, absent → UNSET; Node.service_info.name + Resource labels
become the resource.
"""

from __future__ import annotations

import struct
from typing import Any

from tempo_tpu.model import proto_wire as pw

# OC SpanKind → OTel span kind
_KIND = {0: 0, 1: 2, 2: 3}


def _trunc_str(buf) -> str:
    """TruncatableString{value=1}."""
    for fnum, wt, val in pw.iter_fields(bytes(buf)):
        if fnum == 1 and wt == 2:
            return bytes(val).decode("utf-8", "replace")
    return ""


def _ts_ns(buf) -> int:
    sec = nanos = 0
    for fnum, wt, val in pw.iter_fields(bytes(buf)):
        if fnum == 1 and wt == 0:
            sec = val
        elif fnum == 2 and wt == 0:
            nanos = val
    return sec * 1_000_000_000 + nanos


def _attr_value(buf) -> Any:
    for fnum, wt, val in pw.iter_fields(bytes(buf)):
        if fnum == 1 and wt == 2:
            return _trunc_str(val)
        if fnum == 2 and wt == 0:
            return val - (1 << 64) if val >= (1 << 63) else val
        if fnum == 3 and wt == 0:
            return bool(val)
        if fnum == 4 and wt == 1:
            return pw.f64(val)
    return ""


def _attributes(buf) -> dict:
    """Attributes{attribute_map=1 (map<string, AttributeValue>)}."""
    out: dict[str, Any] = {}
    for fnum, wt, val in pw.iter_fields(bytes(buf)):
        if fnum != 1 or wt != 2:
            continue
        key, av = "", None
        for efn, ewt, ev in pw.iter_fields(bytes(val)):
            if efn == 1 and ewt == 2:
                key = bytes(ev).decode("utf-8", "replace")
            elif efn == 2 and ewt == 2:
                av = _attr_value(ev)
        if key:
            out[key] = av if av is not None else ""
    return out


def node_service(buf: bytes) -> str:
    """Node{service_info=3 ServiceInfo{name=1}}."""
    for fnum, wt, val in pw.iter_fields(bytes(buf)):
        if fnum == 3 and wt == 2:
            for sfn, swt, sv in pw.iter_fields(bytes(val)):
                if sfn == 1 and swt == 2:
                    return bytes(sv).decode("utf-8", "replace")
    return ""


def resource_labels(buf: bytes) -> dict:
    """Resource{type=1, labels=2 map<string,string>}."""
    out: dict[str, str] = {}
    for fnum, wt, val in pw.iter_fields(bytes(buf)):
        if fnum != 2 or wt != 2:
            continue
        k = v = ""
        for efn, ewt, ev in pw.iter_fields(bytes(val)):
            if efn == 1 and ewt == 2:
                k = bytes(ev).decode("utf-8", "replace")
            elif efn == 2 and ewt == 2:
                v = bytes(ev).decode("utf-8", "replace")
        if k:
            out[k] = v
    return out


def _oc_span(buf, service: str, res_attrs: dict) -> dict:
    f = pw.decode_fields(bytes(buf))
    first = lambda n: bytes(f[n][0]) if f.get(n) else b""
    status_code = 0
    if f.get(13):                         # Status{code=1, message=2}
        code = 0
        for sfn, swt, sv in pw.iter_fields(first(13)):
            if sfn == 1 and swt == 0:
                code = sv
        status_code = 1 if code == 0 else 2
    kind = 0
    for fnum, wt, val in pw.iter_fields(bytes(buf)):
        if fnum == 6 and wt == 0:
            kind = _KIND.get(val, 0)
    span_res = dict(res_attrs)
    span_service = service
    if f.get(14):                         # per-span Resource override
        labels = resource_labels(first(14))
        span_res.update(labels)
        span_service = labels.get("service.name", service)
    span_res.setdefault("service.name", span_service)
    start = _ts_ns(first(7)) if f.get(7) else 0
    end = _ts_ns(first(8)) if f.get(8) else start
    return {
        "trace_id": first(1), "span_id": first(2),
        "parent_span_id": first(4),
        "name": _trunc_str(first(5)) if f.get(5) else "",
        "service": span_service, "kind": kind,
        "status_code": status_code,
        "start_unix_nano": start, "end_unix_nano": end,
        "attrs": _attributes(first(9)) if f.get(9) else {},
        "res_attrs": span_res,
    }


def spans_from_opencensus(data: bytes, service: str = "",
                          res_attrs: "dict | None" = None
                          ) -> tuple[list[dict], str, dict]:
    """Decode one ExportTraceServiceRequest{node=1, spans=2, resource=3}.

    Returns (spans, service, res_attrs) — node/resource persist across a
    stream, so the caller threads the previous values back in for
    messages that omit them. Raises ValueError on malformed bytes.
    """
    try:
        f = pw.decode_fields(data)
        if f.get(1):
            got = node_service(bytes(f[1][0]))
            if got:
                service = got
        res = dict(res_attrs or {})
        if f.get(3):
            res.update(resource_labels(bytes(f[3][0])))
        res.setdefault("service.name", service)
        spans = [_oc_span(b, service, res) for b in f.get(2, [])]
        return spans, service, res
    except (ValueError, struct.error, IndexError, KeyError) as e:
        raise ValueError(f"malformed opencensus payload: {e}") from None


__all__ = ["spans_from_opencensus", "node_service", "resource_labels"]
