"""Jaeger ingest: thrift-over-HTTP collector payloads → span dicts.

The reference hosts a jaeger receiver inside the distributor's OTel shim
(`modules/distributor/receiver/shim.go:165-171`); Jaeger SDK reporters
POST a TBinaryProtocol-encoded `jaeger.thrift` Batch to
`/api/traces` with content-type application/x-thrift. This module is a
from-scratch minimal TBinaryProtocol reader for exactly the structures in
the public jaeger.thrift IDL (Batch/Process/Span/Tag/SpanRef/Log) plus
the OTel semantic mapping (span.kind / error tags → kind/status), the
same translation the jaeger receiver performs before handing ptraces to
the distributor.
"""

from __future__ import annotations

import struct
from typing import Any, Iterator

# thrift TBinaryProtocol type ids
T_STOP, T_BOOL, T_BYTE, T_DOUBLE = 0, 2, 3, 4
T_I16, T_I32, T_I64, T_STRING = 6, 8, 10, 11
T_STRUCT, T_MAP, T_SET, T_LIST = 12, 13, 14, 15

_KIND_FROM_STR = {"unspecified": 0, "internal": 1, "server": 2,
                  "client": 3, "producer": 4, "consumer": 5}


class _R:
    """Cursor over TBinaryProtocol bytes."""

    __slots__ = ("b", "i")

    def __init__(self, b: bytes):
        self.b = b
        self.i = 0

    def u8(self) -> int:
        v = self.b[self.i]
        self.i += 1
        return v

    def i16(self) -> int:
        v = struct.unpack_from(">h", self.b, self.i)[0]
        self.i += 2
        return v

    def i32(self) -> int:
        v = struct.unpack_from(">i", self.b, self.i)[0]
        self.i += 4
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.b, self.i)[0]
        self.i += 8
        return v

    def f64(self) -> float:
        v = struct.unpack_from(">d", self.b, self.i)[0]
        self.i += 8
        return v

    def raw(self) -> bytes:
        n = self.i32()
        if n < 0 or self.i + n > len(self.b):
            raise ValueError("thrift string overruns buffer")
        v = self.b[self.i:self.i + n]
        self.i += n
        return v

    # minimum wire bytes per element of each type (guards collection
    # counts: an attacker-supplied count must fit the remaining buffer
    # before any loop runs, or a tiny payload spins for billions of steps)
    _MIN = {T_BOOL: 1, T_BYTE: 1, T_DOUBLE: 8, T_I16: 2, T_I32: 4,
            T_I64: 8, T_STRING: 4, T_STRUCT: 1, T_MAP: 6, T_SET: 5,
            T_LIST: 5}

    def count(self, elem_type: int) -> int:
        n = self.i32()
        per = self._MIN.get(elem_type)
        if per is None:
            raise ValueError(f"unknown thrift type {elem_type}")
        if n < 0 or n * per > len(self.b) - self.i:
            raise ValueError("thrift collection count overruns buffer")
        return n

    def skip(self, t: int, depth: int = 0) -> None:
        if depth > 64:
            # hostile nesting must be a 400, not a RecursionError/500
            raise ValueError("thrift nesting too deep")
        if t == T_BOOL or t == T_BYTE:
            self.i += 1
        elif t == T_I16:
            self.i += 2
        elif t == T_I32:
            self.i += 4
        elif t in (T_I64, T_DOUBLE):
            self.i += 8
        elif t == T_STRING:
            self.raw()
        elif t == T_STRUCT:
            while True:
                ft = self.u8()
                if ft == T_STOP:
                    break
                self.i16()
                self.skip(ft, depth + 1)
        elif t in (T_LIST, T_SET):
            et = self.u8()
            for _ in range(self.count(et)):
                self.skip(et, depth + 1)
        elif t == T_MAP:
            kt, vt = self.u8(), self.u8()
            n = self.count(kt)
            if n * self._MIN[vt] > len(self.b) - self.i:
                raise ValueError("thrift map count overruns buffer")
            for _ in range(n):
                self.skip(kt, depth + 1)
                self.skip(vt, depth + 1)
        else:
            raise ValueError(f"unknown thrift type {t}")

    def fields(self) -> Iterator[tuple[int, int]]:
        """Yield (field_id, type) until STOP; caller reads or skips."""
        while True:
            ft = self.u8()
            if ft == T_STOP:
                return
            yield self.i16(), ft


def _read_tag(r: _R) -> tuple[str, Any]:
    key, vtype = "", 0
    vstr: bytes = b""
    vdouble, vbool, vlong = 0.0, False, 0
    vbin: bytes = b""
    for fid, ft in r.fields():
        if fid == 1 and ft == T_STRING:
            key = r.raw().decode("utf-8", "replace")
        elif fid == 2 and ft == T_I32:
            vtype = r.i32()
        elif fid == 3 and ft == T_STRING:
            vstr = r.raw()
        elif fid == 4 and ft == T_DOUBLE:
            vdouble = r.f64()
        elif fid == 5 and ft == T_BOOL:
            vbool = r.u8() != 0
        elif fid == 6 and ft == T_I64:
            vlong = r.i64()
        elif fid == 7 and ft == T_STRING:
            vbin = r.raw()
        else:
            r.skip(ft)
    val: Any
    if vtype == 0:
        val = vstr.decode("utf-8", "replace")
    elif vtype == 1:
        val = vdouble
    elif vtype == 2:
        val = vbool
    elif vtype == 3:
        val = vlong
    else:
        val = vbin
    return key, val


def _read_tags(r: _R) -> dict[str, Any]:
    et = r.u8()
    n = r.count(et)
    out: dict[str, Any] = {}
    for _ in range(n):
        if et == T_STRUCT:
            k, v = _read_tag(r)
            out[k] = v
        else:
            r.skip(et)
    return out


def _intrinsics_from_tags(attrs: dict) -> tuple[int, int]:
    """(kind, status_code) from OTel-mapped jaeger tags — span.kind is
    POPPED from attrs; error/otel.status_code stay (the translator keeps
    them). Shared by the thrift and api_v2-proto decoders so the two
    receiver protocols can never diverge on the mapping."""
    kind = 0
    sk = attrs.pop("span.kind", None)
    if isinstance(sk, str):
        kind = _KIND_FROM_STR.get(sk.lower(), 0)
    status_code = 0
    err = attrs.get("error")
    if err is True or (isinstance(err, str) and err.lower() == "true"):
        status_code = 2            # STATUS_CODE_ERROR, like the translator
    otel_status = attrs.get("otel.status_code")
    if isinstance(otel_status, str):
        status_code = {"OK": 1, "ERROR": 2}.get(otel_status.upper(),
                                                status_code)
    return kind, status_code



def _span_dict(tid_hi: int, tid_lo: int, sid: int, psid: int, name: str,
               start_us: int, dur_us: int, attrs: dict) -> dict:
    """Shared span-dict epilogue for the thrift decoders (binary +
    compact agent — the api_v2 proto path carries ids as bytes and times
    in ns, so it shares only `_intrinsics_from_tags`): one place owns the
    id packing and the µs→ns mapping, so the wire forms cannot diverge."""
    kind, status_code = _intrinsics_from_tags(attrs)
    u64 = lambda v: v & ((1 << 64) - 1)
    start_ns = start_us * 1000
    return {
        "trace_id": struct.pack(">QQ", u64(tid_hi), u64(tid_lo)),
        "span_id": struct.pack(">Q", u64(sid)),
        "parent_span_id": struct.pack(">Q", u64(psid)) if psid else b"",
        "name": name,
        "service": "",
        "kind": kind,
        "status_code": status_code,
        "start_unix_nano": start_ns,
        "end_unix_nano": start_ns + dur_us * 1000,
        "attrs": attrs,
        "res_attrs": None,
    }


def _patch_batch(out: list, service: str, res_attrs: dict) -> list:
    """Apply the Batch's Process (service + resource tags) to its spans."""
    res_attrs = dict(res_attrs)
    res_attrs.setdefault("service.name", service)
    for s in out:
        s["service"] = service
        s["res_attrs"] = res_attrs
    return out


def _read_span(r: _R) -> dict:
    """One jaeger.thrift Span → span dict (service/res_attrs patched in by
    the caller once the Process struct is known)."""
    tid_lo = tid_hi = sid = psid = 0
    name = ""
    start_us = dur_us = 0
    attrs: dict[str, Any] = {}
    for fid, ft in r.fields():
        if fid == 1 and ft == T_I64:
            tid_lo = r.i64()
        elif fid == 2 and ft == T_I64:
            tid_hi = r.i64()
        elif fid == 3 and ft == T_I64:
            sid = r.i64()
        elif fid == 4 and ft == T_I64:
            psid = r.i64()
        elif fid == 5 and ft == T_STRING:
            name = r.raw().decode("utf-8", "replace")
        elif fid == 8 and ft == T_I64:
            start_us = r.i64()
        elif fid == 9 and ft == T_I64:
            dur_us = r.i64()
        elif fid == 10 and ft == T_LIST:
            attrs = _read_tags(r)
        else:
            r.skip(ft)

    return _span_dict(tid_hi, tid_lo, sid, psid, name, start_us, dur_us,
                      attrs)


def spans_from_jaeger_thrift(data: bytes) -> list[dict]:
    """Decode one TBinaryProtocol `jaeger.thrift` Batch into span dicts.

    One pass: spans decode as encountered, and the Process struct
    (service name + resource tags) patches them afterwards, so a
    Process-after-spans field order costs nothing extra. Raises ValueError
    on malformed bytes (the receiver maps it to 400)."""
    try:
        r = _R(data)
        service = ""
        res_attrs: dict[str, Any] = {}
        out: list[dict] = []
        for fid, ft in r.fields():
            if fid == 1 and ft == T_STRUCT:       # Process
                for pfid, pft in r.fields():
                    if pfid == 1 and pft == T_STRING:
                        service = r.raw().decode("utf-8", "replace")
                    elif pfid == 2 and pft == T_LIST:
                        res_attrs = _read_tags(r)
                    else:
                        r.skip(pft)
            elif fid == 2 and ft == T_LIST:       # spans
                et = r.u8()
                n = r.count(et)
                if n and et != T_STRUCT:
                    raise ValueError("Batch.spans must hold structs")
                for _ in range(n):
                    out.append(_read_span(r))
            else:
                r.skip(ft)
        return _patch_batch(out, service, res_attrs)
    except (struct.error, IndexError) as e:
        raise ValueError(f"malformed jaeger thrift payload: {e}") from None


# -- jaeger api_v2 protobuf (model.proto) -----------------------------------
#
# The gRPC collector variant (`jaeger.api_v2.CollectorService/PostSpans`,
# ref `modules/distributor/receiver/shim.go:165-171` jaeger receiver
# protocols). Same span-dict mapping as the thrift path above; the wire is
# protobuf Batch{spans=1, process=2} instead of TBinaryProtocol.

def _pb_ts_ns(buf: bytes) -> int:
    """Timestamp/Duration {seconds=1, nanos=2} → nanoseconds."""
    from tempo_tpu.model.proto_wire import iter_fields

    sec = nanos = 0
    for fnum, wt, val in iter_fields(buf):
        if fnum == 1 and wt == 0:
            sec = val
        elif fnum == 2 and wt == 0:
            nanos = val
    return sec * 1_000_000_000 + nanos


def _pb_keyvalues(bufs: list) -> dict:
    """repeated model.KeyValue → attrs dict (typed like the thrift tags)."""
    from tempo_tpu.model.proto_wire import f64, iter_fields

    out: dict[str, Any] = {}
    for kv in bufs:
        key = ""
        vtype = 0
        vals: dict[int, Any] = {}
        for fnum, wt, val in iter_fields(kv):
            if fnum == 1 and wt == 2:
                key = bytes(val).decode("utf-8", "replace")
            elif fnum == 2 and wt == 0:
                vtype = val
            elif fnum in (3, 7) and wt == 2:
                vals[fnum] = val
            elif fnum in (4, 5) and wt == 0:
                vals[fnum] = val
            elif fnum == 6 and wt == 1:
                vals[fnum] = f64(val)
        if not key:
            continue
        if vtype == 1:
            out[key] = bool(vals.get(4, 0))
        elif vtype == 2:
            v = vals.get(5, 0)
            out[key] = v - (1 << 64) if v >= (1 << 63) else v
        elif vtype == 3:
            out[key] = float(vals.get(6, 0.0))
        elif vtype == 4:
            out[key] = bytes(vals.get(7) or b"").hex()
        else:
            out[key] = bytes(vals.get(3) or b"").decode("utf-8", "replace")
    return out


def _pb_process(buf: bytes) -> tuple[str, dict]:
    from tempo_tpu.model.proto_wire import decode_fields

    f = decode_fields(buf)
    service = bytes(f.get(1, [b""])[0] or b"").decode("utf-8", "replace") \
        if f.get(1) else ""
    return service, _pb_keyvalues(f.get(2, []))


def _pb_span(buf: bytes) -> dict:
    from tempo_tpu.model.proto_wire import decode_fields, iter_fields

    f = decode_fields(buf)
    tid = bytes(f.get(1, [b""])[0] or b"")
    sid = bytes(f.get(2, [b""])[0] or b"")
    name = bytes(f.get(3, [b""])[0] or b"").decode("utf-8", "replace") \
        if f.get(3) else ""
    psid = b""
    for ref in f.get(4, []):
        r_sid = b""
        r_type = 0
        for fnum, wt, val in iter_fields(ref):
            if fnum == 2 and wt == 2:
                r_sid = bytes(val)
            elif fnum == 3 and wt == 0:
                r_type = val
        if r_type == 0 and r_sid:                 # CHILD_OF
            psid = r_sid
    start_ns = _pb_ts_ns(f[6][0]) if f.get(6) else 0
    dur_ns = _pb_ts_ns(f[7][0]) if f.get(7) else 0
    attrs = _pb_keyvalues(f.get(8, []))
    service = ""
    res_attrs: "dict | None" = None
    if f.get(10):                                 # per-span Process override
        service, tags = _pb_process(f[10][0])
        res_attrs = dict(tags)
        res_attrs.setdefault("service.name", service)

    kind, status_code = _intrinsics_from_tags(attrs)
    return {
        "trace_id": tid, "span_id": sid,
        "parent_span_id": psid,
        "name": name, "service": service, "kind": kind,
        "status_code": status_code,
        "start_unix_nano": start_ns,
        "end_unix_nano": start_ns + dur_ns,
        "attrs": attrs, "res_attrs": res_attrs,
    }


def spans_from_jaeger_proto(data: bytes, wrapped: bool = True) -> list[dict]:
    """Decode one api_v2 `PostSpansRequest` (wrapped=True; its field 1 is
    the Batch) or a bare `Batch` into span dicts. Raises ValueError on
    malformed bytes."""
    from tempo_tpu.model.proto_wire import decode_fields

    try:
        f = decode_fields(data)
        if wrapped:
            f = decode_fields(f[1][0]) if f.get(1) else {}
        service = ""
        res_attrs: dict[str, Any] = {}
        if f.get(2):
            service, res_attrs = _pb_process(f[2][0])
        out = [_pb_span(b) for b in f.get(1, [])]
        base = dict(res_attrs)
        base.setdefault("service.name", service)
        for s in out:
            if s["res_attrs"] is None:            # batch Process applies
                s["service"] = service
                s["res_attrs"] = base
            elif not s["service"]:
                s["service"] = s["res_attrs"].get("service.name", "")
        return out
    except (ValueError, TypeError, struct.error, IndexError, KeyError) as e:
        # TypeError: a message-typed field encoded as a varint decodes to
        # int and memoryview()/iter_fields() reject it
        raise ValueError(f"malformed jaeger proto payload: {e}") from None


__all__ = ["spans_from_jaeger_thrift", "spans_from_jaeger_proto",
           "spans_from_jaeger_agent"]


# -- jaeger agent UDP (TCompactProtocol Agent.emitBatch) ---------------------
#
# The deprecated-but-still-deployed jaeger agent path: clients fire
# one-way `Agent.emitBatch(Batch)` calls as UDP datagrams on port 6831,
# encoded with the thrift COMPACT protocol (ref
# `modules/distributor/receiver/shim.go:165-171` jaeger protocols map).
# Same span-dict mapping as the binary/protobuf decoders above — the
# three jaeger wire forms cannot diverge because they share
# `_intrinsics_from_tags` and the field semantics below.

_C_BOOL_TRUE, _C_BOOL_FALSE = 1, 2
_C_BYTE, _C_I16, _C_I32, _C_I64, _C_DOUBLE = 3, 4, 5, 6, 7
_C_BINARY, _C_LIST, _C_SET, _C_MAP, _C_STRUCT = 8, 9, 10, 11, 12


class _CR:
    """Cursor over TCompactProtocol bytes."""

    __slots__ = ("b", "i")

    def __init__(self, b: bytes):
        self.b = b
        self.i = 0

    def u8(self) -> int:
        v = self.b[self.i]
        self.i += 1
        return v

    def uvarint(self) -> int:
        out = shift = 0
        while True:
            byte = self.b[self.i]
            self.i += 1
            out |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return out
            shift += 7
            if shift > 70:
                raise ValueError("varint too long")

    def zigzag(self) -> int:
        v = self.uvarint()
        return (v >> 1) ^ -(v & 1)

    def f64(self) -> float:
        # compact doubles are little-endian (the thrift library quirk —
        # opposite of the binary protocol)
        v = struct.unpack_from("<d", self.b, self.i)[0]
        self.i += 8
        return v

    def raw(self) -> bytes:
        n = self.uvarint()
        if self.i + n > len(self.b):
            raise ValueError("binary field overruns datagram")
        v = self.b[self.i:self.i + n]
        self.i += n
        return v

    def fields(self):
        """Yield (field id, compact type) until STOP; short-form ids are
        delta-encoded against the previous field of THIS struct."""
        last = 0
        while True:
            h = self.u8()
            if h == 0:
                return
            delta, ctype = h >> 4, h & 0x0F
            fid = last + delta if delta else self.zigzag()
            last = fid
            yield fid, ctype

    def check_count(self, n: int, elem_type: int, pairs: bool = False
                    ) -> int:
        """Bound an attacker-supplied collection count by the remaining
        datagram bytes BEFORE any loop runs — fixed-size skips (`i += 1`)
        never touch the buffer, so a crafted 13-byte datagram claiming
        2^40 elements would otherwise spin the receiver thread forever
        (remote unauthenticated DoS)."""
        per = 8 if elem_type == _C_DOUBLE else 1
        if pairs:
            per += 1                     # a map entry is >= 2 wire bytes
        if n < 0 or n * per > len(self.b) - self.i:
            raise ValueError("compact collection count overruns datagram")
        return n

    def list_header(self) -> tuple[int, int]:
        h = self.u8()
        n, et = h >> 4, h & 0x0F
        if n == 15:
            n = self.uvarint()
        return self.check_count(n, et), et

    def skip(self, ctype: int, depth: int = 0) -> None:
        if depth > 32:
            raise ValueError("nesting too deep")
        if ctype in (_C_BOOL_TRUE, _C_BOOL_FALSE):
            return                       # value lives in the field header
        if ctype == _C_BYTE:
            self.i += 1
        elif ctype in (_C_I16, _C_I32, _C_I64):
            self.zigzag()
        elif ctype == _C_DOUBLE:
            self.i += 8
        elif ctype == _C_BINARY:
            self.raw()
        elif ctype in (_C_LIST, _C_SET):
            n, et = self.list_header()
            for _ in range(n):
                self.skip_elem(et, depth + 1)
        elif ctype == _C_MAP:
            n = self.uvarint()
            if n:
                kv = self.u8()
                self.check_count(n, kv & 0x0F, pairs=True)
                for _ in range(n):
                    self.skip_elem(kv >> 4, depth + 1)
                    self.skip_elem(kv & 0x0F, depth + 1)
        elif ctype == _C_STRUCT:
            for _fid, ft in self.fields():
                self.skip(ft, depth + 1)
        else:
            raise ValueError(f"bad compact type {ctype}")

    def skip_elem(self, et: int, depth: int = 0) -> None:
        # list/set/map elements: bools take one byte (unlike field bools)
        if et in (_C_BOOL_TRUE, _C_BOOL_FALSE):
            self.i += 1
        else:
            self.skip(et, depth)


def _c_read_tag(r: _CR) -> tuple[str, Any]:
    key, vtype = "", 0
    vstr: bytes = b""
    vdouble, vbool, vlong = 0.0, False, 0
    vbin: bytes = b""
    for fid, ft in r.fields():
        if fid == 1 and ft == _C_BINARY:
            key = r.raw().decode("utf-8", "replace")
        elif fid == 2 and ft == _C_I32:
            vtype = r.zigzag()
        elif fid == 3 and ft == _C_BINARY:
            vstr = r.raw()
        elif fid == 4 and ft == _C_DOUBLE:
            vdouble = r.f64()
        elif fid == 5 and ft in (_C_BOOL_TRUE, _C_BOOL_FALSE):
            vbool = ft == _C_BOOL_TRUE
        elif fid == 6 and ft == _C_I64:
            vlong = r.zigzag()
        elif fid == 7 and ft == _C_BINARY:
            vbin = r.raw()
        else:
            r.skip(ft)
    val: Any
    if vtype == 0:
        val = vstr.decode("utf-8", "replace")
    elif vtype == 1:
        val = vdouble
    elif vtype == 2:
        val = vbool
    elif vtype == 3:
        val = vlong
    else:
        val = vbin
    return key, val


def _c_read_tag_list(r: _CR) -> dict[str, Any]:
    n, et = r.list_header()
    out: dict[str, Any] = {}
    for _ in range(n):
        if et == _C_STRUCT:
            k, v = _c_read_tag(r)
            out[k] = v
        else:
            r.skip_elem(et)
    return out


def _c_read_span(r: _CR) -> dict:
    tid_lo = tid_hi = sid = psid = 0
    name = ""
    start_us = dur_us = 0
    attrs: dict[str, Any] = {}
    for fid, ft in r.fields():
        if fid == 1 and ft == _C_I64:
            tid_lo = r.zigzag()
        elif fid == 2 and ft == _C_I64:
            tid_hi = r.zigzag()
        elif fid == 3 and ft == _C_I64:
            sid = r.zigzag()
        elif fid == 4 and ft == _C_I64:
            psid = r.zigzag()
        elif fid == 5 and ft == _C_BINARY:
            name = r.raw().decode("utf-8", "replace")
        elif fid == 8 and ft == _C_I64:
            start_us = r.zigzag()
        elif fid == 9 and ft == _C_I64:
            dur_us = r.zigzag()
        elif fid == 10 and ft == _C_LIST:
            attrs = _c_read_tag_list(r)
        else:
            r.skip(ft)
    return _span_dict(tid_hi, tid_lo, sid, psid, name, start_us, dur_us,
                      attrs)


def spans_from_jaeger_agent(datagram: bytes) -> list[dict]:
    """Decode one UDP `Agent.emitBatch` datagram (compact protocol) into
    span dicts. Raises ValueError on malformed bytes (the receiver counts
    and drops — UDP has nobody to answer)."""
    try:
        r = _CR(datagram)
        if r.u8() != 0x82:
            raise ValueError("not a compact-protocol message")
        vt = r.u8()
        if (vt & 0x1F) != 1:
            raise ValueError("unsupported compact version")
        if (vt >> 5) not in (1, 4):          # CALL / ONEWAY
            raise ValueError("not a call message")
        r.uvarint()                          # seqid
        if r.raw() != b"emitBatch":
            raise ValueError("not an emitBatch call")
        service = ""
        res_attrs: dict[str, Any] = {}
        out: list[dict] = []
        for fid, ft in r.fields():           # Agent.emitBatch args
            if fid == 1 and ft == _C_STRUCT:     # Batch
                for bfid, bft in r.fields():
                    if bfid == 1 and bft == _C_STRUCT:   # Process
                        for pfid, pft in r.fields():
                            if pfid == 1 and pft == _C_BINARY:
                                service = r.raw().decode("utf-8", "replace")
                            elif pfid == 2 and pft == _C_LIST:
                                res_attrs = _c_read_tag_list(r)
                            else:
                                r.skip(pft)
                    elif bfid == 2 and bft == _C_LIST:   # spans
                        n, et = r.list_header()
                        if n and et != _C_STRUCT:
                            raise ValueError("Batch.spans must hold structs")
                        for _ in range(n):
                            out.append(_c_read_span(r))
                    else:
                        r.skip(bft)
            else:
                r.skip(ft)
        return _patch_batch(out, service, res_attrs)
    except (struct.error, IndexError) as e:
        raise ValueError(f"malformed jaeger agent datagram: {e}") from None
