"""Vectorized OTLP protobuf → SpanBatch staging (the ingest hot path).

The per-span route (`spans_from_otlp_proto` → `SpanBatchBuilder.append`)
pays Python dict+append work per span — fine for the distributor's
regroup/validate path, ruinous for sustained generator ingest (VERDICT r1
weak #7). Here the whole decode runs in the C++ staging kernel
(`native.otlp_stage`): one pass over the wire bytes emits fixed columns
AND intern ids (names, services, attr keys/values are dictionary-encoded
inside C++, see native.cpp Interner); numpy only pads and scatters the id
columns. Python touches per-span data exactly zero times on this path —
only rare non-scalar AnyValues cross back for stringification.

Reference anchor: this is the TPU-era `requestsByTraceID` + PushSpans
staging (`modules/distributor/distributor.go:694-801`,
`modules/generator/generator.go:275`) — the reference walks protos span by
span; here one C scan emits interned columns and numpy finishes the job.
"""

from __future__ import annotations

import numpy as np

from tempo_tpu.model.interner import INVALID_ID, StringInterner
from tempo_tpu.model.span_batch import (
    ATTR_STRING,
    SpanBatch,
    SpanBatchBuilder,
    _pad_rows,
    _pad_width,
)

_MAX_SPAN_ATTRS = 64
_MAX_RES_ATTRS = 32


def batch_from_otlp(data: bytes, interner: StringInterner,
                    return_sizes: bool = False,
                    include_span_attrs: bool = True,
                    include_res_attrs: bool = True,
                    trusted: bool = False):
    """OTLP ExportTraceServiceRequest bytes → SpanBatch.

    Uses the one-pass C++ staging kernel when the native layer is
    available; otherwise the per-span decoder + builder (identical output
    contract, modulo the duplicate-attr-key note on `_batch_from_staged`).
    With `return_sizes` also returns [cap] f32 wire bytes per span for the
    size_total subprocessor (`spanmetrics.go:27-31`; zeros on the fallback
    path, which does not track wire offsets).

    `include_*_attrs=False` skips materializing that attr matrix (the
    columns come back 0-wide): callers whose processors read only
    intrinsic dimensions — the default spanmetrics config — drop a third
    of the staging work. service.name extraction is unaffected.
    """
    from tempo_tpu import native

    nat = interner.native_handle() if hasattr(interner, "native_handle") \
        else None
    if nat is not None:
        staged = native.otlp_stage(nat, data,
                                   skip_span_attrs=not include_span_attrs,
                                   trust_attrs=trusted)
        if staged is not None:
            return _batch_from_staged(data, interner, staged, return_sizes,
                                      include_span_attrs, include_res_attrs)

    from tempo_tpu.model.otlp import spans_from_otlp_proto

    b = SpanBatchBuilder(interner)
    for s in spans_from_otlp_proto(data):
        b.append(**s)
    sb = b.build()
    if return_sizes:
        return sb, np.zeros(sb.capacity, np.float32)
    return sb


def _batch_from_staged(data: bytes, interner: StringInterner, staged,
                       return_sizes: bool,
                       include_span_attrs: bool = True,
                       include_res_attrs: bool = True):
    """C++-staged records → SpanBatch: numpy does only padding/scatter.

    Known divergence from the dict path: duplicate attribute keys within
    one scope keep one column per occurrence instead of last-wins dict
    semantics (`attr_sval_column` reads the first)."""
    from tempo_tpu.model.otlp import _pb_anyvalue

    spans, sattrs, rattrs, res = staged
    interner.sync()                      # mirror ids created in C++
    n = len(spans)
    cap = _pad_rows(max(n, 1))
    empty_id = interner.intern("")

    name_id = np.full(cap, INVALID_ID, np.int32)
    sm_id = np.full(cap, INVALID_ID, np.int32)
    service_id = np.full(cap, INVALID_ID, np.int32)
    kind = np.zeros(cap, np.int32)
    status_code = np.zeros(cap, np.int32)
    start = np.zeros(cap, np.int64)
    end = np.zeros(cap, np.int64)
    tid = np.zeros((cap, 16), np.uint8)
    sid = np.zeros((cap, 8), np.uint8)
    pid = np.zeros((cap, 8), np.uint8)
    if n:
        name_id[:n] = spans["name_id"]
        sm = spans["status_msg_id"]
        # builder semantics: empty status message → INVALID_ID
        sm_id[:n] = np.where((sm < 0) | (sm == empty_id), INVALID_ID, sm)
        kind[:n] = spans["kind"]
        status_code[:n] = spans["status_code"]
        start[:n] = spans["start_ns"].astype(np.int64)
        end[:n] = spans["end_ns"].astype(np.int64)
        tid[:n] = spans["trace_id"]
        sid[:n] = spans["span_id"]
        pid[:n] = spans["parent_span_id"]

    def _scalar_fvals(a: np.ndarray) -> np.ndarray:
        typ = a["typ"]
        f = np.zeros(len(a), np.float32)
        f[typ == 2] = a["fval"][typ == 2]
        f[typ == 3] = a["ival"][typ == 3]
        f[typ == 4] = a["fval"][typ == 4]
        return f

    def _fix_nonscalar(a: np.ndarray, sval: np.ndarray, typ: np.ndarray):
        """Stringify array/kvlist/bytes AnyValues (rare Python pass)."""
        for i in np.flatnonzero(a["typ"] == 0):
            o, ln = int(a["sval_off"][i]), int(a["sval_len"][i])
            sval[i] = interner.intern(str(_pb_anyvalue(data[o:o + ln])))
            typ[i] = ATTR_STRING

    def _attr_matrix(a: np.ndarray, owners: np.ndarray, starts: np.ndarray,
                     n_rows: int, max_attrs: int):
        """Scatter flat StageAttrs into [n_rows, W] id columns."""
        key = a["key_id"].astype(np.int32)
        sval = a["sval_id"].astype(np.int32)
        typ = a["typ"].astype(np.int8)
        fval = _scalar_fvals(a)
        _fix_nonscalar(a, sval, typ)
        pos = np.arange(len(a), dtype=np.int64) - starts[owners]
        w = _pad_width(int(min((pos.max() if len(a) else -1) + 1, max_attrs)))
        km = np.full((n_rows, w), INVALID_ID, np.int32)
        sm_ = np.full((n_rows, w), INVALID_ID, np.int32)
        fm = np.zeros((n_rows, w), np.float32)
        tm = np.zeros((n_rows, w), np.int8)
        if len(a) and w:
            keep = pos < min(max_attrs, w)
            oi, pi = owners[keep], pos[keep]
            km[oi, pi] = key[keep]
            sm_[oi, pi] = sval[keep]
            fm[oi, pi] = fval[keep]
            tm[oi, pi] = typ[keep]
        return km, sm_, fm, tm, sval

    # -- resources ---------------------------------------------------------
    nres = len(res)
    if nres and n:
        svc = res["service_id"].astype(np.int32)
        # service.name: dict semantics are last-occurrence-wins regardless
        # of value type (C++ recorded the last STRING occurrence only).
        # This fixup runs over the per-RESOURCE attr rows (tiny) and so is
        # independent of include_res_attrs.
        svc_key = interner.get("service.name")
        svc_hits = np.flatnonzero(rattrs["key_id"] == svc_key)
        if svc_hits.size and (rattrs["typ"][svc_hits] != 1).any():
            last: dict[int, int] = {}
            for idx in svc_hits.tolist():
                last[int(rattrs["owner"][idx])] = idx
            for o, idx in last.items():
                t = int(rattrs["typ"][idx])
                if t == 1:
                    v = interner.lookup(int(rattrs["sval_id"][idx]))
                elif t == 2:
                    v = str(bool(rattrs["fval"][idx]))
                elif t == 3:
                    v = str(int(rattrs["ival"][idx]))
                elif t == 4:
                    v = str(float(rattrs["fval"][idx]))
                else:   # non-scalar: stringify from its raw range
                    so = int(rattrs["sval_off"][idx])
                    sl = int(rattrs["sval_len"][idx])
                    v = str(_pb_anyvalue(data[so:so + sl]))
                svc[o] = interner.intern(v)
        res_idx = spans["res_idx"].astype(np.int64)
        service_id[:n] = svc[res_idx]
        if include_res_attrs:
            r_owner = rattrs["owner"].astype(np.int64)
            u_rkey, u_rsval, u_rfval, u_rtyp, _ = _attr_matrix(
                rattrs, r_owner, res["attr_start"].astype(np.int64), nres,
                _MAX_RES_ATTRS)
            r_w = u_rkey.shape[1]
            res_attr_key = np.full((cap, r_w), INVALID_ID, np.int32)
            res_attr_sval = np.full((cap, r_w), INVALID_ID, np.int32)
            res_attr_fval = np.zeros((cap, r_w), np.float32)
            res_attr_typ = np.zeros((cap, r_w), np.int8)
            res_attr_key[:n] = u_rkey[res_idx]
            res_attr_sval[:n] = u_rsval[res_idx]
            res_attr_fval[:n] = u_rfval[res_idx]
            res_attr_typ[:n] = u_rtyp[res_idx]
        else:
            res_attr_key = np.full((cap, 0), INVALID_ID, np.int32)
            res_attr_sval = np.full((cap, 0), INVALID_ID, np.int32)
            res_attr_fval = np.zeros((cap, 0), np.float32)
            res_attr_typ = np.zeros((cap, 0), np.int8)
    else:
        if n:
            service_id[:n] = empty_id
        res_attr_key = np.full((cap, 0), INVALID_ID, np.int32)
        res_attr_sval = np.full((cap, 0), INVALID_ID, np.int32)
        res_attr_fval = np.zeros((cap, 0), np.float32)
        res_attr_typ = np.zeros((cap, 0), np.int8)

    # -- span attrs --------------------------------------------------------
    na = len(sattrs) if include_span_attrs else 0
    if na and n:
        span_idx = sattrs["owner"].astype(np.int64)
        counts = np.bincount(span_idx, minlength=n)
        starts = np.zeros(n, np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        u_k, u_s, u_f, u_t, _ = _attr_matrix(
            sattrs, span_idx, starts, n, _MAX_SPAN_ATTRS)
        k_w = u_k.shape[1]
        span_attr_key = np.full((cap, k_w), INVALID_ID, np.int32)
        span_attr_sval = np.full((cap, k_w), INVALID_ID, np.int32)
        span_attr_fval = np.zeros((cap, k_w), np.float32)
        span_attr_typ = np.zeros((cap, k_w), np.int8)
        span_attr_key[:n] = u_k
        span_attr_sval[:n] = u_s
        span_attr_fval[:n] = u_f
        span_attr_typ[:n] = u_t
    else:
        span_attr_key = np.full((cap, 0), INVALID_ID, np.int32)
        span_attr_sval = np.full((cap, 0), INVALID_ID, np.int32)
        span_attr_fval = np.zeros((cap, 0), np.float32)
        span_attr_typ = np.zeros((cap, 0), np.int8)

    valid = np.zeros(cap, bool)
    valid[:n] = True
    sb = SpanBatch(
        n=n,
        trace_id=tid, span_id=sid, parent_span_id=pid,
        name_id=name_id, service_id=service_id,
        kind=kind, status_code=status_code, status_message_id=sm_id,
        start_unix_nano=start, end_unix_nano=end,
        span_attr_key=span_attr_key, span_attr_sval=span_attr_sval,
        span_attr_fval=span_attr_fval, span_attr_typ=span_attr_typ,
        res_attr_key=res_attr_key, res_attr_sval=res_attr_sval,
        res_attr_fval=res_attr_fval, res_attr_typ=res_attr_typ,
        valid=valid, interner=interner,
    )
    if return_sizes:
        sizes = np.zeros(cap, np.float32)
        if n:
            sizes[:n] = spans["span_len"]
        return sb, sizes
    return sb
