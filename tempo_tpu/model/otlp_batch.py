"""Vectorized OTLP protobuf → SpanBatch staging (the ingest hot path).

The per-span route (`spans_from_otlp_proto` → `SpanBatchBuilder.append`)
pays Python dict+append work per span — fine for the distributor's
regroup/validate path, ruinous for sustained generator ingest (VERDICT r1
weak #7). This module goes straight from the native C++ scanner's columnar
output (`native.otlp_scan2`: SpanRec + flattened AttrRec arrays) to the
padded SoA SpanBatch with numpy passes; Python loops touch only UNIQUE
strings (names/services/attr keys), not spans.

Reference anchor: this is the TPU-era `requestsByTraceID` + PushSpans
staging (`modules/distributor/distributor.go:694-801`,
`modules/generator/generator.go:275`) — the reference walks protos span by
span; here one C scan emits columns and numpy finishes the job.
"""

from __future__ import annotations

import numpy as np

from tempo_tpu.model.interner import INVALID_ID, StringInterner
from tempo_tpu.model.span_batch import (
    ATTR_STRING,
    SpanBatch,
    SpanBatchBuilder,
    _pad_rows,
    _pad_width,
)

_MAX_SPAN_ATTRS = 64
_MAX_RES_ATTRS = 32


def _intern_ranges(data: bytes, offs: np.ndarray, lens: np.ndarray,
                   interner: StringInterner) -> np.ndarray:
    """Interned ids for byte ranges; Python work is O(unique CONTENT).

    The same string lands at a different offset in every span, so deduping
    on (offset, len) degrades to O(rows). Instead: bucket by length, gather
    each bucket into an [m, L] byte matrix (one vectorized fancy-index),
    and np.unique the matrix rows — content dedupe at numpy speed; only
    the handful of distinct strings reach Python.
    """
    n = len(offs)
    if n == 0:
        return np.zeros(0, np.int32)
    buf = np.frombuffer(data, np.uint8)
    offs = offs.astype(np.int64)
    lens = lens.astype(np.int64)
    out = np.empty(n, np.int32)
    for ln in np.unique(lens):
        sel = np.flatnonzero(lens == ln)
        if ln <= 0:
            out[sel] = interner.intern("")
            continue
        mat = buf[offs[sel, None] + np.arange(int(ln))]
        # dedupe via vectorized FNV-1a64 row hash: uint64 unique is a
        # radix-friendly sort, vs np.unique(axis=0)'s void-dtype argsort
        # which dominated the whole ingest path at this call site
        h = np.full(len(sel), 0xCBF29CE484222325, np.uint64)
        prime = np.uint64(0x100000001B3)
        for c in range(int(ln)):
            h = (h ^ mat[:, c].astype(np.uint64)) * prime
        uniq_h, first, inverse = np.unique(h, return_index=True,
                                           return_inverse=True)
        ids = np.empty(len(uniq_h), np.int32)
        for j, fi in enumerate(first.tolist()):
            ids[j] = interner.intern(
                mat[fi].tobytes().decode("utf-8", "replace"))
        out[sel] = ids[inverse]
    return out


def batch_from_otlp(data: bytes, interner: StringInterner) -> SpanBatch:
    """OTLP ExportTraceServiceRequest bytes → SpanBatch.

    Uses the native scanner when available; falls back to the per-span
    decoder otherwise (identical output contract either way).
    """
    from tempo_tpu import native

    scanned = native.otlp_scan2(data)
    if scanned is None:
        from tempo_tpu.model.otlp import spans_from_otlp_proto

        b = SpanBatchBuilder(interner)
        for s in spans_from_otlp_proto(data):
            b.append(**s)
        return b.build()
    recs, attrs = scanned
    n = len(recs)
    cap = _pad_rows(max(n, 1))

    def pad_u8(field: str, w: int) -> np.ndarray:
        out = np.zeros((cap, w), np.uint8)
        if n:
            out[:n] = recs[field]
        return out

    def pad_i(a: np.ndarray, dtype) -> np.ndarray:
        out = np.zeros(cap, dtype)
        out[:n] = a.astype(dtype)
        return out

    name_id = np.full(cap, INVALID_ID, np.int32)
    name_id[:n] = _intern_ranges(data, recs["name_off"], recs["name_len"],
                                 interner)
    # status_message: builder semantics — INVALID_ID when empty
    sm_id = np.full(cap, INVALID_ID, np.int32)
    if n:
        sm = _intern_ranges(data, recs["status_msg_off"],
                            recs["status_msg_len"], interner)
        sm_id[:n] = np.where(recs["status_msg_len"] > 0, sm, INVALID_ID)

    # -- resources: parse each UNIQUE Resource message once ----------------
    service_id = np.full(cap, INVALID_ID, np.int32)
    if n:
        res_pairs = np.stack([recs["res_off"].astype(np.int64),
                              recs["res_len"].astype(np.int64)], axis=1)
        uniq_res, inv_res = np.unique(res_pairs, axis=0, return_inverse=True)
        coder = SpanBatchBuilder(interner)   # reuse its attr-coding rules
        from tempo_tpu.model import proto_wire as pw
        from tempo_tpu.model.otlp import _pb_attrs

        res_rows: list[list[tuple]] = []
        svc_ids = np.empty(len(uniq_res), np.int32)
        for j, (o, ln) in enumerate(uniq_res):
            ra = _pb_attrs(
                [v for f, _, v in pw.iter_fields(data[int(o):int(o) + int(ln)])
                 if f == 1]) if ln > 0 else {}
            res_rows.append(coder._code_attrs(ra, _MAX_RES_ATTRS))
            svc_ids[j] = interner.intern(str(ra.get("service.name", "")))
        service_id[:n] = svc_ids[inv_res]
        r_w = _pad_width(max((len(r) for r in res_rows), default=0))
        u_rkey = np.full((len(uniq_res), r_w), INVALID_ID, np.int32)
        u_rsval = np.full((len(uniq_res), r_w), INVALID_ID, np.int32)
        u_rfval = np.zeros((len(uniq_res), r_w), np.float32)
        u_rtyp = np.zeros((len(uniq_res), r_w), np.int8)
        for j, row in enumerate(res_rows):
            for jj, (kk, sv, fv, tt) in enumerate(row):
                u_rkey[j, jj], u_rsval[j, jj] = kk, sv
                u_rfval[j, jj], u_rtyp[j, jj] = fv, tt
        res_attr_key = np.full((cap, r_w), INVALID_ID, np.int32)
        res_attr_sval = np.full((cap, r_w), INVALID_ID, np.int32)
        res_attr_fval = np.zeros((cap, r_w), np.float32)
        res_attr_typ = np.zeros((cap, r_w), np.int8)
        res_attr_key[:n] = u_rkey[inv_res]
        res_attr_sval[:n] = u_rsval[inv_res]
        res_attr_fval[:n] = u_rfval[inv_res]
        res_attr_typ[:n] = u_rtyp[inv_res]
    else:
        res_attr_key = np.full((cap, 0), INVALID_ID, np.int32)
        res_attr_sval = np.full((cap, 0), INVALID_ID, np.int32)
        res_attr_fval = np.zeros((cap, 0), np.float32)
        res_attr_typ = np.zeros((cap, 0), np.int8)

    # -- span attrs: flattened AttrRec → [N,K] columns ---------------------
    na = len(attrs)
    if na:
        key_ids = _intern_ranges(data, attrs["key_off"], attrs["key_len"],
                                 interner)
        typ = attrs["typ"].astype(np.int8)   # native codes == ATTR_* enums
        sval_ids = np.full(na, INVALID_ID, np.int32)
        smask = typ == ATTR_STRING
        if smask.any():
            sval_ids[smask] = _intern_ranges(
                data, attrs["sval_off"][smask], attrs["sval_len"][smask],
                interner)
        fval = np.zeros(na, np.float32)
        fval[typ == 2] = attrs["fval"][typ == 2]                 # bool 0/1
        fval[typ == 3] = attrs["ival"][typ == 3].astype(np.float32)
        fval[typ == 4] = attrs["fval"][typ == 4]
        # non-scalar AnyValues (typ 0): stringified, like the dict path
        for i in np.flatnonzero(typ == 0):
            from tempo_tpu.model.otlp import _pb_anyvalue

            o, ln = int(attrs["sval_off"][i]), int(attrs["sval_len"][i])
            sval_ids[i] = interner.intern(str(_pb_anyvalue(data[o:o + ln])))
            typ[i] = ATTR_STRING
        span_idx = attrs["span_idx"].astype(np.int64)
        counts = np.bincount(span_idx, minlength=n)
        starts = np.zeros(n, np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        pos = np.arange(na, dtype=np.int64) - starts[span_idx]
        keep = pos < _MAX_SPAN_ATTRS          # truncate, like the builder
        k_w = _pad_width(int(min(counts.max(), _MAX_SPAN_ATTRS)))
        span_attr_key = np.full((cap, k_w), INVALID_ID, np.int32)
        span_attr_sval = np.full((cap, k_w), INVALID_ID, np.int32)
        span_attr_fval = np.zeros((cap, k_w), np.float32)
        span_attr_typ = np.zeros((cap, k_w), np.int8)
        si, pi = span_idx[keep], pos[keep]
        span_attr_key[si, pi] = key_ids[keep]
        span_attr_sval[si, pi] = sval_ids[keep]
        span_attr_fval[si, pi] = fval[keep]
        span_attr_typ[si, pi] = typ[keep]
    else:
        k_w = 0
        span_attr_key = np.full((cap, 0), INVALID_ID, np.int32)
        span_attr_sval = np.full((cap, 0), INVALID_ID, np.int32)
        span_attr_fval = np.zeros((cap, 0), np.float32)
        span_attr_typ = np.zeros((cap, 0), np.int8)

    valid = np.zeros(cap, bool)
    valid[:n] = True
    return SpanBatch(
        n=n,
        trace_id=pad_u8("trace_id", 16),
        span_id=pad_u8("span_id", 8),
        parent_span_id=pad_u8("parent_span_id", 8),
        name_id=name_id,
        service_id=service_id,
        kind=pad_i(recs["kind"], np.int32) if n else np.zeros(cap, np.int32),
        status_code=pad_i(recs["status_code"], np.int32)
        if n else np.zeros(cap, np.int32),
        status_message_id=sm_id,
        start_unix_nano=pad_i(recs["start_ns"], np.int64)
        if n else np.zeros(cap, np.int64),
        end_unix_nano=pad_i(recs["end_ns"], np.int64)
        if n else np.zeros(cap, np.int64),
        span_attr_key=span_attr_key,
        span_attr_sval=span_attr_sval,
        span_attr_fval=span_attr_fval,
        span_attr_typ=span_attr_typ,
        res_attr_key=res_attr_key,
        res_attr_sval=res_attr_sval,
        res_attr_fval=res_attr_fval,
        res_attr_typ=res_attr_typ,
        valid=valid,
        interner=interner,
    )
