"""Vectorized OTLP protobuf → SpanBatch staging (the ingest hot path).

The per-span route (`spans_from_otlp_proto` → `SpanBatchBuilder.append`)
pays Python dict+append work per span — fine for the distributor's
regroup/validate path, ruinous for sustained generator ingest (VERDICT r1
weak #7). Here the whole decode runs in the C++ staging kernel
(`native.otlp_stage`): one pass over the wire bytes emits fixed columns
AND intern ids (names, services, attr keys/values are dictionary-encoded
inside C++, see native.cpp Interner); numpy only pads and scatters the id
columns. Python touches per-span data exactly zero times on this path —
only rare non-scalar AnyValues cross back for stringification.

Reference anchor: this is the TPU-era `requestsByTraceID` + PushSpans
staging (`modules/distributor/distributor.go:694-801`,
`modules/generator/generator.go:275`) — the reference walks protos span by
span; here one C scan emits interned columns and numpy finishes the job.
"""

from __future__ import annotations

import numpy as np

from tempo_tpu.model.interner import INVALID_ID, StringInterner
from tempo_tpu.model.span_batch import (
    ATTR_STRING,
    SpanBatch,
    SpanBatchBuilder,
    _pad_rows,
    _pad_width,
)

_MAX_SPAN_ATTRS = 64
_MAX_RES_ATTRS = 32


def _staged_service_ids(data: bytes, interner: StringInterner,
                        rattrs, res) -> np.ndarray:
    """Per-resource service.name intern ids with the Python fixup applied.

    Dict semantics are last-occurrence-wins regardless of value type (C++
    recorded the last STRING occurrence only); the fixup runs over the
    per-RESOURCE attr rows (tiny). Shared by full SpanBatch staging and
    the decode-once tee's usage attribution."""
    svc = res["service_id"].astype(np.int32)
    svc_key = interner.get("service.name")
    svc_hits = np.flatnonzero(rattrs["key_id"] == svc_key)
    if svc_hits.size and (rattrs["typ"][svc_hits] != 1).any():
        from tempo_tpu.model.otlp import _pb_anyvalue

        last: dict[int, int] = {}
        for idx in svc_hits.tolist():
            last[int(rattrs["owner"][idx])] = idx
        for o, idx in last.items():
            t = int(rattrs["typ"][idx])
            if t == 1:
                v = interner.lookup(int(rattrs["sval_id"][idx]))
            elif t == 2:
                v = str(bool(rattrs["fval"][idx]))
            elif t == 3:
                v = str(int(rattrs["ival"][idx]))
            elif t == 4:
                v = str(float(rattrs["fval"][idx]))
            else:   # non-scalar: stringify from its raw range
                so = int(rattrs["sval_off"][idx])
                sl = int(rattrs["sval_len"][idx])
                v = str(_pb_anyvalue(data[so:so + sl]))
            svc[o] = interner.intern(v)
    return svc


def batch_from_otlp(data: bytes, interner: StringInterner,
                    return_sizes: bool = False,
                    include_span_attrs: bool = True,
                    include_res_attrs: bool = True,
                    trusted: bool = False):
    """OTLP ExportTraceServiceRequest bytes → SpanBatch.

    Uses the one-pass C++ staging kernel when the native layer is
    available; otherwise the per-span decoder + builder (identical output
    contract, modulo the duplicate-attr-key note on `_batch_from_staged`).
    With `return_sizes` also returns [cap] f32 wire bytes per span for the
    size_total subprocessor (`spanmetrics.go:27-31`; zeros on the fallback
    path, which does not track wire offsets).

    `include_*_attrs=False` skips materializing that attr matrix (the
    columns come back 0-wide): callers whose processors read only
    intrinsic dimensions — the default spanmetrics config — drop a third
    of the staging work. service.name extraction is unaffected.
    """
    from tempo_tpu import native

    nat = interner.native_handle() if hasattr(interner, "native_handle") \
        else None
    if nat is not None:
        staged = native.otlp_stage(nat, data,
                                   skip_span_attrs=not include_span_attrs,
                                   trust_attrs=trusted)
        if staged is not None:
            return _batch_from_staged(data, interner, staged, return_sizes,
                                      include_span_attrs, include_res_attrs)

    from tempo_tpu.model.otlp import spans_from_otlp_proto

    b = SpanBatchBuilder(interner)
    for s in spans_from_otlp_proto(data):
        b.append(**s)
    sb = b.build()
    if return_sizes:
        return sb, np.zeros(sb.capacity, np.float32)
    return sb


def _batch_from_staged(data: bytes, interner: StringInterner, staged,
                       return_sizes: bool,
                       include_span_attrs: bool = True,
                       include_res_attrs: bool = True):
    """C++-staged records → SpanBatch: numpy does only padding/scatter.

    Known divergence from the dict path: duplicate attribute keys within
    one scope keep one column per occurrence instead of last-wins dict
    semantics (`attr_sval_column` reads the first)."""
    from tempo_tpu.model.otlp import _pb_anyvalue

    spans, sattrs, rattrs, res = staged
    interner.sync()                      # mirror ids created in C++
    n = len(spans)
    cap = _pad_rows(max(n, 1))
    empty_id = interner.intern("")

    name_id = np.full(cap, INVALID_ID, np.int32)
    sm_id = np.full(cap, INVALID_ID, np.int32)
    service_id = np.full(cap, INVALID_ID, np.int32)
    kind = np.zeros(cap, np.int32)
    status_code = np.zeros(cap, np.int32)
    start = np.zeros(cap, np.int64)
    end = np.zeros(cap, np.int64)
    tid = np.zeros((cap, 16), np.uint8)
    sid = np.zeros((cap, 8), np.uint8)
    pid = np.zeros((cap, 8), np.uint8)
    if n:
        name_id[:n] = spans["name_id"]
        sm = spans["status_msg_id"]
        # builder semantics: empty status message → INVALID_ID
        sm_id[:n] = np.where((sm < 0) | (sm == empty_id), INVALID_ID, sm)
        kind[:n] = spans["kind"]
        status_code[:n] = spans["status_code"]
        start[:n] = spans["start_ns"].astype(np.int64)
        end[:n] = spans["end_ns"].astype(np.int64)
        tid[:n] = spans["trace_id"]
        sid[:n] = spans["span_id"]
        pid[:n] = spans["parent_span_id"]

    def _scalar_fvals(a: np.ndarray) -> np.ndarray:
        typ = a["typ"]
        f = np.zeros(len(a), np.float32)
        f[typ == 2] = a["fval"][typ == 2]
        f[typ == 3] = a["ival"][typ == 3]
        f[typ == 4] = a["fval"][typ == 4]
        return f

    def _fix_nonscalar(a: np.ndarray, sval: np.ndarray, typ: np.ndarray):
        """Stringify array/kvlist/bytes AnyValues (rare Python pass)."""
        for i in np.flatnonzero(a["typ"] == 0):
            o, ln = int(a["sval_off"][i]), int(a["sval_len"][i])
            sval[i] = interner.intern(str(_pb_anyvalue(data[o:o + ln])))
            typ[i] = ATTR_STRING

    def _attr_matrix(a: np.ndarray, owners: np.ndarray, starts: np.ndarray,
                     n_rows: int, max_attrs: int):
        """Scatter flat StageAttrs into [n_rows, W] id columns."""
        key = a["key_id"].astype(np.int32)
        sval = a["sval_id"].astype(np.int32)
        typ = a["typ"].astype(np.int8)
        fval = _scalar_fvals(a)
        _fix_nonscalar(a, sval, typ)
        pos = np.arange(len(a), dtype=np.int64) - starts[owners]
        w = _pad_width(int(min((pos.max() if len(a) else -1) + 1, max_attrs)))
        km = np.full((n_rows, w), INVALID_ID, np.int32)
        sm_ = np.full((n_rows, w), INVALID_ID, np.int32)
        fm = np.zeros((n_rows, w), np.float32)
        tm = np.zeros((n_rows, w), np.int8)
        if len(a) and w:
            keep = pos < min(max_attrs, w)
            oi, pi = owners[keep], pos[keep]
            km[oi, pi] = key[keep]
            sm_[oi, pi] = sval[keep]
            fm[oi, pi] = fval[keep]
            tm[oi, pi] = typ[keep]
        return km, sm_, fm, tm, sval

    # -- resources ---------------------------------------------------------
    nres = len(res)
    if nres and n:
        svc = _staged_service_ids(data, interner, rattrs, res)
        res_idx = spans["res_idx"].astype(np.int64)
        service_id[:n] = svc[res_idx]
        if include_res_attrs:
            r_owner = rattrs["owner"].astype(np.int64)
            u_rkey, u_rsval, u_rfval, u_rtyp, _ = _attr_matrix(
                rattrs, r_owner, res["attr_start"].astype(np.int64), nres,
                _MAX_RES_ATTRS)
            r_w = u_rkey.shape[1]
            res_attr_key = np.full((cap, r_w), INVALID_ID, np.int32)
            res_attr_sval = np.full((cap, r_w), INVALID_ID, np.int32)
            res_attr_fval = np.zeros((cap, r_w), np.float32)
            res_attr_typ = np.zeros((cap, r_w), np.int8)
            res_attr_key[:n] = u_rkey[res_idx]
            res_attr_sval[:n] = u_rsval[res_idx]
            res_attr_fval[:n] = u_rfval[res_idx]
            res_attr_typ[:n] = u_rtyp[res_idx]
        else:
            res_attr_key = np.full((cap, 0), INVALID_ID, np.int32)
            res_attr_sval = np.full((cap, 0), INVALID_ID, np.int32)
            res_attr_fval = np.zeros((cap, 0), np.float32)
            res_attr_typ = np.zeros((cap, 0), np.int8)
    else:
        if n:
            service_id[:n] = empty_id
        res_attr_key = np.full((cap, 0), INVALID_ID, np.int32)
        res_attr_sval = np.full((cap, 0), INVALID_ID, np.int32)
        res_attr_fval = np.zeros((cap, 0), np.float32)
        res_attr_typ = np.zeros((cap, 0), np.int8)

    # -- span attrs --------------------------------------------------------
    na = len(sattrs) if include_span_attrs else 0
    if na and n:
        span_idx = sattrs["owner"].astype(np.int64)
        counts = np.bincount(span_idx, minlength=n)
        starts = np.zeros(n, np.int64)
        np.cumsum(counts[:-1], out=starts[1:])
        u_k, u_s, u_f, u_t, _ = _attr_matrix(
            sattrs, span_idx, starts, n, _MAX_SPAN_ATTRS)
        k_w = u_k.shape[1]
        span_attr_key = np.full((cap, k_w), INVALID_ID, np.int32)
        span_attr_sval = np.full((cap, k_w), INVALID_ID, np.int32)
        span_attr_fval = np.zeros((cap, k_w), np.float32)
        span_attr_typ = np.zeros((cap, k_w), np.int8)
        span_attr_key[:n] = u_k
        span_attr_sval[:n] = u_s
        span_attr_fval[:n] = u_f
        span_attr_typ[:n] = u_t
    else:
        span_attr_key = np.full((cap, 0), INVALID_ID, np.int32)
        span_attr_sval = np.full((cap, 0), INVALID_ID, np.int32)
        span_attr_fval = np.zeros((cap, 0), np.float32)
        span_attr_typ = np.zeros((cap, 0), np.int8)

    valid = np.zeros(cap, bool)
    valid[:n] = True
    sb = SpanBatch(
        n=n,
        trace_id=tid, span_id=sid, parent_span_id=pid,
        name_id=name_id, service_id=service_id,
        kind=kind, status_code=status_code, status_message_id=sm_id,
        start_unix_nano=start, end_unix_nano=end,
        span_attr_key=span_attr_key, span_attr_sval=span_attr_sval,
        span_attr_fval=span_attr_fval, span_attr_typ=span_attr_typ,
        res_attr_key=res_attr_key, res_attr_sval=res_attr_sval,
        res_attr_fval=res_attr_fval, res_attr_typ=res_attr_typ,
        valid=valid, interner=interner,
    )
    if return_sizes:
        sizes = np.zeros(cap, np.float32)
        if n:
            sizes[:n] = spans["span_len"]
        return sb, sizes
    return sb


# ---------------------------------------------------------------------------
# decode-once staging: one OTLP payload, shared by every tee target
# ---------------------------------------------------------------------------


def stage_otlp(data: bytes, interner: StringInterner, *,
               trusted: bool = False, include_span_attrs: bool = True,
               include_res_attrs: bool = True) -> "StagedIngest | None":
    """OTLP wire bytes → a `StagedIngest`: ONE C++ staging pass whose
    product every ring target shares through row-index views.

    None when the native staging kernel is unavailable (callers keep
    their byte-slice / span-dict compatibility route). Raises ValueError
    on a malformed payload — the staging pass IS the validation pass."""
    from tempo_tpu import native

    nat = interner.native_handle() if hasattr(interner, "native_handle") \
        else None
    if nat is None:
        return None
    staged = native.otlp_stage(nat, data,
                               skip_span_attrs=not include_span_attrs,
                               trust_attrs=trusted)
    if staged is None:
        return None
    interner.sync()
    return StagedIngest(data, interner, staged,
                        has_span_attrs=include_span_attrs,
                        include_res_attrs=include_res_attrs)


class StagedIngest:
    """The decode-once product of one OTLP payload.

    Holds the C++-staged record arrays (fixed columns + intern ids), the
    interner they were staged against, and the raw payload; materializes
    the columnar SpanBatch LAZILY (a dedicated-spanmetrics generator
    consumes the StageRec rows directly and never pays the numpy
    padding/scatter). `view(rows)` hands out per-target row-index slices
    over the shared arrays — the distributor's tee unit: no
    re-serialization, no second staging pass, no per-target decode."""

    __slots__ = ("raw", "interner", "spans", "sattrs", "rattrs", "res",
                 "has_span_attrs", "include_res_attrs", "sample_weight",
                 "_batch", "_sizes", "_events", "_fixup", "_svc_ids")

    def __init__(self, raw: bytes, interner: StringInterner, staged,
                 has_span_attrs: bool = True,
                 include_res_attrs: bool = True) -> None:
        self.raw = raw
        self.interner = interner
        self.spans, self.sattrs, self.rattrs, self.res = staged
        self.has_span_attrs = has_span_attrs
        self.include_res_attrs = include_res_attrs
        # per-row Horvitz-Thompson weights set by the distributor's
        # overload sampling stage (None = unsampled, every weight 1.0);
        # views slice it so the generator can upscale sampled rates
        self.sample_weight: "np.ndarray | None" = None
        self._batch = None
        self._sizes = None
        self._events = None
        self._fixup: "bool | None" = None
        self._svc_ids: "np.ndarray | None" = None

    @property
    def n(self) -> int:
        return len(self.spans)

    @property
    def needs_service_fixup(self) -> bool:
        """True when some resource carries a non-string service.name (the
        staged service_id column then needs the Python stringify fixup —
        the StageRec fast consumers bail to the SpanBatch route, where
        `_staged_service_ids` applies it)."""
        if self._fixup is None:
            svc_key = self.interner.get("service.name")
            hits = self.rattrs["key_id"] == svc_key
            self._fixup = bool(hits.any()
                               and (self.rattrs["typ"][hits] != 1).any())
        return self._fixup

    def service_ids(self) -> np.ndarray:
        """Per-RESOURCE service.name intern ids, fixup applied (usage
        attribution reads these without materializing the batch)."""
        if self._svc_ids is None:
            self._svc_ids = _staged_service_ids(
                self.raw, self.interner, self.rattrs, self.res)
        return self._svc_ids

    def batch(self) -> tuple["SpanBatch", np.ndarray]:
        """The staged columnar SpanBatch + per-span wire sizes, built on
        first use and shared by every subsequent view."""
        if self._batch is None:
            self._batch, self._sizes = _batch_from_staged(
                self.raw, self.interner,
                (self.spans, self.sattrs, self.rattrs, self.res),
                return_sizes=True,
                include_span_attrs=self.has_span_attrs,
                include_res_attrs=self.include_res_attrs)
        return self._batch, self._sizes

    def events_links(self) -> tuple[dict, dict]:
        """{span_idx: [event dicts]}, {span_idx: [link dicts]} — one lazy
        native pass over the payload; events/links are persistence-only
        fields (the metrics plane never columnizes them)."""
        if self._events is None:
            from tempo_tpu import native

            ev_by: dict[int, list] = {}
            ln_by: dict[int, list] = {}
            got = native.otlp_events(self.raw)
            if got is not None:
                evs, links = got
                raw = self.raw
                for rec in evs:
                    off, ln = int(rec["name_off"]), int(rec["name_len"])
                    ev_by.setdefault(int(rec["span_idx"]), []).append({
                        "time_unix_nano": int(rec["time_ns"]),
                        "name": raw[off:off + ln].decode("utf-8", "replace"),
                    })
                for rec in links:
                    ln_by.setdefault(int(rec["span_idx"]), []).append({
                        "trace_id": bytes(rec["trace_id"])[
                            :int(rec["tid_len"])],
                        "span_id": bytes(rec["span_id"])[
                            :int(rec["sid_len"])],
                    })
            self._events = (ev_by, ln_by)
        return self._events

    def view(self, rows: "np.ndarray | None" = None) -> "StagedView":
        """A row-index slice over this staging (None = every row)."""
        return StagedView(self, rows)


class StagedView:
    """One tee target's slice of a `StagedIngest`: row indices over the
    shared staged arrays. The full-coverage view (the common single-target
    ring case) is genuinely zero-copy — consumers receive the shared
    arrays themselves."""

    __slots__ = ("staged", "rows")

    def __init__(self, staged: StagedIngest,
                 rows: "np.ndarray | None" = None) -> None:
        self.staged = staged
        self.rows = None if rows is None else np.asarray(rows, np.int64)

    @property
    def n(self) -> int:
        return self.staged.n if self.rows is None else int(len(self.rows))

    @property
    def is_full(self) -> bool:
        return self.rows is None or len(self.rows) == self.staged.n

    def row_indices(self) -> np.ndarray:
        if self.rows is None:
            return np.arange(self.staged.n, dtype=np.int64)
        return self.rows

    def stage_rows(self) -> np.ndarray:
        """This view's StageRec rows — the SHARED array when full (zero
        copy), an 88B/row gather otherwise."""
        if self.is_full:
            return self.staged.spans
        return self.staged.spans[self.rows]

    def weights(self) -> "np.ndarray | None":
        """This view's sampling weights (None when the push was not
        sampled — the common case; consumers then use weight 1.0)."""
        w = self.staged.sample_weight
        if w is None or self.is_full:
            return w
        return w[self.rows]

    def batch_slice(self) -> tuple["SpanBatch", np.ndarray]:
        """(SpanBatch, sizes) for this view's rows — the shared staged
        batch when full, a column gather (`SpanBatch.take_rows`)
        otherwise. Never re-decodes wire bytes."""
        sb, sizes = self.staged.batch()
        if self.is_full:
            return sb, sizes
        out = sb.take_rows(self.rows)
        out_sizes = np.zeros(out.capacity, np.float32)
        out_sizes[:len(self.rows)] = sizes[self.rows]
        return out, out_sizes

    def trace_groups(self) -> list[tuple[bytes, list[int]]]:
        """(exact trace-id bytes, row indices) in first-seen order — the
        ingester's live-trace grouping straight off the columns."""
        spans = self.staged.spans
        rows = self.row_indices()
        tids = spans["trace_id"]
        tls = spans["tid_len"]
        groups: dict[bytes, list[int]] = {}
        for i in rows.tolist():
            tid = bytes(tids[i])[:int(tls[i])]
            groups.setdefault(tid, []).append(i)
        return list(groups.items())

    def to_span_dicts(self, rows: "np.ndarray | list[int] | None" = None
                      ) -> list[dict]:
        """Wire-parity span dicts for this view's rows (or a sub-slice):
        the shape `spans_from_otlp_proto` yields, with exact id byte
        lengths restored from the staged records and events/links merged
        from the lazy payload pass."""
        st = self.staged
        if not st.has_span_attrs:
            raise ValueError(
                "staged without span attrs: dict conversion would drop "
                "attributes (stage with include_span_attrs=True)")
        sb, _ = st.batch()
        spans = st.spans
        ev_by, ln_by = st.events_links()
        it = st.interner
        out = []
        idx = self.row_indices() if rows is None else np.asarray(rows)
        k_has = sb.span_attr_key.shape[1] > 0
        r_has = sb.res_attr_key.shape[1] > 0
        for i in idx.tolist():
            rec = spans[i]
            sm = int(sb.status_message_id[i])
            s: dict = {
                "trace_id": bytes(rec["trace_id"])[:int(rec["tid_len"])],
                "span_id": bytes(rec["span_id"])[:int(rec["sid_len"])],
                "parent_span_id":
                    bytes(rec["parent_span_id"])[:int(rec["pid_len"])],
                "name": it.lookup(int(sb.name_id[i]))
                    if int(sb.name_id[i]) != INVALID_ID else "",
                "service": it.lookup(int(sb.service_id[i]))
                    if int(sb.service_id[i]) != INVALID_ID else "",
                "kind": int(sb.kind[i]),
                "status_code": int(sb.status_code[i]),
                "status_message": it.lookup(sm) if sm != INVALID_ID else "",
                "start_unix_nano": int(sb.start_unix_nano[i]),
                "end_unix_nano": int(sb.end_unix_nano[i]),
                "attrs": sb._decode_attrs(
                    sb.span_attr_key[i], sb.span_attr_sval[i],
                    sb.span_attr_fval[i], sb.span_attr_typ[i])
                    if k_has else {},
                "res_attrs": sb._decode_attrs(
                    sb.res_attr_key[i], sb.res_attr_sval[i],
                    sb.res_attr_fval[i], sb.res_attr_typ[i])
                    if r_has else {},
            }
            if i in ev_by:
                s["events"] = ev_by[i]
            if i in ln_by:
                s["links"] = ln_by[i]
            out.append(s)
        return out
