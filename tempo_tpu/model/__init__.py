"""Wire model and span tensors.

The analog of the reference's `pkg/tempopb` (wire protos) + `pkg/model`
(internal codecs), re-shaped for a dense-tensor machine: spans are staged into
padded structure-of-arrays `SpanBatch`es with dictionary-coded strings so the
per-span loops of the reference become batched device kernels.
"""

from tempo_tpu.model.interner import StringInterner
from tempo_tpu.model.span_batch import (
    KIND_CLIENT,
    KIND_CONSUMER,
    KIND_INTERNAL,
    KIND_PRODUCER,
    KIND_SERVER,
    KIND_UNSPECIFIED,
    STATUS_ERROR,
    STATUS_OK,
    STATUS_UNSET,
    SpanBatch,
    SpanBatchBuilder,
)
from tempo_tpu.model.otlp import (
    otlp_json_to_batch,
    otlp_proto_to_batch,
    spans_from_otlp_json,
)

__all__ = [k for k in dir() if not k.startswith("_")]
