"""OTLP trace ingest: JSON and protobuf → SpanBatch.

The receiver-side conversion the reference performs in its OTel receiver shim
plus `ptrace→tempopb` marshal round-trip
(`modules/distributor/receiver/shim.go:165`, `distributor.go:421-432`),
collapsed into a single decode straight into span tensors. Handles the public
OTLP wire schemas (opentelemetry-proto trace.proto v1 field numbers, and the
OTLP/JSON camelCase mapping).
"""

from __future__ import annotations

import binascii
from typing import Any, Iterable

from tempo_tpu.model import proto_wire as pw
from tempo_tpu.model.span_batch import SpanBatch, SpanBatchBuilder

_KIND_NAMES = {
    "SPAN_KIND_UNSPECIFIED": 0, "SPAN_KIND_INTERNAL": 1, "SPAN_KIND_SERVER": 2,
    "SPAN_KIND_CLIENT": 3, "SPAN_KIND_PRODUCER": 4, "SPAN_KIND_CONSUMER": 5,
}
_STATUS_NAMES = {"STATUS_CODE_UNSET": 0, "STATUS_CODE_OK": 1, "STATUS_CODE_ERROR": 2}


# ---------------------------------------------------------------------------
# OTLP/JSON
# ---------------------------------------------------------------------------

def _json_anyvalue(v: dict[str, Any]) -> Any:
    if "stringValue" in v:
        return v["stringValue"]
    if "intValue" in v:
        return int(v["intValue"])
    if "doubleValue" in v:
        return float(v["doubleValue"])
    if "boolValue" in v:
        return bool(v["boolValue"])
    if "arrayValue" in v:
        return [_json_anyvalue(x) for x in v["arrayValue"].get("values", [])]
    if "kvlistValue" in v:
        return {kv["key"]: _json_anyvalue(kv.get("value", {}))
                for kv in v["kvlistValue"].get("values", [])}
    if "bytesValue" in v:
        return v["bytesValue"]
    return None


def _json_attrs(lst: Iterable[dict] | None) -> dict[str, Any]:
    return {kv["key"]: _json_anyvalue(kv.get("value", {})) for kv in (lst or [])}


def spans_from_otlp_json(payload: dict) -> Iterable[dict]:
    """Yield flat span dicts from an OTLP/JSON ExportTraceServiceRequest."""
    for rs in payload.get("resourceSpans", []):
        res_attrs = _json_attrs(rs.get("resource", {}).get("attributes"))
        service = str(res_attrs.get("service.name", ""))
        for ss in rs.get("scopeSpans", rs.get("instrumentationLibrarySpans", [])):
            for sp in ss.get("spans", []):
                kind = sp.get("kind", 0)
                if isinstance(kind, str):
                    kind = _KIND_NAMES.get(kind, 0)
                status = sp.get("status", {})
                scode = status.get("code", 0)
                if isinstance(scode, str):
                    scode = _STATUS_NAMES.get(scode, 0)
                span = {
                    "trace_id": binascii.unhexlify(sp.get("traceId", "")),
                    "span_id": binascii.unhexlify(sp.get("spanId", "")),
                    "parent_span_id": binascii.unhexlify(sp.get("parentSpanId", "") or ""),
                    "name": sp.get("name", ""),
                    "service": service,
                    "kind": int(kind),
                    "status_code": int(scode),
                    "status_message": status.get("message", ""),
                    "start_unix_nano": int(sp.get("startTimeUnixNano", 0)),
                    "end_unix_nano": int(sp.get("endTimeUnixNano", 0)),
                    "attrs": _json_attrs(sp.get("attributes")),
                    "res_attrs": res_attrs,
                }
                if sp.get("events"):
                    span["events"] = [
                        {"time_unix_nano": int(e.get("timeUnixNano", 0)),
                         "name": e.get("name", "")}
                        for e in sp["events"]]
                if sp.get("links"):
                    span["links"] = [
                        {"trace_id": binascii.unhexlify(
                            ln.get("traceId", "") or ""),
                         "span_id": binascii.unhexlify(
                            ln.get("spanId", "") or "")}
                        for ln in sp["links"]]
                yield span


def otlp_json_to_batch(payload: dict, builder: SpanBatchBuilder | None = None) -> SpanBatch:
    b = builder or SpanBatchBuilder()
    for span in spans_from_otlp_json(payload):
        b.append(**span)
    return b.build()


# ---------------------------------------------------------------------------
# OTLP/protobuf (field numbers from public opentelemetry-proto trace.proto)
# ---------------------------------------------------------------------------

def _pb_anyvalue(buf) -> Any:
    for fnum, _, val in pw.iter_fields(bytes(buf)):
        if fnum == 1:
            return bytes(val).decode("utf-8", "replace")
        if fnum == 2:
            return bool(val)
        if fnum == 3:
            # int64 varint, two's complement
            return val - (1 << 64) if val >= (1 << 63) else val
        if fnum == 4:
            return pw.f64(val)
        if fnum == 5:  # ArrayValue{ repeated AnyValue values = 1 }
            return [_pb_anyvalue(v) for f, _, v in pw.iter_fields(bytes(val)) if f == 1]
        if fnum == 6:  # KeyValueList{ repeated KeyValue values = 1 }
            return _pb_attrs([v for f, _, v in pw.iter_fields(bytes(val)) if f == 1])
        if fnum == 7:
            return bytes(val)
    return None


def _pb_attrs(kvs: Iterable) -> dict[str, Any]:
    out = {}
    for kv in kvs:
        key, val = "", None
        for fnum, _, v in pw.iter_fields(bytes(kv)):
            if fnum == 1:
                key = bytes(v).decode("utf-8", "replace")
            elif fnum == 2:
                val = _pb_anyvalue(v)
        out[key] = val
    return out


def otlp_proto_to_batch(data: bytes, builder: SpanBatchBuilder | None = None) -> SpanBatch:
    """Decode an OTLP protobuf ExportTraceServiceRequest into a SpanBatch."""
    b = builder or SpanBatchBuilder()
    for span in spans_from_otlp_proto(data):
        b.append(**span)
    return b.build()


def spans_from_otlp_proto(data: bytes):
    """Decode OTLP protobuf into flat span dicts (the distributor's wire
    entry: the regroup/validate path consumes dicts, batch staging happens
    at the generator/ingester seams)."""
    for fnum, _, rs in pw.iter_fields(data):
        if fnum != 1:  # ResourceSpans
            continue
        res_attrs: dict[str, Any] = {}
        scope_bufs = []
        for f2, _, v2 in pw.iter_fields(bytes(rs)):
            if f2 == 1:  # Resource{ repeated KeyValue attributes = 1 }
                res_attrs = _pb_attrs(
                    [v for f, _, v in pw.iter_fields(bytes(v2)) if f == 1])
            elif f2 == 2:  # ScopeSpans
                scope_bufs.append(v2)
        service = str(res_attrs.get("service.name", ""))
        for sbuf in scope_bufs:
            for f3, _, v3 in pw.iter_fields(bytes(sbuf)):
                if f3 != 2:  # Span
                    continue
                span = {
                    "trace_id": b"", "span_id": b"", "parent_span_id": b"",
                    "name": "", "service": service, "kind": 0,
                    "status_code": 0, "status_message": "",
                    "start_unix_nano": 0, "end_unix_nano": 0,
                    "attrs": {}, "res_attrs": res_attrs,
                }
                kvs = []
                for f4, _, v4 in pw.iter_fields(bytes(v3)):
                    if f4 == 1:
                        span["trace_id"] = bytes(v4)
                    elif f4 == 2:
                        span["span_id"] = bytes(v4)
                    elif f4 == 4:
                        span["parent_span_id"] = bytes(v4)
                    elif f4 == 5:
                        span["name"] = bytes(v4).decode("utf-8", "replace")
                    elif f4 == 6:
                        span["kind"] = v4
                    elif f4 == 7:
                        span["start_unix_nano"] = v4
                    elif f4 == 8:
                        span["end_unix_nano"] = v4
                    elif f4 == 9:
                        kvs.append(v4)
                    elif f4 == 11:  # Event{ time=1 fixed64, name=2 }
                        ev = {"time_unix_nano": 0, "name": ""}
                        for f5, _, v5 in pw.iter_fields(bytes(v4)):
                            if f5 == 1:
                                ev["time_unix_nano"] = v5
                            elif f5 == 2:
                                ev["name"] = bytes(v5).decode("utf-8",
                                                              "replace")
                        span.setdefault("events", []).append(ev)
                    elif f4 == 13:  # Link{ trace_id=1, span_id=2 }
                        ln = {"trace_id": b"", "span_id": b""}
                        for f5, _, v5 in pw.iter_fields(bytes(v4)):
                            if f5 == 1:
                                ln["trace_id"] = bytes(v5)
                            elif f5 == 2:
                                ln["span_id"] = bytes(v5)
                        span.setdefault("links", []).append(ln)
                    elif f4 == 15:  # Status{ message=2, code=3 }
                        for f5, _, v5 in pw.iter_fields(bytes(v4)):
                            if f5 == 2:
                                span["status_message"] = bytes(v5).decode("utf-8", "replace")
                            elif f5 == 3:
                                span["status_code"] = v5
                if kvs:
                    span["attrs"] = _pb_attrs(kvs)
                yield span


# ---------------------------------------------------------------------------
# OTLP/protobuf encoding (the distributor→generator tee wire shape)
# ---------------------------------------------------------------------------

def _enc_anyvalue(v: Any) -> bytes:
    if isinstance(v, bool):
        return pw.enc_field_varint(2, 1 if v else 0)
    if isinstance(v, int):
        return pw.enc_field_varint(3, v & ((1 << 64) - 1))
    if isinstance(v, float):
        return pw.enc_field_double(4, v)
    if isinstance(v, bytes):
        return pw.enc_field_bytes(7, v)
    if isinstance(v, (list, tuple)):      # ArrayValue{ values = 1 }
        return pw.enc_field_msg(5, b"".join(
            pw.enc_field_msg(1, _enc_anyvalue(x)) for x in v))
    if isinstance(v, dict):               # KeyValueList{ values = 1 }
        return pw.enc_field_msg(6, b"".join(
            pw.enc_field_msg(1, pw.enc_field_str(1, k) +
                             pw.enc_field_msg(2, _enc_anyvalue(x)))
            for k, x in v.items()))
    return pw.enc_field_str(1, str(v))


def _enc_attrs(fnum: int, attrs: dict[str, Any] | None) -> bytes:
    if not attrs:
        return b""
    return b"".join(
        pw.enc_field_msg(fnum, pw.enc_field_str(1, k) +
                         pw.enc_field_msg(2, _enc_anyvalue(v)))
        for k, v in attrs.items())


def encode_spans_otlp(spans: Iterable[dict]) -> bytes:
    """Flat span dicts → ExportTraceServiceRequest bytes.

    The inverse of `spans_from_otlp_proto`, used when the distributor tees
    spans that did not arrive as raw OTLP (Zipkin/Jaeger receivers, or
    after attribute truncation) — the tee is always OTLP on the wire
    (`sendToGenerators` `distributor.go:563` ships tempopb ResourceSpans).
    Spans are grouped into ResourceSpans by res_attrs content.
    """
    groups: dict[tuple, list[dict]] = {}
    for s in spans:
        ra = s.get("res_attrs") or {}
        if not ra and s.get("service"):
            ra = {"service.name": s["service"]}
        key = tuple(sorted((k, repr(v)) for k, v in ra.items()))
        groups.setdefault(key, []).append(s)
    out = []
    for _, group in groups.items():
        ra = group[0].get("res_attrs") or {}
        if not ra and group[0].get("service"):
            ra = {"service.name": group[0]["service"]}
        span_bufs = []
        for s in group:
            status = b""
            if s.get("status_message"):
                status += pw.enc_field_str(2, s["status_message"])
            if s.get("status_code"):
                status += pw.enc_field_varint(3, int(s["status_code"]))
            b = (pw.enc_field_bytes(1, s.get("trace_id", b"")) +
                 pw.enc_field_bytes(2, s.get("span_id", b"")))
            if s.get("parent_span_id"):
                b += pw.enc_field_bytes(4, s["parent_span_id"])
            b += pw.enc_field_str(5, s.get("name", ""))
            if s.get("kind"):
                b += pw.enc_field_varint(6, int(s["kind"]))
            # fields 7/8 are fixed64 in trace.proto (varint would decode as
            # unknown fields in conformant consumers)
            b += (pw.enc_field_fixed64(7, int(s.get("start_unix_nano", 0))) +
                  pw.enc_field_fixed64(8, int(s.get("end_unix_nano", 0))) +
                  _enc_attrs(9, s.get("attrs")))
            for ev in s.get("events") or ():
                b += pw.enc_field_msg(11, pw.enc_field_fixed64(
                    1, int(ev.get("time_unix_nano", 0))) +
                    pw.enc_field_str(2, ev.get("name", "")))
            for ln in s.get("links") or ():
                b += pw.enc_field_msg(13, pw.enc_field_bytes(
                    1, ln.get("trace_id", b"")) +
                    pw.enc_field_bytes(2, ln.get("span_id", b"")))
            if status:
                b += pw.enc_field_msg(15, status)
            span_bufs.append(pw.enc_field_msg(2, b))
        rs = (pw.enc_field_msg(1, _enc_attrs(1, ra)) +
              pw.enc_field_msg(2, b"".join(span_bufs)))
        out.append(pw.enc_field_msg(1, rs))
    return b"".join(out)


def slice_otlp_payload(raw: bytes, recs, wire_indices) -> bytes:
    """Rebuild an OTLP payload containing only `wire_indices` spans, by
    concatenating raw wire slices (no re-encoding). `recs` is the native
    scan's SpanRec array over `raw` (span_off/span_len + res_off/res_len
    byte ranges). The per-instance splitter of the generator tee — the
    analog of the per-trace proto re-marshal in `sendToGenerators`."""
    out = []
    cur_res: tuple[int, int] | None = None
    span_bufs: list[bytes] = []

    def flush() -> None:
        if not span_bufs:
            return
        ro, rl = cur_res
        rs = b""
        if ro >= 0:
            rs += pw.enc_field_msg(1, raw[ro:ro + rl])
        rs += pw.enc_field_msg(2, b"".join(span_bufs))
        out.append(pw.enc_field_msg(1, rs))
        span_bufs.clear()

    for i in sorted(wire_indices):
        res = (int(recs["res_off"][i]), int(recs["res_len"][i]))
        if res != cur_res:
            flush()
            cur_res = res
        o, ln = int(recs["span_off"][i]), int(recs["span_len"][i])
        span_bufs.append(pw.enc_field_msg(2, raw[o:o + ln]))
    flush()
    return b"".join(out)
