"""tempopb wire codec: protobuf bodies for the inter-service RPC seams.

The reference's services speak protobuf end to end (`pkg/tempopb/
tempo.proto:9-44`); round 2 carried JSON bodies under tempopb-named gRPC
methods — functional parity, not wire parity, and real CPU on the hot
push path (VERDICT r2 #3). This module hand-rolls the message codecs on
`proto_wire` (as the prompb remote-write codec already does): search
responses, query-range series, trace-by-id, push responses. Field
numbers follow tempo.proto where a direct counterpart exists
(TraceSearchMetadata 1-7, SpanSet/Span) and stay internal-only where the
reference nests deeper generated types.

Trace payloads themselves ride OTLP ResourceSpans bytes (tempopb.Trace
is OTLP-shaped), produced by `model.otlp.encode_spans_otlp`.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from tempo_tpu.model import proto_wire as pw
from tempo_tpu.obs.querystats import COUNTER_FIELDS, QueryStats


def _dec(buf: bytes) -> dict[int, list]:
    return pw.decode_fields(bytes(buf))


def _first(d: dict, n: int, default=None):
    v = d.get(n)
    return v[0] if v else default


def _s(v, default: str = "") -> str:
    return bytes(v).decode("utf-8", "replace") if v is not None else default


# -- search (SearchRequest / SearchResponse; tempo.proto SearchRequest) ----

def enc_search_request(query: str, limit: int, start_s: float | None,
                       end_s: float | None) -> bytes:
    out = pw.enc_field_str(1, query) + pw.enc_field_varint(2, int(limit))
    if start_s is not None:
        out += pw.enc_field_double(3, float(start_s))
    if end_s is not None:
        out += pw.enc_field_double(4, float(end_s))
    return out


def dec_search_request(buf: bytes) -> dict:
    d = _dec(buf)
    out = {"q": _s(_first(d, 1), "{ }"), "limit": _first(d, 2, 20)}
    if 3 in d:
        out["start"] = pw.f64(d[3][0])
    if 4 in d:
        out["end"] = pw.f64(d[4][0])
    return out


def _enc_kv(fnum: int, k: str, v) -> bytes:
    """Typed label pair: str → 2, float → 3, int → 4, bool → 5. Series
    labels carry numeric values (log2 histogram buckets, by(int-attr)
    groups) and the combiner keys on the EXACT labels tuple — stringified
    values would stop generator- and backend-side halves of one series
    from merging."""
    body = pw.enc_field_str(1, k)
    if isinstance(v, bool):
        body += pw.enc_field_varint(5, 1 if v else 0)
    elif isinstance(v, float):
        body += pw.enc_field_double(3, v)
    elif isinstance(v, int):
        body += pw.enc_field_varint(4, v & ((1 << 64) - 1))
    else:
        body += pw.enc_field_str(2, str(v))
    return pw.enc_field_msg(fnum, body)


def _dec_kv(buf: bytes) -> tuple[str, object]:
    d = _dec(buf)
    k = _s(_first(d, 1))
    if 3 in d:
        return k, pw.f64(d[3][0])
    if 4 in d:
        v = d[4][0]
        if v >= (1 << 63):
            v -= 1 << 64
        return k, v
    if 5 in d:
        return k, bool(d[5][0])
    return k, _s(_first(d, 2))


def _enc_spanset_span(sp: dict) -> bytes:
    out = (pw.enc_field_str(1, sp.get("spanID", "")) +
           pw.enc_field_str(2, sp.get("name", "")) +
           pw.enc_field_varint(3, int(sp.get("startTimeUnixNano", "0"))) +
           pw.enc_field_varint(4, int(sp.get("durationNanos", "0"))))
    for a in sp.get("attributes", ()):
        v = a.get("value", {})
        out += _enc_kv(5, a.get("key", ""),
                       v.get("stringValue", "") if isinstance(v, dict) else v)
    return out


def _dec_spanset_span(buf: bytes) -> dict:
    d = _dec(buf)
    out = {"spanID": _s(_first(d, 1)), "name": _s(_first(d, 2)),
           "startTimeUnixNano": str(_first(d, 3, 0)),
           "durationNanos": str(_first(d, 4, 0))}
    attrs = []
    for kv in d.get(5, ()):
        k, v = _dec_kv(kv)
        attrs.append({"key": k, "value": {"stringValue": v}})
    if attrs:
        out["attributes"] = attrs
    return out


def _enc_spanset(ss: dict) -> bytes:
    out = b"".join(pw.enc_field_msg(1, _enc_spanset_span(sp))
                   for sp in ss.get("spans", ()))
    out += pw.enc_field_varint(2, int(ss.get("matched", 0)))
    for a in ss.get("attributes", ()):
        v = a.get("value", {})
        out += _enc_kv(3, a.get("key", ""),
                       v.get("stringValue", "") if isinstance(v, dict) else v)
    return out


def _dec_spanset(buf: bytes) -> dict:
    d = _dec(buf)
    out = {"spans": [_dec_spanset_span(b) for b in d.get(1, ())],
           "matched": _first(d, 2, 0)}
    attrs = []
    for kv in d.get(3, ()):
        k, v = _dec_kv(kv)
        attrs.append({"key": k, "value": {"stringValue": v}})
    if attrs:
        out["attributes"] = attrs
    return out


def enc_trace_metadata(md) -> bytes:
    """One TraceSearchMetadata (tempo.proto fields 1-5, 7)."""
    out = (pw.enc_field_str(1, md.trace_id) +
           pw.enc_field_str(2, md.root_service_name) +
           pw.enc_field_str(3, md.root_trace_name) +
           pw.enc_field_varint(4, int(md.start_time_unix_nano)) +
           pw.enc_field_varint(5, int(md.duration_ms)))
    for ss in md.span_sets:
        out += pw.enc_field_msg(7, _enc_spanset(ss))
    return out


def dec_trace_metadata(buf: bytes):
    from tempo_tpu.traceql.engine import TraceSearchMetadata

    d = _dec(buf)
    return TraceSearchMetadata(
        trace_id=_s(_first(d, 1)),
        root_service_name=_s(_first(d, 2)),
        root_trace_name=_s(_first(d, 3)),
        start_time_unix_nano=_first(d, 4, 0),
        duration_ms=_first(d, 5, 0),
        span_sets=[_dec_spanset(b) for b in d.get(7, ())])


# SearchMetrics submessage layout (field 2 of SearchResponse). Field 1 is
# the legacy single `inspected` varint; fields 2.. follow querystats
# COUNTER_FIELDS order (skipping inspected_traces, which IS field 1), so
# old decoders that only read field 1 and old encoders that only write it
# stay wire-compatible in both directions. Field 15 carries the per-stage
# wall-time breakdown as repeated {1: stage name, 2: nanos} submessages.
_STATS_TAIL_FIELDS = tuple(
    (i + 2, name) for i, name in enumerate(
        f for f in COUNTER_FIELDS if f != "inspected_traces"))


def enc_query_stats(stats) -> bytes:
    """QueryStats → SearchMetrics submessage body."""
    out = pw.enc_field_varint(1, int(stats.inspected_traces))
    for fnum, name in _STATS_TAIL_FIELDS:
        v = int(getattr(stats, name))
        if v:
            out += pw.enc_field_varint(fnum, v)
    for s, ns in stats.stage_ns.items():
        out += pw.enc_field_msg(
            15, pw.enc_field_str(1, s) + pw.enc_field_varint(2, int(ns)))
    return out


def dec_query_stats(buf: bytes):
    """SearchMetrics submessage body → QueryStats (old single-`inspected`
    bodies decode with just inspected_traces set)."""
    d = _dec(buf)
    st = QueryStats()
    st.inspected_traces = _first(d, 1, 0)
    for fnum, name in _STATS_TAIL_FIELDS:
        setattr(st, name, _first(d, fnum, 0))
    for b in d.get(15, ()):
        sd = _dec(b)
        st.stage_ns[_s(_first(sd, 1))] = _first(sd, 2, 0)
    return st


def enc_search_response(mds: Sequence, *, inspected: int = 0,
                        final: bool = True, stats=None) -> bytes:
    """SearchResponse (+ `final` marker for the streaming diff variant).
    `stats` (QueryStats, optional) rides the SearchMetrics submessage —
    wire-compatible extension of the single `inspected` varint."""
    out = b"".join(pw.enc_field_msg(1, enc_trace_metadata(m)) for m in mds)
    if stats is not None:
        out += pw.enc_field_msg(2, enc_query_stats(stats))
    else:
        out += pw.enc_field_msg(2, pw.enc_field_varint(1, int(inspected)))
    out += pw.enc_field_varint(15, 1 if final else 0)
    return out


def dec_search_response(buf: bytes):
    """Returns (metadatas, final, inspected, stats). `inspected` keeps the
    legacy scalar (== stats.inspected_traces); `stats` is the full
    QueryStats, zero-filled when the peer sent the old format."""
    d = _dec(buf)
    mds = [dec_trace_metadata(b) for b in d.get(1, ())]
    stats = dec_query_stats(d[2][0]) if 2 in d else QueryStats()
    return mds, bool(_first(d, 15, 1)), stats.inspected_traces, stats


# -- query range (TimeSeries; internal dense-sample layout) -----------------

def enc_query_range_response(series: Iterable) -> bytes:
    out = []
    for s in series:
        body = b"".join(_enc_kv(1, k, v) for k, v in s.labels)
        vals = np.asarray(s.samples, "<f8").tobytes()
        body += pw.enc_field_bytes(2, vals)     # packed doubles
        out.append(pw.enc_field_msg(1, body))
    return b"".join(out)


def dec_query_range_response(buf: bytes):
    from tempo_tpu.traceql.engine_metrics import TimeSeries

    d = _dec(buf)
    out = []
    for b in d.get(1, ()):
        sd = _dec(b)
        labels = tuple(_dec_kv(kv) for kv in sd.get(1, ()))
        raw = _first(sd, 2, b"")
        samples = np.frombuffer(raw, "<f8").copy()  # copy: escape r/o view
        out.append(TimeSeries(labels=labels, samples=samples))
    return out


# -- trace by id ------------------------------------------------------------

def enc_trace_by_id_request(trace_id: bytes) -> bytes:
    return pw.enc_field_bytes(1, trace_id)


def dec_trace_by_id_request(buf: bytes) -> bytes:
    return bytes(_first(_dec(buf), 1, b""))


def enc_trace_by_id_response(spans: "list[dict] | None") -> bytes:
    """Found → field 1 = OTLP ResourceSpans bytes (tempopb.Trace shape);
    not found → empty body."""
    from tempo_tpu.model.otlp import encode_spans_otlp

    if spans is None:
        return b""
    return pw.enc_field_bytes(1, encode_spans_otlp(spans))


def dec_trace_by_id_response(buf: bytes) -> "list[dict] | None":
    from tempo_tpu.model.otlp import spans_from_otlp_proto

    if not buf:
        return None
    return list(spans_from_otlp_proto(bytes(_first(_dec(buf), 1, b""))))


# -- push response ----------------------------------------------------------

def enc_push_response(errors: Sequence) -> bytes:
    """Per-trace discard reasons; "" = accepted (the PushResponse
    errorsByTrace idea with string reasons)."""
    return b"".join(pw.enc_field_str(1, e or "") for e in errors)


def dec_push_response(buf: bytes, n: int) -> list:
    d = _dec(buf)
    got = [_s(v) or None for v in d.get(1, ())]
    if len(got) < n:                 # empty body = all accepted
        got += [None] * (n - len(got))
    return got


__all__ = [
    "enc_search_request", "dec_search_request",
    "enc_search_response", "dec_search_response",
    "enc_query_stats", "dec_query_stats",
    "enc_trace_metadata", "dec_trace_metadata",
    "enc_query_range_response", "dec_query_range_response",
    "enc_trace_by_id_request", "dec_trace_by_id_request",
    "enc_trace_by_id_response", "dec_trace_by_id_response",
    "enc_push_response", "dec_push_response",
]
