"""Tenant-fair request queue with worker pull.

Analog of `modules/frontend/queue/queue.go:59-211`: one FIFO per tenant,
round-robin dispatch across tenants (shard-fairness), a per-tenant
outstanding cap (`v1/frontend.go:40-41` default 2000), and batch dequeue
for workers (`max_batch_size` batching `v1/frontend.go:35`).
"""

from __future__ import annotations

import collections
import threading
from typing import Any


class QueueFull(RuntimeError):
    pass


class RequestQueue:
    def __init__(self, max_outstanding_per_tenant: int = 2000) -> None:
        self.max_outstanding = max_outstanding_per_tenant
        self._queues: dict[str, collections.deque] = {}
        self._tenants: collections.deque[str] = collections.deque()  # RR order
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._closed = False

    def enqueue(self, tenant: str, job: Any) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError("queue closed")
            q = self._queues.get(tenant)
            if q is None:
                q = self._queues[tenant] = collections.deque()
                self._tenants.append(tenant)
            if len(q) >= self.max_outstanding:
                raise QueueFull(f"tenant {tenant} has {len(q)} outstanding")
            q.append(job)
            self._cv.notify()

    def dequeue_batch(self, max_batch: int = 1,
                      timeout_s: float | None = None) -> list[Any]:
        """Pop up to max_batch jobs from ONE tenant (the next in round-robin
        order), like the frontend's per-tenant job batches."""
        with self._cv:
            if not self._wait_nonempty(timeout_s):
                return []
            # rotate to the next tenant with work
            for _ in range(len(self._tenants)):
                tenant = self._tenants[0]
                self._tenants.rotate(-1)
                q = self._queues.get(tenant)
                if q:
                    out = []
                    while q and len(out) < max_batch:
                        out.append(q.popleft())
                    if not q:
                        self._drop_tenant(tenant)
                    return out
            return []

    def _wait_nonempty(self, timeout_s: float | None) -> bool:
        if any(self._queues.values()):
            return True
        if timeout_s is None or timeout_s <= 0:
            return False
        self._cv.wait(timeout_s)
        return any(self._queues.values())

    def _drop_tenant(self, tenant: str) -> None:
        self._queues.pop(tenant, None)
        try:
            self._tenants.remove(tenant)
        except ValueError:
            pass

    def lengths(self) -> dict[str, int]:
        with self._lock:
            return {t: len(q) for t, q in self._queues.items()}

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
