"""Query frontend: shard, queue, dispatch, combine.

Analog of `modules/frontend`: per-endpoint pipelines shard a query into
block/row-group jobs targeting `target_bytes_per_job`
(`search_sharder.go:69-336`, `metrics_query_range_sharder.go:61-298`),
a tenant-fair queue hands jobs to querier workers
(`queue/queue.go:59-211`, worker pull model `v1/frontend.go:204-293`),
combiners merge partial results (`combiner/`), and SLO counters record
per-op latency/throughput conformance (`slos.go:29-38`).
"""

from tempo_tpu.frontend.frontend import Frontend, FrontendConfig
from tempo_tpu.frontend.queue import RequestQueue
from tempo_tpu.frontend.sharders import (
    SearchJob,
    backend_search_jobs,
    query_range_jobs,
    time_windows,
)

__all__ = [
    "Frontend", "FrontendConfig", "RequestQueue",
    "SearchJob", "backend_search_jobs", "query_range_jobs", "time_windows",
]
