"""Per-op SLO accounting.

Analog of `modules/frontend/slos.go:29-38`: a query is `within_slo` when
its latency beat the threshold OR its bytes/sec throughput beat the
throughput floor (slow-but-huge queries still count as good).
Counters follow the `tempo_query_frontend_queries_within_slo_total` shape.
"""

from __future__ import annotations

import dataclasses
import threading


@dataclasses.dataclass
class SLOConfig:
    duration_slo_s: float = 0.0        # 0 disables the latency criterion
    throughput_bytes_slo: float = 0.0  # 0 disables the throughput criterion


class SLORecorder:
    def __init__(self, per_op: dict[str, SLOConfig] | None = None) -> None:
        self.per_op = per_op or {}
        self._lock = threading.Lock()
        self.total: dict[tuple[str, str], int] = {}
        self.within: dict[tuple[str, str], int] = {}

    def record(self, op: str, tenant: str, latency_s: float,
               bytes_processed: int) -> bool:
        cfg = self.per_op.get(op, SLOConfig())
        good = False
        if cfg.duration_slo_s and latency_s < cfg.duration_slo_s:
            good = True
        if (cfg.throughput_bytes_slo and latency_s > 0
                and bytes_processed / latency_s > cfg.throughput_bytes_slo):
            good = True
        if not cfg.duration_slo_s and not cfg.throughput_bytes_slo:
            good = True
        key = (op, tenant)
        with self._lock:
            self.total[key] = self.total.get(key, 0) + 1
            if good:
                self.within[key] = self.within.get(key, 0) + 1
        return good
