"""Query sharding: time windows, block jobs, trace-id shards.

Analog of the frontend sharders:
- search: recent window → ingesters, older → backend block jobs of
  ~`target_bytes_per_job` built from row groups
  (`search_sharder.go:123-161,284-336`; 100MB default `search_sharder.go:25`)
- metrics: the same split with step-aligned window edges
  (`metrics_query_range_sharder.go:216-298`)
- trace-by-id: uniform trace-id keyspace shards
  (`traceid_sharder.go` + `pkg/blockboundary`)
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np

from tempo_tpu.backend.meta import BlockMeta

DEFAULT_TARGET_BYTES_PER_JOB = 100 * 1024 * 1024
DEFAULT_QUERY_BACKEND_AFTER_S = 15 * 60      # query_backend_after default 15m
DEFAULT_QUERY_INGESTERS_UNTIL_S = 30 * 60    # query_ingesters_until default 30m


@dataclasses.dataclass
class SearchJob:
    """One dispatchable unit: a block slice (or an ingester window)."""
    kind: str                       # "backend" | "ingester" | "generator"
    tenant: str
    meta: BlockMeta | None = None
    row_groups: tuple[int, ...] = ()
    start_s: float = 0.0
    end_s: float = 0.0


def time_windows(now_s: float, start_s: float, end_s: float,
                 backend_after_s: float = DEFAULT_QUERY_BACKEND_AFTER_S,
                 ingesters_until_s: float = DEFAULT_QUERY_INGESTERS_UNTIL_S,
                 ) -> tuple[tuple[float, float] | None, tuple[float, float] | None]:
    """Split [start,end] into (ingester_window, backend_window)
    (`search_sharder.go:166-283`, `backendRange` :266). Windows overlap in
    [now-ingesters_until, now-backend_after] — both sides are queried there,
    dedupe happens in the combiner."""
    ing_lo = now_s - ingesters_until_s
    be_hi = now_s - backend_after_s
    ingester = None
    if end_s > ing_lo:
        ingester = (max(start_s, ing_lo), end_s)
    backend = None
    if start_s < be_hi:
        backend = (start_s, min(end_s, be_hi))
    return ingester, backend


def backend_search_jobs(tenant: str, metas: Sequence[BlockMeta],
                        start_s: float, end_s: float,
                        target_bytes_per_job: int = DEFAULT_TARGET_BYTES_PER_JOB,
                        ) -> list[SearchJob]:
    """Blocks overlapping the window → jobs of N row groups ≈ target bytes
    (`backendRequests`/`buildBackendRequests` `search_sharder.go:284-336`)."""
    jobs: list[SearchJob] = []
    for m in metas:
        if m.end_time < start_s or m.start_time > end_s:
            continue
        n_rg = max(m.row_group_count, 1)
        bytes_per_rg = max(m.size_bytes // n_rg, 1)
        rg_per_job = max(int(target_bytes_per_job // bytes_per_rg), 1)
        for lo in range(0, n_rg, rg_per_job):
            jobs.append(SearchJob(
                "backend", tenant, meta=m,
                row_groups=tuple(range(lo, min(lo + rg_per_job, n_rg))),
                start_s=start_s, end_s=end_s))
    return jobs


def query_range_jobs(tenant: str, metas: Sequence[BlockMeta],
                     start_s: float, end_s: float, step_s: float,
                     target_bytes_per_job: int = 225 * 1024 * 1024,
                     ) -> list[SearchJob]:
    """Metrics jobs: same block slicing, window edges aligned down/up to
    step boundaries so partial steps never straddle a job boundary
    (`metrics_query_range_sharder.go:216-298`; 225MB/job per docs)."""
    if step_s > 0:
        start_s = np.floor(start_s / step_s) * step_s
        end_s = np.ceil(end_s / step_s) * step_s
    return [dataclasses.replace(j, kind="backend_metrics")
            for j in backend_search_jobs(tenant, metas, start_s, end_s,
                                         target_bytes_per_job)]


def trace_id_shards(n_shards: int) -> list[tuple[bytes, bytes]]:
    """Uniform [min,max) trace-id boundaries: adjacent shards SHARE the
    boundary value (shard i's max == shard i+1's min), like
    `CreateBlockBoundaries` (`pkg/blockboundary/blockboundary.go:9`)."""
    bounds = np.linspace(0.0, float(2 ** 64), n_shards + 1, dtype=np.float64)
    edges = [min(int(b), 2 ** 64 - 1).to_bytes(8, "big") + b"\x00" * 8
             for b in bounds]
    edges[-1] = b"\xff" * 16
    return [(edges[i], edges[i + 1]) for i in range(n_shards)]


def prune_blocks_rf(metas: Iterable[BlockMeta], rf_filter: int | None = None
                    ) -> list[BlockMeta]:
    """Keep blocks matching the requested replication factor (RF1 generator
    blocks vs RF3 ingester blocks, `frontend.go:357-375`)."""
    out = []
    for m in metas:
        if rf_filter is None or m.replication_factor == rf_filter:
            out.append(m)
    return out
