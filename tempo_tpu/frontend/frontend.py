"""The query-frontend service: per-endpoint pipelines over a job queue.

Mirrors `modules/frontend/frontend.go:100-224`: each public endpoint
(search, trace-by-id, query-range, tags) shards into jobs, dispatches via
the tenant-fair queue to querier workers (pull model — in-process threads
here, gRPC streams in the reference), and folds partial results through a
combiner with early exit. With no workers started, jobs execute inline
(the single-binary fast path).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Sequence

from tempo_tpu.db.tempodb import TempoDB
from tempo_tpu.frontend.queue import RequestQueue
from tempo_tpu.frontend.sharders import (
    SearchJob,
    backend_search_jobs,
    prune_blocks_rf,
    query_range_jobs,
    time_windows,
)
from tempo_tpu.frontend.slos import SLOConfig, SLORecorder
from tempo_tpu.model.combine import combine_spans, sort_spans
from tempo_tpu.obs import Registry, exponential_buckets
from tempo_tpu.obs import querystats
from tempo_tpu.obs.qlog import QueryLogger
from tempo_tpu.obs.querystats import QueryStats
from tempo_tpu.overrides import Overrides
from tempo_tpu.querier.querier import Querier
from tempo_tpu.traceql.engine import MetadataCombiner
from tempo_tpu.traceql.engine_metrics import (
    QueryRangeRequest,
    SeriesCombiner,
    TimeSeries,
    metrics_kind,
)


@dataclasses.dataclass
class FrontendConfig:
    target_bytes_per_job: int = 100 * 1024 * 1024
    metrics_target_bytes_per_job: int = 225 * 1024 * 1024
    concurrent_jobs: int = 1000
    max_outstanding_per_tenant: int = 2000
    max_batch_size: int = 5
    query_backend_after_s: float = 15 * 60
    query_ingesters_until_s: float = 30 * 60
    # RF of backend blocks eligible for metrics queries: 1 = generator
    # localblocks / blockbuilder output (the reference's rule); None admits
    # all blocks for single-writer deployments whose blocks are deduped
    metrics_block_rf: int | None = 1
    # historical metrics from sketch sidecars: blocks entirely behind the
    # cutoff whose sidecar can answer the query fold on the request
    # thread (no scan jobs); blocks without a sidecar fall back to jobs
    sidecar_folds: bool = True
    slo: dict[str, SLOConfig] = dataclasses.field(default_factory=dict)
    # structured query log (obs/qlog.py): errors always log; queries over
    # the sketch-estimated `qlog_slow_quantile` latency log as slow;
    # 1-in-`qlog_sample_every` of the rest logs, under a token-bucket cap
    qlog_slow_quantile: float = 0.95
    qlog_sample_every: int = 100
    qlog_rate_limit_per_s: float = 10.0


class _Job:
    __slots__ = ("job", "fn", "spec", "result", "error", "event", "_lock",
                 "_claimed", "enqueued_at", "queue_wait", "stats",
                 "traceparent")

    def __init__(self, job: SearchJob, fn: Callable[[SearchJob], Any],
                 spec: dict | None = None):
        self.job = job
        self.fn = fn
        self.spec = spec      # JSON-safe descriptor for remote workers
        # issuer's trace context, captured at construction: the worker
        # thread (or remote stream executor) re-enters it so querier /
        # tempodb spans join the REQUEST's tree, not the worker's —
        # contextvars do not cross the pool boundary, this string does
        from tempo_tpu.utils import tracing
        self.traceparent = tracing.tracer().traceparent()
        self.result: Any = None
        self.error: Exception | None = None
        self.event = threading.Event()
        self._lock = threading.Lock()
        self._claimed = False
        # queue-wait clock, attached at enqueue: observed at CLAIM time,
        # because remote worker streams claim a job and ship its spec
        # without ever invoking fn — only the claim is common to local
        # workers, remote streams, and the issuer's inline fallback
        self.enqueued_at: float | None = None
        self.queue_wait = None
        # per-job QueryStats: the executor (worker thread, remote stream
        # reader, or inline fallback) records into it; the issuer merges
        # it into the parent request scope at fold time — contextvars do
        # not cross the thread-pool boundary, per-job objects do
        self.stats = QueryStats()

    def try_claim(self) -> bool:
        """Exactly-once execution claim: local workers, remote worker
        streams, and the issuer's inline fallback race for the same queued
        job; whoever claims it runs it, everyone else skips."""
        with self._lock:
            if self._claimed:
                return False
            self._claimed = True
        if self.enqueued_at is not None:
            wait_s = time.perf_counter() - self.enqueued_at
            if self.queue_wait is not None:
                self.queue_wait.observe(wait_s)
            self.stats.add_stage_ns("queue_wait", int(wait_s * 1e9))
        return True

    def run(self) -> None:
        if not self.try_claim():
            return
        self.run_claimed()

    def run_claimed(self) -> None:
        from tempo_tpu.utils import tracing
        try:
            with tracing.adopted(self.traceparent), \
                    querystats.scope(self.stats):
                self.result = self.fn(self.job)
        except Exception as e:  # combiner decides whether partials suffice
            self.error = e
        self.event.set()


class UnsupportedMultiTenant(ValueError):
    """Client error: the endpoint does not support `a|b` org ids
    (→ HTTP 400, like the reference's unsupported middleware)."""


def split_tenants(tenant: str) -> list[str]:
    """`X-Scope-OrgID: a|b` → ["a", "b"] (order-preserving, deduped) —
    the multi-tenant federation split (`modules/frontend/frontend.go:
    113-136` multiTenantMiddleware / pkg tenant.ValidTenantID)."""
    seen: list[str] = []
    for t in tenant.split("|"):
        t = t.strip()
        if t and t not in seen:
            seen.append(t)
    return seen or [tenant]


class Frontend:
    def __init__(self, db: TempoDB, querier: Querier,
                 cfg: FrontendConfig | None = None,
                 overrides: Overrides | None = None,
                 generator_query_range: Callable[..., list[TimeSeries]] | None = None,
                 cache_provider=None,
                 registry: Registry | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.db = db
        self.querier = querier
        self.cfg = cfg or FrontendConfig()
        self.overrides = overrides or Overrides()
        self.generator_query_range = generator_query_range
        self.now = now
        self.queue = RequestQueue(self.cfg.max_outstanding_per_tenant)
        self.slos = SLORecorder(self.cfg.slo)
        self._workers: list[threading.Thread] = []
        self._remote_lock = threading.Lock()
        self._remote_workers = 0  # connected gRPC worker-pull streams
        self._stop = threading.Event()
        # search-response cache: sub-request results keyed by (block id,
        # query, shard) — blocks are immutable so no invalidation exists
        # (`modules/frontend/frontend.go:101` newFrontendCache +
        # `cache_keys.go` searchJobCacheKey)
        self._job_cache = None
        if cache_provider is not None:
            from tempo_tpu.backend.cache import ROLE_FRONTEND_SEARCH

            self._job_cache = cache_provider.cache_for(ROLE_FRONTEND_SEARCH)
        self.qlog = QueryLogger(
            slow_quantile=self.cfg.qlog_slow_quantile,
            sample_every=self.cfg.qlog_sample_every,
            rate_limit_per_s=self.cfg.qlog_rate_limit_per_s,
            now=now)
        # per-tenant read-cost accounting, fed once per finished request
        # from its merged QueryStats (render-time callback families — the
        # hot path never touches the registry)
        self._tenant_read_lock = threading.Lock()
        self._tenant_read_cost: dict[str, dict[str, int]] = {}
        # requests rejected with 503 under device-scheduler query
        # backpressure, by op (rendered via a callback family below)
        self.shed_requests: dict[str, int] = {}
        # per-op response-cache accounting (the aggregate cache_stats
        # dict cannot say WHICH endpoint is cold): hits/misses counted
        # at job-dispatch time in _run_jobs, keyed by endpoint op
        self._cache_ops: dict[str, dict[str, int]] = {}
        self.obs = registry if registry is not None else Registry()
        self._register_obs(self.obs)

    def _register_obs(self, reg: Registry) -> None:
        reg.counter_func(
            "tempo_query_frontend_queries_total",
            lambda: [(k, v) for k, v in self.slos.total.items()],
            help="Frontend queries, by endpoint op and tenant",
            labels=("op", "tenant"))
        reg.counter_func(
            "tempo_query_frontend_queries_within_slo_total",
            lambda: [(k, v) for k, v in self.slos.within.items()],
            help="Frontend queries that met the latency or throughput SLO",
            labels=("op", "tenant"))
        reg.counter_func(
            "tempo_query_frontend_cache_hits_total",
            lambda: [((), self.cache_stats["hits"])],
            help="Search-response cache hits")
        reg.counter_func(
            "tempo_query_frontend_cache_misses_total",
            lambda: [((), self.cache_stats["misses"])],
            help="Search-response cache misses")
        self.op_duration = reg.histogram(
            "tempo_query_frontend_request_duration_seconds",
            "Frontend query latency by endpoint op; observations over the "
            "op's SLO threshold carry the active trace id as an exemplar",
            labels=("op",))
        self.queue_wait = reg.histogram(
            "tempo_query_frontend_queue_wait_seconds",
            "Time a sharded sub-request spent in the tenant-fair queue "
            "before a worker claimed it")
        self.shard_fanout = reg.histogram(
            "tempo_query_frontend_shard_fanout",
            "Sub-requests one query sharded into",
            buckets=exponential_buckets(1.0, 2.0, 12))

        def read_cost(field):
            def fn():
                with self._tenant_read_lock:
                    return [((t,), c.get(field, 0))
                            for t, c in self._tenant_read_cost.items()]
            return fn

        reg.counter_func(
            "tempo_tpu_query_inspected_bytes_total",
            read_cost("inspected_bytes"),
            help="Bytes of block data inspected by queries, per tenant "
                 "(merged request-scoped QueryStats — read-cost accounting)",
            labels=("tenant",))
        reg.counter_func(
            "tempo_tpu_query_blocks_scanned_total",
            read_cost("blocks_scanned"),
            help="Backend block slices scanned by queries, per tenant",
            labels=("tenant",))
        reg.counter_func(
            "tempo_tpu_query_device_seconds_total",
            lambda: [(labels, ns / 1e9) for labels, ns in
                     read_cost("device_ns")()],
            help="Device-dispatch wall seconds consumed by queries, per "
                 "tenant (device-time-ledger attribution via "
                 "QueryStats.device_ns — the read-side twin of "
                 "tempo_devtime_tenant_device_seconds_total)",
            labels=("tenant",))

        def cache_by_op(field):
            def fn():
                with self._tenant_read_lock:
                    return [((op,), c.get(field, 0))
                            for op, c in self._cache_ops.items()]
            return fn

        reg.counter_func(
            "tempo_tpu_frontend_cache_hits_total", cache_by_op("hits"),
            help="Search-response cache hits by endpoint op (per-op twin "
                 "of tempo_query_frontend_cache_hits_total)",
            labels=("op",))
        reg.counter_func(
            "tempo_tpu_frontend_cache_misses_total", cache_by_op("misses"),
            help="Search-response cache misses by endpoint op (cacheable "
                 "sub-requests that had to execute)",
            labels=("op",))

        def shed():
            with self._tenant_read_lock:
                return [((op,), n) for op, n in self.shed_requests.items()]

        reg.counter_func(
            "tempo_query_frontend_shed_total", shed,
            help="Requests rejected with 503 + Retry-After because the "
                 "device scheduler's query class was saturated, by op",
            labels=("op",))
        reg.counter_func(
            "tempo_query_log_records_total",
            self.qlog.emitted_by_reason,
            help="Query-log emission outcomes (error/slow/sampled lines "
                 "written, suppressed = sampled-out or rate-limited)",
            labels=("reason",))

    def _record_op(self, op: str, tenant: str, latency_s: float,
                   nbytes: int) -> None:
        """SLO accounting + the op latency histogram. A request outside
        its SLO stamps the active self-tracing span's trace id as the
        observation's exemplar, so a p99 spike links to a concrete trace
        in the dogfood tenant."""
        good = self.slos.record(op, tenant, latency_s, nbytes)
        trace_id = None
        if not good:
            from tempo_tpu.utils import tracing
            trace_id = tracing.current_trace_id_hex()
            # tail-keep: an SLO-missing request's WHOLE tree survives
            # head sampling (the exemplar above only named the id; the
            # buffered spans are what make it retrievable)
            tracing.mark_keep()
        self.op_duration.observe(latency_s, (op,), trace_id=trace_id)

    @property
    def cache_stats(self) -> dict:
        """Hit/miss counters straight from the role cache (it counts under
        its own lock; duplicating here would race worker threads)."""
        c = self._job_cache
        return {"hits": getattr(c, "hits", 0),
                "misses": getattr(c, "misses", 0)}

    def cache_hit_ratio(self) -> float:
        s = self.cache_stats
        total = s["hits"] + s["misses"]
        return s["hits"] / total if total else 0.0

    @property
    def remote_workers(self) -> int:
        return self._remote_workers

    def remote_worker_attached(self) -> None:
        with self._remote_lock:
            self._remote_workers += 1

    def remote_worker_detached(self) -> None:
        with self._remote_lock:
            self._remote_workers -= 1

    # -- worker pool (querier pull model) ----------------------------------

    def start_workers(self, n: int = 2) -> None:
        def loop():
            while not self._stop.is_set():
                batch = self.queue.dequeue_batch(self.cfg.max_batch_size,
                                                 timeout_s=0.2)
                for j in batch:
                    j.run()
        self._workers = [threading.Thread(target=loop, daemon=True)
                         for _ in range(n)]
        for t in self._workers:
            t.start()

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._workers:
            t.join(timeout=2)
        self.queue.close()

    def _check_device_pressure(self, op: str) -> None:
        """Shed NEW queries when the device scheduler's query class is
        saturated (503 + Retry-After at the API) — admitted work keeps
        running; backpressure applies at the request boundary, like the
        ingest-side 429 at the distributor. Sheds are counted per op
        (tempo_query_frontend_shed_total) so an operator can see the
        503s the scheduler's own shed counter (which tracks JOBS, not
        requests) does not cover."""
        from tempo_tpu import sched
        sc = sched.scheduler()
        if sc is not None and sc.query_saturated():
            with self._tenant_read_lock:
                self.shed_requests[op] = self.shed_requests.get(op, 0) + 1
            raise sched.QueryBackpressure(sc.cfg.retry_after_s)

    def _note_cache(self, op: str, hits: int = 0, misses: int = 0) -> None:
        with self._tenant_read_lock:
            c = self._cache_ops.setdefault(op, {})
            c["hits"] = c.get("hits", 0) + hits
            c["misses"] = c.get("misses", 0) + misses

    def _run_jobs(self, tenant: str, jobs: Sequence[SearchJob],
                  fn: Callable[[SearchJob], Any],
                  on_result: Callable[[Any], bool],
                  spec_fn: Callable[[SearchJob], dict] | None = None,
                  cache: "tuple | None" = None, op: str = "search") -> int:
        """Dispatch jobs; fold results via on_result (return False = early
        exit, like streaming combiners cancelling remaining work). Raises
        the first job error — a failed sub-query fails the whole query, as
        partial silent results are worse than an error. Keeps at most
        `concurrent_jobs` in flight so wide queries never trip the
        per-tenant outstanding cap. Returns bytes processed (SLO).

        `cache` = (key_fn, encode, decode): the search-response cache ware
        (`frontend.go:101`). Hits are consulted BEFORE dispatch and writes
        happen at fold time, so cached sub-requests are skipped no matter
        who would have executed them — inline, local worker, or remote
        worker stream. key_fn returning None marks a job uncacheable."""
        self.shard_fanout.observe(float(len(jobs)))
        querystats.add(total_jobs=len(jobs))
        key_fn = encode = decode = None
        if cache is not None and self._job_cache is not None:
            key_fn, encode, decode = cache

        hits: dict[int, Any] = {}
        pending: list[tuple[int, "_Job"]] = []
        wrapped: list = []
        n_hit = n_miss = 0
        for idx, j in enumerate(jobs):
            key = key_fn(j) if key_fn else None
            raw = self._job_cache.get(key) if key is not None else None
            if raw is not None:
                hits[idx] = decode(raw)
                wrapped.append(None)
                n_hit += 1
            else:
                if key is not None:
                    n_miss += 1       # cacheable but had to execute
                wj = _Job(j, fn, spec_fn(j) if spec_fn else None)
                wrapped.append(wj)
                pending.append((idx, wj))
        if n_hit or n_miss:
            self._note_cache(op, hits=n_hit, misses=n_miss)

        nbytes = 0

        def fold(idx, job, result) -> bool:
            nonlocal nbytes
            if key_fn and idx not in hits:
                key = key_fn(job)
                if key is not None:
                    try:
                        self._job_cache.put(key, encode(result))
                    except Exception:
                        pass           # cache write is best-effort
            nbytes += _job_bytes(job)
            # shard stats → parent request scope (per-job accumulators for
            # executed jobs; a cache hit inspected nothing this time)
            wj = wrapped[idx]
            if wj is not None:
                querystats.absorb(wj.stats)
            else:
                querystats.add(cache_hits=1)
            querystats.add(completed_jobs=1)
            with querystats.stage("merge"):
                return on_result(result)

        if not self._workers and not self.remote_workers:
            for idx, j in enumerate(jobs):    # inline single-binary path
                if idx in hits:
                    if not fold(idx, j, hits[idx]):
                        break
                    continue
                wj = wrapped[idx]
                wj.run()
                if wj.error is not None:
                    raise wj.error
                if not fold(idx, j, wj.result):
                    break
            return nbytes
        window = max(1, min(self.cfg.concurrent_jobs,
                            self.cfg.max_outstanding_per_tenant - 1))
        for _, wj in pending[:window]:
            self._enqueue_timed(tenant, wj)
        qi = window                 # next pending job to enqueue
        for idx, j in enumerate(jobs):
            if idx in hits:
                if not fold(idx, j, hits[idx]):
                    break
                continue
            wj = wrapped[idx]
            while not wj.event.wait(timeout=0.5):
                if self._stop.is_set():
                    raise RuntimeError("frontend shutting down")
                if not self._workers and not self.remote_workers \
                        and wj.try_claim():
                    # every worker disconnected with this job still queued:
                    # run it inline rather than hanging the query forever
                    wj.run_claimed()
            if qi < len(pending):
                self._enqueue_timed(tenant, pending[qi][1])
                qi += 1
            if wj.error is not None:
                raise wj.error
            if not fold(idx, j, wj.result):
                break
        return nbytes

    def _enqueue_timed(self, tenant: str, wj: "_Job") -> None:
        """Enqueue with the queue-wait clock attached: the wait histogram
        observes enqueue → claim, whoever claims (local worker, remote
        stream, or the issuer's inline fallback)."""
        wj.enqueued_at = time.perf_counter()
        wj.queue_wait = self.queue_wait
        self.queue.enqueue(tenant, wj)

    # -- endpoints ---------------------------------------------------------

    def _finish_query(self, op: str, tenant: str, query: str,
                      duration_s: float, st: QueryStats,
                      error: Exception | None = None,
                      extra: dict | None = None) -> None:
        """Close out one frontend request: per-tenant read-cost counters
        and exactly one structured "query complete" log decision — called
        once per public endpoint invocation, success or failure."""
        from tempo_tpu.utils import tracing

        # normalize the label the same way every per-tenant metric does
        # (' a ' → 'a', 'a|a' → 'a'); a true federation keeps its composite
        # 'a|b' label — merged stats cannot be apportioned per member
        tenant = "|".join(split_tenants(tenant))
        sm = st.search_metrics()
        with self._tenant_read_lock:
            cost = self._tenant_read_cost.setdefault(tenant, {})
            cost["inspected_bytes"] = \
                cost.get("inspected_bytes", 0) + sm["inspectedBytes"]
            cost["blocks_scanned"] = \
                cost.get("blocks_scanned", 0) + sm["blocksScanned"]
            cost["device_ns"] = \
                cost.get("device_ns", 0) + sm["deviceNanos"]
        # overload-sampling exemplar: while the write path is sampling,
        # every emitted query line says so — rates/quantiles in this
        # window describe an upscaled sampled stream, and a reader of a
        # slow line must be able to tell
        from tempo_tpu import sched
        keep = sched.ingest_keep_fraction()
        merged = dict(extra or {})
        if keep < 1.0:
            merged["ingestKeepFraction"] = round(keep, 4)
        # selfTraceId: present ONLY when this request's self-trace tree
        # was (or will be) kept by tail-keep — the line then links
        # directly to a retrievable trace in the ops tenant (runbook
        # "Reading the query log")
        kept = tracing.kept_trace_id_hex()
        if kept:
            merged["selfTraceId"] = kept
        self.qlog.log_query(
            op=op, tenant=tenant, query=query,
            status="error" if error is not None else "ok",
            duration_s=duration_s, stats=st,
            trace_id=tracing.current_trace_id_hex(),
            error=str(error) if error is not None else None,
            extra=merged or None)

    def search(self, tenant: str, query: str, *, limit: int = 20,
               start_s: float | None = None, end_s: float | None = None,
               on_partial: Callable[[list], None] | None = None
               ) -> list:
        """on_partial (optional) receives the combiner's current results
        after each fold — the hook the streaming gRPC endpoint uses to
        emit diff responses (`combiner/search.go`)."""
        from tempo_tpu.utils import tracing
        self._check_device_pressure("search")
        t0 = self.now()
        with tracing.span_for_tenant("frontend.Search", tenant, query=query), \
                querystats.ensure_scope() as st:
            try:
                res = self._search_fanout(tenant, query, limit=limit,
                                          start_s=start_s, end_s=end_s,
                                          on_partial=on_partial)
            except Exception as e:
                self._finish_query("search", tenant, query,
                                   self.now() - t0, st, error=e)
                raise
            self._finish_query("search", tenant, query, self.now() - t0, st)
            return res

    def _search_fanout(self, tenant: str, query: str, *, limit: int,
                       start_s: float | None, end_s: float | None,
                       on_partial: Callable[[list], None] | None) -> list:
        tenants = split_tenants(tenant)
        if len(tenants) == 1:
            # normalized: 'a|a', 'a|', ' a ' all mean tenant 'a'
            return self._search(tenants[0], query, limit=limit,
                                start_s=start_s, end_s=end_s,
                                on_partial=on_partial)
        # multi-tenant federation: fan out per tenant, merge through
        # the same top-N combiner (frontend.go:113-136)
        comb = MetadataCombiner(limit)
        for t in tenants:
            for md in self._search(t, query, limit=limit,
                                   start_s=start_s, end_s=end_s):
                comb.add(md)
            if on_partial is not None:
                on_partial(comb.results())
            if comb.exhausted():
                break               # top-N full: skip remaining tenants
        return comb.results()

    def _search(self, tenant: str, query: str, *, limit: int = 20,
                start_s: float | None = None, end_s: float | None = None,
                on_partial: Callable[[list], None] | None = None) -> list:
        t0 = self.now()
        end_s = end_s if end_s is not None else self.now()
        start_s = start_s if start_s is not None else end_s - 3600.0
        ing_win, be_win = time_windows(
            self.now(), start_s, end_s,
            self.cfg.query_backend_after_s, self.cfg.query_ingesters_until_s)
        combiner = MetadataCombiner(limit)
        nbytes = 0
        if ing_win is not None:
            for md in self.querier.search_recent(tenant, query, limit,
                                                 *ing_win):
                combiner.add(md)
            if on_partial is not None:
                on_partial(combiner.results())
        if be_win is not None and not combiner.exhausted():
            metas = self.db.blocks(tenant, be_win[0], be_win[1])
            querystats.add(total_blocks=len(metas))
            jobs = backend_search_jobs(tenant, metas, be_win[0], be_win[1],
                                       self.cfg.target_bytes_per_job)

            def fold(res) -> bool:
                for md in res:
                    combiner.add(md)
                if on_partial is not None:
                    on_partial(combiner.results())
                return not combiner.exhausted()

            def search_key(j) -> str:
                # times join the key only when the window cuts INTO the
                # block; a fully-covered block's results are window-free
                # (`cache_keys.go` searchJobCacheKey semantics)
                m = j.meta
                tpart = ("" if j.start_s <= m.start_time
                         and j.end_s >= m.end_time
                         else f":{j.start_s}:{j.end_s}")
                return (f"sj:{tenant}:{m.block_id}:{_qhash(query)}:"
                        f"{','.join(map(str, j.row_groups))}:{limit}{tpart}")

            nbytes += self._run_jobs(
                tenant, jobs,
                lambda j: self.querier.search_block(
                    tenant, query, j.meta, j.row_groups, limit,
                    j.start_s, j.end_s),
                fold,
                spec_fn=lambda j: {
                    "kind": "search_block", "tenant": tenant,
                    "query": query, "meta": j.meta.to_json(),
                    "row_groups": list(j.row_groups), "limit": limit,
                    "start_s": j.start_s, "end_s": j.end_s},
                cache=(search_key, _encode_metadata, _decode_metadata),
                op="search")
        self._record_op("search", tenant, self.now() - t0, nbytes)
        return combiner.results()

    def find_trace(self, tenant: str, trace_id: bytes,
                   start_s: float | None = None, end_s: float | None = None
                   ) -> list[dict] | None:
        t0 = self.now()
        spans: list[dict] = []
        for t in split_tenants(tenant):
            got = self.querier.find_trace_by_id(t, trace_id, start_s, end_s)
            if got:
                spans.extend(got)
        self._record_op("traces", tenant, self.now() - t0,
                        len(spans) * 200)
        return sort_spans(combine_spans(spans)) if spans else None

    def query_range(self, tenant: str, query: str, *,
                    start_s: float, end_s: float, step_s: float = 60.0,
                    on_partial: Callable[[list], None] | None = None
                    ) -> list[TimeSeries]:
        """TraceQL metrics: recent window from generators (RF1 local
        blocks), older from backend jobs; job series merge via
        SeriesCombiner then final quantile/rate pass
        (`metrics_query_range_sharder.go` + `combiner/metrics_query_range.go`).

        `on_partial` (optional) receives the current FINALIZED series set
        after each contributing sub-result — the incremental feed behind
        the streaming MetricsQueryRange endpoint (diffed there)."""
        from tempo_tpu.utils import tracing
        tenants = split_tenants(tenant)
        if len(tenants) > 1:
            # the reference mounts newMultiTenantUnsupportedMiddleware on
            # the metrics endpoints (frontend.go:163-175 analog)
            raise UnsupportedMultiTenant(
                "multi-tenant query of the metrics endpoint is not supported")
        self._check_device_pressure("metrics")
        t0 = self.now()
        # the recurring-query identity (obs/queryfp.py) rides every
        # "query complete" line, so the hot set qlog sees and the set
        # the materializer serves are greppably the same thing
        from tempo_tpu.obs.queryfp import query_fingerprint
        fp_extra = {"queryFp": query_fingerprint("metrics", query, step_s)}
        with tracing.span_for_tenant("frontend.QueryRange", tenants[0],
                                     query=query), \
                querystats.ensure_scope() as st:
            try:
                res = self._query_range(tenants[0], query, start_s=start_s,
                                        end_s=end_s, step_s=step_s,
                                        on_partial=on_partial)
            except Exception as e:
                self._finish_query("metrics", tenants[0], query,
                                   self.now() - t0, st, error=e,
                                   extra=fp_extra)
                raise
            self._finish_query("metrics", tenants[0], query,
                               self.now() - t0, st, extra=fp_extra)
            return res

    def _query_range(self, tenant: str, query: str, *,
                     start_s: float, end_s: float, step_s: float = 60.0,
                     on_partial: Callable[[list], None] | None = None
                     ) -> list[TimeSeries]:
        t0 = self.now()
        req = QueryRangeRequest(query=query,
                                start_ns=int(start_s * 1e9),
                                end_ns=int(end_s * 1e9),
                                step_ns=int(step_s * 1e9))
        # materialized-view tier: a subscribed query whose grid covers
        # the window is a slice + final pass — no generator recompute,
        # no backend jobs. Misses feed qlog's recurrence counter, which
        # drives auto-subscription of the hot set.
        from tempo_tpu import matview
        mv = matview.materializer()
        if mv is not None:
            got = mv.read(tenant, req)
            if got is not None:
                comb = SeriesCombiner(metrics_kind(query), req.n_steps)
                comb.add_all(got)
                self._record_op("metrics", tenant, self.now() - t0, 0)
                with querystats.stage("combine"):
                    res = comb.final(req)
                if on_partial is not None:
                    on_partial(res)
                return res
            mv.consider_auto_subscribe(
                tenant, query, step_s,
                self.qlog.note_fingerprint(mv.fingerprint(query, step_s)))
        # single cutoff, not overlapping windows: generators own
        # (cutoff, end], backend RF1 blocks own [start, cutoff] — sub-results
        # keep the full step grid and clip observations to their side, so
        # nothing is counted twice (TrimToBefore/After split,
        # `metrics_query_range_sharder.go:125-190`)
        cutoff_s = self.now() - self.cfg.query_backend_after_s
        cutoff_ns = int(cutoff_s * 1e9)
        # sidecar fold tier (block/sidecar.py): for a fold-eligible
        # rate()/quantile_over_time(duration) query, blocks entirely
        # behind the cutoff that carry a sketch sidecar are answered by
        # folding ~15 floats per series instead of scanning spans. The
        # tier only engages when some block will ACTUALLY fold (meta
        # flags are enough to decide — no sidecar reads yet); quantiles
        # then ride the moments axis END TO END — generator shards, scan
        # fallbacks and folds all emit __moment series, or the combiner
        # would mix them with log2 __bucket partials and emit the
        # ("p", q) output series twice
        plan = (self.db.sidecar_plan(query)
                if self.cfg.sidecar_folds and start_s < cutoff_s else None)
        metas: list = []
        if start_s < cutoff_s:
            metas = prune_blocks_rf(
                self.db.blocks(tenant, start_s, min(end_s, cutoff_s)),
                self.cfg.metrics_block_rf)
        if plan is not None and not any(
                m.sidecar and m.end_time * 1e9 < cutoff_ns for m in metas):
            plan = None
        if plan is not None and plan.quantile:
            req = dataclasses.replace(req, moments=True)
        comb = SeriesCombiner(metrics_kind(query), req.n_steps)
        nbytes = 0
        if end_s > cutoff_s and self.generator_query_range is not None:
            comb.add_all(self.generator_query_range(
                tenant, req, clip_start_ns=cutoff_ns))
            if on_partial is not None:
                on_partial(comb.final(req))
        if start_s < cutoff_s:
            # metrics read ONLY RF1 blocks (generator localblocks /
            # blockbuilder output) — ingester RF3 blocks hold every trace 3x
            # (`blockMetasForSearch(..., rf=1)` sharder :190). Configurable
            # for RF-deduped (compacted single-writer) setups.
            querystats.add(total_blocks=len(metas))
            # folds run inline on the request thread — each is a handful
            # of host flops over sidecar rows; blocks without a usable
            # sidecar (or straddling the moving cutoff) fall back to jobs
            scan_metas = []
            for m in metas:
                got = None
                if plan is not None and m.sidecar \
                        and m.end_time * 1e9 < cutoff_ns:
                    got = self.db.sidecar_series(tenant, req, m, plan,
                                                 clip_end_ns=cutoff_ns)
                if got is None:
                    scan_metas.append(m)
                else:
                    comb.add_all(got)
            if len(scan_metas) != len(metas) and on_partial is not None:
                on_partial(comb.final(req))
            jobs = query_range_jobs(tenant, scan_metas, start_s,
                                    min(end_s, cutoff_s), step_s,
                                    self.cfg.metrics_target_bytes_per_job)

            def fold(res) -> bool:
                comb.add_all(res)
                if on_partial is not None:   # folds run on THIS thread
                    on_partial(comb.final(req))
                return True

            def qr_key(j) -> "str | None":
                # cacheable only when the moving cutoff cannot affect the
                # block (block entirely before it); the clip then drops
                # out of the key and old blocks stay cacheable forever
                m = j.meta
                if m.end_time * 1e9 >= cutoff_ns:
                    return None
                return (f"qj:{tenant}:{m.block_id}:{_qhash(query)}:"
                        f"{','.join(map(str, j.row_groups))}:"
                        f"{req.start_ns}:{req.end_ns}:{req.step_ns}"
                        f"{':m' if req.moments else ''}")

            nbytes += self._run_jobs(
                tenant, jobs,
                lambda j: self.querier.query_range_block(
                    tenant, req, j.meta, j.row_groups,
                    clip_end_ns=cutoff_ns),
                fold,
                spec_fn=lambda j: {
                    "kind": "query_range_block", "tenant": tenant,
                    "query": query, "start_ns": req.start_ns,
                    "end_ns": req.end_ns, "step_ns": req.step_ns,
                    "moments": req.moments,
                    "meta": j.meta.to_json(),
                    "row_groups": list(j.row_groups),
                    "clip_end_ns": cutoff_ns},
                cache=(qr_key, _encode_series, _decode_series),
                op="metrics")
        self._record_op("metrics", tenant, self.now() - t0, nbytes)
        # the cross-shard/cross-job fold happens here (lazily): on the
        # serving mesh, count-exact kinds collapse into one in-mesh
        # reduce (see SeriesCombiner) — stage-timed so qlog shows where
        # combine cost went
        with querystats.stage("combine"):
            return comb.final(req)

    def subscribe_query(self, tenant: str, query: str, step_s: float
                        ) -> "tuple[bool, str]":
        """Explicit materialized-view subscription (the API half of the
        matview tier; the other half is qlog-recurrence auto-subscribe).
        Returns (ok, reason-when-refused)."""
        from tempo_tpu import matview
        mv = matview.materializer()
        if mv is None:
            return False, "matview tier disabled"
        sub, why = mv.subscribe(tenant, query, step_s)
        return sub is not None, why

    def unsubscribe_query(self, tenant: str, query: str,
                          step_s: float) -> bool:
        from tempo_tpu import matview
        mv = matview.materializer()
        return mv is not None and mv.unsubscribe(tenant, query, step_s)

    def decode_job_result(self, spec: dict, result):
        """Decode a remote worker's JSON job result back into the objects
        the fold expects (the inverse of `execute_job_spec`). Shares the
        cache codecs so the remote path and the cache path cannot drift."""
        import json

        if spec["kind"] == "search_block":
            return _decode_metadata(json.dumps(result or []).encode())
        if spec["kind"] == "query_range_block":
            return _decode_series(json.dumps(result or []).encode())
        raise ValueError(f"unknown job kind {spec['kind']!r}")

    def tag_names(self, tenant: str,
                  on_partial: Callable[[dict], None] | None = None
                  ) -> dict[str, list[str]]:
        t0 = self.now()
        merged: dict[str, list[str]] = {}

        def fold(partial: dict[str, list[str]]) -> None:
            for scope, names in partial.items():
                cur = merged.setdefault(scope, [])
                cur.extend(n for n in names if n not in cur)

        def hook(partial: dict[str, list[str]]) -> None:
            # partial snapshots are cumulative; fold dedupes, so re-folding
            # a superset later (the final return) is idempotent
            fold(partial)
            on_partial({k: sorted(v) for k, v in merged.items()})

        for t in split_tenants(tenant):
            fold(self.querier.tag_names(
                t, on_partial=hook if on_partial is not None else None))
        for scope in merged:
            merged[scope] = sorted(merged[scope])
        self._record_op("metadata", tenant, self.now() - t0, 0)
        return merged

    def tag_values(self, tenant: str, name: str, limit: int = 1000,
                   on_partial: Callable[[list], None] | None = None
                   ) -> list[dict]:
        t0 = self.now()
        out: list[dict] = []
        seen: set = set()

        def fold(values: list[dict]) -> None:
            for v in values:
                key = (v.get("type"), v.get("value"))
                if key not in seen:
                    seen.add(key)
                    out.append(v)

        def hook(partial: list[dict]) -> None:
            fold(partial)
            on_partial(out[:limit])

        for t in split_tenants(tenant):
            # each tenant is asked for the FULL limit: cross-tenant
            # duplicates collapse in `seen`, so a smaller ask could
            # starve distinct values hiding behind shared ones
            fold(self.querier.tag_values(
                t, name, limit,
                on_partial=hook if on_partial is not None else None))
        self._record_op("metadata", tenant, self.now() - t0, 0)
        return out[:limit]


def _qhash(query: str) -> str:
    import hashlib

    return hashlib.sha1(query.encode()).hexdigest()[:16]


def _encode_metadata(res) -> bytes:
    import json

    return json.dumps([m.to_json() for m in res]).encode()


def _decode_metadata(raw: bytes):
    import json

    from tempo_tpu.traceql.engine import TraceSearchMetadata

    return [TraceSearchMetadata.from_json(t) for t in json.loads(raw)]


def _encode_series(res) -> bytes:
    import json

    return json.dumps([
        {"labels": [[k, v] for k, v in s.labels],
         "samples": list(map(float, s.samples)),
         "exemplars": s.exemplars} for s in res]).encode()


def _decode_series(raw: bytes):
    import json

    import numpy as np

    return [TimeSeries(labels=tuple((k, v) for k, v in s["labels"]),
                       samples=np.asarray(s["samples"], np.float64),
                       exemplars=list(s.get("exemplars", [])))
            for s in json.loads(raw)]


def _job_bytes(job: SearchJob) -> int:
    if job.meta is None:
        return 0
    n_rg = max(job.meta.row_group_count, 1)
    return int(job.meta.size_bytes * (len(job.row_groups) or n_rg) / n_rg)
