"""The query-frontend service: per-endpoint pipelines over a job queue.

Mirrors `modules/frontend/frontend.go:100-224`: each public endpoint
(search, trace-by-id, query-range, tags) shards into jobs, dispatches via
the tenant-fair queue to querier workers (pull model — in-process threads
here, gRPC streams in the reference), and folds partial results through a
combiner with early exit. With no workers started, jobs execute inline
(the single-binary fast path).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Sequence

from tempo_tpu.db.tempodb import TempoDB
from tempo_tpu.frontend.queue import RequestQueue
from tempo_tpu.frontend.sharders import (
    SearchJob,
    backend_search_jobs,
    prune_blocks_rf,
    query_range_jobs,
    time_windows,
)
from tempo_tpu.frontend.slos import SLOConfig, SLORecorder
from tempo_tpu.model.combine import combine_spans, sort_spans
from tempo_tpu.overrides import Overrides
from tempo_tpu.querier.querier import Querier
from tempo_tpu.traceql.engine import MetadataCombiner
from tempo_tpu.traceql.engine_metrics import (
    QueryRangeRequest,
    SeriesCombiner,
    TimeSeries,
    metrics_kind,
)


@dataclasses.dataclass
class FrontendConfig:
    target_bytes_per_job: int = 100 * 1024 * 1024
    metrics_target_bytes_per_job: int = 225 * 1024 * 1024
    concurrent_jobs: int = 1000
    max_outstanding_per_tenant: int = 2000
    max_batch_size: int = 5
    query_backend_after_s: float = 15 * 60
    query_ingesters_until_s: float = 30 * 60
    # RF of backend blocks eligible for metrics queries: 1 = generator
    # localblocks / blockbuilder output (the reference's rule); None admits
    # all blocks for single-writer deployments whose blocks are deduped
    metrics_block_rf: int | None = 1
    slo: dict[str, SLOConfig] = dataclasses.field(default_factory=dict)


class _Job:
    __slots__ = ("job", "fn", "spec", "result", "error", "event", "_lock",
                 "_claimed")

    def __init__(self, job: SearchJob, fn: Callable[[SearchJob], Any],
                 spec: dict | None = None):
        self.job = job
        self.fn = fn
        self.spec = spec      # JSON-safe descriptor for remote workers
        self.result: Any = None
        self.error: Exception | None = None
        self.event = threading.Event()
        self._lock = threading.Lock()
        self._claimed = False

    def try_claim(self) -> bool:
        """Exactly-once execution claim: local workers, remote worker
        streams, and the issuer's inline fallback race for the same queued
        job; whoever claims it runs it, everyone else skips."""
        with self._lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def run(self) -> None:
        if not self.try_claim():
            return
        self.run_claimed()

    def run_claimed(self) -> None:
        try:
            self.result = self.fn(self.job)
        except Exception as e:  # combiner decides whether partials suffice
            self.error = e
        self.event.set()


class Frontend:
    def __init__(self, db: TempoDB, querier: Querier,
                 cfg: FrontendConfig | None = None,
                 overrides: Overrides | None = None,
                 generator_query_range: Callable[..., list[TimeSeries]] | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.db = db
        self.querier = querier
        self.cfg = cfg or FrontendConfig()
        self.overrides = overrides or Overrides()
        self.generator_query_range = generator_query_range
        self.now = now
        self.queue = RequestQueue(self.cfg.max_outstanding_per_tenant)
        self.slos = SLORecorder(self.cfg.slo)
        self._workers: list[threading.Thread] = []
        self._remote_lock = threading.Lock()
        self._remote_workers = 0  # connected gRPC worker-pull streams
        self._stop = threading.Event()

    @property
    def remote_workers(self) -> int:
        return self._remote_workers

    def remote_worker_attached(self) -> None:
        with self._remote_lock:
            self._remote_workers += 1

    def remote_worker_detached(self) -> None:
        with self._remote_lock:
            self._remote_workers -= 1

    # -- worker pool (querier pull model) ----------------------------------

    def start_workers(self, n: int = 2) -> None:
        def loop():
            while not self._stop.is_set():
                batch = self.queue.dequeue_batch(self.cfg.max_batch_size,
                                                 timeout_s=0.2)
                for j in batch:
                    j.run()
        self._workers = [threading.Thread(target=loop, daemon=True)
                         for _ in range(n)]
        for t in self._workers:
            t.start()

    def shutdown(self) -> None:
        self._stop.set()
        for t in self._workers:
            t.join(timeout=2)
        self.queue.close()

    def _run_jobs(self, tenant: str, jobs: Sequence[SearchJob],
                  fn: Callable[[SearchJob], Any],
                  on_result: Callable[[Any], bool],
                  spec_fn: Callable[[SearchJob], dict] | None = None) -> int:
        """Dispatch jobs; fold results via on_result (return False = early
        exit, like streaming combiners cancelling remaining work). Raises
        the first job error — a failed sub-query fails the whole query, as
        partial silent results are worse than an error. Keeps at most
        `concurrent_jobs` in flight so wide queries never trip the
        per-tenant outstanding cap. Returns bytes processed (SLO)."""
        wrapped = [_Job(j, fn, spec_fn(j) if spec_fn else None) for j in jobs]
        nbytes = 0
        if not self._workers and not self.remote_workers:
            for wj in wrapped:          # inline single-binary path
                wj.run()
                if wj.error is not None:
                    raise wj.error
                nbytes += _job_bytes(wj.job)
                if not on_result(wj.result):
                    break
            return nbytes
        window = max(1, min(self.cfg.concurrent_jobs,
                            self.cfg.max_outstanding_per_tenant - 1))
        for wj in wrapped[:window]:
            self.queue.enqueue(tenant, wj)
        for i, wj in enumerate(wrapped):
            while not wj.event.wait(timeout=0.5):
                if self._stop.is_set():
                    raise RuntimeError("frontend shutting down")
                if not self._workers and not self.remote_workers \
                        and wj.try_claim():
                    # every worker disconnected with this job still queued:
                    # run it inline rather than hanging the query forever
                    wj.run_claimed()
            if i + window < len(wrapped):
                self.queue.enqueue(tenant, wrapped[i + window])
            if wj.error is not None:
                raise wj.error
            nbytes += _job_bytes(wj.job)
            if not on_result(wj.result):
                break
        return nbytes

    # -- endpoints ---------------------------------------------------------

    def search(self, tenant: str, query: str, *, limit: int = 20,
               start_s: float | None = None, end_s: float | None = None,
               on_partial: Callable[[list], None] | None = None
               ) -> list:
        """on_partial (optional) receives the combiner's current results
        after each fold — the hook the streaming gRPC endpoint uses to
        emit diff responses (`combiner/search.go`)."""
        from tempo_tpu.utils import tracing
        with tracing.span_for_tenant("frontend.Search", tenant, query=query):
            return self._search(tenant, query, limit=limit, start_s=start_s,
                                end_s=end_s, on_partial=on_partial)

    def _search(self, tenant: str, query: str, *, limit: int = 20,
                start_s: float | None = None, end_s: float | None = None,
                on_partial: Callable[[list], None] | None = None) -> list:
        t0 = self.now()
        end_s = end_s if end_s is not None else self.now()
        start_s = start_s if start_s is not None else end_s - 3600.0
        ing_win, be_win = time_windows(
            self.now(), start_s, end_s,
            self.cfg.query_backend_after_s, self.cfg.query_ingesters_until_s)
        combiner = MetadataCombiner(limit)
        nbytes = 0
        if ing_win is not None:
            for md in self.querier.search_recent(tenant, query, limit,
                                                 *ing_win):
                combiner.add(md)
            if on_partial is not None:
                on_partial(combiner.results())
        if be_win is not None and not combiner.exhausted():
            metas = self.db.blocks(tenant, be_win[0], be_win[1])
            jobs = backend_search_jobs(tenant, metas, be_win[0], be_win[1],
                                       self.cfg.target_bytes_per_job)

            def fold(res) -> bool:
                for md in res:
                    combiner.add(md)
                if on_partial is not None:
                    on_partial(combiner.results())
                return not combiner.exhausted()

            nbytes += self._run_jobs(
                tenant, jobs,
                lambda j: self.querier.search_block(
                    tenant, query, j.meta, j.row_groups, limit,
                    j.start_s, j.end_s),
                fold,
                spec_fn=lambda j: {
                    "kind": "search_block", "tenant": tenant,
                    "query": query, "meta": j.meta.to_json(),
                    "row_groups": list(j.row_groups), "limit": limit,
                    "start_s": j.start_s, "end_s": j.end_s})
        self.slos.record("search", tenant, self.now() - t0, nbytes)
        return combiner.results()

    def find_trace(self, tenant: str, trace_id: bytes,
                   start_s: float | None = None, end_s: float | None = None
                   ) -> list[dict] | None:
        t0 = self.now()
        spans = self.querier.find_trace_by_id(tenant, trace_id, start_s, end_s)
        self.slos.record("traces", tenant, self.now() - t0,
                         len(spans or []) * 200)
        return sort_spans(combine_spans(spans)) if spans else None

    def query_range(self, tenant: str, query: str, *,
                    start_s: float, end_s: float, step_s: float = 60.0
                    ) -> list[TimeSeries]:
        """TraceQL metrics: recent window from generators (RF1 local
        blocks), older from backend jobs; job series merge via
        SeriesCombiner then final quantile/rate pass
        (`metrics_query_range_sharder.go` + `combiner/metrics_query_range.go`)."""
        from tempo_tpu.utils import tracing
        with tracing.span_for_tenant("frontend.QueryRange", tenant,
                                     query=query):
            return self._query_range(tenant, query, start_s=start_s,
                                     end_s=end_s, step_s=step_s)

    def _query_range(self, tenant: str, query: str, *,
                     start_s: float, end_s: float, step_s: float = 60.0
                     ) -> list[TimeSeries]:
        t0 = self.now()
        req = QueryRangeRequest(query=query,
                                start_ns=int(start_s * 1e9),
                                end_ns=int(end_s * 1e9),
                                step_ns=int(step_s * 1e9))
        # single cutoff, not overlapping windows: generators own
        # (cutoff, end], backend RF1 blocks own [start, cutoff] — sub-results
        # keep the full step grid and clip observations to their side, so
        # nothing is counted twice (TrimToBefore/After split,
        # `metrics_query_range_sharder.go:125-190`)
        cutoff_s = self.now() - self.cfg.query_backend_after_s
        cutoff_ns = int(cutoff_s * 1e9)
        comb = SeriesCombiner(metrics_kind(query), req.n_steps)
        nbytes = 0
        if end_s > cutoff_s and self.generator_query_range is not None:
            comb.add_all(self.generator_query_range(
                tenant, req, clip_start_ns=cutoff_ns))
        if start_s < cutoff_s:
            # metrics read ONLY RF1 blocks (generator localblocks /
            # blockbuilder output) — ingester RF3 blocks hold every trace 3x
            # (`blockMetasForSearch(..., rf=1)` sharder :190). Configurable
            # for RF-deduped (compacted single-writer) setups.
            metas = prune_blocks_rf(
                self.db.blocks(tenant, start_s, min(end_s, cutoff_s)),
                self.cfg.metrics_block_rf)
            jobs = query_range_jobs(tenant, metas, start_s,
                                    min(end_s, cutoff_s), step_s,
                                    self.cfg.metrics_target_bytes_per_job)

            def fold(res) -> bool:
                comb.add_all(res)
                return True

            nbytes += self._run_jobs(
                tenant, jobs,
                lambda j: self.querier.query_range_block(
                    tenant, req, j.meta, j.row_groups,
                    clip_end_ns=cutoff_ns),
                fold,
                spec_fn=lambda j: {
                    "kind": "query_range_block", "tenant": tenant,
                    "query": query, "start_ns": req.start_ns,
                    "end_ns": req.end_ns, "step_ns": req.step_ns,
                    "meta": j.meta.to_json(),
                    "row_groups": list(j.row_groups),
                    "clip_end_ns": cutoff_ns})
        self.slos.record("metrics", tenant, self.now() - t0, nbytes)
        return comb.final(req)

    def decode_job_result(self, spec: dict, result):
        """Decode a remote worker's JSON job result back into the objects
        the fold expects (the inverse of `execute_job_spec`)."""
        import numpy as np

        from tempo_tpu.traceql.engine import TraceSearchMetadata

        if spec["kind"] == "search_block":
            return [TraceSearchMetadata.from_json(t) for t in (result or [])]
        if spec["kind"] == "query_range_block":
            return [TimeSeries(
                labels=tuple((k, v) for k, v in s["labels"]),
                samples=np.asarray(s["samples"], np.float64))
                for s in (result or [])]
        raise ValueError(f"unknown job kind {spec['kind']!r}")

    def tag_names(self, tenant: str) -> dict[str, list[str]]:
        t0 = self.now()
        out = self.querier.tag_names(tenant)
        self.slos.record("metadata", tenant, self.now() - t0, 0)
        return out

    def tag_values(self, tenant: str, name: str, limit: int = 1000) -> list[dict]:
        t0 = self.now()
        out = self.querier.tag_values(tenant, name, limit)
        self.slos.record("metadata", tenant, self.now() - t0, 0)
        return out


def _job_bytes(job: SearchJob) -> int:
    if job.meta is None:
        return 0
    n_rg = max(job.meta.row_group_count, 1)
    return int(job.meta.size_bytes * (len(job.row_groups) or n_rg) / n_rg)
