"""Compactor service: ring-sharded ownership over tempodb compaction.

Analog of `modules/compactor`: the service joins a compactor ring and only
runs compaction jobs whose hash it owns (`Owns` `compactor.go:190`), so N
compactors split tenants' job space with no coordination beyond the ring.
Trace dedupe during merge (`Combine` `compactor.go:220`) lives in
`tempo_tpu.model.combine` and the block compactor.
"""

from tempo_tpu.compactor.compactor import Compactor

__all__ = ["Compactor"]
