"""The compactor service."""

from __future__ import annotations

import time
from typing import Callable

from tempo_tpu.db.tempodb import TempoDB
from tempo_tpu.obs import Registry
from tempo_tpu.ring import KVStore, Lifecycler, Ring

COMPACTOR_RING = "compactor"


class Compactor:
    def __init__(self, db: TempoDB, kv: KVStore | None = None,
                 instance_id: str = "compactor-0",
                 registry: Registry | None = None,
                 now: Callable[[], float] = time.time) -> None:
        self.db = db
        self.id = instance_id
        self.now = now
        # share the db's registry by default so a compactor target's
        # /metrics carries both the service sweep and the per-tenant
        # cycle histogram the db records
        self.obs = registry if registry is not None else db.obs
        self.sweeps = self.obs.counter(
            "tempo_compactor_sweeps_total",
            "Full compactor sweeps over all tenants")
        self.kv = kv
        self.ring: Ring | None = None
        self.lifecycler: Lifecycler | None = None
        if kv is not None:
            self.ring = Ring(kv=kv, key=COMPACTOR_RING, replication_factor=1,
                             now=now)
            self.lifecycler = Lifecycler(kv, instance_id, key=COMPACTOR_RING,
                                         now=now)

    def owns(self, key: str) -> bool:
        """Hash the job key onto the compactor ring (`Owns`
        `compactor.go:190`); single-instance mode owns everything."""
        if self.ring is None or len(self.ring) <= 1:
            return True
        return self.ring.owns(self.id, key)

    def run_once(self) -> int:
        """One sweep over all tenants; returns jobs executed. Retention is
        ring-gated per tenant too — N compactors must not race the same
        delete/mark writes — and the sweep keeps our heartbeat fresh so a
        caller-driven loop can't age itself out of the ring."""
        self.heartbeat()
        self.sweeps.inc()
        done = 0
        for tenant in self.db.blocklist.tenants():
            try:
                done += self.db.compact_tenant_once(tenant, owns=self.owns)
                # low-priority sidecar backfill for pre-sidecar blocks —
                # rides the compaction sched class so sustained ingest
                # only reaches it via the min-share valve
                if self.owns(f"sidecars/{tenant}"):
                    done += self.db.backfill_sidecars_once(tenant)
                if self.owns(f"retention/{tenant}"):
                    self.db.retention_once(tenant)
            except Exception:
                continue  # a failed tenant must not stall the sweep
        return done

    def enable(self, interval_s: float = 30.0) -> None:
        self.db.enable_compaction(interval_s, owns=self.owns)

    def heartbeat(self) -> None:
        if self.lifecycler:
            self.lifecycler.heartbeat()

    def shutdown(self) -> None:
        if self.lifecycler:
            self.lifecycler.leave()
