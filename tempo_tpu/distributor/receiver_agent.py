"""Jaeger agent UDP receiver: thrift-compact `Agent.emitBatch` datagrams.

The deprecated-but-deployed jaeger client path (ref
`modules/distributor/receiver/shim.go:165-171`, jaeger `thrift_compact`
protocol on port 6831). Datagrams decode via
`model.jaeger.spans_from_jaeger_agent` and push through the SAME
distributor entry as every other receiver. UDP has no reply channel:
malformed datagrams and push failures are counted, never raised.
"""

from __future__ import annotations

import dataclasses
import socket
import threading

from tempo_tpu.model.jaeger import spans_from_jaeger_agent


@dataclasses.dataclass
class JaegerAgentConfig:
    # SECURITY: this receiver is an UNAUTHENTICATED single-tenant UDP
    # ingest — it binds loopback by default. Exposing it on every
    # interface requires the explicit opt-in below; set it only on
    # networks where the agent port is meant to be reachable (the
    # reference ships the same unauthenticated jaeger agent surface).
    host: str = "127.0.0.1"
    port: int = 6831             # jaeger thrift-compact agent port
    allow_wildcard_bind: bool = False   # opt-in for 0.0.0.0 / :: binds
    tenant: str = "single-tenant"
    max_datagram: int = 65_000


class JaegerAgentReceiver:
    def __init__(self, distributor, cfg: JaegerAgentConfig | None = None):
        self.distributor = distributor
        self.cfg = cfg or JaegerAgentConfig()
        self.batches_received = 0
        self.spans_received = 0
        self.errors = 0
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    @property
    def port(self) -> int:
        assert self._sock is not None
        return self._sock.getsockname()[1]

    def start(self) -> None:
        host = self.cfg.host
        if host in ("", "0.0.0.0", "::") and not self.cfg.allow_wildcard_bind:
            raise ValueError(
                "jaeger agent wildcard bind requires "
                "allow_wildcard_bind=True (unauthenticated UDP ingest on "
                "all interfaces); default to 127.0.0.1 instead")
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((host, self.cfg.port))
        self._sock.settimeout(0.5)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                data, _addr = self._sock.recvfrom(self.cfg.max_datagram)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                spans = spans_from_jaeger_agent(data)
                if spans:
                    self.distributor.push_spans(
                        self.cfg.tenant, spans, size_bytes=len(data))
                self.batches_received += 1
                self.spans_received += len(spans)
            except Exception:
                self.errors += 1     # UDP: count and drop, nobody to answer

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        if self._sock is not None:
            self._sock.close()
