"""Generic per-tenant trace forwarder (tee to external endpoints).

Analog of `modules/distributor/forwarder` (`forwarder/manager.go:291`):
each tenant may configure named forwarders; matching spans are teed
asynchronously to the forwarder's sink. Sinks are pluggable — an
OTLP-JSON HTTP sink is provided; tests inject callables. Filtering uses
the span-filter policy engine (the OTTL-filter analog).
"""

from __future__ import annotations

import dataclasses
import json
import queue
import threading
import urllib.request
from typing import Callable, Sequence

@dataclasses.dataclass
class ForwarderConfig:
    name: str
    endpoint: str = ""                    # http(s) OTLP-JSON target
    # filter: {"include": {key: value, ...}} and/or {"exclude": {...}} —
    # strict matches on name/service/kind/status_code or span attrs (the
    # OTTL-filter analog, dict-level since the tee runs pre-batching)
    filter: dict = dataclasses.field(default_factory=dict)
    queue_size: int = 1000


def _span_matches(span: dict, wants: dict) -> bool:
    for k, v in wants.items():
        have = span.get(k)
        if have is None:
            have = (span.get("attrs") or {}).get(k)
        if have is None:
            have = (span.get("res_attrs") or {}).get(k)
        if str(have) != str(v):
            return False
    return True


def keep_span(span: dict, flt: dict) -> bool:
    inc = flt.get("include")
    if inc and not _span_matches(span, inc):
        return False
    exc = flt.get("exclude")
    if exc and _span_matches(span, exc):
        return False
    return True


def otlp_json_payload(spans: Sequence[dict]) -> dict:
    """Flat span dicts → OTLP-JSON ExportTraceServiceRequest."""
    by_service: dict[str, list[dict]] = {}
    for s in spans:
        by_service.setdefault(s.get("service", ""), []).append(s)
    rss = []
    for svc, group in by_service.items():
        rss.append({
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": svc}}]},
            "scopeSpans": [{"spans": [{
                "traceId": s.get("trace_id", b"").hex(),
                "spanId": s.get("span_id", b"").hex(),
                "parentSpanId": s.get("parent_span_id", b"").hex(),
                "name": s.get("name", ""),
                "kind": s.get("kind", 0),
                "startTimeUnixNano": str(s.get("start_unix_nano", 0)),
                "endTimeUnixNano": str(s.get("end_unix_nano", 0)),
                "attributes": [
                    {"key": k, "value": _anyvalue(v)}
                    for k, v in (s.get("attrs") or {}).items()],
                "status": {"code": s.get("status_code", 0)},
            } for s in group]}],
        })
    return {"resourceSpans": rss}


def _anyvalue(v) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def http_sink(endpoint: str, timeout_s: float = 10.0
              ) -> Callable[[Sequence[dict]], None]:
    def send(spans: Sequence[dict]) -> None:
        body = json.dumps(otlp_json_payload(spans)).encode()
        req = urllib.request.Request(
            endpoint, data=body, headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=timeout_s).read()
    return send


class Forwarder:
    """One tenant's forwarder: bounded queue + worker thread, drop-on-full
    (forwarding is best-effort; it must never block ingest)."""

    def __init__(self, cfg: ForwarderConfig,
                 sink: Callable[[Sequence[dict]], None] | None = None) -> None:
        self.cfg = cfg
        self.sink = sink or http_sink(cfg.endpoint)
        self._q: queue.Queue = queue.Queue(maxsize=cfg.queue_size)
        self.dropped = 0
        self.forwarded = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def offer(self, spans: Sequence[dict]) -> None:
        if self.cfg.filter:
            spans = [s for s in spans if keep_span(s, self.cfg.filter)]
        if not spans:
            return
        try:
            self._q.put_nowait(list(spans))
        except queue.Full:
            self.dropped += len(spans)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                spans = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self.sink(spans)
                self.forwarded += len(spans)
            except Exception:
                self.dropped += len(spans)

    def flush(self, timeout_s: float = 2.0) -> None:
        import time
        deadline = time.time() + timeout_s
        while not self._q.empty() and time.time() < deadline:
            time.sleep(0.01)

    def shutdown(self) -> None:
        self.flush()
        self._stop.set()
        self._thread.join(timeout=2)


class ForwarderManager:
    """Per-tenant forwarder registry driven by overrides/config
    (`forwarder/manager.go`)."""

    def __init__(self) -> None:
        self._by_tenant: dict[str, list[Forwarder]] = {}
        self._lock = threading.Lock()
        self.empty = True   # lock-free hot-path gate (flips once)

    def register(self, tenant: str, fwd: Forwarder) -> None:
        with self._lock:
            self._by_tenant.setdefault(tenant, []).append(fwd)
            self.empty = False

    def for_tenant(self, tenant: str) -> list[Forwarder]:
        with self._lock:
            return list(self._by_tenant.get(tenant, ()))

    def offer(self, tenant: str, spans: Sequence[dict]) -> None:
        if self.empty:
            return
        for fwd in self.for_tenant(tenant):
            fwd.offer(spans)

    def shutdown(self) -> None:
        with self._lock:
            all_fwds = [f for fs in self._by_tenant.values() for f in fs]
        for f in all_fwds:
            f.shutdown()
