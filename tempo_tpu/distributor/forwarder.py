"""Generic per-tenant trace forwarder (tee to external endpoints).

Analog of `modules/distributor/forwarder` (`forwarder/manager.go:291`):
each tenant may configure named forwarders; matching spans are teed
asynchronously to the forwarder's sink. Sinks are pluggable — an
OTLP-JSON HTTP sink is provided; tests inject callables. Filtering uses
the span-filter policy engine (the OTTL-filter analog).
"""

from __future__ import annotations

import dataclasses
import json
import queue
import threading
import urllib.request
from typing import Callable, Sequence

@dataclasses.dataclass
class ForwarderConfig:
    name: str
    endpoint: str = ""                    # http(s) OTLP-JSON target
    # filter: {"include": {key: value, ...}} and/or {"exclude": {...}} —
    # strict matches on name/service/kind/status_code or span attrs (the
    # OTTL-filter analog, dict-level since the tee runs pre-batching)
    filter: dict = dataclasses.field(default_factory=dict)
    # filter_policies: full pkg/spanfilter-shape policies
    # [{"include": {"match_type": "strict"|"regex",
    #               "attributes": [{"key": ..., "value": ...}]},
    #   "exclude": {...}}, ...] — keys: kind/status/name/span.*/resource.*
    # (the per-tenant OTTL filtering of `modules/distributor/forwarder`)
    filter_policies: list = dataclasses.field(default_factory=list)
    queue_size: int = 1000


# intrinsic string forms (pkg/spanfilter's splitPolicy enum strings)
_KIND_STRS = ("SPAN_KIND_UNSPECIFIED", "SPAN_KIND_INTERNAL",
              "SPAN_KIND_SERVER", "SPAN_KIND_CLIENT",
              "SPAN_KIND_PRODUCER", "SPAN_KIND_CONSUMER")
_STATUS_STRS = ("STATUS_CODE_UNSET", "STATUS_CODE_OK", "STATUS_CODE_ERROR")


def _span_value(span: dict, key: str):
    """Resolve a policy key on a span dict, mirroring the vectorized
    engine's scoping (`utils/spanfilter._match_one`)."""
    if key in ("kind", "span.kind"):
        k = int(span.get("kind", 0) or 0)
        return _KIND_STRS[k] if 0 <= k < len(_KIND_STRS) else _KIND_STRS[0]
    if key in ("status", "span.status", "status.code"):
        c = int(span.get("status_code", 0) or 0)
        return _STATUS_STRS[c] if 0 <= c < 3 else _STATUS_STRS[0]
    if key in ("name", "span.name"):
        return span.get("name", "")
    if key.startswith("resource."):
        return (span.get("res_attrs") or {}).get(key[len("resource."):])
    if key.startswith("span."):
        return (span.get("attrs") or {}).get(key[len("span."):])
    return (span.get("attrs") or {}).get(key)


def _policy_matches(span: dict, pm: dict) -> bool:
    """Every attribute of the PolicyMatch must match (spanfilter.go:53)."""
    import re

    regex = pm.get("match_type") == "regex"
    for am in pm.get("attributes", ()):
        have = _span_value(span, str(am.get("key", "")))
        if have is None:
            return False
        want = str(am.get("value", ""))
        if regex:
            if not re.fullmatch(want, str(have)):
                return False
        elif str(have) != want:
            return False
    return True


def _span_matches(span: dict, wants: dict) -> bool:
    for k, v in wants.items():
        have = span.get(k)
        if have is None:
            have = (span.get("attrs") or {}).get(k)
        if have is None:
            have = (span.get("res_attrs") or {}).get(k)
        if str(have) != str(v):
            return False
    return True


def keep_span(span: dict, flt: dict,
              policies: "Sequence[dict] | None" = None) -> bool:
    inc = flt.get("include") if flt else None
    if inc and not _span_matches(span, inc):
        return False
    exc = flt.get("exclude") if flt else None
    if exc and _span_matches(span, exc):
        return False
    # policy semantics: kept iff for EVERY policy (include absent or
    # matched) and (exclude absent or not matched)
    for p in policies or ():
        pinc = p.get("include")
        if pinc and not _policy_matches(span, pinc):
            return False
        pexc = p.get("exclude")
        if pexc and _policy_matches(span, pexc):
            return False
    return True


def otlp_json_payload(spans: Sequence[dict]) -> dict:
    """Flat span dicts → OTLP-JSON ExportTraceServiceRequest."""
    by_service: dict[str, list[dict]] = {}
    for s in spans:
        by_service.setdefault(s.get("service", ""), []).append(s)
    rss = []
    for svc, group in by_service.items():
        rss.append({
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": svc}}]},
            "scopeSpans": [{"spans": [{
                "traceId": s.get("trace_id", b"").hex(),
                "spanId": s.get("span_id", b"").hex(),
                "parentSpanId": s.get("parent_span_id", b"").hex(),
                "name": s.get("name", ""),
                "kind": s.get("kind", 0),
                "startTimeUnixNano": str(s.get("start_unix_nano", 0)),
                "endTimeUnixNano": str(s.get("end_unix_nano", 0)),
                "attributes": [
                    {"key": k, "value": _anyvalue(v)}
                    for k, v in (s.get("attrs") or {}).items()],
                "status": {"code": s.get("status_code", 0)},
            } for s in group]}],
        })
    return {"resourceSpans": rss}


def _anyvalue(v) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def http_sink(endpoint: str, timeout_s: float = 10.0
              ) -> Callable[[Sequence[dict]], None]:
    def send(spans: Sequence[dict]) -> None:
        body = json.dumps(otlp_json_payload(spans)).encode()
        req = urllib.request.Request(
            endpoint, data=body, headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=timeout_s).read()
    return send


class Forwarder:
    """One tenant's forwarder: bounded queue + worker thread, drop-on-full
    (forwarding is best-effort; it must never block ingest)."""

    def __init__(self, cfg: ForwarderConfig,
                 sink: Callable[[Sequence[dict]], None] | None = None) -> None:
        import re

        self.cfg = cfg
        # validate regex policies at REGISTRATION, where a config error
        # belongs — not per span on the ingest path
        for p in cfg.filter_policies or ():
            for pm in (p.get("include"), p.get("exclude")):
                if pm and pm.get("match_type") == "regex":
                    for am in pm.get("attributes", ()):
                        re.compile(str(am.get("value", "")))
        self.sink = sink or http_sink(cfg.endpoint)
        self._q: queue.Queue = queue.Queue(maxsize=cfg.queue_size)
        self.dropped = 0
        self.forwarded = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def offer(self, spans: Sequence[dict]) -> None:
        if self.cfg.filter or self.cfg.filter_policies:
            try:
                spans = [s for s in spans
                         if keep_span(s, self.cfg.filter,
                                      self.cfg.filter_policies)]
            except Exception:
                # the tee is best-effort and must NEVER fail ingest: a
                # filter blow-up counts the batch as dropped
                self.dropped += len(spans)
                return
        if not spans:
            return
        try:
            self._q.put_nowait(list(spans))
        except queue.Full:
            self.dropped += len(spans)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                spans = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self.sink(spans)
                self.forwarded += len(spans)
            except Exception:
                self.dropped += len(spans)

    def flush(self, timeout_s: float = 2.0) -> None:
        import time
        deadline = time.time() + timeout_s
        while not self._q.empty() and time.time() < deadline:
            time.sleep(0.01)

    def shutdown(self) -> None:
        self.flush()
        self._stop.set()
        self._thread.join(timeout=2)


class ForwarderManager:
    """Per-tenant forwarder registry driven by overrides/config
    (`forwarder/manager.go`)."""

    def __init__(self) -> None:
        self._by_tenant: dict[str, list[Forwarder]] = {}
        self._lock = threading.Lock()
        self.empty = True   # lock-free hot-path gate (flips once)

    def register(self, tenant: str, fwd: Forwarder) -> None:
        with self._lock:
            self._by_tenant.setdefault(tenant, []).append(fwd)
            self.empty = False

    def for_tenant(self, tenant: str) -> list[Forwarder]:
        with self._lock:
            return list(self._by_tenant.get(tenant, ()))

    def offer(self, tenant: str, spans: Sequence[dict]) -> None:
        if self.empty:
            return
        for fwd in self.for_tenant(tenant):
            fwd.offer(spans)

    def shutdown(self) -> None:
        with self._lock:
            all_fwds = [f for fs in self._by_tenant.values() for f in fs]
        for f in all_fwds:
            f.shutdown()
