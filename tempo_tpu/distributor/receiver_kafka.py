"""Kafka receiver: OTLP payloads consumed FROM a topic into the
distributor.

The reference's distributor can host a kafka receiver among its OTel
receivers (`modules/distributor/receiver/shim.go:165-171` "kafka"): an
external pipeline (e.g. an OTel collector exporting to Kafka) produces
OTLP ExportTraceServiceRequest bytes to a topic; the distributor consumes
and ingests them. This is the INVERSE of the ingest-storage bus (where
the distributor is the producer). Works against any `ingest.bus.Bus`
surface — the in-memory bus in tests, `KafkaBus` in deployments.

Record key = tenant (the same convention the write path uses); empty key
falls back to the configured default tenant. Offsets commit after a
successful push, so a crash replays at-least-once — the distributor's
trace-id regroup and the ingester's live-trace merge absorb duplicates
the same way the blockbuilder path does.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Sequence

log = logging.getLogger("tempo_tpu.distributor.kafka_receiver")


@dataclasses.dataclass
class KafkaReceiverConfig:
    partitions: Sequence[int] = (0,)
    group: str = "tempo-distributor-receiver"
    default_tenant: str = "single-tenant"
    max_records: int = 100
    poll_interval_s: float = 0.25


class KafkaReceiver:
    """Consume OTLP payload records from bus partitions into a
    distributor. `run_once()` drives one poll (tests); `start()` runs the
    daemon loop."""

    def __init__(self, bus, distributor, cfg: KafkaReceiverConfig | None = None):
        self.bus = bus
        self.dist = distributor
        self.cfg = cfg or KafkaReceiverConfig()
        self.records_consumed = 0
        self.spans_pushed = 0
        self.errors = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def run_once(self) -> int:
        """One fetch+push+commit pass over every owned partition; returns
        records consumed."""
        from tempo_tpu.distributor.distributor import (MalformedPayload,
                                                       RateLimited)

        n = 0
        for partition in self.cfg.partitions:
            offset = self.bus.committed(self.cfg.group, partition)
            recs = self.bus.fetch(partition, offset, self.cfg.max_records)
            if not recs:
                continue
            for rec in recs:
                tenant = rec.tenant or self.cfg.default_tenant
                try:
                    self.dist.push_otlp(tenant, rec.value)
                    self.spans_pushed += 1
                except MalformedPayload:
                    self.errors += 1      # poison record: skip, don't wedge
                except RateLimited:
                    # leave the offset where it is: retry this slice later
                    # instead of dropping over-limit data
                    return n
                n += 1
                self.records_consumed += 1
            # commit AFTER the pushes (at-least-once, like blockbuilder's
            # offset-commit-after-flush)
            self.bus.commit(self.cfg.group, partition, recs[-1].offset + 1)
        return n

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.cfg.poll_interval_s):
                try:
                    self.run_once()
                except Exception:
                    log.exception("kafka receiver poll failed")

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
